"""One serving replica: a worker thread pulling micro-batches off the queue.

Replicas are intentionally dumb — pull a batch, inject any scheduled faults,
run the shared :class:`~sheeprl_tpu.serve.model.ModelStore` executable,
complete the futures. All recovery intelligence lives one level up
(:mod:`sheeprl_tpu.serve.supervisor`); the replica's contribution to
robustness is the contract it dies by:

- **no request is lost to a crash** — the batch is re-queued *before* the
  failure propagates, so in-flight requests ride out replica death (they are
  re-served by a sibling, or expire against their own deadline).
- **circuit breaker** — ``breaker_threshold`` consecutive inference failures
  trip the replica: it re-queues and exits rather than chewing through the
  queue failing every batch. The supervisor then restarts it under the
  restart budget; a sick model (rather than a sick replica) therefore fails
  N replicas * budget restarts and degrades to an empty replica set instead
  of spinning forever.
- **heartbeats** — a monotone timestamp the supervisor uses to detect a hung
  (not dead) replica; inference runs between heartbeats, so a replica stuck
  in a pathological forward is indistinguishable from a dead one and gets
  restarted the same way.

Batch indices are per-replica-slot monotone counters owned by the
supervisor, so the deterministic fault schedule keeps its position across
restarts (a restarted replica does not re-fire ``at_batch`` faults).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Callable, List, Optional

from sheeprl_tpu.serve.batching import MicroBatcher, Request
from sheeprl_tpu.serve.errors import InferenceFailed
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule
from sheeprl_tpu.serve.model import ModelStore


class InjectedCrash(RuntimeError):
    """A scheduled ``replica_crash`` fault firing (distinguishable in logs
    from an organic inference failure)."""


class ReplicaStats:
    """Shared mutable counters, written by the replica thread, read by the
    supervisor/stats reporters. Single-writer, so plain attributes are fine;
    ``heartbeat`` is the liveness signal."""

    __slots__ = ("heartbeat", "batches", "requests", "failures", "consecutive_failures")

    def __init__(self) -> None:
        self.heartbeat = time.monotonic()
        self.batches = 0
        self.requests = 0
        self.failures = 0
        self.consecutive_failures = 0

    def beat(self) -> None:
        self.heartbeat = time.monotonic()


class Replica(threading.Thread):
    """A serving worker. ``batch_counter`` is the supervisor-owned iterator
    yielding this slot's monotone batch indices; ``on_batch(n, latency_s)``
    reports completed work for the stats pipeline."""

    def __init__(
        self,
        index: int,
        *,
        batcher: MicroBatcher,
        store: ModelStore,
        stats: ReplicaStats,
        batch_counter: "itertools.count[int]",
        max_batch: int,
        breaker_threshold: int,
        fault_schedule: Optional[ServeFaultSchedule] = None,
        poll_timeout_s: float = 0.05,
        on_batch: Optional[Callable[[int, float], None]] = None,
        name: Optional[str] = None,
    ) -> None:
        super().__init__(name=name or f"serve-replica-{index}", daemon=True)
        self.index = index
        self.batcher = batcher
        self.store = store
        self.stats = stats
        self._batch_counter = batch_counter
        self.max_batch = int(max_batch)
        self.breaker_threshold = int(breaker_threshold)
        self._faults = fault_schedule
        self._poll_timeout_s = float(poll_timeout_s)
        self._on_batch = on_batch
        self._stop_evt = threading.Event()
        self.exit_reason: Optional[str] = None

    def request_stop(self) -> None:
        self._stop_evt.set()

    # ------------------------------------------------------------------- loop
    def run(self) -> None:  # pragma: no cover - exercised via the server tests
        try:
            self._loop()
        except InjectedCrash as err:
            self.exit_reason = f"injected crash: {err}"
        except Exception as err:
            self.exit_reason = f"crashed: {err!r}"
        else:
            self.exit_reason = self.exit_reason or "stopped"

    def _loop(self) -> None:
        while not self._stop_evt.is_set() and not self.batcher.closed:
            self.stats.beat()
            batch = self.batcher.next_batch(self.max_batch, self._poll_timeout_s)
            if not batch:
                continue
            self._serve_batch(batch)
        # drain nothing on the way out: pending work belongs to siblings

    def _serve_batch(self, batch: List[Request]) -> None:
        batch_index = next(self._batch_counter)
        if self._faults is not None:
            for fault in self._faults.batch_faults(self.index, batch_index):
                if fault.kind == "slow_inference":
                    self._sleep_injected(fault.duration_s)
                elif fault.kind == "replica_crash":
                    # crash contract: work survives the worker
                    self.batcher.requeue(batch)
                    raise InjectedCrash(f"scheduled replica_crash at batch {batch_index}")
        t0 = time.monotonic()
        try:
            outputs = self.store.infer([r.obs for r in batch])
        except Exception as err:
            self.stats.failures += 1
            self.stats.consecutive_failures += 1
            if self.stats.consecutive_failures >= self.breaker_threshold:
                # breaker trip: hand the work back, die, let the supervisor
                # decide whether this slot has restart budget left
                self.batcher.requeue(batch)
                raise RuntimeError(
                    f"circuit breaker open after {self.stats.consecutive_failures} "
                    f"consecutive inference failures"
                ) from err
            self.batcher.requeue(batch)
            return
        latency_s = time.monotonic() - t0
        self.stats.consecutive_failures = 0
        self.stats.batches += 1
        self.stats.requests += len(batch)
        self.stats.beat()
        now = time.monotonic()
        for req, out in zip(batch, outputs):
            if not req.future.done():
                if req.expired(now):
                    # result arrived too late: route through requeue so the
                    # expiry is completed AND counted as shed in one place
                    self.batcher.requeue([req])
                else:
                    # stamp the serving checkpoint step before completion —
                    # the online bridge reads it off the request after wait()
                    req.served_step = self.store.current.step
                    req.future.set_result(out)
        if self._on_batch is not None:
            try:
                self._on_batch(len(batch), latency_s)
            except Exception:
                pass

    def _sleep_injected(self, duration_s: float) -> None:
        # interruptible sleep so close() doesn't wait out a long slow-fault
        end = time.monotonic() + duration_s
        while not self._stop_evt.is_set():
            remaining = end - time.monotonic()
            if remaining <= 0:
                return
            self.stats.beat()  # slow, not hung: keep the supervisor informed
            time.sleep(min(0.02, remaining))
