"""Serving-tier knobs, parsed once from the composed config's ``serve`` node.

Everything lives under top-level ``serve`` (``configs/config.yaml``) so CLI
overrides read ``serve.slo_ms=50``; a checkpoint written before the node
existed composes to all-defaults (``serve_config_from_cfg({})`` is valid).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional

from sheeprl_tpu.serve.fault_injection import ServeFaultSpec, parse_serve_faults


@dataclass
class LoadConfig:
    """Scripted load-generator run (``serve.load.*``): the CLI's measurable
    proxy for "heavy traffic" — N concurrent closed-loop clients (optionally
    rate-limited) hammering the server for ``duration_s``."""

    enabled: bool = False
    duration_s: float = 10.0
    concurrency: int = 8
    rate_hz: float = 0.0  # >0: open-loop target request rate across all clients
    timeout_ms: Optional[float] = None  # per-request client deadline; None: server default
    max_retries: int = 3
    seed: int = 0


@dataclass
class ServeConfig:
    """Parameters for :class:`~sheeprl_tpu.serve.server.PolicyServer`.

    The SLO drives the derived knobs: the micro-batcher coalesces requests
    for at most ``gather_window_s`` (default ``slo_ms / 5``) so queueing can
    never eat the whole latency budget, and requests default to a
    ``4 * slo_ms`` deadline.
    """

    batch_ladder: List[int] = field(default_factory=lambda: [1, 2, 4, 8])
    slo_ms: float = 100.0
    gather_window_ms: Optional[float] = None  # None: slo_ms / 5, capped at 10ms
    max_queue: int = 64  # admission-control bound (pending requests)
    default_deadline_ms: Optional[float] = None  # None: 4 * slo_ms
    num_replicas: int = 2
    max_restarts: int = 3
    restart_refund_s: Optional[float] = 600.0  # healthy window refunding one restart
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    replica_timeout_s: float = 30.0  # stale heartbeat -> replica presumed hung
    breaker_threshold: int = 3  # consecutive inference failures trip the replica
    monitor_interval_s: float = 0.05
    swap_poll_s: float = 0.0  # >0: watch the ckpt dir and hot-swap newer manifests
    stats_interval_s: float = 5.0  # serve_stats telemetry cadence
    faults: List[ServeFaultSpec] = field(default_factory=list)
    load: LoadConfig = field(default_factory=LoadConfig)

    def __post_init__(self) -> None:
        ladder = sorted({int(b) for b in self.batch_ladder})
        if not ladder or ladder[0] < 1:
            raise ValueError(f"serve.batch_ladder must be positive ints, got {self.batch_ladder!r}")
        self.batch_ladder = ladder
        if self.num_replicas < 1:
            raise ValueError(f"serve.num_replicas must be >= 1, got {self.num_replicas}")
        if self.max_queue < 1:
            raise ValueError(f"serve.max_queue must be >= 1, got {self.max_queue}")

    @property
    def max_batch(self) -> int:
        return self.batch_ladder[-1]

    @property
    def gather_window_s(self) -> float:
        if self.gather_window_ms is not None:
            return float(self.gather_window_ms) / 1e3
        return min(self.slo_ms / 5.0, 10.0) / 1e3

    @property
    def default_deadline_s(self) -> float:
        if self.default_deadline_ms is not None:
            return float(self.default_deadline_ms) / 1e3
        return 4.0 * self.slo_ms / 1e3

    def backoff_s(self, charge: int) -> float:
        return min(self.backoff_max_s, self.backoff_base_s * (2 ** max(0, charge - 1)))


def serve_config_from_cfg(cfg: Mapping[str, Any]) -> ServeConfig:
    """Build a :class:`ServeConfig` from the composed run config's ``serve``
    node (absent node -> all defaults, faults disabled)."""
    node = _get(cfg, "serve") or {}
    fault_node = _get(node, "fault_injection") or {}
    faults: List[ServeFaultSpec] = []
    if bool(_get(fault_node, "enabled", False)):
        faults = parse_serve_faults(_get(fault_node, "faults") or [])
    load_node = _get(node, "load") or {}
    load = LoadConfig(
        enabled=bool(_get(load_node, "enabled", False)),
        duration_s=float(_get(load_node, "duration_s", 10.0)),
        concurrency=int(_get(load_node, "concurrency", 8)),
        rate_hz=float(_get(load_node, "rate_hz", 0.0) or 0.0),
        timeout_ms=_opt_float(_get(load_node, "timeout_ms", None)),
        max_retries=int(_get(load_node, "max_retries", 3)),
        seed=int(_get(load_node, "seed", 0)),
    )
    return ServeConfig(
        batch_ladder=list(_get(node, "batch_ladder", None) or [1, 2, 4, 8]),
        slo_ms=float(_get(node, "slo_ms", 100.0)),
        gather_window_ms=_opt_float(_get(node, "gather_window_ms", None)),
        max_queue=int(_get(node, "max_queue", 64)),
        default_deadline_ms=_opt_float(_get(node, "default_deadline_ms", None)),
        num_replicas=int(_get(node, "num_replicas", 2)),
        max_restarts=int(_get(node, "max_restarts", 3)),
        restart_refund_s=_opt_float(_get(node, "restart_refund_s", 600.0)),
        backoff_base_s=float(_get(node, "backoff_base_s", 0.05)),
        backoff_max_s=float(_get(node, "backoff_max_s", 2.0)),
        replica_timeout_s=float(_get(node, "replica_timeout_s", 30.0)),
        breaker_threshold=int(_get(node, "breaker_threshold", 3)),
        monitor_interval_s=float(_get(node, "monitor_interval_s", 0.05)),
        swap_poll_s=float(_get(node, "swap_poll_s", 0.0) or 0.0),
        stats_interval_s=float(_get(node, "stats_interval_s", 5.0)),
        faults=faults,
        load=load,
    )


def _opt_float(v: Any) -> Optional[float]:
    return None if v is None else float(v)


def _get(node: Any, key: str, default: Any = None) -> Any:
    if node is None:
        return default
    if hasattr(node, "get"):
        return node.get(key, default)
    return getattr(node, key, default)
