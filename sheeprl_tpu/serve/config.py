"""Serving-tier knobs, parsed once from the composed config's ``serve`` node.

Everything lives under top-level ``serve`` (``configs/config.yaml``) so CLI
overrides read ``serve.slo_ms=50``; a checkpoint written before the node
existed composes to all-defaults (``serve_config_from_cfg({})`` is valid).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional

from sheeprl_tpu.serve.fault_injection import ServeFaultSpec, parse_serve_faults


@dataclass
class LoadConfig:
    """Scripted load-generator run (``serve.load.*``): the CLI's measurable
    proxy for "heavy traffic" — N concurrent closed-loop clients (optionally
    rate-limited) hammering the server for ``duration_s``."""

    enabled: bool = False
    duration_s: float = 10.0
    concurrency: int = 8
    rate_hz: float = 0.0  # >0: open-loop target request rate across all clients
    timeout_ms: Optional[float] = None  # per-request client deadline; None: server default
    max_retries: int = 3
    seed: int = 0
    # stepped saturation ramp (``run_ramp``): 0 steps = plain run_load
    ramp_steps: int = 0
    ramp_start_hz: float = 50.0
    ramp_factor: float = 1.6


@dataclass
class FleetConfig:
    """Replica-fleet knobs (``serve.fleet.*``): router admission/hedging,
    elastic scaling bounds and the CPU spill tier. Disabled by default — the
    single :class:`PolicyServer` stays the small-deployment path."""

    enabled: bool = False
    num_replicas: int = 4  # initially-active device replicas
    min_replicas: int = 1  # autoscale floor
    max_replicas: int = 8  # autoscale ceiling (standby slots pre-created)
    cpu_spill_replicas: int = 0  # host-backend replicas for batch-priority spill
    backlog_per_replica: int = 16  # per-pool FIFO behind the slot window
    max_pending: Optional[int] = None  # fleet admission bound; None: derived
    hedge_quantile: float = 0.95  # hedge requests waiting past this latency quantile
    hedge_floor_ms: float = 0.0  # never hedge earlier than this
    hedge_max: int = 1  # hedge copies per request
    hedge_scan_ms: float = 5.0  # hedge/rescue scan cadence
    spill_depth: int = 4  # per-device-replica depth that opens the spill tier
    autoscale_interval_s: float = 0.25
    scale_up_depth: float = 4.0  # avg queued per active replica that adds one
    scale_down_depth: float = 0.5  # avg queued per active replica that retires one
    scale_patience: int = 3  # consecutive breaches before acting
    # remote replicas: per-host agent processes (howto/multihost.md) the fleet
    # adopts over TCP — "host:port" endpoints, one slot each. A remote slot is
    # routed exactly like a device slot; its restarts are reconnects.
    remote_agents: List[str] = field(default_factory=list)
    remote_timeout_s: float = 10.0  # per-batch reply deadline on the agent link

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError(f"serve.fleet.min_replicas must be >= 1, got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"serve.fleet.max_replicas ({self.max_replicas}) must be >= "
                f"min_replicas ({self.min_replicas})"
            )
        if not (self.min_replicas <= self.num_replicas <= self.max_replicas):
            raise ValueError(
                f"serve.fleet.num_replicas ({self.num_replicas}) must lie in "
                f"[min_replicas={self.min_replicas}, max_replicas={self.max_replicas}]"
            )
        if self.cpu_spill_replicas < 0:
            raise ValueError(
                f"serve.fleet.cpu_spill_replicas must be >= 0, got {self.cpu_spill_replicas}"
            )
        if not 0.0 < self.hedge_quantile <= 1.0:
            raise ValueError(
                f"serve.fleet.hedge_quantile must be in (0, 1], got {self.hedge_quantile}"
            )
        if self.hedge_max < 0:
            raise ValueError(f"serve.fleet.hedge_max must be >= 0, got {self.hedge_max}")
        if self.backlog_per_replica < 1:
            raise ValueError(
                f"serve.fleet.backlog_per_replica must be >= 1, got {self.backlog_per_replica}"
            )
        if self.remote_timeout_s <= 0:
            raise ValueError(
                f"serve.fleet.remote_timeout_s must be > 0, got {self.remote_timeout_s}"
            )

    def resolved_max_pending(self, serve: "ServeConfig") -> int:
        """The fleet-wide admission bound: explicit, else every active
        replica's slot window + backlog (the fleet analogue of the single
        server's ``max_queue``)."""
        if self.max_pending is not None:
            return int(self.max_pending)
        per_replica = serve.max_batch + self.backlog_per_replica
        return per_replica * (self.num_replicas + self.cpu_spill_replicas + len(self.remote_agents))


@dataclass
class ServeConfig:
    """Parameters for :class:`~sheeprl_tpu.serve.server.PolicyServer`.

    The SLO drives the derived knobs: the micro-batcher coalesces requests
    for at most ``gather_window_s`` (default ``slo_ms / 5``) so queueing can
    never eat the whole latency budget, and requests default to a
    ``4 * slo_ms`` deadline.
    """

    batch_ladder: List[int] = field(default_factory=lambda: [1, 2, 4, 8])
    slo_ms: float = 100.0
    gather_window_ms: Optional[float] = None  # None: slo_ms / 5, capped at 10ms
    max_queue: int = 64  # admission-control bound (pending requests)
    default_deadline_ms: Optional[float] = None  # None: 4 * slo_ms
    num_replicas: int = 2
    max_restarts: int = 3
    restart_refund_s: Optional[float] = 600.0  # healthy window refunding one restart
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    replica_timeout_s: float = 30.0  # stale heartbeat -> replica presumed hung
    breaker_threshold: int = 3  # consecutive inference failures trip the replica
    monitor_interval_s: float = 0.05
    swap_poll_s: float = 0.0  # >0: watch the ckpt dir and hot-swap newer manifests
    stats_interval_s: float = 5.0  # serve_stats telemetry cadence
    # AOT executable cache dir (howto/aot_cache.md): replica boots
    # deserialize the batch ladder instead of compiling it; None disables
    aot_cache_dir: Optional[str] = None
    faults: List[ServeFaultSpec] = field(default_factory=list)
    load: LoadConfig = field(default_factory=LoadConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)

    def __post_init__(self) -> None:
        ladder = sorted({int(b) for b in self.batch_ladder})
        if not ladder or ladder[0] < 1:
            raise ValueError(f"serve.batch_ladder must be positive ints, got {self.batch_ladder!r}")
        self.batch_ladder = ladder
        if self.num_replicas < 1:
            raise ValueError(f"serve.num_replicas must be >= 1, got {self.num_replicas}")
        if self.max_queue < 1:
            raise ValueError(f"serve.max_queue must be >= 1, got {self.max_queue}")

    @property
    def max_batch(self) -> int:
        return self.batch_ladder[-1]

    @property
    def gather_window_s(self) -> float:
        if self.gather_window_ms is not None:
            return float(self.gather_window_ms) / 1e3
        return min(self.slo_ms / 5.0, 10.0) / 1e3

    @property
    def default_deadline_s(self) -> float:
        if self.default_deadline_ms is not None:
            return float(self.default_deadline_ms) / 1e3
        return 4.0 * self.slo_ms / 1e3

    def backoff_s(self, charge: int) -> float:
        return min(self.backoff_max_s, self.backoff_base_s * (2 ** max(0, charge - 1)))


def serve_config_from_cfg(cfg: Mapping[str, Any]) -> ServeConfig:
    """Build a :class:`ServeConfig` from the composed run config's ``serve``
    node (absent node -> all defaults, faults disabled)."""
    node = _get(cfg, "serve") or {}
    fault_node = _get(node, "fault_injection") or {}
    faults: List[ServeFaultSpec] = []
    if bool(_get(fault_node, "enabled", False)):
        faults = parse_serve_faults(_get(fault_node, "faults") or [])
    fleet_node = _get(node, "fleet") or {}
    fleet = FleetConfig(
        enabled=bool(_get(fleet_node, "enabled", False)),
        num_replicas=int(_get(fleet_node, "num_replicas", 4)),
        min_replicas=int(_get(fleet_node, "min_replicas", 1)),
        max_replicas=int(_get(fleet_node, "max_replicas", 8)),
        cpu_spill_replicas=int(_get(fleet_node, "cpu_spill_replicas", 0)),
        backlog_per_replica=int(_get(fleet_node, "backlog_per_replica", 16)),
        max_pending=(
            None
            if _get(fleet_node, "max_pending", None) is None
            else int(_get(fleet_node, "max_pending"))
        ),
        hedge_quantile=float(_get(fleet_node, "hedge_quantile", 0.95)),
        hedge_floor_ms=float(_get(fleet_node, "hedge_floor_ms", 0.0) or 0.0),
        hedge_max=int(_get(fleet_node, "hedge_max", 1)),
        hedge_scan_ms=float(_get(fleet_node, "hedge_scan_ms", 5.0)),
        spill_depth=int(_get(fleet_node, "spill_depth", 4)),
        autoscale_interval_s=float(_get(fleet_node, "autoscale_interval_s", 0.25)),
        scale_up_depth=float(_get(fleet_node, "scale_up_depth", 4.0)),
        scale_down_depth=float(_get(fleet_node, "scale_down_depth", 0.5)),
        scale_patience=int(_get(fleet_node, "scale_patience", 3)),
        remote_agents=[str(a) for a in (_get(fleet_node, "remote_agents") or [])],
        remote_timeout_s=float(_get(fleet_node, "remote_timeout_s", 10.0)),
    )
    load_node = _get(node, "load") or {}
    load = LoadConfig(
        enabled=bool(_get(load_node, "enabled", False)),
        duration_s=float(_get(load_node, "duration_s", 10.0)),
        concurrency=int(_get(load_node, "concurrency", 8)),
        rate_hz=float(_get(load_node, "rate_hz", 0.0) or 0.0),
        timeout_ms=_opt_float(_get(load_node, "timeout_ms", None)),
        max_retries=int(_get(load_node, "max_retries", 3)),
        seed=int(_get(load_node, "seed", 0)),
        ramp_steps=int(_get(load_node, "ramp_steps", 0)),
        ramp_start_hz=float(_get(load_node, "ramp_start_hz", 50.0)),
        ramp_factor=float(_get(load_node, "ramp_factor", 1.6)),
    )
    return ServeConfig(
        batch_ladder=list(_get(node, "batch_ladder", None) or [1, 2, 4, 8]),
        slo_ms=float(_get(node, "slo_ms", 100.0)),
        gather_window_ms=_opt_float(_get(node, "gather_window_ms", None)),
        max_queue=int(_get(node, "max_queue", 64)),
        default_deadline_ms=_opt_float(_get(node, "default_deadline_ms", None)),
        num_replicas=int(_get(node, "num_replicas", 2)),
        max_restarts=int(_get(node, "max_restarts", 3)),
        restart_refund_s=_opt_float(_get(node, "restart_refund_s", 600.0)),
        backoff_base_s=float(_get(node, "backoff_base_s", 0.05)),
        backoff_max_s=float(_get(node, "backoff_max_s", 2.0)),
        replica_timeout_s=float(_get(node, "replica_timeout_s", 30.0)),
        breaker_threshold=int(_get(node, "breaker_threshold", 3)),
        monitor_interval_s=float(_get(node, "monitor_interval_s", 0.05)),
        swap_poll_s=float(_get(node, "swap_poll_s", 0.0) or 0.0),
        stats_interval_s=float(_get(node, "stats_interval_s", 5.0)),
        aot_cache_dir=(None if _get(node, "aot_cache_dir", None) is None else str(_get(node, "aot_cache_dir"))),
        faults=faults,
        load=load,
        fleet=fleet,
    )


def _opt_float(v: Any) -> Optional[float]:
    return None if v is None else float(v)


def _get(node: Any, key: str, default: Any = None) -> Any:
    if node is None:
        return default
    if hasattr(node, "get"):
        return node.get(key, default)
    return getattr(node, key, default)
