"""Version-stable ``shard_map`` wrapper.

jax >= 0.7 promotes ``shard_map`` to ``jax.shard_map`` and renames
``check_rep`` to ``check_vma``; older versions only have
``jax.experimental.shard_map.shard_map``. Every algorithm shards its fused
train step through this wrapper (replication checking off: train steps mix
replicated params with data-sharded batches and per-device RNG folding).
"""

from __future__ import annotations

import jax


def shard_map(f, mesh, in_specs, out_specs):
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _legacy

    return _legacy(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)
