"""Mesh runtime + collectives (replaces lightning.fabric, SURVEY.md §2.7)."""

from sheeprl_tpu.parallel.fabric import Fabric, Precision, seed_everything
from sheeprl_tpu.parallel.collectives import (
    all_gather_object,
    broadcast_object,
    host_allreduce_sum,
)

__all__ = [
    "Fabric",
    "Precision",
    "all_gather_object",
    "broadcast_object",
    "host_allreduce_sum",
    "seed_everything",
]
