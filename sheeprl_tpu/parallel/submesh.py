"""Sub-mesh fabric for heterogeneous process roles (reference:
the decoupled player/trainer topology, sheeprl/algos/ppo/ppo_decoupled.py:645-669).

The reference splits ranks into a player (rank 0) and a trainer DDP group
(ranks 1..N-1, ``optimization_pg``). The TPU-native counterpart: the trainer
processes form their OWN ``jax.sharding.Mesh`` over their devices — XLA
collectives among trainers ride ICI/DCN exactly like the reference's
process-group NCCL — while the player never enters that mesh and exchanges
rollouts/params over the host-object plane (``parallel.collectives``).
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class SubMeshFabric:
    """A Fabric-like handle over an explicit device subset. Exposes the
    surface the fused train-step builders consume (``mesh``, ``data_axis``,
    ``world_size``, ``precision``, ``replicate``, ``make_global``,
    ``local_device_count``) so e.g. ``ppo.make_train_fn`` runs unchanged on a
    trainer-only mesh."""

    def __init__(self, base: Any, devices: Sequence[jax.Device], data_axis: str = "data") -> None:
        self.base = base
        self.devices = list(devices)
        self.mesh = Mesh(np.asarray(self.devices), (data_axis,))
        self.data_axis = data_axis
        self.precision = base.precision
        self._process_ids = sorted({d.process_index for d in self.devices})

    @property
    def world_size(self) -> int:
        return len(self.devices)

    @property
    def num_processes(self) -> int:
        return len(self._process_ids)

    @property
    def local_device_count(self) -> int:
        pid = jax.process_index()
        return len([d for d in self.devices if d.process_index == pid])

    @property
    def is_participant(self) -> bool:
        return jax.process_index() in self._process_ids

    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    def replicate(self, tree: Any) -> Any:
        return self.make_global(tree, P())

    def make_global(self, tree: Any, spec: Any) -> Any:
        """Assemble per-process local blocks into a global array over THIS
        mesh (the trainer group's DistributedSampler equivalent)."""
        sharding = NamedSharding(self.mesh, spec if isinstance(spec, P) else P(*spec))
        if self.num_processes == 1:
            return jax.device_put(tree, sharding)
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)), tree
        )


class LocalFabric:
    """Single-process fabric shim for a role that never enters a mesh (the
    decoupled PLAYER): precision from the base fabric, plain device_put
    replication onto the local default device."""

    def __init__(self, base: Any) -> None:
        self.precision = base.precision

    @staticmethod
    def replicate(tree: Any) -> Any:
        return jax.device_put(tree)


def probe_spaces(cfg: Any):
    """Read the observation/action spaces without keeping an env (the
    decoupled TRAINER owns no environments; the reference ships agent args
    from the player instead, ppo_decoupled.py:121-125)."""
    from sheeprl_tpu.envs import make_env

    probe = make_env(cfg, cfg.seed, 0, None, "train", vector_env_idx=0)()
    observation_space = probe.observation_space
    action_space = probe.action_space
    probe.close()
    return observation_space, action_space
