"""Collectives (reference: §2.7 call-site inventory — fabric.all_gather /
all_reduce and TorchCollective object collectives).

Two planes:

- **Device plane**: inside jitted/shard_mapped code use ``jax.lax.psum`` /
  ``pmean`` / ``all_gather`` with a mesh axis name directly — XLA lowers them
  onto ICI. Nothing to wrap; algorithms reference ``fabric.data_axis``.
- **Host/object plane**: the reference moves *Python objects* (log dirs,
  configs, replay-buffer gathers) over gloo object collectives
  (utils/logger.py:52-88, callback.py:40-51). The JAX counterpart here rides
  the device ICI/DCN fabric: objects are pickled to uint8 arrays and moved
  with ``jax.experimental.multihost_utils``-style broadcast built on
  ``process_allgather`` semantics. Single-process fall-through is free.
"""

from __future__ import annotations

import pickle
from typing import Any, List

import jax
import numpy as np


def broadcast_object(obj: Any, src: int = 0) -> Any:
    """Broadcast a picklable object from process ``src`` to every process
    (replaces TorchCollective.broadcast_object_list, utils/logger.py:83-88)."""
    if jax.process_count() == 1:
        return obj
    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj) if jax.process_index() == src else b""
    # equalize lengths: first agree on the size, then ship the bytes
    size = np.asarray([len(payload)], dtype=np.int64)
    all_sizes = multihost_utils.process_allgather(size)
    max_size = int(all_sizes.max())
    buf = np.zeros(max_size, dtype=np.uint8)
    if jax.process_index() == src:
        buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    data = gathered[src]
    length = int(all_sizes[src, 0])
    return pickle.loads(data[:length].tobytes())


def all_gather_object(obj: Any) -> List[Any]:
    """Gather one picklable object per process to every process (replaces
    gloo ``gather_object`` buffer gathers, callback.py:40-51)."""
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj)
    size = np.asarray([len(payload)], dtype=np.int64)
    all_sizes = multihost_utils.process_allgather(size)
    max_size = int(all_sizes.max())
    buf = np.zeros(max_size, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    return [
        pickle.loads(gathered[p, : int(all_sizes[p, 0])].tobytes()) for p in range(jax.process_count())
    ]


def gather_object(obj: Any, dst: int = 0) -> List[Any] | None:
    """Gather one picklable object per process to process ``dst`` only
    (reference rank-0 gloo ``gather_object``, callback.py:40-51). The wire
    pattern is still an allgather (the only primitive the device fabric
    offers), but non-destination processes skip the P unpickles — the
    dominant cost for replay-buffer-sized payloads."""
    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils

    payload = pickle.dumps(obj)
    size = np.asarray([len(payload)], dtype=np.int64)
    all_sizes = multihost_utils.process_allgather(size)
    max_size = int(all_sizes.max())
    buf = np.zeros(max_size, dtype=np.uint8)
    buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
    gathered = multihost_utils.process_allgather(buf)
    if jax.process_index() != dst:
        return None
    return [
        pickle.loads(gathered[p, : int(all_sizes[p, 0])].tobytes()) for p in range(jax.process_count())
    ]


def host_allreduce_sum(value: float) -> float:
    """Sum a host scalar across processes (replaces small fabric.all_reduce
    host syncs, e.g. metric counters)."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    arr = np.asarray([value], dtype=np.float64)
    return float(multihost_utils.process_allgather(arr).sum())
