"""TPU mesh runtime — the framework's replacement for ``lightning.fabric``
(reference L0, SURVEY.md §1/§2.7).

Where the reference wraps each module in DDP and all-reduces gradients over
NCCL (``fabric.setup_module`` / ``fabric.backward``), here distribution is
*declarative*: a ``jax.sharding.Mesh`` with a ``data`` axis (optionally a
``model`` axis for param sharding), batches placed with a data-axis
``NamedSharding`` and params replicated. A ``jax.jit`` train step closed over
those shardings gets its gradient all-reduce inserted by XLA as an ICI
collective — there is no imperative backward/all-reduce pair to call.

Multi-host: ``jax.distributed.initialize`` (DCN) is triggered by env vars or
explicit coordinator config; the same mesh then spans all processes and the
identical jitted step runs on every host (SPMD), replacing the reference's
launcher-spawned DDP ranks (cli.py:190).
"""

from __future__ import annotations

import dataclasses
import os
import re
import warnings
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def seed_everything(seed: int) -> jax.Array:
    """Seed numpy + return the root PRNG key (reference reproducibility
    wrapper, cli.py:174-189; torch/cudnn flags have no TPU counterpart —
    XLA is deterministic modulo collective reduction order)."""
    np.random.seed(seed)
    return jax.random.PRNGKey(seed)


_PRECISIONS = ("fp32", "bf16-mixed", "bf16-true")
# lightning-style spellings accepted from configs (reference fabric configs)
_PRECISION_ALIASES = {"32-true": "fp32", "32": "fp32", "bf16": "bf16-mixed"}


@dataclasses.dataclass(frozen=True)
class Precision:
    """Numeric policy (reference: Fabric precision ``bf16-mixed``,
    configs/fabric/default.yaml; SURVEY §2.8.3).

    - ``fp32``: everything float32.
    - ``bf16-mixed``: fp32 params/optimizer state, bf16 compute on the MXU —
      the policy matching the reference's GPU recipe.
    - ``bf16-true``: bf16 params and compute (halves HBM, used by the
      reference test-suite).
    """

    name: str = "fp32"

    def __post_init__(self) -> None:
        object.__setattr__(self, "name", _PRECISION_ALIASES.get(self.name, self.name))
        if self.name not in _PRECISIONS:
            raise ValueError(
                f"unknown precision {self.name!r}; choose from {_PRECISIONS} (aliases: {_PRECISION_ALIASES})"
            )

    @property
    def param_dtype(self) -> jnp.dtype:
        return jnp.bfloat16 if self.name == "bf16-true" else jnp.float32

    @property
    def compute_dtype(self) -> jnp.dtype:
        return jnp.bfloat16 if self.name in ("bf16-mixed", "bf16-true") else jnp.float32

    def cast_to_compute(self, tree: Any) -> Any:
        dtype = self.compute_dtype
        return jax.tree.map(
            lambda x: x.astype(dtype) if isinstance(x, (jax.Array, np.ndarray)) and jnp.issubdtype(x.dtype, jnp.floating) else x,
            tree,
        )


# --------------------------------------------------------------------------- #
# Regex partition-rule table (megatron-lm / EasyLM style)
# --------------------------------------------------------------------------- #
# Rules map a regex over the '/'-joined pytree path of a leaf to a sharding
# strategy. Because Adam's mu/nu (and any EMA twin of the params) mirror the
# param tree structure, a rule anchored on the leaf name ("kernel") covers the
# param AND its optimizer-state twins — the property the fused superstep needs
# so opt/EMA carries stay model-sharded instead of silently riding replicated.
#
# Strategies: "auto" (shape-based model-axis rule, Fabric.param_spec),
# "replicate" (force P()), or an explicit PartitionSpec. First match wins;
# unmatched leaves fall back to replicated with a warn-once per path.
DEFAULT_PARTITION_RULES: Tuple[Tuple[str, Any], ...] = (
    # dense/conv kernels and embeddings (+ their mu/nu/EMA twins): shape rule
    (r"(^|/)(kernel|embedding)$", "auto"),
    # LayerNorm affine, biases, the learnable h0: small — keep replicated
    (r"(^|/)(bias|scale|initial_recurrent_state)$", "replicate"),
    # optimizer bookkeeping and return-normalizer moments: scalars
    (r"(^|/)(count|mu_hat|nu_hat|low|high)$", "replicate"),
)

_warned_unmatched_paths: set = set()


def reset_partition_rule_warnings() -> None:
    """Re-arm the unmatched-leaf warn-once filter (tests / repeated runs)."""
    _warned_unmatched_paths.clear()


def _path_token(entry: Any) -> str:
    """One tree-path entry as a plain string: dict keys, namedtuple/attr
    fields and sequence indices all render bare so rules can anchor on
    ``(^|/)name$`` regardless of the container type."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def tree_path_str(path: Sequence[Any]) -> str:
    """'/'-joined rendering of a ``tree_flatten_with_path`` key path, e.g.
    ``1/0/mu/Dense_0/kernel`` for the Adam mu twin of a flax kernel."""
    return "/".join(_path_token(e) for e in path)


class Fabric:
    """Device mesh + precision + process topology in one handle.

    Args:
        devices: number of devices to use (``-1`` / ``None`` = all).
        precision: one of ``fp32`` / ``bf16-mixed`` / ``bf16-true``.
        mesh_axes: axis names; first axis is the data axis. Default 1-D
            ``("data",)`` — pure DP, the reference's only strategy
            (SURVEY §2.7). A 2-D ``("data", "model")`` mesh enables param
            sharding for larger models.
        mesh_shape: sizes per axis; ``-1`` infers from the device count.
    """

    def __init__(
        self,
        devices: Optional[int | str] = None,
        precision: str = "fp32",
        accelerator: str = "auto",
        num_nodes: int = 1,
        mesh_axes: Sequence[str] = ("data",),
        mesh_shape: Optional[Sequence[int]] = None,
        callbacks: Optional[Sequence[Any]] = None,
        distributed_coordinator: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
        compilation_cache_dir: Optional[str] = None,
        aot_cache_dir: Optional[str] = None,
    ) -> None:
        group_size = self._maybe_init_distributed(distributed_coordinator, num_processes, process_id)
        if accelerator not in ("auto", "tpu", "cpu", "gpu"):
            raise ValueError(f"unknown accelerator {accelerator!r}")
        if accelerator == "cpu":
            # must happen before the first device query in this process
            try:
                jax.config.update("jax_platforms", "cpu")
            except RuntimeError:
                pass  # backend already initialized; devices below reflect it
        self.compilation_cache_dir = self._configure_compilation_cache(compilation_cache_dir, group_size)
        # AOT *executable* cache (ops/aotcache, howto/aot_cache.md): one tier
        # above the trace cache — the fused-superstep builders serialize
        # whole compiled windows through it so a preemption-resume skips the
        # compile entirely instead of just the retrace
        self.aot_cache = None
        self.aot_cache_dir = None
        if aot_cache_dir:
            from sheeprl_tpu.ops.aotcache import AotCache

            self.aot_cache_dir = os.path.abspath(os.path.expanduser(str(aot_cache_dir)))
            self.aot_cache = AotCache(self.aot_cache_dir)
        self.accelerator = accelerator
        self.num_nodes = num_nodes
        self.callbacks = list(callbacks or [])
        if devices in ("auto", "-1"):
            devices = None
        all_devices = jax.devices()
        n = len(all_devices) if devices in (None, -1) else int(devices)
        if n <= 0 or n > len(all_devices):
            raise ValueError(f"requested {devices} devices but {len(all_devices)} are available")
        self.devices = all_devices[:n]
        self.precision = Precision(precision)
        axes = tuple(mesh_axes)
        if mesh_shape is None:
            shape: Tuple[int, ...] = (n,) + (1,) * (len(axes) - 1)
        else:
            shape = tuple(mesh_shape)
            inferred = [i for i, s in enumerate(shape) if s == -1]
            if len(inferred) > 1:
                raise ValueError("at most one mesh axis may be -1")
            if inferred:
                known = int(np.prod([s for s in shape if s != -1])) or 1
                shape = tuple(n // known if s == -1 else s for s in shape)
        if int(np.prod(shape)) != n:
            raise ValueError(f"mesh shape {shape} does not cover {n} devices")
        self.mesh = Mesh(np.asarray(self.devices).reshape(shape), axes)
        self.data_axis = axes[0]

    @staticmethod
    def _configure_compilation_cache(cache_dir: Optional[str], group_size: int = 1) -> Optional[str]:
        """Point JAX's persistent compilation cache at
        ``fabric.compilation_cache_dir`` (default off) so restarts and
        resumes skip the multi-minute retrace of the train programs. The
        min-compile-time/min-entry-size gates are zeroed so even the small
        kernels (buffer writes, gathers) persist — the cache-outcome
        telemetry (``compile_cache`` events) counts every request.

        With ``group_size`` > 1 the cache is refused on the CPU backend and
        suffixed per group size elsewhere: the trace cache keys on HLO +
        device assignment but NOT on process topology, and a gloo
        cross-process CPU executable does not even survive a warm-cache
        reload of its own topology — both failure modes deserialize an
        executable whose collectives no longer reach the group and compute
        garbage without erroring."""
        if not cache_dir:
            return None
        path = os.path.abspath(os.path.expanduser(str(cache_dir)))
        if group_size > 1:
            if jax.default_backend() == "cpu":
                return None
            path = f"{path}-p{group_size}"
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        for flag, value in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", 0),
        ):
            try:
                jax.config.update(flag, value)
            except Exception:
                pass  # knob not present in this jax version
        return path

    @staticmethod
    def _maybe_init_distributed(
        coordinator: Optional[str], num_processes: Optional[int], process_id: Optional[int]
    ) -> int:
        """DCN process-group bring-up (replaces TorchCollective.setup,
        ppo_decoupled.py:645-649). No-op on a single host. Returns the
        process-group size (1 when not distributed)."""
        if coordinator is None and "SHEEPRL_TPU_COORDINATOR" in os.environ:
            coordinator = os.environ["SHEEPRL_TPU_COORDINATOR"]
            num_processes = int(os.environ["SHEEPRL_TPU_NUM_PROCESSES"]) if "SHEEPRL_TPU_NUM_PROCESSES" in os.environ else None
            process_id = int(os.environ["SHEEPRL_TPU_PROCESS_ID"]) if "SHEEPRL_TPU_PROCESS_ID" in os.environ else None
        if coordinator is None:
            return 1
        # a configured coordinator with a missing/1 process count is a broken
        # launch, not a single-host run: every host would train independently
        # as process 0 with no cross-host reduction
        if not num_processes or num_processes <= 1 or process_id is None:
            raise ValueError(
                "distributed coordinator is set but num_processes/process_id are not — set "
                "SHEEPRL_TPU_NUM_PROCESSES (> 1) and SHEEPRL_TPU_PROCESS_ID on every host"
            )
        # CPU multi-process meshes need the gloo collectives client (the
        # default CPU backend refuses cross-process computations outright);
        # harmless on TPU hosts, where it only governs their cpu devices
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # knob not present in this jax version
        # NOTE: do not probe jax.process_count() here — it initializes the
        # backend, after which distributed init is impossible; initialize
        # eagerly and tolerate an already-connected process group
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=num_processes,
                process_id=process_id,
            )
        except RuntimeError as e:
            # jax raises "distributed.initialize should only be called once"
            # on re-init and "must be called before any JAX computations" when
            # the caller initialized the backend first (e.g. an external
            # launcher already connected the process group)
            msg = str(e).lower()
            if not any(s in msg for s in ("already", "only be called once", "must be called before")):
                raise
        # tolerating the error is only safe when a process group actually
        # exists: otherwise every host would silently train alone as rank 0
        if jax.process_count() != num_processes:
            raise RuntimeError(
                f"distributed init requested {num_processes} processes but the JAX backend sees "
                f"{jax.process_count()} — initialize jax.distributed before any JAX computation "
                "(or let Fabric do it by constructing it first)"
            )
        # The persistent trace cache cannot round-trip a gloo cross-process
        # CPU executable: a warm-cache run — even of the SAME topology that
        # wrote the entry — deserializes an executable whose collectives no
        # longer reach the group and computes garbage without erroring.
        # Disable any env-configured cache for multi-process CPU groups
        # (jax already copied the env value into config at import, so the
        # config update is the one that matters). TPU groups keep theirs.
        if jax.default_backend() == "cpu":
            os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
            try:
                jax.config.update("jax_compilation_cache_dir", None)
            except Exception:
                pass
        return num_processes

    # ------------------------------------------------------------------ #
    # topology
    # ------------------------------------------------------------------ #
    @property
    def world_size(self) -> int:
        return len(self.devices)

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        """Host-process count — the analogue of the reference's rank count for
        step accounting (each process drives ``num_envs`` envs). NOT the chip
        count: one SPMD process feeds many chips."""
        return jax.process_count()

    @property
    def is_global_zero(self) -> bool:
        return jax.process_index() == 0

    @property
    def local_device_count(self) -> int:
        return len([d for d in self.devices if d.process_index == jax.process_index()])

    @property
    def model_axis(self) -> Optional[str]:
        """Name of the param-sharding mesh axis, or None on a pure-DP mesh
        (``mesh_axes=[data, model]`` + ``mesh_shape=[d, m]`` with m > 1
        enables it)."""
        if "model" in self.mesh.axis_names and self.mesh.shape["model"] > 1:
            return "model"
        return None

    @property
    def model_parallel_size(self) -> int:
        return self.mesh.shape["model"] if "model" in self.mesh.axis_names else 1

    @property
    def data_parallel_size(self) -> int:
        """Width of the batch split — the data axis alone, NOT world_size
        (on a 2-D mesh each batch shard is co-owned by ``model`` peers)."""
        return self.mesh.shape[self.data_axis]

    @property
    def local_data_parallel_size(self) -> int:
        """This process's share of the data axis (its sampling quota)."""
        return max(1, self.local_device_count // self.model_parallel_size)

    @property
    def pure_data_parallel(self) -> bool:
        """True when the whole mesh is one process × one data axis — the only
        topology where explicit-collective SPMD (``shard_map`` supersteps,
        the sharded replay ring) is sound: no param axis to cut across, and
        every shard of the scan lives in this process's dispatch."""
        return self.num_processes == 1 and self.model_axis is None

    # ------------------------------------------------------------------ #
    # placement
    # ------------------------------------------------------------------ #
    def sharding(self, *spec: Any) -> NamedSharding:
        return NamedSharding(self.mesh, P(*spec))

    @property
    def batch_sharding(self) -> NamedSharding:
        """Leading-axis data-parallel placement."""
        return NamedSharding(self.mesh, P(self.data_axis))

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    def shard_batch(self, tree: Any) -> Any:
        """Place host arrays with the leading axis split across the data axis
        (replaces per-rank ``to(device)`` copies; one transfer per shard)."""
        return jax.device_put(tree, self.batch_sharding)

    def replicate(self, tree: Any) -> Any:
        """Fully replicate params/state across the mesh (the JAX counterpart
        of DDP module broadcast, dreamer_v3/agent.py:1205-1214)."""
        return jax.device_put(tree, self.replicated)

    def param_spec(self, leaf: Any) -> P:
        """PartitionSpec for one param/optimizer-state leaf on this mesh.

        Rule (scaling-book tensor-parallel recipe, GSPMD does the rest): on a
        mesh with a ``model`` axis, shard the LAST dimension of any >=2-D
        array over it when divisible (column-parallel dense/conv kernels —
        activations pick up the sharding and XLA inserts the all-gathers /
        reduce-scatters); fall back to the second-to-last dimension
        (row-parallel) when only that divides; replicate everything else
        (biases, scales, scalars). Applying the same rule to optimizer state
        automatically co-shards Adam moments with their params."""
        axis = self.model_axis
        shape = getattr(leaf, "shape", ())
        if axis is None or len(shape) < 2:
            return P()
        m = self.mesh.shape[axis]
        if shape[-1] % m == 0 and shape[-1] >= m:
            return P(*([None] * (len(shape) - 1) + [axis]))
        if shape[-2] % m == 0 and shape[-2] >= m:
            return P(*([None] * (len(shape) - 2) + [axis, None]))
        return P()

    def shard_params(self, tree: Any) -> Any:
        """Place a param/optimizer pytree with the :meth:`param_spec` rule —
        param sharding over the ``model`` axis when the mesh has one,
        plain replication otherwise (so call sites need no topology check)."""
        if self.model_axis is None:
            return self.replicate(tree)
        # ONE batched device_put for the whole tree: per-leaf puts would pay
        # a dispatch round trip per leaf (remote-attached chips: ~100 ms
        # each, minutes for an XL tree)
        shardings = jax.tree.map(
            lambda leaf: NamedSharding(self.mesh, self.param_spec(leaf)), tree
        )
        return jax.device_put(tree, shardings)

    def match_partition_rules(self, tree: Any, rules: Optional[Sequence[Tuple[str, Any]]] = None) -> Any:
        """PartitionSpec pytree for ``tree`` from a regex rule table.

        Every leaf's '/'-joined path (:func:`tree_path_str`) is matched
        against ``rules`` (default :data:`DEFAULT_PARTITION_RULES`) in order;
        the first hit decides the spec: ``"auto"`` delegates to the
        shape-based :meth:`param_spec`, ``"replicate"`` forces ``P()``, and
        an explicit ``PartitionSpec`` is used verbatim. Unmatched leaves fall
        back to replicated with a warn-once per path — a silent fallback on
        a large matrix is exactly the all-gather-per-scan-step bug this
        table exists to prevent.

        Because optimizer state (Adam mu/nu) and EMA twins mirror the param
        tree, applying the same table to the whole superstep carry
        ``(params, opt, ema, moments)`` co-shards every twin of a kernel
        with the kernel itself. Returns a pytree with the exact structure of
        ``tree`` whose leaves are ``PartitionSpec``s (feed through
        ``NamedSharding(mesh, spec)`` for placement or jit shardings).
        """
        table = DEFAULT_PARTITION_RULES if rules is None else tuple(rules)
        compiled = [(re.compile(pattern), strategy) for pattern, strategy in table]
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = []
        for path, leaf in flat:
            name = tree_path_str(path)
            for pattern, strategy in compiled:
                if pattern.search(name):
                    if strategy == "auto":
                        specs.append(self.param_spec(leaf))
                    elif strategy == "replicate":
                        specs.append(P())
                    elif isinstance(strategy, P):
                        specs.append(strategy)
                    else:
                        raise ValueError(
                            f"unknown partition-rule strategy {strategy!r} for pattern "
                            f"{pattern.pattern!r} (use 'auto', 'replicate' or a PartitionSpec)"
                        )
                    break
            else:
                if name not in _warned_unmatched_paths:
                    _warned_unmatched_paths.add(name)
                    warnings.warn(
                        f"no partition rule matched leaf {name!r} "
                        f"(shape={getattr(leaf, 'shape', ())}); replicating it — add a rule "
                        "if this leaf should be model-sharded",
                        UserWarning,
                        stacklevel=2,
                    )
                specs.append(P())
        return jax.tree_util.tree_unflatten(treedef, specs)

    def carry_shardings(self, tree: Any, rules: Optional[Sequence[Tuple[str, Any]]] = None) -> Any:
        """:meth:`match_partition_rules` materialised as ``NamedSharding``s
        (same structure as ``tree``) — the form ``jax.jit`` in/out shardings
        and ``with_sharding_constraint`` consume."""
        specs = self.match_partition_rules(tree, rules)
        return jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), specs, is_leaf=lambda s: isinstance(s, P)
        )

    def make_global(self, tree: Any, spec: Any) -> Any:
        """Assemble per-process host arrays into one global sharded array
        (multi-host only; single process returns the tree untouched). ``spec``
        is the PartitionSpec of the GLOBAL array — each process contributes
        its local block along the sharded axes, replacing the reference's
        per-rank DistributedSampler feeding (SURVEY §2.7)."""
        if jax.process_count() == 1:
            return tree
        sharding = NamedSharding(self.mesh, spec if isinstance(spec, P) else P(*spec))
        return jax.tree.map(lambda x: jax.make_array_from_process_local_data(sharding, np.asarray(x)), tree)

    def local_batch_size(self, global_batch_size: int) -> int:
        data_size = self.mesh.shape[self.data_axis]
        if global_batch_size % data_size != 0:
            raise ValueError(
                f"global batch size {global_batch_size} is not divisible by the data-axis size {data_size}"
            )
        return global_batch_size // data_size

    # ------------------------------------------------------------------ #
    # checkpoint I/O (process-0 writes; reference fabric.save/load)
    # ------------------------------------------------------------------ #
    def save(self, path: str, state: Dict[str, Any]) -> None:
        from sheeprl_tpu.utils.checkpoint import save_checkpoint

        if self.is_global_zero:
            save_checkpoint(path, state)

    def load(self, path: str) -> Dict[str, Any]:
        from sheeprl_tpu.utils.checkpoint import load_checkpoint

        return load_checkpoint(path)

    def call(self, hook: str, **kwargs: Any) -> None:
        """Invoke ``hook`` on every registered callback (replaces
        ``fabric.call("on_checkpoint_coupled")``, dreamer_v3.py:752-758)."""
        for cb in self.callbacks:
            fn = getattr(cb, hook, None)
            if callable(fn):
                fn(fabric=self, **kwargs)

    def __repr__(self) -> str:
        return (
            f"Fabric(devices={self.world_size}, mesh={dict(self.mesh.shape)}, "
            f"precision={self.precision.name!r}, processes={jax.process_count()})"
        )


# --------------------------------------------------------------------------- #
# Player placement — learner-on-chip / actor-on-host split
# --------------------------------------------------------------------------- #
#
# The reference runs the player's forward on the same device as training (its
# player shares CUDA storage with the trainer, dreamer_v3/agent.py:1229-1235).
# On TPU that is also the default — but when the chip is *remote-attached*
# (e.g. tunnelled), every per-env-step action fetch pays a full network round
# trip, which caps env-steps/sec at 1/RTT regardless of model speed. In that
# regime the policy-inference nets (small in every reference recipe) are
# cheaper to run on the host CPU backend, with parameters streamed chip→host
# once per train block instead of one action fetch per env step.

_RTT_PROBE_THRESHOLD_S = 0.005
_rtt_cache: Dict[str, float] = {}


def dispatch_roundtrip_seconds() -> float:
    """Measured dispatch+fetch latency of a tiny op on the default backend
    (compile excluded, cached per process)."""
    if "rtt" not in _rtt_cache:
        import time

        f = jax.jit(lambda a: a + 1.0)
        x = jnp.zeros((1,), jnp.float32)
        np.asarray(f(x))  # compile
        t0 = time.perf_counter()
        for _ in range(3):
            np.asarray(f(x))
        _rtt_cache["rtt"] = (time.perf_counter() - t0) / 3
    return _rtt_cache["rtt"]


#: params budget under which 'auto' host-trains: a 2x64 control MLP's whole
#: fused update is ~100 ms of 1-core CPU per 8k-step rollout, far cheaper
#: than the per-update upload + blocking fetches a remote-attached chip
#: charges; a pixel CNN (>~1M params) stays on the accelerator
_HOST_TRAIN_PARAM_BUDGET = 300_000


def resolve_train_device(spec: str, params: Any, world_size: int) -> Optional[jax.Device]:
    """Resolve a train-placement spec to a device (None = default backend).

    The PPO-family interaction benchmark is dominated by the env loop on the
    host; when the accelerator is REMOTE-attached, shipping each update's
    tiny minibatch program across the link (upload + dispatch + metric and
    param fetches) costs more wall-clock than running the whole fused update
    on the host core. ``auto`` host-trains exactly in that regime: single
    device, remote backend (same RTT probe as the player), and a model under
    ``_HOST_TRAIN_PARAM_BUDGET`` params. Multi-device runs always train on
    the mesh.
    """
    if spec not in (None, "accelerator", "device", "cpu", "auto"):
        raise ValueError(f"unknown train_device spec {spec!r} (accelerator | cpu | auto)")
    if spec in (None, "accelerator", "device"):
        return None
    if world_size > 1:
        if spec == "cpu":
            raise ValueError("algo.train_device=cpu requires a single-device run")
        return None
    if spec == "cpu":
        return jax.local_devices(backend="cpu")[0]
    # auto
    if jax.local_devices()[0].platform == "cpu":
        return None  # default backend is already the host
    n_params = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(params))
    if n_params <= _HOST_TRAIN_PARAM_BUDGET and dispatch_roundtrip_seconds() > _RTT_PROBE_THRESHOLD_S:
        return jax.local_devices(backend="cpu")[0]
    return None


def resolve_player_device(spec: str = "auto") -> Optional[jax.Device]:
    """Resolve a player-placement spec to a device (None = default backend).

    - ``accelerator``: play on the training backend (reference behavior).
    - ``cpu``: play on the host CPU backend.
    - ``auto``: play on the training backend unless a tiny-op probe shows it
      is remote-attached (round trip > 5 ms) — then the host runs the policy
      and the env loop never blocks on the link. This includes conv policies:
      measured on the round-3 box, a pixel-encoder forward at benchmark sizes
      is ~0.5 ms and ~2.6 ms at the S model size on one host core, both far
      under the ~95 ms tunnel round trip an on-accelerator action fetch pays.
    """
    if spec in (None, "accelerator"):
        return None
    cpu = jax.local_devices(backend="cpu")[0]
    if spec == "cpu":
        return None if jax.default_backend() == "cpu" else cpu
    if spec == "auto":
        if jax.default_backend() == "cpu":
            return None
        return cpu if dispatch_roundtrip_seconds() > _RTT_PROBE_THRESHOLD_S else None
    raise ValueError(f"unknown player device spec {spec!r}; use accelerator/cpu/auto")


def put_tree(tree: Any, device: Optional[jax.Device]) -> Any:
    """``jax.device_put`` a pytree onto ``device`` (async); identity when
    ``device`` is None. The cross-backend chip→CPU copy is how player params
    refresh after each train block in host-player mode."""
    if device is None:
        return tree
    return jax.device_put(tree, device)


class _ParamStreamer:
    """One-round-trip cross-backend pytree transfer.

    ``jax.device_put`` of a pytree moves it leaf by leaf — over a
    remote-attached chip that is one network round trip PER LEAF (measured:
    60 small leaves ≈ 7.6 s vs 0.2 s for one flat array). This packs every
    leaf into a single byte vector with a jitted concat on the source
    backend, crosses once, and rebuilds the tree with a jitted split on the
    target backend — the TPU analogue of the reference's flat param-vector
    broadcast (ppo_decoupled.py:126-130)."""

    def __init__(self, tree: Any, device: jax.Device) -> None:
        leaves, self.treedef = jax.tree.flatten(tree)
        self.shapes = tuple(tuple(l.shape) for l in leaves)
        self.dtypes = tuple(jnp.dtype(l.dtype) for l in leaves)
        self.device = device
        sizes = [int(np.prod(s)) * d.itemsize for s, d in zip(self.shapes, self.dtypes)]
        self.offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int64)
        self.nbytes = int(self.offsets[-1])  # pack size; sizes the landing estimate

        def _to_bytes(leaf, dtype):
            if dtype == jnp.uint8:
                return leaf.reshape(-1)
            if dtype == jnp.dtype(jnp.bool_):
                return leaf.astype(jnp.uint8).reshape(-1)
            # same-width bitcast for int8, per-byte split for wider dtypes
            return jax.lax.bitcast_convert_type(leaf, jnp.uint8).reshape(-1)

        def pack(leaves):
            return jnp.concatenate([_to_bytes(l, d) for l, d in zip(leaves, self.dtypes)])

        def unpack(flat):
            out = []
            for s, d, o0, o1 in zip(self.shapes, self.dtypes, self.offsets[:-1], self.offsets[1:]):
                seg = flat[int(o0) : int(o1)]
                if d == jnp.uint8:
                    out.append(seg.reshape(s))
                elif d == jnp.dtype(jnp.bool_):
                    out.append(seg.reshape(s).astype(d))
                elif d.itemsize == 1:
                    out.append(jax.lax.bitcast_convert_type(seg.reshape(s), d))
                else:
                    out.append(jax.lax.bitcast_convert_type(seg.reshape(s + (d.itemsize,)), d))
            return out

        self._pack = jax.jit(pack)
        self._unpack = jax.jit(unpack)

    def matches(self, tree: Any) -> bool:
        leaves, treedef = jax.tree.flatten(tree)
        return (
            treedef == self.treedef
            and tuple(tuple(l.shape) for l in leaves) == self.shapes
            and tuple(jnp.dtype(l.dtype) for l in leaves) == self.dtypes
        )

    def __call__(self, tree: Any) -> Any:
        leaves = jax.tree.leaves(tree)
        flat = self._pack(leaves)
        flat = jax.device_put(flat, self.device)
        return jax.tree.unflatten(self.treedef, self._unpack(flat))

    # Deferred two-phase transfer: ``begin`` packs on the source backend and
    # starts the device→host copy without waiting for it; ``finish`` (called
    # a train block or two later) materializes the bytes — by then the copy
    # has landed and costs ~0 instead of one blocking round trip. This is
    # what lets a host-pinned player refresh params without ever stalling
    # the env loop on the tunnel.
    def begin(self, tree: Any) -> Any:
        flat = self._pack(jax.tree.leaves(tree))
        try:
            flat.copy_to_host_async()
        except AttributeError:  # non-jax.Array inputs (already host)
            pass
        return flat

    def finish(self, flat: Any) -> Any:
        host = np.asarray(flat)
        placed = jax.device_put(host, self.device)
        return jax.tree.unflatten(self.treedef, self._unpack(placed))


class DispatchFence:
    """Bounded-backlog throttle for fully-asynchronous training loops.

    A loop that never fetches from the device can race arbitrarily far ahead
    of it — thousands of queued executions eventually overload the transfer
    plane of a remote-attached chip (observed as spurious INVALID_ARGUMENT
    surfacing at unrelated dispatches). ``push`` takes any device array from
    the newest dispatch group, keeps a 1-element slice of it as a marker with
    an async device→host copy, and blocks on the OLDEST marker once more than
    ``depth`` groups are in flight — so the host stays at most ``depth``
    groups ahead while paying ~0 per fence in the steady state (the old
    marker's copy has long landed)."""

    def __init__(self, depth: int = 4) -> None:
        import collections

        self.depth = max(1, int(depth))
        self._pending: "collections.deque" = collections.deque()

    def push(self, marker: Any) -> None:
        m = jnp.ravel(marker)[:1]
        try:
            m.copy_to_host_async()
        except AttributeError:
            pass
        self._pending.append(m)
        while len(self._pending) > self.depth:
            np.asarray(self._pending.popleft())

    def drain(self) -> None:
        while self._pending:
            np.asarray(self._pending.popleft())


class _StreamPipe:
    """At-most-one-in-flight async param stream with a pending candidate.

    ``offer`` never blocks: if a transfer is in flight the newest tree is
    stashed and streamed when the current one lands. ``poll`` returns a
    materialized tree once the in-flight copy is old enough to have crossed
    the link (age gate — the axon client exposes no completion event for
    host copies), else None."""

    def __init__(self, streamer: "_ParamStreamer") -> None:
        self.streamer = streamer
        self._inflight: Optional[Tuple[Any, float]] = None
        self._candidate: Any = None

    @staticmethod
    def _link_bytes_per_s() -> float:
        """Assumed device→host bulk bandwidth for the landing estimate —
        conservative floor of the measured ~14 MB/s tunnel rate (BASELINE.md
        link table); override with SHEEPRL_TPU_LINK_BYTES_PER_S."""
        try:
            value = float(os.environ.get("SHEEPRL_TPU_LINK_BYTES_PER_S", 10e6))
        except ValueError:
            return 10e6
        # `v > 1e3` is False for nan too — max() would keep nan and silently
        # disable the bytes term of the gate
        return value if value > 1e3 else 1e3

    def _age_threshold(self) -> float:
        # the copy cannot have landed before bytes/bandwidth + one RTT have
        # passed; polling earlier turns the "free" finish into a BLOCKING
        # partial-transfer wait (measured 1.5 s per poll on ~20 MB packs in
        # the SAC-AE loop, which polls every update). Waiting the full
        # landing estimate costs only param staleness, which the async
        # design already accepts. The bytes term only applies on REMOTE
        # links (same RTT probe as player auto-placement) — a locally
        # attached device moves GB/s and the old cheap gate is right.
        rtt = dispatch_roundtrip_seconds()
        if rtt <= _RTT_PROBE_THRESHOLD_S:
            return max(1.5 * rtt, 0.02)
        xfer = self.streamer.nbytes / self._link_bytes_per_s()
        return max(1.5 * rtt, 0.02, xfer + rtt)

    def offer(self, tree: Any) -> None:
        import time

        if self._inflight is None:
            self._inflight = (self.streamer.begin(tree), time.perf_counter())
        else:
            self._candidate = tree

    def poll(self) -> Any:
        import time

        if self._inflight is None:
            return None
        flat, t0 = self._inflight
        if time.perf_counter() - t0 < self._age_threshold():
            return None
        tree = self.streamer.finish(flat)
        self._inflight = None
        if self._candidate is not None:
            self._inflight = (self.streamer.begin(self._candidate), time.perf_counter())
            self._candidate = None
        return tree

    def flush(self) -> Any:
        """Force-finish everything in flight (end of training): returns the
        NEWEST tree, blocking as needed — the age gate does not apply."""
        out = None
        if self._inflight is not None:
            out = self.streamer.finish(self._inflight[0])
            self._inflight = None
        if self._candidate is not None:
            out = self.streamer.finish(self.streamer.begin(self._candidate))
            self._candidate = None
        return out


class HostPlayerParams:
    """Mixin for player classes: any assignment to an attribute named in
    ``_placed_attrs`` is placed onto ``self.device`` (async) when the player
    is pinned to another backend. This keeps every
    ``player.params = new_params`` sync site in the algorithm loops — and the
    exploration/task actor swaps of the P2E entrypoints — correct in
    host-player mode without touching the call sites; with ``device=None``
    assignments pass through untouched.

    Cross-backend trees with several device-resident leaves stream as ONE
    flat transfer (see ``_ParamStreamer``); host/numpy trees and trees
    already on the target device fall through to a plain ``device_put``."""

    _placed_attrs: Tuple[str, ...] = ()

    def __setattr__(self, name: str, value: Any) -> None:
        if name in self._placed_attrs and value is not None:
            dev = getattr(self, "device", None)
            if dev is not None:
                value = self._place(name, value, dev)
        object.__setattr__(self, name, value)

    def _place(self, name: str, value: Any, dev: jax.Device) -> Any:
        remote = [
            l
            for l in jax.tree.leaves(value)
            if isinstance(l, jax.Array) and dev not in l.devices()
        ]
        if len(remote) <= 2:
            return jax.device_put(value, dev)
        streamer = self._streamer_for(name, value, dev)
        return streamer(value)

    def _streamer_for(self, name: str, value: Any, dev: jax.Device) -> "_ParamStreamer":
        streamers = getattr(self, "_streamers", None)
        if streamers is None:
            streamers = {}
            object.__setattr__(self, "_streamers", streamers)
        streamer = streamers.get(name)
        if streamer is None or not streamer.matches(value):
            streamer = _ParamStreamer(value, dev)
            streamers[name] = streamer
        return streamer

    def stream_attr(self, name: str, value: Any) -> None:
        """Non-blocking variant of ``self.<name> = value`` for hot loops.

        Synchronous placement pays one blocking device→host round trip per
        train block (~0.1–0.2 s over a remote-attached chip). This streams the
        tree through a :class:`_StreamPipe` instead: the assignment returns
        immediately and the attribute flips to the new params one or two
        blocks later, once the async copy has landed. Use only where a few
        blocks of param staleness is acceptable (the actor-learner lag of any
        async RL system); latency-sensitive swaps (e.g. exchanging the
        exploration actor for the task actor) must keep plain assignment."""
        dev = getattr(self, "device", None)
        if dev is None or value is None:
            object.__setattr__(self, name, value)
            return
        remote = [
            l
            for l in jax.tree.leaves(value)
            if isinstance(l, jax.Array) and dev not in l.devices()
        ]
        if len(remote) <= 2:
            object.__setattr__(self, name, jax.device_put(value, dev))
            return
        pipes = getattr(self, "_stream_pipes", None)
        if pipes is None:
            pipes = {}
            object.__setattr__(self, "_stream_pipes", pipes)
        streamer = self._streamer_for(name, value, dev)
        pipe = pipes.get(name)
        if pipe is None or pipe.streamer is not streamer:
            pipe = _StreamPipe(streamer)
            pipes[name] = pipe
        landed = pipe.poll()
        if landed is not None:
            object.__setattr__(self, name, landed)
        pipe.offer(value)

    def poll_stream_attrs(self) -> None:
        """Land any in-flight async param stream that has finished copying
        (non-blocking). Players call this from the action path so params
        still flip under sparse Ratio schedules, where the next
        :meth:`stream_attr` call — the only other landing site — may be many
        env steps away."""
        pipes = getattr(self, "_stream_pipes", None)
        if not pipes:
            return
        for name, pipe in pipes.items():
            landed = pipe.poll()
            if landed is not None:
                object.__setattr__(self, name, landed)

    def flush_stream_attrs(self) -> None:
        """Land every in-flight async param stream NOW (blocking). Training
        loops call this after their last update so the closing evaluation /
        model registration sees the final weights, not ones a train block
        stale."""
        pipes = getattr(self, "_stream_pipes", None)
        if not pipes:
            return
        for name, pipe in pipes.items():
            tree = pipe.flush()
            if tree is not None:
                object.__setattr__(self, name, tree)
