"""``python -m sheeprl_tpu serve checkpoint_path=... [overrides]``.

Serve a *committed* training checkpoint as a policy service (see
``howto/serving.md``). Follows the ``cli_eval`` conventions: the run config
stored beside the checkpoint is rebuilt, ``key=value`` overrides are applied
on top (so ``serve.slo_ms=50 serve.num_replicas=4`` tune the tier without
touching the stored config), and the algorithm name picks the policy builder.

Sources, one of:

- ``checkpoint_path=<ckpt>`` — serve exactly this checkpoint; it must carry
  a commit manifest (a torn write is refused up front).
- ``ckpt_dir=<dir>`` — serve the newest committed checkpoint in the dir;
  with ``serve.swap_poll_s>0`` the server keeps watching the dir and
  hot-swaps newer commits as training lands them.

With ``serve.load.enabled=True`` the scripted load generator drives the
server and the run report (QPS, p50/p95 vs SLO, shed/retry counts) is
printed as JSON and emitted as the final ``serve_stats`` telemetry event —
this is the acceptance path ``bench.py --serve-stats`` reads. Otherwise the
server runs until SIGTERM/SIGINT, emitting ``serve_stats`` every
``serve.stats_interval_s``.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from typing import Any, Dict, List, Optional


def _apply_kv_overrides(cfg: Any, kv: Dict[str, str], skip: tuple = ()) -> Any:
    """The ``cli.evaluation`` override semantics: dotted-path assignment with
    YAML-typed values; bare ``group=name`` strings re-compose config groups."""
    import yaml

    from sheeprl_tpu.config.compose import compose_group
    from sheeprl_tpu.utils.utils import dotdict

    for k, v in kv.items():
        if k in skip:
            continue
        value = yaml.safe_load(v)
        if "." not in k and isinstance(cfg.get(k), dict) and isinstance(value, str):
            cfg[k] = dotdict(compose_group(k, value))
            continue
        node = cfg
        parts = k.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, dotdict({})) if isinstance(node, dict) else node[p]
        node[parts[-1]] = value
    return cfg


def serving(args: Optional[List[str]] = None) -> None:
    import yaml

    from sheeprl_tpu.utils.utils import dotdict

    overrides = list(sys.argv[1:] if args is None else args)
    kv = dict(o.split("=", 1) for o in overrides if "=" in o and not o.startswith(("+", "~")))
    ckpt_path = kv.get("checkpoint_path")
    ckpt_dir = kv.get("ckpt_dir")
    if not ckpt_path and not ckpt_dir:
        raise ValueError("serve needs checkpoint_path=<ckpt> or ckpt_dir=<dir>")

    from sheeprl_tpu.resilience.manifest import read_manifest
    from sheeprl_tpu.serve.errors import SwapRejected

    if ckpt_path:
        man = read_manifest(ckpt_path)
        if man is None:
            raise SwapRejected(
                f"checkpoint {ckpt_path} has no commit manifest — refusing to serve a torn "
                f"or foreign write (committed checkpoints carry a manifest; see howto/resilience.md)"
            )
        ckpt_dir = ckpt_dir or os.path.dirname(os.path.abspath(ckpt_path))
    else:
        import warnings

        from sheeprl_tpu.resilience.discovery import newest_committed, validation_load_gate

        newest = newest_committed(
            ckpt_dir,
            gates=(validation_load_gate,),
            on_reject=lambda cand, reason: warnings.warn(
                f"serve: skipping checkpoint {cand.path!r} (step {cand.step}): {reason}"
            ),
        )
        if newest is None:
            raise FileNotFoundError(f"no committed, loadable checkpoint found in {ckpt_dir}")
        ckpt_path, man = newest.path, newest.manifest

    cfg_path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(ckpt_path))), "config.yaml")
    if not os.path.isfile(cfg_path):
        raise ValueError(f"no config.yaml found next to the checkpoint: {cfg_path}")
    with open(cfg_path) as f:
        cfg = dotdict(yaml.safe_load(f))
    _apply_kv_overrides(cfg, kv, skip=("checkpoint_path", "ckpt_dir"))
    from sheeprl_tpu.config.compose import resolve

    cfg = dotdict(resolve(cfg))
    # serving never records video and needs no training env fan-out
    if isinstance(cfg.get("env"), dict):
        cfg.env["capture_video"] = False

    from sheeprl_tpu.obs import configure_telemetry, shutdown_telemetry, telemetry_serve_event, telemetry_serve_stats
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.loadgen import run_load, run_ramp
    from sheeprl_tpu.serve.policy import build_served_policy
    from sheeprl_tpu.serve.server import PolicyServer
    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    serve_cfg = serve_config_from_cfg(cfg)
    run_dir = os.path.dirname(cfg_path)
    configure_telemetry(cfg, log_dir=run_dir)
    from sheeprl_tpu.obs import set_trace_role

    set_trace_role("serve")  # trace-plane handshake carries the serving role

    state = load_checkpoint(ckpt_path)
    policy = build_served_policy(cfg, state)
    del state  # the server keeps only the extracted params

    def on_event(kind: str, info: Dict[str, Any]) -> None:
        telemetry_serve_event(kind, **info)

    if serve_cfg.fleet.enabled:
        from sheeprl_tpu.serve.fleet import FleetServer

        server: Any = FleetServer(
            policy,
            serve_cfg,
            step=int(man["step"]),
            path=ckpt_path,
            ckpt_dir=ckpt_dir,
            on_event=on_event,
        )
    else:
        server = PolicyServer(
            policy,
            serve_cfg,
            step=int(man["step"]),
            path=ckpt_path,
            ckpt_dir=ckpt_dir,
            on_event=on_event,
        )
    t0 = time.perf_counter()
    server.start()
    warm = ", ".join(f"b{b}={dt * 1e3:.0f}ms" for b, dt in sorted(server.warmup_s.items()))
    if serve_cfg.fleet.enabled:
        tier = (
            f"fleet replicas={serve_cfg.fleet.num_replicas} "
            f"(min={serve_cfg.fleet.min_replicas} max={serve_cfg.fleet.max_replicas} "
            f"spill={serve_cfg.fleet.cpu_spill_replicas}) "
            f"pending<={serve_cfg.fleet.resolved_max_pending(serve_cfg)} "
            f"hedge@p{serve_cfg.fleet.hedge_quantile * 100:.0f}"
        )
    else:
        tier = (
            f"gather={serve_cfg.gather_window_s * 1e3:.1f}ms "
            f"queue<={serve_cfg.max_queue} replicas={serve_cfg.num_replicas}"
        )
    cache_note = ""
    if getattr(server, "aot_cache", None) is not None:
        st = server.aot_cache.stats()
        cache_note = f" [aot cache: {st['hits']} deserialized / {st['misses']} compiled]"
    print(
        f"serving {policy.name} step={man['step']} from {ckpt_path}\n"
        f"AOT ladder warmed in {time.perf_counter() - t0:.2f}s ({warm}){cache_note}; "
        f"slo={serve_cfg.slo_ms:.0f}ms {tier}"
    )

    stop = threading.Event()

    def _on_signal(signum: int, frame: Any) -> None:
        stop.set()

    try:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
    except ValueError:
        pass  # not the main thread (tests drive serving() directly)

    outcome, error = "completed", None
    final_snap: Optional[Dict[str, Any]] = None
    try:
        if serve_cfg.load.enabled:
            if serve_cfg.load.ramp_steps > 0:
                report = run_ramp(server, serve_cfg.load)
            else:
                report = run_load(server, serve_cfg.load)
            snap = server.snapshot()
            snap["load_report"] = report
            telemetry_serve_stats(snap)
            final_snap = snap
            print(json.dumps({"serve_stats": snap}, indent=2, default=str))
        else:
            while not stop.wait(serve_cfg.stats_interval_s):
                telemetry_serve_stats(server.snapshot())
            final_snap = server.snapshot()
            telemetry_serve_stats(final_snap)
    except BaseException as err:
        outcome, error = "crashed", repr(err)
        raise
    finally:
        server.close()
        # serve sessions register in RUNS.jsonl too: the record's `serve`
        # section (run_summary folds in the last serve_stats snapshot)
        # feeds the regression gates' serve_qps / serve_p95_ms cells
        from sheeprl_tpu.obs.registry import register_run

        extra: Dict[str, Any] = {}
        if serve_cfg.fleet.enabled:
            # fleet runs get their own regress cells (`serve:...:fleet`) so
            # the fleet's QPS gates never mix with single-server history
            extra["variant"] = "fleet"
        register_run(
            cfg,
            kind="serve",
            outcome=outcome,
            error=error,
            checkpoint=ckpt_path,
            serve_stats=final_snap,
            **extra,
        )
        shutdown_telemetry()


if __name__ == "__main__":
    serving()
