"""Hydra-style YAML config composition, first-party.

The reference delegates config handling to hydra-core 1.3 (sheeprl/cli.py:344,
sheeprl/configs/config.yaml, hydra_plugins/sheeprl_search_path.py). Hydra is not a
dependency of this framework; this module implements the subset the framework
needs, with compatible surface syntax so configs read the same:

- a config *tree* rooted at ``sheeprl_tpu/configs`` with groups as directories
  (``algo/``, ``env/``, ``exp/``, ``fabric/``, ...);
- ``defaults`` lists: ``- group: name``, ``- /group: name``, ``- override
  /group: name``, ``- group@pkg.path: name``, ``- _self_``, ``name: null`` to
  skip, ``name: ???`` to force a CLI choice;
- ``# @package _global_`` headers (exp configs merge at the root);
- CLI overrides: ``group=name`` (group re-selection), ``key.path=value``
  (value set), ``+key=value`` (add), ``~key`` (delete);
- ``${dotted.path}`` interpolation resolved on the composed tree;
- ``_target_``/``_partial_``/``_args_`` object instantiation;
- a search path extendable via the ``SHEEPRL_TPU_SEARCH_PATH`` env var with
  ``file://`` and ``pkg://`` schemes (reference: hydra_plugins/sheeprl_search_path.py:24-33).
"""

from __future__ import annotations

import functools
import importlib
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import yaml

from sheeprl_tpu.utils.utils import del_nested, dotdict, set_nested

MISSING = "???"
_SEARCH_PATH_ENV = "SHEEPRL_TPU_SEARCH_PATH"


class _YamlLoader(yaml.SafeLoader):
    """SafeLoader with a YAML-1.2-style float resolver so ``3e-4`` is a float
    (plain YAML 1.1 would read it as a string — omegaconf fixes this too)."""


_YamlLoader.add_implicit_resolver(
    "tag:yaml.org,2002:float",
    re.compile(
        r"""^(?:[-+]?(?:[0-9][0-9_]*)\.[0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?(?:[0-9][0-9_]*)(?:[eE][-+]?[0-9]+)
        |\.[0-9][0-9_]*(?:[eE][-+]?[0-9]+)?
        |[-+]?\.(?:inf|Inf|INF)
        |\.(?:nan|NaN|NAN))$""",
        re.X,
    ),
    list("-+0123456789."),
)


def _yaml_load(text: str) -> Any:
    return yaml.load(text, Loader=_YamlLoader)


class ConfigCompositionError(Exception):
    pass


class MissingMandatoryValue(ConfigCompositionError):
    pass


# --------------------------------------------------------------------------- #
# Search path
# --------------------------------------------------------------------------- #


def _default_search_path() -> List[str]:
    """Roots searched for config files, in priority order (first hit wins)."""
    paths: List[str] = []
    env = os.environ.get(_SEARCH_PATH_ENV, "")
    for entry in filter(None, (e.strip() for e in env.split(";"))):
        if entry.startswith("file://"):
            paths.append(entry[len("file://") :])
        elif entry.startswith("pkg://"):
            mod = importlib.import_module(entry[len("pkg://") :])
            paths.append(os.path.dirname(mod.__file__))
        else:
            paths.append(entry)
    builtin = os.path.join(os.path.dirname(__file__), "..", "configs")
    paths.append(os.path.abspath(builtin))
    return paths


def _find_config_file(rel: str, search_path: Sequence[str]) -> Optional[str]:
    for root in search_path:
        candidate = os.path.join(root, rel + ".yaml")
        if os.path.isfile(candidate):
            return candidate
        candidate = os.path.join(root, rel + ".yml")
        if os.path.isfile(candidate):
            return candidate
    return None


def group_options(group: str, search_path: Optional[Sequence[str]] = None) -> List[str]:
    """All option names available for a config group (for error messages/CLI)."""
    search_path = list(search_path) if search_path else _default_search_path()
    names: List[str] = []
    for root in search_path:
        d = os.path.join(root, group)
        if os.path.isdir(d):
            for f in sorted(os.listdir(d)):
                if f.endswith((".yaml", ".yml")):
                    names.append(os.path.splitext(f)[0])
    return sorted(set(names))


# --------------------------------------------------------------------------- #
# Overrides
# --------------------------------------------------------------------------- #


@dataclass
class OverrideEntry:
    key: str
    value: Any
    # Bare-word string values with an undotted key *may* be a config-group
    # re-selection (`env=dmc`); composition consumes them as such when the key
    # matches a defaults group, otherwise they fall back to value overrides.
    group_candidate: bool = False


@dataclass
class Overrides:
    values: List[OverrideEntry] = field(default_factory=list)
    additions: List[Tuple[str, Any]] = field(default_factory=list)
    deletions: List[str] = field(default_factory=list)
    consumed_groups: set = field(default_factory=set)

    @property
    def groups(self) -> Dict[str, str]:
        return {e.key: e.value for e in self.values if e.group_candidate}


def parse_overrides(overrides: Sequence[str]) -> Overrides:
    out = Overrides()
    for ov in overrides:
        ov = ov.strip()
        if not ov:
            continue
        if ov.startswith("~"):
            # hydra allows '~key=value'; the value is advisory — strip it
            out.deletions.append(ov[1:].partition("=")[0])
            continue
        if "=" not in ov:
            raise ConfigCompositionError(f"override {ov!r} is not of the form key=value")
        key, _, raw = ov.partition("=")
        add = key.startswith("+")
        key = key.lstrip("+").lstrip("/")
        try:
            value = _yaml_load(raw) if raw != "" else ""
        except yaml.YAMLError:
            value = raw
        if add:
            out.additions.append((key, value))
        else:
            is_group = isinstance(value, str) and bool(value) and "." not in key and "/" not in key
            out.values.append(OverrideEntry(key, value, group_candidate=is_group))
    return out


# --------------------------------------------------------------------------- #
# Defaults-list processing
# --------------------------------------------------------------------------- #

_PKG_RE = re.compile(r"^#\s*@package\s+(\S+)")


def _load_yaml(path: str) -> Tuple[dict, Optional[str]]:
    """Load a yaml file, returning (content, package_directive)."""
    with open(path) as f:
        text = f.read()
    pkg = None
    for line in text.splitlines()[:3]:
        m = _PKG_RE.match(line.strip())
        if m:
            pkg = m.group(1)
            break
    data = _yaml_load(text) or {}
    if not isinstance(data, dict):
        raise ConfigCompositionError(f"config file {path} must contain a mapping")
    return data, pkg


def _merge(dst: dict, src: Mapping) -> dict:
    """Recursive dict merge; ``src`` wins. Lists are replaced, not concatenated."""
    for k, v in src.items():
        if k in dst and isinstance(dst[k], dict) and isinstance(v, Mapping):
            _merge(dst[k], v)
        else:
            dst[k] = _copy_tree(v)
    return dst


def _copy_tree(v: Any) -> Any:
    if isinstance(v, Mapping):
        return {k: _copy_tree(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_copy_tree(x) for x in v]
    return v


def _merge_at(dst: dict, package: Optional[str], src: Mapping) -> None:
    if package in (None, "_global_", ""):
        _merge(dst, src)
        return
    node = dst
    for part in package.split("."):
        node = node.setdefault(part, {})
        if not isinstance(node, dict):
            raise ConfigCompositionError(f"package path {package!r} collides with a non-dict value")
    _merge(node, src)


def _parse_default_entry(entry: Any) -> Tuple[Optional[str], Optional[str], Optional[str], bool, bool]:
    """Returns (group, option, package, is_self, is_override)."""
    if entry == "_self_":
        return None, None, None, True, False
    if isinstance(entry, str):
        # bare include of a sibling config file, e.g. "- base"
        return entry, None, None, False, False
    if isinstance(entry, Mapping) and len(entry) == 1:
        key, option = next(iter(entry.items()))
        key = str(key)
        is_override = key.startswith("override ")
        if is_override:
            key = key[len("override ") :].strip()
        package = None
        if "@" in key:
            key, _, package = key.partition("@")
        key = key.lstrip("/")
        return key, (None if option is None else str(option)), package, False, is_override
    raise ConfigCompositionError(f"malformed defaults entry: {entry!r}")


class _Composer:
    def __init__(self, search_path: Sequence[str], overrides: Overrides) -> None:
        self.search_path = list(search_path)
        self.overrides = overrides
        self._loading: List[str] = []  # cycle guard

    def compose_file(self, rel: str, dst: dict, package_override: Optional[str] = None) -> None:
        path = _find_config_file(rel, self.search_path)
        if path is None:
            opts = "\n".join(f"  - {o}" for o in group_options(os.path.dirname(rel), self.search_path))
            raise ConfigCompositionError(
                f"config file {rel!r} not found in search path {self.search_path}"
                + (f"\navailable options:\n{opts}" if opts else "")
            )
        if path in self._loading:
            raise ConfigCompositionError(f"defaults cycle detected at {path}")
        self._loading.append(path)
        try:
            content, pkg = _load_yaml(path)
            package = package_override if package_override is not None else pkg
            if package is None and os.path.dirname(rel):
                package = os.path.dirname(rel).replace("/", ".")
            defaults = content.pop("defaults", None)
            own_merged = False
            if defaults is not None:
                if not isinstance(defaults, list):
                    raise ConfigCompositionError(f"'defaults' in {path} must be a list")
                for entry in defaults:
                    group, option, entry_pkg, is_self, is_override = _parse_default_entry(entry)
                    if is_self:
                        _merge_at(dst, package, content)
                        own_merged = True
                        continue
                    if isinstance(entry, str):
                        # sibling include (e.g. `- default` inside env/dummy.yaml):
                        # not a group, never overridable from the CLI
                        rel_dir = os.path.dirname(rel)
                        sibling = os.path.join(rel_dir, group) if rel_dir else group
                        self.compose_file(sibling, dst, package_override=package)
                        continue
                    chosen = self._choice(group)
                    if chosen is not None:
                        option = chosen
                    if option is None:
                        # `- group: null` → explicitly skipped unless overridden
                        continue
                    if option == MISSING:
                        raise MissingMandatoryValue(
                            f"you must specify '{group}=<option>'; available options:\n"
                            + "\n".join(f"  - {o}" for o in group_options(group, self.search_path))
                        )
                    # `@pkg` in a defaults entry is relative to this file's
                    # package (hydra semantics: metric/default.yaml's
                    # `/logger@logger` lands at metric.logger); `_global_...`
                    # prefixes make it absolute.
                    eff_pkg = entry_pkg
                    if entry_pkg is not None:
                        if entry_pkg == "_global_":
                            eff_pkg = "_global_"
                        elif entry_pkg.startswith("_global_."):
                            eff_pkg = entry_pkg[len("_global_.") :]
                        elif package not in (None, "_global_", ""):
                            eff_pkg = f"{package}.{entry_pkg}"
                    if is_override:
                        # hydra semantics: `override /group: opt` REPLACES the
                        # earlier selection — drop what that group already
                        # merged so stale keys from the old option cannot leak.
                        clear_at = eff_pkg if eff_pkg is not None else group.replace("/", ".")
                        if clear_at not in (None, "_global_", ""):
                            node: Any = dst
                            parts = clear_at.split(".")
                            for part in parts[:-1]:
                                node = node.get(part, {}) if isinstance(node, dict) else {}
                            if isinstance(node, dict):
                                node.pop(parts[-1], None)
                    self.compose_file(
                        os.path.join(group, option),
                        dst,
                        package_override=eff_pkg,
                    )
            if not own_merged:
                _merge_at(dst, package, content)
        finally:
            self._loading.pop()

    def _choice(self, group: str) -> Optional[str]:
        if group in self.overrides.groups:
            self.overrides.consumed_groups.add(group)
            return self.overrides.groups[group]
        return None


# --------------------------------------------------------------------------- #
# Interpolation
# --------------------------------------------------------------------------- #

_INTERP_RE = re.compile(r"\$\{([^${}]+)\}")


def _lookup(root: Mapping, dotted: str) -> Any:
    node: Any = root
    for part in dotted.split("."):
        if isinstance(node, Mapping) and part in node:
            node = node[part]
        elif isinstance(node, list):
            node = node[int(part)]
        else:
            raise KeyError(dotted)
    return node


def _resolve_value(root: Mapping, value: Any, stack: Tuple[str, ...]) -> Any:
    if isinstance(value, str):
        full = _INTERP_RE.fullmatch(value)
        if full:
            return _resolve_ref(root, full.group(1).strip(), stack)

        def sub(m: re.Match) -> str:
            return str(_resolve_ref(root, m.group(1).strip(), stack))

        return _INTERP_RE.sub(sub, value)
    return value


def _resolve_ref(root: Mapping, expr: str, stack: Tuple[str, ...]) -> Any:
    if expr.startswith("env:"):
        name, sep, default = expr[4:].partition(",")
        name = name.strip()
        if name in os.environ:
            return os.environ[name]
        if not sep:
            raise ConfigCompositionError(f"environment variable {name!r} is not set and no default was given")
        return _yaml_load(default)
    if expr.startswith("now:"):
        import datetime

        return datetime.datetime.now().strftime(expr[4:] or "%Y-%m-%d_%H-%M-%S")
    if expr in stack:
        raise ConfigCompositionError(f"interpolation cycle: {' -> '.join(stack + (expr,))}")
    try:
        target = _lookup(root, expr)
    except (KeyError, IndexError, ValueError):
        raise ConfigCompositionError(f"interpolation key {expr!r} not found") from None
    return _resolve_tree(root, target, stack + (expr,)) if isinstance(target, (str, Mapping, list)) else target


def _resolve_tree(root: Mapping, node: Any, stack: Tuple[str, ...] = ()) -> Any:
    if isinstance(node, Mapping):
        return {k: _resolve_tree(root, v, stack) for k, v in node.items()}
    if isinstance(node, list):
        return [_resolve_tree(root, v, stack) for v in node]
    return _resolve_value(root, node, stack)


def resolve(cfg: Mapping) -> dict:
    return _resolve_tree(cfg, cfg)


# --------------------------------------------------------------------------- #
# Public API
# --------------------------------------------------------------------------- #


def compose(
    config_name: str = "config",
    overrides: Optional[Sequence[str]] = None,
    search_path: Optional[Sequence[str]] = None,
    interpolate: bool = True,
) -> dotdict:
    """Compose a config tree the way ``hydra.main`` would (reference cli.py:344)."""
    ovs = parse_overrides(overrides or [])
    sp = list(search_path) if search_path else _default_search_path()
    composer = _Composer(sp, ovs)
    out: dict = {}
    composer.compose_file(config_name, out)
    for entry in ovs.values:
        if entry.group_candidate and entry.key in ovs.consumed_groups:
            continue  # consumed as a group re-selection during composition
        if not _has_nested(out, entry.key):
            raise ConfigCompositionError(
                f"could not override {entry.key!r}: no such key in the composed config "
                f"(use '+{entry.key}={entry.value}' to add a new key)"
            )
        set_nested(out, entry.key, entry.value)
    for key, value in ovs.additions:
        try:
            set_nested(out, key, value)
        except KeyError as e:
            raise ConfigCompositionError(str(e)) from None
    for key in ovs.deletions:
        try:
            del_nested(out, key)
        except (KeyError, TypeError):
            raise ConfigCompositionError(f"cannot delete missing key {key!r}") from None
    _check_missing(out, prefix="")
    if interpolate:
        out = resolve(out)
    return dotdict(out)


def compose_group(group: str, option: str, search_path: Optional[Sequence[str]] = None) -> dict:
    """Compose a single config group's subtree (``<group>/<option>.yaml``
    with its sibling-include defaults) and return just that subtree.

    Used by the eval/registration CLIs, whose base config comes from a
    checkpoint's ``config.yaml`` rather than full composition: a
    ``group=option`` override there must re-compose the group the way
    ``hydra`` would, not assign the bare string."""
    sp = list(search_path) if search_path else _default_search_path()
    composer = _Composer(sp, Overrides())
    out: dict = {}
    composer.compose_file(os.path.join(group, option), out)
    return out.get(group, out)


def _has_nested(d: Mapping, dotted: str) -> bool:
    node: Any = d
    for part in dotted.split("."):
        if not isinstance(node, Mapping) or part not in node:
            return False
        node = node[part]
    return True


def _check_missing(node: Any, prefix: str) -> None:
    if isinstance(node, Mapping):
        for k, v in node.items():
            _check_missing(v, f"{prefix}{k}.")
    elif isinstance(node, list):
        for i, v in enumerate(node):
            _check_missing(v, f"{prefix}{i}.")
    elif node == MISSING:
        raise MissingMandatoryValue(f"mandatory value {prefix[:-1]!r} is missing — set it on the command line")


def instantiate(node: Any, *args: Any, _recursive_: bool = True, **kwargs: Any) -> Any:
    """Build an object from a ``_target_`` node (hydra.utils.instantiate-alike).

    Reference usage sites: fabric construction (cli.py:140), env wrappers
    (utils/env.py:72), optimizers, metric aggregators.
    """
    if not isinstance(node, Mapping) or "_target_" not in node:
        raise ConfigCompositionError(f"instantiate() requires a mapping with '_target_', got {node!r}")
    spec = dict(node)
    target = spec.pop("_target_")
    partial = bool(spec.pop("_partial_", False))
    pos = list(spec.pop("_args_", [])) + list(args)
    if _recursive_:
        spec = {k: _instantiate_tree(v) for k, v in spec.items()}
        pos = [_instantiate_tree(v) for v in pos]
    spec.update(kwargs)
    module_name, _, attr = target.rpartition(".")
    if not module_name:
        raise ConfigCompositionError(f"invalid _target_ {target!r}")
    obj = getattr(importlib.import_module(module_name), attr)
    if partial:
        return functools.partial(obj, *pos, **spec)
    return obj(*pos, **spec)


def _instantiate_tree(v: Any) -> Any:
    """Recursively build every ``_target_`` node at any depth (hydra recurses
    through nested dicts and lists alike)."""
    if isinstance(v, Mapping):
        if "_target_" in v:
            return instantiate(v)
        return {k: _instantiate_tree(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return type(v)(_instantiate_tree(x) for x in v)
    return v


def get_class(target: str) -> Any:
    module_name, _, attr = target.rpartition(".")
    return getattr(importlib.import_module(module_name), attr)
