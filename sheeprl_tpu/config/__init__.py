from sheeprl_tpu.config.compose import (
    MISSING,
    ConfigCompositionError,
    MissingMandatoryValue,
    compose,
    get_class,
    group_options,
    instantiate,
    parse_overrides,
    resolve,
)

__all__ = [
    "MISSING",
    "ConfigCompositionError",
    "MissingMandatoryValue",
    "compose",
    "get_class",
    "group_options",
    "instantiate",
    "parse_overrides",
    "resolve",
]
