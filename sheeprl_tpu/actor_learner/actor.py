"""Actor child process: own env slice + jitted CPU player → trajectory slabs.

Each actor is a fully self-contained collection loop: it builds its env slice
(``envs_per_actor`` envs of the global vector arrangement, same per-env seed
arithmetic as ``envs.factory.build_vector_env``), deterministically
initializes the SAME agent as the learner (``build_agent`` inits from
``cfg.seed``, so the ``_ParamStreamer`` wire format agrees by construction),
and then loops: poll the param lane → collect ``rollout_steps`` env steps →
GAE → flatten → write one slab into an owned ring slot → commit. The slab is
a *complete training batch* — the learner's fused update consumes it without
further shaping.

TPU hygiene is inherited from the env-worker pool: the parent spawns under
``rollout.supervisor._spawn_environ`` and ``actor_main`` re-applies
``sanitize_worker_environ`` first thing, so the actor's jax is pinned to the
CPU backend and can never initialize the TPU runtime or join the learner's
process group.

Protocol (pickled tuples over a duplex ``multiprocessing.Pipe``)::

    parent -> actor                     actor -> parent
    ----------------------------------------------------------------
                                        ("ready",)
    ("close",)                          ("bye",)

Everything else — slabs out, params in — rides the transport (shared memory
same-host, length-prefixed TCP frames cross-host). Heartbeats go
through the supervisor's lock-free double array after every env step, so the
parent distinguishes a slow rollout from a wedged one exactly like the env
pool does.

Fault drills (see ``fault_injection``): ``actor_crash_mid_write`` dies via
``os._exit`` after payload+meta but BEFORE the commit marker — the canonical
torn write; ``actor_hang`` stops heartbeating before collecting a slab.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Dict, List

# step-counter salt between the actor action streams and the learner's train
# key chain (same role as ops/rollout_scan.ENV_STREAM_SALT)
ACTOR_KEY_SALT = 1009


def actor_main(conn, hb, actor_index: int, blob: bytes) -> None:
    """Child-process entrypoint (module-level: spawn pickles it by name)."""
    from sheeprl_tpu.rollout.worker import sanitize_worker_environ

    sanitize_worker_environ()
    envs = None
    transport = None
    try:
        import cloudpickle

        spec: Dict[str, Any] = cloudpickle.loads(blob)
        cfg = spec["cfg"]
        generation = int(spec["generation"])
        slots: List[int] = list(spec["slots"])
        envs_per_actor = int(spec["envs_per_actor"])
        rollout_steps = int(spec["rollout_steps"])
        faults = list(spec["faults"])  # wire dicts; empty after a restart
        trace_dir = spec.get("trace_dir")  # None when the learner runs untelemetered

        import gymnasium as gym
        import jax
        import numpy as np

        from functools import partial

        from sheeprl_tpu.actor_learner.ring import SlabLayout
        from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent
        from sheeprl_tpu.algos.ppo.utils import prepare_obs
        from sheeprl_tpu.envs.factory import make_env
        from sheeprl_tpu.net.transport import attach_actor_transport
        from sheeprl_tpu.ops.math import gae
        from sheeprl_tpu.parallel.fabric import Precision, _ParamStreamer

        cpu = jax.local_devices(backend="cpu")[0]

        class _CpuFabric:
            precision = Precision(str(spec["precision"]))

            @staticmethod
            def replicate(tree):
                return jax.device_put(tree, cpu)

        # env slice: global vector indices [offset, offset+E); seed arithmetic
        # identical to build_vector_env at rank 0, shifted by the restart
        # generation so a respawned actor replays a deterministic (but fresh)
        # seed stream — the rollout pool's _restart_seed discipline.
        offset = actor_index * envs_per_actor
        seed_shift = 7919 * generation
        thunks = [
            make_env(cfg, int(cfg.seed) + seed_shift + offset + i, 0, None, "train", vector_env_idx=offset + i)
            for i in range(envs_per_actor)
        ]
        envs = gym.vector.SyncVectorEnv(thunks, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)

        cnn_keys = list(cfg.algo.cnn_keys.encoder)
        mlp_keys = list(cfg.algo.mlp_keys.encoder)
        obs_keys = cnn_keys + mlp_keys
        is_continuous = isinstance(envs.single_action_space, gym.spaces.Box)
        is_multidiscrete = isinstance(envs.single_action_space, gym.spaces.MultiDiscrete)
        actions_dim = tuple(
            envs.single_action_space.shape
            if is_continuous
            else (
                envs.single_action_space.nvec.tolist()
                if is_multidiscrete
                else [envs.single_action_space.n]
            )
        )

        # deterministic init from cfg.seed — bit-identical tree structure to
        # the learner's, which is what makes the packed lane bytes decodable
        agent, params = build_agent(_CpuFabric(), actions_dim, is_continuous, cfg, envs.single_observation_space)
        player = PPOPlayer(agent, params, device=cpu)
        streamer = _ParamStreamer(params, cpu)
        gae_fn = jax.jit(partial(gae, gamma=float(cfg.algo.gamma), gae_lambda=float(cfg.algo.gae_lambda)))

        transport = attach_actor_transport(
            spec["transport"], actor_id=actor_index, generation=generation, slots=slots
        )
        layout = SlabLayout.from_wire(spec["layout"])

        # standalone flush-per-event trace recorder: the actor has no
        # telemetry hub and the crash drills kill it via os._exit (no atexit,
        # no buffered flush), so every event must hit disk as it happens —
        # that is what puts the actor-side half of a torn slab's trace on the
        # merged timeline. Restarted generations append to the same file.
        from sheeprl_tpu.obs.trace import configure_trace, new_trace_id, trace_event

        traced = bool(trace_dir)
        if traced:
            configure_trace(
                f"actor{actor_index}",
                os.path.join(trace_dir, f"trace.actor{actor_index}.jsonl"),
                generation=generation,
            )

        hb[actor_index] = time.time()
        conn.send(("ready",))

        # wait for the first publish so every slab carries a real version
        param_version = -1
        while param_version < 0:
            got = transport.poll_params()
            if got is not None:
                param_version, flat = got
                player.update_params(streamer.finish(flat))
            else:
                hb[actor_index] = time.time()
                time.sleep(0.01)
            if conn.poll(0):
                if conn.recv()[0] == "close":
                    conn.send(("bye",))
                    return

        reset_seeds = [int(cfg.seed) + seed_shift + offset + i for i in range(envs_per_actor)]
        next_obs, _ = envs.reset(seed=reset_seeds)
        next_obs = prepare_obs(next_obs, cnn_keys=cnn_keys, num_envs=envs_per_actor)

        player_key = jax.device_put(
            jax.random.fold_in(jax.random.PRNGKey(int(cfg.seed)), ACTOR_KEY_SALT + actor_index), cpu
        )
        T, E = rollout_steps, envs_per_actor
        # warm the player/GAE jits on the reset obs + zero buffers (results
        # discarded, all purely functional) so the first slab's COLLECT_US
        # stamps collection, not compile — compile is actor boot, like the
        # spawn itself
        jax.block_until_ready(player.rollout_actions(next_obs, player_key, 0))
        nv = np.asarray(player.get_values(next_obs))
        z = np.zeros((T, E, 1), np.float32)
        jax.block_until_ready(gae_fn(z, z, z, nv))
        hb[actor_index] = time.time()
        store = {
            k: np.zeros((T, E, *v.shape[1:]), dtype=v.dtype) for k, v in next_obs.items() if k in obs_keys
        }
        slab_seq = int(spec["start_seq"])
        local_slab = 0  # within-generation counter; faults key off it
        step_counter = 0

        while True:
            if conn.poll(0):
                if conn.recv()[0] == "close":
                    conn.send(("bye",))
                    return

            # refresh params between rollouts (never mid-rollout: a slab is
            # collected against exactly one version)
            if transport.param_version() > param_version:
                got = transport.poll_params()
                if got is not None and got[0] > param_version:
                    param_version, flat = got
                    player.update_params(streamer.finish(flat))

            for fault in [f for f in faults if f["kind"] == "actor_hang" and f["at_slab"] == local_slab]:
                # stop heartbeating: the supervisor's deadline must fire
                deadline = time.time() + (float(fault.get("duration_s") or 0.0) or 3600.0)
                while time.time() < deadline:
                    time.sleep(0.05)

            t0 = time.perf_counter()
            update_key = jax.random.fold_in(player_key, slab_seq)
            values_buf = np.zeros((T, E, 1), np.float32)
            actions_buf = None
            logprobs_buf = np.zeros((T, E, 1), np.float32)
            rewards_buf = np.zeros((T, E, 1), np.float32)
            dones_buf = np.zeros((T, E, 1), np.float32)
            ep_ret_sum = ep_len_sum = ep_count = 0.0
            for t in range(T):
                step_counter += 1
                actions, real_actions, logprobs, values = player.rollout_actions(
                    next_obs, update_key, step_counter
                )
                actions_np, real_actions, logprobs_np, values_np = jax.device_get(
                    (actions, real_actions, logprobs, values)
                )
                if not is_continuous and real_actions.shape[-1] == 1 and not is_multidiscrete:
                    real_actions = real_actions[..., 0]
                obs, rewards, terminated, truncated, info = envs.step(
                    real_actions.reshape(envs.action_space.shape)
                )
                rewards = np.asarray(rewards, dtype=np.float32).reshape(E, 1)

                truncated_envs = np.nonzero(truncated)[0]
                if len(truncated_envs) > 0 and "final_obs" in info:
                    final_obs = {
                        k: np.stack([np.asarray(info["final_obs"][e][k]) for e in truncated_envs])
                        for k in obs_keys
                    }
                    final_obs = prepare_obs(final_obs, cnn_keys=cnn_keys, num_envs=len(truncated_envs))
                    vals = np.asarray(player.get_values(final_obs)).reshape(len(truncated_envs))
                    rewards[truncated_envs, 0] += float(cfg.algo.gamma) * vals

                for k in obs_keys:
                    store[k][t] = next_obs[k]
                dones_buf[t] = np.logical_or(terminated, truncated).reshape(E, 1).astype(np.float32)
                values_buf[t] = values_np
                logprobs_buf[t] = logprobs_np
                rewards_buf[t] = rewards
                if actions_buf is None:
                    actions_buf = np.zeros((T, E, actions_np.shape[-1]), actions_np.dtype)
                actions_buf[t] = actions_np

                next_obs = prepare_obs(obs, cnn_keys=cnn_keys, num_envs=E)
                if "final_info" in info:
                    ep = info["final_info"].get("episode")
                    if ep is not None:
                        for i in np.nonzero(ep.get("_r", []))[0]:
                            ep_ret_sum += float(ep["r"][i])
                            ep_len_sum += float(ep["l"][i])
                            ep_count += 1.0
                hb[actor_index] = time.time()

            next_values = np.asarray(player.get_values(next_obs))
            returns, advantages = gae_fn(rewards_buf, values_buf, dones_buf, next_values)
            flat = {k: store[k].reshape(T * E, *store[k].shape[2:]) for k in obs_keys}
            flat["actions"] = actions_buf.reshape(T * E, -1)
            flat["logprobs"] = logprobs_buf.reshape(T * E, 1)
            flat["values"] = values_buf.reshape(T * E, 1)
            flat["returns"] = np.asarray(returns).reshape(T * E, 1)
            flat["advantages"] = np.asarray(advantages).reshape(T * E, 1)
            flat["ep_stats"] = np.asarray([ep_ret_sum, ep_len_sum, ep_count], np.float32)
            collect_us = int((time.perf_counter() - t0) * 1e6)
            # mint the slab's cross-process trace id and record the actor-side
            # span BEFORE the ring write: a crash between write_meta and
            # commit (the torn drill) must still leave this half of the chain
            slab_tid = new_trace_id() if traced else 0
            if slab_tid:
                trace_event(
                    "slab_collect",
                    slab_tid,
                    seq=slab_seq,
                    actor=actor_index,
                    param_version=param_version,
                    collect_us=collect_us,
                    env_steps=T * E,
                )

            # acquire write capacity (spin with heartbeats while the learner
            # drains a full ring / the credit window is empty — backpressure,
            # not an error)
            while not transport.try_begin_write():
                hb[actor_index] = time.time()
                if conn.poll(0.005):
                    if conn.recv()[0] == "close":
                        conn.send(("bye",))
                        return

            layout.pack_into(transport.payload_view(), flat)
            transport.write_meta(
                seq=slab_seq,
                param_version=param_version,
                actor_id=actor_index,
                n_rows=T * E,
                collect_us=collect_us,
                env_steps=T * E,
                trace_id=slab_tid,
                commit_t_us=int(time.time() * 1e6),
            )
            if any(f["kind"] == "actor_crash_mid_write" and f["at_slab"] == local_slab for f in faults):
                # the torn write: payload + meta are in place, the commit
                # marker is NOT — and never will be (tcp: half a frame hits
                # the wire). Skip atexit/finalizers; a SIGKILL-like death is
                # what the reader must survive.
                transport.abort_torn()
                os._exit(13)
            transport.commit()
            if slab_tid:
                trace_event("slab_commit", slab_tid, seq=slab_seq)
            slab_seq += 1
            local_slab += 1
            hb[actor_index] = time.time()
    except (EOFError, KeyboardInterrupt):
        pass
    except Exception:
        try:
            conn.send(("error", traceback.format_exc()))
        except Exception:
            pass
        os._exit(1)
    finally:
        try:
            from sheeprl_tpu.obs.trace import shutdown_trace

            shutdown_trace()
        except Exception:
            pass
        for closer in (transport, envs):
            if closer is not None:
                try:
                    closer.close()
                except Exception:
                    pass
        try:
            conn.close()
        except Exception:
            pass
