"""Actor lifecycle: spawn, heartbeat-aware health checks, budgeted restarts,
quiesce. The parent-side half of the topology's fault tolerance — a direct
reuse of the env-pool supervision machinery (``rollout.supervisor``): the
same ``RestartBudget`` healthy-window refund, the same heartbeat-extended
deadlines, the same sanitized-environ spawn window.

Differences from the env-pool supervisor:

- actors are *push* producers (slabs ride the transport, not the pipe), so
  health is checked by polling liveness+heartbeats (:meth:`check_health`)
  instead of around a request/reply;
- a restart first **reclaims the dead actor's transport capacity**
  (:meth:`~sheeprl_tpu.net.transport.LearnerTransport.reclaim_actor`: shm
  frees any ring slot stuck ``WRITING`` — the torn-write check — and tcp
  bumps the generation floor + severs zombie connections) before respawning
  with a bumped generation — the in-flight slab is abandoned by design and
  the fresh env seeds are replayed deterministically from the generation
  counter;
- budget exhaustion raises :class:`ActorBudgetExhausted` (the run aborts with
  a distinct outcome) instead of masking: a masked env slot can serve zeros,
  a masked actor would silently shrink the training batch distribution.
"""

from __future__ import annotations

import time
from typing import TYPE_CHECKING, Callable, List, Optional

from sheeprl_tpu.actor_learner.config import ActorLearnerConfig
from sheeprl_tpu.rollout.supervisor import (
    RestartBudget,
    Supervisor,
    WorkerDied,
    WorkerHandle,
    WorkerTimeout,
    _spawn_environ,
)

if TYPE_CHECKING:  # import cycle: net.transport wraps the ring this package owns
    from sheeprl_tpu.net.transport import LearnerTransport


class ActorBudgetExhausted(RuntimeError):
    """An actor burnt through its restart budget — the topology cannot hold
    its env-slice distribution, so the run aborts (outcome: actor_exhausted)."""

    def __init__(self, actor: int, restarts: int) -> None:
        super().__init__(f"actor {actor} exhausted its restart budget after {restarts} restarts")
        self.actor = actor
        self.restarts = restarts


class ActorSupervisor(Supervisor):
    """``rollout.supervisor.Supervisor`` with the actor spawn target and the
    ring-reclaim restart path. Inherits ``wait_reply`` (heartbeat-extended
    deadline), ``kill``, ``shutdown`` (graceful ("close",)→("bye",) then
    kill), and ``backoff_s`` unchanged."""

    def __init__(
        self,
        config: ActorLearnerConfig,
        transport: "LearnerTransport",
        make_blob: Callable[[int, int], bytes],
        on_restart: Optional[Callable[[int, str, int], None]] = None,
    ) -> None:
        super().__init__(config, config.num_actors, on_restart=on_restart, on_mask=None)
        self.transport = transport
        self.make_blob = make_blob
        self.generations: List[int] = [0] * config.num_actors
        self.handles: List[WorkerHandle] = [
            WorkerHandle(i, config.actor_slots(i), b"") for i in range(config.num_actors)
        ]
        self.torn_reclaimed = 0

    # ------------------------------------------------------------- lifecycle
    def launch(self, handle: WorkerHandle) -> None:
        from sheeprl_tpu.actor_learner.actor import actor_main

        if handle.budget is None:
            handle.budget = RestartBudget(self.config.max_restarts, self.config.restart_refund_s)
        handle.thunk_blob = self.make_blob(handle.index, self.generations[handle.index])
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=actor_main,
            args=(child_conn, self.heartbeats, handle.index, handle.thunk_blob),
            name=f"al-actor-{handle.index}",
            daemon=True,
        )
        with _spawn_environ():
            proc.start()
        child_conn.close()
        handle.proc = proc
        handle.conn = parent_conn
        self.heartbeats[handle.index] = time.time()

    def handshake(self, handle: WorkerHandle) -> None:  # type: ignore[override]
        # keep servicing the transport while blocked: a tcp actor's attach
        # (dial + HELLO/ACK) happens BEFORE its ("ready",), so the learner
        # must accept and answer during this wait or the boot deadlocks
        reply = self.wait_reply(
            handle, timeout=self.config.spawn_timeout_s, idle=self.transport.pump
        )
        if reply[0] != "ready":
            raise WorkerDied(handle.index, f"bad handshake: {reply[0]!r}")

    def spawn_all(self) -> None:
        # overlap the (jax-importing, slow) boots: start every actor before
        # waiting on any handshake
        for handle in self.handles:
            self.launch(handle)
        for handle in self.handles:
            self.handshake(handle)

    # ---------------------------------------------------------------- health
    def check_health(self) -> None:
        """One supervision pass: detect dead/wedged actors, restart within
        budget. Called from the learner's admission loop — cheap when healthy
        (a liveness flag and a timestamp compare per actor)."""
        now = time.time()
        for handle in self.handles:
            if not handle.alive:
                detail = f"exitcode={getattr(handle.proc, 'exitcode', None)}"
                self._restart_or_raise(handle, WorkerDied(handle.index, detail))
            elif now - self.heartbeats[handle.index] > self.config.heartbeat_grace:
                self._restart_or_raise(
                    handle, WorkerTimeout(handle.index, now - self.heartbeats[handle.index])
                )

    def _restart_or_raise(self, handle: WorkerHandle, reason: Exception) -> None:
        if handle.budget is not None and handle.budget.exhausted:
            self.kill(handle)
            raise ActorBudgetExhausted(handle.index, handle.restarts)
        self.restart_actor(handle, repr(reason))

    # --------------------------------------------------------------- restart
    def restart_actor(self, handle: WorkerHandle, reason: str) -> None:
        """Kill + reclaim transport capacity + backoff + respawn (bumped
        generation: fresh deterministic env seeds, scripted faults NOT
        re-shipped)."""
        self.kill(handle)
        handle.restarts += 1
        # the abandoned in-flight slab: any WRITING slot of this actor is by
        # definition torn — free it so the ring never wedges on a dead writer
        # (tcp: raise the generation floor so a zombie's late slab is stale)
        self.torn_reclaimed += self.transport.reclaim_actor(handle.index, handle.slots)
        charge = handle.budget.charge() if handle.budget is not None else handle.restarts
        if self.on_restart is not None:
            self.on_restart(handle.index, reason, handle.restarts)
        time.sleep(self.backoff_s(charge))
        self.generations[handle.index] += 1
        self.launch(handle)
        self.handshake(handle)

    # --------------------------------------------------------------- quiesce
    def quiesce_all(self, timeout_s: Optional[float] = None) -> None:
        """Explicit orderly stop for every actor: ("close",) → ("bye",) with
        a deadline, then kill. Used by BOTH the normal teardown and the
        learner's crash/SIGTERM drain — no orphaned actor processes."""
        timeout = self.config.quiesce_timeout_s if timeout_s is None else float(timeout_s)
        for handle in self.handles:
            try:
                self.shutdown(handle, timeout=timeout)
            except Exception:
                self.kill(handle)
