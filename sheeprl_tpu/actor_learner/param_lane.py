"""Versioned param broadcast lane (learner → actors), classic seqlock.

One ``SharedMemory`` block: ``[seq, version, nbytes]`` int64 header + the
``_ParamStreamer``-packed flat param bytes. The learner is the only writer;
every actor reads. Seqlock protocol:

writer: seq += 1 (odd) → payload + version → seq += 1 (even)
reader: s1 = seq; even? → copy payload + version → s2 = seq; accept iff s1 == s2

A reader that races a publish sees an odd ``seq`` or ``s1 != s2`` and simply
keeps its current params — staleness is bounded by the *ring* admission check
on the learner side, so a missed broadcast costs one dropped slab at worst,
never a torn param read.

The wire format is exactly ``parallel.fabric._ParamStreamer``'s packed byte
vector. Both ends build their streamer from the same deterministically
initialized agent (``build_agent`` inits from ``cfg.seed``), so treedef,
shapes, dtypes and offsets agree without ever shipping a treedef across.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np

from sheeprl_tpu.rollout.shm import attach_untracked, create_untracked, unregister_owned_segment

_SEQ, _VERSION, _NBYTES = 0, 1, 2
_HEADER_WORDS = 4  # one word reserved
_HEADER_BYTES = _HEADER_WORDS * 8


@dataclass
class LaneSpec:
    name: str
    nbytes: int


class ParamLane:
    def __init__(self, nbytes: int, *, spec: Optional[LaneSpec] = None) -> None:
        self.nbytes = int(nbytes)
        if spec is None:
            self._block = create_untracked(_HEADER_BYTES + self.nbytes)
            self._owner = True
        else:
            self._block = attach_untracked(spec.name)
            self._owner = False
        self._hdr = np.ndarray((_HEADER_WORDS,), dtype=np.int64, buffer=self._block.buf)
        self._payload = np.ndarray(
            (self.nbytes,), dtype=np.uint8, buffer=self._block.buf, offset=_HEADER_BYTES
        )
        if self._owner:
            self._hdr[...] = 0
            self._hdr[_VERSION] = -1  # nothing published yet
            self._hdr[_NBYTES] = self.nbytes

    def spec(self) -> LaneSpec:
        return LaneSpec(name=self._block.name, nbytes=self.nbytes)

    @classmethod
    def attach(cls, spec: LaneSpec) -> "ParamLane":
        return cls(spec.nbytes, spec=spec)

    # ---------------------------------------------------------------- writer
    def publish(self, flat: np.ndarray, version: int) -> None:
        flat = np.asarray(flat, dtype=np.uint8).reshape(-1)
        if flat.shape[0] != self.nbytes:
            raise ValueError(f"param lane expects {self.nbytes} bytes, got {flat.shape[0]}")
        self._hdr[_SEQ] += 1  # odd: write in progress
        self._payload[...] = flat
        self._hdr[_VERSION] = int(version)
        self._hdr[_SEQ] += 1  # even: stable

    # ---------------------------------------------------------------- reader
    def version(self) -> int:
        """Cheap peek at the published version (-1 before the first publish).
        May be momentarily stale during a publish — callers poll."""
        return int(self._hdr[_VERSION])

    def poll(self) -> Optional[Tuple[int, np.ndarray]]:
        """One seqlock read attempt: ``(version, bytes copy)`` or None when a
        publish is in flight (retry next poll)."""
        s1 = int(self._hdr[_SEQ])
        if s1 % 2 == 1:
            return None
        version = int(self._hdr[_VERSION])
        if version < 0:
            return None
        data = self._payload.copy()
        if int(self._hdr[_SEQ]) != s1:
            return None
        return version, data

    # -------------------------------------------------------------- teardown
    def close(self) -> None:
        self._hdr = None
        self._payload = None
        if self._block is None:
            return
        block, self._block = self._block, None
        try:
            block.close()
        except Exception:
            pass
        if self._owner:
            unregister_owned_segment(block.name)
            try:
                block.unlink()
            except FileNotFoundError:
                pass
