"""Disaggregated actor–learner topology (single-host, multi-process).

M supervised **actor** processes (each pinned to the CPU jax backend, owning
an env slice + a jitted player) stream fixed-size trajectory slabs into a
torn-write-safe shared-memory ring; the **learner** (this process, owning the
accelerators) runs the donated fused PPO update continuously over
staleness-admitted slabs and broadcasts versioned params back over a packed
seqlock lane. See ``howto/actor_learner.md``.

Module map:

- :mod:`~sheeprl_tpu.actor_learner.ring` — the slab ring: per-slot seqlock
  commit protocol (state word written last, checksum over the meta words), so
  a writer death at ANY point is detected and skipped, never admitted.
- :mod:`~sheeprl_tpu.actor_learner.param_lane` — single-writer versioned
  param broadcast (classic seqlock: odd/even sequence around the payload).
- :mod:`~sheeprl_tpu.actor_learner.actor` — the actor child process.
- :mod:`~sheeprl_tpu.actor_learner.supervisor` — heartbeat supervision with
  budgeted restarts + ring-slot reclaim (reuses ``rollout.supervisor``).
- :mod:`~sheeprl_tpu.actor_learner.learner` — the admission/update loop.
- :mod:`~sheeprl_tpu.actor_learner.config` — the ``algo.actor_learner`` node.
- :mod:`~sheeprl_tpu.actor_learner.fault_injection` — deterministic chaos
  drills (actor_crash_mid_write, actor_hang, learner_kill, param_lane_stall).
"""

from sheeprl_tpu.actor_learner.config import ActorLearnerConfig, actor_learner_config_from_cfg, admit
from sheeprl_tpu.actor_learner.fault_injection import (
    ALFaultSpec,
    LearnerFaultSchedule,
    actor_faults_for,
    parse_al_fault_config,
)
from sheeprl_tpu.actor_learner.param_lane import LaneSpec, ParamLane
from sheeprl_tpu.actor_learner.ring import RingSpec, SlabLayout, SlabMeta, TrajectoryRing
from sheeprl_tpu.actor_learner.supervisor import ActorBudgetExhausted, ActorSupervisor

__all__ = [
    "ALFaultSpec",
    "ActorBudgetExhausted",
    "ActorLearnerConfig",
    "ActorSupervisor",
    "LaneSpec",
    "LearnerFaultSchedule",
    "ParamLane",
    "RingSpec",
    "SlabLayout",
    "SlabMeta",
    "TrajectoryRing",
    "actor_faults_for",
    "actor_learner_config_from_cfg",
    "admit",
    "parse_al_fault_config",
]
