"""Deterministic fault injection for the actor–learner topology.

Actor faults are addressed by actor index and fire at that actor's *n*-th
produced slab (0-based, lifetime counter across restarts): the spec rides the
spawn blob, the actor checks its slab counter, and the parent never re-ships
a fault that already fired (it strips delivered faults before a respawn), so
every drill fires exactly once regardless of restarts. Learner faults fire at
the learner's *n*-th admitted slab. The parse/schedule machinery is the
shared engine in :mod:`sheeprl_tpu.utils.faults`; the ``actor``/``at_slab``
config keys are this domain's aliases into it.

Config shape (``algo.actor_learner.fault_injection``)::

    algo:
      actor_learner:
        fault_injection:
          enabled: true
          faults:
            - {kind: actor_crash_mid_write, actor: 0, at_slab: 2}
            - {kind: actor_hang,            actor: 1, at_slab: 3, duration_s: 30}
            - {kind: learner_kill,          at_slab: 4}
            - {kind: param_lane_stall,      at_slab: 2, duration_s: 1.0}

``kind``:
- ``actor_crash_mid_write`` — the actor writes the slab payload + meta but
  dies (``os._exit(13)``) *before* the commit marker: the canonical torn
  write. The learner must skip the slot; the supervisor reclaims it on
  restart and charges the budget.
- ``actor_hang`` — the actor stops heartbeating and sleeps before producing
  the slab; the supervisor's heartbeat deadline fires → kill + restart.
- ``learner_kill`` — the learner SIGTERMs itself after admitting the slab:
  exercises the resilience drain (emergency checkpoint, quiesce, distinct
  exit code).
- ``param_lane_stall`` — the learner skips publishing params for
  ``duration_s`` seconds: actors keep sampling stale versions and the
  staleness-admission path (count, drop, refill) is exercised end to end.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Mapping, Sequence

from sheeprl_tpu.utils.faults import DeterministicSchedule, parse_fault_entries, register_fault_domain

ACTOR_KINDS = ("actor_crash_mid_write", "actor_hang")
LEARNER_KINDS = ("learner_kill", "param_lane_stall")
_KINDS = ACTOR_KINDS + LEARNER_KINDS
register_fault_domain("actor_learner", _KINDS)


@dataclass
class ALFaultSpec:
    kind: str
    at_slab: int
    actor: int = -1  # required for actor kinds, ignored for learner kinds
    duration_s: float = 0.0

    def __post_init__(self) -> None:
        self.kind = str(self.kind).lower()
        if self.kind not in _KINDS:
            raise ValueError(f"unknown actor_learner fault kind {self.kind!r}; expected one of {_KINDS}")
        self.at_slab = int(self.at_slab)
        self.actor = int(self.actor)
        self.duration_s = float(self.duration_s)
        if self.at_slab < 0:
            raise ValueError(f"fault at_slab must be >= 0, got {self.at_slab}")
        if self.kind in ACTOR_KINDS and self.actor < 0:
            raise ValueError(f"fault kind {self.kind!r} needs an actor index >= 0")

    @property
    def is_actor_fault(self) -> bool:
        return self.kind in ACTOR_KINDS

    def to_wire(self) -> Dict[str, Any]:
        """Plain-dict form shipped inside the actor spawn blob."""
        return {"kind": self.kind, "at_slab": self.at_slab, "duration_s": self.duration_s}


def parse_al_fault_config(node: Sequence[Mapping[str, Any]]) -> List[ALFaultSpec]:
    entries = parse_fault_entries(
        node,
        domain="actor_learner.fault_injection",
        required=("kind", "at_slab"),
        fields=(
            ("at_slab", int, 0),
            ("actor", int, -1),
            ("duration_s", float, 0.0),
        ),
    )
    return [ALFaultSpec(**e) for e in entries]


class LearnerFaultSchedule:
    """Learner-side half of the drill script; popped per admitted slab."""

    def __init__(self, faults: Sequence[ALFaultSpec]) -> None:
        self._schedule = DeterministicSchedule(
            [f for f in faults if not f.is_actor_fault], at=lambda f: f.at_slab
        )

    def __bool__(self) -> bool:
        return bool(self._schedule)

    def pop_due(self, admitted: int) -> List[ALFaultSpec]:
        """Faults due at (or before — nothing is silently dropped) the
        ``admitted``-th admitted slab, marked fired."""
        return self._schedule.pop_due(admitted)


def actor_faults_for(faults: Sequence[ALFaultSpec], actor: int) -> List[ALFaultSpec]:
    return [f for f in faults if f.is_actor_fault and f.actor == int(actor)]
