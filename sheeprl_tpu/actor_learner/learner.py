"""Learner process: continuous donated updates over staleness-admitted slabs.

``run_actor_learner`` is the single-process entrypoint of the disaggregated
topology (``ppo_decoupled`` dispatches here when there is no jax.distributed
process group): it owns the devices, the trajectory ring, the param lane and
the actor supervisor, and runs the admission loop

    poll ring → admit (staleness bound) → fused donated update →
    bump version → publish packed params → repeat

until ``num_updates`` slabs have trained. Every slab is a complete training
batch (the actors run GAE), so the learner never blocks on collection — its
idle time is exactly the slab-starved wait, reported as
``Time/train_wait_time`` so the heartbeat's ``overlap_fraction`` reads the
topology's health directly (→ 1.0 when actors keep the ring fed).

Fault surface wired here: the resilience crash guard + preemption watcher
(SIGTERM → emergency checkpoint → quiesce actors → exit 77), the NaN
sentinel/rollback, the actor supervisor's budgeted restarts (budget
exhaustion aborts the run with :class:`ActorBudgetExhausted` → outcome
``actor_exhausted``), and the learner-side halves of the scripted drills
(``learner_kill``, ``param_lane_stall``).
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, Dict, Optional

import gymnasium as gym
import jax
import numpy as np
import optax

from sheeprl_tpu.actor_learner.config import ActorLearnerConfig, actor_learner_config_from_cfg, admit
from sheeprl_tpu.actor_learner.fault_injection import LearnerFaultSchedule, actor_faults_for
from sheeprl_tpu.actor_learner.ring import SlabLayout
from sheeprl_tpu.actor_learner.supervisor import ActorSupervisor
from sheeprl_tpu.net.transport import build_learner_transport
from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent
from sheeprl_tpu.algos.ppo.ppo import make_train_fn
from sheeprl_tpu.algos.ppo.utils import AGGREGATOR_KEYS, test
from sheeprl_tpu.config.compose import instantiate
from sheeprl_tpu.obs import (
    telemetry_actor_restart,
    telemetry_advance,
    telemetry_child_file,
    telemetry_register_flops,
    telemetry_run_metrics,
    telemetry_slab,
    telemetry_slab_lag,
    telemetry_torn_slabs,
    telemetry_train_window,
)
from sheeprl_tpu.obs.telemetry import get_telemetry
from sheeprl_tpu.obs.trace import set_trace_role, trace_event
from sheeprl_tpu.parallel.fabric import _ParamStreamer, put_tree, resolve_player_device, resolve_train_device
from sheeprl_tpu.parallel.submesh import probe_spaces
from sheeprl_tpu.resilience import RunResilience
from sheeprl_tpu.utils.logger import get_log_dir, get_logger
from sheeprl_tpu.utils.metric import MetricAggregator
from sheeprl_tpu.utils.utils import SteadyStateProbe, polynomial_decay, save_configs


def build_slab_layout(obs_space, cnn_keys, mlp_keys, actions_width: int, rows: int) -> SlabLayout:
    """The slab wire format both ends agree on: prepared obs (cnn stack-folded
    uint8, mlp float32), the flattened training columns, and the 3-word
    episode-stats tail ``[ret_sum, len_sum, ep_count]``."""
    fields: Dict[str, Any] = {}
    for k in cnn_keys:
        shape = obs_space[k].shape  # [S,H,W,C] (stacked) or [H,W,C]
        if len(shape) == 4:
            s, h, w, c = shape
            shape = (h, w, s * c)
        fields[k] = ((rows, *shape), "uint8")
    for k in mlp_keys:
        fields[k] = ((rows, *obs_space[k].shape), "float32")
    fields["actions"] = ((rows, actions_width), "float32")
    for k in ("logprobs", "values", "returns", "advantages"):
        fields[k] = ((rows, 1), "float32")
    fields["ep_stats"] = ((3,), "float32")
    return SlabLayout(fields)


def run_actor_learner(fabric, cfg: Dict[str, Any], state: Optional[Dict[str, Any]] = None):
    log_dir = get_log_dir(cfg)
    logger = get_logger(cfg, log_dir)
    fabric.logger = logger
    logger.log_hyperparams(cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg))
    print(f"Log dir: {log_dir}")

    resil = RunResilience(fabric, cfg, log_dir)
    alcfg: ActorLearnerConfig = actor_learner_config_from_cfg(cfg)
    # name this process's track on the merged cross-process timeline; actors
    # hand their standalone recorders their own roles (actor<i>)
    set_trace_role("learner")
    # actors get a trace dir only when the run is telemetered — their
    # flush-per-event recorders exist to be merged with telemetry.jsonl
    trace_dir = log_dir if get_telemetry() is not None else None

    num_envs = int(cfg.env.num_envs)
    rollout_steps = int(cfg.algo.rollout_steps)
    envs_per_actor = alcfg.envs_per_actor(num_envs)
    slab_rows = rollout_steps * envs_per_actor

    observation_space, action_space = probe_spaces(cfg)
    if not isinstance(observation_space, gym.spaces.Dict):
        raise RuntimeError(f"Unexpected observation type, should be of type Dict, got: {observation_space}")
    cnn_keys = list(cfg.algo.cnn_keys.encoder)
    mlp_keys = list(cfg.algo.mlp_keys.encoder)
    obs_keys = cnn_keys + mlp_keys
    if not obs_keys:
        raise RuntimeError(
            "You should specify at least one CNN key or MLP key from the cli: "
            "`algo.cnn_keys.encoder=[rgb]` or `algo.mlp_keys.encoder=[state]`"
        )
    is_continuous = isinstance(action_space, gym.spaces.Box)
    is_multidiscrete = isinstance(action_space, gym.spaces.MultiDiscrete)
    actions_dim = tuple(
        action_space.shape
        if is_continuous
        else (action_space.nvec.tolist() if is_multidiscrete else [action_space.n])
    )

    agent, params = build_agent(
        fabric, actions_dim, is_continuous, cfg, observation_space, state["agent"] if state else None
    )
    player = PPOPlayer(agent, params, device=resolve_player_device(cfg.algo.get("player_device", "auto")))

    world_size = fabric.data_parallel_size
    if slab_rows % world_size != 0:
        raise ValueError(
            f"rollout_steps*envs_per_actor ({slab_rows}) must be divisible by the device count ({world_size})"
        )
    n_local = slab_rows // world_size
    num_minibatches = max(1, n_local // int(cfg.algo.per_rank_batch_size))
    update_epochs = int(cfg.algo.update_epochs)
    # each admitted slab is one update worth slab_rows env steps
    policy_steps_per_update = slab_rows
    num_updates = int(cfg.algo.total_steps) // policy_steps_per_update if not cfg.dry_run else 1

    opt_cfg = dict(cfg.algo.optimizer.to_dict() if hasattr(cfg.algo.optimizer, "to_dict") else cfg.algo.optimizer)
    if cfg.algo.max_grad_norm and float(cfg.algo.max_grad_norm) > 0:
        opt_cfg["max_grad_norm"] = float(cfg.algo.max_grad_norm)
    if cfg.algo.anneal_lr:
        opt_cfg["schedule"] = optax.linear_schedule(
            float(opt_cfg.get("lr", 1e-3)), 0.0, num_updates * update_epochs * num_minibatches
        )
    tx = instantiate(opt_cfg)
    train_device = resolve_train_device(cfg.algo.get("train_device", "auto"), params, fabric.world_size)
    if train_device is not None:
        params = put_tree(jax.device_get(params), train_device)
        player.update_params(params)
    opt_state = state["opt_state"] if state else tx.init(params)
    opt_state = put_tree(opt_state, train_device) if train_device is not None else fabric.replicate(opt_state)

    if fabric.is_global_zero:
        save_configs(cfg, log_dir)

    aggregator = MetricAggregator(cfg.metric.get("aggregator", {}).get("metrics", {}) or {})
    for k in AGGREGATOR_KEYS - set(aggregator.metrics):
        aggregator.add(k, "mean")

    train_fn = make_train_fn(fabric, agent, tx, cfg, obs_keys, n_local, host_device=train_device)

    # ------------------------------------------------------------- transport
    layout = build_slab_layout(observation_space, cnn_keys, mlp_keys, int(sum(actions_dim)), slab_rows)
    pack_device = train_device if train_device is not None else jax.local_devices()[0]
    streamer = _ParamStreamer(jax.device_get(params), pack_device)
    transport = build_learner_transport(
        alcfg.transport,
        payload_bytes=layout.nbytes,
        num_slots=alcfg.num_actors * alcfg.slots_per_actor,
        slots_per_actor=alcfg.slots_per_actor,
        param_nbytes=streamer.nbytes,
        host=alcfg.bind_host,
        port=alcfg.bind_port,
    )

    precision_name = fabric.precision.name

    def make_blob(actor_index: int, generation: int) -> bytes:
        import cloudpickle

        # scripted faults ride ONLY the generation-0 blob: a respawned actor
        # must not re-fire the drill that killed it (crash loop)
        faults = (
            [f.to_wire() for f in actor_faults_for(alcfg.faults, actor_index)] if generation == 0 else []
        )
        return cloudpickle.dumps(
            {
                "cfg": cfg,
                "generation": generation,
                "slots": alcfg.actor_slots(actor_index),
                "envs_per_actor": envs_per_actor,
                "rollout_steps": rollout_steps,
                "faults": faults,
                "precision": precision_name,
                "transport": transport.actor_wire(actor_index),
                "layout": layout.to_wire(),
                "trace_dir": trace_dir,
                # seq-disjoint generations keep the fold_in action streams
                # unique across restarts
                "start_seq": generation * (1 << 20),
            }
        )

    version = 0
    transport.publish_params(np.asarray(streamer.begin(params)), version)
    trace_event("param_publish", version=version)

    supervisor = ActorSupervisor(alcfg, transport, make_blob, on_restart=telemetry_actor_restart)
    if trace_dir is not None:
        # declare the child trace files up front so the registry record names
        # the run's full file set even if an actor dies before its first slab
        for i in range(alcfg.num_actors):
            telemetry_child_file(os.path.join(trace_dir, f"trace.actor{i}.jsonl"))

    # --------------------------------------------------------------- counters
    start_update = (state["update"] + 1) if state else 1
    policy_step = state["update"] * policy_steps_per_update if state else 0
    last_log = state["last_log"] if state else 0
    last_checkpoint = state["last_checkpoint"] if state else 0
    train_step = 0
    last_train = 0
    update = start_update - 1  # completed updates

    key = jax.random.PRNGKey(int(cfg.seed))
    if state and "rng_key" in state:
        key = np.asarray(state["rng_key"])
    if train_device is not None:
        key = put_tree(key, train_device)
    elif state and "rng_key" in state:
        import jax.numpy as jnp

        key = jnp.asarray(key)

    clip_coef = float(cfg.algo.clip_coef)
    ent_coef = float(cfg.algo.ent_coef)
    initial_clip_coef, initial_ent_coef = clip_coef, ent_coef

    def ckpt_state_fn(completed_update: int) -> Dict[str, Any]:
        return {
            "agent": jax.device_get(params),
            "opt_state": jax.device_get(opt_state),
            "update": completed_update,
            "batch_size": int(cfg.algo.per_rank_batch_size) * world_size,
            "last_log": last_log,
            "last_checkpoint": last_checkpoint,
            "rng_key": jax.device_get(key),
        }

    def ckpt_path_fn(step: int) -> str:
        return os.path.join(log_dir, "checkpoint", f"ckpt_{step}_{fabric.process_index}.ckpt")

    def rollback_state(at_update: int) -> None:
        # restore the newest committed checkpoint and fork the train key away
        # from the diverged stream; the actors never saw the poisoned params
        # (publish happens only after the finite check), so the lane stays on
        # the last good version
        nonlocal params, opt_state, key
        restored = resil.rollback(update=at_update)
        params = resil.place_like(restored["agent"], params)
        opt_state = resil.place_like(restored["opt_state"], opt_state)
        if "rng_key" in restored:
            key = resil.place_like(restored["rng_key"], key)
        key = resil.resalt_key(key)

    def maybe_checkpoint() -> None:
        nonlocal last_checkpoint
        if (cfg.checkpoint.every > 0 and policy_step - last_checkpoint >= cfg.checkpoint.every) or (
            update == num_updates and cfg.checkpoint.save_last
        ):
            last_checkpoint = policy_step
            fabric.call("on_checkpoint_coupled", ckpt_path=ckpt_path_fn(policy_step), state=ckpt_state_fn(update))

    resil.arm_crash_guard(
        path_fn=lambda: ckpt_path_fn(policy_step),
        state_fn=lambda: ckpt_state_fn(update),
    )

    fault_sched = LearnerFaultSchedule(alcfg.faults)
    stall_until = 0.0  # param_lane_stall: monotonic deadline; 0 = not stalled
    published_version = version

    # window accumulators for the heartbeat: env time is credited from the
    # slabs' COLLECT_US stamps (the actors' wall clock), wait time is the
    # learner's slab-starved idle, train time is measured around the update.
    # Idle before the FIRST admitted slab is actor boot (process spawn + jax
    # import + jit warmup) — the analogue of the sync loop's pre-loop env
    # construction, which its timers never see either — so it is reported as
    # its own spawn_wait event instead of polluting the steady-state
    # overlap_fraction.
    win_env_s = 0.0
    win_env_steps = 0
    win_train_s = 0.0
    win_wait_s = 0.0
    spawn_wait_s = 0.0
    torn_seen = 0
    admitted = 0
    dropped_stale = 0

    def sync_torn() -> None:
        nonlocal torn_seen
        total = transport.torn_detected + supervisor.torn_reclaimed
        if total > torn_seen:
            telemetry_torn_slabs(total - torn_seen, source=transport.kind)
            torn_seen = total
        # terminate each victim's causal chain on the merged timeline: its
        # trace ends at `torn`, never at `slab_train`
        for tid in transport.drain_torn_trace_ids():
            trace_event("torn", tid, source=transport.kind)

    def maybe_heartbeat(final: bool = False) -> None:
        nonlocal last_log, last_train, win_env_s, win_env_steps, win_train_s, win_wait_s
        if cfg.metric.log_level <= 0 or (policy_step - last_log < cfg.metric.log_every and not final):
            return
        metrics_dict = aggregator.compute()
        logger.log_metrics(metrics_dict, policy_step)
        telemetry_run_metrics(metrics_dict)
        aggregator.reset()
        sps = {}
        if win_train_s > 0:
            sps["Time/sps_train"] = (train_step - last_train) / win_train_s
        if win_env_s > 0:
            sps["Time/sps_env_interaction"] = win_env_steps / win_env_s
        if sps:
            logger.log_metrics(sps, policy_step)
        tel = get_telemetry()
        if tel is not None:
            tel.heartbeat(
                logger,
                step=policy_step,
                env_steps=win_env_steps,
                train_steps=train_step - last_train,
                train_invocations=(train_step - last_train) // world_size,
                timer_window={
                    "Time/env_interaction_time": win_env_s,
                    "Time/train_time": win_train_s,
                    "Time/train_wait_time": win_wait_s,
                },
            )
        last_log = policy_step
        last_train = train_step
        win_env_s = win_env_steps = 0
        win_train_s = win_wait_s = 0.0

    preempted = False
    probe = SteadyStateProbe()
    try:
        supervisor.spawn_all()
        while update < num_updates:
            if resil.preempt_requested():
                last_checkpoint = policy_step
                resil.emergency_checkpoint(ckpt_path_fn(policy_step), ckpt_state_fn(update))
                preempted = True
                break

            # an expired param_lane_stall: catch the lane up to the current
            # version so actors stop sampling the stalled one
            if stall_until and time.monotonic() >= stall_until:
                stall_until = 0.0
                if published_version < version:
                    transport.publish_params(np.asarray(streamer.begin(params)), version)
                    trace_event("param_publish", version=version, after_stall=True)
                    published_version = version

            meta = transport.poll()
            sync_torn()
            if meta is None:
                t0 = time.perf_counter()
                supervisor.check_health()
                time.sleep(alcfg.poll_interval_s)
                if admitted:
                    win_wait_s += time.perf_counter() - t0
                else:
                    spawn_wait_s += time.perf_counter() - t0
                continue

            staleness = version - meta.param_version
            ok = admit(meta.param_version, version, alcfg.max_staleness)
            telemetry_slab(staleness=staleness, occupancy=transport.occupancy(), admitted=ok)
            # commit→admit ring wait from the slab header's epoch-µs commit
            # stamp (same host, so the epoch clocks agree)
            ring_wait_us = (
                max(0, int(time.time() * 1e6) - meta.commit_t_us) if meta.commit_t_us else 0
            )
            if not ok:
                # count, drop, free the slot — the owning actor refills it
                # against a fresher version
                dropped_stale += 1
                if meta.trace_id:
                    trace_event(
                        "slab_drop_stale",
                        meta.trace_id,
                        actor=meta.actor_id,
                        seq=meta.seq,
                        param_version=meta.param_version,
                        staleness=staleness,
                    )
                transport.release(meta)
                continue
            if meta.trace_id:
                trace_event(
                    "slab_admit",
                    meta.trace_id,
                    slot=meta.slot,
                    actor=meta.actor_id,
                    seq=meta.seq,
                    param_version=meta.param_version,
                    staleness=staleness,
                    ring_wait_us=ring_wait_us,
                )

            if admitted == 0 and spawn_wait_s > 0:
                # the first slab just landed: everything the learner waited
                # through so far was actor boot, not slab starvation
                tel = get_telemetry()
                if tel is not None:
                    tel.emit("spawn_wait", seconds=spawn_wait_s)

            flat = layout.unpack(transport.payload(meta))  # copies out
            transport.release(meta)
            ep_stats = flat.pop("ep_stats")

            telemetry_advance(policy_step)
            if update == start_update:
                probe.mark(policy_step)
            t0 = time.perf_counter()
            key, train_key = jax.random.split(key)
            params, opt_state, metrics = train_fn(
                params,
                opt_state,
                flat,
                train_key,
                np.float32(clip_coef),
                np.float32(ent_coef),
            )
            metrics = np.asarray(metrics)
            train_dt = time.perf_counter() - t0
            win_train_s += train_dt
            telemetry_train_window(1, update_epochs * num_minibatches)

            if not resil.check_finite(metrics, update + 1):
                rollback_state(update + 1)
                continue

            update += 1
            train_step += world_size
            policy_step += meta.n_rows
            win_env_s += meta.collect_us / 1e6
            win_env_steps += meta.env_steps
            if meta.trace_id:
                train_us = int(train_dt * 1e6)
                trace_event("slab_train", meta.trace_id, train_us=train_us, update=update)
                telemetry_slab_lag(
                    collect_us=meta.collect_us, ring_wait_us=ring_wait_us, train_us=train_us
                )
            if update == start_update:
                telemetry_register_flops(
                    train_fn, params, opt_state, flat, train_key, np.float32(clip_coef), np.float32(ent_coef)
                )

            if cfg.metric.log_level > 0:
                aggregator.update("Loss/policy_loss", float(metrics[0]))
                aggregator.update("Loss/value_loss", float(metrics[1]))
                aggregator.update("Loss/entropy_loss", float(metrics[2]))
                if ep_stats[2] > 0:
                    aggregator.update("Rewards/rew_avg", float(ep_stats[0] / ep_stats[2]))
                    aggregator.update("Game/ep_len_avg", float(ep_stats[1] / ep_stats[2]))

            # versioned broadcast: the bump precedes the publish, and a
            # scripted lane stall suppresses ONLY the publish — admission
            # keeps counting against the bumped version, which is what drives
            # the staleness drill's count/drop/refill path
            version += 1
            for f in fault_sched.pop_due(admitted):
                if f.kind == "param_lane_stall":
                    stall_until = time.monotonic() + f.duration_s
                elif f.kind == "learner_kill":
                    os.kill(os.getpid(), signal.SIGTERM)
            if not stall_until:
                transport.publish_params(np.asarray(streamer.begin(params)), version)
                trace_event("param_publish", version=version)
                published_version = version
            admitted += 1

            if cfg.algo.anneal_clip_coef:
                clip_coef = polynomial_decay(
                    update, initial=initial_clip_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            if cfg.algo.anneal_ent_coef:
                ent_coef = polynomial_decay(
                    update, initial=initial_ent_coef, final=0.0, max_decay_steps=num_updates, power=1.0
                )
            maybe_heartbeat(update == num_updates)
            maybe_checkpoint()
    finally:
        # BOTH exits — clean and crash — must leave zero orphaned actors and
        # zero leaked shm segments; the cli's crash drain runs after this
        try:
            supervisor.quiesce_all()
        except Exception:
            pass
        sync_torn()
        transport.close()

    probe.finish(policy_step, sync=lambda: jax.device_get(jax.tree.leaves(params)[0]))
    maybe_heartbeat(final=True)
    if fabric.is_global_zero and cfg.algo.run_test and not preempted:
        player.update_params(params)
        test(player, fabric, cfg, log_dir)
    logger.finalize()
    resil.close()
    if preempted:
        resil.exit_preempted()
