"""Torn-write-safe shared-memory trajectory ring (actor → learner transport).

One ``SharedMemory`` block: a per-slot int64 header table followed by
fixed-size payload slabs. Slots are **partitioned per actor** — each actor
round-robins over its own slots, so every slot has exactly one writer and the
learner is the only reader. That single-writer/single-reader discipline is
what lets a seqlock-style commit protocol stand in for locks:

writer (actor)                          reader (learner)
--------------------------------------------------------------------------
state = WRITING                         state != COMMITTED  -> skip
payload[...] = slab bytes               state == COMMITTED:
meta words (seq, version, rows, ...)        checksum over header words
checksum over the meta words                mismatch -> torn, reclaim+count
state = COMMITTED   <- written LAST         match    -> copy payload, FREE

A crashed actor can die at any point of the left column. Death before the
final ``state = COMMITTED`` store leaves the slot ``WRITING`` forever — the
reader never admits it, and the supervisor reclaims it on restart
(:meth:`TrajectoryRing.reclaim_actor_slots`, the "in-flight slab abandoned"
path). The checksum is belt and braces for the one remaining hazard: a
commit marker that lands over stale meta (e.g. a slot recycled across an
actor generation), which surfaces as ``COMMITTED`` + checksum mismatch and
is counted as torn rather than admitted.

Aligned int64 stores are atomic on every platform jax runs on, so header
words are never themselves torn; the protocol only has to order them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sheeprl_tpu.rollout.shm import attach_untracked, create_untracked, unregister_owned_segment

# header word indices — TRACE_ID/COMMIT_T_US are trace-plane context
# (sheeprl_tpu.obs.trace): the slab's cross-process causal id and the epoch-µs
# stamp taken just before commit, read back at learner admission to measure
# the commit→admit ring wait. They sit BEFORE CHECKSUM so the meta checksum
# slice (SEQ..COMMIT_T_US) covers them.
(
    STATE,
    SEQ,
    PARAM_VERSION,
    ACTOR_ID,
    N_ROWS,
    COLLECT_US,
    ENV_STEPS,
    TRACE_ID,
    COMMIT_T_US,
    CHECKSUM,
) = range(10)
HEADER_WORDS = 10
_HEADER_BYTES = HEADER_WORDS * 8

# slot states
FREE, WRITING, COMMITTED = 0, 1, 2

_MASK = (1 << 63) - 1
_SALT = 0x9E3779B97F4A7C15 & _MASK


def _checksum(words: Sequence[int]) -> int:
    """Order-sensitive mix of the meta words (SEQ..ENV_STEPS)."""
    acc = _SALT
    for w in words:
        acc = ((acc * 31) ^ (int(w) & _MASK)) & _MASK
    return acc


@dataclass
class SlabMeta:
    """Header snapshot of one committed slab."""

    slot: int
    seq: int
    param_version: int
    actor_id: int
    n_rows: int
    collect_us: int
    env_steps: int
    trace_id: int = 0
    commit_t_us: int = 0


@dataclass
class RingSpec:
    """Wire-format handle (std-picklable) an actor uses to attach."""

    name: str
    num_slots: int
    payload_bytes: int


class SlabLayout:
    """Fixed dict-of-arrays ⇄ flat-bytes codec for one slab payload.

    The same role ``_ParamStreamer`` plays for params, but host-side numpy:
    both ends agree on ``(key, shape, dtype)`` per field, so a slab is one
    contiguous byte write/read with zero per-field protocol."""

    def __init__(self, fields: Dict[str, Tuple[Tuple[int, ...], str]]) -> None:
        self.fields: Dict[str, Tuple[Tuple[int, ...], np.dtype]] = {
            k: (tuple(int(d) for d in shape), np.dtype(dtype)) for k, (shape, dtype) in fields.items()
        }
        self.offsets: Dict[str, Tuple[int, int]] = {}
        off = 0
        for k, (shape, dtype) in self.fields.items():
            nbytes = int(np.prod(shape)) * dtype.itemsize
            self.offsets[k] = (off, off + nbytes)
            off += nbytes
        self.nbytes = off

    def to_wire(self) -> List[Tuple[str, Tuple[int, ...], str]]:
        return [(k, shape, dtype.str) for k, (shape, dtype) in self.fields.items()]

    @classmethod
    def from_wire(cls, wire: Sequence[Tuple[str, Tuple[int, ...], str]]) -> "SlabLayout":
        return cls({k: (tuple(shape), dtype) for k, shape, dtype in wire})

    def pack_into(self, buf: np.ndarray, data: Dict[str, np.ndarray]) -> None:
        for k, (shape, dtype) in self.fields.items():
            o0, o1 = self.offsets[k]
            arr = np.ascontiguousarray(data[k], dtype=dtype)
            if arr.shape != shape:
                raise ValueError(f"slab field {k!r}: expected shape {shape}, got {arr.shape}")
            buf[o0:o1] = arr.view(np.uint8).reshape(-1)

    def unpack(self, buf: np.ndarray) -> Dict[str, np.ndarray]:
        out = {}
        for k, (shape, dtype) in self.fields.items():
            o0, o1 = self.offsets[k]
            out[k] = np.frombuffer(bytes(buf[o0:o1]), dtype=dtype).reshape(shape)
        return out


class TrajectoryRing:
    """The shared slab ring. Learner constructs (owner), actors attach."""

    def __init__(
        self,
        num_slots: int,
        payload_bytes: int,
        *,
        spec: Optional[RingSpec] = None,
    ) -> None:
        self.num_slots = int(num_slots)
        self.payload_bytes = int(payload_bytes)
        total = self.num_slots * (_HEADER_BYTES + self.payload_bytes)
        if spec is None:
            self._block = create_untracked(total)
            self._owner = True
        else:
            self._block = attach_untracked(spec.name)
            self._owner = False
        self._hdr = np.ndarray((self.num_slots, HEADER_WORDS), dtype=np.int64, buffer=self._block.buf)
        self._payload = np.ndarray(
            (self.num_slots, self.payload_bytes),
            dtype=np.uint8,
            buffer=self._block.buf,
            offset=self.num_slots * _HEADER_BYTES,
        )
        if self._owner:
            self._hdr[...] = 0  # all slots FREE
        self.torn_detected = 0  # reader-side: COMMITTED with a bad checksum
        # trace ids of torn slabs (poll mismatch + reclaim sweep), drained by
        # the learner into `torn` trace events so a victim's causal chain
        # terminates visibly on the merged timeline
        self.torn_trace_ids: List[int] = []

    # ------------------------------------------------------------------ wire
    def spec(self) -> RingSpec:
        return RingSpec(name=self._block.name, num_slots=self.num_slots, payload_bytes=self.payload_bytes)

    @classmethod
    def attach(cls, spec: RingSpec) -> "TrajectoryRing":
        return cls(spec.num_slots, spec.payload_bytes, spec=spec)

    # ---------------------------------------------------------------- writer
    def try_begin_write(self, slot: int) -> bool:
        """Claim ``slot`` for writing; False while the reader still owns it."""
        if int(self._hdr[slot, STATE]) != FREE:
            return False
        self._hdr[slot, STATE] = WRITING
        return True

    def payload_view(self, slot: int) -> np.ndarray:
        return self._payload[slot]

    def write_meta(
        self,
        slot: int,
        *,
        seq: int,
        param_version: int,
        actor_id: int,
        n_rows: int,
        collect_us: int,
        env_steps: int,
        trace_id: int = 0,
        commit_t_us: int = 0,
    ) -> None:
        """Meta + checksum; the slot is still ``WRITING`` after this — a death
        here is exactly the torn write the reader must skip."""
        hdr = self._hdr[slot]
        hdr[SEQ] = seq
        hdr[PARAM_VERSION] = param_version
        hdr[ACTOR_ID] = actor_id
        hdr[N_ROWS] = n_rows
        hdr[COLLECT_US] = collect_us
        hdr[ENV_STEPS] = env_steps
        hdr[TRACE_ID] = trace_id
        hdr[COMMIT_T_US] = commit_t_us
        hdr[CHECKSUM] = _checksum(hdr[SEQ:CHECKSUM])

    def commit(self, slot: int) -> None:
        """The seqlock publish: the state word flips to COMMITTED strictly
        after payload, meta and checksum are in place."""
        self._hdr[slot, STATE] = COMMITTED

    # ---------------------------------------------------------------- reader
    def poll(self, slot: int) -> Optional[SlabMeta]:
        """Admit-or-skip one slot. Returns the meta of a cleanly committed
        slab (payload still in place — read it, then :meth:`release`), or
        None for FREE/WRITING/torn slots. A torn COMMITTED slot (checksum
        mismatch) is reclaimed to FREE and counted, never surfaced."""
        hdr = self._hdr[slot]
        if int(hdr[STATE]) != COMMITTED:
            return None
        if int(hdr[CHECKSUM]) != _checksum(hdr[SEQ:CHECKSUM]):
            self.torn_detected += 1
            # best-effort victim attribution: the checksum failed, so the
            # trace-id word may be stale — a nonzero value still names the
            # newest trace that touched this slot
            tid = int(hdr[TRACE_ID])
            if tid:
                self.torn_trace_ids.append(tid)
            hdr[STATE] = FREE
            return None
        return SlabMeta(
            slot=slot,
            seq=int(hdr[SEQ]),
            param_version=int(hdr[PARAM_VERSION]),
            actor_id=int(hdr[ACTOR_ID]),
            n_rows=int(hdr[N_ROWS]),
            collect_us=int(hdr[COLLECT_US]),
            env_steps=int(hdr[ENV_STEPS]),
            trace_id=int(hdr[TRACE_ID]),
            commit_t_us=int(hdr[COMMIT_T_US]),
        )

    def release(self, slot: int) -> None:
        self._hdr[slot, STATE] = FREE

    def reclaim_actor_slots(self, slots: Sequence[int]) -> int:
        """Free every non-COMMITTED slot of a dead actor (its in-flight slab
        is abandoned by definition). Returns how many WRITING slots — i.e.
        torn writes — were reclaimed. Committed slabs survive: they were
        published before the crash and are still valid."""
        torn = 0
        for slot in slots:
            state = int(self._hdr[slot, STATE])
            if state == WRITING:
                torn += 1
                # crash-mid-write: if the meta words (incl. TRACE_ID) landed
                # before the death, the checksum matches and the trace id is
                # trustworthy — capture it so the torn trace terminates
                # attributed instead of dangling
                hdr = self._hdr[slot]
                tid = int(hdr[TRACE_ID])
                if tid and int(hdr[CHECKSUM]) == _checksum(hdr[SEQ:CHECKSUM]):
                    self.torn_trace_ids.append(tid)
                self._hdr[slot, STATE] = FREE
        return torn

    def drain_torn_trace_ids(self) -> List[int]:
        """Hand the accumulated torn-slab trace ids to the caller (learner)
        exactly once each."""
        ids, self.torn_trace_ids = self.torn_trace_ids, []
        return ids

    def occupancy(self) -> float:
        """Fraction of slots holding a committed, unconsumed slab."""
        return float(np.count_nonzero(self._hdr[:, STATE] == COMMITTED)) / max(1, self.num_slots)

    # -------------------------------------------------------------- teardown
    def close(self) -> None:
        self._hdr = None
        self._payload = None
        if self._block is None:
            return
        block, self._block = self._block, None
        try:
            block.close()
        except Exception:
            pass
        if self._owner:
            unregister_owned_segment(block.name)
            try:
                block.unlink()
            except FileNotFoundError:
                pass
