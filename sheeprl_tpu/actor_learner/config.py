"""Actor–learner topology knobs, parsed once from ``algo.actor_learner``.

The node lives under ``algo`` (not top-level) because the topology is a
property of the training algorithm — the decoupled PPO entrypoint reads it;
CLI overrides read ``algo.actor_learner.max_staleness=0``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Mapping, Optional

from sheeprl_tpu.actor_learner.fault_injection import ALFaultSpec, parse_al_fault_config
from sheeprl_tpu.rollout.config import _get


@dataclass
class ActorLearnerConfig:
    """Sizing, staleness and supervision parameters for the disaggregated
    topology. Supervision attribute names deliberately match
    :class:`~sheeprl_tpu.rollout.config.PoolConfig` so the actor supervisor
    reuses ``rollout.supervisor`` machinery unchanged."""

    num_actors: int = 2
    slots_per_actor: int = 2
    max_staleness: int = 1
    # data-plane transport: "shm" (same-host shared memory, the default) or
    # "tcp" (length-prefixed frames over a socket — actors may live on other
    # hosts; see howto/multihost.md). bind_host/bind_port are the learner's
    # listen address in tcp mode; port 0 picks an ephemeral port that rides
    # to the actors inside the spawn blob.
    transport: str = "shm"
    bind_host: str = "127.0.0.1"
    bind_port: int = 0
    poll_interval_s: float = 0.002
    step_timeout_s: float = 120.0
    spawn_timeout_s: float = 300.0
    heartbeat_grace_s: Optional[float] = None  # default: step_timeout_s
    max_restarts: int = 3
    restart_refund_s: Optional[float] = 600.0
    backoff_base_s: float = 0.5
    backoff_max_s: float = 10.0
    quiesce_timeout_s: float = 5.0
    start_method: str = "spawn"
    faults: List[ALFaultSpec] = field(default_factory=list)

    def __post_init__(self) -> None:
        if self.num_actors < 1:
            raise ValueError(f"actor_learner.num_actors must be >= 1, got {self.num_actors}")
        if self.slots_per_actor < 1:
            raise ValueError(f"actor_learner.slots_per_actor must be >= 1, got {self.slots_per_actor}")
        if self.max_staleness < 0:
            raise ValueError(f"actor_learner.max_staleness must be >= 0, got {self.max_staleness}")
        if self.transport not in ("shm", "tcp"):
            raise ValueError(
                f"actor_learner.transport must be 'shm' or 'tcp', got {self.transport!r}"
            )

    @property
    def heartbeat_grace(self) -> float:
        return self.step_timeout_s if self.heartbeat_grace_s is None else float(self.heartbeat_grace_s)

    def envs_per_actor(self, num_envs: int) -> int:
        if num_envs % self.num_actors != 0:
            raise ValueError(
                f"env.num_envs ({num_envs}) must be divisible by actor_learner.num_actors ({self.num_actors})"
            )
        return num_envs // self.num_actors

    def actor_slots(self, actor: int) -> List[int]:
        """The ring slot indices owned (single-writer) by ``actor``."""
        base = int(actor) * self.slots_per_actor
        return list(range(base, base + self.slots_per_actor))


def actor_learner_config_from_cfg(cfg: Mapping[str, Any]) -> ActorLearnerConfig:
    """Build from the composed run config's ``algo.actor_learner`` node
    (absent node → all defaults, faults disabled)."""
    algo = _get(cfg, "algo") or {}
    node = _get(algo, "actor_learner") or {}
    fault_node = _get(node, "fault_injection") or {}
    faults: List[ALFaultSpec] = []
    if bool(_get(fault_node, "enabled", False)):
        faults = parse_al_fault_config(_get(fault_node, "faults") or [])
    refund = _get(node, "restart_refund_s", 600.0)
    return ActorLearnerConfig(
        num_actors=int(_get(node, "num_actors", 2)),
        slots_per_actor=int(_get(node, "slots_per_actor", 2)),
        max_staleness=int(_get(node, "max_staleness", 1)),
        transport=str(_get(node, "transport", "shm")),
        bind_host=str(_get(node, "bind_host", "127.0.0.1")),
        bind_port=int(_get(node, "bind_port", 0)),
        poll_interval_s=float(_get(node, "poll_interval_s", 0.002)),
        step_timeout_s=float(_get(node, "step_timeout_s", 120.0)),
        spawn_timeout_s=float(_get(node, "spawn_timeout_s", 300.0)),
        heartbeat_grace_s=_get(node, "heartbeat_grace_s", None),
        max_restarts=int(_get(node, "max_restarts", 3)),
        restart_refund_s=float(refund) if refund is not None else None,
        backoff_base_s=float(_get(node, "backoff_base_s", 0.5)),
        backoff_max_s=float(_get(node, "backoff_max_s", 10.0)),
        quiesce_timeout_s=float(_get(node, "quiesce_timeout_s", 5.0)),
        start_method=str(_get(node, "start_method", "spawn")),
        faults=faults,
    )


def admit(slab_param_version: int, param_version: int, max_staleness: int) -> bool:
    """The staleness-bounded admission predicate (the tentpole's contract):
    a slab collected against params ``slab_param_version`` is trainable under
    current ``param_version`` iff the gap is within ``max_staleness`` updates.
    ``max_staleness=0`` admits only on-policy slabs; version -1 (an actor that
    never saw a publish) is never admissible."""
    if slab_param_version < 0:
        return False
    return (int(param_version) - int(slab_param_version)) <= int(max_staleness)
