"""Per-run telemetry sink: JSONL event stream, device poller, heartbeat.

One :class:`RunTelemetry` per process per run, created by
:func:`configure_telemetry` from ``cfg.metric.telemetry`` and torn down by
:func:`shutdown_telemetry` (both wired in ``cli.run_algorithm``).  Everything
funnels into an append-only ``telemetry.jsonl`` next to the run's logs —
process 0 owns ``telemetry.jsonl``, the others write ``telemetry.<i>.jsonl``.

Event schema (one JSON object per line, documented in howto/telemetry.md):
every event carries ``event`` (kind), ``t`` (unix seconds), ``step``
(policy step at emission), ``process_index`` and optionally ``name``; the
kinds are ``run_start``, ``span``, ``compile``, ``device_poll``,
``heartbeat``, ``bench_probe``, ``worker_restart``, ``masked_slot`` and
``run_end``.

The module-level accessor :func:`get_telemetry` returns ``None`` unless a run
configured telemetry — callers on hot paths pay one global read when the
subsystem is off.

Evidence-engine extensions (howto/evidence.md):

- **flight recorder** — a bounded ring of the last
  ``metric.telemetry.flightrec_events`` events, dumped to ``flightrec.json``
  by the crash-guard / NaN-rollback / preemption paths so every abnormal
  exit leaves a post-mortem artifact (newest event last).
- **rotation** — ``metric.telemetry.max_bytes`` caps the JSONL stream: on
  overflow the file rotates once to ``telemetry.jsonl.1`` (overwriting the
  previous rotation), bounding disk at ~2× the cap for soak/serve runs.
- **triggered profiler** — ``metric.telemetry.profile_windows`` /
  ``slow_window_factor`` drive :class:`~sheeprl_tpu.obs.profile.TriggeredProfiler`
  through :meth:`RunTelemetry.advance` and the span stream.
- **run rollup** — :meth:`RunTelemetry.run_summary` condenses the run into
  the registry record appended to ``RUNS.jsonl``
  (:mod:`sheeprl_tpu.obs.registry`).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Mapping, Optional

from sheeprl_tpu.obs.profile import TriggeredProfiler
from sheeprl_tpu.obs.recompile import CompileWatchdog

_FLUSH_EVERY_EVENTS = 64
_FLUSH_EVERY_SECONDS = 5.0
# bound on per-heartbeat-window env-step latency samples: at sane log
# intervals the window never fills; a runaway loop degrades to "first N"
_ENV_STEP_RESERVOIR = 8192
_FLIGHTREC_EVENTS = 256
_TRACE_PATH_RESERVOIR = 8192


def _pct(values: list, q: float) -> Optional[float]:
    """Nearest-rank percentile over an unsorted sample (None when empty)."""
    if not values:
        return None
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(round(q * (len(ordered) - 1))))
    return float(ordered[idx])

_active_telemetry: Optional["RunTelemetry"] = None


class TelemetryWriter:
    """Buffered, thread-safe JSONL appender.

    jax.monitoring listeners and the poller can fire from any thread; the
    lock keeps lines whole.  Events are buffered and flushed every
    ``_FLUSH_EVERY_EVENTS`` events or ``_FLUSH_EVERY_SECONDS`` seconds so the
    hot path never waits on the filesystem.

    ``max_bytes > 0`` enables size-capped rotation: when the current segment
    exceeds the cap it is renamed to ``<path>.1`` (overwriting any previous
    rotation) and a fresh segment starts, so a soak run's stream occupies at
    most ~2× the cap on disk."""

    def __init__(self, path: str, *, max_bytes: int = 0) -> None:
        self.path = path
        self.max_bytes = int(max_bytes or 0)
        self.rotations = 0
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._fh = open(path, "a", buffering=1)
        try:
            self._bytes = os.path.getsize(path)
        except OSError:
            self._bytes = 0
        self._lock = threading.Lock()
        self._buf: list = []
        self._last_flush = time.time()

    def write(self, event: Dict[str, Any]) -> None:
        line = json.dumps(event, default=str)
        with self._lock:
            self._buf.append(line)
            if len(self._buf) >= _FLUSH_EVERY_EVENTS or time.time() - self._last_flush > _FLUSH_EVERY_SECONDS:
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if self._buf:
            data = "\n".join(self._buf) + "\n"
            # rotate BEFORE a write that would cross the cap (not after): the
            # newest events — run_end, a crash's final flush — always land in
            # the CURRENT segment, never stranded at the tail of ``.1``
            if self.max_bytes > 0 and self._bytes > 0 and self._bytes + len(data) >= self.max_bytes:
                self._rotate_locked()
            self._fh.write(data)
            self._buf.clear()
            self._bytes += len(data)
        self._fh.flush()
        self._last_flush = time.time()

    def _rotate_locked(self) -> None:
        self._fh.close()
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass  # someone removed the segment under us: just start fresh
        self._fh = open(self.path, "a", buffering=1)
        self._bytes = 0
        self.rotations += 1

    def segments(self) -> List[str]:
        """Existing stream segments, oldest first (``.1`` before current)."""
        return [p for p in (self.path + ".1", self.path) if os.path.exists(p)]

    def close(self) -> None:
        # under the lock: a racing write() could rotate and swap _fh between
        # a bare flush() and the close, leaking the fresh segment's handle
        with self._lock:
            self._flush_locked()
            self._fh.close()


class RunTelemetry:
    """The per-run telemetry hub.

    Owns the JSONL writer, the :class:`CompileWatchdog`, the low-rate device
    poller, and the heartbeat assembly.  ``step`` is advanced by the training
    loops (:func:`telemetry_advance`) so asynchronous events (compiles,
    polls) are attributable to a policy step."""

    def __init__(
        self,
        jsonl_path: str,
        *,
        poll_interval: float = 30.0,
        poll_rtt: bool = False,
        max_bytes: int = 0,
        flightrec_events: int = _FLIGHTREC_EVENTS,
        profiler: Optional[TriggeredProfiler] = None,
    ) -> None:
        import jax

        self._jax = jax
        self.process_index = jax.process_index()
        self.step = 0
        self.poll_interval = float(poll_interval)
        self.poll_rtt = bool(poll_rtt)
        self.writer = TelemetryWriter(jsonl_path, max_bytes=max_bytes)
        self.watchdog = CompileWatchdog(self.emit)
        # flight recorder: bounded ring of the newest events, dumped to
        # flightrec.json on the abnormal-exit paths (newest event last)
        self._flightrec: Optional[deque] = (
            deque(maxlen=int(flightrec_events)) if int(flightrec_events or 0) > 0 else None
        )
        stem = "flightrec.json" if self.process_index == 0 else f"flightrec.{self.process_index}.json"
        self.flightrec_path = os.path.join(os.path.dirname(jsonl_path) or ".", stem)
        # triggered profiler (obs/profile.py): driven by advance()/emit_span
        self.profiler = profiler
        self.profile_captures: List[Dict[str, Any]] = []
        self._window_index = 0
        self._last_poll: Optional[float] = None
        self._hbm_peak_bytes = 0
        self._device_polls = 0
        self._flops_source: Optional[Callable[[], Optional[float]]] = None
        self._flops_per_train_step: Optional[float] = None
        self._flops_resolved = False
        # per-train-window dispatch accounting (fused-superstep observability):
        # "window_*" accumulates since the last heartbeat, "total_*" over the run
        self._window_train_windows = 0
        self._window_train_dispatches = 0
        self._window_train_gradient_steps = 0
        self._total_train_windows = 0
        self._total_train_dispatches = 0
        self._total_train_gradient_steps = 0
        # rollout-pool accounting (sheeprl_tpu.rollout): per-window env-step
        # latency/queue-wait reservoirs + run totals for restarts/masked slots
        self._env_step_durs: list = []
        self._env_queue_waits: list = []
        self._window_worker_restarts = 0
        self._total_worker_restarts = 0
        self._total_masked_slots = 0
        # why fused supersteps fell back to per-step dispatch (reason -> count)
        self._fused_fallbacks: Dict[str, int] = {}
        # actor-learner accounting (sheeprl_tpu.actor_learner): staleness-
        # bounded slab admission (histogram keyed by staleness-in-updates),
        # dropped-stale/torn counters, ring occupancy samples, per-actor
        # restart totals — heartbeat windows + run_end totals
        self._window_slabs_admitted = 0
        self._window_dropped_stale = 0
        self._window_staleness_hist: Dict[str, int] = {}
        self._window_ring_occupancy: list = []
        self._total_slabs_admitted = 0
        self._total_dropped_stale = 0
        self._total_torn_slabs = 0
        self._total_staleness_hist: Dict[str, int] = {}
        self._actor_restarts: Dict[str, int] = {}
        # resilience accounting (sheeprl_tpu.resilience): committed/skipped
        # checkpoint saves, NaN rollbacks, preemption requests, auto-resume
        # fallbacks — events at each occurrence + run_end totals
        self._total_ckpt_commits = 0
        self._total_ckpt_skipped = 0
        self._total_nan_rollbacks = 0
        self._total_preemptions = 0
        self._total_crash_checkpoints = 0
        self._total_resume_fallbacks = 0
        # policy-serving accounting (sheeprl_tpu.serve): the server's own
        # counters are cumulative, so the run_end totals keep the LAST
        # serve_stats snapshot; supervision/swap events are counted by kind
        self._serve_last_stats: Optional[Dict[str, Any]] = None
        self._serve_events: Dict[str, int] = {}
        # multi-host data plane (sheeprl_tpu.net): sparse transport events
        # (reconnect, checksum_reject, heartbeat_gap, torn_frame) are counted
        # by kind here; the dense per-frame/byte counters accumulate in
        # net.stats and are snapshotted into the run_end `net` section
        self._net_events: Dict[str, int] = {}
        # AOT executable cache (sheeprl_tpu.ops.aotcache): deserialized-load
        # hits vs compile fallbacks plus staged-store outcomes — one
        # `aot_cache` event per action + run_end totals
        self._aot_cache_hits = 0
        self._aot_cache_misses = 0
        self._aot_cache_stores = 0
        self._aot_cache_errors = 0
        # trace-plane critical-path reservoirs (sheeprl_tpu.obs.trace): per-
        # slab lag decomposition (collect -> ring-wait -> train, µs) and
        # per-request latency decomposition (queue-wait -> batch-assembly ->
        # compute, ms) — rolled up to p50/p95 in run_end/run_summary
        self._slab_lags: list = []
        self._req_paths: list = []
        self._req_hedged = 0
        self._req_rerouted = 0
        # telemetry files of CHILD processes (actor trace recorders): the
        # learner declares them so the registry record names the run's full
        # file set and the trace merger never has to glob
        self._child_files: list = []
        # run-registry rollup: cumulative heartbeat-window sums (run-average
        # SPS/duty cycle survive the per-window resets above) + the latest
        # aggregator scalars (final losses/returns for the run record)
        self._cum_env_steps = 0.0
        self._cum_env_time = 0.0
        self._cum_train_steps = 0.0
        self._cum_train_time = 0.0
        # overlapped collection: time spent *blocked* on the previous async
        # train dispatch (Time/train_wait_time) — the overlap win is the gap
        # between this and window_train_time. The flag records that the loop
        # *measures* wait at all: a fully-hidden run legitimately reports
        # zero wait, which is overlap_fraction == 1.0, not "no overlap data".
        self._cum_train_wait_time = 0.0
        self._saw_train_wait = False
        self._last_mfu: Optional[float] = None
        self._last_train_flops_per_sec: Optional[float] = None
        self._final_metrics: Dict[str, float] = {}

    # -- core event plumbing -------------------------------------------------

    def emit(self, event: str, name: Optional[str] = None, **fields: Any) -> None:
        record: Dict[str, Any] = {
            "event": event,
            "t": time.time(),
            "step": self.step,
            "process_index": self.process_index,
        }
        if name is not None:
            record["name"] = name
        record.update(fields)
        self.writer.write(record)
        ring = self._flightrec
        if ring is not None:
            ring.append(record)

    def emit_span(self, name: str, t_start: Optional[float], dur: float, attrs: Mapping[str, Any]) -> None:
        fields: Dict[str, Any] = {"t_start": t_start, "dur": dur}
        if attrs:
            fields["attrs"] = dict(attrs)
        self.emit("span", name=name, **fields)
        if self.profiler is not None:
            self.profiler.observe_span(name, dur)

    def trace_annotation(self, name: Optional[str]):
        if name is None:
            return None
        return self._jax.profiler.TraceAnnotation(name)

    # -- loop hooks ----------------------------------------------------------

    def advance(self, step: int) -> None:
        self.step = int(step)
        # every advance() is one loop update = one train window (1-based);
        # the triggered profiler keys its captures off this counter
        self._window_index += 1
        if self.profiler is not None:
            self.profiler.on_window(self._window_index)
        self.maybe_poll_devices()

    def mark_warm(self) -> None:
        self.watchdog.mark_warm()

    def set_flops_source(self, source: Callable[[], Optional[float]]) -> None:
        if not self._flops_resolved:
            self._flops_source = source

    def record_train_window(self, dispatches: int, gradient_steps: int) -> None:
        """One train window happened: the loop issued ``dispatches`` jitted
        calls (gathers + EMA refreshes + train/superstep calls) to run
        ``gradient_steps`` gradient steps.  The per-step path reports
        O(gradient_steps) dispatches, a fused superstep reports
        ceil(gradient_steps / K) — the O(K)→O(1) reduction the dispatch
        counters exist to make visible (``bench.py --dispatch-stats``)."""
        self._window_train_windows += 1
        self._window_train_dispatches += int(dispatches)
        self._window_train_gradient_steps += int(gradient_steps)
        self._total_train_windows += 1
        self._total_train_dispatches += int(dispatches)
        self._total_train_gradient_steps += int(gradient_steps)

    def record_env_step(self, dur_s: float, queue_wait_s: Optional[float] = None) -> None:
        """One pooled env step happened: ``dur_s`` wall seconds end to end,
        of which ``queue_wait_s`` were spent NOT stepping envs (dispatch +
        pipe wait beyond the slowest worker's busy time). Feeds the
        heartbeat's env_step_p50/p95 and queue_wait_p50/p95 fields."""
        if len(self._env_step_durs) < _ENV_STEP_RESERVOIR:
            self._env_step_durs.append(float(dur_s))
            if queue_wait_s is not None:
                self._env_queue_waits.append(float(queue_wait_s))

    def record_worker_restart(self, worker: int, reason: str, restarts: int, **fields: Any) -> None:
        """An env worker was restarted (crash or step timeout): one
        ``worker_restart`` event + heartbeat/run_end counters."""
        self._window_worker_restarts += 1
        self._total_worker_restarts += 1
        self.emit("worker_restart", worker=worker, reason=reason, restarts=restarts, **fields)
        self.writer.flush()

    def record_masked_slot(self, worker: int, slots: Any, reason: str, **fields: Any) -> None:
        """An env worker exhausted its restart budget and its slots were
        masked dead: one ``masked_slot`` event + run_end counter."""
        nslots = len(slots) if isinstance(slots, (list, tuple)) else 1
        self._total_masked_slots += nslots
        self.emit("masked_slot", worker=worker, slots=slots, reason=reason, **fields)
        self.writer.flush()

    def record_slab(self, *, staleness: int, occupancy: float, admitted: bool) -> None:
        """One trajectory slab reached the learner's admission check:
        ``staleness`` is ``param_version - slab.param_version`` in updates,
        ``occupancy`` the ring's committed-slot fraction at poll time.
        Per-slab events would be hot-path noise — this only feeds the
        heartbeat window aggregates and run_end totals."""
        key = str(int(staleness))
        self._window_staleness_hist[key] = self._window_staleness_hist.get(key, 0) + 1
        self._total_staleness_hist[key] = self._total_staleness_hist.get(key, 0) + 1
        self._window_ring_occupancy.append(float(occupancy))
        if admitted:
            self._window_slabs_admitted += 1
            self._total_slabs_admitted += 1
        else:
            self._window_dropped_stale += 1
            self._total_dropped_stale += 1

    def record_torn_slabs(self, count: int, source: str = "", **fields: Any) -> None:
        """``count`` torn writes were detected and reclaimed (reader checksum
        or supervisor restart sweep): one ``torn_slab`` event + run_end
        counter. Rare by construction — the event is worth its cost."""
        if count <= 0:
            return
        self._total_torn_slabs += int(count)
        self.emit("torn_slab", count=int(count), source=source, **fields)
        self.writer.flush()

    def record_actor_restart(self, actor: int, reason: str, restarts: int, **fields: Any) -> None:
        """A trajectory actor was restarted (crash, torn write, or heartbeat
        timeout): one ``actor_restart`` event, the per-actor total for
        heartbeats/run_end, and the shared worker_restarts counters (the
        regress gate's restart budget covers both worker kinds)."""
        self._actor_restarts[str(int(actor))] = int(restarts)
        self._window_worker_restarts += 1
        self._total_worker_restarts += 1
        self.emit("actor_restart", actor=int(actor), reason=reason, restarts=int(restarts), **fields)
        self.writer.flush()

    def record_fused_fallback(self, reason: str, detail: str = "", **fields: Any) -> None:
        """``algo.fused_gradient_steps`` was requested but this run dispatches
        per-step: one structured ``fused_fallback`` event + run_end counter,
        so ``bench.py --dispatch-stats`` can say *why* a run shows zero fused
        windows instead of silently reporting O(K) dispatches."""
        self._fused_fallbacks[reason] = self._fused_fallbacks.get(reason, 0) + 1
        self.emit("fused_fallback", reason=reason, detail=detail, **fields)
        self.writer.flush()

    def record_ckpt_commit(self, path: str, step: int, backend: str, emergency: bool = False, **fields: Any) -> None:
        """A checkpoint committed (manifest landed): one ``ckpt_committed``
        event + run_end counter. ``emergency=True`` marks the preemption
        drain's final save."""
        self._total_ckpt_commits += 1
        self.emit("ckpt_committed", path=path, ckpt_step=int(step), backend=backend, emergency=bool(emergency), **fields)
        self.writer.flush()

    def record_ckpt_skipped(self, path: str, step: int, **fields: Any) -> None:
        """An async save request arrived while one was still in flight and
        was dropped: one ``ckpt_skipped`` event + run_end counter. The next
        checkpoint interval retries with fresher state, so nothing is lost
        beyond that interval's granularity."""
        self._total_ckpt_skipped += 1
        self.emit("ckpt_skipped", path=path, ckpt_step=int(step), **fields)
        self.writer.flush()

    def record_nan_rollback(self, path: Optional[str], reason: str, remaining: int, **fields: Any) -> None:
        """The non-finite sentinel tripped and the run restored from the last
        committed checkpoint: one ``nan_rollback`` event + run_end counter +
        a flight-record dump (the trigger event is the newest in the ring)."""
        self._total_nan_rollbacks += 1
        self.emit("nan_rollback", path=path, reason=reason, remaining=int(remaining), **fields)
        self.writer.flush()
        self.dump_flight_record("nan_rollback")

    def record_preemption(self, signum: int, **fields: Any) -> None:
        """A preemption signal (SIGTERM/SIGINT) reached the train-loop
        boundary: one ``preempt`` event + run_end counter + a flight-record
        dump before the drain exits the process."""
        self._total_preemptions += 1
        self.emit("preempt", signum=int(signum), **fields)
        self.writer.flush()
        self.dump_flight_record("preempt")

    def record_crash_checkpoint(self, path: str, error: str, **fields: Any) -> None:
        """An unhandled train-loop exception drained the async writer and
        committed an emergency checkpoint before re-raising: one
        ``crash_checkpoint`` event + run_end counter + a flight-record dump."""
        self._total_crash_checkpoints += 1
        self.emit("crash_checkpoint", path=path, error=error, **fields)
        self.writer.flush()
        self.dump_flight_record("crash")

    def record_run_metrics(self, metrics: Mapping[str, Any]) -> None:
        """Keep the newest numeric aggregator scalars (losses, returns,
        episode lengths): the LAST values at run end become the registry
        record's ``final_metrics``. No event is emitted — the logger already
        carries the per-interval scalars."""
        for key, value in dict(metrics).items():
            try:
                num = float(value)
            except (TypeError, ValueError):
                continue
            if num == num:  # drop NaN — a poisoned final metric is useless
                self._final_metrics[str(key)] = num

    def dump_flight_record(self, trigger: str) -> Optional[str]:
        """Write the ring to ``flightrec.json`` (atomic tmp+rename; events
        oldest→newest, so the abnormal-exit trigger event is LAST). Each dump
        overwrites the previous — the newest post-mortem wins. Returns the
        path, or ``None`` when the ring is disabled or the write failed."""
        ring, path = self._flightrec, self.flightrec_path
        if ring is None or path is None:
            return None
        from sheeprl_tpu.obs.trace import active_trace_ids, clock_offset, current_role

        payload = {
            "schema": 1,
            "trigger": trigger,
            "t": time.time(),
            "step": self.step,
            "process_index": self.process_index,
            # process identity + active trace ids: a crash dump is an orphan
            # artifact until the merger can place it on one process's track
            # of the cross-process timeline (tools/trace.py)
            "role": current_role(),
            "pid": os.getpid(),
            "clock_offset": clock_offset(),
            "active_traces": active_trace_ids(),
            "ring_capacity": ring.maxlen,
            "events": list(ring),
        }
        try:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, default=str)
            os.replace(tmp, path)
        except Exception:
            return None
        return path

    def record_serve_stats(self, snapshot: Mapping[str, Any]) -> None:
        """One periodic serving-tier stats snapshot (QPS, queue depth, shed
        counts, p50/p95, replica/swap health): a ``serve_stats`` event; the
        last snapshot becomes the ``run_end`` serve totals."""
        snap = dict(snapshot)
        self._serve_last_stats = snap
        self.emit("serve_stats", **snap)
        self.writer.flush()

    def record_serve_event(self, kind: str, **fields: Any) -> None:
        """One serving supervision/swap event (``replica_restart``,
        ``replica_masked``, ``replica_hung``, ``swap``, ``swap_rejected``,
        ``rollback``): a ``serve_event`` line + run_end per-kind counters."""
        self._serve_events[kind] = self._serve_events.get(kind, 0) + 1
        self.emit("serve_event", kind=kind, **fields)
        self.writer.flush()

    def record_net_event(self, kind: str, **fields: Any) -> None:
        """One data-plane transport event (``reconnect``, ``checksum_reject``,
        ``heartbeat_gap``, ``torn_frame``, ``stale_slab``, ``disconnect``,
        ``transport_close``): a ``net_event`` line + run_end per-kind
        counters, mirroring the serve/rollout event pattern."""
        self._net_events[kind] = self._net_events.get(kind, 0) + 1
        self.emit("net_event", kind=kind, **fields)
        self.writer.flush()

    def _net_section(self) -> Dict[str, Any]:
        """The run_end/run_summary ``net`` section: per-kind sparse event
        counts plus every registered transport endpoint's frame/byte/reconnect
        counters (``bench.py --net-stats`` reads this path)."""
        section: Dict[str, Any] = {"events": dict(self._net_events)}
        try:
            from sheeprl_tpu.net.stats import net_stats_snapshot

            counters = net_stats_snapshot()
        except Exception:
            counters = {}
        if counters:
            section["transports"] = counters
        return section

    def _net_active(self) -> bool:
        if self._net_events:
            return True
        try:
            from sheeprl_tpu.net.stats import net_stats_snapshot

            return bool(net_stats_snapshot())
        except Exception:
            return False

    def record_aot_cache(self, action: str, tag: str = "", **fields: Any) -> None:
        """One executable-cache outcome (``hit`` / ``miss`` / ``store`` /
        ``store_failed`` / ``corrupt_gc`` / ``torn_gc`` / ``prewarm``): an
        ``aot_cache`` line + run_end totals. A ``hit`` means a cold path
        skipped its compile; ``miss`` and the error actions mean it fell back
        to the compile ladder (degraded, never failed)."""
        if action == "hit":
            self._aot_cache_hits += 1
        elif action == "miss":
            self._aot_cache_misses += 1
        elif action == "store":
            # "prewarm" is a rollup of the per-entry "store" events the
            # gauntlet's sync commits already emitted — not counted twice
            self._aot_cache_stores += 1
        elif action in ("store_failed", "corrupt_gc"):
            self._aot_cache_errors += 1
        self.emit("aot_cache", action=action, tag=tag, **fields)
        self.writer.flush()

    def _serve_section(self) -> Dict[str, Any]:
        """The run_end/run_summary ``serve`` section. Fleet runs (PR 12) get
        a dedicated ``fleet`` sub-section — router counters, scale events,
        per-replica rows — lifted out of the last stats snapshot so registry
        consumers (bench --serve-stats, regress) read it at a stable path."""
        section: Dict[str, Any] = {
            "stats": self._serve_last_stats or {},
            "events": dict(self._serve_events),
        }
        fleet = (self._serve_last_stats or {}).get("fleet")
        if fleet:
            section["fleet"] = fleet
        return section

    # -- trace-plane rollups -------------------------------------------------

    def record_child_file(self, path: str) -> None:
        """Declare a child process's telemetry/trace file (actor trace
        recorders): the path lands in ``run_summary()['telemetry_files']`` so
        the collector locates the run's full file set without globbing."""
        p = str(path)
        if p not in self._child_files:
            self._child_files.append(p)

    def record_slab_lag(self, *, collect_us: int, ring_wait_us: int, train_us: int) -> None:
        """One admitted slab's critical-path decomposition, in microseconds:
        actor collect wall time, commit→admission ring wait (epoch-aligned
        via the slab header's commit stamp), and the learner train window.
        Reservoir-sampled; rolled up as slab-age p50/p95 at run end."""
        if len(self._slab_lags) < _TRACE_PATH_RESERVOIR:
            self._slab_lags.append((int(collect_us), int(ring_wait_us), int(train_us)))

    def record_request_path(
        self,
        *,
        queue_wait_ms: float,
        assembly_ms: float,
        compute_ms: float,
        hedged: bool = False,
        rerouted: bool = False,
    ) -> None:
        """One completed request's critical-path decomposition, in
        milliseconds: enqueue→dispatch queue wait, batch assembly (staging),
        and compute. Hedged/re-routed requests are counted so the rollup can
        attribute fault/hedge overhead."""
        if len(self._req_paths) < _TRACE_PATH_RESERVOIR:
            self._req_paths.append((float(queue_wait_ms), float(assembly_ms), float(compute_ms)))
        if hedged:
            self._req_hedged += 1
        if rerouted:
            self._req_rerouted += 1

    def _slab_lag_section(self) -> Dict[str, Any]:
        rows = self._slab_lags
        if not rows:
            return {}
        ages = [(c + r + t) / 1e3 for c, r, t in rows]
        collect = [c / 1e3 for c, _, _ in rows]
        ring_wait = [r / 1e3 for _, r, _ in rows]
        train = [t / 1e3 for _, _, t in rows]
        return {
            "samples": len(rows),
            "age_p50_ms": _pct(ages, 0.50),
            "age_p95_ms": _pct(ages, 0.95),
            "collect_p50_ms": _pct(collect, 0.50),
            "collect_p95_ms": _pct(collect, 0.95),
            "ring_wait_p50_ms": _pct(ring_wait, 0.50),
            "ring_wait_p95_ms": _pct(ring_wait, 0.95),
            "train_p50_ms": _pct(train, 0.50),
            "train_p95_ms": _pct(train, 0.95),
        }

    def _request_path_section(self) -> Dict[str, Any]:
        rows = self._req_paths
        if not rows and not (self._req_hedged or self._req_rerouted):
            return {}
        totals = [q + a + c for q, a, c in rows]
        queue = [q for q, _, _ in rows]
        assembly = [a for _, a, _ in rows]
        compute = [c for _, _, c in rows]
        return {
            "samples": len(rows),
            "p50_ms": _pct(totals, 0.50),
            "p95_ms": _pct(totals, 0.95),
            "queue_wait_p50_ms": _pct(queue, 0.50),
            "queue_wait_p95_ms": _pct(queue, 0.95),
            "assembly_p50_ms": _pct(assembly, 0.50),
            "assembly_p95_ms": _pct(assembly, 0.95),
            "compute_p50_ms": _pct(compute, 0.50),
            "compute_p95_ms": _pct(compute, 0.95),
            "hedged": self._req_hedged,
            "rerouted": self._req_rerouted,
        }

    def record_resume_fallback(self, path: str, error: str, **fields: Any) -> None:
        """``resume_from=auto`` rejected a candidate checkpoint (load failure
        or mesh mismatch) and fell back to the next-newest: one
        ``resume_fallback`` event + run_end counter."""
        self._total_resume_fallbacks += 1
        self.emit("resume_fallback", path=path, error=error, **fields)
        self.writer.flush()

    def _resolve_flops(self) -> Optional[float]:
        if not self._flops_resolved and self._flops_source is not None:
            # the AOT cost-analysis compile is deliberate, not a retrace —
            # run it inside the watchdog's allowlist window
            with self.watchdog.deliberate("aot_cost_analysis"):
                try:
                    self._flops_per_train_step = self._flops_source()
                except Exception:
                    self._flops_per_train_step = None
            self._flops_source = None
            self._flops_resolved = True
        return self._flops_per_train_step

    # -- device poller -------------------------------------------------------

    def maybe_poll_devices(self, force: bool = False) -> None:
        now = time.time()
        if not force and self._last_poll is not None and now - self._last_poll < self.poll_interval:
            return
        self._last_poll = now
        devices = []
        for dev in self._jax.local_devices():
            entry: Dict[str, Any] = {
                "id": dev.id,
                "kind": getattr(dev, "device_kind", "unknown"),
                "platform": getattr(dev, "platform", "unknown"),
            }
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if stats:
                in_use = stats.get("bytes_in_use")
                peak = stats.get("peak_bytes_in_use", in_use)
                if in_use is not None:
                    entry["bytes_in_use"] = int(in_use)
                if peak is not None:
                    entry["peak_bytes_in_use"] = int(peak)
                    self._hbm_peak_bytes = max(self._hbm_peak_bytes, int(peak))
            devices.append(entry)
        fields: Dict[str, Any] = {"devices": devices}
        if self.poll_rtt and self._jax.default_backend() != "cpu":
            # Link-health probe for remote-attached chips. It is a real sync
            # point, so it is opt-in (metric.telemetry.poll_rtt) and rides the
            # same low-rate schedule as the memory poll.
            try:
                from sheeprl_tpu.utils.profiler import tiny_op_rtt_seconds

                fields["rtt_ms"] = tiny_op_rtt_seconds() * 1e3
            except Exception:
                pass
        self._device_polls += 1
        self.emit("device_poll", **fields)

    def device_kind(self) -> str:
        devs = self._jax.local_devices()
        return getattr(devs[0], "device_kind", "unknown") if devs else "unknown"

    # -- heartbeat -----------------------------------------------------------

    def heartbeat(
        self,
        logger,
        *,
        step: int,
        env_steps: float,
        train_steps: float,
        train_invocations: Optional[float],
        timer_window: Mapping[str, float],
    ) -> None:
        """Assemble the per-log-interval health summary: SPS, train/rollout
        duty cycle, MFU (via the registered ``compiled_flops`` source), HBM
        peak, recompile count — one JSONL event + ``Telemetry/*`` scalars."""
        env_t = float(timer_window.get("Time/env_interaction_time") or 0.0)
        train_t = float(timer_window.get("Time/train_time") or 0.0)
        train_wait_t = float(timer_window.get("Time/train_wait_time") or 0.0)
        # run-registry rollup: the window sums reset every heartbeat, these
        # cumulative mirrors survive to run_summary()
        self._cum_env_steps += float(env_steps or 0.0)
        self._cum_env_time += env_t
        self._cum_train_steps += float(train_steps or 0.0)
        self._cum_train_time += train_t
        self._cum_train_wait_time += train_wait_t
        fields: Dict[str, Any] = {
            "window_env_steps": env_steps,
            "window_train_steps": train_steps,
            "window_env_time": env_t,
            "window_train_time": train_t,
            "device_kind": self.device_kind(),
            "hbm_peak_bytes": self._hbm_peak_bytes,
            "recompiles": self.watchdog.recompiles,
            "compiles_total": self.watchdog.compiles,
        }
        scalars: Dict[str, float] = {"Counters/recompiles": float(self.watchdog.recompiles)}
        if self._window_train_windows:
            fields["window_train_windows"] = self._window_train_windows
            fields["window_train_dispatches"] = self._window_train_dispatches
            fields["window_train_gradient_steps"] = self._window_train_gradient_steps
            scalars["Telemetry/train_dispatches_per_window"] = (
                self._window_train_dispatches / self._window_train_windows
            )
            self._window_train_windows = 0
            self._window_train_dispatches = 0
            self._window_train_gradient_steps = 0
        if self._env_step_durs:
            import numpy as _np

            durs = _np.asarray(self._env_step_durs)
            fields["env_step_p50_ms"] = float(_np.percentile(durs, 50)) * 1e3
            fields["env_step_p95_ms"] = float(_np.percentile(durs, 95)) * 1e3
            fields["env_step_samples"] = int(durs.size)
            scalars["Telemetry/env_step_p95_ms"] = fields["env_step_p95_ms"]
            if self._env_queue_waits:
                waits = _np.asarray(self._env_queue_waits)
                fields["env_queue_wait_p50_ms"] = float(_np.percentile(waits, 50)) * 1e3
                fields["env_queue_wait_p95_ms"] = float(_np.percentile(waits, 95)) * 1e3
            self._env_step_durs = []
            self._env_queue_waits = []
        if self._window_worker_restarts:
            fields["window_worker_restarts"] = self._window_worker_restarts
            self._window_worker_restarts = 0
        if self._total_worker_restarts:
            fields["worker_restarts_total"] = self._total_worker_restarts
            scalars["Counters/worker_restarts"] = float(self._total_worker_restarts)
        if self._total_masked_slots:
            fields["masked_slots_total"] = self._total_masked_slots
            scalars["Counters/masked_slots"] = float(self._total_masked_slots)
        # actor-learner window: slab admission/staleness/ring health — only
        # present when the disaggregated topology actually moved slabs
        if self._window_staleness_hist or self._window_ring_occupancy:
            fields["window_slabs_admitted"] = self._window_slabs_admitted
            fields["window_dropped_stale_slabs"] = self._window_dropped_stale
            fields["window_staleness_hist"] = dict(self._window_staleness_hist)
            if self._window_ring_occupancy:
                occ = sum(self._window_ring_occupancy) / len(self._window_ring_occupancy)
                fields["ring_occupancy"] = occ
                scalars["Telemetry/ring_occupancy"] = occ
            if train_t + train_wait_t > 0:
                # the learner's duty cycle: fraction of its loop spent
                # training vs starved waiting for an admissible slab
                fields["learner_duty_cycle"] = train_t / (train_t + train_wait_t)
                scalars["Telemetry/learner_duty_cycle"] = fields["learner_duty_cycle"]
            self._window_slabs_admitted = 0
            self._window_dropped_stale = 0
            self._window_staleness_hist = {}
            self._window_ring_occupancy = []
        if self._total_dropped_stale:
            fields["dropped_stale_slabs_total"] = self._total_dropped_stale
            scalars["Counters/dropped_stale_slabs"] = float(self._total_dropped_stale)
        if self._total_torn_slabs:
            fields["torn_slabs_total"] = self._total_torn_slabs
            scalars["Counters/torn_slabs"] = float(self._total_torn_slabs)
        if self._actor_restarts:
            fields["actor_restarts"] = dict(self._actor_restarts)
        # checkpoint duty-cycle: only the snapshot span blocks the train loop
        # (the write happens on the background thread), so the heartbeat
        # reports them separately
        ckpt_snap_t = float(timer_window.get("ckpt/snapshot") or 0.0)
        ckpt_write_t = float(timer_window.get("ckpt/write") or 0.0)
        if ckpt_snap_t > 0:
            fields["window_ckpt_snapshot_time"] = ckpt_snap_t
            scalars["Telemetry/ckpt_snapshot_time"] = ckpt_snap_t
        if ckpt_write_t > 0:
            fields["window_ckpt_write_time"] = ckpt_write_t
        if self._total_ckpt_commits:
            fields["ckpt_commits_total"] = self._total_ckpt_commits
            scalars["Counters/ckpt_commits"] = float(self._total_ckpt_commits)
        if self._total_ckpt_skipped:
            fields["ckpt_skipped_total"] = self._total_ckpt_skipped
            scalars["Counters/ckpt_skipped"] = float(self._total_ckpt_skipped)
        if self._total_nan_rollbacks:
            fields["nan_rollbacks_total"] = self._total_nan_rollbacks
            scalars["Counters/nan_rollbacks"] = float(self._total_nan_rollbacks)
        if env_t > 0:
            fields["sps_env"] = env_steps / env_t
        if train_t > 0:
            fields["sps_train"] = train_steps / train_t
        if env_t + train_t > 0:
            fields["duty_cycle_train"] = train_t / (env_t + train_t)
            scalars["Telemetry/duty_cycle_train"] = fields["duty_cycle_train"]
        if "Time/train_wait_time" in timer_window:
            # overlapped collection: train_time is the (non-blocking) dispatch
            # span, train_wait_time the later block on its results — the env
            # loop ran in between, so the hidden fraction of the update cycle
            # is env / (env + wait).  1.0 = train fully hidden.
            self._saw_train_wait = True
            fields["window_train_wait_time"] = train_wait_t
            scalars["Telemetry/train_wait_time"] = train_wait_t
            if env_t + train_wait_t > 0:
                fields["overlap_fraction"] = env_t / (env_t + train_wait_t)
                scalars["Telemetry/overlap_fraction"] = fields["overlap_fraction"]
        if self._hbm_peak_bytes:
            scalars["Telemetry/hbm_peak_bytes"] = float(self._hbm_peak_bytes)
        flops = self._resolve_flops()
        if flops is not None:
            fields["flops_per_train_step"] = flops
            if train_invocations is not None:
                fields["window_train_invocations"] = train_invocations
                if train_t > 0 and train_invocations > 0:
                    fps = flops * train_invocations / train_t
                    fields["train_flops_per_sec"] = fps
                    scalars["Telemetry/train_flops_per_sec"] = fps
                    self._last_train_flops_per_sec = fps
                    from sheeprl_tpu.utils.profiler import PEAK_BF16_FLOPS

                    peak = PEAK_BF16_FLOPS.get(fields["device_kind"])
                    if peak:
                        fields["mfu"] = fps / peak
                        scalars["Telemetry/mfu"] = fields["mfu"]
                        self._last_mfu = fields["mfu"]
        self.emit("heartbeat", **fields)
        self.writer.flush()
        if logger is not None:
            try:
                logger.log_metrics(scalars, step)
            except Exception:
                pass

    # -- run-registry rollup -------------------------------------------------

    def run_summary(self) -> Dict[str, Any]:
        """Condense the run for the registry record (``RUNS.jsonl``): run-wide
        SPS/duty cycle from the cumulative heartbeat sums, the latest MFU,
        HBM peak, compile/recompile/dispatch/fallback and resilience totals,
        rollout restart/mask totals, the last serve snapshot, profile
        captures and the telemetry segments on disk."""
        summary: Dict[str, Any] = {
            "backend": self._jax.default_backend(),
            "device_kind": self.device_kind(),
            "local_device_count": self._jax.local_device_count(),
            "process_count": self._jax.process_count(),
            "hbm_peak_bytes": self._hbm_peak_bytes,
            "compiles_total": self.watchdog.compiles,
            "recompiles": self.watchdog.recompiles,
            "deliberate_compiles": dict(self.watchdog.deliberate_compiles),
            "train_windows": self._total_train_windows,
            "train_dispatches": self._total_train_dispatches,
            "train_gradient_steps": self._total_train_gradient_steps,
            "fused_fallbacks": dict(self._fused_fallbacks),
            "worker_restarts": self._total_worker_restarts,
            "masked_slots": self._total_masked_slots,
            "ckpt_commits": self._total_ckpt_commits,
            "ckpt_skipped": self._total_ckpt_skipped,
            "nan_rollbacks": self._total_nan_rollbacks,
            "preemptions": self._total_preemptions,
            "crash_checkpoints": self._total_crash_checkpoints,
            "resume_fallbacks": self._total_resume_fallbacks,
            "aot_cache_hits": self._aot_cache_hits,
            "aot_cache_misses": self._aot_cache_misses,
            "aot_cache_stores": self._aot_cache_stores,
            "aot_cache_errors": self._aot_cache_errors,
        }
        if self._cum_env_time > 0:
            summary["sps_env"] = self._cum_env_steps / self._cum_env_time
        if self._cum_train_time > 0:
            summary["sps_train"] = self._cum_train_steps / self._cum_train_time
        if self._cum_env_time + self._cum_train_time > 0:
            summary["duty_cycle_train"] = self._cum_train_time / (self._cum_env_time + self._cum_train_time)
        # env steps over the whole timed loop (collect + train + any train
        # wait): the number fused/overlap runs actually move, and the regress
        # gate cell for them
        loop_t = self._cum_env_time + self._cum_train_time + self._cum_train_wait_time
        if loop_t > 0 and self._cum_env_steps > 0:
            summary["sps_end_to_end"] = self._cum_env_steps / loop_t
        if self._saw_train_wait:
            summary["train_wait_time"] = self._cum_train_wait_time
            if self._cum_env_time + self._cum_train_wait_time > 0:
                summary["overlap_fraction"] = self._cum_env_time / (
                    self._cum_env_time + self._cum_train_wait_time
                )
        if self._total_slabs_admitted or self._total_dropped_stale or self._total_torn_slabs:
            summary["slabs_admitted"] = self._total_slabs_admitted
            summary["dropped_stale_slabs"] = self._total_dropped_stale
            summary["torn_slabs"] = self._total_torn_slabs
            summary["staleness_hist"] = dict(self._total_staleness_hist)
            if self._cum_train_time + self._cum_train_wait_time > 0:
                summary["learner_duty_cycle"] = self._cum_train_time / (
                    self._cum_train_time + self._cum_train_wait_time
                )
        if self._actor_restarts:
            summary["actor_restarts"] = dict(self._actor_restarts)
        if self._flops_per_train_step is not None:
            summary["flops_per_train_step"] = self._flops_per_train_step
        if self._last_train_flops_per_sec is not None:
            summary["train_flops_per_sec"] = self._last_train_flops_per_sec
        if self._last_mfu is not None:
            summary["mfu"] = self._last_mfu
        if self._serve_last_stats is not None or self._serve_events:
            summary["serve"] = self._serve_section()
        if self._net_active():
            summary["net"] = self._net_section()
        captures = self.profile_captures or (self.profiler.captures if self.profiler is not None else [])
        if captures:
            summary["profile_captures"] = [dict(c) for c in captures]
        if self._final_metrics:
            summary["final_metrics"] = dict(self._final_metrics)
        slab_lag = self._slab_lag_section()
        if slab_lag:
            summary["slab_lag"] = slab_lag
        req_path = self._request_path_section()
        if req_path:
            summary["request_critical_path"] = req_path
        summary["telemetry_jsonl"] = self.writer.path
        summary["telemetry_segments"] = [os.path.basename(p) for p in self.writer.segments()]
        # the run's FULL per-process file set (this process's segments,
        # oldest first, plus declared child trace files) — the trace
        # collector reads this instead of globbing the log dir
        summary["telemetry_files"] = list(self.writer.segments()) + list(self._child_files)
        return summary

    # -- lifecycle -----------------------------------------------------------

    def start(self, run_info: Optional[Mapping[str, Any]] = None) -> None:
        self.watchdog.start()
        self.emit("run_start", **dict(run_info or {}))
        # trace handshake at spawn: role/pid + the monotonic→epoch clock
        # offset the cross-process merger (tools/trace.py) aligns this
        # stream's t_mono stamps with
        from sheeprl_tpu.obs.trace import clock_offset, current_role

        self.emit(
            "trace_handshake",
            role=current_role(),
            pid=os.getpid(),
            clock_offset=clock_offset(),
            t_mono=time.monotonic(),
        )
        self.maybe_poll_devices(force=True)

    def close(self) -> None:
        if self.profiler is not None:
            # stop a capture straddling run end so the trace file is complete
            # BEFORE run_end reports it
            self.profile_captures = self.profiler.finish()
        extra_fields: Dict[str, Any] = {}
        # only serving runs grow a `serve` section: training-run run_end
        # consumers keep seeing exactly the fields they already parse
        if self._serve_last_stats is not None or self._serve_events:
            extra_fields["serve"] = self._serve_section()
        # likewise the `net` section: only runs that touched a transport
        if self._net_active():
            extra_fields["net"] = self._net_section()
        # same for the trace-plane critical-path rollups: only runs that
        # recorded slab/request decompositions carry them
        slab_lag = self._slab_lag_section()
        if slab_lag:
            extra_fields["slab_lag"] = slab_lag
        req_path = self._request_path_section()
        if req_path:
            extra_fields["request_critical_path"] = req_path
        self.emit(
            "run_end",
            **extra_fields,
            compiles_total=self.watchdog.compiles,
            recompiles=self.watchdog.recompiles,
            device_polls=self._device_polls,
            hbm_peak_bytes=self._hbm_peak_bytes,
            train_windows=self._total_train_windows,
            train_dispatches=self._total_train_dispatches,
            train_gradient_steps=self._total_train_gradient_steps,
            compile_cache_hits=self.watchdog.cache_hits,
            compile_cache_misses=self.watchdog.cache_misses,
            worker_restarts=self._total_worker_restarts,
            masked_slots=self._total_masked_slots,
            fused_fallbacks=dict(self._fused_fallbacks),
            slabs_admitted=self._total_slabs_admitted,
            dropped_stale_slabs=self._total_dropped_stale,
            torn_slabs=self._total_torn_slabs,
            staleness_hist=dict(self._total_staleness_hist),
            actor_restarts=dict(self._actor_restarts),
            ckpt_commits=self._total_ckpt_commits,
            ckpt_skipped=self._total_ckpt_skipped,
            nan_rollbacks=self._total_nan_rollbacks,
            preemptions=self._total_preemptions,
            crash_checkpoints=self._total_crash_checkpoints,
            resume_fallbacks=self._total_resume_fallbacks,
            aot_cache_hits=self._aot_cache_hits,
            aot_cache_misses=self._aot_cache_misses,
            aot_cache_stores=self._aot_cache_stores,
            aot_cache_errors=self._aot_cache_errors,
            aot_loads=dict(self.watchdog.aot_loads),
            deliberate_compiles=dict(self.watchdog.deliberate_compiles),
            profile_captures=[dict(c) for c in self.profile_captures],
            telemetry_rotations=self.writer.rotations,
            telemetry_segments=[os.path.basename(p) for p in self.writer.segments()],
        )
        self.watchdog.stop()
        self.writer.close()


# -- module-level accessors (cheap no-ops when telemetry is off) -------------


def get_telemetry() -> Optional[RunTelemetry]:
    return _active_telemetry


def configure_telemetry(cfg: Mapping[str, Any], log_dir: Optional[str] = None) -> Optional[RunTelemetry]:
    """Build the process-wide :class:`RunTelemetry` from
    ``cfg.metric.telemetry`` (``{enabled, jsonl, poll_interval, poll_rtt,
    max_bytes, flightrec_events, profile_windows, slow_window_factor,
    slow_window_min_history}``).  Returns ``None`` (and leaves the subsystem
    inert) unless enabled."""
    global _active_telemetry
    tel_cfg = ((cfg.get("metric") or {}).get("telemetry")) or {}
    if not bool(tel_cfg.get("enabled", False)):
        return None
    if _active_telemetry is not None:
        shutdown_telemetry()
    import jax

    path = tel_cfg.get("jsonl") or os.path.join(log_dir or ".", "telemetry.jsonl")
    proc = jax.process_index()
    if proc != 0:
        root, ext = os.path.splitext(path)
        path = f"{root}.{proc}{ext or '.jsonl'}"
    profiler: Optional[TriggeredProfiler] = None
    windows = tel_cfg.get("profile_windows") or []
    slow_factor = float(tel_cfg.get("slow_window_factor", 0.0) or 0.0)
    if proc == 0 and (windows or slow_factor > 0.0):
        # process-0 only, like the whole-run profiler: one Perfetto writer
        # per host is plenty and the traces already carry every local device
        profiler = TriggeredProfiler(
            os.path.join(os.path.dirname(path) or ".", "profile_triggered"),
            windows=[int(w) for w in windows],
            slow_factor=slow_factor,
            slow_min_history=int(tel_cfg.get("slow_window_min_history", 8) or 8),
        )
    tel = RunTelemetry(
        path,
        poll_interval=float(tel_cfg.get("poll_interval", 30.0) or 0.0),
        poll_rtt=bool(tel_cfg.get("poll_rtt", False)),
        max_bytes=int(tel_cfg.get("max_bytes", 0) or 0),
        flightrec_events=int(tel_cfg.get("flightrec_events", _FLIGHTREC_EVENTS) or 0),
        profiler=profiler,
    )
    tel.start(
        run_info={
            "backend": jax.default_backend(),
            "local_device_count": jax.local_device_count(),
            "process_count": jax.process_count(),
        }
    )
    _active_telemetry = tel
    return tel


def shutdown_telemetry() -> None:
    global _active_telemetry
    tel = _active_telemetry
    _active_telemetry = None
    if tel is not None:
        try:
            tel.close()
        except Exception:
            pass


def telemetry_advance(step: int) -> None:
    tel = _active_telemetry
    if tel is not None:
        tel.advance(step)


def telemetry_mark_warm() -> None:
    tel = _active_telemetry
    if tel is not None:
        tel.mark_warm()


@contextmanager
def telemetry_deliberate_compiles(reason: str):
    """Allowlist window for deliberate compiles (serve batch-ladder AOT,
    hot-swap revalidation, AOT cost analysis): inside the context, compiles
    on this thread never count as post-warmup recompiles (see
    :meth:`CompileWatchdog.deliberate`). Yields even when telemetry is off."""
    tel = _active_telemetry
    if tel is None:
        yield
    else:
        with tel.watchdog.deliberate(reason):
            yield


def telemetry_aot_cache(action: str, tag: str = "", **fields: Any) -> None:
    """Record an executable-cache outcome (see
    :meth:`RunTelemetry.record_aot_cache`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_aot_cache(action, tag, **fields)


@contextmanager
def telemetry_aot_load(tag: str):
    """Executable-cache deserialization window: compile-monitoring events on
    this thread are classified as ``aot_load`` — neither recompiles nor
    ``deliberate:`` compiles (see :meth:`CompileWatchdog.aot_load`). Yields
    even when telemetry is off."""
    tel = _active_telemetry
    if tel is None:
        yield
    else:
        with tel.watchdog.aot_load(tag):
            yield


def telemetry_run_metrics(metrics: Mapping[str, Any]) -> None:
    """Capture the latest aggregator scalars for the run-registry record
    (see :meth:`RunTelemetry.record_run_metrics`); no-op when telemetry is
    off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_run_metrics(metrics)


def telemetry_dump_flight_record(trigger: str) -> Optional[str]:
    """Dump the flight-recorder ring now (see
    :meth:`RunTelemetry.dump_flight_record`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        return tel.dump_flight_record(trigger)
    return None


def telemetry_train_window(dispatches: int, gradient_steps: int) -> None:
    """Record one train window's dispatch count (see
    :meth:`RunTelemetry.record_train_window`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_train_window(dispatches, gradient_steps)


def telemetry_env_step(dur_s: float, queue_wait_s: Optional[float] = None) -> None:
    """Record one pooled env step's latency (see
    :meth:`RunTelemetry.record_env_step`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_env_step(dur_s, queue_wait_s)


def telemetry_worker_restart(worker: int, reason: str, restarts: int, **fields: Any) -> None:
    """Record an env-worker restart (see
    :meth:`RunTelemetry.record_worker_restart`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_worker_restart(worker, reason, restarts, **fields)


def telemetry_slab(*, staleness: int, occupancy: float, admitted: bool) -> None:
    """Record one ring-slab admission decision (see
    :meth:`RunTelemetry.record_slab`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_slab(staleness=staleness, occupancy=occupancy, admitted=admitted)


def telemetry_torn_slabs(count: int, source: str = "", **fields: Any) -> None:
    """Record detected/reclaimed torn slabs (see
    :meth:`RunTelemetry.record_torn_slabs`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_torn_slabs(count, source, **fields)


def telemetry_actor_restart(actor: int, reason: str, restarts: int, **fields: Any) -> None:
    """Record an actor-process restart (see
    :meth:`RunTelemetry.record_actor_restart`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_actor_restart(actor, reason, restarts, **fields)


def telemetry_fused_fallback(reason: str, detail: str = "", **fields: Any) -> None:
    """Record a fused-superstep fallback on the active telemetry (see
    :meth:`RunTelemetry.record_fused_fallback`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_fused_fallback(reason, detail, **fields)


def telemetry_masked_slot(worker: int, slots: Any, reason: str, **fields: Any) -> None:
    """Record env slots masked dead (see
    :meth:`RunTelemetry.record_masked_slot`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_masked_slot(worker, slots, reason, **fields)


def telemetry_ckpt_commit(path: str, step: int, backend: str, emergency: bool = False, **fields: Any) -> None:
    """Record a committed checkpoint (see
    :meth:`RunTelemetry.record_ckpt_commit`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_ckpt_commit(path, step, backend, emergency, **fields)


def telemetry_ckpt_skipped(path: str, step: int, **fields: Any) -> None:
    """Record a dropped async save request (see
    :meth:`RunTelemetry.record_ckpt_skipped`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_ckpt_skipped(path, step, **fields)


def telemetry_nan_rollback(path: Optional[str], reason: str, remaining: int, **fields: Any) -> None:
    """Record a non-finite rollback (see
    :meth:`RunTelemetry.record_nan_rollback`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_nan_rollback(path, reason, remaining, **fields)


def telemetry_preemption(signum: int, **fields: Any) -> None:
    """Record a preemption request (see
    :meth:`RunTelemetry.record_preemption`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_preemption(signum, **fields)


def telemetry_crash_checkpoint(path: str, error: str, **fields: Any) -> None:
    """Record a crash-guard emergency save (see
    :meth:`RunTelemetry.record_crash_checkpoint`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_crash_checkpoint(path, error, **fields)


def telemetry_resume_fallback(path: str, error: str, **fields: Any) -> None:
    """Record an auto-resume candidate rejection (see
    :meth:`RunTelemetry.record_resume_fallback`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_resume_fallback(path, error, **fields)


def telemetry_serve_stats(snapshot: Mapping[str, Any]) -> None:
    """Record a serving-tier stats snapshot (see
    :meth:`RunTelemetry.record_serve_stats`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_serve_stats(snapshot)


def telemetry_serve_event(kind: str, **fields: Any) -> None:
    """Record a serving supervision/swap event (see
    :meth:`RunTelemetry.record_serve_event`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_serve_event(kind, **fields)


def telemetry_net_event(kind: str, **fields: Any) -> None:
    """Record a data-plane transport event (see
    :meth:`RunTelemetry.record_net_event`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_net_event(kind, **fields)


def telemetry_child_file(path: str) -> None:
    """Declare a child process's telemetry/trace file for the registry
    record (see :meth:`RunTelemetry.record_child_file`); no-op when
    telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_child_file(path)


def telemetry_slab_lag(*, collect_us: int, ring_wait_us: int, train_us: int) -> None:
    """Record one admitted slab's critical-path decomposition (see
    :meth:`RunTelemetry.record_slab_lag`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_slab_lag(collect_us=collect_us, ring_wait_us=ring_wait_us, train_us=train_us)


def telemetry_request_path(
    *,
    queue_wait_ms: float,
    assembly_ms: float,
    compute_ms: float,
    hedged: bool = False,
    rerouted: bool = False,
) -> None:
    """Record one completed request's critical-path decomposition (see
    :meth:`RunTelemetry.record_request_path`); no-op when telemetry is off."""
    tel = _active_telemetry
    if tel is not None:
        tel.record_request_path(
            queue_wait_ms=queue_wait_ms,
            assembly_ms=assembly_ms,
            compute_ms=compute_ms,
            hedged=hedged,
            rerouted=rerouted,
        )


def telemetry_register_flops(jitted_fn: Any, *args: Any, scale: float = 1.0) -> None:
    """Register a lazy ``compiled_flops`` source for MFU: shapes are captured
    eagerly (so no device buffers are pinned), the AOT cost analysis runs at
    most once, at the first heartbeat that needs it.  ``scale`` converts the
    analyzed program's cost to per-train-step flops — a fused superstep over K
    gradient steps registers ``scale=1/K`` so the heartbeat's MFU arithmetic
    (flops × gradient-step invocations / train time) stays consistent across
    fused and per-step paths."""
    tel = _active_telemetry
    if tel is None:
        return
    import jax

    def as_shape(x: Any) -> Any:
        return jax.ShapeDtypeStruct(x.shape, x.dtype) if hasattr(x, "shape") and hasattr(x, "dtype") else x

    shapes = jax.tree.map(as_shape, args)

    def source() -> Optional[float]:
        from sheeprl_tpu.utils.profiler import compiled_flops

        flops = compiled_flops(jitted_fn, *shapes)
        return flops * float(scale) if flops else flops

    tel.set_flops_source(source)
