"""Recompile watchdog: turn silent retracing into loud, counted events.

``jax.monitoring`` fires duration events for every trace/lower/compile.  The
robust "a new computation variant exists" signal is
``/jax/core/compile/jaxpr_to_mlir_module_duration``: it fires exactly once per
traced-and-lowered variant even when the persistent compilation cache
satisfies the backend compile (``backend_compile_duration`` can be skipped or
be near-zero on cache hits, so it is emitted as a secondary ``phase`` only).

jax.monitoring passes no function names, so while the watchdog is active the
``jax._src.interpreters.pxla`` logger is lowered to DEBUG and a capture
handler parses the "Compiling <name> with global shapes and types" line that
immediately precedes lowering; the original level is restored on ``stop()``.

After :meth:`mark_warm` (called from the bench steady-state probe, or
explicitly by loops without one), every further lowering is a *recompile*:
it increments the ``Counters/recompiles`` counter, is tagged
``post_warm=true`` in the JSONL stream, and raises a ``RecompileWarning`` —
silent retracing is the #1 TPU perf killer.
"""

from __future__ import annotations

import logging
import threading
import warnings
from contextlib import contextmanager
from typing import Any, Dict, Optional

_LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_BACKEND_EVENT = "/jax/core/compile/backend_compile_duration"
# persistent-compilation-cache outcomes (plain events, no duration): one per
# backend-compile request when ``jax_compilation_cache_dir`` is configured
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_PXLA_LOGGER = "jax._src.interpreters.pxla"


class RecompileWarning(UserWarning):
    """A jitted function was re-traced/re-lowered after the warmup point."""


class _NameCaptureHandler(logging.Handler):
    """Grabs the function name from pxla's 'Compiling <name> with global
    shapes and types ...' DEBUG line, emitted just before lowering."""

    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.last_name: Optional[str] = None

    def emit(self, record: logging.LogRecord) -> None:
        try:
            msg = record.getMessage()
        except Exception:
            return
        if msg.startswith("Compiling "):
            self.last_name = msg[len("Compiling ") :].split(" ", 1)[0]


class CompileWatchdog:
    """Subscriber for jax.monitoring compile-duration events.

    Lifecycle is owned by :class:`~sheeprl_tpu.obs.telemetry.RunTelemetry`:
    ``start()`` on configure, ``mark_warm()`` at the steady-state point,
    ``stop()`` on shutdown (unregisters the listener and restores the pxla
    logger).  ``emit`` is the telemetry event sink.
    """

    def __init__(self, emit) -> None:
        self._emit = emit
        self.compiles = 0
        self.recompiles = 0
        self.cache_hits = 0
        self.cache_misses = 0
        # deliberate (allowlisted) post-warmup compiles by reason — AOT cost
        # analysis, the serve batch ladder, hot-swap revalidation
        self.deliberate_compiles: Dict[str, int] = {}
        # executable-cache loads by tag: work XLA does while deserializing a
        # cached executable (ops/aotcache) is neither a compile nor a
        # recompile — a third category, counted separately
        self.aot_loads: Dict[str, int] = {}
        self.warm = False
        # compiles fire on the compiling thread (serve AOT on the server's
        # caller, revalidation on watcher threads), so the allowlist flag
        # must be thread-local: one thread's deliberate window must not
        # silence a real retrace racing on another thread
        self._deliberate = threading.local()
        # same thread-locality argument for aot-load windows: the fleet
        # deserializes per-replica ladders concurrently with live traffic
        self._aot_load = threading.local()
        self._started = False
        self._handler = _NameCaptureHandler()
        self._logger = logging.getLogger(_PXLA_LOGGER)
        self._saved_level: Optional[int] = None
        self._saved_propagate: Optional[bool] = None

    def start(self) -> None:
        if self._started:
            return
        import jax

        self._saved_level = self._logger.level
        self._logger.addHandler(self._handler)
        if self._logger.getEffectiveLevel() > logging.DEBUG:
            self._logger.setLevel(logging.DEBUG)
            # the DEBUG records exist only for the capture handler — don't
            # spray them through the root handler for the watchdog's lifetime
            self._saved_propagate = self._logger.propagate
            self._logger.propagate = False
        jax.monitoring.register_event_duration_secs_listener(self._on_event)
        jax.monitoring.register_event_listener(self._on_plain_event)
        self._started = True

    def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        try:
            from jax._src import monitoring as _mon  # no public unregister API

            _mon._unregister_event_duration_listener_by_callback(self._on_event)
        except Exception:
            pass
        try:
            from jax._src import monitoring as _mon

            _mon._unregister_event_listener_by_callback(self._on_plain_event)
        except Exception:
            pass
        self._logger.removeHandler(self._handler)
        if self._saved_level is not None:
            self._logger.setLevel(self._saved_level)
            self._saved_level = None
        if self._saved_propagate is not None:
            self._logger.propagate = self._saved_propagate
            self._saved_propagate = None

    def mark_warm(self) -> None:
        self.warm = True

    @contextmanager
    def deliberate(self, reason: str):
        """Allowlist window: compiles on THIS thread while the context is
        open are deliberate (counted per ``reason``, tagged in the event
        stream) and never raise :class:`RecompileWarning`, even after
        :meth:`mark_warm` — the carve-out for AOT cost analysis, the serve
        tier's batch-ladder warmup and hot-swap revalidation."""
        prev = getattr(self._deliberate, "reason", None)
        self._deliberate.reason = str(reason)
        try:
            yield
        finally:
            self._deliberate.reason = prev

    @contextmanager
    def aot_load(self, tag: str):
        """Executable-cache load window: monitoring events fired on THIS
        thread while a serialized executable deserializes are classified as
        ``aot_load`` — neither a (re)compile nor a ``deliberate:`` compile.
        A cache hit must leave ``compiles``/``recompiles`` untouched or the
        'recompiles 0 after resume' acceptance signal would be noise."""
        prev = getattr(self._aot_load, "tag", None)
        self._aot_load.tag = str(tag)
        try:
            yield
        finally:
            self._aot_load.tag = prev

    def _on_plain_event(self, event: str, **kwargs: Any) -> None:
        """Persistent-compilation-cache outcome: one ``compile_cache`` event
        per backend-compile request, so a resumed run can show its retraces
        were served from ``fabric.compilation_cache_dir``."""
        if event == _CACHE_HIT_EVENT:
            self.cache_hits += 1
            hit = True
        elif event == _CACHE_MISS_EVENT:
            self.cache_misses += 1
            hit = False
        else:
            return
        try:
            self._emit("compile_cache", name=self._handler.last_name or "<unknown>", hit=hit)
        except Exception:
            pass

    def _on_event(self, event: str, duration: float, **kwargs: Any) -> None:
        if event == _LOWER_EVENT:
            phase = "lower"
        elif event == _BACKEND_EVENT:
            phase = "backend"
        else:
            return
        name = self._handler.last_name or "<unknown>"
        aot_tag = getattr(self._aot_load, "tag", None)
        if aot_tag is not None:
            if phase == "lower":
                self.aot_loads[aot_tag] = self.aot_loads.get(aot_tag, 0) + 1
            try:
                self._emit("compile", name=name, phase=phase, dur=duration, post_warm=False, aot_load=aot_tag)
            except Exception:
                pass
            return
        reason = getattr(self._deliberate, "reason", None)
        post_warm = self.warm and reason is None
        if phase == "lower":
            self.compiles += 1
            if reason is not None:
                self.deliberate_compiles[reason] = self.deliberate_compiles.get(reason, 0) + 1
            elif post_warm:
                self.recompiles += 1
                # a dedicated event carrying the offending function's
                # qualified name, so runtime retraces can be cross-referenced
                # against jaxcheck's static JX05 findings (tools/jaxcheck,
                # howto/static_analysis.md) — the `compile` stream below is
                # shared with warmup and deliberate compiles
                try:
                    self._emit("recompile", name=name, qualname=name, dur=duration, count=self.recompiles)
                except Exception:
                    pass
                warnings.warn(
                    f"recompile after warmup: {name} was re-traced/re-lowered "
                    f"({duration:.3f}s). Check for weak-type or shape drift in its inputs. "
                    f"Static complement: python -m tools.jaxcheck (JX05).",
                    RecompileWarning,
                    stacklevel=2,
                )
        extra = {"deliberate": reason} if reason is not None else {}
        try:
            self._emit("compile", name=name, phase=phase, dur=duration, post_warm=post_warm, **extra)
        except Exception:
            pass
