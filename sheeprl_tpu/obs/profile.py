"""Triggered XLA profiler capture (the evidence engine's trace arm).

``metric.profiler.enabled`` (``utils/profiler.maybe_profile``) traces a
*whole* run — fine for a 30-second bench, useless for answering "why was
window 4812 slow" on a day-long job. :class:`TriggeredProfiler` wraps
``jax.profiler.start_trace/stop_trace`` around *individual train windows*
(one window = one ``telemetry_advance`` interval, i.e. one loop update) with
two triggers:

- **explicit** — ``metric.telemetry.profile_windows=[k..m]`` captures the
  listed 1-based windows; consecutive indices share one trace so a ``[2,3]``
  request produces a single Perfetto file spanning both.
- **slow-window watchdog** — with ``metric.telemetry.slow_window_factor=k``
  (>0) the profiler watches ``Time/train_time`` span durations and, once a
  window exceeds ``k×`` the trailing median (after
  ``slow_window_min_history`` healthy windows), schedules ONE capture of the
  next window. One capture per run: the point is a post-hoc artifact for the
  first anomaly, not a trace-everything regression.

Traces land under ``profile_triggered/window_<k>`` next to ``telemetry.jsonl``
and every capture is registered in the run record
(``obs/registry.py`` → ``RUNS.jsonl`` ``profile_captures``), so the MFU
question gets answered with a trace, not a guess.

``start_trace``/``stop_trace`` are injectable for tests; the defaults import
jax lazily. A failed ``start_trace`` (e.g. ``maybe_profile`` already owns the
process-wide profiler session) is swallowed — capture is best-effort evidence,
never a reason to kill the run.
"""

from __future__ import annotations

import os
import statistics
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence

_TRAIN_SPAN = "Time/train_time"
_HISTORY_WINDOW = 64


def _default_start(path: str) -> None:
    import jax

    jax.profiler.start_trace(path)


def _default_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class TriggeredProfiler:
    """Per-train-window trace capture with explicit and slow-window triggers.

    Driven by :class:`~sheeprl_tpu.obs.telemetry.RunTelemetry`:
    :meth:`on_window` at every ``advance`` (window boundary),
    :meth:`observe_span` for every ``Time/train_time`` span close,
    :meth:`finish` at shutdown (stops a straddling capture and returns the
    capture manifest for ``run_end``/the run record).
    """

    def __init__(
        self,
        trace_root: str,
        *,
        windows: Optional[Sequence[int]] = None,
        slow_factor: float = 0.0,
        slow_min_history: int = 8,
        start_trace: Optional[Callable[[str], None]] = None,
        stop_trace: Optional[Callable[[], None]] = None,
    ) -> None:
        self.trace_root = trace_root
        self.windows = {int(w) for w in (windows or [])}
        self.slow_factor = float(slow_factor or 0.0)
        self.slow_min_history = max(1, int(slow_min_history))
        self.captures: List[Dict[str, Any]] = []
        self._start_trace = start_trace or _default_start
        self._stop_trace = stop_trace or _default_stop
        self._active: Optional[Dict[str, Any]] = None
        self._history: deque = deque(maxlen=_HISTORY_WINDOW)
        self._slow_fired = False
        self._slow_pending: Optional[int] = None
        self._window = 0

    # -- window boundary (telemetry.advance) --------------------------------

    def on_window(self, index: int) -> None:
        """Window ``index`` (1-based) starts now. Stop a capture whose
        windows are over, start/extend one the triggers ask for."""
        self._window = int(index)
        want = index in self.windows or index == self._slow_pending
        if self._active is not None:
            if want:
                self._active["windows"].append(index)
                return
            self._stop()
        if want:
            self._start(index)

    # -- slow-window watchdog (telemetry.emit_span) -------------------------

    def observe_span(self, name: str, dur: float) -> None:
        if name != _TRAIN_SPAN:
            return
        if (
            self.slow_factor > 0.0
            and not self._slow_fired
            and len(self._history) >= self.slow_min_history
        ):
            median = statistics.median(self._history)
            if median > 0.0 and dur > self.slow_factor * median:
                # capture the NEXT window: this one already ran untraced
                self._slow_fired = True
                self._slow_pending = self._window + 1
        self._history.append(float(dur))

    # -- lifecycle ----------------------------------------------------------

    def finish(self) -> List[Dict[str, Any]]:
        if self._active is not None:
            self._stop()
        return list(self.captures)

    # -- internals ----------------------------------------------------------

    def _start(self, index: int) -> None:
        trigger = "slow_window" if index == self._slow_pending else "explicit"
        path = os.path.join(self.trace_root, f"window_{index:05d}")
        try:
            os.makedirs(path, exist_ok=True)
            self._start_trace(path)
        except Exception:
            return  # profiler busy (whole-run maybe_profile) or unavailable
        self._active = {
            "trigger": trigger,
            "windows": [index],
            "trace_dir": path,
            "t_start": time.time(),
        }

    def _stop(self) -> None:
        try:
            self._stop_trace()
        except Exception:
            pass
        assert self._active is not None
        self._active["t_end"] = time.time()
        self.captures.append(self._active)
        self._active = None
