"""Span events: one section name, three sinks.

``span`` subsumes the old ``utils.timer.timer`` context-decorator (same class
attributes, same ``TimerError`` semantics — ``utils/timer.py`` is now a shim
over this class) and, when run telemetry is configured, additionally:

- wraps the block in ``jax.profiler.TraceAnnotation(name)`` so the section
  shows up by the same name in the XLA/Perfetto trace, and
- emits one ``span`` JSON event per close to the per-process
  ``telemetry.jsonl`` (name, t_start, dur, step, process_index, attrs).

With telemetry off the hot path is byte-for-byte the old timer plus a single
module-global read, so ``metric.telemetry.enabled=False`` costs nothing.
"""

from __future__ import annotations

import time
from contextlib import ContextDecorator
from typing import Any, Dict, Optional

from sheeprl_tpu.utils.metric import Metric, SumMetric, make_metric


class TimerError(Exception):
    pass


class span(ContextDecorator):
    """Context-decorator that accumulates wall-clock seconds per ``name`` in a
    class-level :class:`Metric` registry and mirrors the section into the XLA
    trace and the telemetry JSONL stream when telemetry is active.

    ``disabled`` only silences the metric registry (the old ``timer.disabled``
    contract, driven by ``metric.log_level`` / ``metric.disable_timer``);
    telemetry emission is governed independently by
    ``metric.telemetry.enabled`` so a low log level still yields JSONL spans.
    """

    disabled: bool = False
    timers: Dict[str, Metric] = {}

    def __init__(self, name: str, metric: Optional[object] = None, **attrs: Any) -> None:
        self.name = name
        self.attrs = attrs
        self._start_time: Optional[float] = None
        self._wall_start: Optional[float] = None
        self._annotation = None
        if not span.disabled and name is not None and name not in span.timers:
            span.timers[name] = make_metric(metric) if metric is not None else SumMetric()

    def start(self) -> None:
        if self._start_time is not None:
            raise TimerError("timer is running. Use .stop() to stop it")
        self._start_time = time.perf_counter()

    def stop(self) -> float:
        if self._start_time is None:
            raise TimerError("timer is not running. Use .start() to start it")
        elapsed = time.perf_counter() - self._start_time
        self._start_time = None
        if self.name and not span.disabled and self.name in span.timers:
            span.timers[self.name].update(elapsed)
        return elapsed

    @classmethod
    def reset(cls) -> None:
        for m in cls.timers.values():
            m.reset()

    @classmethod
    def compute(cls) -> Dict[str, float]:
        return {k: v.compute() for k, v in cls.timers.items()}

    def __enter__(self) -> "span":
        from sheeprl_tpu.obs.telemetry import get_telemetry

        tel = get_telemetry()
        if tel is not None:
            self._wall_start = time.time()
            self._annotation = tel.trace_annotation(self.name)
            if self._annotation is not None:
                self._annotation.__enter__()
        if not span.disabled or tel is not None:
            # When only telemetry wants the span, still run the clock; stop()
            # skips the registry for names registered while disabled.
            if self.name is not None and not span.disabled and self.name not in span.timers:
                span.timers[self.name] = SumMetric()
            self.start()
        return self

    def __exit__(self, *exc_info) -> None:
        from sheeprl_tpu.obs.telemetry import get_telemetry

        tel = get_telemetry()
        elapsed: Optional[float] = None
        if self._start_time is not None:
            elapsed = self.stop()
        if self._annotation is not None:
            self._annotation.__exit__(*exc_info)
            self._annotation = None
        if tel is not None and elapsed is not None:
            tel.emit_span(self.name, self._wall_start, elapsed, self.attrs)
        self._wall_start = None
