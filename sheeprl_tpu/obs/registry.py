"""Run registry: one durable line per run in a repo-level ``RUNS.jsonl``.

Every entrypoint (the train mains via ``cli.run_algorithm``, ``cli_eval``,
``cli_serve`` — and the bench workloads, which run through ``cli.run`` in
subprocesses) appends ONE compact JSON record at run end: what ran (algo,
env, config digest, git sha, topology), how it went (heartbeat rollup — SPS,
MFU, duty cycle, HBM peak, recompiles, fused-dispatch and fallback counts,
rollout restarts/masks, serve stats — plus final losses/returns) and how it
ended (``completed | preempted | crashed | rolled_back`` — plus the
disaggregated actor–learner outcomes ``actor_exhausted`` / ``learner_crashed``,
see ``howto/actor_learner.md``). The registry is
the memory the per-run ``telemetry.jsonl`` lacks: it survives the run
directory and feeds the regression gates (``tools/regress.py``,
``bench.py --regress`` → ``SCENARIOS.json``).

Appends are atomic (``O_APPEND`` + ``flock``) so concurrent runs on one host
interleave whole lines; the reader is tolerant (unparsable lines are
skipped) so one torn write can never poison the history.

Path resolution, first match wins:

1. explicit ``path=`` argument,
2. ``cfg.metric.telemetry.runs_jsonl`` (set to ``false`` to disable),
3. ``SHEEPRL_TPU_RUNS_JSONL`` env var (empty string disables — the test
   harness points this at a tmp dir so suites never pollute the repo file),
4. ``<cwd>/RUNS.jsonl``.

Records carry ``schema`` (currently :data:`SCHEMA_VERSION`); readers keep
older-schema records and skip newer-schema ones they cannot interpret.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import time
from typing import Any, Dict, List, Mapping, Optional

SCHEMA_VERSION = 1
_ENV_VAR = "SHEEPRL_TPU_RUNS_JSONL"

OUTCOMES = ("completed", "preempted", "crashed", "rolled_back", "actor_exhausted", "learner_crashed")


# ------------------------------------------------------------------ paths ----


def runs_jsonl_path(cfg: Optional[Mapping[str, Any]] = None, path: Optional[str] = None) -> Optional[str]:
    """Resolve the registry path (see module docstring); ``None`` = disabled."""
    if path is not None:
        return path or None
    tel_cfg = (((cfg or {}).get("metric") or {}).get("telemetry")) or {}
    cfg_path = tel_cfg.get("runs_jsonl")
    if cfg_path is False:
        return None
    if cfg_path:
        return str(cfg_path)
    if _ENV_VAR in os.environ:
        return os.environ[_ENV_VAR] or None
    return os.path.join(os.getcwd(), "RUNS.jsonl")


# ------------------------------------------------------------ record build ----


def config_digest(cfg: Mapping[str, Any]) -> str:
    """Short stable digest of the composed run config (sorted-key JSON)."""
    try:
        as_dict = cfg.to_dict() if hasattr(cfg, "to_dict") else dict(cfg)
        blob = json.dumps(as_dict, sort_keys=True, default=str)
    except Exception:
        blob = repr(cfg)
    return hashlib.sha256(blob.encode()).hexdigest()[:12]


def git_sha() -> Optional[str]:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:
        return None


def build_run_record(
    cfg: Optional[Mapping[str, Any]],
    *,
    kind: str,
    outcome: str,
    summary: Optional[Mapping[str, Any]] = None,
    **extra: Any,
) -> Dict[str, Any]:
    """Assemble one registry record. ``summary`` is
    :meth:`~sheeprl_tpu.obs.telemetry.RunTelemetry.run_summary` when telemetry
    ran (rollup + topology + final metrics); without it the record still pins
    identity (kind/algo/env/digest/sha/outcome), so the registry works even
    for ``metric.telemetry.enabled=False`` runs."""
    record: Dict[str, Any] = {
        "schema": SCHEMA_VERSION,
        "t": time.time(),
        "kind": str(kind),
        "outcome": outcome if outcome in OUTCOMES else "crashed",
        "git_sha": git_sha(),
    }
    if cfg:
        algo = (cfg.get("algo") or {}) if isinstance(cfg.get("algo"), Mapping) else {}
        env = (cfg.get("env") or {}) if isinstance(cfg.get("env"), Mapping) else {}
        record["algo"] = algo.get("name")
        record["env"] = env.get("id")
        record["exp_name"] = cfg.get("exp_name")
        record["run_name"] = cfg.get("run_name")
        record["seed"] = cfg.get("seed")
        record["config_digest"] = config_digest(cfg)
    if summary:
        record.update(dict(summary))
    record.update(extra)
    return record


# ---------------------------------------------------------------- append ----


def append_run_record(record: Mapping[str, Any], path: str) -> None:
    """Atomically append ``record`` as one JSONL line.

    ``O_APPEND`` makes single-``write`` appends atomic on POSIX; the
    advisory ``flock`` additionally serializes writers that might split a
    very large record across writes."""
    line = json.dumps(dict(record), default=str) + "\n"
    data = line.encode()
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        try:
            import fcntl

            fcntl.flock(fd, fcntl.LOCK_EX)
        except Exception:
            pass  # flock unavailable (exotic fs): O_APPEND still holds
        os.write(fd, data)
    finally:
        os.close(fd)


def read_run_records(path: str) -> List[Dict[str, Any]]:
    """All parseable records in ``path``, file order. Unparsable lines and
    records from a NEWER schema than this reader understands are skipped."""
    records: List[Dict[str, Any]] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if int(rec.get("schema", 1) or 1) > SCHEMA_VERSION:
                    continue
                records.append(rec)
    except OSError:
        return []
    return records


# ------------------------------------------------------------- run-end hook ----


def register_run(
    cfg: Optional[Mapping[str, Any]],
    *,
    kind: str,
    outcome: str,
    error: Optional[str] = None,
    path: Optional[str] = None,
    **extra: Any,
) -> Optional[Dict[str, Any]]:
    """The entrypoint hook: roll up the active telemetry (if any), build the
    record and append it. Never raises — a registry failure must not mask
    the run's own outcome. Returns the record (or ``None`` when the registry
    is disabled or the append failed)."""
    try:
        resolved = runs_jsonl_path(cfg, path)
        if not resolved:
            return None
        from sheeprl_tpu.obs.telemetry import get_telemetry

        tel = get_telemetry()
        summary = tel.run_summary() if tel is not None else None
        # a crash after one or more NaN rollbacks is the rollback budget (or
        # its aftermath) ending the run — classify it as such
        if outcome == "crashed" and summary and summary.get("nan_rollbacks"):
            outcome = "rolled_back"
        if error:
            extra = {**extra, "error": str(error)[:500]}
        record = build_run_record(cfg, kind=kind, outcome=outcome, summary=summary, **extra)
        append_run_record(record, resolved)
        return record
    except Exception:
        return None
