"""Distributed trace plane: cross-process causal ids for slabs and requests.

The telemetry stream (:mod:`sheeprl_tpu.obs.telemetry`) is per-process — every
process of the disaggregated topology (learner, M actors, router + N replica
threads, env workers) writes its own JSONL, so the life of a trajectory slab
or a served request across process boundaries is invisible end to end. This
module adds the causal layer:

- **trace ids** — :func:`new_trace_id` mints a random 63-bit id (nonzero,
  fits the ring's int64 header words). A slab's id is stamped into its
  ``SlabLayout`` header at actor write and read back at learner admission; a
  request's id survives hedging, re-route-at-front and requeue because it
  lives on the shared :class:`~sheeprl_tpu.serve.batching.Request` object.
- **handshakes** — every trace sink opens with a ``trace_handshake`` record
  carrying ``role``, ``pid`` and ``clock_offset = time.time() -
  time.monotonic()`` measured at spawn. Monotonic clocks are per-process and
  arbitrary; the offset lets the merger (``tools/trace.py``) align every
  process's ``t_mono`` stamps onto one epoch timeline.
- **two sinks** — processes that own a :class:`RunTelemetry` (learner, serve
  CLI) ride trace events on their existing ``telemetry.jsonl`` (buffered,
  rotated, registered in RUNS.jsonl). Actor children have no telemetry hub
  and die via ``os._exit`` on the crash drills, so they use a *standalone*
  :class:`TraceRecorder` (``trace.actor<i>.jsonl``) that flushes every event
  — a torn-write crash still leaves the actor-side half of the trace on
  disk.

Event schema (one JSON object per line, merged by ``tools/trace.py``)::

    {"event": "trace_handshake", "role", "pid", "clock_offset", "t", "t_mono"}
    {"event": "trace", "kind", "trace_id", "role", "pid", "t", "t_mono", ...}

``trace_id == 0`` marks process-scoped events that belong to no one causal
chain (``param_publish``, ``replica_killed``, batched ``request_reroute``
carriers); the merger files them on the emitting process's track.

Everything here is a cheap no-op when neither a standalone recorder nor an
active telemetry exists — the disabled hot path is two global reads.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

_MASK63 = (1 << 63) - 1
_ACTIVE_RING = 64  # recent trace ids kept for flight-recorder dumps


def new_trace_id() -> int:
    """Random nonzero 63-bit trace id (fits an int64 ring-header word)."""
    tid = 0
    while tid == 0:
        tid = int.from_bytes(os.urandom(8), "little") & _MASK63
    return tid


def clock_offset() -> float:
    """This process's monotonic→epoch alignment: ``epoch = t_mono + offset``."""
    return time.time() - time.monotonic()


class TraceRecorder:
    """Standalone trace sink: one flush-per-event JSONL file.

    For processes without a telemetry hub (actor children) and for tests that
    trace threaded servers without configuring telemetry. The handshake is
    written at construction and every event is flushed immediately — a
    process that dies via ``os._exit`` (the crash drills) still leaves every
    event it emitted on disk.
    """

    def __init__(self, role: str, path: str, **handshake_fields: Any) -> None:
        self.role = str(role)
        self.pid = os.getpid()
        self.clock_offset = clock_offset()
        self.path = str(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(self.path, "a", buffering=1)
        self._active: deque = deque(maxlen=_ACTIVE_RING)
        self._write(self._handshake_record(**handshake_fields))

    def _handshake_record(self, **fields: Any) -> Dict[str, Any]:
        return {
            "event": "trace_handshake",
            "role": self.role,
            "pid": self.pid,
            "clock_offset": self.clock_offset,
            "t": time.time(),
            "t_mono": time.monotonic(),
            **fields,
        }

    def _write(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            self._fh.write(line + "\n")
            self._fh.flush()

    def emit(self, kind: str, trace_id: int = 0, **fields: Any) -> None:
        tid = int(trace_id)
        if tid:
            self._active.append(tid)
        self._write(
            {
                "event": "trace",
                "kind": str(kind),
                "trace_id": tid,
                "role": self.role,
                "pid": self.pid,
                "t": time.time(),
                "t_mono": time.monotonic(),
                **fields,
            }
        )

    def rehandshake(self) -> None:
        """Re-emit the handshake (after a role change); the merger keeps the
        newest handshake per stream."""
        self._write(self._handshake_record())

    def active_trace_ids(self) -> List[int]:
        return list(self._active)

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:
                pass


# -- module state (per process) ----------------------------------------------

_recorder: Optional[TraceRecorder] = None
_role: Optional[str] = None
# telemetry-attached sink state: reset whenever the active RunTelemetry
# instance changes (a new run re-handshakes on its fresh stream)
_tel_ref: Any = None
_tel_active: deque = deque(maxlen=_ACTIVE_RING)


def _get_telemetry():
    from sheeprl_tpu.obs.telemetry import get_telemetry

    return get_telemetry()


def current_role() -> str:
    """The role this process emits traces under (handshake + every event)."""
    if _recorder is not None:
        return _recorder.role
    return _role or "proc"


def set_trace_role(role: str) -> None:
    """Name this process's trace track (``learner``, ``serve``, ...). If a
    sink is already live, re-handshake so the merger picks up the role."""
    global _role
    _role = str(role)
    if _recorder is not None:
        _recorder.role = _role
        _recorder.rehandshake()
        return
    tel = _get_telemetry()
    if tel is not None:
        _emit_handshake_via(tel)


def configure_trace(role: str, path: str, **handshake_fields: Any) -> TraceRecorder:
    """Open a standalone trace recorder for this process (actor children,
    telemetry-less tests). Replaces any previous recorder."""
    global _recorder, _role
    shutdown_trace()
    _role = str(role)
    _recorder = TraceRecorder(role, path, **handshake_fields)
    return _recorder


def get_trace() -> Optional[TraceRecorder]:
    return _recorder


def shutdown_trace() -> None:
    global _recorder
    rec, _recorder = _recorder, None
    if rec is not None:
        rec.close()


def tracing_active() -> bool:
    """True when trace events have somewhere to go — callers that must pay
    to *build* a context (mint an id) check this first; plain emission just
    calls :func:`trace_event`, which is a cheap no-op when off."""
    return _recorder is not None or _get_telemetry() is not None


def _emit_handshake_via(tel: Any) -> None:
    global _tel_ref
    if tel is not _tel_ref:
        _tel_active.clear()
    _tel_ref = tel
    tel.emit(
        "trace_handshake",
        role=current_role(),
        pid=os.getpid(),
        clock_offset=clock_offset(),
        t_mono=time.monotonic(),
    )


def trace_event(kind: str, trace_id: int = 0, **fields: Any) -> None:
    """Emit one trace event through whichever sink this process has: the
    standalone recorder if configured, else the active telemetry stream
    (handshaking it lazily), else nothing."""
    rec = _recorder
    if rec is not None:
        rec.emit(kind, trace_id, **fields)
        return
    tel = _get_telemetry()
    if tel is None:
        return
    if tel is not _tel_ref:
        _emit_handshake_via(tel)
    tid = int(trace_id)
    if tid:
        _tel_active.append(tid)
    tel.emit(
        "trace",
        kind=str(kind),
        trace_id=tid,
        role=current_role(),
        pid=os.getpid(),
        t_mono=time.monotonic(),
        **fields,
    )


def active_trace_ids() -> List[int]:
    """Recently-seen trace ids (newest last) — stamped into flight-recorder
    dumps so a crash artifact can be placed on the merged timeline."""
    if _recorder is not None:
        return _recorder.active_trace_ids()
    return list(_tel_active)
