"""Shared per-log-interval throughput block for the training loops.

Every algorithm's loop used to hand-roll the same ``timer.compute()`` →
``Time/sps_*`` → ``timer.reset()`` dance; this helper centralizes it and, when
run telemetry is active, feeds the same window into
:meth:`RunTelemetry.heartbeat` so the JSONL stream, TensorBoard scalars and
``bench.py`` all report identical numbers.

Callers pass their own window deltas (the env-steps formula differs between
on-policy and off-policy loops) and reset their ``last_log``/``last_train``
bookkeeping themselves.
"""

from __future__ import annotations

from typing import Optional

from sheeprl_tpu.obs.span import span
from sheeprl_tpu.obs.telemetry import get_telemetry


def log_sps_and_heartbeat(
    logger,
    *,
    policy_step: int,
    env_steps: float,
    train_steps: float,
    train_invocations: Optional[float] = None,
) -> None:
    """Log ``Time/sps_train`` / ``Time/sps_env_interaction`` for the window
    since the last call, reset the span registry, and emit a telemetry
    heartbeat when the subsystem is active.

    ``env_steps``/``train_steps`` are the caller's window deltas;
    ``train_invocations`` is how many times the jitted train fn ran in the
    window (feeds MFU; None when the loop has no registered flops source)."""
    timer_window = {}
    if not span.disabled:
        timer_window = span.compute()
        sps = {}
        if timer_window.get("Time/train_time"):
            sps["Time/sps_train"] = train_steps / timer_window["Time/train_time"]
        if timer_window.get("Time/env_interaction_time"):
            sps["Time/sps_env_interaction"] = env_steps / timer_window["Time/env_interaction_time"]
        if sps:
            logger.log_metrics(sps, policy_step)
        span.reset()
    tel = get_telemetry()
    if tel is not None:
        tel.heartbeat(
            logger,
            step=policy_step,
            env_steps=env_steps,
            train_steps=train_steps,
            train_invocations=train_invocations,
            timer_window=timer_window,
        )
