"""Run-telemetry subsystem (observability layer).

One instrumentation vocabulary for the whole framework:

- :class:`~sheeprl_tpu.obs.span.span` — context-decorator that puts the SAME
  section name into the wall-clock metric registry (the old ``timer``), the
  XLA/Perfetto trace (``jax.profiler.TraceAnnotation``) and the per-process
  ``telemetry.jsonl`` event stream.
- :class:`~sheeprl_tpu.obs.recompile.CompileWatchdog` — ``jax.monitoring``
  subscriber that turns every trace+lower into a ``compile`` event and raises
  a loud warning on post-warmup recompiles (silent retracing is the #1 TPU
  perf killer).
- :class:`~sheeprl_tpu.obs.telemetry.RunTelemetry` — the per-run sink: JSONL
  writer, low-rate device poller (HBM in-use/peak, optional link RTT) and the
  per-log-interval ``heartbeat`` assembly (SPS, duty cycle, MFU, HBM peak,
  recompile count).

The event schema is documented in ``howto/telemetry.md``; ``bench.py``
consumes the same stream (``telemetry_summary``) so the bench and the run
report the same numbers. Everything is inert unless
``metric.telemetry.enabled=True`` — the disabled hot path is one global read.
"""

from sheeprl_tpu.obs.heartbeat import log_sps_and_heartbeat
from sheeprl_tpu.obs.profile import TriggeredProfiler
from sheeprl_tpu.obs.registry import append_run_record, build_run_record, read_run_records, register_run
from sheeprl_tpu.obs.span import TimerError, span
from sheeprl_tpu.obs.telemetry import (
    RunTelemetry,
    configure_telemetry,
    get_telemetry,
    shutdown_telemetry,
    telemetry_actor_restart,
    telemetry_advance,
    telemetry_aot_cache,
    telemetry_aot_load,
    telemetry_child_file,
    telemetry_ckpt_commit,
    telemetry_ckpt_skipped,
    telemetry_crash_checkpoint,
    telemetry_deliberate_compiles,
    telemetry_dump_flight_record,
    telemetry_env_step,
    telemetry_fused_fallback,
    telemetry_mark_warm,
    telemetry_masked_slot,
    telemetry_nan_rollback,
    telemetry_net_event,
    telemetry_preemption,
    telemetry_register_flops,
    telemetry_request_path,
    telemetry_resume_fallback,
    telemetry_run_metrics,
    telemetry_serve_event,
    telemetry_serve_stats,
    telemetry_slab,
    telemetry_slab_lag,
    telemetry_torn_slabs,
    telemetry_train_window,
    telemetry_worker_restart,
)
from sheeprl_tpu.obs.trace import (
    TraceRecorder,
    active_trace_ids,
    clock_offset,
    configure_trace,
    get_trace,
    new_trace_id,
    set_trace_role,
    shutdown_trace,
    trace_event,
    tracing_active,
)

__all__ = [
    "RunTelemetry",
    "TimerError",
    "TraceRecorder",
    "TriggeredProfiler",
    "active_trace_ids",
    "append_run_record",
    "build_run_record",
    "clock_offset",
    "configure_telemetry",
    "configure_trace",
    "get_telemetry",
    "get_trace",
    "log_sps_and_heartbeat",
    "new_trace_id",
    "read_run_records",
    "register_run",
    "set_trace_role",
    "shutdown_telemetry",
    "shutdown_trace",
    "span",
    "telemetry_actor_restart",
    "telemetry_advance",
    "telemetry_aot_cache",
    "telemetry_aot_load",
    "telemetry_child_file",
    "telemetry_ckpt_commit",
    "telemetry_ckpt_skipped",
    "telemetry_crash_checkpoint",
    "telemetry_deliberate_compiles",
    "telemetry_dump_flight_record",
    "telemetry_env_step",
    "telemetry_fused_fallback",
    "telemetry_mark_warm",
    "telemetry_masked_slot",
    "telemetry_nan_rollback",
    "telemetry_net_event",
    "telemetry_preemption",
    "telemetry_register_flops",
    "telemetry_request_path",
    "telemetry_resume_fallback",
    "telemetry_run_metrics",
    "telemetry_serve_event",
    "telemetry_serve_stats",
    "telemetry_slab",
    "telemetry_slab_lag",
    "telemetry_torn_slabs",
    "telemetry_train_window",
    "telemetry_worker_restart",
    "trace_event",
    "tracing_active",
]
