"""``python -m sheeprl_tpu.cli_registration checkpoint_path=...``
(reference: sheeprl_model_manager.py)."""

from sheeprl_tpu.cli import registration

if __name__ == "__main__":
    registration()
