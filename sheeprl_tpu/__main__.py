"""``python -m sheeprl_tpu exp=... overrides`` (reference: sheeprl.py:3)."""

from sheeprl_tpu.cli import run

if __name__ == "__main__":
    run()
