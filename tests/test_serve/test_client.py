"""ServeClient retry policy (jax-free: the client is duck-typed over the
server) and the scripted load generator's report shape."""

import pytest

from sheeprl_tpu.serve.client import ServeClient
from sheeprl_tpu.serve.errors import DeadlineExceeded, Overloaded, ServerClosed

pytestmark = pytest.mark.serve


class _ScriptedServer:
    """infer() raises the scripted exceptions in order, then returns."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = 0

    def infer(self, obs, deadline_s=None):
        self.calls += 1
        if self.script:
            raise self.script.pop(0)
        return {"action": 1}


def _overloaded():
    return Overloaded(4, 4, retry_after_s=0.001)


def test_client_retries_overloaded_with_backoff_then_succeeds():
    server = _ScriptedServer([_overloaded(), _overloaded()])
    client = ServeClient(server, max_retries=3, seed=0)
    assert client.infer({"x": 1}) == {"action": 1}
    assert server.calls == 3
    assert client.retries == 2 and client.rejected == 2


def test_client_gives_up_after_max_retries():
    server = _ScriptedServer([_overloaded()] * 10)
    client = ServeClient(server, max_retries=2, seed=0)
    with pytest.raises(Overloaded):
        client.infer({"x": 1})
    assert server.calls == 3  # initial + 2 retries
    assert client.rejected == 3


@pytest.mark.parametrize("err", [DeadlineExceeded(0.5, 0.5), ServerClosed("down")])
def test_client_does_not_retry_terminal_failures(err):
    server = _ScriptedServer([err])
    client = ServeClient(server, max_retries=3, seed=0)
    with pytest.raises(type(err)):
        client.infer({"x": 1})
    assert server.calls == 1 and client.retries == 0


def test_client_never_backs_off_past_its_own_deadline():
    # retry_after so large the jittered pause cannot fit the timeout budget
    server = _ScriptedServer([Overloaded(4, 4, retry_after_s=10.0)] * 5)
    client = ServeClient(server, max_retries=5, timeout_s=0.05, seed=0)
    with pytest.raises(Overloaded):
        client.infer({"x": 1})
    assert client.retries == 0  # rejected, but no sleep was affordable
