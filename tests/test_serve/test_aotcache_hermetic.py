"""Hermetic launcher for the AOT executable-cache serving drills.

The drills in test_aotcache_serving.py serialize real XLA executables and
load them back; that round trip is only sound in a process where NOTHING was
ever deserialized from the warm cross-run trace cache (see that module's
docstring — a deserialized executable registers generically-named kernel
symbols process-wide, and the cache's on/off/dir state latches at the first
compile). A shared pytest session cannot guarantee that: even collection
imports compile. So each launcher here boots a fresh interpreter with the
persistent cache stripped from the environment and runs the real drills
there, asserting the child's verdict.
"""

import os
import subprocess
import sys

import pytest

pytestmark = [pytest.mark.serve]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
DRILLS = os.path.join("tests", "test_serve", "test_aotcache_serving.py")


def _run_hermetic(extra_args, timeout=420):
    env = dict(os.environ)
    env["SHEEPRL_TPU_AOT_HERMETIC"] = "1"
    # a clean room, not merely a disabled flag: the child must never see the
    # shared warm cache dir, or its first compile latches onto it
    env["SHEEPRL_TPU_NO_COMPILE_CACHE"] = "1"
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            DRILLS,
            "-q",
            "-p",
            "no:cacheprovider",
            "-p",
            "no:randomly",
            *extra_args,
        ],
        cwd=REPO_ROOT,
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, (
        f"hermetic AOT drills failed (rc={proc.returncode}):\n"
        f"{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


def test_aot_roundtrip_drills_hermetic():
    out = _run_hermetic(["-m", "not slow"])
    assert "3 passed" in out, out[-2000:]


@pytest.mark.slow
@pytest.mark.fleet
def test_aot_autoscale_drill_hermetic():
    out = _run_hermetic(["-m", "slow"], timeout=540)
    assert "1 passed" in out, out[-2000:]
