"""Shared fixtures for the serving-tier tests: committed linear-policy
checkpoints on disk (the env-free synthetic policy) and a PolicyServer
factory with drill-friendly supervision timings."""

import os
from typing import Any, Dict, Optional, Tuple

import pytest

from sheeprl_tpu.resilience.manifest import build_manifest
from sheeprl_tpu.utils.checkpoint import save_checkpoint


def commit_linear(ckpt_dir: str, step: int, *, seed: int = 0, state: Optional[Dict[str, Any]] = None) -> Tuple[str, Dict[str, Any]]:
    """Write a COMMITTED linear-policy checkpoint (payload + manifest) the
    way a training run would, returning ``(path, state)``."""
    from sheeprl_tpu.serve.policy import make_linear_state

    os.makedirs(ckpt_dir, exist_ok=True)
    state = state if state is not None else make_linear_state(seed=seed)
    path = os.path.join(ckpt_dir, f"ckpt_{step}_0.ckpt")
    man = build_manifest(step=step, backend="pickle", world_size=1, state=state)
    save_checkpoint(path, state, backend="pickle", manifest=man)
    return path, state


# supervision timings tuned for drills: fast monitor, near-zero backoff, a
# small ladder so tests stay sub-second outside the deliberate fault windows
DRILL_SERVE: Dict[str, Any] = {
    "batch_ladder": [1, 2, 4],
    "slo_ms": 200.0,
    "monitor_interval_s": 0.01,
    "backoff_base_s": 0.01,
    "backoff_max_s": 0.05,
    "replica_timeout_s": 5.0,
}


@pytest.fixture
def make_server(tmp_path):
    """Factory: a PolicyServer over a committed linear checkpoint at step
    100. Keyword overrides merge into the drill serve node; every server is
    closed at teardown even when the test raises."""
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.policy import build_linear_policy
    from sheeprl_tpu.serve.server import PolicyServer

    servers = []

    def build(**serve_overrides: Any) -> Tuple[PolicyServer, str, Dict[str, Any]]:
        ckpt_dir = str(tmp_path / "checkpoint")
        path, state = commit_linear(ckpt_dir, 100, seed=0)
        policy = build_linear_policy({"algo": {"name": "linear"}}, state)
        cfg = serve_config_from_cfg({"serve": {**DRILL_SERVE, **serve_overrides}})
        server = PolicyServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir)
        servers.append(server)
        return server, ckpt_dir, state

    yield build
    for server in servers:
        server.close()


# fleet drill node merged over DRILL_SERVE: small fleet, fast autoscale and
# hedge scans so chaos drills converge in tens of milliseconds
DRILL_FLEET: Dict[str, Any] = {
    "enabled": True,
    "num_replicas": 2,
    "min_replicas": 1,
    "max_replicas": 2,
    "backlog_per_replica": 64,
    "hedge_scan_ms": 2.0,
    "autoscale_interval_s": 0.05,
}


@pytest.fixture
def make_fleet(tmp_path):
    """Factory: a FleetServer over the same committed linear checkpoint.
    ``fleet=`` overrides merge into the drill fleet node, other keywords into
    the serve node; every fleet is closed at teardown."""
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.fleet import FleetServer
    from sheeprl_tpu.serve.policy import build_linear_policy

    servers = []

    def build(*, fleet: Optional[Dict[str, Any]] = None, **serve_overrides: Any):
        ckpt_dir = str(tmp_path / "checkpoint")
        path, state = commit_linear(ckpt_dir, 100, seed=0)
        policy = build_linear_policy({"algo": {"name": "linear"}}, state)
        node = {**DRILL_SERVE, **serve_overrides, "fleet": {**DRILL_FLEET, **(fleet or {})}}
        cfg = serve_config_from_cfg({"serve": node})
        server = FleetServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir)
        servers.append(server)
        return server, ckpt_dir, state

    yield build
    for server in servers:
        server.close()


def linear_obs(state: Dict[str, Any], value: float = 1.0):
    """A deterministic observation matching the linear policy's spec."""
    import numpy as np

    in_dim = state["agent"]["w"].shape[0]
    return {"vector": np.full((in_dim,), value, dtype=np.float32)}


def expected_action(state: Dict[str, Any], obs) -> Any:
    import numpy as np

    return np.asarray(obs["vector"]) @ state["agent"]["w"] + state["agent"]["b"]
