"""ServeConfig derivation/validation, serve node parsing, fault-spec
parsing and the fire-once fault schedule. jax-free."""

import pytest

from sheeprl_tpu.serve.config import ServeConfig, serve_config_from_cfg
from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule, ServeFaultSpec, parse_serve_faults

pytestmark = pytest.mark.serve


def test_slo_derives_gather_window_and_deadline():
    cfg = ServeConfig(slo_ms=25.0)
    assert cfg.gather_window_s == pytest.approx(0.005)  # slo/5
    assert cfg.default_deadline_s == pytest.approx(0.1)  # 4x slo
    # the window is capped at 10ms no matter how loose the SLO
    assert ServeConfig(slo_ms=1000.0).gather_window_s == pytest.approx(0.010)
    # explicit values win over derivation
    explicit = ServeConfig(slo_ms=25.0, gather_window_ms=2.0, default_deadline_ms=50.0)
    assert explicit.gather_window_s == pytest.approx(0.002)
    assert explicit.default_deadline_s == pytest.approx(0.050)


def test_ladder_sorted_deduped_and_validated():
    cfg = ServeConfig(batch_ladder=[8, 1, 4, 4, 2])
    assert cfg.batch_ladder == [1, 2, 4, 8]
    assert cfg.max_batch == 8
    with pytest.raises(ValueError, match="batch_ladder"):
        ServeConfig(batch_ladder=[])
    with pytest.raises(ValueError, match="batch_ladder"):
        ServeConfig(batch_ladder=[0, 2])
    with pytest.raises(ValueError, match="num_replicas"):
        ServeConfig(num_replicas=0)
    with pytest.raises(ValueError, match="max_queue"):
        ServeConfig(max_queue=0)


def test_restart_backoff_exponential_and_capped():
    cfg = ServeConfig(backoff_base_s=0.05, backoff_max_s=0.4)
    assert [cfg.backoff_s(n) for n in (1, 2, 3, 4, 10)] == [0.05, 0.1, 0.2, 0.4, 0.4]


def test_serve_config_from_cfg_reads_node_and_defaults():
    # a checkpoint written before the serve node existed composes to defaults
    assert serve_config_from_cfg({}).slo_ms == 100.0
    cfg = serve_config_from_cfg(
        {
            "serve": {
                "slo_ms": 50,
                "max_queue": 8,
                "num_replicas": 3,
                "fault_injection": {
                    "enabled": True,
                    "faults": [{"kind": "replica_crash", "replica": 1, "at_batch": 5}],
                },
                "load": {"enabled": True, "duration_s": 2, "concurrency": 4},
            }
        }
    )
    assert cfg.slo_ms == 50.0 and cfg.max_queue == 8 and cfg.num_replicas == 3
    assert [f.kind for f in cfg.faults] == ["replica_crash"]
    assert cfg.load.enabled and cfg.load.duration_s == 2.0 and cfg.load.concurrency == 4


def test_faults_gated_by_enabled_flag():
    cfg = serve_config_from_cfg(
        {
            "serve": {
                "fault_injection": {
                    "enabled": False,
                    "faults": [{"kind": "replica_crash", "at_batch": 1}],
                }
            }
        }
    )
    assert cfg.faults == []


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="kind"):
        ServeFaultSpec(kind="segfault")
    with pytest.raises(ValueError, match="at_swap"):
        ServeFaultSpec(kind="poison_swap", at_swap=0)
    with pytest.raises(ValueError, match="mapping"):
        parse_serve_faults(["replica_crash@5"])
    with pytest.raises(ValueError, match="kind"):
        parse_serve_faults([{"replica": 0}])


def test_schedule_crash_fires_once_and_late():
    sched = ServeFaultSchedule([ServeFaultSpec(kind="replica_crash", replica=0, at_batch=3)])
    assert sched.batch_faults(1, 10) == []  # other replica: never
    assert sched.batch_faults(0, 2) == []
    # scheduled step was passed while the replica restarted: fire on the NEXT
    # batch rather than silently dropping the drill
    due = sched.batch_faults(0, 5)
    assert [f.kind for f in due] == ["replica_crash"]
    assert sched.batch_faults(0, 6) == []  # exactly once
    assert not sched


def test_schedule_slow_window_then_expires():
    sched = ServeFaultSchedule(
        [ServeFaultSpec(kind="slow_inference", replica=0, at_batch=2, duration_s=0.1, for_batches=3)]
    )
    assert sched.batch_faults(0, 1) == []
    for b in (2, 3, 4):  # the whole window fires
        assert [f.kind for f in sched.batch_faults(0, b)] == ["slow_inference"]
    assert sched.batch_faults(0, 5) == []  # window over: expired
    assert not sched


def test_schedule_poison_swap_fires_once():
    sched = ServeFaultSchedule([ServeFaultSpec(kind="poison_swap", at_swap=2)])
    assert not sched.poison_swap(1)
    assert sched.poison_swap(2)
    assert not sched.poison_swap(3)  # consumed
