"""Replica-fleet drills (howto/serving.md, fleet section): warmup before
traffic on every replica, health-weighted routing, hedged retries rescuing a
stuck primary, router blackhole rescue, kill-mid-burst with zero dropped
admitted requests, budget exhaustion -> masked degraded N-1, CPU spill for
batch-priority traffic, elastic scale up/down — and the slow chaos ramp:
kill a replica mid-ramp on a 4-replica fleet and hold the SLO on survivors.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.batching import Request
from sheeprl_tpu.serve.errors import Overloaded

from .conftest import expected_action, linear_obs

pytestmark = [pytest.mark.serve, pytest.mark.fleet]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _wait_until(predicate, timeout_s=5.0, interval_s=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


# ------------------------------------------------------------------ fleet ----


def test_fleet_warmup_then_correct_actions_and_snapshot(make_fleet):
    server, _, state = make_fleet()
    server.start()
    assert sorted(server.warmup_s) == [1, 2, 4]
    obs = linear_obs(state, value=0.5)
    out = server.infer(obs)
    np.testing.assert_allclose(out, expected_action(state, obs), rtol=1e-5)
    snap = server.snapshot()
    assert snap["completed"] == 1 and snap["serving_step"] == 100
    assert snap["replicas_alive"] == 2 and not snap["degraded"]
    fleet = snap["fleet"]
    assert fleet["active_device_replicas"] == 2
    assert fleet["router"]["routed"] == 1 and fleet["router"]["shed"] == 0
    assert len(fleet["replicas"]) == 2
    assert all(r["health"] > 0 for r in fleet["replicas"] if r["active"])


def test_fleet_admission_bound_sheds_typed(make_fleet):
    server, _, state = make_fleet(
        fleet={"max_pending": 1, "num_replicas": 1, "max_replicas": 1},
        fault_injection={
            "enabled": True,
            "faults": [
                {"kind": "slow_inference", "replica": 0, "at_batch": 0, "duration_s": 0.2, "for_batches": 50}
            ],
        },
    )
    server.start()
    reqs = []
    shed = 0
    for _ in range(6):
        try:
            reqs.append(server.submit(linear_obs(state), deadline_s=5.0))
        except Overloaded:
            shed += 1
    assert shed >= 1  # past the fleet-wide pending bound: typed, immediate
    for req in reqs:
        server.wait(req)  # admitted requests still complete
    assert server.router.shed == shed


def test_kill_replica_mid_burst_zero_dropped(make_fleet, tmp_path):
    """The fast chaos drill: kill a replica while a burst is in flight —
    every admitted request completes (re-route-at-front), the fleet restarts
    the dead replica, and the survivors keep serving. Runs under the trace
    plane: the merged timeline must show one complete causal chain per
    request, the kill's stranded batch attributed re-routed, and the
    queue-wait/assembly/compute decomposition via ``bench.py --trace``."""
    from sheeprl_tpu.obs.trace import configure_trace, shutdown_trace

    trace_path = str(tmp_path / "trace.serve.jsonl")
    configure_trace("serve", trace_path)
    try:
        server, _, state = make_fleet(
            fleet={"num_replicas": 2, "max_replicas": 2, "max_pending": 10_000},
            # pin a batch in flight on replica 0 so the kill strands it —
            # the re-route-at-front path fires deterministically
            fault_injection={
                "enabled": True,
                "faults": [
                    {"kind": "slow_inference", "replica": 0, "at_batch": 0, "duration_s": 0.25, "for_batches": 50}
                ],
            },
        )
        server.start()
        results, errors = [], []

        def client(n):
            for i in range(n):
                try:
                    obs = linear_obs(state, value=float(i % 7))
                    out = server.infer(obs, deadline_s=10.0)
                    np.testing.assert_allclose(out, expected_action(state, obs), rtol=1e-5)
                    results.append(out)
                except Exception as err:  # noqa: BLE001 — drill collects everything
                    errors.append(err)

        threads = [threading.Thread(target=client, args=(30,)) for _ in range(4)]
        for t in threads:
            t.start()
        # kill only once replica 0 actually holds a batch — the slow_inference
        # fault pins EVERY burst batch for 0.25s, so whichever batch we observe
        # in flight, the kill lands inside its pin window and strands it; a
        # narrower window races the observed batch completing before the kill
        assert _wait_until(lambda: len(server.slots[0].pool._inflight) > 0)
        assert server.kill_replica(0)
        for t in threads:
            t.join(20.0)
        assert not errors and len(results) == 120
        assert _wait_until(lambda: server.slots[0].alive)  # budgeted restart
        snap = server.snapshot()
        assert snap["failed"] == 0 and snap["restarts"] >= 1
        # the stranded batch was re-homed: by the monitor's re-route-at-front,
        # or by a hedge twin when the adaptive hedge scan (threshold learned
        # down to ~ms on a warm ladder) beats the monitor pass to the rescue
        router_snap = snap["fleet"]["router"]
        assert router_snap["rerouted_requests"] + router_snap["hedged"] >= 1

        # request_done is emitted by the delivering replica thread right
        # after the future resolves — give the last few a beat to land
        def done_count():
            with open(trace_path) as f:
                return sum(1 for line in f if '"request_done"' in line)

        assert _wait_until(lambda: done_count() >= 120)
    finally:
        shutdown_trace()

    # -- merged end-to-end trace: the drill's acceptance evidence -----------
    from tools import trace as trace_tool

    merged = trace_tool.merge([trace_path])
    summary = trace_tool.summarize(merged)
    req = summary["requests"]
    assert req["traces"] == 120  # every admitted request minted one chain
    assert req["terminals"] == {"request_done": 120}  # zero dangling/expired
    # the kill's victims carry request_reroute, or request_hedge when the
    # adaptive hedge scan won the rescue race (same either/or as the snapshot)
    assert req["rerouted"] + req["hedged"] >= 1
    assert "hedge_winner_dupes" not in req  # first-completion-wins held
    for tid, evs in merged["traces"].items():
        kinds = trace_tool.trace_kinds(evs)
        assert kinds[0] == "request_admit", (tid, kinds)
        assert kinds.count("request_done") == 1, (tid, kinds)
    # the fault victim's chain: re-homed, then done exactly once
    victims = [
        evs for evs in merged["traces"].values()
        if any(e["kind"] in ("request_reroute", "request_hedge") for e in evs)
    ]
    assert victims
    for evs in victims:
        done = [e for e in evs if e["kind"] == "request_done"][0]
        rescued = [e["kind"] for e in evs]
        if "request_reroute" in rescued:
            assert done["rerouted"] is True
        else:
            assert done["hedged"] is True
    # the kill itself lands on the untraced (process-scoped) timeline
    assert any(e["kind"] == "replica_killed" for e in merged["untraced"])

    # bench.py --trace prints the request latency decomposition
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--trace", trace_path],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    printed = json.loads(proc.stdout)
    for key in ("total_ms", "queue_wait_ms", "assembly_ms", "compute_ms"):
        assert "p50" in printed["requests"][key] and "p95" in printed["requests"][key]


def test_budget_exhaustion_masks_and_fleet_serves_degraded(make_fleet):
    server, _, state = make_fleet(
        max_restarts=1,
        restart_refund_s=None,
        fleet={"num_replicas": 2, "max_replicas": 2},
    )
    server.start()
    for _ in range(2):  # budget of 1: second death masks the slot
        assert _wait_until(lambda: server.slots[0].alive)
        server.kill_replica(0)
        assert _wait_until(lambda: not server.slots[0].alive, timeout_s=2.0)
        _wait_until(lambda: server.slots[0].masked or server.slots[0].restart_at is not None or server.slots[0].alive)
    assert _wait_until(lambda: server.slots[0].masked)
    obs = linear_obs(state)
    np.testing.assert_allclose(server.infer(obs), expected_action(state, obs), rtol=1e-5)
    snap = server.snapshot()
    assert snap["degraded"] and snap["replicas_masked"] == 1
    assert snap["fleet"]["active_device_replicas"] == 1  # N-1, still serving


def test_emergency_floor_reactivates_standby_after_last_replica_masked(make_fleet):
    """Losing the LAST active replica (masked, budget spent) must not strand
    the fleet at zero capacity: the autoscaler's emergency floor activates a
    standby slot immediately — no queue-depth signal required, because an
    empty fleet can never generate one — and the hedge scan re-places every
    stranded request on the recovered capacity."""
    server, _, state = make_fleet(
        max_restarts=0,
        restart_refund_s=None,
        fleet={"num_replicas": 1, "min_replicas": 1, "max_replicas": 2, "max_pending": 10_000},
    )
    server.start()
    obs = linear_obs(state)
    server.infer(obs)
    server.kill_replica(0)
    assert _wait_until(lambda: server.slots[0].masked, timeout_s=5.0)
    # a request submitted into the dead window is parked unplaced and
    # rescued once the standby comes up
    req = server.submit(obs, deadline_s=10.0)
    np.testing.assert_allclose(server.wait(req), expected_action(state, obs), rtol=1e-5)
    assert server.slots[1].alive and server.slots[1].active
    snap = server.snapshot()
    assert snap["degraded"] and snap["fleet"]["active_device_replicas"] == 1
    assert snap["fleet"]["scale_ups"] >= 1


def test_cpu_spill_absorbs_batch_priority(make_fleet):
    server, _, state = make_fleet(
        fleet={
            "num_replicas": 1,
            "max_replicas": 1,
            "cpu_spill_replicas": 1,
            "spill_depth": 0,  # device "saturated" immediately: spill opens
        }
    )
    server.start()
    spill_index = server.config.fleet.max_replicas  # spill slots follow device slots
    obs = linear_obs(state)
    req = server.submit(obs, deadline_s=5.0, priority="batch")
    assert req.placements == [spill_index]
    np.testing.assert_allclose(server.wait(req), expected_action(state, obs), rtol=1e-5)
    assert server.router.spilled == 1
    # interactive traffic never lands on the spill tier while a device
    # replica is routable
    req = server.submit(obs, deadline_s=5.0)
    assert req.placements and req.placements[0] != spill_index
    server.wait(req)


def test_autoscale_up_under_pressure_then_down_when_idle(make_fleet):
    server, _, state = make_fleet(
        fleet={
            "num_replicas": 1,
            "min_replicas": 1,
            "max_replicas": 2,
            "max_pending": 10_000,
            "scale_up_depth": 2.0,
            "scale_down_depth": 0.5,
            "scale_patience": 1,
            "autoscale_interval_s": 0.02,
        },
        fault_injection={
            "enabled": True,
            "faults": [
                {"kind": "slow_inference", "replica": 0, "at_batch": 0, "duration_s": 0.1, "for_batches": 30}
            ],
        },
    )
    server.start()
    assert server.snapshot()["fleet"]["active_device_replicas"] == 1
    reqs = [server.submit(linear_obs(state, value=float(i)), deadline_s=30.0) for i in range(24)]
    assert _wait_until(lambda: server.scale_ups >= 1, timeout_s=5.0)
    for req in reqs:
        # the scaled-up replica (no fault) plus hedges past the latency
        # quantile drain the backlog
        server.wait(req)
    assert _wait_until(lambda: server.scale_downs >= 1, timeout_s=5.0)
    snap = server.snapshot()
    assert snap["fleet"]["scale_ups"] >= 1 and snap["fleet"]["scale_downs"] >= 1
    assert snap["fleet"]["active_device_replicas"] == 1  # back at the floor
    assert snap["failed"] == 0


# ----------------------------------------------------------------- router ----


def _pools(n, capacity=4):
    from sheeprl_tpu.serve.slots import SlotPool

    return [SlotPool(capacity=capacity, backlog_bound=64) for _ in range(n)]


def _targets(pools, healths=None, kinds=None):
    from sheeprl_tpu.serve.router import RouteTarget

    healths = healths or [1.0] * len(pools)
    kinds = kinds or ["device"] * len(pools)
    return lambda: [
        RouteTarget(i, p, h, k) for i, (p, h, k) in enumerate(zip(pools, healths, kinds))
    ]


def test_router_health_weighted_least_loaded():
    from sheeprl_tpu.serve.router import Router

    pools = _pools(3)
    now = time.monotonic()
    # pool 0 holds 2 requests, sickly pool 1 holds 1, pool 2 is empty
    for _ in range(2):
        pools[0].offer(Request(None, now, now + 60.0))
    pools[1].offer(Request(None, now, now + 60.0))
    healths = [1.0, 0.1, 1.0]
    router = Router(targets=_targets(pools, healths), max_pending=100, slo_s=0.1)
    req = router.submit(None, 60.0)
    assert req.placements == [2]  # least loaded wins outright
    # saturate pool 2: now the sick-but-emptier pool 1 (1/0.1 = 10) loses to
    # the healthy-but-busier pool 0 (2/1.0 = 2) — traffic tapers off a
    # struggling replica before the supervisor ever declares it dead
    for _ in range(3):
        pools[2].offer(Request(None, now, now + 60.0))
    req2 = router.submit(None, 60.0)
    assert req2.placements == [0]
    router.close()


def test_hedged_retry_first_completion_wins():
    """A request stuck on a silent primary is duplicated to a sibling after
    the hedge threshold; the twin's completion wins the Future and the
    loser's copy is dropped at its pool's next dispatch assembly."""
    from sheeprl_tpu.serve.router import Router
    from sheeprl_tpu.serve.slots import safe_complete

    pools = _pools(2)
    router = Router(
        targets=_targets(pools),
        max_pending=100,
        slo_s=0.02,  # few samples -> hedge threshold = max(floor, slo)
        hedge_scan_s=0.002,
    ).start()
    req = router.submit(np.float32(7.0), 60.0)
    assert req.placements == [0]
    assert _wait_until(lambda: req.hedges == 1, timeout_s=5.0)
    assert req.placements == [0, 1]
    # the sibling serves the hedge twin
    batch = pools[1].take_batch(1.0)
    assert [r.rid for r in batch] == [req.rid]
    assert safe_complete(batch[0], "served-by-1")
    pools[1].complete_batch(batch)
    assert req.future.result(timeout=1.0) == "served-by-1"
    # the loser's copy is skipped (future already done), not served dead
    assert pools[0].take_batch(0.05) == []
    assert _wait_until(lambda: router.hedged_won == 1, timeout_s=2.0)
    assert router.hedged == 1
    router.close()


def test_router_blackhole_rescued_by_scan():
    from sheeprl_tpu.serve.fault_injection import parse_serve_faults, ServeFaultSchedule
    from sheeprl_tpu.serve.router import Router

    pools = _pools(2)
    schedule = ServeFaultSchedule(
        parse_serve_faults([
            {"kind": "router_blackhole", "at_request": 0, "duration_s": 0.05}
        ])
    )
    router = Router(
        targets=_targets(pools),
        max_pending=100,
        slo_s=60.0,  # hedging out of the picture: only the rescue path moves it
        hedge_scan_s=0.002,
        fault_schedule=schedule,
    ).start()
    req = router.submit(None, 60.0)
    assert req.placements == []  # swallowed at the front door
    assert router.blackholed == 1
    assert _wait_until(lambda: req.placements != [], timeout_s=5.0)  # rescued
    assert pools[req.placements[0]].outstanding() == 1
    router.close()


def test_reroute_at_front_lands_on_healthiest_sibling():
    from sheeprl_tpu.serve.router import Router

    pools = _pools(3, capacity=2)
    now = time.monotonic()
    pools[2].offer(Request(None, now, now + 60.0))  # sibling 2 is busier
    router = Router(targets=_targets(pools), max_pending=100, slo_s=60.0)
    victims = [router.submit(None, 60.0) for _ in range(2)]
    assert all(v.placements == [1] or v.placements == [0] for v in victims)
    dead = victims[0].placements[0]
    moved = router.reroute(dead, pools[dead], "drill")
    survivors = [v for v in victims if v.placements[0] == dead]
    assert moved == len(survivors)
    for v in survivors:
        assert v.rerouted == 1 and v.placements[-1] not in (dead, 2)
    assert router.rerouted_requests == moved
    router.close()


def test_stale_incarnation_cannot_clobber_live_inflight_window():
    """A hung incarnation that wakes AFTER its window was drained and a new
    incarnation started must release nothing: in-flight tracking is
    ownership-checked per dispatch, so the live window survives a stale
    complete/requeue and stays recoverable by a later drain."""
    from sheeprl_tpu.serve.slots import SlotPool

    pool = SlotPool(capacity=2, backlog_bound=8)
    now = time.monotonic()
    a, b = Request(None, now, now + 60.0), Request(None, now, now + 60.0)
    pool.offer(a), pool.offer(b)
    stale = pool.take_batch(0.0)  # the incarnation that will hang here
    assert [r.rid for r in stale] == [a.rid, b.rid]
    drained = pool.drain()  # declared hung/dead: the fleet re-homes its window
    assert [r.rid for r in drained] == [a.rid, b.rid]
    c = Request(None, now, now + 60.0)
    pool.offer(c)
    live = pool.take_batch(0.0)  # the restarted incarnation dispatches
    assert [r.rid for r in live] == [c.rid]
    pool.complete_batch(stale)  # stale thread wakes late: releases nothing
    assert pool.outstanding() == 1
    pool.requeue_failed(stale)  # ...and requeues nothing it no longer owns
    assert pool.depth() == 0 and pool.outstanding() == 1
    assert [r.rid for r in pool.drain()] == [c.rid]  # live window recoverable


def test_drain_scopes_inflight_by_executor_liveness():
    """Re-homing a live thread's in-flight window would run non-idempotent
    requests twice, so drain scopes it: a healthy retiring replica keeps the
    whole window, a hung-but-alive one gives up only idempotent requests
    (duplication there is hedging), a confirmed-dead one gives up all."""
    from sheeprl_tpu.serve.router import RoutedRequest
    from sheeprl_tpu.serve.slots import SlotPool

    pool = SlotPool(capacity=4, backlog_bound=8)
    now = time.monotonic()
    idem = RoutedRequest(None, now, now + 60.0, idempotent=True)
    nonidem = RoutedRequest(None, now, now + 60.0, idempotent=False)
    pool.offer(idem), pool.offer(nonidem)
    assert len(pool.take_batch(0.0)) == 2
    queued = RoutedRequest(None, now, now + 60.0, idempotent=False)
    pool.offer(queued)
    assert [r.rid for r in pool.drain(inflight="none")] == [queued.rid]
    assert pool.outstanding() == 2  # the whole window stays with its executor
    assert [r.rid for r in pool.drain(inflight="idempotent")] == [idem.rid]
    assert pool.outstanding() == 1  # non-idempotent stays with its executor
    assert [r.rid for r in pool.drain()] == [nonidem.rid]
    assert pool.outstanding() == 0


def test_router_expires_unplaced_requests_at_deadline():
    """A request admitted but never placed (blackhole, full fleet) is in NO
    pool, so no pool can expire it — the scan's backstop must fail it at its
    own deadline and drop the in-flight tracking, or it leaks forever and a
    raw-future consumer hangs."""
    from sheeprl_tpu.serve.errors import DeadlineExceeded
    from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule, parse_serve_faults
    from sheeprl_tpu.serve.router import Router

    pools = _pools(2)
    schedule = ServeFaultSchedule(
        parse_serve_faults([
            {"kind": "router_blackhole", "at_request": 0, "duration_s": 30.0}
        ])
    )
    router = Router(
        targets=_targets(pools),
        max_pending=100,
        slo_s=60.0,  # hedging out of the picture: only the backstop can act
        hedge_scan_s=0.002,
        fault_schedule=schedule,
    ).start()
    req = router.submit(None, 0.05)
    assert req.placements == []
    with pytest.raises(DeadlineExceeded):
        req.future.result(timeout=5.0)
    assert _wait_until(lambda: router.inflight_count() == 0, timeout_s=5.0)
    assert router.expired == 1
    router.close()


def test_admission_bound_counts_unplaced_inflight():
    """Blackholed requests occupy no pool, so pool depth alone would let the
    router admit past ``max_pending`` for the blackhole's whole duration —
    the admission signal must include admitted-but-unplaced requests."""
    from sheeprl_tpu.serve.fault_injection import ServeFaultSchedule, parse_serve_faults
    from sheeprl_tpu.serve.router import Router

    pools = _pools(2)
    schedule = ServeFaultSchedule(
        parse_serve_faults([
            {"kind": "router_blackhole", "at_request": 0, "duration_s": 30.0}
        ])
    )
    router = Router(
        targets=_targets(pools),
        max_pending=2,
        slo_s=60.0,
        fault_schedule=schedule,
    ).start()
    for _ in range(2):
        assert router.submit(None, 60.0).placements == []
    assert router.unplaced_inflight() == 2
    with pytest.raises(Overloaded):
        router.submit(None, 60.0)
    router.close()


# ------------------------------------------------------------- chaos ramp ----


@pytest.mark.slow
def test_chaos_ramp_kill_mid_ramp_holds_slo_on_survivors(make_fleet):
    """The headline drill: a 4-replica fleet under a stepped saturation
    ramp; one replica is killed as the second step begins. Zero admitted
    requests are dropped or expired, the ramp still finds a knee, and the
    surviving N-1 fleet holds the SLO at the knee."""
    from sheeprl_tpu.serve.config import LoadConfig
    from sheeprl_tpu.serve.loadgen import run_ramp

    server, _, state = make_fleet(
        slo_ms=500.0,
        max_restarts=0,  # the dead replica stays dead: survivors own the SLO
        restart_refund_s=None,
        fleet={
            # min == num == max: the elasticity is pinned out of the drill —
            # this one measures crash resilience on a fixed fleet
            "num_replicas": 4,
            "min_replicas": 4,
            "max_replicas": 4,
            "max_pending": 10_000,
        },
    )
    server.start()
    assert server.snapshot()["replicas_alive"] == 4
    killed = []

    def on_step(step, rate):
        if step == 1:
            killed.append(server.kill_replica(0))

    report = run_ramp(
        server,
        LoadConfig(enabled=True, duration_s=1.0, concurrency=8, max_retries=5, seed=0),
        rates_hz=[60.0, 100.0, 160.0],
        step_duration_s=0.6,
        on_step=on_step,
    )
    assert killed == [True]
    total_expired = sum(s["expired"] for s in report["steps"])
    total_errors = sum(s["errors"] for s in report["steps"])
    assert total_expired == 0 and total_errors == 0  # zero dropped admitted
    assert report["knee_rate_hz"] is not None and report["max_good_qps"] > 0
    snap = server.snapshot()
    assert snap["replicas_alive"] == 3  # survivors, no restart budget
    assert snap["shed_expired"] == 0 and snap["failed"] == 0
    assert snap["p95_ms"] is not None and snap["p95_ms"] <= server.config.slo_ms
