"""Serving-tier AOT executable cache drills (ISSUE 17): a second server boot
deserializes the whole batch ladder instead of compiling it, hot swap
re-populates missing cache entries before the gauntlet flips versions, a
fleet reboot loads every per-device ladder from cache, and the slow
autoscale-under-spike drill proves a scale-up replica becomes routable from
a cached executable while the fleet holds the SLO with zero dropped admitted
requests."""

import glob
import os
import time

import numpy as np
import pytest

from .conftest import commit_linear, expected_action, linear_obs

# These drills only run hermetically: tests/test_serve/test_aotcache_hermetic.py
# spawns a fresh interpreter (persistent trace cache OFF from the first compile)
# and re-runs this file with the marker env var set. In a shared suite process
# they are structurally unsound: any executable DESERIALIZED from the warm
# cross-run trace cache — even a module-level ``PRNGKey(0)`` constant compiled
# during collection — registers its kernel symbols process-wide, and later
# fresh compiles that reuse a same-named kernel (the fusion names are generic,
# e.g. ``dot_add_fusion``) serialize WITHOUT embedding it and can never be
# loaded back ("Symbols not found"). AotCache's store-time verification then
# rightly refuses every store. Nothing can undo a deserialize that already
# happened, and the cache's enabled/dir state latches process-wide at the
# first compile — a fresh child process is the only clean room.
pytestmark = [
    pytest.mark.serve,
    pytest.mark.skipif(
        not os.environ.get("SHEEPRL_TPU_AOT_HERMETIC"),
        reason="AOT round-trip drills run in a hermetic child via test_aotcache_hermetic.py",
    ),
]


@pytest.fixture(autouse=True)
def _real_compiles():
    """Belt-and-suspenders for direct runs of this file: disable the XLA
    persistent trace cache (tests/conftest.py) so a trace-cache HIT cannot
    hand these drills an executable whose serialized payload is unloadable
    (CPU backend, "Symbols not found"). The hermetic child already strips
    the cache via SHEEPRL_TPU_NO_COMPILE_CACHE=1; see the module docstring
    for why a shared warm-cache process can still poison same-named kernels
    in ways this fixture cannot undo."""
    import jax

    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _wait_until(predicate, timeout_s=5.0, interval_s=0.005):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def _entries(cache_dir):
    return sorted(glob.glob(os.path.join(str(cache_dir), "*.aotx")))


def test_second_server_boot_deserializes_ladder(make_server, tmp_path):
    cache_dir = tmp_path / "aotcache"
    cold, _, state = make_server(aot_cache_dir=str(cache_dir))
    cold.start()
    obs = linear_obs(state)
    np.testing.assert_allclose(cold.infer(obs), expected_action(state, obs), rtol=1e-5)
    snap = cold.snapshot()
    assert snap["ladder_from_cache"] == {1: False, 2: False, 4: False}
    assert snap["aot_cache"]["misses"] == 3 and snap["aot_cache"]["hits"] == 0
    cold.close()  # drains the async writer: all three rungs committed
    assert len(_entries(cache_dir)) == 3

    warm, _, state = make_server(aot_cache_dir=str(cache_dir))
    warm.start()
    snap = warm.snapshot()
    assert snap["ladder_from_cache"] == {1: True, 2: True, 4: True}
    assert snap["aot_cache"] == {"hits": 3, "misses": 0, "stores": 0, "errors": 0}
    np.testing.assert_allclose(warm.infer(obs), expected_action(state, obs), rtol=1e-5)


def test_hot_swap_prewarms_missing_entries(make_server, tmp_path):
    """Entries GC'd between boot and swap (cleaned cache volume): the swap
    gauntlet re-populates them synchronously before the flip, so the NEXT
    boot still cold-starts from cache."""
    cache_dir = tmp_path / "aotcache"
    server, ckpt_dir, state = make_server(aot_cache_dir=str(cache_dir))
    server.start()
    server.aot_cache.flush()
    assert len(_entries(cache_dir)) == 3
    for path in _entries(cache_dir):
        os.remove(path)

    path2, state2 = commit_linear(ckpt_dir, 200, seed=1)
    version = server.request_swap(path2)
    assert version.step == 200
    # prewarm ran inside the swap: the structurally-identical entries are back
    assert len(_entries(cache_dir)) == 3
    obs = linear_obs(state2)
    np.testing.assert_allclose(server.infer(obs), expected_action(state2, obs), rtol=1e-5)


def test_fleet_reboot_loads_every_ladder_from_cache(make_fleet, tmp_path):
    cache_dir = tmp_path / "aotcache"
    cold, _, state = make_fleet(aot_cache_dir=str(cache_dir))
    cold.start()
    obs = linear_obs(state)
    np.testing.assert_allclose(cold.wait(cold.submit(obs, deadline_s=10.0)), expected_action(state, obs), rtol=1e-5)
    cold.close()
    assert _entries(cache_dir)  # base + per-device ladders committed

    warm, _, state = make_fleet(aot_cache_dir=str(cache_dir))
    warm.start()
    snap = warm.snapshot()
    assert snap["aot_cache"]["misses"] == 0 and snap["aot_cache"]["hits"] > 0
    assert snap["ladder_from_cache"] and all(
        rungs and all(rungs.values()) for rungs in snap["ladder_from_cache"].values()
    )
    np.testing.assert_allclose(warm.wait(warm.submit(obs, deadline_s=10.0)), expected_action(state, obs), rtol=1e-5)


@pytest.mark.slow
@pytest.mark.fleet
def test_autoscale_spike_scales_up_from_cache_holding_slo(make_fleet, tmp_path):
    """The ISSUE 17 drill: a load spike forces a scale-up and the new
    replica's ladder comes from the cache (populated by an earlier
    full-fleet boot), p95 stays within the SLO and zero admitted requests
    are dropped."""
    cache_dir = tmp_path / "aotcache"
    # boot the full fleet once to populate every device's entries (the
    # steady-state a long-running service reaches before any preemption)
    seed_fleet, _, state = make_fleet(
        aot_cache_dir=str(cache_dir),
        fleet={"num_replicas": 2, "min_replicas": 2, "max_replicas": 2},
    )
    seed_fleet.start()
    seed_fleet.close()
    assert _entries(cache_dir)

    server, _, state = make_fleet(
        slo_ms=1000.0,
        aot_cache_dir=str(cache_dir),
        fleet={
            "num_replicas": 1,
            "min_replicas": 1,
            "max_replicas": 2,
            "max_pending": 10_000,
            "scale_up_depth": 2.0,
            "scale_down_depth": 0.0,  # never scale back down mid-drill
            "scale_patience": 1,
            "autoscale_interval_s": 0.02,
        },
        fault_injection={
            "enabled": True,
            "faults": [
                # the spike: the only active replica turns slow, queue depth
                # crosses scale_up_depth, the autoscaler activates a standby
                {"kind": "slow_inference", "replica": 0, "at_batch": 0, "duration_s": 0.08, "for_batches": 30}
            ],
        },
    )
    server.start()
    assert server.snapshot()["fleet"]["active_device_replicas"] == 1
    # stepped ramp: three widening waves of admitted traffic
    reqs = []
    for wave in (8, 16, 24):
        reqs += [server.submit(linear_obs(state, value=float(i)), deadline_s=30.0) for i in range(wave)]
        time.sleep(0.05)
    assert _wait_until(lambda: server.scale_ups >= 1, timeout_s=10.0)
    for req in reqs:
        server.wait(req)

    snap = server.snapshot()
    assert snap["fleet"]["scale_ups"] >= 1
    # the scaled-up replica (and everything else) deserialized its ladder:
    # the spike never paid a compile
    assert snap["aot_cache"]["misses"] == 0 and snap["aot_cache"]["hits"] > 0
    assert snap["ladder_from_cache"] and all(
        rungs and all(rungs.values()) for rungs in snap["ladder_from_cache"].values()
    )
    # SLO held, zero dropped admitted requests
    assert snap["failed"] == 0 and snap["shed_expired"] == 0
    assert snap["p95_ms"] is not None and snap["p95_ms"] <= server.config.slo_ms
