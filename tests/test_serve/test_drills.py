"""Serving-tier fault drills (ISSUE acceptance): AOT warmup before traffic,
slow-inference overload -> bounded queue + typed shedding, replica crash ->
restart with no lost request, budget exhaustion -> masked/degraded, all
masked -> deadline-bounded failure, hot swap with zero dropped in-flight
requests, poisoned/torn/mismatched swap rejection, and rollback."""

import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.errors import DeadlineExceeded, Overloaded, ServeError, SwapRejected

from .conftest import commit_linear, expected_action, linear_obs

pytestmark = pytest.mark.serve


def _wait_until(predicate, timeout_s=5.0, interval_s=0.01):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()


def test_warmup_before_traffic_and_correct_actions(make_server):
    server, _, state = make_server()
    with pytest.raises(ServeError):
        server.submit(linear_obs(state))  # no traffic before warmup
    server.start()
    # every rung was AOT-compiled during start()
    assert sorted(server.warmup_s) == [1, 2, 4]
    assert all(dt >= 0 for dt in server.warmup_s.values())
    obs = linear_obs(state, value=0.5)
    out = server.infer(obs)
    np.testing.assert_allclose(out, expected_action(state, obs), rtol=1e-5)
    snap = server.snapshot()
    assert snap["completed"] == 1 and snap["submitted"] == 1
    assert snap["serving_step"] == 100
    assert snap["replicas_alive"] == 2 and not snap["degraded"]


def test_concurrent_requests_coalesce_into_batches(make_server):
    server, _, state = make_server(num_replicas=1, gather_window_ms=20.0)
    server.start()
    results, errors = [], []

    def one():
        try:
            results.append(server.infer(linear_obs(state)))
        except Exception as err:  # noqa: BLE001 — drill collects everything
            errors.append(err)

    threads = [threading.Thread(target=one) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(5.0)
    assert not errors and len(results) == 8
    snap = server.snapshot()
    assert snap["batches"] < 8  # some requests rode a shared rung
    assert snap["mean_batch"] > 1.0


def test_slow_inference_drill_bounded_queue_and_typed_shedding(make_server):
    """The overload drill: one replica held slow by fault injection, a burst
    of submits -> the queue never grows past its bound, extras are rejected
    with a typed Overloaded immediately (not queued to time out), and
    in-flight requests still fail by their own deadline, never hang."""
    server, _, state = make_server(
        num_replicas=1,
        max_queue=3,
        slo_ms=50.0,
        fault_injection={
            "enabled": True,
            "faults": [
                {"kind": "slow_inference", "replica": 0, "at_batch": 0, "duration_s": 0.15, "for_batches": 200}
            ],
        },
    )
    server.start()
    obs = linear_obs(state)
    overloads, admitted = 0, []
    for _ in range(30):
        t0 = time.monotonic()
        try:
            admitted.append(server.submit(obs, deadline_s=0.4))
        except Overloaded as err:
            overloads += 1
            assert err.depth >= err.bound == 3
            assert err.retry_after_s > 0
        # shed or admitted, the submit path never blocks
        assert time.monotonic() - t0 < 0.1
        assert server.batcher.depth() <= 3
    assert overloads > 0, "the bounded queue never shed under a slow replica"
    # admitted requests resolve by their deadline: served or DeadlineExceeded
    t0 = time.monotonic()
    outcomes = []
    for req in admitted:
        try:
            outcomes.append(server.wait(req))
        except DeadlineExceeded:
            outcomes.append("expired")
    assert time.monotonic() - t0 < 5.0  # bounded, not hung
    snap = server.snapshot()
    assert snap["shed_overloaded"] == overloads
    assert snap["shed_overloaded"] + snap["shed_expired"] > 0


def test_replica_crash_restart_serves_requeued_request(make_server):
    """Crash drill: the injected crash requeues the batch first, the
    supervisor restarts the replica under budget, and the SAME request is
    served by the next incarnation — nothing dropped."""
    server, _, state = make_server(
        num_replicas=1,
        slo_ms=500.0,
        fault_injection={
            "enabled": True,
            "faults": [{"kind": "replica_crash", "replica": 0, "at_batch": 1}],
        },
    )
    server.start()
    obs = linear_obs(state, value=2.0)
    np.testing.assert_allclose(server.infer(obs), expected_action(state, obs), rtol=1e-5)  # batch 0
    out = server.infer(obs)  # batch 1 crashes mid-flight; restart re-serves it
    np.testing.assert_allclose(out, expected_action(state, obs), rtol=1e-5)
    assert _wait_until(lambda: server.replicas.total_restarts == 1)
    snap = server.snapshot()
    assert snap["restarts"] == 1 and not snap["degraded"]
    assert snap["events"].get("replica_restart") == 1


def test_budget_exhausted_masks_slot_and_serves_degraded(make_server):
    """Repeated crashes exhaust the slot's restart budget: the slot is
    masked (not restarted forever), the server keeps serving on N-1 and
    reports degraded mode."""
    server, _, state = make_server(
        num_replicas=2,
        max_restarts=1,
        restart_refund_s=None,
        slo_ms=500.0,
        fault_injection={
            "enabled": True,
            "faults": [
                {"kind": "replica_crash", "replica": 0, "at_batch": 0},
                {"kind": "replica_crash", "replica": 0, "at_batch": 1},
            ],
        },
    )
    server.start()
    obs = linear_obs(state)

    def drive():
        try:
            server.infer(obs, deadline_s=0.5)
        except ServeError:
            pass

    assert _wait_until(lambda: (drive(), server.replicas.masked_count == 1)[-1], timeout_s=10.0)
    snap = server.snapshot()
    assert snap["replicas_masked"] == 1 and snap["degraded"]
    assert snap["events"].get("replica_masked") == 1
    # the surviving replica still serves correctly
    np.testing.assert_allclose(server.infer(obs), expected_action(state, obs), rtol=1e-5)


def test_all_masked_fails_by_deadline_not_hang(make_server):
    """With every slot masked the server stays up and requests fail by
    their own deadline — the typed failure clients can reason about."""
    server, _, state = make_server(
        num_replicas=1,
        max_restarts=0,  # first fault masks immediately
        fault_injection={
            "enabled": True,
            "faults": [{"kind": "replica_crash", "replica": 0, "at_batch": 0}],
        },
    )
    server.start()
    obs = linear_obs(state)
    try:  # triggers the crash; may or may not be re-served before the mask
        server.infer(obs, deadline_s=0.3)
    except ServeError:
        pass
    assert _wait_until(lambda: server.replicas.all_masked)
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        server.infer(obs, deadline_s=0.3)
    assert time.monotonic() - t0 < 2.0  # bounded by the deadline, not hung


def test_hot_swap_zero_dropped_in_flight(make_server):
    """Swap drill: continuous traffic while a newer committed checkpoint is
    promoted — zero failed requests, and every response matches either the
    old or the new params (never garbage)."""
    server, ckpt_dir, state = make_server(num_replicas=2, slo_ms=500.0)
    server.start()
    new_path, new_state = commit_linear(ckpt_dir, 200, seed=7)
    obs = linear_obs(state, value=1.0)
    old_expected = expected_action(state, obs)
    new_expected = expected_action(new_state, obs)

    stop = threading.Event()
    failures, outputs = [], []

    def traffic():
        while not stop.is_set():
            try:
                outputs.append(server.infer(obs))
            except Exception as err:  # noqa: BLE001 — the drill counts everything
                failures.append(err)

    threads = [threading.Thread(target=traffic) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.1)  # in-flight traffic established
    promoted = server.request_swap(new_path)
    time.sleep(0.1)  # post-swap traffic
    stop.set()
    for t in threads:
        t.join(5.0)

    assert promoted.step == 200
    assert not failures, f"hot swap dropped in-flight requests: {failures[:3]}"
    assert outputs
    for out in outputs:  # every answer came from a real version
        assert np.allclose(out, old_expected, rtol=1e-5) or np.allclose(out, new_expected, rtol=1e-5)
    assert any(np.allclose(out, new_expected, rtol=1e-5) for out in outputs[-4:])
    snap = server.snapshot()
    assert snap["serving_step"] == 200 and snap["swaps"] == 1
    assert snap["events"].get("swap") == 1


def test_swap_watcher_promotes_newer_commit(make_server):
    server, ckpt_dir, state = make_server(swap_poll_s=0.02)
    server.start()
    assert server.snapshot()["serving_step"] == 100
    commit_linear(ckpt_dir, 300, seed=3)
    assert _wait_until(lambda: server.snapshot()["serving_step"] == 300)


def test_poisoned_swap_rejected_then_clean_retry_promotes(make_server):
    """Poison drill: the first swap attempt has its loaded weights
    NaN-poisoned by fault injection — validation must refuse it and keep the
    old version serving; the second (clean) attempt promotes."""
    server, ckpt_dir, state = make_server(
        fault_injection={"enabled": True, "faults": [{"kind": "poison_swap", "at_swap": 1}]},
    )
    server.start()
    new_path, new_state = commit_linear(ckpt_dir, 200, seed=7)
    with pytest.raises(SwapRejected, match="non-finite"):
        server.request_swap(new_path)
    snap = server.snapshot()
    assert snap["serving_step"] == 100 and snap["swap_rejects"] == 1 and snap["swaps"] == 0
    assert snap["events"].get("swap_rejected") == 1
    obs = linear_obs(state)
    np.testing.assert_allclose(server.infer(obs), expected_action(state, obs), rtol=1e-5)
    # the fault fired once; the same checkpoint now passes validation
    assert server.request_swap(new_path).step == 200
    assert server.snapshot()["serving_step"] == 200


def test_torn_checkpoint_refused(make_server, tmp_path):
    server, ckpt_dir, _ = make_server()
    server.start()
    import pickle

    torn = str(tmp_path / "checkpoint" / "ckpt_999_0.ckpt")
    with open(torn, "wb") as f:
        pickle.dump({"agent": {}}, f)  # payload without a commit manifest
    with pytest.raises(SwapRejected, match="manifest"):
        server.request_swap(torn)
    assert server.snapshot()["serving_step"] == 100


def test_structure_mismatch_rejected(make_server):
    from sheeprl_tpu.serve.policy import make_linear_state

    server, ckpt_dir, _ = make_server()
    server.start()
    bad_path, _ = commit_linear(ckpt_dir, 400, state=make_linear_state(in_dim=9))
    with pytest.raises(SwapRejected, match="structure|shape"):
        server.request_swap(bad_path)
    snap = server.snapshot()
    assert snap["serving_step"] == 100 and snap["swap_rejects"] == 1


def test_rollback_restores_previous_version(make_server):
    server, ckpt_dir, state = make_server()
    server.start()
    new_path, new_state = commit_linear(ckpt_dir, 200, seed=7)
    server.request_swap(new_path)
    assert server.snapshot()["serving_step"] == 200
    restored = server.store.rollback()
    assert restored.step == 100
    snap = server.snapshot()
    assert snap["serving_step"] == 100 and snap["rollbacks"] == 1
    assert snap["events"].get("rollback") == 1
    obs = linear_obs(state)
    np.testing.assert_allclose(server.infer(obs), expected_action(state, obs), rtol=1e-5)
