"""CLI acceptance (`python -m sheeprl_tpu serve` == cli_serve.serving): load
a committed checkpoint by manifest, AOT-warm the ladder, run the scripted
load generator, and have `bench.py --serve-stats` digest the telemetry — plus
the torn-checkpoint refusal and bench's targeted degradation."""

import json
import os
import sys

import pytest
import yaml

from sheeprl_tpu.serve.errors import SwapRejected

from .conftest import commit_linear

pytestmark = pytest.mark.serve

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _bench():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def _serve_run(tmp_path, step=100):
    """A run directory the serve CLI can consume: config.yaml + a committed
    linear checkpoint under <run>/checkpoint/."""
    run_dir = tmp_path / "run"
    run_dir.mkdir(parents=True, exist_ok=True)
    cfg = {
        "algo": {"name": "linear"},
        "seed": 42,
        "metric": {"telemetry": {"enabled": True, "poll_interval": 0.0}},
    }
    with open(run_dir / "config.yaml", "w") as f:
        yaml.safe_dump(cfg, f)
    path, state = commit_linear(str(run_dir / "checkpoint"), step)
    return run_dir, path, state


def _parse_serve_stats(stdout: str) -> dict:
    payload = stdout[stdout.index('{\n  "serve_stats"') :]
    return json.loads(payload)["serve_stats"]


def test_cli_acceptance_load_run_meets_slo_and_bench_reads_it(tmp_path, capsys, monkeypatch):
    """The ISSUE acceptance path: serve a committed checkpoint, AOT-warm,
    drive the load generator, sustain QPS with p95 <= SLO on CPU, and read
    the same numbers back through bench.py --serve-stats."""
    from sheeprl_tpu.cli_serve import serving

    run_dir, ckpt_path, _ = _serve_run(tmp_path)
    monkeypatch.chdir(tmp_path)
    serving(
        [
            f"checkpoint_path={ckpt_path}",
            "serve.slo_ms=150",
            "serve.num_replicas=2",
            "serve.load.enabled=true",
            "serve.load.duration_s=1.0",
            "serve.load.concurrency=4",
        ]
    )
    out = capsys.readouterr().out
    assert "serving linear step=100" in out
    assert "AOT ladder warmed" in out
    snap = _parse_serve_stats(out)
    report = snap["load_report"]
    assert report["ok"] > 0 and report["qps"] > 0
    assert report["p95_ms"] is not None and report["p95_ms"] <= 150.0
    assert report["slo_met"] is True
    assert snap["completed"] >= report["ok"]
    # every rung of the default ladder was AOT-warmed before traffic
    assert sorted(int(k) for k in snap["warmup_s"]) == [1, 2, 4, 8]

    # bench reads the run's own telemetry stream — no log scraping
    jsonl = str(run_dir / "telemetry.jsonl")
    stats = _bench().serve_stats(jsonl)
    assert "error" not in stats
    assert stats["totals"]["completed"] == snap["completed"]
    assert stats["load_report"]["ok"] == report["ok"]
    assert stats["slo_met"] is True


def test_cli_serves_newest_commit_from_ckpt_dir(tmp_path, capsys, monkeypatch):
    from sheeprl_tpu.cli_serve import serving

    run_dir, _, _ = _serve_run(tmp_path, step=100)
    commit_linear(str(run_dir / "checkpoint"), 250, seed=5)
    monkeypatch.chdir(tmp_path)
    serving(
        [
            f"ckpt_dir={run_dir / 'checkpoint'}",
            "serve.load.enabled=true",
            "serve.load.duration_s=0.2",
            "serve.load.concurrency=2",
        ]
    )
    out = capsys.readouterr().out
    assert "serving linear step=250" in out
    assert _parse_serve_stats(out)["serving_step"] == 250


def test_cli_refuses_torn_checkpoint(tmp_path):
    from sheeprl_tpu.cli_serve import serving

    run_dir, _, _ = _serve_run(tmp_path)
    torn = str(run_dir / "checkpoint" / "ckpt_999_0.ckpt")
    with open(torn, "wb") as f:
        f.write(b"half a checkpoint")
    with pytest.raises(SwapRejected, match="manifest"):
        serving([f"checkpoint_path={torn}"])


def test_cli_requires_a_source():
    from sheeprl_tpu.cli_serve import serving

    with pytest.raises(ValueError, match="checkpoint_path"):
        serving(["serve.slo_ms=50"])


def test_bench_serve_stats_degrades_with_targeted_errors(tmp_path):
    bench = _bench()
    missing = bench.serve_stats(str(tmp_path / "nope.jsonl"))
    assert "cannot read telemetry stream" in missing["error"]
    # a training-run stream without serve activity: targeted message, no dump
    stream = tmp_path / "telemetry.jsonl"
    with open(stream, "w") as f:
        f.write(json.dumps({"event": "run_start"}) + "\n")
        f.write(json.dumps({"event": "run_end", "preemptions": 0}) + "\n")
    empty = bench.serve_stats(str(stream))
    assert "no serve telemetry" in empty["error"]


@pytest.mark.slow
def test_load_drill_open_loop_sheds_and_clients_back_off(tmp_path):
    """The full load drill (slow tier): open-loop traffic over capacity
    against a deliberately slowed single replica — admission control sheds,
    clients retry with backoff, and the report accounts for every request."""
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.loadgen import run_load
    from sheeprl_tpu.serve.policy import build_linear_policy, make_linear_state
    from sheeprl_tpu.serve.server import PolicyServer

    ckpt_dir = str(tmp_path / "checkpoint")
    path, state = commit_linear(ckpt_dir, 100)
    cfg = serve_config_from_cfg(
        {
            "serve": {
                "batch_ladder": [1, 2, 4],
                "slo_ms": 50.0,
                # generous server-side deadline: admitted work still succeeds,
                # so the drill isolates admission-control shedding
                "default_deadline_ms": 2000.0,
                "max_queue": 4,
                "num_replicas": 1,
                "monitor_interval_s": 0.01,
                "fault_injection": {
                    "enabled": True,
                    "faults": [
                        {
                            "kind": "slow_inference",
                            "replica": 0,
                            "at_batch": 0,
                            "duration_s": 0.1,
                            "for_batches": 100000,
                        }
                    ],
                },
                "load": {
                    "enabled": True,
                    "duration_s": 3.0,
                    "concurrency": 16,
                    "rate_hz": 1000.0,  # far over the ~40 req/s slowed capacity
                    "max_retries": 2,
                    "seed": 0,
                },
            }
        }
    )
    policy = build_linear_policy({"algo": {"name": "linear"}}, state)
    server = PolicyServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir)
    try:
        server.start()
        report = run_load(server, cfg.load)
    finally:
        server.close()
    assert report["mode"] == "open-loop"
    assert report["ok"] > 0  # the slowed replica still serves
    assert report["shed"] > 0, "over-capacity open-loop traffic must shed"
    assert report["client_rejections"] > 0
    assert report["client_retries"] > 0, "clients must back off and retry, not just fail"
    snap = server.snapshot()
    assert snap["shed_overloaded"] > 0
    assert snap["queue_depth"] <= cfg.max_queue
