"""Ordering property tests for the continuous-batching slot pool and the
crash re-route path (seeded randomized trials — the repo carries no
hypothesis dependency, so each property runs across many seeded
interleavings instead).

The two contracts under test (sheeprl_tpu/serve/slots.py docstring):

1. **admission order is dispatch order** — within a pool, an admitted
   request is never reordered behind a later admission, across any
   interleaving of offers, dispatches and completions.
2. **re-route-at-front** — when a replica dies mid-flight, its drained work
   (in-flight window first, admission order preserved) lands AHEAD of the
   surviving pool's backlog: no admitted request is dropped, none is
   duplicated, neither pool's internal admission order is disturbed, and no
   request is expired by a crash it didn't cause.
"""

import random
import time

import numpy as np
import pytest

from sheeprl_tpu.serve.batching import Request
from sheeprl_tpu.serve.slots import SlotPool, safe_complete

pytestmark = [pytest.mark.serve, pytest.mark.fleet]

FAR = 3600.0  # deadlines far enough that only a bug could expire a request


def _req():
    now = time.monotonic()
    return Request(None, now, now + FAR)


def _dispatch_all(pool):
    """Serve the pool dry, returning the request dispatch order."""
    order = []
    while pool.depth() or pool.outstanding():
        batch = pool.take_batch(0.0)
        if not batch:
            break
        order.extend(batch)
        for req in batch:
            safe_complete(req, "ok")
        pool.complete_batch(batch)
    return order


def test_admission_order_is_dispatch_order_across_interleavings():
    for seed in range(25):
        rng = random.Random(seed)
        pool = SlotPool(capacity=rng.choice([1, 2, 4]), backlog_bound=128)
        admitted, dispatched = [], []
        for _ in range(rng.randrange(20, 60)):
            if rng.random() < 0.6:
                req = _req()
                assert pool.offer(req)
                admitted.append(req)
            else:
                batch = pool.take_batch(0.0)
                dispatched.extend(batch)
                for req in batch:
                    safe_complete(req, "ok")
                pool.complete_batch(batch)
        dispatched.extend(_dispatch_all(pool))
        assert [r.rid for r in dispatched] == [r.rid for r in admitted], f"seed {seed}"
        assert all(r.future.result(timeout=0) == "ok" for r in admitted)
        pool.close()


def test_staging_survives_admission_during_inflight_batch():
    """Continuous batching admits into slots while the previous dispatch
    still holds its staged rows — the pool must stage BOTH windows at once
    (regression: rows sized to the slot window alone left mid-flight
    admissions row-less, and the next dispatch assembly blew up, turning
    sustained load into an inference-failure storm)."""
    import jax

    spec = {"vector": jax.ShapeDtypeStruct((3,), np.float32)}
    for seed in range(25):
        rng = random.Random(seed)
        cap = rng.choice([1, 2, 4])
        pool = SlotPool(capacity=cap, backlog_bound=64, obs_spec=spec)
        value = {}

        def req():
            now = time.monotonic()
            r = Request(
                {"vector": np.full((3,), float(len(value)), np.float32)}, now, now + FAR
            )
            value[r.rid] = float(len(value))
            return r

        inflight = []
        for _ in range(rng.randrange(20, 60)):
            roll = rng.random()
            if roll < 0.55:
                pool.offer(req())
            elif roll < 0.8 and not inflight:
                inflight = pool.take_batch(0.0)
            elif inflight:
                # assemble while later admissions sit staged in the slots
                staged = pool.staged_batch(inflight, cap)
                got = staged["vector"][: len(inflight), 0]
                want = [value[r.rid] for r in inflight]
                assert got.tolist() == want, f"seed {seed}: staged rows corrupt"
                for r in inflight:
                    safe_complete(r, "ok")
                pool.complete_batch(inflight)
                inflight = []
        while inflight or pool.depth():
            if not inflight:
                inflight = pool.take_batch(0.0)
            staged = pool.staged_batch(inflight, cap)
            got = staged["vector"][: len(inflight), 0]
            assert got.tolist() == [value[r.rid] for r in inflight], f"seed {seed}"
            for r in inflight:
                safe_complete(r, "ok")
            pool.complete_batch(inflight)
            inflight = []
        pool.close()


def test_crash_reroute_at_front_never_reorders_drops_or_expires():
    for seed in range(25):
        rng = random.Random(seed)
        pool_a = SlotPool(capacity=rng.choice([2, 4]), backlog_bound=128)
        pool_b = SlotPool(capacity=rng.choice([2, 4]), backlog_bound=128)
        admitted = {id(pool_a): [], id(pool_b): []}
        # phase 1: random admissions to both pools, occasional dispatches on
        # B, and A "takes a batch" it will never finish (the in-flight window
        # a crash strands)
        dispatched_b = []
        for _ in range(rng.randrange(10, 40)):
            pool = rng.choice([pool_a, pool_b])
            req = _req()
            assert pool.offer(req)
            admitted[id(pool)].append(req)
            if rng.random() < 0.2:
                batch = pool_b.take_batch(0.0)
                dispatched_b.extend(batch)
                for r in batch:
                    safe_complete(r, "ok")
                pool_b.complete_batch(batch)
        stranded = pool_a.take_batch(0.0)  # A dies holding this window

        # phase 2: the crash — drain A (in-flight first, admission order) and
        # plant the block at the front of B, ahead of B's backlog
        drained = pool_a.drain()
        assert [r.rid for r in drained] == [r.rid for r in admitted[id(pool_a)]], (
            f"seed {seed}: drain lost admission order (in-flight window "
            f"{[r.rid for r in stranded]})"
        )
        pool_b.offer_front(drained)

        # phase 3: post-crash admissions to the survivor only
        post = []
        for _ in range(rng.randrange(0, 15)):
            req = _req()
            assert pool_b.offer(req)
            post.append(req)

        order = dispatched_b + _dispatch_all(pool_b)
        rids = [r.rid for r in order]

        # zero dropped, zero duplicated: every admitted request dispatched once
        everything = admitted[id(pool_a)] + admitted[id(pool_b)] + post
        assert sorted(rids) == sorted(r.rid for r in everything), f"seed {seed}"
        # per-source admission order survives the re-route
        for source in (admitted[id(pool_a)], admitted[id(pool_b)], post):
            want = [r.rid for r in source]
            assert [rid for rid in rids if rid in set(want)] == want, f"seed {seed}"
        # the re-routed block went AHEAD of B's backlog: every A request
        # dispatches before every post-crash admission
        if admitted[id(pool_a)] and post:
            last_a = max(rids.index(r.rid) for r in admitted[id(pool_a)])
            first_post = min(rids.index(r.rid) for r in post)
            assert last_a < first_post, f"seed {seed}: re-route fell behind later admissions"
        # nothing expired: a crash-induced re-route must not cost a request
        # its deadline (all deadlines are an hour out)
        for req in everything:
            assert req.future.result(timeout=0) == "ok", f"seed {seed}: rid {req.rid} expired"
        pool_a.close()
        pool_b.close()
