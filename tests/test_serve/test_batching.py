"""MicroBatcher units: bounded admission with typed shedding, SLO-derived
gather window, deadline expiry at assembly, requeue-bypasses-admission, and
fail-everything-on-close. jax-free (the batcher never touches the model)."""

import threading
import time

import pytest

from sheeprl_tpu.serve.batching import MicroBatcher
from sheeprl_tpu.serve.errors import DeadlineExceeded, Overloaded, ServerClosed

pytestmark = pytest.mark.serve


def _batcher(max_queue=4, window=0.01, on_shed=None, clock=None):
    kw = {"max_queue": max_queue, "gather_window_s": window, "on_shed": on_shed}
    if clock is not None:
        kw["clock"] = clock
    return MicroBatcher(**kw)


def test_admission_bound_sheds_typed_and_immediately():
    shed = []
    b = _batcher(max_queue=2, on_shed=shed.append)
    b.submit({"x": 1}, deadline_s=10.0)
    b.submit({"x": 2}, deadline_s=10.0)
    t0 = time.monotonic()
    with pytest.raises(Overloaded) as err:
        b.submit({"x": 3}, deadline_s=10.0)
    # shedding is a rejection at admission, not a blocking wait
    assert time.monotonic() - t0 < 0.1
    assert err.value.depth == 2 and err.value.bound == 2
    assert err.value.retry_after_s > 0
    assert shed == ["overloaded"]
    assert b.depth() == 2  # nothing was enqueued for the shed request


def test_next_batch_coalesces_up_to_max_within_window():
    b = _batcher(max_queue=16, window=0.02)
    for i in range(3):
        b.submit({"x": i}, deadline_s=10.0)
    batch = b.next_batch(max_batch=8, wait_timeout_s=0.5)
    assert [r.obs["x"] for r in batch] == [0, 1, 2]
    # an empty queue returns [] on timeout so replica loops can heartbeat
    assert b.next_batch(max_batch=8, wait_timeout_s=0.01) == []


def test_next_batch_closes_at_top_rung_without_waiting_out_the_window():
    b = _batcher(max_queue=16, window=30.0)  # pathological window
    for i in range(4):
        b.submit({"x": i}, deadline_s=10.0)
    t0 = time.monotonic()
    batch = b.next_batch(max_batch=4, wait_timeout_s=0.5)
    assert len(batch) == 4
    assert time.monotonic() - t0 < 1.0  # full rung: no window wait
    assert b.depth() == 0


def test_expired_requests_fail_at_assembly_and_never_reach_the_model():
    shed = []
    b = _batcher(max_queue=8, on_shed=shed.append)
    dead = b.submit({"x": 0}, deadline_s=0.0)  # already expired
    live = b.submit({"x": 1}, deadline_s=10.0)
    batch = b.next_batch(max_batch=8, wait_timeout_s=0.5)
    assert [r.rid for r in batch] == [live.rid]
    with pytest.raises(DeadlineExceeded):
        dead.future.result(timeout=0)
    assert shed == ["expired"]


def test_requeue_front_of_queue_bypasses_admission_and_fails_expired():
    shed = []
    b = _batcher(max_queue=2, on_shed=shed.append)
    first = b.submit({"x": 0}, deadline_s=10.0)
    second = b.submit({"x": 1}, deadline_s=10.0)
    batch = b.next_batch(max_batch=8, wait_timeout_s=0.5)
    assert len(batch) == 2
    # fill the queue back to its bound, then requeue the failed batch: the
    # already-admitted requests MUST go back (no shedding of in-flight work)
    b.submit({"x": 2}, deadline_s=10.0)
    b.submit({"x": 3}, deadline_s=10.0)
    b.requeue(batch)
    assert b.depth() == 4  # above the admission bound, by design
    nxt = b.next_batch(max_batch=8, wait_timeout_s=0.5)
    # requeued requests come FIRST (they have waited longest), in order
    assert [r.obs["x"] for r in nxt[:2]] == [0, 1]
    assert all(r.attempts == 1 for r in (first, second))
    # a requeued request past its deadline is completed exceptionally instead
    expired = b.submit({"x": 4}, deadline_s=0.0)
    b.next_batch(max_batch=8, wait_timeout_s=0.1)  # drains + fails it
    with pytest.raises(DeadlineExceeded):
        expired.future.result(timeout=0)
    assert "expired" in shed


def test_close_fails_pending_and_refuses_new_work():
    b = _batcher()
    req = b.submit({"x": 0}, deadline_s=10.0)
    b.close()
    with pytest.raises(ServerClosed):
        req.future.result(timeout=0)
    with pytest.raises(ServerClosed):
        b.submit({"x": 1}, deadline_s=10.0)
    # requeue after close fails the requests rather than stranding them
    stranded = type(req)({"x": 2}, time.monotonic(), time.monotonic() + 10.0)
    b.requeue([stranded])
    with pytest.raises(ServerClosed):
        stranded.future.result(timeout=0)


def test_submit_wakes_a_waiting_replica():
    b = _batcher(window=0.005)
    got = []

    def puller():
        got.extend(b.next_batch(max_batch=4, wait_timeout_s=2.0))

    t = threading.Thread(target=puller)
    t.start()
    time.sleep(0.05)  # puller is parked in the condition wait
    b.submit({"x": 7}, deadline_s=10.0)
    t.join(2.0)
    assert not t.is_alive()
    assert [r.obs["x"] for r in got] == [7]
