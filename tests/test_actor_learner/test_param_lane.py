"""Param-lane seqlock units: publish/poll round trip, pre-publish sentinel,
in-flight-write rejection. Host-side numpy only — tier-1."""

import numpy as np
import pytest

from sheeprl_tpu.actor_learner.param_lane import _SEQ, ParamLane

pytestmark = pytest.mark.actor_learner


def test_lane_publish_poll_roundtrip():
    lane = ParamLane(64)
    try:
        assert lane.version() == -1  # nothing published yet
        assert lane.poll() is None

        payload = np.arange(64, dtype=np.uint8)
        lane.publish(payload, 0)
        assert lane.version() == 0
        version, data = lane.poll()
        assert version == 0
        np.testing.assert_array_equal(data, payload)

        lane.publish(payload[::-1].copy(), 7)  # versions need not be dense
        version, data = lane.poll()
        assert version == 7
        np.testing.assert_array_equal(data, payload[::-1])
    finally:
        lane.close()


def test_lane_attach_shares_the_segment():
    lane = ParamLane(16)
    reader = ParamLane.attach(lane.spec())
    try:
        lane.publish(np.full(16, 3, np.uint8), 2)
        version, data = reader.poll()
        assert version == 2
        np.testing.assert_array_equal(data, np.full(16, 3, np.uint8))
    finally:
        reader.close()
        lane.close()


def test_lane_rejects_in_flight_publish():
    """A reader racing a publish sees an odd seq and keeps its params —
    simulated by freezing the lane mid-write (odd sequence word)."""
    lane = ParamLane(8)
    try:
        lane.publish(np.zeros(8, np.uint8), 0)
        lane._hdr[_SEQ] += 1  # writer died / is paused mid-publish
        assert lane.poll() is None
        lane._hdr[_SEQ] += 1  # publish completes
        version, _ = lane.poll()
        assert version == 0
    finally:
        lane.close()


def test_lane_wrong_size_raises():
    lane = ParamLane(8)
    try:
        with pytest.raises(ValueError, match="expects 8 bytes"):
            lane.publish(np.zeros(9, np.uint8), 0)
        # a failed publish leaves the seq even (lane still readable)
        lane.publish(np.zeros(8, np.uint8), 1)
        assert lane.poll()[0] == 1
    finally:
        lane.close()
