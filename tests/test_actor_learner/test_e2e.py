"""Single-process disaggregated actor–learner end-to-end: `exp=ppo_decoupled`
without a jax.distributed group dispatches to run_actor_learner, spawns a real
CPU actor process, trains over ring-delivered slabs, checkpoints, and lands a
variant=actor_learner record in the run registry. One spawned actor (a jax
import + jit warmup) keeps this inside the tier-1 budget."""

import json
import os

import pytest

from sheeprl_tpu.cli import run

pytestmark = pytest.mark.actor_learner


def al_args(tmp_path):
    return [
        "exp=ppo_decoupled",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        "algo.actor_learner.num_actors=1",
        "algo.actor_learner.slots_per_actor=2",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def read_runs(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def test_actor_learner_e2e(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(
        al_args(tmp_path)
        + [
            "metric.telemetry.enabled=True",
            "metric.telemetry.poll_interval=0.0",
            f"metric.telemetry.runs_jsonl={runs}",
        ]
    )

    # the run checkpointed at its final update
    assert find_checkpoints(tmp_path)

    # zero leaked shm segments: ring + lane unlinked on the clean exit
    from sheeprl_tpu.rollout.shm import _OWNED_SEGMENTS

    assert not _OWNED_SEGMENTS

    # zero orphaned actor processes
    import multiprocessing as mp

    assert not [p for p in mp.active_children() if p.name.startswith("al-actor")]

    # the registry record: its own regress cell (variant) + the rollup the
    # acceptance gate reads (slabs admitted, overlap_fraction present)
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    assert rec["variant"] == "actor_learner"
    assert rec["algo"] == "ppo_decoupled"
    assert rec.get("slabs_admitted", 0) >= 1
    assert rec.get("torn_slabs", 0) == 0
    assert rec.get("dropped_stale_slabs", 0) == 0
    assert "overlap_fraction" in rec
    assert rec.get("staleness_hist")  # every admitted slab recorded its gap

    # telemetry stream carries the topology heartbeat fields
    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1
    events = [json.loads(line) for line in open(jsonls[0]) if line.strip()]
    heartbeats = [e for e in events if e["event"] == "heartbeat"]
    assert heartbeats
    assert any("window_slabs_admitted" in e for e in heartbeats)
    assert any("learner_duty_cycle" in e for e in heartbeats)
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end.get("slabs_admitted", 0) >= 1
