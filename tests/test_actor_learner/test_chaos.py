"""Deterministic chaos drills (ISSUE acceptance) + the decoupled-PPO
learning-parity smoke. Each drill runs the real CLI entrypoint with scripted
``algo.actor_learner.fault_injection`` faults and asserts on the durable
evidence (RUNS.jsonl rollup, checkpoint files, process/shm hygiene). Marked
``slow``: each spawns real actor processes (jax imports) and the parity smoke
trains two runs to completion."""

import json
import os

import pytest

from sheeprl_tpu.cli import run

pytestmark = [pytest.mark.actor_learner, pytest.mark.slow]


def base_args(tmp_path):
    return [
        "exp=ppo_decoupled",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        "algo.actor_learner.num_actors=1",
        "algo.actor_learner.slots_per_actor=2",
        "algo.actor_learner.fault_injection.enabled=True",
        f"log_base_dir={tmp_path}/logs",
    ]


def read_runs(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def assert_clean_process_and_shm_state():
    import multiprocessing as mp

    from sheeprl_tpu.rollout.shm import _OWNED_SEGMENTS

    assert not _OWNED_SEGMENTS, f"leaked shm segments: {list(_OWNED_SEGMENTS)}"
    orphans = [p for p in mp.active_children() if p.name.startswith("al-actor")]
    assert not orphans, f"orphaned actors: {orphans}"


def test_actor_crash_mid_write_drill(tmp_path, monkeypatch):
    """Actor killed mid-write (after payload+meta, before the commit marker):
    the learner must admit ZERO torn slabs, the supervisor charges exactly one
    restart, and the run completes (acceptance drill #1)."""
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(
        base_args(tmp_path)
        + [
            "dry_run=True",
            "algo.actor_learner.fault_injection.faults=[{kind: actor_crash_mid_write, actor: 0, at_slab: 0}]",
            f"metric.telemetry.runs_jsonl={runs}",
        ]
    )
    assert_clean_process_and_shm_state()
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    # the torn slab was detected/reclaimed, never admitted
    assert rec.get("torn_slabs", 0) >= 1
    assert rec.get("slabs_admitted", 0) >= 1
    # exactly one restart charged for the scripted crash
    assert rec.get("actor_restarts") == {"0": 1}
    assert find_checkpoints(tmp_path)

    # -- merged end-to-end trace (acceptance): the registry record names
    # every per-process stream (no globbing), and joining them yields one
    # causal chain per admitted slab plus a torn-terminated victim chain
    files = rec["telemetry_files"]
    assert any(p.endswith("telemetry.jsonl") for p in files)
    assert any("trace.actor0" in p for p in files)
    assert all(os.path.isfile(p) for p in files), files

    from tools import trace as trace_tool

    merged = trace_tool.merge(files)
    roles = {p["role"] for p in merged["processes"]}
    assert "learner" in roles and any(r.startswith("actor") for r in roles)
    summary = trace_tool.summarize(merged)
    slabs = summary["slabs"]
    # every admitted slab's chain is complete across the process boundary:
    # collect+commit in the actor child, admit+train in the learner
    assert slabs["complete_chains"] >= rec["slabs_admitted"]
    assert slabs["terminals"].get("slab_train", 0) >= 1
    # the crash victim: its chain keeps the actor-side slab_collect (the
    # flush-per-event recorder survives os._exit) and terminates at `torn`
    torn_chains = [
        evs
        for evs in merged["traces"].values()
        if trace_tool.slab_terminal(evs) == "torn"
    ]
    assert len(torn_chains) >= 1
    assert any(
        trace_tool.trace_kinds(evs)[0] == "slab_collect" for evs in torn_chains
    )
    # lag decomposition present for the trained population
    for key in ("age_ms", "collect_ms", "ring_wait_ms", "train_ms"):
        assert "p50" in slabs[key] and "p95" in slabs[key]

    # bench.py --trace prints the same decomposition from the jax-free parent
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--trace", *files],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    printed = json.loads(proc.stdout)
    assert printed["slabs"]["complete_chains"] == slabs["complete_chains"]
    assert "p95" in printed["slabs"]["age_ms"]


def test_actor_hang_drill(tmp_path, monkeypatch):
    """A wedged (non-heartbeating) actor trips the supervision deadline and is
    restarted within budget; the run still completes."""
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(
        base_args(tmp_path)
        + [
            "dry_run=True",
            "algo.actor_learner.step_timeout_s=3",
            "algo.actor_learner.heartbeat_grace_s=3",
            "algo.actor_learner.fault_injection.faults=[{kind: actor_hang, actor: 0, at_slab: 0, duration_s: 3600}]",
            f"metric.telemetry.runs_jsonl={runs}",
        ]
    )
    assert_clean_process_and_shm_state()
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    assert rec.get("actor_restarts") == {"0": 1}


def test_learner_kill_drill(tmp_path, monkeypatch):
    """learner_kill (self-SIGTERM after the first admitted slab) must drive
    the resilience drain verbatim: emergency checkpoint, quiesced actors, no
    leaked shm, the distinct preemption exit code, and a `preempted` registry
    record (acceptance drill #2)."""
    from sheeprl_tpu.resilience import PREEMPTED_EXIT_CODE

    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    # num_updates > 1 so the loop re-enters its preemption poll after the
    # admitted slab whose fault pulled the trigger
    with pytest.raises(SystemExit) as exc:
        run(
            base_args(tmp_path)
            + [
                "algo.total_steps=128",
                "algo.actor_learner.fault_injection.faults=[{kind: learner_kill, at_slab: 0}]",
                f"metric.telemetry.runs_jsonl={runs}",
            ]
        )
    assert exc.value.code == PREEMPTED_EXIT_CODE
    assert_clean_process_and_shm_state()
    assert find_checkpoints(tmp_path), "no emergency checkpoint written"
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "preempted"
    assert rec.get("slabs_admitted", 0) >= 1


def test_param_lane_stall_drives_staleness_drops(tmp_path, monkeypatch):
    """param_lane_stall with max_staleness=0: while the publish is suppressed
    the actor keeps refilling against the stalled version, so the learner must
    count+drop stale slabs and train only on refreshed ones."""
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(
        base_args(tmp_path)
        + [
            "algo.total_steps=192",  # 3 updates of 64 rows
            "algo.actor_learner.max_staleness=0",
            "algo.actor_learner.fault_injection.faults=[{kind: param_lane_stall, at_slab: 0, duration_s: 1.5}]",
            f"metric.telemetry.runs_jsonl={runs}",
        ]
    )
    assert_clean_process_and_shm_state()
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    assert rec.get("dropped_stale_slabs", 0) >= 1
    assert rec.get("slabs_admitted", 0) >= 3
    # no restarts, no torn slabs — staleness is a clean drop/refill path
    assert "actor_restarts" not in rec
    assert rec.get("torn_slabs", 0) == 0


def test_decoupled_learning_parity_smoke(tmp_path, monkeypatch):
    """Satellite: async (actor-learner) PPO vs sync PPO at equal env steps,
    fixed seeds, CartPole CPU — the final return must be within tolerance.
    Admission order makes the async path nondeterministic, so the tolerance
    is a did-it-learn band, not bitwise parity."""
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    common = [
        "seed=42",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.total_steps=24576",
        "algo.rollout_steps=64",
        # the async slab is per-actor (64*4 rows / 8 devices = 32 per device),
        # so the shared batch size must fit the smaller of the two layouts
        "algo.per_rank_batch_size=32",
        "env.num_envs=8",
        "algo.run_test=False",
        "checkpoint.save_last=False",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        f"metric.telemetry.runs_jsonl={runs}",
        f"log_base_dir={tmp_path}/logs",
    ]
    run(["exp=ppo"] + common)
    run(["exp=ppo_decoupled"] + common + ["algo.actor_learner.num_actors=2"])
    assert_clean_process_and_shm_state()

    sync_rec, async_rec = read_runs(runs)
    assert sync_rec.get("variant") is None and async_rec["variant"] == "actor_learner"
    sync_ret = sync_rec["final_metrics"]["Rewards/rew_avg"]
    async_ret = async_rec["final_metrics"]["Rewards/rew_avg"]
    # both clearly above CartPole's ~20-step random baseline...
    assert sync_ret > 40, f"sync PPO failed to learn: {sync_ret}"
    assert async_ret > 40, f"async PPO failed to learn: {async_ret}"
    # ...and the async path within tolerance of the sync path
    assert async_ret >= 0.25 * sync_ret, f"async={async_ret} vs sync={sync_ret}"
    # every admitted slab stayed within the staleness bound; nothing torn
    assert async_rec.get("torn_slabs", 0) == 0
    assert async_rec.get("slabs_admitted", 0) >= 1
