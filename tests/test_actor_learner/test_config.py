"""actor_learner config node, the staleness-admission predicate (boundary
cases per the ISSUE: max_staleness 0 and N), fault-spec parsing, and the
evidence-engine hooks (registry outcomes, regress metric). Tier-1."""

import importlib.util
import os

import pytest

from sheeprl_tpu.actor_learner.config import ActorLearnerConfig, actor_learner_config_from_cfg, admit
from sheeprl_tpu.actor_learner.fault_injection import (
    ALFaultSpec,
    LearnerFaultSchedule,
    actor_faults_for,
    parse_al_fault_config,
)

pytestmark = pytest.mark.actor_learner

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# ------------------------------------------------------------------ admission


def test_admit_max_staleness_zero_is_on_policy_only():
    assert admit(5, 5, 0)
    assert not admit(4, 5, 0)
    assert not admit(0, 1, 0)


def test_admit_max_staleness_n_boundary():
    n = 3
    assert admit(2, 5, n)  # gap == N: admitted
    assert not admit(1, 5, n)  # gap == N+1: dropped
    assert admit(5, 5, n)  # fresh always admitted
    assert admit(7, 5, n)  # ahead-of-version (restart race) admitted


def test_admit_unversioned_slab_never_admissible():
    # version -1 = the actor never saw a publish; no staleness bound can
    # make that trainable
    assert not admit(-1, 0, 0)
    assert not admit(-1, 1000, 10**9)


# --------------------------------------------------------------- config node


def test_config_defaults_from_empty_cfg():
    alcfg = actor_learner_config_from_cfg({})
    assert alcfg.num_actors == 2
    assert alcfg.slots_per_actor == 2
    assert alcfg.max_staleness == 1
    assert alcfg.faults == []
    assert alcfg.heartbeat_grace == alcfg.step_timeout_s  # grace defaults to the step deadline


def test_config_parses_node_and_faults():
    cfg = {
        "algo": {
            "actor_learner": {
                "num_actors": 4,
                "slots_per_actor": 1,
                "max_staleness": 0,
                "heartbeat_grace_s": 2.5,
                "restart_refund_s": None,
                "fault_injection": {
                    "enabled": True,
                    "faults": [
                        {"kind": "actor_crash_mid_write", "actor": 1, "at_slab": 2},
                        {"kind": "learner_kill", "at_slab": 3},
                    ],
                },
            }
        }
    }
    alcfg = actor_learner_config_from_cfg(cfg)
    assert alcfg.num_actors == 4 and alcfg.max_staleness == 0
    assert alcfg.heartbeat_grace == 2.5
    assert alcfg.restart_refund_s is None
    assert [f.kind for f in alcfg.faults] == ["actor_crash_mid_write", "learner_kill"]


def test_config_faults_disabled_by_default():
    cfg = {
        "algo": {
            "actor_learner": {
                "fault_injection": {"faults": [{"kind": "learner_kill", "at_slab": 0}]}
            }
        }
    }
    assert actor_learner_config_from_cfg(cfg).faults == []  # enabled=False gates


def test_config_validation():
    with pytest.raises(ValueError, match="num_actors"):
        ActorLearnerConfig(num_actors=0)
    with pytest.raises(ValueError, match="max_staleness"):
        ActorLearnerConfig(max_staleness=-1)
    with pytest.raises(ValueError, match="divisible"):
        ActorLearnerConfig(num_actors=3).envs_per_actor(8)
    assert ActorLearnerConfig(num_actors=4).envs_per_actor(8) == 2


def test_actor_slots_partition_is_disjoint_and_total():
    alcfg = ActorLearnerConfig(num_actors=3, slots_per_actor=2)
    slots = [alcfg.actor_slots(a) for a in range(3)]
    flat = [s for per in slots for s in per]
    assert sorted(flat) == list(range(6))  # exactly the ring, no overlap


# -------------------------------------------------------------------- faults


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="unknown actor_learner fault kind"):
        ALFaultSpec(kind="nope", at_slab=0)
    with pytest.raises(ValueError, match="needs an actor index"):
        ALFaultSpec(kind="actor_hang", at_slab=0)
    with pytest.raises(ValueError, match="at_slab"):
        ALFaultSpec(kind="learner_kill", at_slab=-1)
    with pytest.raises(ValueError, match="kind/at_slab"):
        parse_al_fault_config([{"kind": "learner_kill"}])
    with pytest.raises(ValueError, match="must be a mapping"):
        parse_al_fault_config(["learner_kill"])


def test_learner_fault_schedule_pop_due():
    faults = parse_al_fault_config(
        [
            {"kind": "param_lane_stall", "at_slab": 2, "duration_s": 1.0},
            {"kind": "learner_kill", "at_slab": 5},
            {"kind": "actor_hang", "actor": 0, "at_slab": 1},  # actor fault: not the learner's
        ]
    )
    sched = LearnerFaultSchedule(faults)
    assert bool(sched)
    assert sched.pop_due(0) == []
    due = sched.pop_due(3)  # at-or-before: a skipped boundary still fires
    assert [f.kind for f in due] == ["param_lane_stall"]
    assert sched.pop_due(3) == []  # fired once, never again
    assert [f.kind for f in sched.pop_due(5)] == ["learner_kill"]
    assert not sched


def test_actor_faults_for_filters_by_actor():
    faults = parse_al_fault_config(
        [
            {"kind": "actor_crash_mid_write", "actor": 0, "at_slab": 0},
            {"kind": "actor_hang", "actor": 1, "at_slab": 0},
            {"kind": "learner_kill", "at_slab": 0},
        ]
    )
    assert [f.kind for f in actor_faults_for(faults, 0)] == ["actor_crash_mid_write"]
    assert [f.kind for f in actor_faults_for(faults, 1)] == ["actor_hang"]
    assert actor_faults_for(faults, 2) == []
    # the wire form an actor receives carries no actor index (it's implicit)
    assert ALFaultSpec(kind="actor_hang", actor=1, at_slab=3, duration_s=2.0).to_wire() == {
        "kind": "actor_hang",
        "at_slab": 3,
        "duration_s": 2.0,
    }


# ---------------------------------------------------------- evidence plumbing


def test_registry_knows_actor_learner_outcomes():
    from sheeprl_tpu.obs.registry import OUTCOMES, build_run_record

    assert {"actor_exhausted", "learner_crashed"} <= set(OUTCOMES)
    rec = build_run_record(None, kind="train", outcome="actor_exhausted")
    assert rec["outcome"] == "actor_exhausted"  # not coerced to "crashed"


def test_regress_gates_overlap_fraction():
    spec = importlib.util.spec_from_file_location(
        "_regress_for_al_test", os.path.join(REPO, "tools", "regress.py")
    )
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)
    assert "overlap_fraction" in regress.METRICS
    higher_better, slack = regress.METRICS["overlap_fraction"]
    assert higher_better and slack == 0.0
