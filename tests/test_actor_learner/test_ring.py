"""Trajectory-ring transport units (ISSUE satellite: wrap-around parity vs a
list-backed reference, torn-write injection, slot reclaim). Pure host-side
numpy — no jax, no subprocesses — so these stay tier-1."""

import numpy as np
import pytest

from sheeprl_tpu.actor_learner.ring import (
    COMMITTED,
    FREE,
    PARAM_VERSION,
    STATE,
    WRITING,
    SlabLayout,
    TrajectoryRing,
)

pytestmark = pytest.mark.actor_learner


def small_layout():
    return SlabLayout({"state": ((4, 3), "float32"), "actions": ((4, 2), "float32")})


def write_slab(ring, layout, slot, seq, payload, param_version=0, actor_id=0):
    assert ring.try_begin_write(slot)
    layout.pack_into(ring.payload_view(slot), payload)
    ring.write_meta(
        slot,
        seq=seq,
        param_version=param_version,
        actor_id=actor_id,
        n_rows=4,
        collect_us=1000 + seq,
        env_steps=4,
    )
    ring.commit(slot)


def test_slab_layout_roundtrip_and_wire():
    layout = small_layout()
    rng = np.random.default_rng(0)
    data = {
        "state": rng.normal(size=(4, 3)).astype(np.float32),
        "actions": rng.normal(size=(4, 2)).astype(np.float32),
    }
    buf = np.zeros(layout.nbytes, np.uint8)
    layout.pack_into(buf, data)
    out = layout.unpack(buf)
    for k in data:
        np.testing.assert_array_equal(out[k], data[k])

    # the wire form rebuilds an identical codec (actor-side from_wire)
    clone = SlabLayout.from_wire(layout.to_wire())
    assert clone.offsets == layout.offsets and clone.nbytes == layout.nbytes
    for k in data:
        np.testing.assert_array_equal(clone.unpack(buf)[k], data[k])

    # unpack COPIES out of the buffer: releasing/overwriting the slot after
    # unpack must not corrupt an already-returned batch
    buf[:] = 0
    np.testing.assert_array_equal(out["state"], data["state"])


def test_slab_layout_shape_mismatch_raises():
    layout = small_layout()
    buf = np.zeros(layout.nbytes, np.uint8)
    with pytest.raises(ValueError, match="expected shape"):
        layout.pack_into(buf, {"state": np.zeros((5, 3), np.float32), "actions": np.zeros((4, 2), np.float32)})


def test_ring_wraparound_parity_vs_list_reference():
    """Many rounds through a 2-slot ring must deliver exactly the slabs a
    plain list-backed FIFO would: same seqs, same payload bytes, in order."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    try:
        rng = np.random.default_rng(7)
        reference = []  # the list-backed FIFO the ring must match
        consumed = []
        seq = 0
        for _ in range(25):  # 50 slabs through 2 slots: heavy wrap-around
            for slot in (0, 1):
                payload = {
                    "state": rng.normal(size=(4, 3)).astype(np.float32),
                    "actions": rng.normal(size=(4, 2)).astype(np.float32),
                }
                write_slab(ring, layout, slot, seq, payload, param_version=seq // 2)
                reference.append((seq, payload))
                seq += 1
            for slot in (0, 1):
                meta = ring.poll(slot)
                assert meta is not None
                flat = layout.unpack(ring.payload_view(meta.slot))
                ring.release(meta.slot)
                assert meta.n_rows == 4 and meta.env_steps == 4
                assert meta.collect_us == 1000 + meta.seq
                assert meta.param_version == meta.seq // 2
                consumed.append((meta.seq, flat))
        assert [s for s, _ in consumed] == [s for s, _ in reference]
        for (_, got), (_, want) in zip(consumed, reference):
            for k in want:
                np.testing.assert_array_equal(got[k], want[k])
        assert ring.torn_detected == 0
        assert ring.occupancy() == 0.0
    finally:
        ring.close()


def test_torn_write_never_surfaced_and_reclaimed():
    """A writer death between write_meta and commit leaves the slot WRITING:
    poll must never admit it, and reclaim_actor_slots counts it as torn."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    try:
        payload = {"state": np.ones((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        assert ring.try_begin_write(0)
        layout.pack_into(ring.payload_view(0), payload)
        ring.write_meta(0, seq=0, param_version=0, actor_id=0, n_rows=4, collect_us=1, env_steps=4)
        # no commit — the canonical torn write (actor_crash_mid_write)
        assert ring.poll(0) is None and ring.poll(1) is None
        assert int(ring._hdr[0, STATE]) == WRITING
        assert not ring.try_begin_write(0)  # a dead writer's claim holds...

        torn = ring.reclaim_actor_slots([0, 1])  # ...until the supervisor reclaims
        assert torn == 1
        assert int(ring._hdr[0, STATE]) == FREE
        assert ring.torn_detected == 0  # reader never even saw it

        # the reclaimed slot is immediately writable again
        write_slab(ring, layout, 0, seq=1, payload=payload)
        meta = ring.poll(0)
        assert meta is not None and meta.seq == 1
        ring.release(0)
    finally:
        ring.close()


def test_commit_over_tampered_meta_counted_torn():
    """COMMITTED + checksum mismatch (commit marker over stale/corrupt meta)
    is counted torn and freed, never returned."""
    layout = small_layout()
    ring = TrajectoryRing(1, layout.nbytes)
    try:
        payload = {"state": np.ones((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        write_slab(ring, layout, 0, seq=3, payload=payload)
        ring._hdr[0, PARAM_VERSION] += 1  # corrupt a meta word after the checksum
        assert ring.poll(0) is None
        assert ring.torn_detected == 1
        assert int(ring._hdr[0, STATE]) == FREE  # reclaimed for the writer
    finally:
        ring.close()


def test_reclaim_preserves_committed_slabs():
    """Restarting a crashed actor must NOT discard slabs it committed before
    dying — they were published cleanly and are still valid batches."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    try:
        payload = {"state": np.ones((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        write_slab(ring, layout, 0, seq=5, payload=payload)  # committed pre-crash
        assert ring.try_begin_write(1)  # in-flight at crash time
        assert ring.reclaim_actor_slots([0, 1]) == 1
        meta = ring.poll(0)
        assert meta is not None and meta.seq == 5
        assert int(ring._hdr[1, STATE]) == FREE
    finally:
        ring.close()


def test_attach_shares_the_segment():
    """Writer-side attach (RingSpec) sees the owner's slots and vice versa —
    the cross-process contract, exercised in one process via two handles."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    writer = TrajectoryRing.attach(ring.spec())
    try:
        payload = {"state": np.full((4, 3), 2.0, np.float32), "actions": np.zeros((4, 2), np.float32)}
        write_slab(writer, layout, 1, seq=9, payload=payload)
        meta = ring.poll(1)
        assert meta is not None and meta.seq == 9 and meta.slot == 1
        got = layout.unpack(ring.payload_view(1))
        np.testing.assert_array_equal(got["state"], payload["state"])
        ring.release(1)
        assert int(writer._hdr[1, STATE]) == FREE  # release is visible to the writer
        assert writer.occupancy() == 0.0
    finally:
        writer.close()
        ring.close()


def test_trace_context_roundtrips_through_header():
    """The trace-plane header words (TRACE_ID, COMMIT_T_US) survive the
    write→poll round trip, are covered by the meta checksum, and default to
    zero for writers that pass no trace context."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    try:
        payload = {"state": np.ones((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        tid, commit_us = (1 << 62) + 12345, 1_700_000_000_000_000
        assert ring.try_begin_write(0)
        layout.pack_into(ring.payload_view(0), payload)
        ring.write_meta(
            0, seq=0, param_version=0, actor_id=0, n_rows=4, collect_us=1,
            env_steps=4, trace_id=tid, commit_t_us=commit_us,
        )
        ring.commit(0)
        meta = ring.poll(0)
        assert meta is not None
        assert meta.trace_id == tid and meta.commit_t_us == commit_us
        ring.release(0)

        # untraced writers (trace plane off) default both words to zero
        write_slab(ring, layout, 1, seq=1, payload=payload)
        meta = ring.poll(1)
        assert meta is not None and meta.trace_id == 0 and meta.commit_t_us == 0
        ring.release(1)

        # the checksum slice covers the trace words: corrupting TRACE_ID
        # after commit is a torn slab, never an admitted one with a bad id
        assert ring.try_begin_write(0)
        ring.write_meta(
            0, seq=2, param_version=0, actor_id=0, n_rows=4, collect_us=1,
            env_steps=4, trace_id=tid, commit_t_us=commit_us,
        )
        ring.commit(0)
        from sheeprl_tpu.actor_learner.ring import TRACE_ID

        ring._hdr[0, TRACE_ID] += 1
        assert ring.poll(0) is None and ring.torn_detected == 1
    finally:
        ring.close()


def test_torn_trace_ids_captured_and_drained_once():
    """Victim attribution: a torn slab's trace id is captured on both torn
    paths — poll (checksum mismatch, best-effort) and reclaim (crash after
    write_meta, checksum-verified) — and drained exactly once."""
    layout = small_layout()
    ring = TrajectoryRing(2, layout.nbytes)
    try:
        payload = {"state": np.ones((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        # path 1: COMMITTED + corrupt meta word → poll captures the id
        assert ring.try_begin_write(0)
        layout.pack_into(ring.payload_view(0), payload)
        ring.write_meta(
            0, seq=0, param_version=0, actor_id=0, n_rows=4, collect_us=1,
            env_steps=4, trace_id=101, commit_t_us=1,
        )
        ring.commit(0)
        ring._hdr[0, PARAM_VERSION] += 1
        assert ring.poll(0) is None

        # path 2: crash between write_meta and commit → reclaim verifies the
        # checksum before trusting the id
        assert ring.try_begin_write(1)
        layout.pack_into(ring.payload_view(1), payload)
        ring.write_meta(
            1, seq=1, param_version=0, actor_id=0, n_rows=4, collect_us=1,
            env_steps=4, trace_id=202, commit_t_us=2,
        )
        assert ring.reclaim_actor_slots([1]) == 1

        assert ring.drain_torn_trace_ids() == [101, 202]
        assert ring.drain_torn_trace_ids() == []  # drained exactly once

        # a crash BEFORE write_meta finished leaves no trustworthy id: the
        # reclaim sweep must not attribute a stale/garbage word
        assert ring.try_begin_write(0)
        assert ring.reclaim_actor_slots([0]) == 1
        assert ring.drain_torn_trace_ids() == []
    finally:
        ring.close()


def test_occupancy_counts_committed_only():
    layout = small_layout()
    ring = TrajectoryRing(4, layout.nbytes)
    try:
        payload = {"state": np.zeros((4, 3), np.float32), "actions": np.zeros((4, 2), np.float32)}
        write_slab(ring, layout, 0, seq=0, payload=payload)
        assert ring.try_begin_write(1)  # WRITING doesn't count
        assert ring.occupancy() == pytest.approx(0.25)
        assert int(ring._hdr[0, STATE]) == COMMITTED
    finally:
        ring.close()
