"""SequentialReplayBuffer specs (reference: tests/test_data/test_sequential_buffer.py)."""

import numpy as np
import pytest

from sheeprl_tpu.data import SequentialReplayBuffer


def make_data(seq_len, n_envs=1, start=0):
    obs = (start + np.arange(seq_len * n_envs)).reshape(seq_len, n_envs, 1).astype(np.float32)
    return {"observations": obs}


def test_wrong_sizes():
    with pytest.raises(ValueError):
        SequentialReplayBuffer(-1)
    with pytest.raises(ValueError):
        SequentialReplayBuffer(1, -1)


def test_sample_shape():
    rb = SequentialReplayBuffer(buffer_size=20, n_envs=2, seed=0)
    rb.add(make_data(10, 2))
    s = rb.sample(4, n_samples=3, sequence_length=5)
    assert s["observations"].shape == (3, 5, 4, 1)


def test_sequences_are_contiguous():
    rb = SequentialReplayBuffer(buffer_size=20, seed=0)
    rb.add(make_data(15))
    s = rb.sample(8, sequence_length=6)
    obs = s["observations"][0, :, :, 0]  # [L, B]
    diffs = np.diff(obs, axis=0)
    assert np.all(diffs == 1)


def test_sample_full_wraps():
    rb = SequentialReplayBuffer(buffer_size=10, seed=0)
    rb.add(make_data(10))
    rb.add(make_data(3, start=100))  # pos=3
    s = rb.sample(64, sequence_length=4)
    obs = s["observations"][0, :, :, 0]  # [L, B]
    # every sequence must be consecutive in insertion order: within a sequence,
    # values either step by +1 or jump from old data (..9) to new (100..)
    for b in range(obs.shape[1]):
        seq = obs[:, b]
        for t in range(3):
            step = seq[t + 1] - seq[t]
            assert step == 1 or (seq[t] == 9 and seq[t + 1] == 100)
    # no sequence may contain the invalid transition across the cursor
    # (index pos-1=2 holds 102; a sequence starting there would read garbage)
    assert not np.any(obs == 102) or np.all(obs[-1] != 102) or True


def test_sequence_never_crosses_cursor():
    rb = SequentialReplayBuffer(buffer_size=10, seed=1)
    rb.add(make_data(10))
    rb.add(make_data(3, start=100))  # slots 0,1,2 = 100,101,102; pos=3
    s = rb.sample(128, sequence_length=4)
    obs = s["observations"][0, :, :, 0]
    # a valid sequence cannot include both a new element (>=100) and then an
    # old element right after the cursor: the pair (102, 3) is the forbidden
    # cursor crossing
    for b in range(obs.shape[1]):
        seq = obs[:, b].tolist()
        for t in range(3):
            assert not (seq[t] == 102 and seq[t + 1] == 3)


def test_sample_full_large_sequence_error():
    rb = SequentialReplayBuffer(buffer_size=10)
    rb.add(make_data(10))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=11)


def test_sample_not_full_too_long_error():
    rb = SequentialReplayBuffer(buffer_size=10)
    rb.add(make_data(5))
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=6)


def test_sample_no_add_error():
    rb = SequentialReplayBuffer(buffer_size=10)
    with pytest.raises(RuntimeError):
        rb.sample(1, sequence_length=2)


def test_sample_bad_args():
    rb = SequentialReplayBuffer(buffer_size=10)
    rb.add(make_data(5))
    with pytest.raises(ValueError):
        rb.sample(0, sequence_length=2)
    with pytest.raises(ValueError):
        rb.sample(1, n_samples=0, sequence_length=2)


def test_sample_one_element():
    rb = SequentialReplayBuffer(buffer_size=1)
    rb.add(make_data(1))
    s = rb.sample(1, sequence_length=1)
    assert s["observations"].shape == (1, 1, 1, 1)


def test_sample_next_obs():
    rb = SequentialReplayBuffer(buffer_size=20, seed=0)
    rb.add(make_data(15))
    s = rb.sample(4, sequence_length=5, sample_next_obs=True)
    assert np.array_equal(s["next_observations"], s["observations"] + 1)


def test_memmap(tmp_path):
    rb = SequentialReplayBuffer(buffer_size=20, memmap=True, memmap_dir=tmp_path / "buf", seed=0)
    rb.add(make_data(10))
    s = rb.sample(2, sequence_length=3)
    assert s["observations"].shape == (1, 3, 2, 1)


def test_sample_device():
    import jax.numpy as jnp

    rb = SequentialReplayBuffer(buffer_size=20, seed=0)
    rb.add(make_data(10))
    s = rb.sample_device(2, sequence_length=3)
    assert isinstance(s["observations"], jnp.ndarray)
    assert s["observations"].shape == (1, 3, 2, 1)


def test_sample_next_obs_never_reads_cursor():
    # not-full: the successor of the last element must already be written
    rb = SequentialReplayBuffer(buffer_size=10, seed=0)
    rb.add(make_data(5))
    s = rb.sample(64, sequence_length=4, sample_next_obs=True)
    assert s["observations"].max() <= 3  # last element at most index 3, next at 4
    assert np.array_equal(s["next_observations"], s["observations"] + 1)
    with pytest.raises(ValueError):
        rb.sample(1, sequence_length=5, sample_next_obs=True)
