"""EnvIndependentReplayBuffer specs (reference: tests/test_data/test_env_independent_rb.py)."""

import numpy as np
import pytest

from sheeprl_tpu.data import EnvIndependentReplayBuffer, SequentialReplayBuffer


def make_data(seq_len, n_envs, start=0):
    obs = (start + np.arange(seq_len * n_envs)).reshape(seq_len, n_envs, 1).astype(np.float32)
    return {"observations": obs}


def test_wrong_sizes():
    with pytest.raises(ValueError):
        EnvIndependentReplayBuffer(-1)
    with pytest.raises(ValueError):
        EnvIndependentReplayBuffer(1, -1)


def test_missing_memmap_dir():
    with pytest.raises(ValueError):
        EnvIndependentReplayBuffer(10, memmap=True, memmap_dir=None)


def test_wrong_memmap_mode(tmp_path):
    with pytest.raises(ValueError):
        EnvIndependentReplayBuffer(10, memmap=True, memmap_mode="x", memmap_dir=tmp_path)


def test_add_all_envs():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=3)
    rb.add(make_data(4, 3))
    assert all(b._pos == 4 for b in rb.buffer)


def test_add_subset_of_envs():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=3)
    rb.add(make_data(4, 2), indices=[0, 2])
    assert rb.buffer[0]._pos == 4
    assert rb.buffer[1]._pos == 0
    assert rb.buffer[2]._pos == 4


def test_add_wrong_indices_length():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=3)
    with pytest.raises(ValueError):
        rb.add(make_data(4, 2), indices=[0])


def test_sample_shape():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=3, seed=0)
    rb.add(make_data(6, 3))
    s = rb.sample(8, n_samples=2)
    assert s["observations"].shape == (2, 8, 1)


def test_sample_sequential_concat_axis():
    rb = EnvIndependentReplayBuffer(
        buffer_size=20, n_envs=2, buffer_cls=SequentialReplayBuffer, seed=0
    )
    rb.add(make_data(10, 2))
    s = rb.sample(6, n_samples=2, sequence_length=4)
    assert s["observations"].shape == (2, 4, 6, 1)


def test_per_env_cursors_differ():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2)
    rb.add(make_data(3, 1), indices=[0])
    rb.add(make_data(5, 1), indices=[1])
    assert rb.buffer[0]._pos == 3 and rb.buffer[1]._pos == 5


def test_sample_bad_args():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2)
    rb.add(make_data(3, 2))
    with pytest.raises(ValueError):
        rb.sample(0)


def test_memmap(tmp_path):
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2, memmap=True, memmap_dir=tmp_path / "buf")
    rb.add(make_data(4, 2))
    assert (tmp_path / "buf" / "env_0" / "observations.memmap").exists()
    assert (tmp_path / "buf" / "env_1" / "observations.memmap").exists()


def test_sample_device():
    import jax.numpy as jnp

    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2, seed=0)
    rb.add(make_data(6, 2))
    s = rb.sample_device(4)
    assert isinstance(s["observations"], jnp.ndarray)


def test_state_dict_roundtrip():
    rb = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2)
    rb.add(make_data(3, 2))
    state = rb.state_dict()
    rb2 = EnvIndependentReplayBuffer(buffer_size=10, n_envs=2)
    rb2.add(make_data(1, 2))
    rb2.load_state_dict(state)
    assert [b._pos for b in rb2.buffer] == [b._pos for b in rb.buffer]
