"""In-graph replay sampling kernels (data/device_buffer.py pure functions):
validity-mask parity with the host-side `_valid_starts`/`_valid_items`
oracles across every ring phase, wrap-around gather parity with the host
`SequentialReplayBuffer` storage for the SAME indices, and the
`superstep_inputs` contract the fused training supersteps consume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.data.buffers import SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    draw_sequence_batch,
    draw_transition_batch,
    gather_sequences,
    sequence_start_mask,
    transition_item_mask,
)

CAP = 8
N_ENVS = 3


def _step_data(t, n_envs=N_ENVS):
    return {
        "observations": np.full((1, n_envs, 2), t, np.float32),
        "actions": np.full((1, n_envs, 1), t, np.float32),
        "rewards": np.full((1, n_envs, 1), t, np.float32),
        "terminated": np.zeros((1, n_envs, 1), np.float32),
        "truncated": np.zeros((1, n_envs, 1), np.float32),
        "is_first": np.zeros((1, n_envs, 1), np.float32),
    }


def _fresh(cap=CAP, n_envs=N_ENVS, seed=0):
    return DeviceReplayBuffer(cap, n_envs=n_envs, obs_keys=("observations",), seed=seed)


@pytest.mark.parametrize("span", [1, 2, 4])
def test_sequence_mask_matches_host_valid_starts_at_every_fill_level(span):
    """The on-device mask must agree with the host `_valid_starts` oracle
    through the whole ring life cycle: filling, exactly full, wrapped."""
    rb = _fresh()
    for t in range(2 * CAP + 3):
        rb.add(_step_data(t))
        mask = np.asarray(
            sequence_start_mask(
                jnp.asarray(rb._pos, jnp.int32), jnp.asarray(rb._full), CAP, span
            )
        )
        for env in range(N_ENVS):
            expected = np.zeros(CAP, bool)
            expected[rb._valid_starts(env, span)] = True
            np.testing.assert_array_equal(
                mask[env], expected, err_msg=f"t={t} env={env} span={span}"
            )


@pytest.mark.parametrize("sample_next_obs", [False, True])
def test_transition_mask_matches_host_valid_items_at_every_fill_level(sample_next_obs):
    rb = _fresh()
    for t in range(2 * CAP + 3):
        rb.add(_step_data(t))
        mask = np.asarray(
            transition_item_mask(
                jnp.asarray(rb._pos, jnp.int32), jnp.asarray(rb._full), CAP, sample_next_obs
            )
        )
        for env in range(N_ENVS):
            expected = np.zeros(CAP, bool)
            expected[rb._valid_items(env, sample_next_obs)] = True
            np.testing.assert_array_equal(
                mask[env], expected, err_msg=f"t={t} env={env} next_obs={sample_next_obs}"
            )


def test_wraparound_sequence_gather_matches_host_buffer_for_same_indices():
    """Feed the SAME step stream to the device ring and to a host
    `SequentialReplayBuffer`; a gather of explicitly wrapped windows (starts
    behind the cursor, time indices wrapping mod capacity) must return
    identical values from both."""
    dev = _fresh()
    host = SequentialReplayBuffer(CAP, n_envs=N_ENVS)
    for t in range(2 * CAP + 5):  # cursor mid-ring, every slot overwritten once
        data = _step_data(t)
        dev.add(data)
        host.add(data)

    seq_len = 3
    # every valid start of every env — includes the wrapped region behind the
    # cursor; windows starting at CAP-1 wrap to slot 0
    env_idx, starts = [], []
    for env in range(N_ENVS):
        for s in dev._valid_starts(env, seq_len):
            env_idx.append(env)
            starts.append(int(s))
    env_idx = np.asarray(env_idx, np.int32)
    starts = np.asarray(starts, np.int32)
    assert (starts + seq_len > CAP).any(), "no wrapping window in the index set"

    offsets = np.arange(seq_len, dtype=np.int32)
    time_idx = (starts[:, None] + offsets[None, :]) % CAP
    got = gather_sequences(dev._bufs, jnp.asarray(env_idx), jnp.asarray(time_idx))

    for k, arr in host.buffer.items():
        # host layout is [time, env, ...]; device gather returns [T, B, ...]
        expected = np.asarray(arr)[time_idx, env_idx[:, None]].swapaxes(0, 1)
        np.testing.assert_array_equal(np.asarray(got[k]), expected, err_msg=k)

    # and the windows are temporally contiguous despite the wrap: the step
    # counter stored in every slot increases by exactly 1 along T
    t_vals = np.asarray(got["actions"])[..., 0]  # [T, B]
    np.testing.assert_array_equal(np.diff(t_vals, axis=0), 1)


def test_draw_sequence_batch_in_graph_draws_valid_windows():
    """The fully in-graph draw (mask -> indices -> gather, jitted as one
    program like a fused superstep does) only ever returns windows that are
    contiguous and inside the valid set."""
    rb = _fresh()
    for t in range(2 * CAP + 5):
        rb.add(_step_data(t))

    bufs, pos, full = rb.superstep_inputs(sequence_length=4)
    draw = jax.jit(lambda key: draw_sequence_batch(bufs, pos, full, key, 16, 4))
    for s in range(5):
        batch = draw(jax.random.PRNGKey(s))
        t_vals = np.asarray(batch["actions"])[..., 0]  # [T, B]
        np.testing.assert_array_equal(np.diff(t_vals, axis=0), 1)
        # never the slot being written next (the cursor) as a window interior
        assert t_vals.min() >= 2 * CAP + 5 - CAP


def test_draw_transition_batch_next_obs_is_the_successor_step():
    rb = _fresh()
    for t in range(CAP + 3):
        rb.add(_step_data(t))
    bufs, pos, full = rb.superstep_inputs(sample_next_obs=True)
    batch = jax.jit(
        lambda key: draw_transition_batch(
            bufs, pos, full, key, 32, sample_next_obs=True, obs_keys=("observations",)
        )
    )(jax.random.PRNGKey(0))
    obs = np.asarray(batch["observations"])[..., 0]
    nxt = np.asarray(batch["next_observations"])[..., 0]
    np.testing.assert_array_equal(nxt, obs + 1)


def test_superstep_inputs_validates_like_the_sampling_paths():
    rb = _fresh()
    with pytest.raises(RuntimeError, match="has not been initialized"):
        rb.superstep_inputs(sequence_length=2)
    rb.add(_step_data(0))
    with pytest.raises(ValueError, match="Cannot sample a sequence of length"):
        rb.superstep_inputs(sequence_length=4)
    with pytest.raises(ValueError, match="next observations"):
        rb.superstep_inputs(sample_next_obs=True)
    rb.add(_step_data(1))
    bufs, pos, full = rb.superstep_inputs(sequence_length=2)
    assert set(bufs) == set(rb._bufs)
    np.testing.assert_array_equal(np.asarray(pos), rb._pos.astype(np.int32))
    np.testing.assert_array_equal(np.asarray(full), rb._full)
    # the cursor snapshot must not alias the live host mirrors (add() mutates
    # them in place while a superstep may still be queued)
    before = np.asarray(pos).copy()
    for t in range(2, 6):
        rb.add(_step_data(t))
    np.testing.assert_array_equal(np.asarray(pos), before)
