"""DevicePrefetcher specs (TPU-specific addition; SURVEY.md §2.2 note)."""

import numpy as np
import pytest

from sheeprl_tpu.data import DevicePrefetcher, ReplayBuffer


def test_prefetch_yields_device_batches():
    import jax.numpy as jnp

    rb = ReplayBuffer(buffer_size=32, seed=0)
    rb.add({"observations": np.arange(16, dtype=np.float32).reshape(16, 1, 1)})
    batches = list(DevicePrefetcher(lambda: rb.sample(4), n_batches=5))
    assert len(batches) == 5
    for b in batches:
        assert isinstance(b["observations"], jnp.ndarray)
        assert b["observations"].shape == (1, 4, 1)


def test_prefetch_zero_batches():
    assert list(DevicePrefetcher(lambda: {}, n_batches=0)) == []


def test_prefetch_negative_batches():
    with pytest.raises(ValueError):
        DevicePrefetcher(lambda: {}, n_batches=-1)


def test_prefetch_propagates_worker_error():
    def bad_sample():
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        list(DevicePrefetcher(bad_sample, n_batches=2))


def test_prefetch_dtype_cast():
    rb = ReplayBuffer(buffer_size=8, seed=0)
    rb.add({"observations": np.ones((4, 1, 1), dtype=np.uint8)})
    (batch,) = list(DevicePrefetcher(lambda: rb.sample(2), n_batches=1, dtype=np.float32))
    assert batch["observations"].dtype == np.float32


def test_prefetch_sharded():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    rb = ReplayBuffer(buffer_size=32, seed=0)
    rb.add({"observations": np.arange(16, dtype=np.float32).reshape(16, 1, 1)})
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    sharding = NamedSharding(mesh, P(None, "data"))
    (batch,) = list(DevicePrefetcher(lambda: rb.sample(8), n_batches=1, sharding=sharding))
    assert batch["observations"].sharding == sharding


def test_prefetch_early_break_and_reuse():
    rb = ReplayBuffer(buffer_size=8, seed=0)
    rb.add({"observations": np.ones((4, 1, 1), dtype=np.float32)})
    pf = DevicePrefetcher(lambda: rb.sample(2), n_batches=10)
    for i, _ in enumerate(pf):
        if i == 2:
            break
    assert pf._thread is None  # worker cleaned up on early exit
    assert len(list(pf)) == 10  # instance is reusable


def test_prefetch_error_with_full_queue_does_not_hang():
    import time

    calls = {"n": 0}

    def sample_fn():
        calls["n"] += 1
        if calls["n"] >= 4:
            raise RuntimeError("boom")
        return {"observations": np.ones((1, 1, 1), dtype=np.float32)}

    pf = DevicePrefetcher(sample_fn, n_batches=10, depth=2)
    with pytest.raises(RuntimeError, match="prefetch worker failed"):
        for _ in pf:
            time.sleep(0.05)  # slow consumer keeps the queue full


def test_prefetcher_custom_place():
    # the multi-host lane: a custom `place` callable (fabric.make_global in
    # production) replaces the default to_device staging
    import numpy as np

    from sheeprl_tpu.data.prefetch import DevicePrefetcher

    placed = []

    def place(host):
        placed.append(True)
        return {k: v + 1 for k, v in host.items()}

    pf = DevicePrefetcher(lambda: {"x": np.zeros((2,), np.float32)}, 3, place=place)
    out = list(pf)
    assert len(out) == 3 and len(placed) == 3
    assert all(np.array_equal(b["x"], np.ones((2,))) for b in out)


def test_prefetch_close_leaves_no_orphaned_batch():
    """close() drain-then-join race: a worker parked in its bounded q.put
    only re-checks the stop flag between put timeouts, so it can complete
    ONE more put after close()'s first drain. The post-join drain must
    release that batch — nothing may linger in the orphaned queue."""
    import time

    pf = DevicePrefetcher(
        lambda: {"x": np.ones((1,), np.float32)}, n_batches=100, depth=1
    )
    it = iter(pf)
    next(it)  # queue refills to depth; the worker parks in its bounded put
    time.sleep(0.3)
    q, thread = pf._queue, pf._thread
    pf.close()
    assert not thread.is_alive()
    assert q.empty(), "close() left a device batch in the orphaned queue"
    it.close()
