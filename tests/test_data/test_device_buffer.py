"""HBM-resident replay ring (data/device_buffer.py): semantic parity with
the EnvIndependent/Sequential host pair, on-device add/gather, checkpoint
round trips, and mode conversion."""

import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    adapt_restored_buffer,
    estimate_ring_bytes,
)


def _step(rb, t, envs=None, n_envs=3):
    n = n_envs if envs is None else len(envs)
    rb.add(
        {
            "rgb": np.full((1, n, 8, 8, 3), t % 256, np.uint8),
            "actions": np.full((1, n, 2), t, np.float32),
            "rewards": np.full((1, n, 1), t, np.float32),
            "terminated": np.zeros((1, n, 1), np.float32),
            "truncated": np.zeros((1, n, 1), np.float32),
            "is_first": np.zeros((1, n, 1), np.float32),
        },
        envs,
    )


def _fresh(cap=16, n_envs=3, seed=0):
    return DeviceReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), seed=seed)


def test_add_and_sample_layout_and_dtypes():
    rb = _fresh()
    for t in range(10):
        _step(rb, t)
    (batch,) = rb.sample_batches(batch_size=5, sequence_length=4, n_samples=1)
    assert batch["rgb"].shape == (4, 5, 8, 8, 3) and str(batch["rgb"].dtype) == "uint8"
    assert batch["actions"].shape == (4, 5, 2) and str(batch["actions"].dtype) == "float32"


def test_sampled_windows_are_contiguous_and_never_straddle_the_cursor():
    rb = _fresh()
    for t in range(10):
        _step(rb, t)
    # wrap the ring: cursor sits mid-ring with old data behind it
    for t in range(20, 40):
        _step(rb, t)
    assert all(rb.full)
    for batch in rb.sample_batches(batch_size=8, sequence_length=6, n_samples=4):
        rewards = np.asarray(batch["rewards"])[..., 0]  # [T, B] step counters
        assert np.all(np.diff(rewards, axis=0) == 1), rewards.T
    # amend flags of the newest step (failure-recovery patch path)
    rb.amend_last(1, terminated=0.0, truncated=1.0, is_first=0.0)
    arrs = rb.host_arrays()
    slot = (rb._pos[1] - 1) % rb.buffer_size
    assert arrs["truncated"][1, slot] == 1.0 and arrs["terminated"][1, slot] == 0.0


def test_partial_add_advances_only_those_envs():
    rb = _fresh()
    for t in range(5):
        _step(rb, t)
    _step(rb, 99, envs=[1])
    assert rb._pos.tolist() == [5, 6, 5]
    arrs = rb.host_arrays()
    assert arrs["rewards"][1, 5, 0] == 99.0
    # the other envs' slot 5 is untouched (zeros)
    assert arrs["rewards"][0, 5, 0] == 0.0


def test_too_short_history_raises_like_host_buffer():
    rb = _fresh()
    for t in range(3):
        _step(rb, t)
    with pytest.raises(ValueError, match="Cannot sample a sequence"):
        list(rb.sample_batches(batch_size=2, sequence_length=8, n_samples=1))


def test_checkpoint_flag_fixup_roundtrip():
    rb = _fresh()
    for t in range(6):
        _step(rb, t)
    saved = rb.flag_last_truncated()
    arrs = rb.host_arrays()
    slots = (rb._pos - 1) % rb.buffer_size
    assert all(arrs["truncated"][e, slots[e]] == 1.0 for e in range(3))
    rb.restore_last_truncated(saved)
    arrs = rb.host_arrays()
    assert all(arrs["truncated"][e, slots[e]] == 0.0 for e in range(3))


def test_pickle_and_mode_conversion_roundtrips():
    import pickle

    rb = _fresh()
    for t in range(12):
        _step(rb, t)
    clone = pickle.loads(pickle.dumps(rb)).restore_to_device()
    assert np.array_equal(clone.host_arrays()["rewards"], rb.host_arrays()["rewards"])

    host = rb.to_host_buffer()
    assert [b._pos for b in host.buffer] == rb._pos.tolist()
    back = DeviceReplayBuffer.from_host_buffer(host)
    assert np.array_equal(back.host_arrays()["rgb"], rb.host_arrays()["rgb"])

    # adapt_restored_buffer covers all four (restored, wanted) combinations
    assert adapt_restored_buffer(host, want_device=False) is host
    assert isinstance(adapt_restored_buffer(host, want_device=True), DeviceReplayBuffer)
    unrestored = pickle.loads(pickle.dumps(rb))
    assert isinstance(adapt_restored_buffer(unrestored, want_device=True), DeviceReplayBuffer)
    host2 = adapt_restored_buffer(pickle.loads(pickle.dumps(rb)), want_device=False)
    assert np.array_equal(host2.buffer[0]["rewards"][:, 0], rb.host_arrays()["rewards"][0])


def test_estimate_ring_bytes():
    import gymnasium as gym

    space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
            "state": gym.spaces.Box(-1, 1, (7,), np.float32),
        }
    )
    est = estimate_ring_bytes(space, actions_dim=(4,), buffer_size=100, n_envs=2)
    per_step = 64 * 64 * 3 + 7 * 4 + (4 + 4) * 4
    assert est == per_step * 100 * 2
