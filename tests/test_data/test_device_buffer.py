"""HBM-resident replay ring (data/device_buffer.py): semantic parity with
the EnvIndependent/Sequential host pair, on-device add/gather, checkpoint
round trips, and mode conversion."""

import numpy as np
import pytest

from sheeprl_tpu.data.device_buffer import (
    DeviceReplayBuffer,
    adapt_restored_buffer,
    estimate_ring_bytes,
)


def _step(rb, t, envs=None, n_envs=3):
    n = n_envs if envs is None else len(envs)
    rb.add(
        {
            "rgb": np.full((1, n, 8, 8, 3), t % 256, np.uint8),
            "actions": np.full((1, n, 2), t, np.float32),
            "rewards": np.full((1, n, 1), t, np.float32),
            "terminated": np.zeros((1, n, 1), np.float32),
            "truncated": np.zeros((1, n, 1), np.float32),
            "is_first": np.zeros((1, n, 1), np.float32),
        },
        envs,
    )


def _fresh(cap=16, n_envs=3, seed=0):
    return DeviceReplayBuffer(cap, n_envs=n_envs, obs_keys=("rgb",), seed=seed)


def test_add_and_sample_layout_and_dtypes():
    rb = _fresh()
    for t in range(10):
        _step(rb, t)
    (batch,) = rb.sample_batches(batch_size=5, sequence_length=4, n_samples=1)
    assert batch["rgb"].shape == (4, 5, 8, 8, 3) and str(batch["rgb"].dtype) == "uint8"
    assert batch["actions"].shape == (4, 5, 2) and str(batch["actions"].dtype) == "float32"


def test_sampled_windows_are_contiguous_and_never_straddle_the_cursor():
    rb = _fresh()
    for t in range(10):
        _step(rb, t)
    # wrap the ring: cursor sits mid-ring with old data behind it
    for t in range(20, 40):
        _step(rb, t)
    assert all(rb.full)
    for batch in rb.sample_batches(batch_size=8, sequence_length=6, n_samples=4):
        rewards = np.asarray(batch["rewards"])[..., 0]  # [T, B] step counters
        assert np.all(np.diff(rewards, axis=0) == 1), rewards.T
    # amend flags of the newest step (failure-recovery patch path)
    rb.amend_last(1, terminated=0.0, truncated=1.0, is_first=0.0)
    arrs = rb.host_arrays()
    slot = (rb._pos[1] - 1) % rb.buffer_size
    assert arrs["truncated"][1, slot] == 1.0 and arrs["terminated"][1, slot] == 0.0


def test_partial_add_advances_only_those_envs():
    rb = _fresh()
    for t in range(5):
        _step(rb, t)
    _step(rb, 99, envs=[1])
    assert rb._pos.tolist() == [5, 6, 5]
    arrs = rb.host_arrays()
    assert arrs["rewards"][1, 5, 0] == 99.0
    # the other envs' slot 5 is untouched (zeros)
    assert arrs["rewards"][0, 5, 0] == 0.0


def test_too_short_history_raises_like_host_buffer():
    rb = _fresh()
    for t in range(3):
        _step(rb, t)
    with pytest.raises(ValueError, match="Cannot sample a sequence"):
        list(rb.sample_batches(batch_size=2, sequence_length=8, n_samples=1))


def test_checkpoint_flag_fixup_roundtrip():
    rb = _fresh()
    for t in range(6):
        _step(rb, t)
    saved = rb.flag_last_truncated()
    arrs = rb.host_arrays()
    slots = (rb._pos - 1) % rb.buffer_size
    assert all(arrs["truncated"][e, slots[e]] == 1.0 for e in range(3))
    rb.restore_last_truncated(saved)
    arrs = rb.host_arrays()
    assert all(arrs["truncated"][e, slots[e]] == 0.0 for e in range(3))


def test_pickle_and_mode_conversion_roundtrips():
    import pickle

    rb = _fresh()
    for t in range(12):
        _step(rb, t)
    clone = pickle.loads(pickle.dumps(rb)).restore_to_device()
    assert np.array_equal(clone.host_arrays()["rewards"], rb.host_arrays()["rewards"])

    host = rb.to_host_buffer()
    assert [b._pos for b in host.buffer] == rb._pos.tolist()
    back = DeviceReplayBuffer.from_host_buffer(host)
    assert np.array_equal(back.host_arrays()["rgb"], rb.host_arrays()["rgb"])

    # adapt_restored_buffer covers all four (restored, wanted) combinations
    assert adapt_restored_buffer(host, want_device=False) is host
    assert isinstance(adapt_restored_buffer(host, want_device=True), DeviceReplayBuffer)
    unrestored = pickle.loads(pickle.dumps(rb))
    assert isinstance(adapt_restored_buffer(unrestored, want_device=True), DeviceReplayBuffer)
    host2 = adapt_restored_buffer(pickle.loads(pickle.dumps(rb)), want_device=False)
    assert np.array_equal(host2.buffer[0]["rewards"][:, 0], rb.host_arrays()["rewards"][0])


def test_estimate_ring_bytes():
    import gymnasium as gym

    space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (64, 64, 3), np.uint8),
            "state": gym.spaces.Box(-1, 1, (7,), np.float32),
        }
    )
    est = estimate_ring_bytes(space, actions_dim=(4,), buffer_size=100, n_envs=2)
    per_step = 64 * 64 * 3 + 7 * 4 + (4 + 4) * 4
    assert est == per_step * 100 * 2


# ---------------------------------------------------- transition mode (SAC)


def _sac_step(rb, t, n_envs=3):
    rb.add(
        {
            "observations": np.full((1, n_envs, 4), t, np.float32),
            "next_observations": np.full((1, n_envs, 4), t + 1, np.float32),
            "actions": np.full((1, n_envs, 2), t, np.float32),
            "rewards": np.full((1, n_envs, 1), t, np.float32),
            "terminated": np.zeros((1, n_envs, 1), np.float32),
            "truncated": np.zeros((1, n_envs, 1), np.float32),
        }
    )


def test_sample_transitions_layout_and_consistency():
    rb = DeviceReplayBuffer(16, n_envs=3, obs_keys=("observations",), seed=0)
    for t in range(10):
        _sac_step(rb, t)
    data = rb.sample_transitions(batch_size=6, n_samples=4)
    assert data["observations"].shape == (4, 6, 4)
    assert data["actions"].shape == (4, 6, 2)
    # each drawn transition is internally consistent: obs == rewards == t
    obs = np.asarray(data["observations"])[..., 0]
    rew = np.asarray(data["rewards"])[..., 0]
    nxt = np.asarray(data["next_observations"])[..., 0]
    assert np.array_equal(obs, rew) and np.array_equal(nxt, obs + 1)


def test_sample_transitions_next_obs_gather():
    rb = DeviceReplayBuffer(16, n_envs=2, obs_keys=("observations",), seed=0)
    for t in range(12):
        rb.add(
            {
                "observations": np.full((1, 2, 4), t, np.float32),
                "actions": np.zeros((1, 2, 2), np.float32),
                "rewards": np.full((1, 2, 1), t, np.float32),
                "terminated": np.zeros((1, 2, 1), np.float32),
                "truncated": np.zeros((1, 2, 1), np.float32),
            }
        )
    data = rb.sample_transitions(batch_size=8, n_samples=2, sample_next_obs=True)
    obs = np.asarray(data["observations"])[..., 0]
    nxt = np.asarray(data["next_observations"])[..., 0]
    assert np.array_equal(nxt, obs + 1)


def test_sample_transitions_wraparound_validity():
    # after wrapping, samples never come from beyond the stored range and
    # sample_next_obs never pairs a transition with the overwritten oldest slot
    rb = DeviceReplayBuffer(8, n_envs=1, obs_keys=("observations",), seed=1)
    for t in range(20):
        rb.add(
            {
                "observations": np.full((1, 1, 1), t, np.float32),
                "rewards": np.full((1, 1, 1), t, np.float32),
            }
        )
    assert all(rb.full)
    data = rb.sample_transitions(batch_size=64, n_samples=1, sample_next_obs=True)
    obs = np.asarray(data["observations"]).reshape(-1)
    nxt = np.asarray(data["next_observations"]).reshape(-1)
    assert obs.min() >= 12 and obs.max() <= 18  # stored range is 12..19; 19's next wrapped
    assert np.array_equal(nxt, obs + 1)


def test_sample_transitions_errors_match_host_contract():
    rb = DeviceReplayBuffer(8, n_envs=1, obs_keys=("observations",), seed=0)
    with pytest.raises(RuntimeError, match="has not been initialized"):
        rb.sample_transitions(batch_size=2)
    rb.add({"observations": np.zeros((1, 1, 1), np.float32)})
    # insufficient data is ValueError, matching the host ReplayBuffer
    # contract (RuntimeError stays reserved for the uninitialized ring)
    with pytest.raises(ValueError, match="at least two samples"):
        rb.sample_transitions(batch_size=2, sample_next_obs=True)
    with pytest.raises(ValueError, match="must be both greater than 0"):
        rb.sample_transitions(batch_size=0)


def test_transition_host_buffer_roundtrip():
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = DeviceReplayBuffer(16, n_envs=2, obs_keys=("observations",), seed=0)
    for t in range(10):
        _sac_step(rb, t, n_envs=2)
    host = rb.to_transition_host_buffer()
    assert isinstance(host, ReplayBuffer)
    assert host._pos == 10 and not host.full
    assert np.array_equal(
        np.asarray(host.buffer["rewards"]).swapaxes(0, 1), rb.host_arrays()["rewards"]
    )
    back = DeviceReplayBuffer.from_transition_host_buffer(host)
    assert back._pos.tolist() == [10, 10]
    assert np.array_equal(back.host_arrays()["rewards"], rb.host_arrays()["rewards"])
    # adapt_restored_buffer in transition mode, both directions
    assert isinstance(
        adapt_restored_buffer(host, want_device=True, mode="transition"), DeviceReplayBuffer
    )
    import pickle

    host2 = adapt_restored_buffer(
        pickle.loads(pickle.dumps(rb)), want_device=False, mode="transition"
    )
    assert isinstance(host2, ReplayBuffer)
    assert np.array_equal(
        np.asarray(host2.buffer["rewards"]).swapaxes(0, 1), rb.host_arrays()["rewards"]
    )


def test_estimate_transition_bytes():
    import gymnasium as gym

    from sheeprl_tpu.data.device_buffer import estimate_transition_bytes

    space = gym.spaces.Dict(
        {
            "rgb": gym.spaces.Box(0, 255, (32, 32, 3), np.uint8),
            "state": gym.spaces.Box(-1, 1, (5,), np.float32),
        }
    )
    est = estimate_transition_bytes(
        space, ["rgb", "state"], actions_dim=(2,), buffer_size=10, n_envs=2, store_next_obs=True
    )
    per_step = (32 * 32 * 3 + 5 * 4) * 2 + (2 + 3) * 4
    assert est == per_step * 10 * 2
