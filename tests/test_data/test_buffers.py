"""ReplayBuffer specs (reference: tests/test_data/test_buffers.py)."""

import numpy as np
import pytest

from sheeprl_tpu.data import ReplayBuffer


def make_data(seq_len, n_envs=1, start=0):
    obs = (start + np.arange(seq_len * n_envs)).reshape(seq_len, n_envs, 1).astype(np.float32)
    return {"observations": obs}


def test_wrong_buffer_size():
    with pytest.raises(ValueError):
        ReplayBuffer(-1)


def test_wrong_n_envs():
    with pytest.raises(ValueError):
        ReplayBuffer(1, -1)


@pytest.mark.parametrize("memmap_mode", ["r", "x", "w", "z"])
def test_wrong_memmap_mode(tmp_path, memmap_mode):
    with pytest.raises(ValueError):
        ReplayBuffer(10, memmap=True, memmap_mode=memmap_mode, memmap_dir=tmp_path)


def test_memmap_no_dir():
    with pytest.raises(ValueError):
        ReplayBuffer(10, memmap=True, memmap_dir=None)


def test_add_not_full():
    rb = ReplayBuffer(buffer_size=10, n_envs=2)
    rb.add(make_data(3, 2))
    assert not rb.full
    assert rb._pos == 3
    assert rb["observations"].shape == (10, 2, 1)


def test_add_wraps_and_overwrites():
    rb = ReplayBuffer(buffer_size=5, n_envs=1)
    rb.add(make_data(4))
    rb.add(make_data(4, start=100))
    assert rb.full
    assert rb._pos == 3
    # positions 4,0,1,2 hold the new data; position 3 holds old step 3
    buf = np.asarray(rb["observations"])[:, 0, 0]
    assert buf[4] == 100 and buf[0] == 101 and buf[1] == 102 and buf[2] == 103
    assert buf[3] == 3


def test_add_exceeding_buffer_size():
    rb = ReplayBuffer(buffer_size=4, n_envs=1)
    rb.add(make_data(11))
    assert rb.full
    # cursor consistent with writing all 11 rows; last rows retained
    assert rb._pos == 11 % 4
    buf = np.asarray(rb["observations"])[:, 0, 0]
    assert set(buf.tolist()) == {7, 8, 9, 10}
    assert buf[(rb._pos - 1) % 4] == 10


def test_add_multiple_times_exceeding():
    rb = ReplayBuffer(buffer_size=4, n_envs=1)
    for i in range(5):
        rb.add(make_data(3, start=i * 10))
    assert rb.full
    assert rb._pos == 15 % 4


def test_add_replay_buffer():
    src = ReplayBuffer(buffer_size=3, n_envs=1)
    src.add(make_data(3))
    dst = ReplayBuffer(buffer_size=5, n_envs=1)
    dst.add(src)
    assert np.array_equal(np.asarray(dst["observations"])[:3], np.asarray(src["observations"]))


def test_add_validate_errors():
    rb = ReplayBuffer(buffer_size=5)
    with pytest.raises(ValueError):
        rb.add([1, 2, 3], validate_args=True)
    with pytest.raises(ValueError):
        rb.add({"observations": [1, 2]}, validate_args=True)
    with pytest.raises(RuntimeError):
        rb.add({"observations": np.zeros((4,))}, validate_args=True)
    with pytest.raises(RuntimeError):
        rb.add(
            {"a": np.zeros((4, 1, 2)), "b": np.zeros((3, 1, 2))},
            validate_args=True,
        )


def test_sample_shape():
    rb = ReplayBuffer(buffer_size=10, n_envs=2)
    rb.add(make_data(5, 2))
    s = rb.sample(4, n_samples=3)
    assert s["observations"].shape == (3, 4, 1)


def test_sample_empty_error():
    rb = ReplayBuffer(buffer_size=10)
    with pytest.raises(RuntimeError):
        rb.sample(2)


def test_sample_no_add_error():
    rb = ReplayBuffer(buffer_size=10)
    with pytest.raises(RuntimeError):
        rb.sample(1)


def test_sample_bad_batch_size():
    rb = ReplayBuffer(buffer_size=10)
    rb.add(make_data(3))
    with pytest.raises(ValueError):
        rb.sample(0)
    with pytest.raises(ValueError):
        rb.sample(2, n_samples=0)


def test_sample_next_obs_one_element_error():
    rb = ReplayBuffer(buffer_size=10)
    rb.add(make_data(1))
    with pytest.raises(RuntimeError):
        rb.sample(1, sample_next_obs=True)


def test_sample_next_obs_not_full():
    rb = ReplayBuffer(buffer_size=10, seed=0)
    rb.add(make_data(5))
    s = rb.sample(64, sample_next_obs=True)
    assert "next_observations" in s
    # next obs is always current + 1 in our arange data
    assert np.array_equal(s["next_observations"], s["observations"] + 1)
    # never samples the last added position as current (its next is invalid)
    assert s["observations"].max() <= 3


def test_sample_next_obs_full_avoids_cursor():
    rb = ReplayBuffer(buffer_size=5, seed=0)
    rb.add(make_data(5))
    rb.add(make_data(2, start=100))  # pos=2, slots 0,1 = 100,101
    s = rb.sample(256, sample_next_obs=True)
    # the transition (pos-1 -> pos) crosses the cursor; start pos-1 is invalid
    starts = s["observations"][..., 0]
    assert 101 not in starts  # idx 1 = pos-1 is excluded
    assert 4 not in s["next_observations"][..., 0] or rb._pos != 0


def test_sample_full():
    rb = ReplayBuffer(buffer_size=5, seed=3)
    rb.add(make_data(5))
    s = rb.sample(6)
    assert s["observations"].shape == (1, 6, 1)


def test_sample_one_element():
    rb = ReplayBuffer(buffer_size=1)
    rb.add(make_data(1))
    s = rb.sample(1)
    assert s["observations"][0, 0, 0] == 0
    with pytest.raises(RuntimeError):
        rb.sample(1, sample_next_obs=True)


def test_memmap_buffer(tmp_path):
    rb = ReplayBuffer(buffer_size=10, n_envs=2, memmap=True, memmap_dir=tmp_path / "buf")
    rb.add(make_data(5, 2))
    assert rb.is_memmap
    assert (tmp_path / "buf" / "observations.memmap").exists()
    s = rb.sample(3)
    assert s["observations"].shape == (1, 3, 1)


def test_memmap_buffer_dtype_preserved(tmp_path):
    rb = ReplayBuffer(buffer_size=8, memmap=True, memmap_dir=tmp_path / "buf")
    rb.add({"x": np.ones((2, 1, 3), dtype=np.uint8)})
    assert np.asarray(rb["x"]).dtype == np.uint8


def test_obs_keys_sample_next_obs():
    rb = ReplayBuffer(buffer_size=10, obs_keys=("observations", "vector"))
    rb.add({**make_data(5), "vector": np.ones((5, 1, 3), dtype=np.float32)})
    s = rb.sample(4, sample_next_obs=True)
    assert "next_observations" in s and "next_vector" in s


def test_obs_keys_not_in_obs_no_next():
    rb = ReplayBuffer(buffer_size=10, obs_keys=("observations",))
    rb.add({**make_data(5), "reward": np.ones((5, 1, 1), dtype=np.float32)})
    s = rb.sample(4, sample_next_obs=True)
    assert "next_observations" in s and "next_reward" not in s


def test_getitem_errors():
    rb = ReplayBuffer(buffer_size=5)
    with pytest.raises(TypeError):
        rb[1]
    with pytest.raises(RuntimeError):
        rb["observations"]


def test_setitem():
    rb = ReplayBuffer(buffer_size=5, n_envs=2)
    rb.add(make_data(2, 2))
    v = np.ones((5, 2, 4), dtype=np.float32)
    rb["extra"] = v
    assert np.array_equal(np.asarray(rb["extra"]), v)
    v[0, 0, 0] = 7  # stored copy must be independent
    assert rb["extra"][0, 0, 0] == 1


def test_setitem_memmap(tmp_path):
    rb = ReplayBuffer(buffer_size=5, memmap=True, memmap_dir=tmp_path / "buf")
    rb.add(make_data(2))
    rb["extra"] = np.ones((5, 1, 2), dtype=np.float32)
    assert (tmp_path / "buf" / "extra.memmap").exists()


def test_setitem_errors():
    rb = ReplayBuffer(buffer_size=5)
    with pytest.raises(RuntimeError):
        rb["x"] = np.zeros((5, 1))
    rb.add(make_data(2))
    with pytest.raises(ValueError):
        rb["x"] = [1, 2]
    with pytest.raises(RuntimeError):
        rb["x"] = np.zeros((3, 1))


def test_sample_device():
    import jax.numpy as jnp

    rb = ReplayBuffer(buffer_size=10)
    rb.add(make_data(5))
    s = rb.sample_device(4, dtype=np.float32)
    assert isinstance(s["observations"], jnp.ndarray)
    assert s["observations"].shape == (1, 4, 1)


def test_sample_device_sharded():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    rb = ReplayBuffer(buffer_size=16)
    rb.add(make_data(16))
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("data",))
    sharding = NamedSharding(mesh, P(None, "data"))
    s = rb.sample_device(8, sharding=sharding)
    assert s["observations"].sharding == sharding


def test_state_dict_roundtrip():
    rb = ReplayBuffer(buffer_size=5)
    rb.add(make_data(7))
    state = rb.state_dict()
    rb2 = ReplayBuffer(buffer_size=5)
    rb2.add(make_data(1))
    rb2.load_state_dict(state)
    assert rb2._pos == rb._pos and rb2.full == rb.full


def test_setitem_memmap_overwrite_keeps_file(tmp_path):
    rb = ReplayBuffer(buffer_size=5, memmap=True, memmap_dir=tmp_path / "buf")
    rb.add(make_data(2))
    f = tmp_path / "buf" / "observations.memmap"
    rb["observations"] = np.ones((5, 1, 1), dtype=np.float32)
    import gc

    gc.collect()
    assert f.exists()
    assert np.asarray(rb["observations"]).sum() == 5.0
