"""Native C++ gather vs the numpy reference path."""

import numpy as np
import pytest

from sheeprl_tpu import native
from sheeprl_tpu.data.buffers import SequentialReplayBuffer

pytestmark = pytest.mark.skipif(not native.available(), reason="no C++ toolchain")


def test_gather_sequences_matches_numpy():
    rng = np.random.default_rng(0)
    size, n_envs, L, n_samples, batch = 37, 3, 5, 2, 4
    src = rng.normal(size=(size, n_envs, 6, 2)).astype(np.float32)
    starts = rng.integers(0, size, size=(n_samples * batch,))
    envs = rng.integers(0, n_envs, size=(n_samples * batch,))

    got = native.gather_sequences(src, starts, envs, L, n_samples, batch)
    assert got is not None and got.shape == (n_samples, L, batch, 6, 2)
    assert got.flags.c_contiguous

    idxes = (starts[:, None] + np.arange(L)[None, :]) % size
    want = src[idxes, np.repeat(envs[:, None], L, axis=1)]
    want = want.reshape(n_samples, batch, L, 6, 2).swapaxes(1, 2)
    np.testing.assert_array_equal(got, want)

    # shifted (next-obs) window
    got1 = native.gather_sequences(src, starts, envs, L, n_samples, batch, shift=1)
    want1 = src[(idxes + 1) % size, np.repeat(envs[:, None], L, axis=1)]
    want1 = want1.reshape(n_samples, batch, L, 6, 2).swapaxes(1, 2)
    np.testing.assert_array_equal(got1, want1)


def test_gather_sequences_wraparound():
    size, n_envs, L = 8, 2, 6
    src = np.arange(size * n_envs, dtype=np.int64).reshape(size, n_envs)
    starts = np.array([5])  # rows 5,6,7,0,1,2
    envs = np.array([1])
    got = native.gather_sequences(src, starts, envs, L, 1, 1)
    want = src[(5 + np.arange(L)) % size, 1].reshape(1, L, 1)
    np.testing.assert_array_equal(got, want)


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(1)
    size, n_envs = 19, 4
    src = rng.integers(0, 255, size=(size, n_envs, 3, 3), dtype=np.int64).astype(np.uint8)
    rows = rng.integers(0, size, size=(11,))
    envs = rng.integers(0, n_envs, size=(11,))
    got = native.gather_rows(src, rows, envs)
    np.testing.assert_array_equal(got, src[rows, envs])


def test_replay_buffer_sample_native_equals_numpy(monkeypatch):
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(buffer_size=11, n_envs=2, obs_keys=("obs",))
    rng = np.random.default_rng(5)
    for _ in range(17):
        rb.add({"obs": rng.normal(size=(1, 2, 3)).astype(np.float32)})

    kwargs = dict(batch_size=6, n_samples=3, sample_next_obs=True)
    rb._rng = np.random.default_rng(9)
    with_native = rb.sample(**kwargs)
    rb._rng = np.random.default_rng(9)
    monkeypatch.setattr(native, "gather_rows", lambda *a, **k: None)
    without = rb.sample(**kwargs)
    assert set(with_native) == set(without)
    for k in with_native:
        np.testing.assert_array_equal(with_native[k], without[k])


def test_object_dtype_falls_back():
    src = np.empty((4, 2), dtype=object)
    src[:] = [["a", "b"]] * 4
    assert native.gather_rows(src, np.array([0]), np.array([1])) is None
    assert native.gather_sequences(src, np.array([0]), np.array([1]), 2, 1, 1) is None


@pytest.mark.parametrize("dtype", [np.uint8, np.float32, np.bool_])
def test_buffer_sample_native_equals_numpy(monkeypatch, dtype):
    """SequentialReplayBuffer.sample gives bit-identical batches with the
    native gather on and off (same RNG stream)."""
    rb = SequentialReplayBuffer(buffer_size=23, n_envs=3, obs_keys=("obs",))
    rng = np.random.default_rng(2)
    for _ in range(31):  # wraps
        rb.add(
            {
                "obs": rng.normal(size=(1, 3, 4)).astype(np.float32),
                "flag": rng.integers(0, 2, size=(1, 3, 1)).astype(dtype),
            }
        )

    kwargs = dict(batch_size=4, n_samples=2, sequence_length=5, sample_next_obs=True)
    rb._rng = np.random.default_rng(7)
    with_native = rb.sample(**kwargs)

    rb._rng = np.random.default_rng(7)
    monkeypatch.setattr(native, "gather_sequences", lambda *a, **k: None)
    without = rb.sample(**kwargs)

    assert set(with_native) == set(without)
    for k in with_native:
        np.testing.assert_array_equal(with_native[k], without[k])
        assert with_native[k].dtype == without[k].dtype
