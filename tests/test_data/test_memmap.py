"""MemmapArray specs (reference: tests/test_utils/test_memmap.py)."""

import pickle

import numpy as np
import pytest

from sheeprl_tpu.data.memmap import MemmapArray


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32, np.uint8, np.bool_])
@pytest.mark.parametrize("shape", [(4,), (3, 2), (2, 3, 4)])
def test_memmap_dtype_shape(tmp_path, dtype, shape):
    m = MemmapArray(shape=shape, dtype=dtype, filename=tmp_path / "a.memmap")
    assert m.dtype == np.dtype(dtype)
    assert m.shape == tuple(shape)
    m[:] = np.ones(shape, dtype=dtype)
    assert np.array_equal(np.asarray(m), np.ones(shape, dtype=dtype))


def test_memmap_del_removes_file(tmp_path):
    f = tmp_path / "a.memmap"
    m = MemmapArray(shape=(4,), filename=f)
    assert f.exists()
    del m
    assert not f.exists()


def test_memmap_del_without_ownership_keeps_file(tmp_path):
    f = tmp_path / "a.memmap"
    m = MemmapArray(shape=(4,), filename=f)
    m.has_ownership = False
    del m
    assert f.exists()


def test_memmap_pickling_drops_ownership(tmp_path):
    f = tmp_path / "a.memmap"
    m = MemmapArray(shape=(4,), filename=f)
    m[:] = np.arange(4, dtype=np.float32)
    m2 = pickle.loads(pickle.dumps(m))
    assert not m2.has_ownership
    assert m.has_ownership
    assert np.array_equal(np.asarray(m2), np.arange(4, dtype=np.float32))
    del m2
    assert f.exists()  # the copy must not delete the owner's file


def test_memmap_set_array_from_numpy(tmp_path):
    m = MemmapArray(shape=(3, 2), filename=tmp_path / "a.memmap")
    v = np.arange(6, dtype=np.float32).reshape(3, 2)
    m.array = v
    assert np.array_equal(np.asarray(m), v)


def test_memmap_set_array_wrong_shape(tmp_path):
    m = MemmapArray(shape=(3, 2), filename=tmp_path / "a.memmap")
    with pytest.raises(ValueError):
        m.array = np.zeros((2, 2), dtype=np.float32)


def test_memmap_set_array_not_ndarray(tmp_path):
    m = MemmapArray(shape=(3,), filename=tmp_path / "a.memmap")
    with pytest.raises(ValueError):
        m.array = [1, 2, 3]


def test_memmap_from_array(tmp_path):
    v = np.arange(8, dtype=np.int32).reshape(2, 4)
    m = MemmapArray.from_array(v, filename=tmp_path / "a.memmap")
    assert np.array_equal(np.asarray(m), v)
    assert m.has_ownership


def test_memmap_from_array_same_file_transfers_ownership(tmp_path):
    f = tmp_path / "a.memmap"
    m1 = MemmapArray(shape=(4,), filename=f)
    m1[:] = np.arange(4, dtype=np.float32)
    m2 = MemmapArray.from_array(m1, filename=f)
    assert not m1.has_ownership
    assert m2.has_ownership
    del m1
    assert f.exists()
    assert np.array_equal(np.asarray(m2), np.arange(4, dtype=np.float32))


def test_memmap_from_array_different_filename_copies(tmp_path):
    m1 = MemmapArray(shape=(4,), filename=tmp_path / "a.memmap")
    m1[:] = np.arange(4, dtype=np.float32)
    m2 = MemmapArray.from_array(m1, filename=tmp_path / "b.memmap")
    assert m1.has_ownership and m2.has_ownership
    m2[:] = 0
    assert np.array_equal(np.asarray(m1), np.arange(4, dtype=np.float32))


@pytest.mark.parametrize("mode", ["r", "x", "a"])
def test_memmap_invalid_mode(tmp_path, mode):
    with pytest.raises(ValueError):
        MemmapArray(shape=(4,), mode=mode, filename=tmp_path / "a.memmap")


def test_memmap_ndarray_ops(tmp_path):
    m = MemmapArray(shape=(4,), filename=tmp_path / "a.memmap")
    m[:] = np.ones(4, dtype=np.float32)
    assert np.array_equal(m + 1, np.full(4, 2.0, dtype=np.float32))
    assert (m.sum(), len(m)) == (4.0, 4)


def test_memmap_from_array_same_file_wplus_does_not_truncate(tmp_path):
    f = tmp_path / "a.memmap"
    m1 = MemmapArray(shape=(4,), filename=f)
    m1[:] = np.arange(4, dtype=np.float32)
    m2 = MemmapArray.from_array(m1, mode="w+", filename=f)
    assert np.array_equal(np.asarray(m2), np.arange(4, dtype=np.float32))


def test_memmap_unpickle_wplus_does_not_truncate(tmp_path):
    f = tmp_path / "a.memmap"
    m1 = MemmapArray(shape=(4,), mode="w+", filename=f)
    m1[:] = np.arange(4, dtype=np.float32)
    m1.array.flush()
    m2 = pickle.loads(pickle.dumps(m1))
    assert np.array_equal(np.asarray(m2), np.arange(4, dtype=np.float32))
    assert np.array_equal(np.asarray(m1), np.arange(4, dtype=np.float32))
