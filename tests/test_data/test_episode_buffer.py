"""EpisodeBuffer specs (reference: tests/test_data/test_episode_buffer.py)."""

import numpy as np
import pytest

from sheeprl_tpu.data import EpisodeBuffer


def make_episode(ep_len, n_envs=1, end=True, start=0):
    """[seq_len, n_envs, ...] data ending (or not) with a done."""
    obs = (start + np.arange(ep_len * n_envs)).reshape(ep_len, n_envs, 1).astype(np.float32)
    terminated = np.zeros((ep_len, n_envs, 1), dtype=np.float32)
    truncated = np.zeros((ep_len, n_envs, 1), dtype=np.float32)
    if end:
        terminated[-1] = 1
    return {"observations": obs, "terminated": terminated, "truncated": truncated}


def test_wrong_sizes():
    with pytest.raises(ValueError):
        EpisodeBuffer(-1, 10)
    with pytest.raises(ValueError):
        EpisodeBuffer(10, -1)
    with pytest.raises(ValueError):
        EpisodeBuffer(5, 10)


@pytest.mark.parametrize("memmap_mode", ["r", "x"])
def test_wrong_memmap_mode(tmp_path, memmap_mode):
    with pytest.raises(ValueError):
        EpisodeBuffer(10, 2, memmap=True, memmap_mode=memmap_mode, memmap_dir=tmp_path)


def test_add_complete_episode():
    eb = EpisodeBuffer(buffer_size=50, minimum_episode_length=3)
    eb.add(make_episode(10))
    assert len(eb.buffer) == 1
    assert len(eb) == 10


def test_add_open_episode_not_stored():
    eb = EpisodeBuffer(buffer_size=50, minimum_episode_length=3)
    eb.add(make_episode(10, end=False))
    assert len(eb.buffer) == 0
    assert len(eb._open_episodes[0]) == 1


def test_add_chunked_episode():
    eb = EpisodeBuffer(buffer_size=50, minimum_episode_length=3)
    eb.add(make_episode(5, end=False))
    eb.add(make_episode(5, end=True, start=5))
    assert len(eb.buffer) == 1
    assert len(eb) == 10
    assert np.array_equal(
        eb.buffer[0]["observations"][:, 0], np.arange(10, dtype=np.float32)
    )


def test_add_multiple_episodes_in_one_call():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2)
    data = make_episode(10)
    # insert a mid-sequence done at t=4 -> two episodes (0..4, 5..9)
    data["terminated"][4] = 1
    eb.add(data)
    assert len(eb.buffer) == 2
    assert len(eb) == 10


def test_add_multi_env():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, n_envs=3)
    eb.add(make_episode(6, n_envs=3))
    assert len(eb.buffer) == 3


def test_add_only_for_some_envs():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, n_envs=4)
    eb.add(make_episode(6, n_envs=2), env_idxes=[1, 3])
    assert len(eb.buffer) == 2
    assert len(eb._open_episodes[0]) == 0 and len(eb._open_episodes[2]) == 0


def test_add_env_idxes_out_of_range():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, n_envs=2)
    with pytest.raises(ValueError):
        eb.add(make_episode(6, n_envs=2), env_idxes=[0, 5], validate_args=True)


def test_add_missing_done_keys():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2)
    with pytest.raises(RuntimeError):
        eb.add({"observations": np.zeros((5, 1, 1))}, validate_args=True)


def test_save_episode_too_short():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=5)
    with pytest.raises(RuntimeError):
        eb.add(make_episode(3))


def test_save_episode_too_long():
    eb = EpisodeBuffer(buffer_size=10, minimum_episode_length=2)
    with pytest.raises(RuntimeError):
        eb.add(make_episode(11))


def test_eviction_of_oldest():
    eb = EpisodeBuffer(buffer_size=20, minimum_episode_length=2)
    eb.add(make_episode(8, start=0))
    eb.add(make_episode(8, start=100))
    eb.add(make_episode(8, start=200))  # 24 > 20: evict the first
    assert len(eb.buffer) == 2
    assert eb.buffer[0]["observations"][0, 0] == 100
    assert len(eb) == 16


def test_full_property():
    eb = EpisodeBuffer(buffer_size=10, minimum_episode_length=4)
    assert not eb.full
    eb.add(make_episode(8))
    assert eb.full  # 8 + 4 > 10


def test_sample_shapes():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, seed=0)
    eb.add(make_episode(20))
    s = eb.sample(4, n_samples=3, sequence_length=5)
    assert s["observations"].shape == (3, 5, 4, 1)


def test_sample_sequences_within_episode():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, seed=0)
    eb.add(make_episode(10, start=0))
    eb.add(make_episode(10, start=100))
    s = eb.sample(32, sequence_length=4)
    obs = s["observations"][0, :, :, 0]  # [L, B]
    assert np.all(np.diff(obs, axis=0) == 1)  # contiguous => never crosses episodes


def test_sample_one_element():
    eb = EpisodeBuffer(buffer_size=10, minimum_episode_length=1, seed=0)
    eb.add(make_episode(1))
    s = eb.sample(1, sequence_length=1)
    assert s["observations"].shape == (1, 1, 1, 1)


def test_sample_too_long_error():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2)
    eb.add(make_episode(5))
    with pytest.raises(RuntimeError):
        eb.sample(1, sequence_length=6)


def test_sample_empty_error():
    eb = EpisodeBuffer(buffer_size=10, minimum_episode_length=2)
    with pytest.raises(RuntimeError):
        eb.sample(1, sequence_length=2)


def test_sample_bad_args():
    eb = EpisodeBuffer(buffer_size=10, minimum_episode_length=2)
    with pytest.raises(ValueError):
        eb.sample(0)
    with pytest.raises(ValueError):
        eb.sample(1, n_samples=0)


def test_prioritize_ends_biases_toward_tail():
    eb_uniform = EpisodeBuffer(buffer_size=1000, minimum_episode_length=2, seed=0)
    eb_ends = EpisodeBuffer(buffer_size=1000, minimum_episode_length=2, prioritize_ends=True, seed=0)
    eb_uniform.add(make_episode(100))
    eb_ends.add(make_episode(100))
    L = 10
    s_uniform = eb_uniform.sample(512, sequence_length=L)
    s_ends = eb_ends.sample(512, sequence_length=L)
    # the last possible window ends at 99; prioritized sampling should pick the
    # final window far more often
    tail_uniform = (s_uniform["observations"][0, -1, :, 0] == 99).mean()
    tail_ends = (s_ends["observations"][0, -1, :, 0] == 99).mean()
    assert tail_ends > tail_uniform


def test_sample_next_obs():
    eb = EpisodeBuffer(buffer_size=100, minimum_episode_length=2, seed=0)
    eb.add(make_episode(10))
    s = eb.sample(8, sequence_length=3, sample_next_obs=True)
    assert np.array_equal(s["next_observations"], s["observations"] + 1)


def test_memmap_episode_buffer(tmp_path):
    eb = EpisodeBuffer(buffer_size=50, minimum_episode_length=2, memmap=True, memmap_dir=tmp_path / "eb")
    eb.add(make_episode(10))
    assert len(list((tmp_path / "eb").iterdir())) == 1
    s = eb.sample(2, sequence_length=3)
    assert s["observations"].shape == (1, 3, 2, 1)


def test_memmap_eviction_removes_files(tmp_path):
    eb = EpisodeBuffer(buffer_size=16, minimum_episode_length=2, memmap=True, memmap_dir=tmp_path / "eb")
    eb.add(make_episode(8))
    eb.add(make_episode(8, start=100))
    eb.add(make_episode(8, start=200))
    assert len(list((tmp_path / "eb").iterdir())) == len(eb.buffer)


def test_sample_device():
    import jax.numpy as jnp

    eb = EpisodeBuffer(buffer_size=50, minimum_episode_length=2, seed=0)
    eb.add(make_episode(10))
    s = eb.sample_device(2, sequence_length=3)
    assert isinstance(s["observations"], jnp.ndarray)
