"""Jittable-env parity tests (ISSUE PR 10 tentpole).

The fused on-policy superstep (``algo.fused_rollout``) replaces gymnasium's
CartPole/Pendulum with the pure-functional twins in
``sheeprl_tpu/envs/jittable.py`` — these tests pin the twins to the
reference physics transition-by-transition, so a drift in constants or
integration order fails here, not as a silent learning regression.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jittable import JaxCartPole, JaxPendulum, get_jittable_env


def test_registry_lookup():
    assert get_jittable_env("CartPole-v1") is JaxCartPole
    assert get_jittable_env("Pendulum-v1") is JaxPendulum
    assert get_jittable_env("Acrobot-v1") is None


def test_cartpole_transition_parity():
    """Same state + action => same next obs / reward / terminated as
    gymnasium, across random interior and near-threshold states."""
    env = gym.make("CartPole-v1")
    env.reset(seed=0)
    step = jax.jit(JaxCartPole.step)
    rng = np.random.default_rng(0)
    states = list(rng.uniform(-0.05, 0.05, size=(100, 4)))
    # near the termination thresholds: x = +-2.4, theta = +-12 degrees
    states += [
        np.array([2.39, 1.0, 0.0, 0.0]),
        np.array([-2.39, -1.0, 0.0, 0.0]),
        np.array([0.0, 0.0, 0.2094, 1.0]),
        np.array([0.0, 0.0, -0.2094, -1.0]),
    ]
    for i, s in enumerate(states):
        a = int(rng.integers(0, 2))
        env.reset(seed=i)
        env.unwrapped.state = np.asarray(s, np.float64)
        obs_ref, reward_ref, term_ref, _trunc, _ = env.step(a)
        state = {"y": jnp.asarray(s, jnp.float32), "t": jnp.int32(0)}
        _next_state, out = step(state, jnp.int32(a), jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(out.obs), obs_ref, atol=1e-5)
        assert bool(out.terminated) == bool(term_ref)
        assert float(out.reward) == float(reward_ref)
    env.close()


def test_cartpole_truncation_at_500():
    state = {"y": jnp.zeros((4,), jnp.float32), "t": jnp.int32(499)}
    _, out = JaxCartPole.step(state, jnp.int32(0), jax.random.PRNGKey(0))
    assert bool(out.truncated)
    state = {"y": jnp.zeros((4,), jnp.float32), "t": jnp.int32(42)}
    _, out = JaxCartPole.step(state, jnp.int32(0), jax.random.PRNGKey(0))
    assert not bool(out.truncated)


def test_cartpole_init_matches_gym_bounds():
    keys = jax.random.split(jax.random.PRNGKey(0), 256)
    states = jax.vmap(JaxCartPole.init)(keys)
    y = np.asarray(states["y"])
    assert y.shape == (256, 4)
    assert np.all(np.abs(y) <= 0.05)
    assert np.all(np.asarray(states["t"]) == 0)
    # the reset stream actually varies
    assert np.std(y) > 1e-3


def test_pendulum_transition_parity():
    env = gym.make("Pendulum-v1")
    env.reset(seed=0)
    step = jax.jit(JaxPendulum.step)
    rng = np.random.default_rng(1)
    for i in range(100):
        th = rng.uniform(-np.pi, np.pi)
        thdot = rng.uniform(-8.0, 8.0)
        u = rng.uniform(-3.0, 3.0, size=1)  # out-of-range torque exercises the clip
        env.reset(seed=i)
        env.unwrapped.state = np.array([th, thdot])
        obs_ref, reward_ref, _term, _trunc, _ = env.step(u.astype(np.float32))
        state = {"y": jnp.asarray([th, thdot], jnp.float32), "t": jnp.int32(0)}
        _ns, out = step(state, jnp.asarray(u, jnp.float32), jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(out.obs), obs_ref, atol=1e-4)
        assert float(out.reward) == pytest.approx(float(reward_ref), abs=1e-3)
        assert not bool(out.terminated)
    env.close()


def test_pendulum_truncation_at_200():
    state = {"y": jnp.zeros((2,), jnp.float32), "t": jnp.int32(199)}
    _, out = JaxPendulum.step(state, jnp.zeros((1,), jnp.float32), jax.random.PRNGKey(0))
    assert bool(out.truncated)


def test_spec_metadata():
    assert JaxCartPole.obs_dim == 4 and JaxCartPole.action_dim == 2
    assert not JaxCartPole.is_continuous
    assert JaxPendulum.obs_dim == 3 and JaxPendulum.action_dim == 1
    assert JaxPendulum.is_continuous
    obs = JaxCartPole.observation(JaxCartPole.init(jax.random.PRNGKey(0)))
    assert obs.shape == (4,)
    obs = JaxPendulum.observation(JaxPendulum.init(jax.random.PRNGKey(0)))
    assert obs.shape == (3,)
