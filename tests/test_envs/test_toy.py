"""PixelCatcher — the self-contained pixel learning task (envs/toy.py)."""

import numpy as np

from sheeprl_tpu.envs.toy import PixelCatcher


def test_pixel_catcher_contract():
    env = PixelCatcher(seed=3)
    obs, info = env.reset(seed=3)
    assert set(obs) == {"rgb"} and obs["rgb"].shape == (64, 64, 3)
    assert obs["rgb"].dtype == np.uint8
    assert env.observation_space["rgb"].contains(obs["rgb"])
    for _ in range(50):
        obs, r, term, trunc, info = env.step(env.action_space.sample())
        assert r in (-1.0, 0.0, 1.0) and not trunc
        assert env.observation_space["rgb"].contains(obs["rgb"])
        if term:
            env.reset()


def test_pixel_catcher_miss_terminates_and_cap_truncates():
    # a miss is a (pixel-predictable) termination
    env = PixelCatcher(seed=0, episode_pellets=3)
    env.reset(seed=0)
    for _ in range(1000):
        _, r, term, trunc, info = env.step(0)  # hug the left wall: will miss
        if term:
            assert r == -1.0 and not trunc
            break
    else:
        raise AssertionError("wall-hugging never missed")

    # perfect play runs into the pellet cap -> truncation, return == cap
    env = PixelCatcher(seed=1, episode_pellets=3)
    env.reset(seed=1)
    total = 0.0
    for _ in range(1000):
        a = 0 if env._pellet[0] < env._paddle_x else (2 if env._pellet[0] > env._paddle_x else 1)
        _, r, term, trunc, info = env.step(a)
        total += r
        if trunc:
            assert not term and info["caught"] == 3 and total == 3.0
            break
    else:
        raise AssertionError("oracle never reached the pellet cap")


def test_pixel_catcher_oracle_beats_random():
    """The task is solvable from its state (and thus from pixels): a greedy
    pellet-tracker catches everything, random play mostly misses."""

    def rollout(policy, seed, steps=3000):
        env = PixelCatcher(seed=seed)
        env.reset(seed=seed)
        total = n = 0
        for _ in range(steps):
            _, r, term, trunc, _ = env.step(policy(env))
            if r != 0.0:
                total += r
                n += 1
            if term or trunc:
                env.reset()
        return total / max(n, 1)

    oracle = rollout(
        lambda e: 0 if e._pellet[0] < e._paddle_x else (2 if e._pellet[0] > e._paddle_x else 1),
        seed=1,
    )
    random = rollout(lambda e: e.action_space.sample(), seed=2)
    assert oracle == 1.0
    assert random < 0.0


def test_pixel_catcher_through_make_env_factory():
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.envs import make_env
    from sheeprl_tpu.utils.utils import dotdict

    cfg = dotdict(
        compose(
            "config",
            [
                "exp=dreamer_v3",
                "env=pixel_catcher",
                "env.capture_video=False",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]",
            ],
        )
    )
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (64, 64, 3)
    env.close()


def test_pixel_catcher_continuous_actions():
    env = PixelCatcher(seed=0, continuous_actions=True)
    assert env.action_space.shape == (1,)
    env.reset(seed=0)
    x0 = env._paddle_x
    env.step(np.array([1.0], np.float32))
    assert env._paddle_x == x0 + env._paddle_speed
    env.step(np.array([-1.0], np.float32))
    env.step(np.array([-1.0], np.float32))
    assert env._paddle_x == x0 - env._paddle_speed

    # oracle still catches everything through the continuous interface
    env = PixelCatcher(seed=1, continuous_actions=True, episode_pellets=3)
    env.reset(seed=1)
    total = 0.0
    for _ in range(1000):
        delta = env._pellet[0] - env._paddle_x
        a = np.array([np.clip(delta, -1, 1)], np.float32)
        _, r, term, trunc, info = env.step(a)
        total += r
        if trunc:
            assert info["caught"] == 3 and total == 3.0
            break
        assert not term
    else:
        raise AssertionError("continuous oracle never hit the pellet cap")
