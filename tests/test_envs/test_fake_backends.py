"""Fake-backend tests for the four adapters whose binaries are absent from
CI: DMC, DIAMBRA, Super Mario Bros and MineRL (VERDICT round-3 item 7).

Same technique as the Crafter test (test_env_adapters.py): stub the minimal
external API surface in sys.modules, import the adapter fresh, and drive its
obs/action remap end to end — spec→Box conversion and action rescaling for
DMC, sub-space normalization for DIAMBRA, the joypad menu and clock-based
termination split for Mario, and the action menu / camera clamp / multi-hot
inventory encoding for MineRL (incl. the navigate/obtain custom specs).
"""

import importlib
import sys
import types

import numpy as np
import pytest

# --------------------------------------------------------------------- DMC


def _install_fake_dmc(monkeypatch):
    dm_env = types.ModuleType("dm_env")
    specs_mod = types.ModuleType("dm_env.specs")

    class Array:
        def __init__(self, shape, dtype=np.float64, name=None):
            self.shape = tuple(shape)
            self.dtype = dtype
            self.name = name

    class BoundedArray(Array):
        def __init__(self, shape, dtype=np.float64, minimum=-1.0, maximum=1.0, name=None):
            super().__init__(shape, dtype, name)
            self.minimum = np.asarray(minimum)
            self.maximum = np.asarray(maximum)

    specs_mod.Array = Array
    specs_mod.BoundedArray = BoundedArray
    dm_env.specs = specs_mod

    class TimeStep:
        def __init__(self, observation, reward, discount, last):
            self.observation = observation
            self.reward = reward
            self.discount = discount
            self._last = last

        def last(self):
            return self._last

    class FakePhysics:
        def get_state(self):
            return np.arange(3, dtype=np.float64)

        def render(self, height, width, camera_id=0):
            return np.full((height, width, 3), 7, np.uint8)

    class FakeTask:
        _random = None

    class FakeDmcEnv:
        def __init__(self):
            self.physics = FakePhysics()
            self.task = FakeTask()
            self.received_actions = []
            self._steps = 0

        def action_spec(self):
            # true bounds [0, 10] x2: exercises the [-1, 1] rescale
            return BoundedArray((2,), np.float64, minimum=0.0, maximum=10.0)

        def reward_spec(self):
            return BoundedArray((), np.float64, minimum=0.0, maximum=1.0)

        def observation_spec(self):
            return {
                "position": BoundedArray((2,), np.float64, minimum=-5.0, maximum=5.0),
                "velocity": Array((3,), np.float64),
            }

        def reset(self):
            self._steps = 0
            return TimeStep({"position": np.zeros(2), "velocity": np.ones(3)}, None, 1.0, False)

        def step(self, action):
            self.received_actions.append(np.asarray(action))
            self._steps += 1
            # 3rd step ends by time limit (discount 1), 5th by termination
            last = self._steps in (3, 5)
            discount = 0.0 if self._steps == 5 else 1.0
            obs = {"position": np.full(2, self._steps, np.float64), "velocity": np.ones(3)}
            return TimeStep(obs, 0.5, discount, last)

        def close(self):
            pass

    suite_mod = types.ModuleType("dm_control.suite")
    fake_env_holder = {}

    def load(domain_name, task_name, task_kwargs=None, visualize_reward=False, environment_kwargs=None):
        env = FakeDmcEnv()
        fake_env_holder["env"] = env
        return env

    suite_mod.load = load
    dm_control = types.ModuleType("dm_control")
    dm_control.suite = suite_mod
    monkeypatch.setitem(sys.modules, "dm_env", dm_env)
    monkeypatch.setitem(sys.modules, "dm_env.specs", specs_mod)
    monkeypatch.setitem(sys.modules, "dm_control", dm_control)
    monkeypatch.setitem(sys.modules, "dm_control.suite", suite_mod)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_DMC_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)
    return fake_env_holder


def test_dmc_adapter_with_fake_backend(monkeypatch):
    holder = _install_fake_dmc(monkeypatch)
    dmc_mod = importlib.import_module("sheeprl_tpu.envs.dmc")

    env = dmc_mod.DMCWrapper("walker", "walk", from_pixels=True, from_vectors=True, height=16, width=16, seed=3)
    # spec -> Box: bounded position [-5, 5] concat unbounded velocity
    state_space = env.observation_space["state"]
    assert state_space.shape == (5,)
    assert np.allclose(state_space.low[:2], -5) and np.isneginf(state_space.low[2:]).all()
    assert env.action_space.shape == (2,) and np.allclose(env.action_space.low, -1)

    obs, _ = env.reset(seed=11)
    assert holder["env"].task._random is not None  # seeding reached the task
    assert obs["rgb"].shape == (16, 16, 3) and obs["rgb"].dtype == np.uint8
    assert obs["state"].shape == (5,)

    # [-1, 1] -> [0, 10] rescale: -1 -> 0, 0 -> 5, +1 -> 10
    env.step(np.array([-1.0, 1.0], np.float32))
    assert np.allclose(holder["env"].received_actions[-1], [0.0, 10.0])
    env.step(np.array([0.0, 0.0], np.float32))
    assert np.allclose(holder["env"].received_actions[-1], [5.0, 5.0])

    # discount-based split: step 3 is a time limit, step 5 a termination
    _, _, terminated, truncated, info = env.step(np.zeros(2, np.float32))
    assert truncated and not terminated and info["discount"] == 1.0
    env.step(np.zeros(2, np.float32))
    _, _, terminated, truncated, info = env.step(np.zeros(2, np.float32))
    assert terminated and not truncated and info["discount"] == 0.0
    assert info["internal_state"].shape == (3,)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)


def test_dmc_adapter_rejects_no_obs_source(monkeypatch):
    _install_fake_dmc(monkeypatch)
    dmc_mod = importlib.import_module("sheeprl_tpu.envs.dmc")
    with pytest.raises(ValueError, match="must not be both False"):
        dmc_mod.DMCWrapper("walker", "walk", from_pixels=False, from_vectors=False)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)


def test_dmc_variant_wrappers_with_fake_backend(monkeypatch):
    """The fork-experiment variants layer distractor observations on the base
    adapter (reference dmc_64.py:153-201 / dmc_extended.py): every declared
    space must be produced at reset AND step, with the combined scalar mixing
    pixel[0,0,0] with state[0]."""
    _install_fake_dmc(monkeypatch)
    sys.modules.pop("sheeprl_tpu.envs.dmc_variants", None)
    variants = importlib.import_module("sheeprl_tpu.envs.dmc_variants")

    env = variants.DMC64Wrapper("walker", "walk", from_pixels=True, from_vectors=True, height=16, width=16)
    assert env.observation_space["camera_rgb"].shape == (64, 64, 1)
    assert env.observation_space["camera_depth"].shape == (64, 64, 1)
    for obs in (env.reset()[0], env.step(np.zeros(2, np.float32))[0]):
        assert set(obs) == set(env.observation_space.spaces)
        for k, space in env.observation_space.spaces.items():
            assert obs[k].shape == space.shape, k

    env = variants.DMCExtendedWrapper("walker", "walk", from_pixels=True, from_vectors=True, height=16, width=16)
    assert env.observation_space["random_img"].shape == (16, 16, 3)
    assert env.observation_space["random_values"].shape == (10,)
    obs, _ = env.reset()
    assert set(obs) == set(env.observation_space.spaces)
    assert np.isclose(obs["combined_values"][0], float(obs["rgb"][0, 0, 0]) + float(obs["state"][0]))

    # vectors-only: no distractors beyond the base spaces
    env = variants.DMCExtendedWrapper("walker", "walk", from_pixels=False, from_vectors=True)
    assert set(env.observation_space.spaces) == {"state"}
    sys.modules.pop("sheeprl_tpu.envs.dmc_variants", None)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)


def test_dmc_through_env_factory(monkeypatch):
    """Drive the full factory path (``env=dmc`` config -> make_env thunk ->
    wrapped Dict obs env) against the fake backend — the adapter contract the
    reference exercises with real dm_control (sheeprl/envs/dmc.py:49-244)."""
    _install_fake_dmc(monkeypatch)
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)

    from sheeprl_tpu.config import compose
    from sheeprl_tpu.envs.factory import make_env
    from sheeprl_tpu.utils.utils import dotdict

    cfg = dotdict(
        compose(
            "config",
            [
                "exp=dreamer_v3",
                "env=dmc",
                "env.capture_video=False",
                "env.screen_size=16",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]",
                "metric.log_level=0",
            ],
        )
    )
    env = make_env(cfg, seed=7, rank=0)()
    try:
        obs, _ = env.reset(seed=7)
        assert obs["rgb"].shape == (16, 16, 3) and obs["rgb"].dtype == np.uint8
        # action_repeat=2 (the dmc recipe): one env.step drives two backend steps
        obs, reward, terminated, truncated, info = env.step(env.action_space.sample())
        assert obs["rgb"].shape == (16, 16, 3)
        assert np.isclose(reward, 1.0)  # 2 backend steps x 0.5 reward each
    finally:
        env.close()
    sys.modules.pop("sheeprl_tpu.envs.dmc", None)


# ------------------------------------------------------------------ DIAMBRA


def _install_fake_diambra(monkeypatch):
    import gymnasium as gym

    class Settings(dict):
        """diambra settings object: kwargs-dict with attribute access."""

        def __init__(self, **kwargs):
            super().__init__(**kwargs)

        def __setattr__(self, k, v):
            self[k] = v

        def __getattr__(self, k):
            try:
                return self[k]
            except KeyError:
                raise AttributeError(k)

    class FakeEngine(gym.Env):
        def __init__(self, settings, wrappers):
            self.settings = settings
            self.wrappers = wrappers
            self.observation_space = gym.spaces.Dict(
                {
                    "frame": gym.spaces.Box(0, 255, (64, 64, 1), np.uint8),
                    "stage": gym.spaces.Discrete(4),
                    "moves": gym.spaces.MultiDiscrete([3, 5]),
                }
            )
            self.action_space = gym.spaces.Discrete(6)
            self._steps = 0

        def reset(self, seed=None, options=None):
            self._steps = 0
            return self._obs(), {}

        def _obs(self):
            return {
                "frame": np.zeros((64, 64, 1), np.uint8),
                "stage": 2,  # scalar: the adapter must reshape to (1,)
                "moves": np.array([1, 4]),
            }

        def step(self, action):
            self._steps += 1
            info = {"env_done": self._steps >= 3}
            return self._obs(), 1.0, False, False, info

        def close(self):
            pass

    made = {}

    def make(game_id, settings, wrappers, rank=0, render_mode="rgb_array", log_level=0):
        engine = FakeEngine(settings, wrappers)
        made["engine"] = engine
        return engine

    class SpaceTypes:
        DISCRETE = "discrete"
        MULTI_DISCRETE = "multi_discrete"

    class Roles:
        P1 = "p1"
        P2 = "p2"

    arena = types.ModuleType("diambra.arena")
    arena.make = make
    arena.EnvironmentSettings = Settings
    arena.WrappersSettings = Settings
    arena.SpaceTypes = SpaceTypes
    arena.Roles = Roles
    diambra = types.ModuleType("diambra")
    diambra.arena = arena
    monkeypatch.setitem(sys.modules, "diambra", diambra)
    monkeypatch.setitem(sys.modules, "diambra.arena", arena)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_DIAMBRA_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.diambra", None)
    return made


def test_diambra_adapter_with_fake_backend(monkeypatch):
    import gymnasium as gym

    made = _install_fake_diambra(monkeypatch)
    diambra_mod = importlib.import_module("sheeprl_tpu.envs.diambra")

    with pytest.warns(UserWarning, match="managed by the wrapper"):
        env = diambra_mod.DiambraWrapper(
            "doapp",
            repeat_action=2,
            diambra_settings={"frame_shape": (128, 128, 0), "difficulty": 3},
            diambra_wrappers={"stack_frames": 4},
        )
    # managed keys stripped, user keys kept, step_ratio forced under repeat
    assert made["engine"].settings["difficulty"] == 3
    assert made["engine"].settings["step_ratio"] == 1
    assert made["engine"].wrappers["flatten"] is True
    # engine-side resize (increase_performance default)
    assert made["engine"].settings["frame_shape"] == (64, 64, 0)

    # Discrete/MultiDiscrete sub-spaces re-expressed as int32 Boxes
    assert isinstance(env.observation_space["stage"], gym.spaces.Box)
    assert env.observation_space["stage"].dtype == np.int32
    assert env.observation_space["moves"].shape == (2,)

    obs, info = env.reset()
    assert info["env_domain"] == "DIAMBRA"
    assert obs["stage"].shape == (1,) and obs["moves"].shape == (2,)

    # numpy discrete action unwraps to a python int; env_done -> terminated
    env.step(np.array([2]))
    env.step(np.array(1))
    _, _, terminated, truncated, info = env.step(3)
    assert terminated and not truncated
    with pytest.raises(ValueError, match="action_space must be"):
        diambra_mod.DiambraWrapper("doapp", action_space="BOGUS")
    sys.modules.pop("sheeprl_tpu.envs.diambra", None)


# ------------------------------------------------------------------- Mario


def _install_fake_mario(monkeypatch):
    class FakeNes:
        """old-gym NES env: 4-tuple step, bare reset, info['time'] clock."""

        class observation_space:
            low = np.zeros((240, 256, 3), np.uint8)
            high = np.full((240, 256, 3), 255, np.uint8)
            shape = (240, 256, 3)
            dtype = np.dtype(np.uint8)

        def __init__(self):
            self._steps = 0
            self.reset_seeds = []

        def reset(self, seed=None, options=None):
            self.reset_seeds.append(seed)
            self._steps = 0
            return np.zeros((240, 256, 3), np.uint8)

        def step(self, action):
            assert isinstance(action, int)
            self._steps += 1
            done = self._steps >= 2
            # first episode ends with clock running (truncated), info set below
            return np.zeros((240, 256, 3), np.uint8), 1.0, done, {"time": self.clock}

        def render(self, mode="rgb_array"):
            return np.zeros((240, 256, 3), np.uint8)

        clock = 250

    class FakeJoypad:
        def __init__(self, env, menu):
            self.env = env
            self.menu = menu
            self.observation_space = env.observation_space

        def step(self, action):
            return self.env.step(action)

        def reset(self):
            return self.env.reset()

        def render(self, mode="rgb_array"):
            return self.env.render(mode)

    gsm = types.ModuleType("gym_super_mario_bros")
    gsm.make = lambda id: FakeNes()
    actions = types.ModuleType("gym_super_mario_bros.actions")
    actions.RIGHT_ONLY = [["NOOP"], ["right"]]
    actions.SIMPLE_MOVEMENT = [["NOOP"], ["right"], ["right", "A"]]
    actions.COMPLEX_MOVEMENT = [["NOOP"]] * 12
    gsm.actions = actions
    nes_py = types.ModuleType("nes_py")
    wrappers = types.ModuleType("nes_py.wrappers")
    wrappers.JoypadSpace = FakeJoypad
    nes_py.wrappers = wrappers
    monkeypatch.setitem(sys.modules, "gym_super_mario_bros", gsm)
    monkeypatch.setitem(sys.modules, "gym_super_mario_bros.actions", actions)
    monkeypatch.setitem(sys.modules, "nes_py", nes_py)
    monkeypatch.setitem(sys.modules, "nes_py.wrappers", wrappers)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_SUPER_MARIO_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.super_mario_bros", None)
    return FakeNes


def test_mario_adapter_with_fake_backend(monkeypatch):
    FakeNes = _install_fake_mario(monkeypatch)
    mario_mod = importlib.import_module("sheeprl_tpu.envs.super_mario_bros")

    env = mario_mod.SuperMarioBrosWrapper("SuperMarioBros-v0", action_space="simple")
    assert env.action_space.n == 3  # SIMPLE_MOVEMENT menu length
    obs, _ = env.reset(seed=5)
    assert env.raw.env.reset_seeds == [5]  # seed bypasses JoypadSpace
    assert set(obs) == {"rgb"} and obs["rgb"].shape == (240, 256, 3)

    # clock running at episode end => truncated (timeout death is a cutoff)
    env.step(np.array([1]))
    _, _, terminated, truncated, _ = env.step(np.array(1))
    assert truncated and not terminated

    # clock at zero => real termination
    FakeNes.clock = 0
    env.reset()
    env.step(np.array(0))
    _, _, terminated, truncated, _ = env.step(np.array(0))
    assert terminated and not truncated
    FakeNes.clock = 250
    sys.modules.pop("sheeprl_tpu.envs.super_mario_bros", None)


# ------------------------------------------------------------------ MineRL


ALL_ITEMS = ["air", "compass", "dirt", "log", "planks", "stick", "diamond", "iron_pickaxe"]
KEYMAP = {
    "forward": 17, "back": 31, "left": 30, "right": 32,
    "jump": 57, "sneak": 42, "sprint": 29, "attack": -100, "use": -99,
}


def _install_fake_minerl(monkeypatch):
    class Handler:
        pass

    class Enum:
        def __init__(self, values):
            self.values = np.asarray(list(values))

    class _Recorder(Handler):
        def __init__(self, *args, **kwargs):
            self.args = args
            self.kwargs = kwargs

    class KeybasedCommandAction(_Recorder):
        def __init__(self, key, keycode):
            super().__init__(key, keycode)
            self.key = key

    class CameraAction(_Recorder):
        key = "camera"

    def enum_handler(key_name):
        class H(_Recorder):
            key = key_name

            def __init__(self, values, *a, **k):
                super().__init__(values, *a, **k)
                self.values = list(values)

        H.__name__ = f"Enum_{key_name}"
        return H

    PlaceBlock = enum_handler("place")
    EquipAction = enum_handler("equip")
    CraftAction = enum_handler("craft")
    CraftNearbyAction = enum_handler("nearbyCraft")
    SmeltItemNearby = enum_handler("nearbySmelt")

    class FlatInventoryObservation(_Recorder):
        def __init__(self, items):
            super().__init__(items)
            self.items = list(items)

    class EquippedItemObservation(_Recorder):
        def __init__(self, items, _default="air", _other="other"):
            super().__init__(items)
            self.items = list(items)

    class CompassObservation(_Recorder):
        pass

    class POVObservation(_Recorder):
        pass

    plain = (
        "ObservationFromCurrentLocation", "ObservationFromLifeStats",
        "TimeInitialCondition", "WeatherInitialCondition", "SpawningInitialCondition",
        "ServerQuitWhenAnyAgentFinishes", "DefaultWorldGenerator",
        "SimpleInventoryAgentStart", "AgentQuitFromTouchingBlockType",
        "RewardForTouchingBlockType", "RewardForDistanceTraveledToCompassTarget",
        "BiomeGenerator", "NavigationDecorator", "RewardForCollectingItemsOnce",
        "RewardForCollectingItems", "AgentQuitFromPossessingItem",
        "AgentQuitFromCraftingItem",
    )

    class FakeDictSpace:
        def __init__(self, entries):
            self.spaces = dict(entries)

        def __iter__(self):
            return iter(self.spaces)

        def __getitem__(self, k):
            return self.spaces[k]

    class FakeRawMineRL:
        """Raw env assembled from the spec's handler tables — the adapter's
        menu/obs construction sees exactly what the spec declared."""

        def __init__(self, spec):
            self.spec = spec
            self.commands = []
            act = {}
            for h in spec.create_actionables():
                if isinstance(h, KeybasedCommandAction):
                    act[h.key] = object()
                elif isinstance(h, CameraAction):
                    act["camera"] = object()
                else:
                    act[h.key] = Enum(h.values)
            self.action_space = FakeDictSpace(act)

            obs = {"pov": object(), "life_stats": object()}
            for h in spec.create_observables():
                if isinstance(h, FlatInventoryObservation):
                    obs["inventory"] = FakeDictSpace({i: object() for i in h.items})
                elif isinstance(h, EquippedItemObservation):
                    obs["equipped_items"] = FakeDictSpace(
                        {"mainhand": FakeDictSpace({"type": Enum(h.items)})}
                    )
                elif isinstance(h, CompassObservation):
                    obs["compass"] = object()
            self.observation_space = FakeDictSpace(obs)

        def _obs(self):
            # inventory keyed by the task's declared FlatInventoryObservation
            # items (what the real backend reports)
            inv_items = (
                list(self.observation_space["inventory"].spaces)
                if "inventory" in self.observation_space.spaces
                else []
            )
            raw = {
                "pov": np.full((64, 64, 3), 9, np.uint8),
                "life_stats": {"life": 20.0, "food": 18.0, "air": 300.0},
                "inventory": {i: (3 if i == "dirt" else 0) for i in inv_items},
            }
            if "compass" in self.observation_space.spaces:
                raw["compass"] = {"angle": np.array([42.0])}
            if "equipped_items" in self.observation_space.spaces:
                raw["equipped_items"] = {"mainhand": {"type": "air"}}
            return raw

        def reset(self):
            return self._obs()

        def step(self, command):
            self.commands.append(command)
            return self._obs(), 1.0, False, {}

    class EnvSpec:
        def __init__(self, name=None, *args, max_episode_steps=None, **kwargs):
            self.name = name
            self.max_episode_steps = max_episode_steps

        def make(self):
            return FakeRawMineRL(self)

    minerl = types.ModuleType("minerl")
    herobraine = types.ModuleType("minerl.herobraine")
    hero = types.ModuleType("minerl.herobraine.hero")
    mc = types.ModuleType("minerl.herobraine.hero.mc")
    mc.ALL_ITEMS = list(ALL_ITEMS)
    mc.INVERSE_KEYMAP = dict(KEYMAP)
    spaces_mod = types.ModuleType("minerl.herobraine.hero.spaces")
    spaces_mod.Enum = Enum
    handler_mod = types.ModuleType("minerl.herobraine.hero.handler")
    handler_mod.Handler = Handler
    handlers_mod = types.ModuleType("minerl.herobraine.hero.handlers")
    handlers_mod.KeybasedCommandAction = KeybasedCommandAction
    handlers_mod.CameraAction = CameraAction
    handlers_mod.PlaceBlock = PlaceBlock
    handlers_mod.EquipAction = EquipAction
    handlers_mod.CraftAction = CraftAction
    handlers_mod.CraftNearbyAction = CraftNearbyAction
    handlers_mod.SmeltItemNearby = SmeltItemNearby
    handlers_mod.FlatInventoryObservation = FlatInventoryObservation
    handlers_mod.EquippedItemObservation = EquippedItemObservation
    handlers_mod.CompassObservation = CompassObservation
    handlers_mod.POVObservation = POVObservation
    for name in plain:
        setattr(handlers_mod, name, type(name, (_Recorder,), {}))
    env_spec_mod = types.ModuleType("minerl.herobraine.env_spec")
    env_spec_mod.EnvSpec = EnvSpec

    hero.mc = mc
    hero.spaces = spaces_mod
    hero.handler = handler_mod
    hero.handlers = handlers_mod
    herobraine.hero = hero
    herobraine.env_spec = env_spec_mod
    minerl.herobraine = herobraine
    for mod_name, mod in [
        ("minerl", minerl),
        ("minerl.herobraine", herobraine),
        ("minerl.herobraine.hero", hero),
        ("minerl.herobraine.hero.mc", mc),
        ("minerl.herobraine.hero.spaces", spaces_mod),
        ("minerl.herobraine.hero.handler", handler_mod),
        ("minerl.herobraine.hero.handlers", handlers_mod),
        ("minerl.herobraine.env_spec", env_spec_mod),
    ]:
        monkeypatch.setitem(sys.modules, mod_name, mod)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_MINERL_AVAILABLE", True)
    for mod in [
        "sheeprl_tpu.envs.minerl",
        "sheeprl_tpu.envs.minerl_envs.backend",
        "sheeprl_tpu.envs.minerl_envs.navigate",
        "sheeprl_tpu.envs.minerl_envs.obtain",
    ]:
        sys.modules.pop(mod, None)


def _cleanup_minerl_modules():
    for mod in [
        "sheeprl_tpu.envs.minerl",
        "sheeprl_tpu.envs.minerl_envs.backend",
        "sheeprl_tpu.envs.minerl_envs.navigate",
        "sheeprl_tpu.envs.minerl_envs.obtain",
    ]:
        sys.modules.pop(mod, None)


def test_minerl_navigate_adapter_with_fake_backend(monkeypatch):
    _install_fake_minerl(monkeypatch)
    minerl_mod = importlib.import_module("sheeprl_tpu.envs.minerl")

    env = minerl_mod.MineRLWrapper(
        "custom_navigate", dense=True, extreme=False, seed=7, multihot_inventory=True
    )
    menu = env.action_menu
    assert menu[0] == {}  # no-op entry
    # 8 keyboard keys + 4 camera moves + "dirt" place + no-op
    assert len(menu) == 1 + 8 + 4 + 1
    # jump/sneak/sprint imply forward
    jump_entries = [e for e in menu.values() if e.get("jump") == 1]
    assert jump_entries and all(e["forward"] == 1 for e in jump_entries)
    # enum entry for place=dirt exists ("none" excluded)
    assert {"place": "dirt"} in menu.values()
    # camera entries are the four fixed moves
    cameras = [e["camera"] for e in menu.values() if "camera" in e]
    assert len(cameras) == 4

    obs, _ = env.reset(seed=7)
    assert obs["rgb"].shape == (64, 64, 3)
    assert obs["compass"].shape == (1,) and obs["compass"][0] == 42.0
    # multi-hot inventory against the global item table
    assert obs["inventory"].shape == (len(ALL_ITEMS),)
    assert obs["inventory"][ALL_ITEMS.index("dirt")] == 3
    assert np.array_equal(obs["max_inventory"], obs["inventory"])
    assert obs["life_stats"].tolist() == [20.0, 18.0, 300.0]
    # the air-counts-as-1 rule (air stacks are unbounded in the raw counts)
    packed = env._pack_observation(
        {
            "pov": np.zeros((64, 64, 3), np.uint8),
            "life_stats": {"life": 20.0, "food": 20.0, "air": 300.0},
            "inventory": {"air": 64, "dirt": 2},
            "compass": {"angle": np.array([0.0])},
        }
    )
    assert packed["inventory"][ALL_ITEMS.index("air")] == 1
    # max_inventory is monotonic: dirt high-water mark stays 3
    assert packed["max_inventory"][ALL_ITEMS.index("dirt")] == 3

    # action translation: camera pitch clamp at the limits
    pitch_down = next(
        i for i, e in enumerate(menu.values()) if "camera" in e and e["camera"][0] < 0
    )
    for _ in range(5):
        env.step(np.array(pitch_down))  # -15 x 5 = -75 < limit -60
    sent = env.raw.commands
    # the 5th pitch move would cross -60: camera zeroed on the pitch axis
    assert sent[4]["camera"][0] == 0
    assert sum(c["camera"][0] for c in sent) == -60.0
    _cleanup_minerl_modules()


def test_minerl_obtain_adapter_non_multihot(monkeypatch):
    _install_fake_minerl(monkeypatch)
    minerl_mod = importlib.import_module("sheeprl_tpu.envs.minerl")

    env = minerl_mod.MineRLWrapper("custom_obtain_diamond", dense=False, multihot_inventory=False)
    # task-local inventory indexing: 18 tracked items
    assert env.observation_space["inventory"].shape == (18,)
    # equipment one-hot over the task's equip enum (air + 6 tools + other)
    assert env.observation_space["equipment"].shape == (8,)
    obs, _ = env.reset()
    assert obs["equipment"].sum() == 1  # exactly one held item
    assert "compass" not in obs  # obtain tasks have no compass

    # enum menu entries route to the right command key
    craft_entries = [e for e in env.action_menu.values() if "nearbyCraft" in e]
    assert craft_entries and all(v != "none" for e in craft_entries for v in e.values())
    env.step(np.array(0))
    assert env.raw.commands[-1]["craft"] == "none"  # no-op keeps full NOOP dict
    _cleanup_minerl_modules()


def test_minerl_custom_spec_tables(monkeypatch):
    _install_fake_minerl(monkeypatch)
    navigate = importlib.import_module("sheeprl_tpu.envs.minerl_envs.navigate")
    obtain = importlib.import_module("sheeprl_tpu.envs.minerl_envs.obtain")

    nav = navigate.CustomNavigate(dense=True, extreme=True, break_speed=100)
    assert nav.name == "CustomMineRLNavigateExtremeDense-v0"
    assert nav.is_from_folder("navigateextreme")
    # dense variant adds the distance-shaping reward
    rewardables = nav.create_rewardables()
    assert len(rewardables) == 2
    # extreme variant generates the mountain biome
    gens = nav.create_server_world_generators()
    assert type(gens[0]).__name__ == "BiomeGenerator"
    assert nav.determine_success_from_rewards([100.0, 60.0])
    assert not nav.determine_success_from_rewards([100.0])

    dia = obtain.CustomObtainDiamond(dense=False)
    ladder = dia.reward_schedule
    assert ladder[-1] == {"type": "diamond", "amount": 1, "reward": 1024}
    assert type(dia.create_rewardables()[0]).__name__ == "RewardForCollectingItemsOnce"
    dense_dia = obtain.CustomObtainDiamond(dense=True)
    assert type(dense_dia.create_rewardables()[0]).__name__ == "RewardForCollectingItems"

    pick = obtain.CustomObtainIronPickaxe(dense=False)
    assert type(pick.create_agent_handlers()[0]).__name__ == "AgentQuitFromCraftingItem"
    # success = hitting every DISTINCT rung within 10% slack (reference
    # obtain.py:160-168 parity, including its set-vs-duplicates quirk: the
    # stock ladders repeat values 4 and 32, so they can never fully "hit")
    custom = obtain.CustomObtain(
        target_item="log",
        dense=False,
        reward_schedule=[
            dict(type="log", amount=1, reward=1),
            dict(type="planks", amount=1, reward=2),
            dict(type="stick", amount=1, reward=4),
        ],
    )
    assert custom.determine_success_from_rewards([1, 2, 4])
    assert not custom.determine_success_from_rewards([1, 2])
    _cleanup_minerl_modules()
