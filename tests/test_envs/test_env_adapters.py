"""Environment long-tail adapters (reference: sheeprl/envs/*).

The suite binaries (crafter, minedojo, minerl, diambra, nes-py) are not
installed in CI, so these tests check (a) the import gates raise cleanly,
(b) the config tree dispatches to the right wrapper target, and (c) the
adapters work against fakes where the external API is small enough to stub.
"""

import importlib
import sys
import types

import numpy as np
import pytest

from sheeprl_tpu.config import compose

ADAPTERS = {
    "crafter": ("sheeprl_tpu.envs.crafter", "crafter"),
    "minedojo": ("sheeprl_tpu.envs.minedojo", "minedojo"),
    "minerl": ("sheeprl_tpu.envs.minerl", "minerl"),
    "diambra": ("sheeprl_tpu.envs.diambra", "diambra"),
    "super_mario_bros": ("sheeprl_tpu.envs.super_mario_bros", "gym_super_mario_bros"),
}


@pytest.mark.parametrize("adapter_module,dep", ADAPTERS.values(), ids=list(ADAPTERS))
def test_adapter_import_gate(adapter_module, dep):
    """Without the binary, importing the adapter raises ModuleNotFoundError
    with an actionable message (reference import-gate contract)."""
    if importlib.util.find_spec(dep) is not None:
        pytest.skip(f"{dep} installed; gate not exercised")
    with pytest.raises(ModuleNotFoundError):
        importlib.import_module(adapter_module)


@pytest.mark.parametrize(
    "env_name,target",
    [
        ("atari", "gymnasium.wrappers.AtariPreprocessing"),
        ("crafter", "sheeprl_tpu.envs.crafter.CrafterWrapper"),
        ("minedojo", "sheeprl_tpu.envs.minedojo.MineDojoWrapper"),
        ("minerl", "sheeprl_tpu.envs.minerl.MineRLWrapper"),
        ("diambra", "sheeprl_tpu.envs.diambra.DiambraWrapper"),
        ("super_mario_bros", "sheeprl_tpu.envs.super_mario_bros.SuperMarioBrosWrapper"),
        ("dmc_64", "sheeprl_tpu.envs.dmc_variants.DMC64Wrapper"),
        ("dmc_extended", "sheeprl_tpu.envs.dmc_variants.DMCExtendedWrapper"),
    ],
)
def test_env_config_dispatch(env_name, target):
    cfg = compose("config", [f"env={env_name}", "exp=ppo", "algo.mlp_keys.encoder=[state]"])
    assert cfg["env"]["wrapper"]["_target_"] == target


def test_crafter_adapter_with_fake_backend(monkeypatch):
    """Drive the Crafter adapter against a stub crafter module: obs dict-ify,
    discount-based terminated/truncated split, seeding."""
    import gymnasium as gym

    class FakeCrafterEnv(gym.Env):
        def __init__(self, size, seed, reward):
            self.observation_space = gym.spaces.Box(0, 255, (*size, 3), np.uint8)
            self.action_space = gym.spaces.Discrete(4)
            self.reward_range = (0.0, 1.0)
            self._steps = 0
            self._seed = seed

        def reset(self):
            self._steps = 0
            return np.zeros(self.observation_space.shape, np.uint8)

        def step(self, action):
            self._steps += 1
            done = self._steps >= 3
            # discount 0 => true termination; != 0 => time limit
            info = {"discount": 0 if self._steps % 2 else 1}
            return np.zeros(self.observation_space.shape, np.uint8), 1.0, done, info

        def render(self):
            return np.zeros(self.observation_space.shape, np.uint8)

    fake = types.ModuleType("crafter")
    fake.Env = FakeCrafterEnv
    monkeypatch.setitem(sys.modules, "crafter", fake)
    monkeypatch.setattr("sheeprl_tpu.utils.imports._IS_CRAFTER_AVAILABLE", True)
    sys.modules.pop("sheeprl_tpu.envs.crafter", None)
    crafter_mod = importlib.import_module("sheeprl_tpu.envs.crafter")

    env = crafter_mod.CrafterWrapper("crafter_reward", 32, seed=3)
    obs, _ = env.reset(seed=3)
    assert set(obs) == {"rgb"} and obs["rgb"].shape == (32, 32, 3)
    for _ in range(2):
        obs, reward, terminated, truncated, _ = env.step(0)
    assert {"rgb"} == set(obs)
    obs, reward, terminated, truncated, _ = env.step(0)
    assert terminated or truncated
    sys.modules.pop("sheeprl_tpu.envs.crafter", None)


def test_minedojo_actor_masks():
    """sample_minedojo_actions never picks masked-out entries and routes the
    craft/equip/destroy masks by the sampled action type (reference
    dreamer_v3/agent.py:848-932)."""
    import jax
    import jax.numpy as jnp

    from sheeprl_tpu.algos.dreamer_v3.agent import MinedojoActor, sample_minedojo_actions

    actions_dim = (19, 6, 10)
    actor = MinedojoActor(
        latent_state_size=8,
        actions_dim=actions_dim,
        is_continuous=False,
        dense_units=8,
        mlp_layers=1,
    )
    latent = jnp.zeros((4, 8), jnp.float32)
    params = actor.init(jax.random.PRNGKey(0), latent)

    mask = {
        # only composite actions 0 and 15 (craft) allowed
        "mask_action_type": jnp.asarray([[False] * 19], bool)
        .at[0, 0]
        .set(True)
        .at[0, 15]
        .set(True)
        .repeat(4, axis=0),
        "mask_craft_smelt": jnp.asarray([[True, False, False, False, False, False]], bool).repeat(4, axis=0),
        "mask_equip_place": jnp.ones((4, 10), bool),
        "mask_destroy": jnp.ones((4, 10), bool),
    }
    for seed in range(5):
        acts = sample_minedojo_actions(actor, params, latent, jax.random.PRNGKey(seed), mask)
        a0 = np.argmax(np.asarray(acts[:, :19]), -1)
        a1 = np.argmax(np.asarray(acts[:, 19:25]), -1)
        assert set(a0.tolist()) <= {0, 15}
        # whenever craft was selected, only craft-slot 0 is allowed
        assert all(a1[i] == 0 for i in range(4) if a0[i] == 15)
