"""Jittable pixel envs (envs/jittable_pixels.py) — ISSUE PR 19 satellite.

Pins the rendering determinism contract (jitted and eager draws produce
byte-identical uint8 frames), the host gymnasium adapter, the registry
lazy-import, and a Dreamer-V3 smoke over the pixel pointmass.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.envs.jittable import get_jittable_env
from sheeprl_tpu.envs.jittable_pixels import (
    JittablePixelEnv,
    make_pixel_pendulum_spec,
    make_pixel_pointmass_spec,
)


@pytest.mark.parametrize("factory", [make_pixel_pointmass_spec, make_pixel_pendulum_spec])
def test_render_determinism_jit_vs_eager(factory):
    """The same state renders to BYTE-IDENTICAL uint8 frames jitted and
    eager — the contract that lets the replay buffer and the on-device
    pipeline disagree about where frames are produced without drift."""
    spec = factory(size=32)
    render_jit = jax.jit(spec.observation)
    step_jit = jax.jit(spec.step)
    key = jax.random.PRNGKey(0)
    state = spec.init(key)
    for i in range(20):
        frame_eager = np.asarray(spec.observation(state))
        frame_jit = np.asarray(render_jit(state))
        assert frame_eager.dtype == np.uint8 and frame_jit.dtype == np.uint8
        np.testing.assert_array_equal(frame_jit, frame_eager)
        a = jnp.sin(jnp.arange(spec.action_dim, dtype=jnp.float32) + i)
        k = jax.random.fold_in(key, i)
        state_e, out_e = spec.step(state, a, k)
        state_j, out_j = step_jit(state, a, k)
        np.testing.assert_array_equal(np.asarray(out_j.obs), np.asarray(out_e.obs))
        state = state_j


@pytest.mark.parametrize("factory", [make_pixel_pointmass_spec, make_pixel_pendulum_spec])
def test_render_vmaps_and_matches_sequential(factory):
    spec = factory(size=16)
    keys = jax.random.split(jax.random.PRNGKey(7), 5)
    states = jax.vmap(spec.init)(keys)
    frames = np.asarray(jax.vmap(spec.observation)(states))
    assert frames.shape == (5, 16, 16, 3) and frames.dtype == np.uint8
    for i in range(5):
        one = jax.tree.map(lambda x: x[i], states)
        np.testing.assert_array_equal(frames[i], np.asarray(spec.observation(one)))


def test_registry_lazy_import():
    spec = get_jittable_env("PixelPointmass-v0")
    assert spec is not None and spec.obs_shape == (64, 64, 3)
    spec = get_jittable_env("PixelPendulum-v0")
    assert spec is not None and spec.action_dim == 1


def test_adapter_contract_and_truncation():
    env = JittablePixelEnv(id="PixelPointmass-v0", size=32, seed=3)
    obs, _ = env.reset(seed=3)
    assert set(obs) == {"rgb"} and obs["rgb"].shape == (32, 32, 3)
    assert obs["rgb"].dtype == np.uint8
    assert env.observation_space["rgb"].contains(obs["rgb"])
    for t in range(1, 101):
        obs, r, term, trunc, _ = env.step(env.action_space.sample())
        assert 0.0 <= r <= 1.0 and not term
        assert env.observation_space["rgb"].contains(obs["rgb"])
        assert trunc == (t == 100)


def test_adapter_seeded_reproducibility():
    def rollout(seed):
        env = JittablePixelEnv(id="PixelPendulum-v0", size=16, seed=seed)
        obs, _ = env.reset(seed=seed)
        frames, rewards = [obs["rgb"]], []
        for i in range(10):
            a = np.array([np.sin(i)], np.float32)
            obs, r, *_ = env.step(a)
            frames.append(obs["rgb"])
            rewards.append(r)
        return frames, rewards

    f1, r1 = rollout(11)
    f2, r2 = rollout(11)
    for a, b in zip(f1, f2):
        np.testing.assert_array_equal(a, b)
    assert r1 == r2


def test_pointmass_goal_seeking_beats_random():
    """Solvable from state (and thus pixels): steering at the target earns
    far more than random play over one 100-step episode."""

    def episode(policy, seed):
        env = JittablePixelEnv(id="PixelPointmass-v0", size=16, seed=seed)
        env.reset(seed=seed)
        total = 0.0
        for _ in range(100):
            _, r, _, trunc, _ = env.step(policy(env))
            total += r
            if trunc:
                break
        return total

    def greedy(env):
        pos = np.asarray(env._state["y"][:2])
        return np.clip((np.array([0.5, 0.5]) - pos) * 20.0, -1.0, 1.0).astype(np.float32)

    assert episode(greedy, seed=1) > 80.0
    assert episode(lambda e: e.action_space.sample(), seed=2) < 40.0


def test_through_make_env_factory():
    from sheeprl_tpu.config.compose import compose
    from sheeprl_tpu.envs import make_env
    from sheeprl_tpu.utils.utils import dotdict

    cfg = dotdict(
        compose(
            "config",
            [
                "exp=dreamer_v3",
                "env=pixel_pointmass",
                "env.screen_size=16",
                "env.capture_video=False",
                "algo.cnn_keys.encoder=[rgb]",
                "algo.mlp_keys.encoder=[]",
            ],
        )
    )
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (16, 16, 3) and obs["rgb"].dtype == np.uint8
    env.close()


@pytest.mark.slow
def test_dreamer_v3_pixel_pointmass_smoke(tmp_path, monkeypatch):
    """One Dreamer-V3 update end-to-end over the jittable pixel pointmass —
    the pixel-pipeline benchmark with no dm_control/ALE dependency."""
    import os

    from sheeprl_tpu.cli import run

    monkeypatch.chdir(tmp_path)
    run(
        [
            "exp=dreamer_v3",
            "env=pixel_pointmass",
            "env.screen_size=16",
            "dry_run=True",
            "env.capture_video=False",
            "buffer.memmap=False",
            "algo.per_rank_batch_size=1",
            "algo.per_rank_sequence_length=1",
            "buffer.size=8",
            "algo.learning_starts=0",
            "algo.replay_ratio=1",
            "algo.horizon=8",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.world_model.encoder.cnn_channels_multiplier=2",
            "algo.world_model.recurrent_model.recurrent_state_size=8",
            "algo.world_model.representation_model.hidden_size=8",
            "algo.world_model.transition_model.hidden_size=8",
            "algo.world_model.discrete_size=4",
            "algo.world_model.stochastic_size=4",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
            "env.num_envs=2",
            "algo.run_test=False",
            "checkpoint.save_last=True",
            "metric.log_level=1",
            f"log_base_dir={tmp_path}/logs",
        ]
    )
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert ckpts
