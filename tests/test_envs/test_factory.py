"""make_env factory specs (reference: sheeprl/utils/env.py:25-227 contract)."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs import make_env
from sheeprl_tpu.utils.utils import dotdict


def base_cfg(**env_overrides):
    env = {
        "id": "dummy_discrete",
        "num_envs": 1,
        "frame_stack": 1,
        "sync_env": True,
        "screen_size": 64,
        "action_repeat": 1,
        "grayscale": False,
        "clip_rewards": False,
        "capture_video": False,
        "frame_stack_dilation": 1,
        "max_episode_steps": None,
        "reward_as_observation": False,
        "wrapper": {"_target_": "sheeprl_tpu.envs.dummy.get_dummy_env", "id": "dummy_discrete"},
    }
    env.update(env_overrides)
    return dotdict(
        {
            "env": env,
            "algo": {"cnn_keys": {"encoder": ["rgb"]}, "mlp_keys": {"encoder": ["state"]}},
        }
    )


def test_dummy_env_dict_obs():
    env = make_env(base_cfg(), seed=0, rank=0)()
    obs, _ = env.reset()
    assert set(obs.keys()) >= {"rgb", "state"}
    assert obs["rgb"].shape == (64, 64, 3)
    assert obs["rgb"].dtype == np.uint8


def test_gym_vector_env_mlp_only():
    cfg = base_cfg(
        id="CartPole-v1",
        wrapper={"_target_": "gymnasium.make", "id": "CartPole-v1"},
    )
    cfg.algo.cnn_keys.encoder = []
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert set(obs.keys()) == {"state"}
    assert obs["state"].shape == (4,)


def test_gym_pixel_obs_from_render():
    cfg = base_cfg(
        id="CartPole-v1",
        wrapper={"_target_": "gymnasium.make", "id": "CartPole-v1", "render_mode": "rgb_array"},
        screen_size=32,
        grayscale=True,
    )
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset(seed=0)
    assert obs["rgb"].shape == (32, 32, 1)
    assert obs["state"].shape == (4,)


def test_frame_stack_integration():
    cfg = base_cfg(frame_stack=3, screen_size=16)
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert obs["rgb"].shape == (3, 16, 16, 3)


def test_action_repeat_integration():
    cfg = base_cfg(action_repeat=2)
    env = make_env(cfg, seed=0, rank=0)()
    env.reset()
    env.step(env.action_space.sample())


def test_reward_as_observation_integration():
    cfg = base_cfg(reward_as_observation=True)
    env = make_env(cfg, seed=0, rank=0)()
    obs, _ = env.reset()
    assert "reward" in obs


def test_time_limit_integration():
    cfg = base_cfg(max_episode_steps=3, id="dummy_continuous")
    cfg.env.wrapper["id"] = "dummy_continuous"
    env = make_env(cfg, seed=0, rank=0)()
    env.reset()
    for _ in range(2):
        _, _, done, trunc, _ = env.step(env.action_space.sample())
    _, _, done, trunc, _ = env.step(env.action_space.sample())
    assert trunc


def test_bad_keys_error():
    cfg = base_cfg()
    cfg.algo.cnn_keys.encoder = ["nope"]
    cfg.algo.mlp_keys.encoder = ["also_nope"]
    with pytest.raises(ValueError, match="not a subset"):
        make_env(cfg, seed=0, rank=0)()


def test_no_keys_error():
    cfg = base_cfg()
    cfg.algo.cnn_keys.encoder = []
    cfg.algo.mlp_keys.encoder = []
    with pytest.raises(ValueError):
        make_env(cfg, seed=0, rank=0)()


def test_pixel_only_env_requires_cnn_key():
    cfg = base_cfg(
        id="CarRacing-v3",
        wrapper={"_target_": "gymnasium.make", "id": "CarRacing-v3"},
    )
    cfg.algo.cnn_keys.encoder = []
    cfg.algo.mlp_keys.encoder = ["state"]
    with pytest.raises(ValueError, match="no cnn key"):
        make_env(cfg, seed=0, rank=0)()


def test_episode_statistics_present():
    cfg = base_cfg(id="dummy_discrete")
    env = make_env(cfg, seed=0, rank=0)()
    env.reset()
    done = trunc = False
    info = {}
    while not (done or trunc):
        _, _, done, trunc, info = env.step(env.action_space.sample())
    assert "episode" in info


def test_async_vector_env():
    cfg = base_cfg()
    envs = gym.vector.AsyncVectorEnv([make_env(cfg, seed=i, rank=0) for i in range(2)])
    obs, _ = envs.reset()
    assert obs["rgb"].shape == (2, 64, 64, 3)
    envs.close()
