"""DMC adapter specs (reference: sheeprl/envs/dmc.py contract)."""

import numpy as np
import pytest

from sheeprl_tpu.utils.imports import _IS_DMC_AVAILABLE

if not _IS_DMC_AVAILABLE:
    pytest.skip("dm_control not installed", allow_module_level=True)

import os

# headless rendering backend (the adapter defaults to EGL too)
os.environ.setdefault("MUJOCO_GL", "egl")


@pytest.fixture(scope="module")
def vector_env():
    from sheeprl_tpu.envs.dmc import DMCWrapper

    return DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=True, seed=0)


def test_vector_obs_space(vector_env):
    obs, _ = vector_env.reset(seed=0)
    assert set(obs.keys()) == {"state"}
    assert obs["state"].shape == vector_env.observation_space["state"].shape


def test_action_space_normalized(vector_env):
    assert (vector_env.action_space.low == -1).all()
    assert (vector_env.action_space.high == 1).all()


def test_step_contract(vector_env):
    vector_env.reset(seed=0)
    obs, reward, terminated, truncated, info = vector_env.step(vector_env.action_space.sample())
    assert np.isfinite(reward)
    assert "discount" in info and "internal_state" in info
    assert not terminated  # first steps of cartpole-balance never terminate


def test_time_limit_truncates(vector_env):
    vector_env.reset(seed=0)
    terminated = truncated = False
    steps = 0
    while not (terminated or truncated) and steps < 2000:
        _, _, terminated, truncated, _ = vector_env.step(vector_env.action_space.sample())
        steps += 1
    assert truncated and not terminated  # dm_control ends by time limit


def test_both_false_raises():
    from sheeprl_tpu.envs.dmc import DMCWrapper

    with pytest.raises(ValueError):
        DMCWrapper("cartpole", "balance", from_pixels=False, from_vectors=False)


@pytest.mark.skipif(os.environ.get("SHEEPRL_TPU_SKIP_RENDER_TESTS") == "1", reason="no GL")
def test_pixel_obs_nhwc():
    # EGL rendering segfaults when sharing a process with jax/torch GL state,
    # so probe the pixel path in a clean subprocess
    import subprocess
    import sys

    code = (
        "from sheeprl_tpu.envs.dmc import DMCWrapper\n"
        "import numpy as np\n"
        "try:\n"
        "    env = DMCWrapper('cartpole', 'balance', from_pixels=True, from_vectors=True,"
        " height=32, width=32, seed=0)\n"
        "    obs, _ = env.reset(seed=0)\n"
        "except Exception as e:\n"
        "    print('BACKEND_UNAVAILABLE:', e)\n"
        "    raise SystemExit(0)\n"
        "assert obs['rgb'].shape == (32, 32, 3), obs['rgb'].shape\n"
        "assert obs['rgb'].dtype == np.uint8\n"
        "assert obs['state'].ndim == 1\n"
        "print('PIXEL_OK')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "MUJOCO_GL": "egl", "JAX_PLATFORMS": "cpu"},
    )
    if "BACKEND_UNAVAILABLE" in proc.stdout:
        pytest.skip(f"mujoco rendering unavailable: {proc.stdout[-200:]}")
    # a real contract violation (wrong layout/dtype) must FAIL, not skip
    assert proc.returncode == 0 and "PIXEL_OK" in proc.stdout, proc.stdout + proc.stderr[-500:]
