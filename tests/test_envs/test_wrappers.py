"""Wrapper specs (reference: sheeprl/envs/wrappers.py behaviors)."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs.dummy import DiscreteDummyEnv
from sheeprl_tpu.envs.wrappers import (
    ActionRepeat,
    DictObservation,
    FrameStack,
    ImageTransform,
    MaskVelocityWrapper,
    RestartOnException,
    RewardAsObservationWrapper,
)


class CountingEnv(gym.Env):
    """1-D env whose obs is the step count and reward is 1 per step."""

    def __init__(self, n_steps=10):
        self.observation_space = gym.spaces.Box(-np.inf, np.inf, (1,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self._t = 0
        self._n = n_steps

    def step(self, action):
        self._t += 1
        done = self._t >= self._n
        return np.array([self._t], np.float32), 1.0, done, False, {}

    def reset(self, seed=None, options=None):
        self._t = 0
        return np.array([0.0], np.float32), {}


def test_action_repeat_sums_rewards():
    env = ActionRepeat(CountingEnv(), 3)
    env.reset()
    obs, reward, done, trunc, _ = env.step(0)
    assert reward == 3.0 and obs[0] == 3.0


def test_action_repeat_stops_at_done():
    env = ActionRepeat(CountingEnv(n_steps=2), 5)
    env.reset()
    obs, reward, done, trunc, _ = env.step(0)
    assert done and reward == 2.0


def test_action_repeat_invalid_amount():
    with pytest.raises(ValueError):
        ActionRepeat(CountingEnv(), 0)


def test_mask_velocity():
    env = MaskVelocityWrapper(gym.make("CartPole-v1"))
    obs, _ = env.reset(seed=0)
    assert obs[1] == 0.0 and obs[3] == 0.0


def test_mask_velocity_unsupported():
    with pytest.raises(NotImplementedError):
        MaskVelocityWrapper(gym.make("Acrobot-v1"))


class FlakyEnv(gym.Env):
    """Fails the first `fail_times` step() calls."""

    def __init__(self, fail_times=1):
        self.observation_space = gym.spaces.Box(-1, 1, (1,), np.float32)
        self.action_space = gym.spaces.Discrete(2)
        self.fails_left = fail_times

    def step(self, action):
        if self.fails_left > 0:
            self.fails_left -= 1
            raise RuntimeError("env crash")
        return np.zeros(1, np.float32), 0.0, False, False, {}

    def reset(self, seed=None, options=None):
        return np.zeros(1, np.float32), {}


def test_restart_on_exception_recovers():
    env = RestartOnException(lambda: FlakyEnv(fail_times=1), wait=0)
    env.reset()
    obs, reward, done, trunc, info = env.step(0)
    assert info.get("restart_on_exception") is True
    assert not done


def test_restart_on_exception_budget_exhausted():
    def always_broken():
        return FlakyEnv(fail_times=10**9)

    env = RestartOnException(always_broken, maxfails=2, wait=0)
    env.reset()
    env.step(0)
    env.step(0)
    with pytest.raises(RuntimeError, match="crashed too many times"):
        env.step(0)


def test_frame_stack_shapes_nhwc():
    env = FrameStack(DiscreteDummyEnv(image_size=(8, 8, 3), n_steps=20), 4, ["rgb"])
    obs, _ = env.reset()
    assert obs["rgb"].shape == (4, 8, 8, 3)
    assert env.observation_space["rgb"].shape == (4, 8, 8, 3)
    # after reset all stacked frames are the reset frame
    assert (obs["rgb"] == obs["rgb"][0]).all()


def test_frame_stack_rolls():
    env = FrameStack(DiscreteDummyEnv(image_size=(4, 4, 3), n_steps=20), 2, ["rgb"])
    env.reset()
    obs, *_ = env.step(0)
    obs, *_ = env.step(0)
    # dummy env encodes step index in pixel values: last frame is newest
    assert obs["rgb"][1, 0, 0, 0] == obs["rgb"][0, 0, 0, 0] + 1


def test_frame_stack_dilation():
    env = FrameStack(DiscreteDummyEnv(image_size=(4, 4, 3), n_steps=50), 2, ["rgb"], dilation=2)
    env.reset()
    for _ in range(4):
        obs, *_ = env.step(0)
    assert obs["rgb"].shape == (2, 4, 4, 3)
    # dilation 2: stacked frames are 2 steps apart
    assert obs["rgb"][1, 0, 0, 0] - obs["rgb"][0, 0, 0, 0] == 2


def test_frame_stack_errors():
    with pytest.raises(ValueError):
        FrameStack(DiscreteDummyEnv(), 0, ["rgb"])
    with pytest.raises(RuntimeError):
        FrameStack(CountingEnv(), 2, ["rgb"])
    with pytest.raises(RuntimeError):
        FrameStack(DiscreteDummyEnv(), 2, ["not_an_image"])


def test_reward_as_observation_dict_env():
    env = RewardAsObservationWrapper(DiscreteDummyEnv())
    obs, _ = env.reset()
    assert obs["reward"].shape == (1,) and obs["reward"][0] == 0.0
    assert "reward" in env.observation_space.spaces
    obs, *_ = env.step(0)
    assert obs["reward"][0] == 0.0


def test_reward_as_observation_box_env():
    env = RewardAsObservationWrapper(CountingEnv())
    obs, _ = env.reset()
    assert set(obs.keys()) == {"obs", "reward"}
    obs, reward, *_ = env.step(0)
    assert obs["reward"][0] == reward


def test_dict_observation():
    env = DictObservation(CountingEnv(), "state")
    obs, _ = env.reset()
    assert obs["state"].shape == (1,)
    assert isinstance(env.observation_space, gym.spaces.Dict)
    with pytest.raises(RuntimeError):
        DictObservation(DiscreteDummyEnv(), "x")


def test_image_transform_resize_and_grayscale():
    env = ImageTransform(DiscreteDummyEnv(image_size=(32, 32, 3), n_steps=10), ["rgb"], 16, True)
    obs, _ = env.reset()
    assert obs["rgb"].shape == (16, 16, 1)
    assert obs["rgb"].dtype == np.uint8
    assert env.observation_space["rgb"].shape == (16, 16, 1)


def test_image_transform_keeps_rgb():
    env = ImageTransform(DiscreteDummyEnv(image_size=(32, 32, 3), n_steps=10), ["rgb"], 64, False)
    obs, _ = env.reset()
    assert obs["rgb"].shape == (64, 64, 3)
