"""Scenario-variant parity tests (ISSUE 19 tentpole part 1).

Every combinator in ``sheeprl_tpu/envs/variants.py`` promises that theta = 0
is an exact identity point — these tests pin that promise against the *host
gymnasium envs* (not just the jittable twins), transition-for-transition in
fp32, so a variant that perturbs the base dynamics at its identity point
fails here rather than as a silent learning regression.  The vmapped-N vs
N-sequential test pins the batching contract the fused superstep relies on:
one [N, P] theta matrix through ``jax.vmap`` must equal N hand-instantiated
scenario envs stepped one at a time.
"""

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.config.compose import compose
from sheeprl_tpu.envs.jittable import JaxCartPole, JaxPendulum
from sheeprl_tpu.envs.variants import (
    DEFAULT_RANGES,
    VARIANT_ORDER,
    canonical_variant_order,
    compose_variant_env_id,
    identity_theta,
    make_scenario_family,
    parse_variant_env_id,
    sample_scenario_matrix,
)
from sheeprl_tpu.utils.utils import dotdict


def _with_inner(state, y):
    """Overwrite the base env state wherever the wrapper nests it."""
    if "y" in state:
        return {**state, "y": y, "t": jnp.int32(0)}
    return {**state, "env": _with_inner(state["env"], y)}


def test_compose_parse_roundtrip():
    composed = compose_variant_env_id("CartPole-v1", ("sticky_actions", "distractors"))
    assert composed == "CartPole-v1+sticky_actions+distractors"
    assert parse_variant_env_id(composed) == ("CartPole-v1", ("sticky_actions", "distractors"))
    assert parse_variant_env_id("Pendulum-v1") == ("Pendulum-v1", ())


def test_canonical_order_and_unknown_variant():
    # request order does not matter; composition order is canonical
    assert canonical_variant_order(["distractors", "phys_mass"]) == ("phys_mass", "distractors")
    with pytest.raises(ValueError, match="unknown variant"):
        canonical_variant_order(["phys_mass", "gravity_flip"])


def test_family_metadata():
    family = make_scenario_family("CartPole-v1", ["distractors", "sticky_actions"])
    assert family.env_id == "CartPole-v1+sticky_actions+distractors"
    assert family.base_id == "CartPole-v1"
    assert family.param_dim == 2
    assert family.obs_dim == JaxCartPole.obs_dim + 4  # distractors widen the obs
    assert family.action_dim == JaxCartPole.action_dim
    assert not family.is_continuous
    assert make_scenario_family("Acrobot-v1", ["sticky_actions"]) is None  # no twin
    ident = identity_theta(family)
    assert ident.shape == (2,) and float(jnp.abs(ident).sum()) == 0.0


@pytest.mark.parametrize("variant", VARIANT_ORDER)
def test_cartpole_identity_parity(variant):
    """Each single-variant wrapper at theta=0 matches host gymnasium CartPole:
    same next obs / reward / terminated at random interior and near-threshold
    states (distractor dims must be exactly zero)."""
    family = make_scenario_family("CartPole-v1", [variant])
    spec = family.instantiate(identity_theta(family))
    base_dim = JaxCartPole.obs_dim
    step = jax.jit(spec.step)
    env = gym.make("CartPole-v1")
    env.reset(seed=0)
    rng = np.random.default_rng(0)
    states = list(rng.uniform(-0.05, 0.05, size=(25, 4)))
    states += [
        np.array([2.39, 1.0, 0.0, 0.0]),  # terminates on the x threshold
        np.array([0.0, 0.0, 0.2094, 1.0]),  # terminates on the theta threshold
    ]
    for i, s in enumerate(states):
        a = int(rng.integers(0, 2))
        env.reset(seed=i)
        env.unwrapped.state = np.asarray(s, np.float64)
        obs_ref, reward_ref, term_ref, _trunc, _ = env.step(a)
        state = _with_inner(spec.init(jax.random.PRNGKey(i)), jnp.asarray(s, jnp.float32))
        _ns, out = step(state, jnp.int32(a), jax.random.PRNGKey(100 + i))
        np.testing.assert_allclose(np.asarray(out.obs)[:base_dim], obs_ref, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(out.obs)[base_dim:], 0.0)
        assert bool(out.terminated) == bool(term_ref)
        assert float(out.reward) == float(reward_ref)
    env.close()


@pytest.mark.parametrize("variant", ["phys_mass", "sticky_actions", "reward_delay", "distractors"])
def test_pendulum_identity_parity(variant):
    """Continuous-action coverage: the wrappers at theta=0 match host
    gymnasium Pendulum (including the out-of-range torque clip)."""
    family = make_scenario_family("Pendulum-v1", [variant])
    spec = family.instantiate(identity_theta(family))
    base_dim = JaxPendulum.obs_dim
    step = jax.jit(spec.step)
    env = gym.make("Pendulum-v1")
    env.reset(seed=0)
    rng = np.random.default_rng(1)
    for i in range(25):
        th = rng.uniform(-np.pi, np.pi)
        thdot = rng.uniform(-8.0, 8.0)
        u = rng.uniform(-3.0, 3.0, size=1)
        env.reset(seed=i)
        env.unwrapped.state = np.array([th, thdot])
        obs_ref, reward_ref, _term, _trunc, _ = env.step(u.astype(np.float32))
        state = _with_inner(
            spec.init(jax.random.PRNGKey(i)), jnp.asarray([th, thdot], jnp.float32)
        )
        _ns, out = step(state, jnp.asarray(u, jnp.float32), jax.random.PRNGKey(100 + i))
        np.testing.assert_allclose(np.asarray(out.obs)[:base_dim], obs_ref, atol=1e-4)
        np.testing.assert_array_equal(np.asarray(out.obs)[base_dim:], 0.0)
        assert float(out.reward) == pytest.approx(float(reward_ref), abs=1e-3)
    env.close()


def test_all_variants_stacked_identity_parity():
    """The full six-variant stack at theta=0 is still an exact identity
    against host gymnasium CartPole."""
    family = make_scenario_family("CartPole-v1", list(VARIANT_ORDER))
    assert family.param_dim == len(VARIANT_ORDER)
    spec = family.instantiate(identity_theta(family))
    step = jax.jit(spec.step)
    env = gym.make("CartPole-v1")
    env.reset(seed=0)
    rng = np.random.default_rng(2)
    for i in range(10):
        s = rng.uniform(-0.05, 0.05, size=4)
        a = int(rng.integers(0, 2))
        env.reset(seed=i)
        env.unwrapped.state = np.asarray(s, np.float64)
        obs_ref, reward_ref, term_ref, _trunc, _ = env.step(a)
        state = _with_inner(spec.init(jax.random.PRNGKey(i)), jnp.asarray(s, jnp.float32))
        _ns, out = step(state, jnp.int32(a), jax.random.PRNGKey(100 + i))
        np.testing.assert_allclose(
            np.asarray(out.obs)[: JaxCartPole.obs_dim], obs_ref, atol=1e-5
        )
        np.testing.assert_array_equal(np.asarray(out.obs)[JaxCartPole.obs_dim :], 0.0)
        assert bool(out.terminated) == bool(term_ref)
        assert float(out.reward) == float(reward_ref)
    env.close()


def test_sticky_actions_repeats_previous_action():
    """At theta=1 the requested action is ignored after the first step (the
    previous action repeats); at theta=0 the requested action always lands."""
    family = make_scenario_family("CartPole-v1", ["sticky_actions"])
    sticky = family.instantiate(jnp.ones((1,), jnp.float32))
    ident = family.instantiate(identity_theta(family))
    s0 = sticky.init(jax.random.PRNGKey(0))
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    s1, _ = sticky.step(s0, jnp.int32(1), k1)
    # theta=1: requesting 0 or 1 both replay the previous action (1)
    _, out_forced = sticky.step(s1, jnp.int32(0), k2)
    _, out_explicit = sticky.step(s1, jnp.int32(1), k2)
    np.testing.assert_array_equal(np.asarray(out_forced.obs), np.asarray(out_explicit.obs))
    # theta=0 from the same state: the two actions genuinely differ
    _, out_a0 = ident.step(s1, jnp.int32(0), k2)
    _, out_a1 = ident.step(s1, jnp.int32(1), k2)
    assert not np.array_equal(np.asarray(out_a0.obs), np.asarray(out_a1.obs))


def test_reward_delay_shifts_and_flushes():
    """At theta=1 (delay = max_delay) rewards are held back in the ring, and
    the pending buffer flushes on episode end so the episodic return is
    exactly preserved."""
    family = make_scenario_family("CartPole-v1", ["reward_delay"])
    spec = family.instantiate(jnp.ones((1,), jnp.float32))
    # cart drifting right from x=2.2: terminates at the 2.4 threshold in ~10
    # steps, long enough for the 4-step ring to hold rewards back first
    state = _with_inner(
        spec.init(jax.random.PRNGKey(0)), jnp.asarray([2.2, 1.0, 0.0, 0.0], jnp.float32)
    )
    emitted, steps, out = [], 0, None
    for t in range(50):
        state, out = spec.step(state, jnp.int32(1), jax.random.fold_in(jax.random.PRNGKey(1), t))
        emitted.append(float(out.reward))
        steps += 1
        if bool(out.terminated | out.truncated):
            break
    assert out is not None and bool(out.terminated)
    assert steps > 4, "episode ended before the ring could delay anything"
    assert emitted[:4] == [0.0] * 4  # first rewards held back by the ring
    assert sum(emitted) == pytest.approx(float(steps))  # flushed on episode end


def test_distractors_evolve_and_scale():
    """At theta=1 the extra dims follow a non-degenerate AR(1) walk; the base
    slice of the obs is untouched."""
    family = make_scenario_family("CartPole-v1", ["distractors"])
    spec = family.instantiate(jnp.ones((1,), jnp.float32))
    base = family.instantiate(identity_theta(family))
    assert spec.obs_dim == JaxCartPole.obs_dim + 4
    state = spec.init(jax.random.PRNGKey(0))
    k1, k2 = jax.random.split(jax.random.PRNGKey(1))
    s1, out1 = spec.step(state, jnp.int32(0), k1)
    _s2, out2 = spec.step(s1, jnp.int32(0), k2)
    dx1 = np.asarray(out1.obs)[JaxCartPole.obs_dim :]
    dx2 = np.asarray(out2.obs)[JaxCartPole.obs_dim :]
    assert np.abs(dx1).max() > 0 and not np.array_equal(dx1, dx2)
    # same transition through the identity instance: base slice matches
    _sb, outb = base.step(state, jnp.int32(0), k1)
    np.testing.assert_allclose(
        np.asarray(out1.obs)[: JaxCartPole.obs_dim],
        np.asarray(outb.obs)[: JaxCartPole.obs_dim],
        atol=1e-6,
    )


def test_vmapped_matches_sequential():
    """One vmapped program over the [N, P] theta matrix == N sequentially
    instantiated scenario envs, transition-for-transition — the batching
    contract the fused superstep's shard_map path is built on."""
    names = list(VARIANT_ORDER)
    family = make_scenario_family("CartPole-v1", names)
    n = 8
    thetas = sample_scenario_matrix(jax.random.PRNGKey(0), n, names)
    init_keys = jax.random.split(jax.random.PRNGKey(1), n)

    def v_init(th, k):
        return family.instantiate(th).init(k)

    def v_step(th, s, a, k):
        return family.instantiate(th).step(s, a, k)

    states_v = jax.vmap(v_init)(thetas, init_keys)
    states_s = [family.instantiate(thetas[i]).init(init_keys[i]) for i in range(n)]
    jax.tree.map(
        lambda a, *bs: np.testing.assert_allclose(
            np.asarray(a), np.stack([np.asarray(b) for b in bs]), rtol=1e-6, atol=1e-6
        ),
        states_v,
        *states_s,
    )
    rng = np.random.default_rng(3)
    for t in range(5):
        actions = jnp.asarray(rng.integers(0, 2, size=n), jnp.int32)
        step_keys = jax.random.split(jax.random.fold_in(jax.random.PRNGKey(2), t), n)
        states_v, out_v = jax.vmap(v_step)(thetas, states_v, actions, step_keys)
        next_states, outs = [], []
        for i in range(n):
            si, oi = family.instantiate(thetas[i]).step(states_s[i], actions[i], step_keys[i])
            next_states.append(si)
            outs.append(oi)
        states_s = next_states
        jax.tree.map(
            lambda a, *bs: np.testing.assert_allclose(
                np.asarray(a), np.stack([np.asarray(b) for b in bs]), rtol=1e-6, atol=1e-6
            ),
            out_v,
            *outs,
        )
    jax.tree.map(
        lambda a, *bs: np.testing.assert_allclose(
            np.asarray(a), np.stack([np.asarray(b) for b in bs]), rtol=1e-6, atol=1e-6
        ),
        states_v,
        *states_s,
    )


def test_scenario_matrix_sampling():
    names = ["phys_mass", "sticky_actions"]
    thetas = np.asarray(sample_scenario_matrix(jax.random.PRNGKey(0), 64, names))
    assert thetas.shape == (64, 2) and thetas.dtype == np.float32
    lo, hi = DEFAULT_RANGES["phys_mass"]
    assert np.all(thetas[:, 0] >= lo) and np.all(thetas[:, 0] <= hi)
    lo, hi = DEFAULT_RANGES["sticky_actions"]
    assert np.all(thetas[:, 1] >= lo) and np.all(thetas[:, 1] <= hi)
    assert np.std(thetas[:, 0]) > 1e-3  # actually randomized
    # per-variant range override
    tight = np.asarray(
        sample_scenario_matrix(
            jax.random.PRNGKey(0), 64, names, ranges={"sticky_actions": (0.5, 0.5)}
        )
    )
    np.testing.assert_allclose(tight[:, 1], 0.5)
    # no variants -> [n, 0] matrix, not an error
    assert sample_scenario_matrix(jax.random.PRNGKey(0), 4, []).shape == (4, 0)


def test_fused_fallback_names_composed_variant_id():
    """ISSUE 19 satellite: when the base env has no jittable twin, the
    fallback breadcrumb names the full variant-composed id (sweep triage
    greps which *scenario* was skipped, not just which base env)."""
    from sheeprl_tpu.algos.ppo.ppo import resolve_fused_rollout_spec
    from sheeprl_tpu.ops.superstep import reset_fused_fallback_warnings

    cfg = dotdict(
        compose(
            "config",
            ["exp=ppo", "env.id=Acrobot-v1", "env.variants.enabled=[phys_size,distractors]"],
        )
    )
    reset_fused_fallback_warnings()
    with pytest.warns(UserWarning, match=r"Acrobot-v1\+phys_size\+distractors"):
        spec = resolve_fused_rollout_spec(cfg, None, [], ["state"], None, False, False, (3,))
    assert spec is None
