"""SteadyStateProbe warm-point semantics (utils.SteadyStateProbe — the one
measurement convention every off-policy loop shares; consumed by bench.py).
"""

import json

import pytest

from sheeprl_tpu.utils.utils import SteadyStateProbe


@pytest.fixture()
def probe(tmp_path, monkeypatch):
    path = str(tmp_path / "probe.json")
    monkeypatch.setenv("SHEEPRL_TPU_BENCH_JSON", path)
    return SteadyStateProbe(), path


def test_fresh_run_opens_at_shared_warm_point(probe):
    p, _ = probe
    W = SteadyStateProbe.WARMUP_UPDATES
    for update in range(0, 10 + W + 1):
        p.mark_warm(update, 10, step=update * 4)
        if update < 10 + W:
            assert p._t0 is None, update
    assert p._t0 is not None
    assert p._step0 == (10 + W) * 4


def test_resumed_run_waits_its_own_warmup(probe):
    """A run resuming at update 5000 (long past learning_starts + warmup)
    still compiles its gradient path on its FIRST update — the window must
    wait WARMUP_UPDATES from the first observed update, not open
    immediately (which would put minutes of compile inside the window)."""
    p, _ = probe
    W = SteadyStateProbe.WARMUP_UPDATES
    p.mark_warm(5000, 0, step=0)
    assert p._t0 is None
    p.mark_warm(5000 + W - 1, 0, step=0)
    assert p._t0 is None
    p.mark_warm(5000 + W, 0, step=123)
    assert p._t0 is not None and p._step0 == 123


def test_finish_writes_record(probe):
    p, path = probe
    p.mark(100, work=7)
    p.finish(500, sync=lambda: None, work=27, extra={"note": "x"})
    with open(path) as f:
        rec = json.load(f)
    assert rec["steps"] == 400
    assert rec["train_steps"] == 20
    assert rec["note"] == "x"
    assert rec["seconds"] > 0


def test_inactive_without_env(tmp_path, monkeypatch):
    monkeypatch.delenv("SHEEPRL_TPU_BENCH_JSON", raising=False)
    p = SteadyStateProbe()
    assert not p.active
    p.mark(0)
    p.finish(10)  # no-op, must not raise or write
