"""XLA profiler hook + run-telemetry (sheeprl_tpu.obs) tests."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.obs import configure_telemetry, get_telemetry, shutdown_telemetry, span
from sheeprl_tpu.obs.recompile import RecompileWarning
from sheeprl_tpu.utils.profiler import maybe_profile


def test_disabled_is_noop():
    with maybe_profile({"metric": {}}) as trace_dir:
        assert trace_dir is None
    with maybe_profile({}) as trace_dir:
        assert trace_dir is None


def test_enabled_writes_trace(tmp_path):
    cfg = {"metric": {"profiler": {"enabled": True, "trace_dir": str(tmp_path / "prof")}}}
    with maybe_profile(cfg) as trace_dir:
        assert trace_dir == str(tmp_path / "prof")
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones((8, 8))))
    found = []
    for root, _, files in os.walk(trace_dir):
        found += files
    assert found, "profiler trace produced no files"


def test_default_dir_from_log_dir(tmp_path):
    cfg = {"metric": {"profiler": {"enabled": True}}}
    with maybe_profile(cfg, log_dir=str(tmp_path)) as trace_dir:
        assert trace_dir == os.path.join(str(tmp_path), "profile")
        jax.block_until_ready(jnp.ones(4) + 1)


# ------------------------------------------------- run telemetry (obs/) ----


@pytest.fixture()
def telemetry(tmp_path):
    """Fresh RunTelemetry with fast polling; restores the span registry and
    guarantees shutdown so no listener leaks into later tests."""
    saved_timers, saved_disabled = dict(span.timers), span.disabled
    span.timers, span.disabled = {}, False
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    assert tel is not None
    yield tel
    shutdown_telemetry()
    span.timers, span.disabled = saved_timers, saved_disabled


def _events(tel):
    tel.writer.flush()
    with open(tel.writer.path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_telemetry_disabled_is_inert(tmp_path):
    assert configure_telemetry({"metric": {"telemetry": {"enabled": False}}}, str(tmp_path)) is None
    assert configure_telemetry({"metric": {}}, str(tmp_path)) is None
    assert get_telemetry() is None
    assert not os.path.exists(os.path.join(str(tmp_path), "telemetry.jsonl"))


def test_span_emits_jsonl_event_with_schema(telemetry):
    telemetry.advance(7)
    with span("Time/test_section", kind="unit"):
        jax.block_until_ready(jnp.ones(4) + 1)
    events = _events(telemetry)
    spans = [e for e in events if e["event"] == "span"]
    assert len(spans) == 1
    (ev,) = spans
    assert ev["name"] == "Time/test_section"
    assert ev["step"] == 7
    assert ev["process_index"] == jax.process_index()
    assert ev["attrs"] == {"kind": "unit"}
    assert ev["dur"] > 0 and ev["t_start"] <= ev["t"]
    # the SAME name feeds the timer metric registry — spans and Time/*
    # scalars agree by construction
    assert "Time/test_section" in span.timers
    assert abs(span.compute()["Time/test_section"] - ev["dur"]) < 0.5


def test_span_without_telemetry_is_the_old_timer(tmp_path):
    saved_timers, saved_disabled = dict(span.timers), span.disabled
    span.timers, span.disabled = {}, False
    try:
        assert get_telemetry() is None
        with span("Time/plain"):
            pass
        assert span.compute()["Time/plain"] >= 0
    finally:
        span.timers, span.disabled = saved_timers, saved_disabled


def test_recompile_watchdog_counts_deliberate_retraces(telemetry):
    x = jnp.ones((3,))
    jax.block_until_ready(jax.jit(lambda v: v * 3 + 1)(x))  # pre-warm compile
    pre = telemetry.watchdog.compiles
    assert pre >= 1
    assert telemetry.watchdog.recompiles == 0
    telemetry.mark_warm()
    with pytest.warns(RecompileWarning):
        for _ in range(2):
            # a FRESH lambda per iteration defeats the jit cache: each call
            # re-traces and re-lowers, which is exactly a silent recompile
            jax.block_until_ready(jax.jit(lambda v: v * 3 + 1)(x))
    assert telemetry.watchdog.recompiles >= 2
    post_warm = [
        e
        for e in _events(telemetry)
        if e["event"] == "compile" and e["phase"] == "lower" and e["post_warm"]
    ]
    assert len(post_warm) >= 2
    assert all("dur" in e for e in post_warm)
    # each post-warm retrace also emits a dedicated `recompile` event naming
    # the offending function, for cross-referencing against jaxcheck's
    # static JX05 findings
    recompile_events = [e for e in _events(telemetry) if e["event"] == "recompile"]
    assert len(recompile_events) >= 2
    assert all(e["qualname"] for e in recompile_events)
    assert recompile_events[-1]["count"] == telemetry.watchdog.recompiles


class _FakeLogger:
    def __init__(self):
        self.logged = []

    def log_metrics(self, metrics, step):
        self.logged.append((dict(metrics), step))


def test_heartbeat_assembly_on_fake_logger(telemetry):
    telemetry.set_flops_source(lambda: 2.0e9)
    logger = _FakeLogger()
    telemetry.heartbeat(
        logger,
        step=1000,
        env_steps=200,
        train_steps=600,
        train_invocations=10,
        timer_window={"Time/env_interaction_time": 2.0, "Time/train_time": 6.0},
    )
    (hb,) = [e for e in _events(telemetry) if e["event"] == "heartbeat"]
    assert hb["sps_env"] == pytest.approx(100.0)
    assert hb["sps_train"] == pytest.approx(100.0)
    assert hb["duty_cycle_train"] == pytest.approx(0.75)
    assert hb["flops_per_train_step"] == pytest.approx(2.0e9)
    assert hb["train_flops_per_sec"] == pytest.approx(2.0e9 * 10 / 6.0)
    assert hb["recompiles"] == telemetry.watchdog.recompiles
    assert hb["device_kind"]
    scalars, step = logger.logged[-1]
    assert step == 1000
    assert scalars["Counters/recompiles"] == float(telemetry.watchdog.recompiles)
    assert scalars["Telemetry/duty_cycle_train"] == pytest.approx(0.75)
    assert scalars["Telemetry/train_flops_per_sec"] == pytest.approx(2.0e9 * 10 / 6.0)


def test_device_poll_rides_advance(telemetry):
    telemetry.advance(5)
    telemetry.advance(9)
    polls = [e for e in _events(telemetry) if e["event"] == "device_poll"]
    # one forced poll at start + one per advance (poll_interval=0)
    assert len(polls) >= 3
    assert polls[-1]["step"] == 9
    for entry in polls[-1]["devices"]:
        assert {"id", "kind", "platform"} <= set(entry)
    assert len(polls[-1]["devices"]) == jax.local_device_count()


def test_run_lifecycle_events(telemetry):
    shutdown_telemetry()
    with open(telemetry.writer.path) as f:
        events = [json.loads(line) for line in f if line.strip()]
    assert events[0]["event"] == "run_start"
    assert events[0]["backend"] == "cpu"
    assert events[-1]["event"] == "run_end"
    assert "compiles_total" in events[-1] and "device_polls" in events[-1]
    assert get_telemetry() is None


def test_watchdog_counts_compile_cache_events(telemetry):
    """Persistent-compilation-cache outcomes arrive as plain jax.monitoring
    events; the watchdog counts them and mirrors each as a compile_cache
    telemetry event (fabric.compilation_cache_dir observability)."""
    pre_hits, pre_misses = telemetry.watchdog.cache_hits, telemetry.watchdog.cache_misses
    jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
    jax.monitoring.record_event("/jax/compilation_cache/cache_misses")
    jax.monitoring.record_event("/jax/compilation_cache/cache_misses")
    jax.monitoring.record_event("/jax/unrelated_event")  # ignored
    assert telemetry.watchdog.cache_hits == pre_hits + 1
    assert telemetry.watchdog.cache_misses == pre_misses + 2
    cache_events = [e for e in _events(telemetry) if e["event"] == "compile_cache"]
    assert [e["hit"] for e in cache_events[-3:]] == [True, False, False]


def test_watchdog_stop_unregisters_cache_listener():
    from sheeprl_tpu.obs.recompile import CompileWatchdog

    wd = CompileWatchdog(lambda name, **kw: None)
    wd.start()
    try:
        jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
        assert wd.cache_hits == 1
    finally:
        wd.stop()
    jax.monitoring.record_event("/jax/compilation_cache/cache_hits")
    assert wd.cache_hits == 1, "stop() left the plain-event listener registered"


def test_train_window_counters_roll_into_heartbeat(telemetry):
    from sheeprl_tpu.obs import telemetry_train_window

    telemetry_train_window(1, 4)
    telemetry_train_window(2, 6)
    logger = _FakeLogger()
    telemetry.heartbeat(
        logger,
        step=10,
        env_steps=4,
        train_steps=10,
        train_invocations=2,
        timer_window={"Time/train_time": 1.0},
    )
    hb = [e for e in _events(telemetry) if e["event"] == "heartbeat"][-1]
    assert hb["window_train_windows"] == 2
    assert hb["window_train_dispatches"] == 3
    assert hb["window_train_gradient_steps"] == 10
    scalars, _ = logger.logged[-1]
    assert scalars["Telemetry/train_dispatches_per_window"] == pytest.approx(1.5)
    # the window counters reset; the run totals land in run_end (see the
    # distributed run_end assertions and bench.dispatch_stats)
    logger2 = _FakeLogger()
    telemetry.heartbeat(
        logger2,
        step=11,
        env_steps=4,
        train_steps=0,
        train_invocations=0,
        timer_window={},
    )
    hb2 = [e for e in _events(telemetry) if e["event"] == "heartbeat"][-1]
    assert "window_train_windows" not in hb2
