"""XLA profiler hook tests."""

import os

import jax
import jax.numpy as jnp

from sheeprl_tpu.utils.profiler import maybe_profile


def test_disabled_is_noop():
    with maybe_profile({"metric": {}}) as trace_dir:
        assert trace_dir is None
    with maybe_profile({}) as trace_dir:
        assert trace_dir is None


def test_enabled_writes_trace(tmp_path):
    cfg = {"metric": {"profiler": {"enabled": True, "trace_dir": str(tmp_path / "prof")}}}
    with maybe_profile(cfg) as trace_dir:
        assert trace_dir == str(tmp_path / "prof")
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones((8, 8))))
    found = []
    for root, _, files in os.walk(trace_dir):
        found += files
    assert found, "profiler trace produced no files"


def test_default_dir_from_log_dir(tmp_path):
    cfg = {"metric": {"profiler": {"enabled": True}}}
    with maybe_profile(cfg, log_dir=str(tmp_path)) as trace_dir:
        assert trace_dir == os.path.join(str(tmp_path), "profile")
        jax.block_until_ready(jnp.ones(4) + 1)
