"""Logger tests: versioned log dir, TB writer, MLflow backend (stubbed)."""

import sys
import types

import pytest

from sheeprl_tpu.utils.logger import MlflowLogger, NoOpLogger, get_log_dir, get_logger


def test_get_log_dir_versions(tmp_path):
    cfg = {"root_dir": "algo/env", "run_name": "run", "log_base_dir": str(tmp_path)}
    d0 = get_log_dir(cfg)
    d1 = get_log_dir(cfg)
    assert d0.endswith("version_0") and d1.endswith("version_1")


def test_get_logger_dispatch(tmp_path):
    cfg = {"metric": {"log_level": 1}, "logger": {"name": "tensorboard"}}
    logger = get_logger(cfg, str(tmp_path / "tb"))
    assert type(logger).__name__ == "TensorBoardLogger"
    logger.log_metrics({"a": 1.0}, step=0)
    logger.finalize()

    assert isinstance(get_logger({"metric": {"log_level": 0}}, str(tmp_path)), NoOpLogger)
    with pytest.raises(ValueError):
        get_logger({"metric": {"log_level": 1}, "logger": {"name": "wandb"}}, str(tmp_path))


class _StubMlflow(types.ModuleType):
    def __init__(self):
        super().__init__("mlflow")
        self.metrics = []
        self.params = {}
        self.tracking_uri = None
        self.experiment = None
        self.ended = False

    def set_tracking_uri(self, uri):
        self.tracking_uri = uri

    def set_experiment(self, name):
        self.experiment = name

    def start_run(self, run_name=None, tags=None):
        info = types.SimpleNamespace(run_id="stub-run-id")
        return types.SimpleNamespace(info=info)

    def log_metrics(self, metrics, step=None):
        self.metrics.append((dict(metrics), step))

    def log_params(self, params):
        self.params.update(params)

    def end_run(self):
        self.ended = True


@pytest.fixture
def stub_mlflow(monkeypatch):
    stub = _StubMlflow()
    monkeypatch.setitem(sys.modules, "mlflow", stub)
    import sheeprl_tpu.utils.imports as imports

    monkeypatch.setattr(imports, "_IS_MLFLOW_AVAILABLE", True)
    return stub


def test_mlflow_logger(stub_mlflow, tmp_path):
    logger = MlflowLogger(
        tracking_uri="file:///tmp/mlruns", experiment_name="exp", run_name="r0"
    )
    assert logger.run_id == "stub-run-id"
    assert stub_mlflow.tracking_uri == "file:///tmp/mlruns"
    assert stub_mlflow.experiment == "exp"

    logger.log_metrics({"loss": 1.5, "nan": float("nan")}, step=3)
    assert stub_mlflow.metrics == [({"loss": 1.5}, 3)]

    logger.log_hyperparams({"algo": {"lr": 1e-3, "name": "ppo"}, "seed": 42})
    assert stub_mlflow.params == {"algo.lr": 1e-3, "algo.name": "ppo", "seed": 42}

    logger.finalize()
    assert stub_mlflow.ended


def test_get_logger_mlflow_dispatch(stub_mlflow, tmp_path):
    cfg = {
        "metric": {"log_level": 1},
        "exp_name": "exp",
        "run_name": "run",
        "logger": {"name": "mlflow", "experiment_name": "exp", "tracking_uri": None},
    }
    logger = get_logger(cfg, str(tmp_path))
    assert isinstance(logger, MlflowLogger)
    logger.finalize()
