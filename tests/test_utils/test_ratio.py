from sheeprl_tpu.utils.utils import Ratio, dotdict, polynomial_decay


def test_ratio_basic():
    r = Ratio(0.5)
    assert r(0) == 1  # first call primes the controller
    assert r(8) == 4
    assert r(10) == 1


def test_ratio_fractional_carry():
    r = Ratio(1 / 3)
    r(0)
    total = sum(r(s) for s in range(1, 301))
    assert abs(total - 100) <= 1


def test_ratio_zero():
    r = Ratio(0.0)
    assert r(100) == 0


def test_ratio_state_roundtrip():
    r = Ratio(0.5)
    r(0)
    r(7)
    state = r.state_dict()
    r2 = Ratio(0.5).load_state_dict(state)
    assert r2(11) == r(11)


def test_ratio_pretrain():
    r = Ratio(2.0, pretrain_steps=10)
    assert r(100) == 20


def test_dotdict():
    d = dotdict({"a": {"b": 1}, "c": [{"d": 2}]})
    assert d.a.b == 1
    assert d.c[0].d == 2
    d.a.e = {"f": 3}
    assert d.a.e.f == 3
    assert d.to_dict() == {"a": {"b": 1, "e": {"f": 3}}, "c": [{"d": 2}]}
    assert d.get_nested("a.b") == 1
    assert d.get_nested("a.zz", "fallback") == "fallback"


def test_polynomial_decay():
    assert polynomial_decay(0, initial=1.0, final=0.0, max_decay_steps=10) == 1.0
    assert polynomial_decay(5, initial=1.0, final=0.0, max_decay_steps=10) == 0.5
    assert polynomial_decay(20, initial=1.0, final=0.0, max_decay_steps=10) == 0.0
