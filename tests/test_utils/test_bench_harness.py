"""bench.py outage hardening (round-4 failure: one tunnel outage produced
rc=124 and NO JSON at all — ``BENCH_r04.json parsed: null``).

Contract under test: ``python bench.py`` ALWAYS prints one parseable JSON
line. When the backend probe cannot succeed (dead or hanging), the line
carries the last-known-good numbers from ``BENCH_CACHE.json`` plus
``"outage": true`` — and it does so fast, well inside any external timeout.
"""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BENCH = os.path.join(REPO_ROOT, "bench.py")


def _run_bench(extra_env, timeout=120, argv=None):
    """Run bench (directly, or via a wrapper ``argv``) and return the last
    JSON line; failures carry the captured output."""
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.run(
        argv or [sys.executable, BENCH],
        env=env,
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    return json.loads(lines[-1])


def test_outage_emits_cached_record_when_probe_fails_fast():
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "false",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "1",
        }
    )
    assert rec["outage"] is True
    assert rec["metric"] == "dreamer_v3_env_steps_per_sec_per_chip"
    # the committed BENCH_CACHE.json seed carries the last driver-captured
    # numbers — an outage must surface them, not null
    assert rec["value"] is not None
    assert rec.get("cached_from")


def test_outage_emits_within_budget_when_probe_hangs():
    """A probe that HANGS (the real round-4 signature) must not stall the
    record: the per-probe timeout bounds each attempt and the wait budget
    bounds the loop."""
    t0 = time.monotonic()
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "sleep 300",
            "SHEEPRL_TPU_BENCH_PROBE_TIMEOUT": "2",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "3",
        },
        timeout=90,
    )
    assert rec["outage"] is True
    assert time.monotonic() - t0 < 60
    assert rec["value"] is not None


def test_assemble_partial_marks_stale_sections():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    cache = {
        "record": {
            "value": {
                "metric": "dreamer_v3_env_steps_per_sec_per_chip",
                "value": 100.0,
                "unit": "steps/sec",
                "vs_baseline": 24.0,
                "secondary": {"metric": "ppo_cartpole_env_steps_per_sec", "value": 5000.0},
            },
            "provenance": "test-seed",
        }
    }
    fresh = bench._assemble({"steps": 2048, "seconds": 10.0}, None, [])
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit_from_cache(cache, "ppo timed out", fresh)
    rec = json.loads(buf.getvalue())
    # fresh dv3 section overrides the cached one; ppo stays cached + stale
    assert rec["value"] == 204.8
    assert rec["secondary"]["value"] == 5000.0
    assert rec["stale"] == ["secondary"]
    assert rec["outage"] is True
    assert rec["cached_from"] == "test-seed"


_NOJAX_BENCH_PARENT = r"""
import sys

class _NoJax:
    # the round-4 record died because harness code touched the jax backend
    # with the tunnel down; the bench PARENT must never import jax at all
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("bench parent must not import jax")
        return None

sys.meta_path.insert(0, _NoJax())
import importlib.util

spec = importlib.util.spec_from_file_location("bench", sys.argv[1])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main()
"""


def test_bench_parent_never_imports_jax():
    """Outage path driven with jax imports POISONED in the parent process:
    the emitted record must still appear (probe subprocesses are exempt —
    they are separate interpreters)."""
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "false",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "1",
        },
        argv=[sys.executable, "-c", _NOJAX_BENCH_PARENT, BENCH],
    )
    assert rec["outage"] is True and rec["value"] is not None


def _write_telemetry(path):
    """Synthetic telemetry.jsonl in the documented schema (howto/telemetry.md),
    including a torn final line (run killed mid-flush)."""
    events = [
        {"event": "run_start", "t": 0.0, "step": 0, "process_index": 0, "backend": "cpu"},
        {
            "event": "device_poll",
            "t": 0.1,
            "step": 0,
            "process_index": 0,
            "devices": [{"id": 0, "kind": "TPU v5e", "platform": "tpu", "peak_bytes_in_use": 123456}],
        },
        {"event": "compile", "t": 0.2, "step": 0, "process_index": 0, "name": "train_fn", "phase": "lower", "dur": 1.5, "post_warm": False},
        {"event": "compile", "t": 0.3, "step": 0, "process_index": 0, "name": "train_fn", "phase": "backend", "dur": 3.0, "post_warm": False},
        {"event": "span", "t": 1.0, "step": 10, "process_index": 0, "name": "Time/train_time", "t_start": 0.5, "dur": 0.5},
        {"event": "span", "t": 2.0, "step": 20, "process_index": 0, "name": "Time/train_time", "t_start": 1.5, "dur": 0.5},
        {"event": "compile", "t": 2.5, "step": 20, "process_index": 0, "name": "train_fn", "phase": "lower", "dur": 1.0, "post_warm": True},
        {
            "event": "heartbeat", "t": 3.0, "step": 1000, "process_index": 0,
            "window_env_steps": 1000, "window_env_time": 2.0,
            "window_train_steps": 400, "window_train_time": 1.0,
            "mfu": 0.10, "train_flops_per_sec": 1.0e12,
        },
        {
            "event": "heartbeat", "t": 6.0, "step": 2000, "process_index": 0,
            "window_env_steps": 1000, "window_env_time": 2.0,
            "window_train_steps": 400, "window_train_time": 3.0,
            "mfu": 0.30, "train_flops_per_sec": 3.0e12,
        },
    ]
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
        f.write('{"event": "heartbe')  # torn tail: must be skipped, not fatal


def test_telemetry_summary_from_jsonl(tmp_path):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    path = str(tmp_path / "telemetry.jsonl")
    _write_telemetry(path)
    s = bench.telemetry_summary(path)
    assert s["heartbeats"] == 2
    assert s["sps_env"] == 2000 / 4.0
    assert s["sps_train"] == 800 / 4.0
    assert s["duty_cycle_train"] == 4.0 / 8.0
    # train_time-weighted: (1*0.1 + 3*0.3) / 4
    assert abs(s["mfu"] - 0.25) < 1e-9
    assert abs(s["train_flops_per_sec"] - 2.5e12) < 1e3
    assert s["spans"]["Time/train_time"] == {"count": 2, "total_s": 1.0}
    # only phase=lower counts as a compile; the backend phase is not double-counted
    assert s["compiles"] == 2
    assert s["recompiles_post_warm"] == 1
    assert s["device_polls"] == 1
    assert s["hbm_peak_bytes"] == 123456


def test_telemetry_summary_cli(tmp_path):
    """`bench.py --telemetry PATH` prints one JSON summary line."""
    path = str(tmp_path / "telemetry.jsonl")
    _write_telemetry(path)
    proc = subprocess.run(
        [sys.executable, BENCH, "--telemetry", path],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["sps_env"] == 500.0 and rec["heartbeats"] == 2


def test_telemetry_summary_needs_no_jax(tmp_path):
    """The summary runs with jax imports poisoned — the bench parent must
    stay jax-free even when digesting telemetry."""
    path = str(tmp_path / "telemetry.jsonl")
    _write_telemetry(path)
    code = _NOJAX_BENCH_PARENT.replace("mod.main()", "") + (
        "import json\n"
        "print(json.dumps(mod.telemetry_summary(sys.argv[2])))\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code, BENCH, path],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["sps_train"] == 200.0


def test_read_probe_window_never_opened_is_distinct(tmp_path):
    """The probe's 'window never opened' record must raise a targeted config
    error, not be mistaken for a throughput record or an outage."""
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    import pytest

    path = str(tmp_path / "probe.json")
    with open(path, "w") as f:
        json.dump({"error": "window_never_opened", "detail": "run shorter than warmup"}, f)
    with pytest.raises(RuntimeError, match="before its steady-state window opened"):
        bench._read_probe(path, "dv3")


def test_cache_checkpoint_roundtrip(tmp_path, monkeypatch):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    monkeypatch.setattr(bench, "_CACHE_PATH", str(tmp_path / "cache.json"))
    cache = bench._load_cache()
    assert cache == {}
    bench._checkpoint(cache, "dv3", {"steps": 1, "seconds": 2.0}, "unit-test")
    again = bench._load_cache()
    assert again["dv3"]["value"] == {"steps": 1, "seconds": 2.0}
    assert again["dv3"]["provenance"] == "unit-test"


def test_dispatch_stats_prefers_run_end_totals(tmp_path):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    # run_end totals include the trailing window the heartbeats never flushed
    events = [
        {"event": "run_start"},
        {"event": "heartbeat", "window_train_windows": 2, "window_train_dispatches": 2,
         "window_train_gradient_steps": 5},
        {"event": "run_end", "train_windows": 3, "train_dispatches": 3,
         "train_gradient_steps": 9},
    ]
    ds = bench.dispatch_stats(events)
    assert ds["train_windows"] == 3
    assert ds["dispatches_per_window"] == 1.0
    assert ds["gradient_steps_per_dispatch"] == 3.0

    # still-running stream (no run_end): fall back to summing heartbeats
    ds = bench.dispatch_stats(events[:-1])
    assert ds["train_windows"] == 2
    assert ds["train_dispatches"] == 2

    # and from a file path, the way --dispatch-stats consumes it
    path = tmp_path / "telemetry.jsonl"
    path.write_text("\n".join(json.dumps(e) for e in events) + "\n")
    ds = bench.dispatch_stats(str(path))
    assert ds["dispatches_per_window"] == 1.0

    # no train windows at all -> no ratios, no division by zero
    assert "dispatches_per_window" not in bench.dispatch_stats([{"event": "run_start"}])
