"""bench.py outage hardening (round-4 failure: one tunnel outage produced
rc=124 and NO JSON at all — ``BENCH_r04.json parsed: null``).

Contract under test: ``python bench.py`` ALWAYS prints one parseable JSON
line. When the backend probe cannot succeed (dead or hanging), the line
carries the last-known-good numbers from ``BENCH_CACHE.json`` plus
``"outage": true`` — and it does so fast, well inside any external timeout.
"""

import json
import os
import subprocess
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BENCH = os.path.join(REPO_ROOT, "bench.py")


def _run_bench(extra_env, timeout=120, argv=None):
    """Run bench (directly, or via a wrapper ``argv``) and return the last
    JSON line; failures carry the captured output."""
    env = dict(os.environ)
    env.update(extra_env)
    proc = subprocess.run(
        argv or [sys.executable, BENCH],
        env=env,
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=timeout,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    lines = [l for l in proc.stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in stdout:\n{proc.stdout}\nstderr:\n{proc.stderr[-2000:]}"
    return json.loads(lines[-1])


def test_outage_emits_cached_record_when_probe_fails_fast():
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "false",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "1",
        }
    )
    assert rec["outage"] is True
    assert rec["metric"] == "dreamer_v3_env_steps_per_sec_per_chip"
    # the committed BENCH_CACHE.json seed carries the last driver-captured
    # numbers — an outage must surface them, not null
    assert rec["value"] is not None
    assert rec.get("cached_from")


def test_outage_emits_within_budget_when_probe_hangs():
    """A probe that HANGS (the real round-4 signature) must not stall the
    record: the per-probe timeout bounds each attempt and the wait budget
    bounds the loop."""
    t0 = time.monotonic()
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "sleep 300",
            "SHEEPRL_TPU_BENCH_PROBE_TIMEOUT": "2",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "3",
        },
        timeout=90,
    )
    assert rec["outage"] is True
    assert time.monotonic() - t0 < 60
    assert rec["value"] is not None


def test_assemble_partial_marks_stale_sections():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    cache = {
        "record": {
            "value": {
                "metric": "dreamer_v3_env_steps_per_sec_per_chip",
                "value": 100.0,
                "unit": "steps/sec",
                "vs_baseline": 24.0,
                "secondary": {"metric": "ppo_cartpole_env_steps_per_sec", "value": 5000.0},
            },
            "provenance": "test-seed",
        }
    }
    fresh = bench._assemble({"steps": 2048, "seconds": 10.0}, None, [])
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        bench._emit_from_cache(cache, "ppo timed out", fresh)
    rec = json.loads(buf.getvalue())
    # fresh dv3 section overrides the cached one; ppo stays cached + stale
    assert rec["value"] == 204.8
    assert rec["secondary"]["value"] == 5000.0
    assert rec["stale"] == ["secondary"]
    assert rec["outage"] is True
    assert rec["cached_from"] == "test-seed"


_NOJAX_BENCH_PARENT = r"""
import sys

class _NoJax:
    # the round-4 record died because harness code touched the jax backend
    # with the tunnel down; the bench PARENT must never import jax at all
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("bench parent must not import jax")
        return None

sys.meta_path.insert(0, _NoJax())
import importlib.util

spec = importlib.util.spec_from_file_location("bench", sys.argv[1])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.main()
"""


def test_bench_parent_never_imports_jax():
    """Outage path driven with jax imports POISONED in the parent process:
    the emitted record must still appear (probe subprocesses are exempt —
    they are separate interpreters)."""
    rec = _run_bench(
        {
            "SHEEPRL_TPU_BENCH_PROBE_CMD": "false",
            "SHEEPRL_TPU_BENCH_MAX_WAIT_SECONDS": "1",
        },
        argv=[sys.executable, "-c", _NOJAX_BENCH_PARENT, BENCH],
    )
    assert rec["outage"] is True and rec["value"] is not None


def test_cache_checkpoint_roundtrip(tmp_path, monkeypatch):
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)

    monkeypatch.setattr(bench, "_CACHE_PATH", str(tmp_path / "cache.json"))
    cache = bench._load_cache()
    assert cache == {}
    bench._checkpoint(cache, "dv3", {"steps": 1, "seconds": 2.0}, "unit-test")
    again = bench._load_cache()
    assert again["dv3"]["value"] == {"steps": 1, "seconds": 2.0}
    assert again["dv3"]["provenance"] == "unit-test"
