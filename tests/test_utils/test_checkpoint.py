"""Checkpoint backends + buffer-consistency fixup (reference:
sheeprl/utils/callback.py:87-148 and fabric.save/load)."""

import os
import pickle

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, ReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.utils.callback import CheckpointCallback
from sheeprl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint, select_buffer


class _FakeFabric:
    num_processes = 1
    world_size = 1
    is_global_zero = True


import collections

Opt = collections.namedtuple("Opt", ["mu", "nu"])


def _tree():
    return {
        "params": {"dense": {"kernel": np.random.rand(4, 3).astype(np.float32), "bias": np.zeros(3)}},
        "opt": Opt(mu=np.ones((4, 3)), nu=np.zeros((4, 3))),
        "ratio": {"ratio": 0.5, "prev": 10},
        "update": 7,
        "name": "run",
        "mixed": [np.arange(5), "text", 3],
    }


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_checkpoint_roundtrip(tmp_path, backend):
    state = _tree()
    path = str(tmp_path / ("ck.ckpt" if backend == "pickle" else "ck_dir.ckpt"))
    save_checkpoint(path, state, backend=backend)
    out = load_checkpoint(path)
    np.testing.assert_array_equal(out["params"]["dense"]["kernel"], state["params"]["dense"]["kernel"])
    np.testing.assert_array_equal(out["opt"].mu, state["opt"].mu)
    assert out["ratio"] == state["ratio"] and out["update"] == 7 and out["name"] == "run"
    np.testing.assert_array_equal(out["mixed"][0], np.arange(5))
    assert out["mixed"][1:] == ["text", 3]
    assert type(out["opt"]).__name__ == "Opt"


def test_checkpoint_truncated_fixup(tmp_path):
    """The SAVED buffer ends every env's episode (truncated=1 at the last
    stored step) while the LIVE buffer is untouched (reference
    callback.py:87-142)."""
    rb = EnvIndependentReplayBuffer(8, n_envs=2, buffer_cls=SequentialReplayBuffer, seed=0)
    data = {
        "obs": np.random.rand(3, 2, 4).astype(np.float32),
        "terminated": np.zeros((3, 2, 1), np.float32),
        "truncated": np.zeros((3, 2, 1), np.float32),
    }
    rb.add(data)

    cb = CheckpointCallback()
    ckpt_path = str(tmp_path / "ck.ckpt")
    cb.on_checkpoint_coupled(_FakeFabric(), ckpt_path, {"update": 1}, replay_buffer=rb)

    # live buffer: unchanged
    for b in rb.buffer:
        assert b["truncated"][(b._pos - 1) % b.buffer_size].sum() == 0
    # stored buffer: last step truncated for every env
    saved = load_checkpoint(ckpt_path)["rb"]
    for b in saved.buffer:
        assert b["truncated"][(b._pos - 1) % b.buffer_size].sum() == 1


def test_checkpoint_plain_replay_buffer_fixup(tmp_path):
    rb = ReplayBuffer(8, n_envs=2, seed=0)
    rb.add(
        {
            "observations": np.zeros((3, 2, 4), np.float32),
            "terminated": np.zeros((3, 2, 1), np.float32),
            "truncated": np.zeros((3, 2, 1), np.float32),
        }
    )
    cb = CheckpointCallback()
    ckpt_path = str(tmp_path / "ck.ckpt")
    cb.on_checkpoint_coupled(_FakeFabric(), ckpt_path, {}, replay_buffer=rb)
    assert rb["truncated"][(rb._pos - 1) % rb.buffer_size].sum() == 0
    saved = load_checkpoint(ckpt_path)["rb"]
    assert saved["truncated"][(saved._pos - 1) % saved.buffer_size].sum() == 2


@pytest.mark.slow
def test_dv3_orbax_resume_restores_buffer_and_counters(tmp_path, monkeypatch):
    """End to end: train tiny DV3 with the orbax backend + buffer checkpoint,
    resume, and verify the restored buffer contents and counters match the
    saved run (VERDICT weak #6 done-criterion)."""
    from sheeprl_tpu.cli import run

    args = [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=dummy_discrete",
        # a real (non-dry) 2-update run so the resume has budget left
        "algo.total_steps=4",
        "checkpoint.every=2",
        "env.capture_video=False",
        "buffer.memmap=False",
        "buffer.checkpoint=True",
        "checkpoint.backend=orbax",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=0",
        f"log_base_dir={tmp_path}/logs",
    ]
    def find_ckpt_dirs():
        found = []
        for root, dirs, _ in os.walk(tmp_path):
            found += [os.path.join(root, d) for d in dirs if d.endswith(".ckpt")]
        return sorted(found)

    monkeypatch.chdir(tmp_path)
    run(args)
    ckpts = find_ckpt_dirs()
    assert ckpts and all(os.path.isdir(c) for c in ckpts)  # orbax ckpts are dirs

    # pretend the run died after update 1: resume from the earliest checkpoint
    first = min(ckpts, key=lambda c: int(os.path.basename(c).split("_")[1]))
    state = load_checkpoint(first)
    assert state["update"] == 1
    rb = select_buffer(state["rb"], 0, 1)
    saved_pos = [b._pos for b in rb.buffer]
    # the stored copy ends every env's episode
    for b in rb.buffer:
        assert b["truncated"][(b._pos - 1) % b.buffer_size].sum() == 1

    run(args + [f"checkpoint.resume_from={first}"])
    new = [c for c in find_ckpt_dirs() if c not in ckpts]
    assert new, "resume did not write a new checkpoint"
    last = max(new, key=lambda c: int(os.path.basename(c).split("_")[1]))
    state2 = load_checkpoint(last)
    assert state2["update"] == 2  # counters continued exactly from update 1
    rb2 = select_buffer(state2["rb"], 0, 1)
    # the restored buffer kept the saved contents and grew by the new steps
    for b2, p in zip(rb2.buffer, saved_pos):
        assert b2._pos == p + 1


def test_select_buffer():
    assert select_buffer("rb", 0, 1) == "rb"
    assert select_buffer(["a", "b"], 1, 2) == "b"
    assert select_buffer(["a"], 0, 1) == "a"
    with pytest.raises(RuntimeError):
        select_buffer(["a", "b", "c"], 0, 2)


def test_elastic_per_rank_batch_size():
    """Elastic resume re-splits the checkpoint's GLOBAL batch over the new
    mesh and fails fast instead of silently flooring (ISSUE satellite)."""
    from sheeprl_tpu.utils.checkpoint import elastic_per_rank_batch_size

    assert elastic_per_rank_batch_size(64, 8) == 8
    assert elastic_per_rank_batch_size(64, 1) == 64
    assert elastic_per_rank_batch_size(8, 8) == 1
    with pytest.raises(ValueError, match="does not split"):
        elastic_per_rank_batch_size(64, 6)  # non-dividing
    with pytest.raises(ValueError, match="does not split"):
        elastic_per_rank_batch_size(4, 8)  # would divide to zero
    with pytest.raises(ValueError, match="does not split"):
        elastic_per_rank_batch_size(0, 4)  # degenerate stored batch
    with pytest.raises(ValueError):
        elastic_per_rank_batch_size(64, 0)  # degenerate world size


def test_orbax_saves_sharded_jax_arrays_without_host_copy(tmp_path):
    """jax.Array leaves (incl. sharded ones) ride the orbax store directly;
    restore materializes them back to numpy."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = jax.devices()
    mesh = Mesh(np.array(devs), ("d",))
    sharded = jax.device_put(
        jnp.arange(len(devs) * 4, dtype=jnp.float32).reshape(len(devs), 4),
        NamedSharding(mesh, P("d", None)),
    )
    state = {"w": sharded, "b": jnp.ones(3), "n": 5}
    path = str(tmp_path / "sharded.ckpt")
    save_checkpoint(path, state, backend="orbax")
    out = load_checkpoint(path)
    np.testing.assert_array_equal(np.asarray(out["w"]), np.asarray(sharded))
    np.testing.assert_array_equal(np.asarray(out["b"]), np.ones(3))
    assert out["n"] == 5


def test_orbax_per_process_sidecars_single(tmp_path):
    """per_process_state rides objects_rank_{i}.pkl and reloads as a
    one-entry-per-process list for select_buffer."""
    rb = ReplayBuffer(8, 1, obs_keys=("observations",))
    rb.add({"observations": np.ones((1, 1, 3), np.float32)})
    path = str(tmp_path / "rank.ckpt")
    save_checkpoint(path, {"update": 3}, backend="orbax", per_process_state={"rb": rb})
    assert os.path.exists(os.path.join(path, "objects_rank_0.pkl"))
    out = load_checkpoint(path)
    assert isinstance(out["rb"], list) and len(out["rb"]) == 1
    picked = select_buffer(out["rb"], 0, 1)
    np.testing.assert_array_equal(picked["observations"][0], np.ones((1, 3), np.float32))


def test_orbax_multiprocess_per_rank_buffers(tmp_path):
    """2 real processes save ONE orbax checkpoint: shared arrays plus one
    buffer sidecar per process; the reload yields a 2-entry rb list
    (VERDICT round-2 item 7: no gathered process-0 pickle)."""
    from tests.conftest import run_multi_process

    code = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
import numpy as np
from sheeprl_tpu.data.buffers import ReplayBuffer
from sheeprl_tpu.utils.checkpoint import save_checkpoint

pid = jax.process_index()
rb = ReplayBuffer(8, 1, obs_keys=("observations",))
rb.add({"observations": np.full((1, 1, 3), pid, np.float32)})
save_checkpoint(
    sys.argv[1], {"update": 2}, backend="orbax", per_process_state={"rb": rb}
)
"""
    path = str(tmp_path / "multi.ckpt")
    run_multi_process(code, argv=[path], cwd=str(tmp_path), nproc=2)
    out = load_checkpoint(path)
    assert out["update"] == 2
    assert isinstance(out["rb"], list) and len(out["rb"]) == 2
    for rank in (0, 1):
        picked = select_buffer(out["rb"], rank, 2)
        np.testing.assert_array_equal(
            picked["observations"][0], np.full((1, 3), rank, np.float32)
        )
