"""MetricAggregator / timer specs (reference: utils/metric.py + timer.py)."""

import time

import pytest

from sheeprl_tpu.utils.metric import (
    LastValueMetric,
    MaxMetric,
    MeanMetric,
    MetricAggregator,
    MetricAggregatorException,
    SumMetric,
)
from sheeprl_tpu.utils.timer import TimerError, timer


def test_mean_metric():
    m = MeanMetric()
    m.update(1.0)
    m.update(3.0)
    assert m.compute() == 2.0
    m.reset()
    assert m.compute() != m.compute() or m.compute() != 0  # NaN


def test_sum_last_max():
    s, l, mx = SumMetric(), LastValueMetric(), MaxMetric()
    for v in (1.0, 5.0, 3.0):
        s.update(v)
        l.update(v)
        mx.update(v)
    assert s.compute() == 9.0
    assert l.compute() == 3.0
    assert mx.compute() == 5.0


def test_aggregator_compute_drops_empty():
    agg = MetricAggregator({"a": "mean", "b": "mean"})
    agg.update("a", 2.0)
    assert agg.compute() == {"a": 2.0}


def test_aggregator_missing_key_warns():
    agg = MetricAggregator({"a": "mean"})
    with pytest.warns(UserWarning):
        agg.update("nope", 1.0)


def test_aggregator_missing_key_raises():
    agg = MetricAggregator({"a": "mean"}, raise_on_missing=True)
    with pytest.raises(MetricAggregatorException):
        agg.update("nope", 1.0)


def test_aggregator_add_duplicate_warns():
    agg = MetricAggregator({"a": "mean"})
    with pytest.warns(UserWarning):
        agg.add("a", "mean")


def test_aggregator_target_specs():
    agg = MetricAggregator({"x": {"_target_": "sheeprl_tpu.utils.metric.MeanMetric"}})
    agg.update("x", 4.0)
    assert agg.compute() == {"x": 4.0}


def test_aggregator_array_update():
    import numpy as np

    agg = MetricAggregator({"a": "mean"})
    agg.update("a", np.array([1.0, 3.0]))
    assert agg.compute() == {"a": 2.0}


def test_timer_accumulates():
    timer.disabled = False
    timer.timers.clear()
    with timer("Time/test_section"):
        time.sleep(0.01)
    with timer("Time/test_section"):
        time.sleep(0.01)
    total = timer.compute()["Time/test_section"]
    assert total >= 0.02
    timer.reset()


def test_timer_double_start_raises():
    t = timer("Time/x")
    t.start()
    with pytest.raises(TimerError):
        t.start()
    t.stop()
    with pytest.raises(TimerError):
        t.stop()
    timer.timers.clear()
