"""Model manager + registration CLI (reference: sheeprl/utils/mlflow.py,
cli.py:394-436, tests via the MLflow-integration CI mode).

MLflow is optional; the default file-backed LocalModelManager is exercised
end to end: train a tiny Dreamer-V3, register its sub-models through the
registration CLI, then inspect/transition/download through the manager."""

import json
import os
import pickle

import numpy as np
import pytest

from sheeprl_tpu.cli import registration, run
from sheeprl_tpu.utils.model_manager import LocalModelManager


def dv3_args(tmp_path):
    return [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=dummy_discrete",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=0",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def test_registration_cli_local_backend(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)

    registry_dir = str(tmp_path / "registry")
    registration([f"checkpoint_path={ckpt}", f"model_manager.registry_dir={registry_dir}"])

    index = json.load(open(os.path.join(registry_dir, "registry.json")))
    # the dreamer_v3 model_manager config registers all five sub-models
    names = sorted(index)
    assert len(names) == 5
    assert any("world_model" in n for n in names)
    for records in index.values():
        assert records[-1]["version"] == 1
        with open(records[-1]["artifact"], "rb") as f:
            tree = pickle.load(f)
        assert tree is not None

    # registering again bumps the version
    registration([f"checkpoint_path={ckpt}", f"model_manager.registry_dir={registry_dir}"])
    index = json.load(open(os.path.join(registry_dir, "registry.json")))
    assert all(records[-1]["version"] == 2 for records in index.values())


def test_local_manager_lifecycle(tmp_path):
    mgr = LocalModelManager(None, str(tmp_path / "registry"))
    artifact = tmp_path / "model.pkl"
    artifact.write_bytes(pickle.dumps({"w": np.ones(3)}))

    rec = mgr.register_model(str(artifact), "my_model", "first version", {"algo": "test"})
    assert rec["version"] == 1 and rec["tags"] == {"algo": "test"}
    assert mgr.get_latest_version("my_model")["version"] == 1

    mgr.register_model(str(artifact), "my_model", "second version")
    assert mgr.get_latest_version("my_model")["version"] == 2

    rec = mgr.transition_model("my_model", 1, "production", "promoted")
    assert rec["stage"] == "production"

    out = tmp_path / "download"
    mgr.download_model("my_model", 2, str(out))
    assert (out / "model.pkl").exists()

    mgr.delete_model("my_model", 1)
    with pytest.raises(FileNotFoundError):
        mgr.download_model("my_model", 1, str(out))
    # the latest version is untouched
    assert mgr.get_latest_version("my_model")["version"] == 2
