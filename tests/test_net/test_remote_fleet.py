"""Router→remote-replica drills over localhost TCP (tentpole acceptance).

Real per-host :func:`~sheeprl_tpu.net.agent.agent_child_main` processes are
spawned, the fleet adopts them via ``serve.fleet.remote_agents``, and the
existing router/supervision machinery serves through them:

- the 2-agent drill proves remote slots take real traffic and answer
  correctly (byte-identical to the local linear forward);
- the chaos drill kills an agent process mid-ramp and asserts the fleet's
  zero-dropped-admitted invariant: every submitted request completes
  correctly on the survivors after the re-route-at-front.
"""

import multiprocessing
import time

import numpy as np
import pytest

from tests.test_serve.conftest import DRILL_FLEET, DRILL_SERVE, commit_linear, expected_action, linear_obs

pytestmark = [pytest.mark.serve, pytest.mark.net]


@pytest.fixture(scope="module")
def mp_ctx():
    return multiprocessing.get_context("spawn")


@pytest.fixture
def spawn_agent(mp_ctx):
    """Factory: a real agent process serving the given linear state on an
    ephemeral localhost port. Yields ``(addr, proc)``; all agents are torn
    down (gracefully, then killed) at test exit."""
    import cloudpickle

    from sheeprl_tpu.net.agent import agent_child_main

    spawned = []

    def build(state, rungs=(1, 2, 4)):
        blob = cloudpickle.dumps(
            {"cfg": {"algo": {"name": "linear"}}, "state": state, "rungs": list(rungs)}
        )
        parent, child = mp_ctx.Pipe(duplex=True)
        proc = mp_ctx.Process(target=agent_child_main, args=(child, blob), daemon=True)
        proc.start()
        child.close()
        spawned.append((proc, parent))
        assert parent.poll(120), "agent never became ready"
        msg = parent.recv()
        assert msg[0] == "ready", f"agent boot failed: {msg}"
        return f"{msg[1]}:{msg[2]}", proc

    yield build
    for proc, parent in spawned:
        try:
            if proc.is_alive():
                parent.send(("close",))
                proc.join(5)
        except Exception:
            pass
        if proc.is_alive():
            proc.kill()
            proc.join(5)
        parent.close()


def make_remote_fleet(tmp_path, remote_agents, **fleet_overrides):
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.fleet import FleetServer
    from sheeprl_tpu.serve.policy import build_linear_policy

    ckpt_dir = str(tmp_path / "checkpoint")
    path, state = commit_linear(ckpt_dir, 100, seed=0)
    policy = build_linear_policy({"algo": {"name": "linear"}}, state)
    node = {
        **DRILL_SERVE,
        "fleet": {
            **DRILL_FLEET,
            "remote_agents": list(remote_agents),
            **fleet_overrides,
        },
    }
    cfg = serve_config_from_cfg({"serve": node})
    return FleetServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir), state


def test_fleet_serves_through_two_remote_agents(tmp_path, spawn_agent):
    from sheeprl_tpu.serve.fleet import REMOTE

    # agents serve the SAME committed state the fleet loads, so any replica
    # (local or remote) must produce the identical action
    _, state0 = commit_linear(str(tmp_path / "checkpoint"), 100, seed=0)
    addr_a, _ = spawn_agent(state0)
    addr_b, _ = spawn_agent(state0)

    server, state = make_remote_fleet(
        tmp_path, [addr_a, addr_b], num_replicas=1, max_replicas=1
    )
    with server:
        snap = server.snapshot()
        assert snap["fleet"]["remote_replicas"] == 2
        remote_slots = [s for s in server.slots if s.kind == REMOTE]
        assert [s.remote_addr for s in remote_slots] == [addr_a, addr_b]
        # wait until both remote incarnations are connected and routable
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(s.alive for s in remote_slots):
                break
            time.sleep(0.02)
        assert all(s.alive for s in remote_slots)

        reqs, obs_sent = [], []
        for i in range(48):
            obs = linear_obs(state, value=float(i % 7))
            reqs.append(server.submit(obs, deadline_s=10.0))
            obs_sent.append(obs)
        for req, obs in zip(reqs, obs_sent):
            out = server.wait(req)
            assert np.allclose(np.asarray(out), expected_action(state, obs), atol=1e-5)

        served_remote = sum(
            s.total_requests + (s.stats.requests if s.stats is not None else 0)
            for s in remote_slots
        )
        assert served_remote >= 1, "no request was ever served by a remote agent"
        snap = server.snapshot()
        assert snap["completed"] == 48
        assert snap["failed"] == 0
        rep = {r["index"]: r for r in snap["fleet"]["replicas"]}
        for s in remote_slots:
            assert rep[s.index]["kind"] == "remote"
            assert rep[s.index]["remote"] == s.remote_addr


def test_kill_agent_mid_ramp_drops_nothing(tmp_path, spawn_agent):
    """The multihost chaos drill: the remote agent PROCESS dies while its
    replica holds in-flight work. The thread dies with the batch still in
    the pool's in-flight window, `_handle_fault` re-routes it at the front
    of the local sibling, and every admitted request still completes — the
    fleet edition of zero-dropped-admitted, now across a host boundary."""
    import os
    import signal

    from sheeprl_tpu.serve.fleet import REMOTE

    _, state0 = commit_linear(str(tmp_path / "checkpoint"), 100, seed=0)
    addr, agent_proc = spawn_agent(state0)

    server, state = make_remote_fleet(
        tmp_path,
        [addr],
        num_replicas=1,
        max_replicas=1,
        remote_timeout_s=2.0,
    )
    with server:
        (remote_slot,) = [s for s in server.slots if s.kind == REMOTE]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not remote_slot.alive:
            time.sleep(0.02)
        assert remote_slot.alive

        # freeze the agent first: any work the router places on the remote
        # slot is now guaranteed to still be there when the process dies —
        # the drill cannot race a fast RESULT
        os.kill(agent_proc.pid, signal.SIGSTOP)

        # ramp: keep submitting until the frozen remote demonstrably holds
        # admitted work (queued or in its in-flight window)
        from sheeprl_tpu.serve.errors import Overloaded

        reqs, obs_sent = [], []
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            obs = linear_obs(state, value=float(len(reqs) % 5))
            try:
                reqs.append(server.submit(obs, deadline_s=20.0))
            except Overloaded:
                time.sleep(0.01)
                continue
            obs_sent.append(obs)
            if remote_slot.pool.depth() + remote_slot.pool.outstanding() >= 1:
                break
        assert remote_slot.pool.depth() + remote_slot.pool.outstanding() >= 1

        agent_proc.kill()  # SIGKILL mid-ramp: worst-case peer death
        agent_proc.join(10)
        assert not agent_proc.is_alive()
        for i in range(12):  # the rest of the ramp rides the survivors
            obs = linear_obs(state, value=float(i % 5))
            reqs.append(server.submit(obs, deadline_s=20.0))
            obs_sent.append(obs)

        dropped = 0
        for req, obs in zip(reqs, obs_sent):
            out = server.wait(req)  # raises if the request was lost/expired
            if not np.allclose(np.asarray(out), expected_action(state, obs), atol=1e-5):
                dropped += 1
        assert dropped == 0

        # the fault was charged to the remote slot (restart attempts against
        # a dead endpoint eventually mask it; either state proves the path)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if remote_slot.restarts >= 1 or remote_slot.masked:
                break
            time.sleep(0.02)
        assert remote_slot.restarts >= 1 or remote_slot.masked
        snap = server.snapshot()
        assert snap["failed"] == 0
        router_snap = snap["fleet"]["router"]
        # the frozen remote's admitted work was re-homed (reroute at the
        # front, or a hedge twin if the reroute raced the hedge scan)
        assert router_snap.get("rerouted_requests", 0) + router_snap.get("hedged", 0) >= 1
