"""`bench.py --net-stats` plumbing: the report reads the run_end ``net``
section (per-endpoint transport counters + per-kind event totals), the
sparse ``net_event`` log, and the ``net_handshake`` clock-skew observations
— and falls back to summing the event stream when the run is still going
(no run_end yet)."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.net

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
BENCH = os.path.join(REPO_ROOT, "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("_bench_net_stats", BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


_EVENTS = [
    {"event": "trace", "kind": "net_handshake", "trace_id": 0, "t": 1.0, "t_mono": 1.0,
     "peer": "actor0", "skew_s": 0.002, "transport": "tcp"},
    {"event": "trace", "kind": "net_handshake", "trace_id": 0, "t": 1.1, "t_mono": 1.1,
     "peer": "actor0", "skew_s": 0.004, "transport": "tcp"},
    {"event": "net_event", "kind": "reconnect", "transport": "tcp.learner", "actor": 0, "generation": 1, "t": 2.0},
    {"event": "net_event", "kind": "disconnect", "transport": "tcp.agent", "peer": "fleet0", "reason": "eof", "t": 3.0},
]

_RUN_END = {
    "event": "run_end",
    "t": 9.0,
    "net": {
        # run_end counted one more reconnect than the flushed stream shows
        "events": {"reconnect": 2, "disconnect": 1},
        "transports": {
            "tcp.learner": {"frames_sent": 10, "frames_recv": 8, "bytes_sent": 1000,
                            "bytes_recv": 800, "reconnects": 2, "checksum_rejects": 1,
                            "heartbeat_gaps": 0, "stale_slabs": 0, "torn_frames": 1},
            "tcp.actor0": {"frames_sent": 8, "frames_recv": 10, "bytes_sent": 800,
                           "bytes_recv": 1000, "reconnects": 0, "checksum_rejects": 0,
                           "heartbeat_gaps": 1, "stale_slabs": 0, "torn_frames": 0},
        },
    },
}


def test_report_prefers_run_end_counters(tmp_path):
    bench = _load_bench()
    path = str(tmp_path / "telemetry.jsonl")
    _write(path, _EVENTS + [_RUN_END])
    out = bench.net_stats_report(path)
    assert out["events"] == {"reconnect": 2, "disconnect": 1}
    assert set(out["transports"]) == {"tcp.learner", "tcp.actor0"}
    assert out["transports"]["tcp.learner"]["checksum_rejects"] == 1
    assert out["totals"]["frames_sent"] == 18
    assert out["totals"]["bytes_recv"] == 1800
    assert out["totals"]["torn_frames"] == 1
    assert out["handshakes"]["count"] == 2
    assert out["handshakes"]["peers"] == ["actor0"]
    assert out["handshakes"]["skew_s"]["actor0"] == 0.004  # upper median of 2
    # the event log keeps the identifying fields for each sparse event
    kinds = [row["kind"] for row in out["event_log"]]
    assert kinds == ["reconnect", "disconnect"]
    assert out["event_log"][1]["reason"] == "eof"


def test_report_falls_back_to_stream_without_run_end(tmp_path):
    bench = _load_bench()
    path = str(tmp_path / "telemetry.jsonl")
    _write(path, _EVENTS)
    out = bench.net_stats_report(path)
    assert out["events"] == {"disconnect": 1, "reconnect": 1}
    assert "transports" not in out  # counters only live in run_end
    assert out["handshakes"]["count"] == 2


def test_report_notes_streams_with_no_net_plane(tmp_path):
    bench = _load_bench()
    path = str(tmp_path / "telemetry.jsonl")
    _write(path, [{"event": "heartbeat", "t": 1.0}])
    out = bench.net_stats_report(path)
    assert "note" in out and "multihost" in out["note"]


def test_net_stats_cli(tmp_path):
    """`bench.py --net-stats PATH` prints the JSON report (jax-free parent)."""
    path = str(tmp_path / "telemetry.jsonl")
    _write(path, _EVENTS + [_RUN_END])
    proc = subprocess.run(
        [sys.executable, BENCH, "--net-stats", path],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout)
    assert rec["totals"]["frames_sent"] == 18
