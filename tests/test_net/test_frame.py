"""Framing edge cases (satellite: TCP framing): partial reads, corrupt-CRC
frames skipped without poisoning the stream, protocol violations severing the
connection, and the partial-frame report the torn-write classifier reads."""

import struct

import pytest

from sheeprl_tpu.net.frame import (
    F_HEARTBEAT,
    F_HELLO,
    F_SLAB,
    MAGIC,
    PREAMBLE_BYTES,
    PROTO_VERSION,
    FrameDecoder,
    ProtocolError,
    encode_frame,
)

pytestmark = pytest.mark.net


def test_roundtrip_single_and_coalesced():
    d = FrameDecoder()
    a = encode_frame(F_HELLO, b"hello-payload")
    b = encode_frame(F_SLAB, b"\x00" * 100, flags=3)
    c = encode_frame(F_HEARTBEAT)  # empty payload
    # one feed carrying three coalesced frames (Nagle's reality)
    frames = d.feed(a + b + c)
    assert [(t, f, p) for t, f, p in frames] == [
        (F_HELLO, 0, b"hello-payload"),
        (F_SLAB, 3, b"\x00" * 100),
        (F_HEARTBEAT, 0, b""),
    ]
    assert d.buffered == 0
    assert d.partial() is None


def test_partial_reads_byte_by_byte():
    """A frame dribbling in one byte at a time decodes exactly once, at the
    final byte — the mid-read states never yield anything."""
    d = FrameDecoder()
    frame = encode_frame(F_SLAB, bytes(range(64)))
    for byte in frame[:-1]:
        assert d.feed(bytes([byte])) == []
    (got,) = d.feed(frame[-1:])
    assert got == (F_SLAB, 0, bytes(range(64)))


def test_partial_report_stages():
    """`partial()` is the torn-write classifier's evidence: it must say
    *whether* a frame was in flight and how much of it landed."""
    d = FrameDecoder()
    assert d.partial() is None  # idle stream
    frame = encode_frame(F_SLAB, b"x" * 200)
    # preamble incomplete: a frame is in flight but its type is unknowable
    d.feed(frame[: PREAMBLE_BYTES - 4])
    ftype, length, got = d.partial()
    assert ftype == -1
    # mid-payload: type + declared length known, payload partially landed
    d2 = FrameDecoder()
    d2.feed(frame[: PREAMBLE_BYTES + 50])
    ftype, length, got = d2.partial()
    assert ftype == F_SLAB and length == 200 and len(got) == 50


def test_corrupt_crc_skipped_stream_survives():
    """A bit-flipped frame is dropped and counted; the NEXT frame on the same
    stream still decodes — one torn slab must never poison the connection."""
    d = FrameDecoder()
    bad = bytearray(encode_frame(F_SLAB, b"a" * 50))
    bad[PREAMBLE_BYTES + 10] ^= 0xFF  # flip a payload bit: CRC mismatch
    good = encode_frame(F_SLAB, b"b" * 50)
    frames = d.feed(bytes(bad) + good)
    assert frames == [(F_SLAB, 0, b"b" * 50)]
    assert d.checksum_rejects == 1
    assert d.partial() is None


def test_bad_magic_is_protocol_error():
    d = FrameDecoder()
    with pytest.raises(ProtocolError):
        d.feed(b"JUNKJUNKJUNKJUNKJUNK")


def test_bad_version_is_protocol_error():
    frame = bytearray(encode_frame(F_HELLO, b"x"))
    frame[4] = PROTO_VERSION + 1
    with pytest.raises(ProtocolError):
        FrameDecoder().feed(bytes(frame))


def test_absurd_length_is_protocol_error():
    """A declared length past MAX_PAYLOAD_BYTES is a corrupted or hostile
    preamble — drop the connection, don't try to buffer 4 GiB."""
    preamble = struct.pack("<4sBBHII", MAGIC, PROTO_VERSION, F_SLAB, 0, 0xFFFFFFFF, 0)
    with pytest.raises(ProtocolError):
        FrameDecoder().feed(preamble)


def test_empty_feed_is_noop():
    d = FrameDecoder()
    assert d.feed(b"") == []
    assert d.buffered == 0
