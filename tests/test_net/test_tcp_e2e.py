"""Localhost-TCP actor→learner drills (tentpole acceptance): the decoupled
PPO entrypoint with ``algo.actor_learner.transport=tcp`` spawns a real actor
process that dials the learner over 127.0.0.1 and trains to completion with
zero torn slabs trained on and zero admitted slabs dropped. The crash drill
re-runs the canonical mid-write death: over TCP the victim is half a frame on
the wire, classified torn by the learner, restart charged, run completes."""

import json
import os

import pytest

from sheeprl_tpu.cli import run

pytestmark = [pytest.mark.actor_learner, pytest.mark.net]


def tcp_args(tmp_path):
    return [
        "exp=ppo_decoupled",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        "metric.telemetry.poll_interval=0.0",
        "algo.actor_learner.num_actors=1",
        "algo.actor_learner.slots_per_actor=2",
        "algo.actor_learner.transport=tcp",
        f"log_base_dir={tmp_path}/logs",
    ]


def read_runs(path):
    return [json.loads(line) for line in open(path) if line.strip()]


def read_telemetry(tmp_path):
    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1
    return [json.loads(line) for line in open(jsonls[0]) if line.strip()]


def test_ppo_over_localhost_tcp(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(tcp_args(tmp_path) + [f"metric.telemetry.runs_jsonl={runs}"])

    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    assert rec["variant"] == "actor_learner"
    # the zero-torn / zero-dropped-admitted invariants, over the wire
    assert rec.get("slabs_admitted", 0) >= 1
    assert rec.get("torn_slabs", 0) == 0
    assert rec.get("dropped_stale_slabs", 0) == 0

    # no shm segments were ever created: the data plane was sockets
    from sheeprl_tpu.rollout.shm import _OWNED_SEGMENTS

    assert not _OWNED_SEGMENTS

    events = read_telemetry(tmp_path)
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    # run_end carries the per-transport counter rollup (satellite: net-stats)
    net = run_end.get("net")
    assert net, "tcp run_end must carry a net section"
    transports = net["transports"]
    assert "tcp.learner" in transports
    stats = transports["tcp.learner"]
    assert stats["frames_recv"] >= rec["slabs_admitted"]
    assert stats["checksum_rejects"] == 0
    assert stats["bytes_recv"] > 0

    # the cross-host trace seam: the handshake recorded a skew estimate
    trace_files = [p for p in rec["telemetry_files"] if "trace." in os.path.basename(p)]
    assert trace_files
    all_events = []
    for p in rec["telemetry_files"]:
        with open(p) as fh:
            all_events += [json.loads(l) for l in fh if l.strip()]
    handshakes = [e for e in all_events if e.get("kind") == "net_handshake"]
    assert handshakes and all("skew_s" in e for e in handshakes)


def test_tcp_actor_crash_mid_write_drill(tmp_path, monkeypatch):
    """Mid-write death over TCP: half a slab frame on the wire. The learner
    classifies it torn (never admitted), the supervisor charges one restart,
    and the respawned generation completes the run."""
    monkeypatch.chdir(tmp_path)
    runs = tmp_path / "RUNS.jsonl"
    run(
        tcp_args(tmp_path)
        + [
            "algo.actor_learner.fault_injection.enabled=True",
            "algo.actor_learner.fault_injection.faults=[{kind: actor_crash_mid_write, actor: 0, at_slab: 0}]",
            f"metric.telemetry.runs_jsonl={runs}",
        ]
    )
    (rec,) = read_runs(runs)
    assert rec["outcome"] == "completed"
    assert rec.get("torn_slabs", 0) >= 1  # detected, never trained on
    assert rec.get("slabs_admitted", 0) >= 1
    assert rec.get("actor_restarts") == {"0": 1}

    events = read_telemetry(tmp_path)
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    stats = run_end["net"]["transports"]["tcp.learner"]
    # the torn classification is visible in the transport counters too
    assert stats["torn_frames"] + stats["checksum_rejects"] >= 1
    # net_event stream mirrors the serve/rollout pattern
    net_events = [e for e in events if e["event"] == "net_event"]
    assert any(e.get("kind") in ("torn_frame", "disconnect") for e in net_events)

    # the victim's causal chain terminates at `torn` on the merged timeline
    from tools import trace as trace_tool

    merged = trace_tool.merge(rec["telemetry_files"])
    torn_chains = [
        evs for evs in merged["traces"].values() if trace_tool.slab_terminal(evs) == "torn"
    ]
    assert len(torn_chains) >= 1
