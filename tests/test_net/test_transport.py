"""TCP transport unit drills over real localhost sockets, single process.

The learner end is single-threaded and pumped inline, so the actor end dials
from a helper thread while the test thread pumps ``poll()`` — the same
interleaving the two-process drills exercise, without the process spawns.

Edge cases covered (satellite: TCP framing):

- credit flow control == ring backpressure (``try_begin_write`` False at 0)
- mid-frame peer death classified torn, with trace-id attribution when the
  slab header fully landed
- a checksum-corrupt frame is rejected without poisoning the stream: the
  next slab on the same connection is admitted
- reconnect-with-generation-bump never re-admits a stale slab from a zombie
  connection
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from sheeprl_tpu.net.frame import (
    F_HELLO,
    F_HELLO_ACK,
    F_SLAB,
    FrameDecoder,
    encode_frame,
)
from sheeprl_tpu.net.stats import reset_net_stats
from sheeprl_tpu.net.transport import (
    TcpLearnerTransport,
    attach_actor_transport,
)

pytestmark = pytest.mark.net

PAYLOAD = 256  # big enough that half a slab frame includes the full header


@pytest.fixture(autouse=True)
def _fresh_stats():
    reset_net_stats()
    yield
    reset_net_stats()


@pytest.fixture
def learner():
    lt = TcpLearnerTransport(
        payload_bytes=PAYLOAD, num_slots=4, slots_per_actor=2, param_nbytes=32
    )
    yield lt
    lt.close()


def dial(lt, actor_id=0, generation=0):
    """Connect an actor end while pumping the single-threaded learner end."""
    box = {}

    def _dial():
        try:
            box["at"] = attach_actor_transport(
                lt.actor_wire(actor_id),
                actor_id=actor_id,
                generation=generation,
                slots=[0, 1],
            )
        except Exception as err:  # surfaced by the caller
            box["err"] = err

    t = threading.Thread(target=_dial, daemon=True)
    t.start()
    deadline = time.monotonic() + 10
    while t.is_alive() and time.monotonic() < deadline:
        lt.poll()
        time.sleep(0.002)
    t.join(timeout=1)
    if "err" in box:
        raise box["err"]
    return box["at"]


def pump_until(lt, pred, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = lt.poll()
        if pred(got):
            return got
        time.sleep(0.002)
    raise AssertionError("condition not reached while pumping learner transport")


def write_slab(at, seq, fill=0, trace_id=0, param_version=0):
    assert at.try_begin_write()
    at.payload_view()[:] = fill
    at.write_meta(
        seq=seq,
        param_version=param_version,
        actor_id=at.actor_id,
        n_rows=8,
        collect_us=1000,
        env_steps=8,
        trace_id=trace_id,
        commit_t_us=int(time.time() * 1e6),
    )
    at.commit()


def test_handshake_credits_and_param_replay(learner):
    # publish BEFORE any actor exists: the late joiner must still receive it
    learner.publish_params(np.arange(32, dtype=np.uint8), 3)
    at = dial(learner)
    assert at.credits == 2
    deadline = time.monotonic() + 5
    while at.param_version() < 0 and time.monotonic() < deadline:
        learner.poll()
        time.sleep(0.002)
    version, data = at.poll_params()
    assert version == 3
    assert list(data[:4]) == [0, 1, 2, 3]
    at.close()


def test_slab_roundtrip_meta_fidelity(learner):
    at = dial(learner)
    write_slab(at, seq=11, fill=7, trace_id=424242, param_version=5)
    meta = pump_until(learner, lambda m: m is not None)
    assert (meta.seq, meta.param_version, meta.actor_id) == (11, 5, 0)
    assert (meta.trace_id, meta.n_rows, meta.env_steps) == (424242, 8, 8)
    assert meta.collect_us == 1000 and meta.commit_t_us > 0
    assert np.all(learner.payload(meta) == 7)
    learner.release(meta)
    assert learner.torn_detected == 0
    at.close()


def test_credit_exhaustion_is_backpressure(learner):
    at = dial(learner)
    write_slab(at, seq=0)
    write_slab(at, seq=1)
    assert at.credits == 0
    assert not at.try_begin_write()  # blocked, not an error
    m0 = pump_until(learner, lambda m: m is not None)
    learner.release(m0)  # SLAB_ACK returns the credit
    deadline = time.monotonic() + 5
    while not at.try_begin_write():
        assert time.monotonic() < deadline, "credit never returned"
        learner.poll()
        time.sleep(0.002)
    assert at.credits == 1  # begin_write holds a claim on the returned credit
    at.close()


def test_midframe_death_is_torn_with_trace_id(learner):
    at = dial(learner)
    write_slab(at, seq=0, trace_id=101)  # a cleanly committed slab first
    assert at.try_begin_write()
    at.payload_view()[:] = 9
    at.write_meta(
        seq=1, param_version=0, actor_id=0, n_rows=8, collect_us=1,
        env_steps=8, trace_id=777, commit_t_us=1,
    )
    at.abort_torn()  # half the frame hits the wire...
    at.sock.close()  # ...then the peer dies
    meta = pump_until(learner, lambda m: m is not None)
    assert meta.seq == 0  # committed is committed: the full frame is kept
    pump_until(learner, lambda _: learner.torn_detected == 1)
    # header landed whole inside the half-frame: the victim is attributable
    assert learner.drain_torn_trace_ids() == [777]
    assert learner.stats.torn_frames == 1


def test_corrupt_frame_rejected_stream_survives(learner):
    """Raw socket speaking the protocol: a bit-flipped slab frame is counted
    as a checksum reject + torn, and the NEXT frame on the same connection is
    admitted — one corrupt slab never poisons the link."""
    sock = socket.create_connection((learner.host, learner.port), timeout=10)
    decoder = FrameDecoder()
    hello = {"role": "actor0", "actor_id": 0, "generation": 0, "t_wall": time.time()}
    sock.sendall(encode_frame(F_HELLO, json.dumps(hello).encode()))
    # pump the learner until the HELLO_ACK comes back
    acked = []
    deadline = time.monotonic() + 10
    while not acked and time.monotonic() < deadline:
        learner.poll()
        sock.setblocking(False)
        try:
            data = sock.recv(1 << 16)
            acked = [f for f in decoder.feed(data) if f[0] == F_HELLO_ACK]
        except (BlockingIOError, InterruptedError):
            pass
        time.sleep(0.002)
    assert acked
    sock.setblocking(True)

    hdr = np.zeros(10, dtype=np.int64)
    from sheeprl_tpu.actor_learner.ring import CHECKSUM, COMMITTED, SEQ, STATE, _checksum

    hdr[STATE] = COMMITTED
    hdr[SEQ] = 1
    hdr[4] = 8  # n_rows
    hdr[CHECKSUM] = _checksum(hdr[SEQ:CHECKSUM])
    good = encode_frame(F_SLAB, hdr.tobytes() + bytes(PAYLOAD))
    corrupt = bytearray(good)
    corrupt[-1] ^= 0xFF  # payload bit flip: frame CRC mismatch
    sock.sendall(bytes(corrupt))
    hdr[SEQ] = 2
    hdr[CHECKSUM] = _checksum(hdr[SEQ:CHECKSUM])
    sock.sendall(encode_frame(F_SLAB, hdr.tobytes() + bytes(PAYLOAD)))

    meta = pump_until(learner, lambda m: m is not None)
    assert meta.seq == 2  # the frame AFTER the corrupt one decoded cleanly
    assert learner.stats.checksum_rejects == 1
    assert learner.torn_detected == 1
    sock.close()


def test_header_mix_mismatch_is_torn(learner):
    """Frame CRC intact but the slab-header mix wrong (recycled/corrupt meta):
    the slab is torn + attributed, never admitted."""
    sock = socket.create_connection((learner.host, learner.port), timeout=10)
    hello = {"role": "actor0", "actor_id": 0, "generation": 0, "t_wall": time.time()}
    sock.sendall(encode_frame(F_HELLO, json.dumps(hello).encode()))
    from sheeprl_tpu.actor_learner.ring import CHECKSUM, COMMITTED, SEQ, STATE, TRACE_ID

    hdr = np.zeros(10, dtype=np.int64)
    hdr[STATE] = COMMITTED
    hdr[SEQ] = 1
    hdr[TRACE_ID] = 555
    hdr[CHECKSUM] = 12345  # NOT the mix
    sock.sendall(encode_frame(F_SLAB, hdr.tobytes() + bytes(PAYLOAD)))
    pump_until(learner, lambda _: learner.torn_detected == 1)
    assert learner.drain_torn_trace_ids() == [555]
    assert learner.poll() is None
    sock.close()


def test_generation_bump_drops_stale_slab(learner):
    """The zombie drill: gen-0 connection lingers, supervisor reclaims the
    actor (floor bump), gen-1 reconnects. A slab the zombie then flushes must
    be dropped as stale; the successor's slab is admitted."""
    zombie = dial(learner, actor_id=0, generation=0)
    learner.reclaim_actor(0, [0, 1])  # supervisor: actor 0 is dead to me
    successor = dial(learner, actor_id=0, generation=1)
    assert learner.stats.reconnects == 1

    # the zombie flushes a slab on its (severed learner-side) connection:
    # the send may fail outright — either way nothing is admitted
    try:
        write_slab(zombie, seq=99, trace_id=1)
    except Exception:
        pass

    write_slab(successor, seq=100, trace_id=2)
    meta = pump_until(learner, lambda m: m is not None)
    assert meta.seq == 100 and meta.trace_id == 2
    learner.release(meta)
    assert learner.poll() is None  # the zombie's slab never surfaced
    successor.close()


def test_zombie_slab_on_live_connection_is_stale(learner):
    """Even if the zombie's connection survives (reclaim raced the flush),
    a slab arriving with a below-floor generation is counted stale and
    dropped — re-admission is impossible by construction."""
    zombie = dial(learner, actor_id=0, generation=0)
    # successor HELLO raises the floor; zombie's conn is severed learner-side
    # — so instead emulate the race: raise the floor directly, keep the conn
    learner._generations[0] = 5
    write_slab(zombie, seq=7, trace_id=3)
    deadline = time.monotonic() + 5
    while learner.stats.stale_slabs == 0 and time.monotonic() < deadline:
        assert learner.poll() is None, "stale slab must never be admitted"
        time.sleep(0.002)
    assert learner.stats.stale_slabs == 1
    zombie.close()


def test_learner_close_says_bye(learner):
    at = dial(learner)
    learner.close()
    from sheeprl_tpu.net.transport import TransportError

    with pytest.raises(TransportError):
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            at.try_begin_write()  # pumps; sees F_BYE or the closed socket
            time.sleep(0.002)
