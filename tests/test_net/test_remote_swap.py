"""Remote hot-swap drills: the gauntlet runs ON THE REMOTE HOST.

A real :func:`~sheeprl_tpu.net.agent.agent_child_main` process serves a
committed checkpoint while a fleet routes live traffic to it; the parent
then pushes degraded checkpoints at it over the control pipe:

- a *poisoned* checkpoint (NaN planted before the manifest was built, so
  the commit is digest-clean) must be rejected by the remote gauntlet's
  finiteness gate with zero in-flight requests dropped;
- a *torn* checkpoint (payload, no manifest) must be refused before the
  gauntlet even loads it;
- a good checkpoint must then swap in and change the served actions —
  proving the rejections were the gauntlet's judgment, not a dead pipe.
"""

import copy
import multiprocessing
import os
import time

import numpy as np
import pytest

from tests.test_serve.conftest import (
    DRILL_FLEET,
    DRILL_SERVE,
    commit_linear,
    expected_action,
    linear_obs,
)

pytestmark = [pytest.mark.serve, pytest.mark.net, pytest.mark.online]


@pytest.fixture
def spawn_swap_agent(tmp_path):
    """Like test_remote_fleet's spawn_agent, but the blob carries the boot
    checkpoint identity (step/path) so the agent's gauntlet has a baseline,
    and the parent KEEPS the pipe to drive ``("swap", path)`` messages."""
    import cloudpickle

    from sheeprl_tpu.net.agent import agent_child_main

    ctx = multiprocessing.get_context("spawn")
    spawned = []

    def build(state, *, step, path, rungs=(1, 2, 4)):
        blob = cloudpickle.dumps(
            {
                "cfg": {"algo": {"name": "linear"}},
                "state": state,
                "rungs": list(rungs),
                "step": int(step),
                "path": str(path),
            }
        )
        parent, child = ctx.Pipe(duplex=True)
        proc = ctx.Process(target=agent_child_main, args=(child, blob), daemon=True)
        proc.start()
        child.close()
        spawned.append((proc, parent))
        assert parent.poll(120), "agent never became ready"
        msg = parent.recv()
        assert msg[0] == "ready", f"agent boot failed: {msg}"
        return f"{msg[1]}:{msg[2]}", proc, parent

    yield build
    for proc, parent in spawned:
        try:
            if proc.is_alive():
                parent.send(("close",))
                proc.join(5)
        except Exception:
            pass
        if proc.is_alive():
            proc.kill()
            proc.join(5)
        parent.close()


def _pipe_reply(parent, timeout_s=30.0):
    assert parent.poll(timeout_s), "no reply from remote agent"
    return parent.recv()


def _poison(state):
    poisoned = copy.deepcopy(state)
    arr = np.array(poisoned["agent"]["w"])
    arr.flat[0] = np.nan
    poisoned["agent"]["w"] = arr
    return poisoned


def test_remote_gauntlet_rejects_degraded_swaps_in_flight_unharmed(tmp_path, spawn_swap_agent):
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.fleet import REMOTE, FleetServer
    from sheeprl_tpu.serve.policy import build_linear_policy, make_linear_state
    from sheeprl_tpu.utils.checkpoint import save_checkpoint

    ckpt_dir = str(tmp_path / "checkpoint")
    path100, state0 = commit_linear(ckpt_dir, 100, seed=0)
    addr, proc, parent = spawn_swap_agent(state0, step=100, path=path100)

    # the publish dir is separate from the fleet's ckpt_dir: every swap in
    # this drill is explicit, none comes from a background watcher
    pub_dir = str(tmp_path / "published")
    poison_path, _ = commit_linear(pub_dir, 110, state=_poison(state0))
    torn_path = os.path.join(pub_dir, "ckpt_115_0.ckpt")
    save_checkpoint(torn_path, make_linear_state(seed=1), backend="pickle", manifest=None)
    state1 = make_linear_state(seed=1)
    good_path, _ = commit_linear(pub_dir, 120, state=state1)

    policy = build_linear_policy({"algo": {"name": "linear"}}, state0)
    node = {
        **DRILL_SERVE,
        "fleet": {**DRILL_FLEET, "remote_agents": [addr], "num_replicas": 1, "max_replicas": 1},
    }
    cfg = serve_config_from_cfg({"serve": node})
    server = FleetServer(policy, cfg, step=100, path=path100, ckpt_dir=ckpt_dir)
    with server:
        remote_slots = [s for s in server.slots if s.kind == REMOTE]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not all(s.alive for s in remote_slots):
            time.sleep(0.02)
        assert all(s.alive for s in remote_slots)

        # 48 requests in flight, then the poisoned checkpoint lands mid-swarm
        reqs = []
        for i in range(48):
            obs = linear_obs(state0, value=float(i % 7))
            reqs.append((server.submit(obs, deadline_s=10.0), obs))
        parent.send(("swap", poison_path))
        for req, obs in reqs:
            out = server.wait(req)  # zero dropped: every admitted completes
            assert np.allclose(np.asarray(out), expected_action(state0, obs), atol=1e-5)
        msg = _pipe_reply(parent)
        assert msg[0] == "swap_rejected", msg
        assert "non-finite" in msg[1]

        # torn checkpoint: refused before the gauntlet even loads a byte
        parent.send(("swap", torn_path))
        msg = _pipe_reply(parent)
        assert msg[0] == "swap_rejected", msg
        assert "manifest" in msg[1]

        # still serving the boot version, still correct
        obs = linear_obs(state0, value=3.0)
        out = server.wait(server.submit(obs, deadline_s=10.0))
        assert np.allclose(np.asarray(out), expected_action(state0, obs), atol=1e-5)

        # the good checkpoint swaps in remotely AND locally (the same commit
        # the publisher would fan out), and the served actions change with it
        parent.send(("swap", good_path))
        msg = _pipe_reply(parent)
        assert msg == ("swap_ok", 120), msg
        server.request_swap(good_path)
        obs = linear_obs(state1, value=2.0)
        out = server.wait(server.submit(obs, deadline_s=10.0))
        assert np.allclose(np.asarray(out), expected_action(state1, obs), atol=1e-5)

    # the agent's own books agree: one promotion, two gauntlet rejections
    parent.send(("close",))
    msg = _pipe_reply(parent)
    assert msg[0] == "bye"
    _, batches, requests, swaps, swap_rejects = msg
    assert requests >= 1
    assert swaps == 1
    assert swap_rejects == 2
    proc.join(10)
