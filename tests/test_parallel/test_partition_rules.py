"""Regex partition rules (ISSUE 14): every superstep carry leaf gets a
PartitionSpec from the rule table, Adam moment twins co-shard with their
kernels, and unmatched leaves fall back to replication with a warn-once
per path."""

import warnings

import jax
import jax.numpy as jnp
import optax
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.parallel.fabric import (
    Fabric,
    reset_partition_rule_warnings,
    tree_path_str,
)

_IS_SPEC = lambda s: isinstance(s, P)  # noqa: E731 — P() nests as a pytree


@pytest.fixture
def fabric2d():
    return Fabric(devices=8, precision="fp32", mesh_axes=("data", "model"), mesh_shape=(2, 4))


def _params():
    # flax-style names: the repo's only custom param names are
    # kernel / bias / scale / initial_recurrent_state
    return {
        "Dense_0": {"kernel": jnp.zeros((8, 8)), "bias": jnp.zeros((8,))},
        "LayerNorm_0": {"scale": jnp.ones((8,)), "bias": jnp.zeros((8,))},
        "cell": {"initial_recurrent_state": jnp.zeros((1, 8))},
    }


def test_every_carry_leaf_gets_a_spec_and_twins_co_shard(fabric2d):
    """The whole (params, opt) carry maps leaf-for-leaf to PartitionSpecs:
    kernels shard P(None, 'model'), bias/scale/initial state replicate, and
    Adam mu/nu mirror their kernel's spec (the silent-all-gather fix)."""
    params = _params()
    opt = optax.adam(1e-3).init(params)
    specs = fabric2d.match_partition_rules((params, opt))

    spec_leaves = jax.tree.leaves(specs, is_leaf=_IS_SPEC)
    assert len(spec_leaves) == len(jax.tree.leaves((params, opt)))
    assert all(isinstance(s, P) for s in spec_leaves)

    param_specs, opt_specs = specs
    assert param_specs["Dense_0"]["kernel"] == P(None, "model")
    assert param_specs["Dense_0"]["bias"] == P()
    assert param_specs["LayerNorm_0"]["scale"] == P()
    assert param_specs["cell"]["initial_recurrent_state"] == P()
    adam = opt_specs[0]  # optax.adam = chain(scale_by_adam, scale)
    assert adam.mu["Dense_0"]["kernel"] == P(None, "model")
    assert adam.nu["Dense_0"]["kernel"] == P(None, "model")
    assert adam.mu["Dense_0"]["bias"] == P()
    assert adam.count == P()


def test_explicit_spec_and_custom_rules_win_over_defaults(fabric2d):
    """First-match-wins: a caller rule earlier in the table overrides the
    defaults, and an explicit PartitionSpec is used verbatim."""
    params = _params()
    rules = (
        (r"Dense_0/kernel$", P("model", None)),
        (r"(^|/)kernel$", "replicate"),
        (r".*", "replicate"),
    )
    specs = fabric2d.match_partition_rules(params, rules=rules)
    assert specs["Dense_0"]["kernel"] == P("model", None)
    assert specs["LayerNorm_0"]["scale"] == P()

    with pytest.raises(ValueError, match="unknown partition-rule strategy"):
        fabric2d.match_partition_rules(params, rules=((r".*", "shard-it"),))


def test_unmatched_leaf_replicates_with_warn_once(fabric2d):
    """An unmatched leaf falls back to P() and warns exactly once per path;
    reset_partition_rule_warnings re-arms the filter."""
    reset_partition_rule_warnings()
    tree = {"mystery_stat": jnp.zeros((4, 4))}
    with pytest.warns(UserWarning, match="no partition rule matched leaf 'mystery_stat'"):
        specs = fabric2d.match_partition_rules(tree)
    assert specs["mystery_stat"] == P()

    with warnings.catch_warnings():
        warnings.simplefilter("error")  # a second warning would raise
        again = fabric2d.match_partition_rules(tree)
    assert again["mystery_stat"] == P()

    reset_partition_rule_warnings()
    with pytest.warns(UserWarning, match="mystery_stat"):
        fabric2d.match_partition_rules(tree)
    reset_partition_rule_warnings()


def test_carry_shardings_wrap_specs_in_named_shardings(fabric2d):
    """carry_shardings maps the spec tree onto the fabric mesh for
    device_put / jit shardings; leaf-for-leaf with the carry."""
    params = {"Dense_0": {"kernel": jnp.zeros((8, 8)), "bias": jnp.zeros((8,))}}
    shardings = fabric2d.carry_shardings(params)
    kern = shardings["Dense_0"]["kernel"]
    assert kern.mesh == fabric2d.mesh and kern.spec == P(None, "model")
    placed = jax.device_put(params, shardings)
    assert "model" in repr(placed["Dense_0"]["kernel"].sharding)


def test_path_rendering_covers_namedtuple_dict_and_sequence_keys():
    """tree_path_str renders optax namedtuple fields, dict keys and chain
    indices into the '/'-joined names the rule table matches against."""
    params = {"Dense_0": {"kernel": jnp.zeros((4, 4))}}
    opt = optax.adam(1e-3).init(params)
    flat, _ = jax.tree_util.tree_flatten_with_path(opt)
    names = [tree_path_str(p) for p, _ in flat]
    assert "0/count" in names
    assert "0/mu/Dense_0/kernel" in names
    assert "0/nu/Dense_0/kernel" in names
