"""Sharded fused supersteps (ISSUE 4): the K-step training scan runs
data-parallel over the mesh — numerical equivalence against the
single-device superstep, plus the sharded DeviceReplayBuffer ring's
shard-local wrap-around parity with the host Sequential pair."""

import numpy as np
import pytest

from sheeprl_tpu.data.buffers import EnvIndependentReplayBuffer, SequentialReplayBuffer
from sheeprl_tpu.data.device_buffer import DeviceReplayBuffer
from sheeprl_tpu.parallel.fabric import Fabric


# --------------------------------------------------------------------------
# multichip child helper (run by the multichip_run fixture in a fresh
# subprocess with its own --xla_force_host_platform_device_count)
# --------------------------------------------------------------------------
def superstep_equivalence_case(n_devices, out_path):
    """Run ONE K=4 fused superstep window over a deterministic linear-model
    train body on an ``n_devices`` mesh and dump (params, opt state, target
    EMA, metrics) to ``out_path``. The parent runs this at 4 devices and at
    1 device on the SAME pregathered batch stack (the mesh run consumes it
    batch-axis sharded) and asserts the results match: per-shard batch-mean
    loss + grad pmean == full-batch gradient, and the replicated carries put
    every optimizer/EMA update through identical arithmetic."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from sheeprl_tpu.ops.superstep import make_superstep_fn, periodic_target_ema, pregathered

    n_devices = int(n_devices)
    fabric = Fabric(devices=n_devices, precision="fp32")
    multi = n_devices > 1
    axis = fabric.data_axis
    K, B, D = 4, 8, 3
    rng = np.random.default_rng(7)
    xs = jnp.asarray(rng.normal(size=(K, B, D)).astype(np.float32))
    ys = jnp.asarray(rng.normal(size=(K, B, 1)).astype(np.float32))
    model = {
        "w": jnp.asarray(rng.normal(size=(D, 1)).astype(np.float32)),
        "b": jnp.zeros((1,), jnp.float32),
    }
    target = jax.tree.map(jnp.zeros_like, model)
    tx = optax.adam(1e-2)
    opt = tx.init(model)

    def train_body(params, aux, batch, key):
        del key  # deterministic body — a key-dependent loss would (correctly)
        # diverge across device counts, since each shard folds its own key
        model, target = params
        (opt,) = aux
        x, y = batch

        def loss_fn(m):
            return jnp.mean(jnp.square(x @ m["w"] + m["b"] - y))

        loss, grads = jax.value_and_grad(loss_fn)(model)
        if multi:
            grads = jax.lax.pmean(grads, axis)
            loss = jax.lax.pmean(loss, axis)
        updates, opt = tx.update(grads, opt, model)
        model = optax.apply_updates(model, updates)
        return (model, target), (opt,), jnp.stack([loss])

    def pre_step(params, aux, counter):
        # freq=2 exercises both cond branches inside one K=4 window, and the
        # counter==0 hard copy pins the EMA schedule's warm start
        model, target = params
        target = periodic_target_ema(counter, model, target, 2, 0.25)
        return (model, target), aux

    superstep = make_superstep_fn(
        train_body,
        pregathered,
        K,
        pre_step=pre_step,
        mesh=fabric.mesh if multi else None,
        data_axis=axis if multi else None,
        ctx_spec=P(None, axis) if multi else None,
    )
    ctx = (xs, ys)
    if multi:
        ctx = jax.device_put(ctx, fabric.sharding(None, axis))
    params, aux, _key, metrics = superstep(
        (model, target), (opt,), jnp.int32(0), ctx, jax.random.PRNGKey(0)
    )
    leaves = jax.tree.leaves((params, aux, metrics))
    np.savez(out_path, **{f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)})


def superstep_equivalence_case_2d(n_devices, out_path):
    """ISSUE 14: TWO K=4 fused superstep windows over a deterministic linear
    train body on a 2-D ``(data, model)`` GSPMD mesh (``n_devices > 1``; the
    mesh is ``2 x n/2``) or a single device, dumping (params, opt state,
    target EMA, metrics) to ``out_path``. The mesh child additionally asserts
    the ISSUE-14 carry invariants in-process: the kernel AND its Adam moment
    twins stay model-axis sharded across windows, and window 2 reuses window
    1's executable (zero recompiles)."""
    n_devices = int(n_devices)
    multi = n_devices > 1
    if multi:
        fabric = Fabric(
            devices=n_devices,
            precision="fp32",
            mesh_axes=("data", "model"),
            mesh_shape=(2, n_devices // 2),
        )
    else:
        fabric = Fabric(devices=1, precision="fp32")
    run_2d_superstep_case(fabric, multi, out_path)


def run_2d_superstep_case(fabric, multi, out_path):
    """The shared 2-D case body: deterministic inputs, two K=4 windows, leaf
    dump. ``fabric`` may span multiple processes (the ISSUE-18 ``cpux8p2``
    parity cell constructs a 2-process ``(2, 4)`` mesh and calls this with the
    SAME case) — placement then goes through
    ``jax.make_array_from_process_local_data`` (each process contributes its
    data-row slice of the batch; params/carries are process-replicated) and
    the final fetch all-gathers through a replicating identity jit, so the
    npz leaves are global values regardless of topology."""
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from sheeprl_tpu.ops.superstep import make_superstep_fn, periodic_target_ema, pregathered

    K, B, D, H = 4, 8, 8, 8
    rng = np.random.default_rng(11)
    xs = jnp.asarray(rng.normal(size=(K, B, D)).astype(np.float32))
    ys = jnp.asarray(rng.normal(size=(K, B, H)).astype(np.float32))
    # leaf names match the partition-rule table: "kernel" shards its last
    # dim over the model axis, "bias" replicates — and the Adam moments pick
    # up the same specs through match_partition_rules on the aux carry
    model = {
        "kernel": jnp.asarray(rng.normal(size=(D, H)).astype(np.float32)),
        "bias": jnp.zeros((H,), jnp.float32),
    }
    target = jax.tree.map(jnp.zeros_like, model)
    tx = optax.adam(1e-2)
    opt = tx.init(model)

    def train_body(params, aux, batch, key):
        del key  # deterministic body (see superstep_equivalence_case)
        model, target = params
        (opt,) = aux
        x, y = batch

        def loss_fn(m):
            return jnp.mean(jnp.square(x @ m["kernel"] + m["bias"] - y))

        # GSPMD path: global-batch semantics, no explicit pmean — XLA
        # inserts the collectives the shardings imply
        loss, grads = jax.value_and_grad(loss_fn)(model)
        updates, opt = tx.update(grads, opt, model)
        model = optax.apply_updates(model, updates)
        return (model, target), (opt,), jnp.stack([loss])

    def pre_step(params, aux, counter):
        model, target = params
        target = periodic_target_ema(counter, model, target, 2, 0.25)
        return (model, target), aux

    params, aux = (model, target), (opt,)
    kwargs = {}
    if multi:
        carry_specs = (fabric.match_partition_rules(params), fabric.match_partition_rules(aux))
        kwargs = dict(
            mesh=fabric.mesh,
            model_axis=fabric.model_axis,
            carry_specs=carry_specs,
            ctx_spec=P(None, fabric.data_axis),
        )
    superstep = make_superstep_fn(train_body, pregathered, K, pre_step=pre_step, **kwargs)
    ctx = (xs, ys)
    key = jax.random.PRNGKey(0)
    if multi and jax.process_count() > 1:
        # multi-process placement: device_put cannot target devices owned by
        # another process, so each process contributes its local block via
        # make_array_from_process_local_data — the full copy for
        # process-replicated leaves (params/carries/key), its own data-row
        # slice of the batch axis for the ctx
        def global_put(tree, shardings):
            return jax.tree.map(
                lambda x, s: jax.make_array_from_process_local_data(s, np.asarray(x)),
                tree,
                shardings,
            )

        params = global_put(params, fabric.carry_shardings(params))
        aux = global_put(aux, fabric.carry_shardings(aux))
        key = global_put(key, fabric.replicated)
        mesh_devices = fabric.mesh.devices  # [data, model] grid
        my_rows = [
            r
            for r in range(mesh_devices.shape[0])
            if all(d.process_index == jax.process_index() for d in mesh_devices[r].flat)
        ]
        assert len(my_rows) == 1, f"expected one whole data row per process, got {my_rows}"
        rows_per_proc = B // mesh_devices.shape[0]
        lo = my_rows[0] * rows_per_proc
        ctx_sharding = fabric.sharding(None, fabric.data_axis)
        ctx = jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                ctx_sharding, np.asarray(x)[:, lo : lo + rows_per_proc]
            ),
            ctx,
        )
    elif multi:
        # every input enters window 1 committed exactly as the superstep
        # returns it, so window 2 must not key a second executable
        params = jax.device_put(params, fabric.carry_shardings(params))
        aux = jax.device_put(aux, fabric.carry_shardings(aux))
        ctx = jax.device_put(ctx, fabric.sharding(None, fabric.data_axis))
        key = fabric.replicate(key)
    all_metrics = []
    for window in range(2):
        params, aux, key, metrics = superstep(params, aux, np.int32(window * K), ctx, key)
        all_metrics.append(metrics)

    if multi:
        adam = aux[0][0]  # optax.adam = chain(scale_by_adam, scale)
        for name, leaf in (
            ("kernel", params[0]["kernel"]),
            ("target kernel", params[1]["kernel"]),
            ("adam mu", adam.mu["kernel"]),
            ("adam nu", adam.nu["kernel"]),
        ):
            assert "model" in repr(leaf.sharding), f"{name} not model-sharded: {leaf.sharding!r}"
        assert superstep._cache_size() == 1, (
            f"window 2 recompiled: {superstep._cache_size()} executables"
        )
    out = (params, aux, all_metrics)
    if multi and jax.process_count() > 1:
        # np.asarray cannot fetch shards living on another process's devices:
        # all-gather to fully-replicated first (a cross-process collective),
        # after which every process holds the global value of every leaf
        out = jax.jit(lambda t: t, out_shardings=NamedSharding(fabric.mesh, P()))(out)
    leaves = jax.tree.leaves(out)
    if jax.process_index() == 0:
        np.savez(out_path, **{f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)})


@pytest.mark.multichip
def test_2d_superstep_matches_single_device(multichip_run, tmp_path):
    """ISSUE-14 acceptance: two K=4 superstep windows on an 8-device
    (2 data x 4 model) virtual mesh produce the same params / Adam state /
    EMA target / metrics (fp32, CPU) as the single-device superstep — with
    the mesh child's in-process asserts proving the carries stayed
    model-sharded and window 2 hit the window-1 executable."""
    mesh_out = tmp_path / "mesh2d.npz"
    single_out = tmp_path / "single.npz"
    target = "tests.test_parallel.test_sharded_superstep:superstep_equivalence_case_2d"
    multichip_run(target, 8, "8", str(mesh_out))
    multichip_run(target, 1, "1", str(single_out))
    got, want = np.load(mesh_out), np.load(single_out)
    assert set(got.files) == set(want.files) and got.files
    for name in got.files:
        np.testing.assert_allclose(got[name], want[name], rtol=1e-5, atol=1e-6, err_msg=name)


@pytest.mark.multichip
def test_sharded_superstep_matches_single_device(multichip_run, tmp_path):
    """ISSUE acceptance: K fused steps on a 4-device virtual mesh produce
    the same params / opt state / EMA target (fp32, CPU) as the
    single-device superstep fed the concatenated batches."""
    mesh_out = tmp_path / "mesh4.npz"
    single_out = tmp_path / "mesh1.npz"
    target = "tests.test_parallel.test_sharded_superstep:superstep_equivalence_case"
    multichip_run(target, 4, "4", str(mesh_out))
    multichip_run(target, 1, "1", str(single_out))
    got, want = np.load(mesh_out), np.load(single_out)
    assert set(got.files) == set(want.files) and got.files
    for name in got.files:
        np.testing.assert_allclose(got[name], want[name], rtol=1e-5, atol=1e-6, err_msg=name)


# --------------------------------------------------------------------------
# sharded ring (in-process: the test session owns 8 virtual CPU devices)
# --------------------------------------------------------------------------
def _ring_step(rb, t, n_envs):
    rb.add(
        {
            "rgb": np.full((1, n_envs, 8, 8, 3), t % 256, np.uint8),
            # actions encode (env, t) so per-env ring rows are distinguishable
            "actions": np.stack(
                [np.asarray([e, t], np.float32) for e in range(n_envs)]
            )[None],
            "rewards": np.full((1, n_envs, 1), t, np.float32),
            "terminated": np.zeros((1, n_envs, 1), np.float32),
            "truncated": np.zeros((1, n_envs, 1), np.float32),
            "is_first": np.zeros((1, n_envs, 1), np.float32),
        }
    )


def test_sharded_ring_wraparound_parity_vs_host_sequential():
    """Each device's env-slot slice wraps exactly like a host
    SequentialReplayBuffer for the same env: add past capacity on a 4-shard
    ring and compare every env row (and cursor) against the host pair."""
    fabric = Fabric(devices=4, precision="fp32")
    cap, n_envs = 5, 8  # 2 env rows per shard
    ring = DeviceReplayBuffer(
        cap, n_envs=n_envs, obs_keys=("rgb",), seed=3, mesh=fabric.mesh, data_axis=fabric.data_axis
    )
    host = EnvIndependentReplayBuffer(
        cap, n_envs=n_envs, obs_keys=("rgb",), buffer_cls=SequentialReplayBuffer, seed=3
    )
    assert ring.sharded and ring.n_shards == 4
    for t in range(cap + 3):  # 3 steps past capacity -> every env row wrapped
        _ring_step(ring, t, n_envs)
        _ring_step(host, t, n_envs)
    assert all(ring.full)
    assert ring._pos.tolist() == [b._pos for b in host.buffer]

    arrs = ring.host_arrays()
    for env in range(n_envs):
        for key in ("rgb", "actions", "rewards"):
            np.testing.assert_array_equal(
                arrs[key][env], host.buffer[env][key][:, 0], err_msg=f"{key} env {env}"
            )

    # sampled windows stay contiguous and shard-local after the wrap: batch
    # block s draws only from shard s's env rows
    for batch in ring.sample_batches(batch_size=8, sequence_length=3, n_samples=2):
        rewards = np.asarray(batch["rewards"])[..., 0]  # [T, B] step counters
        assert np.all(np.diff(rewards, axis=0) == 1), rewards.T
        env_of = np.asarray(batch["actions"])[0, :, 0]  # [B] env ids
        shard_of = (env_of // (n_envs // 4)).astype(int)
        assert shard_of.tolist() == np.repeat(np.arange(4), 2).tolist()


def test_sharded_ring_placement_and_validation():
    """Satellite: the repr asserts where the ring landed, and the
    constructor rejects impossible placements up front."""
    fabric = Fabric(devices=4, precision="fp32")
    ring = DeviceReplayBuffer(
        4, n_envs=4, obs_keys=("rgb",), mesh=fabric.mesh, data_axis=fabric.data_axis
    )
    assert "placement=sharded(axis='data', shards=4, envs_per_shard=1)" in repr(ring)
    assert "placement=single" in repr(DeviceReplayBuffer(4, n_envs=4, obs_keys=("rgb",)))

    with pytest.raises(ValueError, match="divisible"):
        DeviceReplayBuffer(4, n_envs=3, obs_keys=("rgb",), mesh=fabric.mesh, data_axis=fabric.data_axis)
    import jax

    with pytest.raises(ValueError, match="not both"):
        DeviceReplayBuffer(
            4,
            n_envs=4,
            obs_keys=("rgb",),
            device=jax.devices()[0],
            mesh=fabric.mesh,
            data_axis=fabric.data_axis,
        )


def test_sharded_ring_pickle_drops_mesh_and_restores_single_device():
    """Meshes don't pickle: a checkpointed sharded ring comes back as a
    single-placement ring with identical contents (jitted consumers reshard
    lazily on the next mesh run)."""
    import pickle

    fabric = Fabric(devices=4, precision="fp32")
    ring = DeviceReplayBuffer(
        4, n_envs=4, obs_keys=("rgb",), mesh=fabric.mesh, data_axis=fabric.data_axis
    )
    for t in range(3):
        _ring_step(ring, t, 4)
    clone = pickle.loads(pickle.dumps(ring)).restore_to_device()
    assert not clone.sharded and "placement=single" in repr(clone)
    np.testing.assert_array_equal(
        clone.host_arrays()["rewards"], ring.host_arrays()["rewards"]
    )
