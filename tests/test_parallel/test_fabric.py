"""Fabric/mesh runtime specs."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel import Fabric, Precision


def test_fabric_defaults_all_devices():
    f = Fabric()
    assert f.world_size == len(jax.devices())
    assert dict(f.mesh.shape) == {"data": len(jax.devices())}


def test_fabric_device_subset():
    f = Fabric(devices=4)
    assert f.world_size == 4


def test_fabric_too_many_devices():
    with pytest.raises(ValueError):
        Fabric(devices=10**6)


def test_fabric_2d_mesh():
    f = Fabric(devices=8, mesh_axes=("data", "model"), mesh_shape=(4, 2))
    assert dict(f.mesh.shape) == {"data": 4, "model": 2}


def test_fabric_mesh_infer_axis():
    f = Fabric(devices=8, mesh_axes=("data", "model"), mesh_shape=(-1, 2))
    assert dict(f.mesh.shape) == {"data": 4, "model": 2}


def test_fabric_bad_mesh_shape():
    with pytest.raises(ValueError):
        Fabric(devices=8, mesh_axes=("data", "model"), mesh_shape=(3, 2))


def test_shard_batch_and_replicate():
    f = Fabric(devices=8)
    batch = {"x": np.arange(16, dtype=np.float32).reshape(16, 1)}
    sharded = f.shard_batch(batch)
    assert sharded["x"].sharding == f.batch_sharding
    params = f.replicate({"w": np.ones((3,), np.float32)})
    assert params["w"].sharding == f.replicated


def test_local_batch_size():
    f = Fabric(devices=8)
    assert f.local_batch_size(64) == 8
    with pytest.raises(ValueError):
        f.local_batch_size(63)


def test_precision_aliases():
    assert Precision("32-true").name == "fp32"
    assert Precision("bf16").name == "bf16-mixed"
    with pytest.raises(ValueError):
        Precision("fp16")


def test_precision_dtypes():
    p = Precision("bf16-mixed")
    assert p.param_dtype == jnp.float32
    assert p.compute_dtype == jnp.bfloat16
    t = Precision("bf16-true")
    assert t.param_dtype == jnp.bfloat16


def test_precision_cast_to_compute():
    p = Precision("bf16-mixed")
    tree = {"a": jnp.ones((2,), jnp.float32), "b": jnp.ones((2,), jnp.int32)}
    out = p.cast_to_compute(tree)
    assert out["a"].dtype == jnp.bfloat16
    assert out["b"].dtype == jnp.int32  # non-floating leaves untouched


def test_save_load_roundtrip(tmp_path):
    f = Fabric(devices=1)
    state = {"params": {"w": jnp.arange(4.0)}, "step": 7, "ratio": {"_prev": None}}
    path = str(tmp_path / "ckpt" / "state.ckpt")
    f.save(path, state)
    loaded = f.load(path)
    assert loaded["step"] == 7
    assert np.array_equal(loaded["params"]["w"], np.arange(4.0))
    assert loaded["ratio"]["_prev"] is None


def test_fabric_call_dispatches_to_callbacks():
    calls = []

    class CB:
        def on_checkpoint_coupled(self, fabric, **kw):
            calls.append(kw)

    f = Fabric(devices=1, callbacks=[CB()])
    f.call("on_checkpoint_coupled", ckpt_path="x", state={})
    assert calls == [{"ckpt_path": "x", "state": {}}]


def test_grad_pmean_matches_single_device():
    """DP gradient on an 8-way mesh == single-device gradient on full batch."""
    from functools import partial

    from jax.sharding import PartitionSpec as P

    from sheeprl_tpu.parallel.shard_map import shard_map

    f = Fabric(devices=8)
    w = jnp.asarray([2.0, -1.0])
    x = np.random.default_rng(0).normal(size=(16, 2)).astype(np.float32)

    def loss(w, x):
        return jnp.mean(jnp.square(x @ w))

    full_grad = jax.grad(loss)(w, jnp.asarray(x))

    @partial(
        shard_map,
        mesh=f.mesh,
        in_specs=(P(), P("data")),
        out_specs=P(),
    )
    def dp_grad(w, x):
        return jax.lax.pmean(jax.grad(loss)(w, x), "data")

    np.testing.assert_allclose(jax.jit(dp_grad)(w, x), full_grad, rtol=1e-5)


def test_fabric_compilation_cache_dir(tmp_path):
    """fabric.compilation_cache_dir points JAX's persistent compile cache at
    the given directory, creating it; the default (None) leaves the global
    config untouched."""
    import os

    saved = jax.config.jax_compilation_cache_dir
    try:
        cache = str(tmp_path / "xla-cache")
        f = Fabric(devices=1, compilation_cache_dir=cache)
        assert f.compilation_cache_dir == cache
        assert os.path.isdir(cache)
        assert jax.config.jax_compilation_cache_dir == cache
        # None is a no-op: the previously configured dir stays in force
        assert Fabric(devices=1).compilation_cache_dir is None
        assert jax.config.jax_compilation_cache_dir == cache
    finally:
        jax.config.update("jax_compilation_cache_dir", saved)
