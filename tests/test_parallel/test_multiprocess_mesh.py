"""ISSUE 18: the ``cpux8p2`` virtual two-process mesh training-parity cell.

Two real ``jax.distributed`` CPU processes (4 virtual devices each) form one
global 8-device ``(data=2, model=4)`` mesh — each process owns exactly one
data row — and run the SAME deterministic two-window fused-superstep case as
the single-process 2-D equivalence test (`run_2d_superstep_case`). Process 0
dumps the all-gathered leaves; the parent compares them against a
single-device run of the identical case, proving the fused superstep's
numerics survive the jump from a single-process mesh to a multi-process one
(gloo CPU collectives underneath, DCN on real hardware).
"""

import os

import numpy as np
import pytest

from tests.conftest import run_multi_process

pytestmark = pytest.mark.multichip


def p2_superstep_case(out_path):
    """Worker entry (one of two ``jax.distributed`` processes): build the
    production Fabric with an explicit coordinator (the TEST_* contract from
    ``run_multi_process``) so distributed bring-up — including the gloo CPU
    collectives selection — goes through ``Fabric._maybe_init_distributed``
    exactly as a real multi-host launch would."""
    import jax

    from sheeprl_tpu.parallel.fabric import Fabric
    from tests.test_parallel.test_sharded_superstep import run_2d_superstep_case

    fabric = Fabric(
        devices=8,
        precision="fp32",
        mesh_axes=("data", "model"),
        mesh_shape=(2, 4),
        distributed_coordinator=os.environ["TEST_COORD"],
        num_processes=int(os.environ["TEST_NPROC"]),
        process_id=int(os.environ["TEST_PID"]),
    )
    assert fabric.num_processes == 2, fabric.num_processes
    assert fabric.world_size == 8 and fabric.local_device_count == 4
    # the (2, 4) mesh must put each process's 4 devices on one data row —
    # the layout the batch-slice placement in the shared case relies on
    for row in range(2):
        owners = {d.process_index for d in fabric.mesh.devices[row].flat}
        assert len(owners) == 1, f"data row {row} spans processes {owners}"
    run_2d_superstep_case(fabric, True, out_path)
    print("P2_CASE_OK", jax.process_index())


WORKER = """
import sys
from tests.test_parallel.test_multiprocess_mesh import p2_superstep_case
p2_superstep_case(sys.argv[1])
"""


def test_p2_mesh_superstep_matches_single_device(multichip_run, tmp_path):
    """ISSUE-18 acceptance (`cpux8p2` parity): two K=4 superstep windows on a
    2-process x 4-device `(data, model)` mesh produce the same params / Adam
    state / EMA target / metrics as the single-device superstep — the
    in-child asserts additionally prove the carries stayed model-sharded and
    window 2 reused window 1's executable across the process boundary."""
    p2_out = tmp_path / "p2.npz"
    single_out = tmp_path / "single.npz"
    outs = run_multi_process(WORKER, argv=(str(p2_out),), nproc=2, device_count=4)
    assert all("P2_CASE_OK" in o for o in outs)
    multichip_run(
        "tests.test_parallel.test_sharded_superstep:superstep_equivalence_case_2d",
        1,
        "1",
        str(single_out),
    )
    got, want = np.load(p2_out), np.load(single_out)
    assert set(got.files) == set(want.files) and got.files
    for name in got.files:
        np.testing.assert_allclose(got[name], want[name], rtol=1e-5, atol=1e-6, err_msg=name)
