"""Driver-entry hardening tests.

Round-4 failure mode: ``MULTICHIP_r04.json`` recorded rc=124 because
``dryrun_multichip`` consulted ``jax.devices()`` in the driver's process —
initializing the default (axon TPU) backend, which blocks forever when the
tunnel to the remote-attached chip is down. The contract under test: the
parent process NEVER imports jax; the whole dry run happens in a fresh
``JAX_PLATFORMS=cpu`` child, so its outcome is independent of accelerator
health.
"""

import os
import pytest
import subprocess
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_PARENT_BLOCKER = r"""
import sys

class _NoJax:
    # Simulate a dead accelerator backend: ANY jax import in this process
    # fails loudly (a dead tunnel would instead hang backend init forever;
    # failing fast keeps the test deterministic while proving the same
    # thing: the parent code path never needs jax).
    def find_spec(self, name, path=None, target=None):
        if name == "jax" or name.startswith("jax."):
            raise ImportError("parent process must not import jax (simulated dead backend)")
        return None

sys.meta_path.insert(0, _NoJax())

import importlib.util

spec = importlib.util.spec_from_file_location("__graft_entry__", sys.argv[1])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.dryrun_multichip(2)
print("PARENT-NEVER-IMPORTED-JAX")
"""


@pytest.mark.slow
def test_dryrun_parent_never_imports_jax():
    env = dict(os.environ)
    env.pop("_SHEEPRL_TPU_DRYRUN_CHILD", None)
    # core DP topology only: the decoupled/elastic extras have their own
    # tests (test_sac_decoupled, test_elastic_resume) and would add ~6 min
    env["SHEEPRL_TPU_DRYRUN_CORE_ONLY"] = "1"
    proc = subprocess.run(
        [sys.executable, "-c", _PARENT_BLOCKER, os.path.join(REPO_ROOT, "__graft_entry__.py")],
        env=env,
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=480,
    )
    assert proc.returncode == 0, f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-2000:]}"
    assert "PARENT-NEVER-IMPORTED-JAX" in proc.stdout
    assert "fused train step OK" in proc.stdout
    # the K=2 fused superstep window over the sharded ring compiled and ran
    assert "fused superstep OK" in proc.stdout
    # the fused on-policy PPO superstep (scanned JaxCartPole rollout + GAE +
    # fused update, envs sharded over the mesh) compiled and ran too
    assert "fused on-policy PPO superstep OK" in proc.stdout
