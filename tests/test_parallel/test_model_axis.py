"""Model-axis param sharding (SURVEY §2.7 stretch scope — the reference has
no FSDP/TP at all; here a 2-D ``(data, model)`` mesh shards the large
kernels over ``model`` via ``fabric.param_spec`` and GSPMD inserts the
collectives).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from sheeprl_tpu.parallel.fabric import Fabric


@pytest.fixture(scope="module")
def fabric2x4():
    return Fabric(devices=8, precision="fp32", mesh_axes=("data", "model"), mesh_shape=(2, 4))


def test_topology_properties(fabric2x4):
    f = fabric2x4
    assert f.model_axis == "model"
    assert f.model_parallel_size == 4
    assert f.data_parallel_size == 2
    assert f.local_data_parallel_size == 2
    assert f.world_size == 8


def test_pure_dp_mesh_has_no_model_axis():
    f = Fabric(devices=8, precision="fp32")
    assert f.model_axis is None
    assert f.data_parallel_size == 8
    assert f.local_data_parallel_size == 8
    # shard_params degrades to plain replication
    leaf = jnp.zeros((8, 16))
    out = f.shard_params({"w": leaf})["w"]
    assert out.sharding.spec == P()


def test_param_spec_rule(fabric2x4):
    f = fabric2x4
    # last dim divisible -> column parallel
    assert f.param_spec(jnp.zeros((7, 16))) == P(None, "model")
    # last dim not divisible, second-to-last divisible -> row parallel
    assert f.param_spec(jnp.zeros((16, 7))) == P("model", None)
    # neither divisible -> replicated
    assert f.param_spec(jnp.zeros((7, 7))) == P()
    # 1-D (biases) and scalars -> replicated
    assert f.param_spec(jnp.zeros((16,))) == P()
    assert f.param_spec(jnp.zeros(())) == P()
    # conv kernels shard the output-channel (last) dim
    assert f.param_spec(jnp.zeros((4, 4, 3, 32))) == P(None, None, None, "model")


def test_shard_params_places_distributed(fabric2x4):
    f = fabric2x4
    tree = {"kernel": np.ones((8, 32), np.float32), "bias": np.zeros((32,), np.float32)}
    placed = f.shard_params(tree)
    k = placed["kernel"]
    assert "model" in k.sharding.spec
    # genuinely distributed: each addressable shard holds 1/4 of the columns
    assert k.addressable_shards[0].data.shape == (8, 8)
    assert placed["bias"].sharding.spec == P()
    # round-trips intact
    assert np.array_equal(np.asarray(k), tree["kernel"])


def test_sharded_matmul_and_update_preserve_sharding(fabric2x4):
    """An optax-style elementwise update on model-sharded params keeps the
    sharding (no silent gather-back to replicated)."""
    f = fabric2x4
    w = f.shard_params({"w": np.random.default_rng(0).normal(size=(16, 32)).astype(np.float32)})["w"]
    x = jax.device_put(np.ones((4, 16), np.float32), f.sharding("data", None))

    @jax.jit
    def step(w, x):
        y = x @ w
        g = jax.grad(lambda w: jnp.sum((x @ w) ** 2))(w)
        return w - 0.1 * g, y

    new_w, y = step(w, x)
    assert "model" in new_w.sharding.spec
    np.testing.assert_allclose(
        np.asarray(y), np.ones((4, 16), np.float32) @ np.asarray(w), rtol=1e-5
    )
