"""Host-player placement specs (learner-on-chip / actor-on-host split).

No reference counterpart — the torch player always shares the trainer's
device; this framework adds ``algo.player_device`` for remote-attached chips
(parallel/fabric.py ``resolve_player_device`` / ``HostPlayerParams``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.parallel.fabric import (
    HostPlayerParams,
    _ParamStreamer,
    dispatch_roundtrip_seconds,
    put_tree,
    resolve_player_device,
    resolve_train_device,
)


def test_param_streamer_roundtrip_exact():
    """Mixed-dtype tree survives the flat byte-vector transfer bit-exact."""
    dev = jax.devices("cpu")[0]
    tree = {
        "a": jnp.ones((3, 5), jnp.float32) * 1.5,
        "b": {"c": jnp.arange(7, dtype=jnp.int32), "d": jnp.full((2, 2, 2), 0.25, jnp.bfloat16)},
        "e": jnp.float32(3.25),
    }
    s = _ParamStreamer(tree, dev)
    out = s(tree)
    for l1, l2 in zip(jax.tree.leaves(tree), jax.tree.leaves(out)):
        assert l1.shape == l2.shape and l1.dtype == l2.dtype
        assert np.array_equal(np.asarray(l1, np.float32), np.asarray(l2, np.float32))
    assert s.matches(tree)
    assert not s.matches({"a": tree["a"]})


def test_resolve_accelerator_is_none():
    assert resolve_player_device("accelerator") is None
    assert resolve_player_device(None) is None


def test_resolve_cpu_on_cpu_backend_is_none():
    # the test session runs on the CPU backend: "cpu" means "already there"
    assert resolve_player_device("cpu") is None


def test_resolve_auto_on_cpu_backend_is_none():
    assert resolve_player_device("auto") is None
    # conv policies too: auto depends only on the measured link latency
    # (a host pixel forward is ~ms, far under a remote chip's round trip)
    assert resolve_player_device("auto") is None


def test_resolve_train_device_rules():
    tiny = {"w": np.zeros((8, 8), np.float32)}
    # default-backend spellings are always None
    assert resolve_train_device("accelerator", tiny, 1) is None
    assert resolve_train_device(None, tiny, 1) is None
    # auto on a cpu default backend: already the host, nothing to pin
    assert resolve_train_device("auto", tiny, 1) is None
    # explicit cpu pin commits to the host backend device
    dev = resolve_train_device("cpu", tiny, 1)
    assert dev is not None and dev.platform == "cpu"
    # multi-device: mesh training only — explicit cpu is a config error,
    # auto silently stays on the mesh
    with pytest.raises(ValueError, match="single-device"):
        resolve_train_device("cpu", tiny, 2)
    assert resolve_train_device("auto", tiny, 8) is None


def test_param_streamer_single_byte_dtypes_roundtrip():
    """int8/bool/uint8 leaves survive packing next to wider leaves (the
    round-2 advisor finding: concatenating raw int8 with uint8 segments
    type-promoted and broke the byte layout)."""
    dev = jax.devices("cpu")[0]
    tree = {
        "i8": jnp.array([-3, 0, 127, -128], jnp.int8),
        "u8": jnp.array([0, 255, 7], jnp.uint8),
        "b": jnp.array([True, False, True]),
        "f": jnp.ones((4,), jnp.float32) * 2.5,
    }
    s = _ParamStreamer(tree, dev)
    out = s(tree)
    for k in tree:
        assert out[k].dtype == tree[k].dtype, k
        assert np.array_equal(np.asarray(out[k]), np.asarray(tree[k])), k


def test_param_streamer_begin_finish_deferred():
    dev = jax.devices("cpu")[0]
    tree = {"w": jnp.arange(12, dtype=jnp.float32).reshape(3, 4), "b": jnp.ones(3, jnp.bfloat16)}
    s = _ParamStreamer(tree, dev)
    handle = s.begin(tree)
    out = s.finish(handle)
    for k in tree:
        assert np.array_equal(np.asarray(out[k], np.float32), np.asarray(tree[k], np.float32))


def test_stream_pipe_applies_newest_after_age_gate(monkeypatch):
    from sheeprl_tpu.parallel import fabric as fabric_mod
    from sheeprl_tpu.parallel.fabric import _StreamPipe

    dev = jax.devices("cpu")[0]
    tree1 = {"w": jnp.zeros((4,), jnp.float32)}
    tree2 = {"w": jnp.ones((4,), jnp.float32)}
    s = _ParamStreamer(tree1, dev)
    pipe = _StreamPipe(s)
    monkeypatch.setitem(fabric_mod._rtt_cache, "rtt", 0.0)  # age gate -> 20 ms floor

    import time

    pipe.offer(tree1)
    time.sleep(0.05)
    assert pipe.poll() is not None  # tree1 lands once past the age gate
    pipe.offer(tree2)
    time.sleep(0.05)
    out = pipe.poll()
    assert out is not None and np.asarray(out["w"]).sum() == 4.0


def test_dispatch_fence_bounds_inflight_markers():
    from sheeprl_tpu.parallel.fabric import DispatchFence

    fence = DispatchFence(depth=2)
    for i in range(6):
        fence.push(jnp.full((3, 3), i, jnp.float32))
        assert len(fence._pending) <= 2
    fence.drain()
    assert len(fence._pending) == 0


def test_resolve_unknown_spec_raises():
    with pytest.raises(ValueError):
        resolve_player_device("gpu0")


def test_dispatch_roundtrip_is_fast_locally():
    # virtual CPU devices are in-process: far below the 5 ms remote threshold
    assert dispatch_roundtrip_seconds() < 0.005


def test_put_tree_identity_without_device():
    tree = {"a": np.ones((2,), np.float32)}
    assert put_tree(tree, None) is tree


def test_put_tree_places_on_device():
    dev = jax.devices("cpu")[0]
    out = put_tree({"a": np.ones((2,), np.float32)}, dev)
    assert out["a"].devices() == {dev}


class _Player(HostPlayerParams):
    _placed_attrs = ("params",)

    def __init__(self, params, device=None):
        self.device = device
        self.params = params


def test_mixin_passthrough_without_device():
    p = _Player({"w": np.zeros((2,), np.float32)})
    assert isinstance(p.params["w"], np.ndarray)


def test_mixin_places_assignments():
    dev = jax.devices("cpu")[0]
    p = _Player({"w": np.zeros((2,), np.float32)}, device=dev)
    assert p.params["w"].devices() == {dev}
    # every later assignment is placed too — the loops' `player.params = ...`
    # sync sites rely on this
    p.params = {"w": np.ones((2,), np.float32)}
    assert p.params["w"].devices() == {dev}
    assert float(p.params["w"][0]) == 1.0


def test_mixin_ignores_other_attrs():
    dev = jax.devices("cpu")[0]
    p = _Player({"w": np.zeros((2,), np.float32)}, device=dev)
    p.note = np.ones((1,), np.float32)
    assert isinstance(p.note, np.ndarray)


def test_player_on_explicit_device_end_to_end():
    """A PPOPlayer pinned to an explicit device samples actions correctly and
    keeps its params there after an update_params refresh."""
    import gymnasium as gym

    from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent
    from sheeprl_tpu.parallel import Fabric

    cfg = {
        "algo": {
            "cnn_keys": {"encoder": []},
            "mlp_keys": {"encoder": ["state"]},
            "encoder": {"cnn_features_dim": 64, "mlp_features_dim": 16, "dense_units": 8, "mlp_layers": 1},
            "actor": {"dense_units": 8, "mlp_layers": 1},
            "critic": {"dense_units": 8, "mlp_layers": 1},
            "dense_act": "tanh",
            "layer_norm": False,
        },
        "seed": 0,
    }
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-1, 1, (3,), np.float32)})
    fabric = Fabric(devices=1)
    agent, params = build_agent(fabric, (2,), False, cfg, obs_space)
    dev = jax.devices("cpu")[0]
    player = PPOPlayer(agent, params, device=dev)

    obs = {"state": np.zeros((4, 3), np.float32)}
    actions, logprobs, values = player.get_actions(obs, jax.random.PRNGKey(0))
    assert np.asarray(actions).shape == (4, 2)
    # refresh params through the sync path used by the train loop
    player.update_params(params)
    leaf = jax.tree.leaves(player.params)[0]
    assert leaf.devices() == {dev}


def test_age_threshold_scales_with_pack_size_on_remote_links(monkeypatch):
    """The stream gate waits for the landing estimate (bytes/bandwidth + RTT)
    on remote links, and keeps the cheap RTT-only gate locally — polling a
    large pack early turns the 'free' finish into a blocking partial-transfer
    wait (the round-4 SAC-AE 1.5 s/update regression)."""
    import jax.numpy as jnp

    from sheeprl_tpu.parallel import fabric as fabric_mod
    from sheeprl_tpu.parallel.fabric import _ParamStreamer, _StreamPipe

    monkeypatch.delenv("SHEEPRL_TPU_LINK_BYTES_PER_S", raising=False)
    dev = jax.devices()[0]
    big = {"w": jnp.zeros((1_000_000,), jnp.float32)}  # 4 MB pack
    pipe = _StreamPipe(_ParamStreamer(big, dev))

    # local link (sub-threshold RTT): old cheap gate, bytes ignored
    monkeypatch.setitem(fabric_mod._rtt_cache, "rtt", 0.0001)
    assert pipe._age_threshold() == pytest.approx(0.02)

    # remote link: the 4 MB pack cannot land before bytes/bandwidth + RTT
    monkeypatch.setitem(fabric_mod._rtt_cache, "rtt", 0.1)
    expected = 4_000_000 / _StreamPipe._link_bytes_per_s() + 0.1
    assert pipe._age_threshold() == pytest.approx(expected)

    # a tiny pack on a remote link keeps the RTT-dominated gate
    small = _StreamPipe(_ParamStreamer({"w": jnp.zeros((4,), jnp.float32)}, dev))
    assert small._age_threshold() == pytest.approx(0.15)


def test_link_bytes_per_s_env_validation(monkeypatch):
    from sheeprl_tpu.parallel.fabric import _StreamPipe

    monkeypatch.setenv("SHEEPRL_TPU_LINK_BYTES_PER_S", "0")
    assert _StreamPipe._link_bytes_per_s() == 1e3  # floored, no ZeroDivision
    monkeypatch.setenv("SHEEPRL_TPU_LINK_BYTES_PER_S", "14MB")
    assert _StreamPipe._link_bytes_per_s() == 10e6  # malformed -> default
    monkeypatch.setenv("SHEEPRL_TPU_LINK_BYTES_PER_S", "5e7")
    assert _StreamPipe._link_bytes_per_s() == 5e7
    monkeypatch.setenv("SHEEPRL_TPU_LINK_BYTES_PER_S", "nan")
    assert _StreamPipe._link_bytes_per_s() == 1e3  # nan must not disable the gate
