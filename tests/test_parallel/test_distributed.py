"""Multi-process collectives + mesh assembly (reference: the 2-process gloo
tests of tests/test_algos/test_algos.py:16-51).

Spawns two real ``jax.distributed`` CPU processes and exercises the
host-object plane (broadcast / all-gather / gather-to-zero / scalar
allreduce), the log-dir broadcast, and ``Fabric.make_global`` assembling
per-process blocks into one mesh-global array."""

import os

from tests.conftest import run_two_process

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=2,
    process_id=int(os.environ["TEST_PID"]),
)
import numpy as np

from sheeprl_tpu.parallel.collectives import (
    all_gather_object,
    broadcast_object,
    gather_object,
    host_allreduce_sum,
)
from sheeprl_tpu.parallel.fabric import Fabric
from sheeprl_tpu.utils.logger import get_log_dir

pid = jax.process_index()

# object plane
got = broadcast_object({"cfg": [1, 2, 3]} if pid == 0 else None, src=0)
assert got == {"cfg": [1, 2, 3]}, got
gathered = all_gather_object(("rank", pid))
assert gathered == [("rank", 0), ("rank", 1)], gathered
to_zero = gather_object(np.full(4, pid), dst=0)
if pid == 0:
    assert [int(a[0]) for a in to_zero] == [0, 1]
else:
    assert to_zero is None
assert host_allreduce_sum(pid + 1.0) == 3.0

# log-dir broadcast: both processes must agree on process 0's versioned dir
cfg = {"root_dir": "algo/env", "run_name": "run", "log_base_dir": os.environ["TEST_TMP"]}
log_dir = get_log_dir(cfg)
assert log_dir.endswith("version_0"), log_dir

# make_global: per-process [2, 3] blocks -> one [4, 3] mesh-global array
fabric = Fabric(precision="fp32")
assert fabric.num_processes == 2 and fabric.world_size == 4
local = np.full((2, 3), pid, np.float32)
global_arr = fabric.make_global(local, (fabric.data_axis,))
assert global_arr.shape == (4, 3)
import jax.numpy as jnp

total = float(jnp.sum(global_arr))  # 0*6 + 1*6
assert total == 6.0, total
print(f"proc {pid}: distributed plane OK")
"""


def test_two_process_collectives_and_make_global(tmp_path):
    outs = run_two_process(
        WORKER, cwd=str(tmp_path), extra_env={"TEST_TMP": str(tmp_path)}, timeout=300
    )
    for out in outs:
        assert "distributed plane OK" in out
