"""The bench harness's workload configs must always compose — config-tree
drift (renamed keys, removed groups) would otherwise only surface in the
driver's end-of-round bench run, where it costs the round its numbers."""

import importlib.util
import sys
from pathlib import Path

_REPO_ROOT = Path(__file__).parents[2]


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench_module", _REPO_ROOT / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench_module", mod)
    spec.loader.exec_module(mod)
    return mod


def test_bench_dv3_overrides_compose():
    from sheeprl_tpu.config.compose import compose

    bench = _load_bench()
    cfg = compose("config", bench._dv3_args(bench.DV3_STEPS))
    assert cfg.algo.name == "dreamer_v3"
    assert cfg.env.sync_env is True
    assert cfg.algo.total_steps == bench.DV3_STEPS


def test_bench_ppo_overrides_compose():
    from sheeprl_tpu.config.compose import compose

    bench = _load_bench()
    cfg = compose("config", bench._ppo_args(bench.PPO_STEPS))
    assert cfg.algo.name == "ppo"
    assert cfg.env.num_envs == 64 and cfg.env.sync_env is True


def test_mfu_probe_sizes_compose():
    from benchmarks.mfu_probe import BASE_OVERRIDES, SIZES
    from sheeprl_tpu.config.compose import compose

    for size, overrides in SIZES.items():
        cfg = compose("config", [*BASE_OVERRIDES, *overrides])
        assert cfg.algo.name == "dreamer_v3", size
