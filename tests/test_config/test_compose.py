import os

import pytest

from sheeprl_tpu.config import (
    ConfigCompositionError,
    MissingMandatoryValue,
    compose,
    instantiate,
)
from sheeprl_tpu.utils.utils import dotdict


def _write(root, rel, text):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


@pytest.fixture()
def tree(tmp_path):
    root = str(tmp_path / "configs")
    _write(
        root,
        "config.yaml",
        """# @package _global_
defaults:
  - _self_
  - algo: base
  - env: base
  - exp: ???
seed: 42
name: ${algo.name}_${env.id}
""",
    )
    _write(root, "algo/base.yaml", "name: base_algo\nlr: 1e-3\nlayers: [64, 64]\n")
    _write(root, "algo/other.yaml", "defaults:\n  - base\n  - _self_\nname: other\nlr: 3e-4\n")
    _write(root, "env/base.yaml", "id: CartPole-v1\nnum_envs: 4\n")
    _write(
        root,
        "exp/demo.yaml",
        """# @package _global_
defaults:
  - override /algo: other
seed: 7
extra: ${algo.lr}
""",
    )
    _write(
        root,
        "exp/with_pkg.yaml",
        """# @package _global_
defaults:
  - /opt@algo.optimizer: adam
""",
    )
    _write(root, "opt/adam.yaml", "kind: adam\nlr: ${algo.lr}\n")
    return [root]


def test_defaults_and_groups(tree):
    cfg = compose("config", ["exp=demo"], search_path=tree)
    assert isinstance(cfg, dotdict)
    assert cfg.algo.name == "other"
    assert cfg.algo.lr == 3e-4
    assert cfg.algo.layers == [64, 64]  # inherited from algo/base through sibling include
    assert cfg.seed == 7  # exp wins over root (_self_ first)
    assert cfg.env.id == "CartPole-v1"


def test_missing_mandatory_group(tree):
    with pytest.raises(MissingMandatoryValue):
        compose("config", [], search_path=tree)


def test_interpolation(tree):
    cfg = compose("config", ["exp=demo"], search_path=tree)
    assert cfg.name == "other_CartPole-v1"
    assert cfg.extra == 3e-4


def test_value_overrides(tree):
    cfg = compose("config", ["exp=demo", "algo.lr=0.5", "env.num_envs=16", "+env.new_key=hi", "seed=3"], search_path=tree)
    assert cfg.algo.lr == 0.5
    assert cfg.env.num_envs == 16
    assert cfg.env.new_key == "hi"
    assert cfg.seed == 3


def test_group_reselect_from_cli(tree):
    cfg = compose("config", ["exp=demo", "algo=base"], search_path=tree)
    assert cfg.algo.name == "base_algo"


def test_deletion_and_bad_override(tree):
    cfg = compose("config", ["exp=demo", "~env.num_envs"], search_path=tree)
    assert "num_envs" not in cfg.env
    with pytest.raises(ConfigCompositionError):
        compose("config", ["exp=demo", "~does.not.exist"], search_path=tree)


def test_typoed_override_errors(tree):
    with pytest.raises(ConfigCompositionError, match="could not override"):
        compose("config", ["exp=demo", "envv=gym"], search_path=tree)
    with pytest.raises(ConfigCompositionError, match="could not override"):
        compose("config", ["exp=demo", "algo.lrr=0.1"], search_path=tree)


def test_delete_through_scalar_errors(tree):
    with pytest.raises(ConfigCompositionError):
        compose("config", ["exp=demo", "~seed.x"], search_path=tree)


def test_env_resolver(tree, tmp_path, monkeypatch):
    root = str(tmp_path / "c2")
    _write(root, "config.yaml", "a: ${env:SHEEPRL_TPU_TEST_VAR}\nb: ${env:SHEEPRL_TPU_TEST_MISSING,fallback}\n")
    monkeypatch.setenv("SHEEPRL_TPU_TEST_VAR", "hello")
    cfg = compose("config", [], search_path=[root])
    assert cfg.a == "hello"
    assert cfg.b == "fallback"
    monkeypatch.delenv("SHEEPRL_TPU_TEST_VAR")
    with pytest.raises(ConfigCompositionError, match="not set"):
        compose("config", [], search_path=[root])


def test_missing_inside_list(tree, tmp_path):
    root = str(tmp_path / "c3")
    _write(root, "config.yaml", "items:\n  - ???\n")
    with pytest.raises(ConfigCompositionError):
        compose("config", [], search_path=[root])


def test_package_directive(tree):
    cfg = compose("config", ["exp=with_pkg"], search_path=tree)
    assert cfg.algo.optimizer.kind == "adam"
    assert cfg.algo.optimizer.lr == 1e-3


def test_unknown_group_option_lists_alternatives(tree):
    with pytest.raises(ConfigCompositionError, match="demo"):
        compose("config", ["exp=nope"], search_path=tree)


def test_hydra_style_deletion_with_value(tree):
    cfg = compose("config", ["exp=demo", "~env.num_envs=4"], search_path=tree)
    assert "num_envs" not in cfg.env


def test_addition_through_scalar_errors(tree):
    with pytest.raises(ConfigCompositionError, match="non-dict"):
        compose("config", ["exp=demo", "+env.id.foo=bar"], search_path=tree)


def test_override_defaults_replaces_selection(tmp_path):
    root = str(tmp_path / "c4")
    _write(root, "config.yaml", "defaults:\n  - opt: sgd\n  - exp: ???\n")
    _write(root, "opt/sgd.yaml", "kind: sgd\nmomentum: 0.9\n")
    _write(root, "opt/adam.yaml", "kind: adam\nbetas: [0.9, 0.999]\n")
    _write(root, "exp/use_adam.yaml", "# @package _global_\ndefaults:\n  - override /opt: adam\n")
    cfg = compose("config", ["exp=use_adam"], search_path=[root])
    assert cfg.opt.kind == "adam"
    assert "momentum" not in cfg.opt  # stale key from sgd must not leak
    assert cfg.opt.betas == [0.9, 0.999]


def test_instantiate_recurses_into_lists_and_nested_dicts():
    built = instantiate(
        {
            "_target_": "collections.OrderedDict",
            "items_": [{"_target_": "collections.OrderedDict", "x": 1}],
            "nested": {"inner": {"_target_": "collections.OrderedDict", "y": 2}},
        }
    )
    from collections import OrderedDict

    assert isinstance(built["items_"][0], OrderedDict)
    assert isinstance(built["nested"]["inner"], OrderedDict)


def test_instantiate_builtin_fabric_callbacks_list():
    cfg = compose(
        "config",
        ["exp=default", "algo.name=x", "algo.total_steps=1", "algo.per_rank_batch_size=1", "env.id=e", "env.wrapper=w", "buffer.size=8"],
    )
    from sheeprl_tpu.config.compose import _instantiate_tree

    callbacks = _instantiate_tree(cfg.fabric.callbacks)
    from sheeprl_tpu.utils.callback import CheckpointCallback

    assert isinstance(callbacks[0], CheckpointCallback)


def test_instantiate():
    obj = instantiate({"_target_": "collections.OrderedDict", "a": 1})
    assert dict(obj) == {"a": 1}
    part = instantiate({"_target_": "collections.OrderedDict", "_partial_": True, "a": 1})
    assert dict(part(b=2)) == {"a": 1, "b": 2}
    nested = instantiate({"_target_": "collections.OrderedDict", "inner": {"_target_": "collections.OrderedDict", "x": 2}})
    assert dict(nested["inner"]) == {"x": 2}


def test_builtin_tree_composes():
    cfg = compose("config", ["exp=default", "algo.name=x", "algo.total_steps=1", "algo.per_rank_batch_size=1", "env.id=e", "env.wrapper=w", "buffer.size=8"])
    assert cfg.exp_name == "x_e"
    assert cfg.logger.name == "tensorboard"
    assert cfg.fabric.mesh_axes == ["data"]


def test_compose_group_subtree():
    """compose_group returns just the group's composed subtree (used by the
    eval/registration CLIs for `group=option` overrides on checkpoint
    configs)."""
    from sheeprl_tpu.config.compose import compose_group

    fab = compose_group("fabric", "cpu")
    assert isinstance(fab, dict)
    assert fab["accelerator"] == "cpu"
    # sibling-include defaults of the group are applied
    assert "precision" in fab


def test_compose_group_interpolations_resolve_in_context():
    """Interpolations inside a spliced group resolve against the full tree
    (the eval CLI calls resolve() after splicing)."""
    from sheeprl_tpu.config.compose import compose_group, resolve

    logger = compose_group("logger", "tensorboard")
    tree = {"exp_name": "myexp", "run_name": "r1", "root_dir": "d", "logger": logger}
    resolved = resolve(tree)
    assert "${" not in str(resolved["logger"])
