"""Fixtures for the online-learning bridge tests: an in-process closed loop
(fleet or single server → bridge → learner → publisher → hot swap) over the
committed linear policy, with a hidden target policy as the feedback oracle.

The hook used everywhere is "imitate the hidden expert": reward is the
negative squared distance between the served action and the expert's, the
target is the expert action itself — so learning *provably* improves eval
return as ``w`` converges toward ``w*``, which is what the mid-run
improvement acceptance drill gates on.
"""

import os
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np
import pytest

from tests.test_serve.conftest import (  # noqa: F401  (make_server re-exported as a fixture)
    DRILL_FLEET,
    DRILL_SERVE,
    commit_linear,
    make_server,
)


def make_expert_hook(seed: int = 7):
    """(hook, expert_state): Feedback(reward, target) against a hidden
    expert linear policy drawn from ``seed``."""
    from sheeprl_tpu.online import Feedback
    from sheeprl_tpu.serve.policy import make_linear_state

    expert = make_linear_state(seed=seed)
    w = np.asarray(expert["agent"]["w"], dtype=np.float32)
    b = np.asarray(expert["agent"]["b"], dtype=np.float32)

    def hook(obs: Dict[str, Any], action: Any) -> Feedback:
        x = np.asarray(obs["vector"], dtype=np.float32)
        target = x @ w + b
        reward = -float(np.sum((np.asarray(action, dtype=np.float32) - target) ** 2))
        return Feedback(reward=reward, target=target)

    return hook, expert


def eval_return(server: Any, hook: Callable, *, n: int = 32, seed: int = 123) -> float:
    """Mean hook reward of the CURRENTLY SERVED policy on a fixed eval set."""
    rng = np.random.default_rng(seed)
    in_dim = server.policy.obs_spec["vector"].shape[0]
    total = 0.0
    for _ in range(n):
        obs = {"vector": rng.standard_normal(in_dim).astype(np.float32)}
        out = server.infer(obs, deadline_s=10.0)
        total += hook(obs, out).reward
    return total / n


class OnlineLoop:
    """Everything the closed loop owns, with one close() for teardown."""

    def __init__(self, **parts: Any) -> None:
        self.__dict__.update(parts)
        self.events: List[tuple] = parts.get("events", [])

    def close(self) -> None:
        for name in ("bridge", "learner"):
            part = self.__dict__.get(name)
            if part is not None:
                part.close()
        for name in ("server",):
            part = self.__dict__.get(name)
            if part is not None:
                part.close()
        for name in ("actor_transport", "learner_transport"):
            part = self.__dict__.get(name)
            if part is not None:
                part.close()


@pytest.fixture
def make_loop(tmp_path):
    """Factory for the full in-process loop. Keyword knobs:

    - ``fleet``: route through a FleetServer (default True)
    - ``online``: OnlineConfig field overrides
    - ``faults``: bridge fault dicts (``parse_bridge_faults`` shape)
    - ``hook``: replace the expert hook
    - ``start_learner`` / ``start_bridge``: leave parts un-started
    """
    from sheeprl_tpu.net.transport import ShmLearnerTransport, attach_actor_transport
    from sheeprl_tpu.online import (
        BridgeFaultSchedule,
        CheckpointPublisher,
        ExperienceBridge,
        GuardedHook,
        OnlineConfig,
        OnlineLearner,
        VersionAuthority,
        build_experience_layout,
        linear_feedback_train_step,
        parse_bridge_faults,
    )
    from sheeprl_tpu.online.learner import linear_state
    from sheeprl_tpu.serve.config import serve_config_from_cfg
    from sheeprl_tpu.serve.fleet import FleetServer
    from sheeprl_tpu.serve.policy import build_linear_policy
    from sheeprl_tpu.serve.server import PolicyServer

    loops: List[OnlineLoop] = []

    def build(
        *,
        fleet: bool = True,
        online: Optional[Dict[str, Any]] = None,
        faults: Optional[List[Dict[str, Any]]] = None,
        hook: Optional[Callable] = None,
        start_learner: bool = True,
        start_bridge: bool = True,
    ) -> OnlineLoop:
        ckpt_dir = str(tmp_path / f"checkpoint{len(loops)}")
        path, state = commit_linear(ckpt_dir, 100, seed=0)
        policy = build_linear_policy({"algo": {"name": "linear"}}, state)
        if fleet:
            cfg = serve_config_from_cfg({"serve": {**DRILL_SERVE, "fleet": {**DRILL_FLEET}}})
            server: Any = FleetServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir)
        else:
            cfg = serve_config_from_cfg({"serve": {**DRILL_SERVE}})
            server = PolicyServer(policy, cfg, step=100, path=path, ckpt_dir=ckpt_dir)
        server.start()

        ocfg = OnlineConfig(
            enabled=True,
            rows_per_slab=8,
            ring_slots=4,
            max_staleness=4,
            publish_every=2,
            lr=0.05,
            hook_timeout_s=0.3,
            **(online or {}),
        )
        schedule = BridgeFaultSchedule(parse_bridge_faults(faults)) if faults else None
        authority = VersionAuthority(boot_step=100)
        server.store.version_authority = authority

        expert_hook, expert = make_expert_hook()
        the_hook = hook if hook is not None else expert_hook
        out_dim = np.asarray(state["agent"]["b"]).shape[0]
        layout = build_experience_layout(policy.obs_spec, (out_dim,), ocfg.rows_per_slab)
        learner_transport = ShmLearnerTransport(
            payload_bytes=layout.nbytes, num_slots=ocfg.ring_slots, param_nbytes=64
        )
        actor_transport = attach_actor_transport(
            learner_transport.actor_wire(0),
            actor_id=0,
            generation=0,
            slots=list(range(ocfg.ring_slots)),
        )

        events: List[tuple] = []

        def on_event(kind: str, info: Dict[str, Any]) -> None:
            events.append((kind, dict(info)))

        guard = GuardedHook(the_hook, timeout_s=ocfg.hook_timeout_s, schedule=schedule)
        bridge = ExperienceBridge(
            layout=layout,
            transport=actor_transport,
            authority=authority,
            hook=guard,
            cfg=ocfg,
            schedule=schedule,
            on_event=on_event,
        )
        publisher = CheckpointPublisher(
            ckpt_dir=ckpt_dir,
            authority=authority,
            state_fn=linear_state,
            servers=[server],
            schedule=schedule,
            boot_step=100,
            on_event=on_event,
        )
        params0 = {k: np.asarray(v, dtype=np.float32) for k, v in state["agent"].items()}
        learner = OnlineLearner(
            transport=learner_transport,
            layout=layout,
            authority=authority,
            cfg=ocfg,
            params=params0,
            train_step=linear_feedback_train_step(ocfg.lr),
            publisher=publisher,
            on_event=on_event,
        )
        if start_bridge:
            bridge.start()
        if start_learner:
            learner.start()
        loop = OnlineLoop(
            server=server,
            state=state,
            ckpt_dir=ckpt_dir,
            cfg=ocfg,
            authority=authority,
            layout=layout,
            learner_transport=learner_transport,
            actor_transport=actor_transport,
            hook=the_hook,
            guard=guard,
            bridge=bridge,
            publisher=publisher,
            learner=learner,
            events=events,
            expert=expert,
        )
        loops.append(loop)
        return loop

    yield build
    for loop in loops:
        loop.close()


def drive(loop: OnlineLoop, n: int, *, seed: int = 0, timeout_s: float = 10.0) -> int:
    """Serve ``n`` requests through a tapped ServeClient; returns how many
    completed (raises if any admitted request is dropped — wait() surfaces
    that as an exception)."""
    from sheeprl_tpu.serve.client import ServeClient

    client = ServeClient(loop.server, timeout_s=timeout_s, experience_sink=loop.bridge.observe)
    rng = np.random.default_rng(seed)
    in_dim = loop.server.policy.obs_spec["vector"].shape[0]
    ok = 0
    for _ in range(n):
        obs = {"vector": rng.standard_normal(in_dim).astype(np.float32)}
        client.infer(obs)
        ok += 1
    return ok


def wait_until(predicate: Callable[[], bool], timeout_s: float = 10.0, interval_s: float = 0.01) -> bool:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return predicate()
