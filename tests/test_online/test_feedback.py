"""GuardedHook drills: user feedback code can raise or hang; serving's
experience collector must shed the row (counted) and keep going."""

import time

import numpy as np
import pytest

from sheeprl_tpu.online import BridgeFaultSchedule, Feedback, GuardedHook, parse_bridge_faults

pytestmark = [pytest.mark.online]


def test_normalizes_feedback_tuple_and_scalar():
    returns = [Feedback(1.0, np.ones(2)), (2.0, np.zeros(2)), 3.0, (4.0, None)]
    guard = GuardedHook(lambda obs, a: returns.pop(0), timeout_s=2.0)
    try:
        fb = guard(None, None)
        assert fb.reward == 1.0 and np.allclose(fb.target, 1.0)
        fb = guard(None, None)
        assert fb.reward == 2.0 and np.allclose(fb.target, 0.0)
        fb = guard(None, None)
        assert fb.reward == 3.0 and fb.target is None
        fb = guard(None, None)
        assert fb.reward == 4.0 and fb.target is None
        assert guard.snapshot() == {"hook_calls": 4, "hook_errors": 0, "hook_hangs": 0}
    finally:
        guard.close()


def test_organic_exception_sheds_row_and_counts():
    calls = []

    def hook(obs, action):
        calls.append(action)
        if len(calls) == 2:
            raise ValueError("user code blew up")
        return 1.0

    guard = GuardedHook(hook, timeout_s=2.0)
    try:
        assert guard(None, 0) is not None
        assert guard(None, 1) is None  # the raising call
        assert guard(None, 2) is not None  # guard recovered, same worker
        assert guard.errors == 1
    finally:
        guard.close()


def test_scheduled_hook_exception_fault():
    schedule = BridgeFaultSchedule(parse_bridge_faults([{"kind": "hook_exception", "at_row": 1}]))
    guard = GuardedHook(lambda obs, a: 1.0, timeout_s=2.0, schedule=schedule)
    try:
        assert guard(None, 0) is not None
        assert guard(None, 1) is None  # injected HookError
        assert guard(None, 2) is not None
        assert guard.errors == 1 and guard.hangs == 0
    finally:
        guard.close()


def test_scheduled_hang_is_abandoned_and_recovers():
    events = []
    schedule = BridgeFaultSchedule(
        parse_bridge_faults([{"kind": "hook_hang", "at_row": 1, "duration_s": 0.6}])
    )
    guard = GuardedHook(
        lambda obs, a: 42.0,
        timeout_s=0.1,
        schedule=schedule,
        on_event=lambda k, info: events.append((k, info)),
    )
    try:
        assert guard(None, 0).reward == 42.0
        t0 = time.monotonic()
        assert guard(None, 1) is None  # hang: shed within the budget
        assert time.monotonic() - t0 < 0.5  # did NOT wait out the 0.6s stall
        assert guard.hangs == 1
        # a fresh worker serves the next row even while the old one stalls
        assert guard(None, 2).reward == 42.0
        assert [k for k, _ in events] == ["hook_hang"]
    finally:
        guard.close()


def test_closed_guard_sheds_everything():
    guard = GuardedHook(lambda obs, a: 1.0, timeout_s=1.0)
    assert guard(None, 0) is not None
    guard.close()
    assert guard(None, 1) is None
    guard.close()  # idempotent
