"""bench.py --serve-stats / tools/regress.py folds for the online bridge."""

import importlib.util
import os

import pytest

pytestmark = [pytest.mark.online]

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def bench():
    return _load("_bench_online_fold", "bench.py")


def test_serve_stats_folds_bridge_events_and_run_end_online(bench):
    events = [
        {"event": "serve_stats", "qps": 100.0, "p95_ms": 20.0, "slo_ms": 100.0},
        {"event": "serve_event", "kind": "online_exp_slab", "rows": 8},
        {"event": "serve_event", "kind": "online_exp_slab", "rows": 8},
        {"event": "serve_event", "kind": "online_exp_slab_shed", "rows": 8},
        {"event": "serve_event", "kind": "online_hook_hang"},
        {"event": "serve_event", "kind": "online_publish_committed", "step": 101},
        {
            "event": "run_end",
            "serve": {"stats": {"qps": 100.0, "p95_ms": 20.0, "slo_ms": 100.0}},
            "online": {"shed_experience": 8, "eval_return_delta": 4.2, "hook_hangs": 1},
        },
    ]
    out = bench.serve_stats(events)
    online = out["online"]
    assert online["shed_experience"] == 8
    assert online["eval_return_delta"] == 4.2
    assert online["events"] == {
        "exp_slab": 2,
        "exp_slab_shed": 1,
        "hook_hang": 1,
        "publish_committed": 1,
    }


def test_registry_rows_carry_serve_train_kind_and_online_counters(bench):
    records = [
        {
            "kind": "serve_train",
            "algo": "linear",
            "env": "linear_feedback",
            "outcome": "completed",
            "online": {"eval_return_delta": 4.9, "shed_experience": 80},
            "serve": {"stats": {"qps": 300.0, "p95_ms": 25.0, "slo_ms": 100.0}},
        },
        {"kind": "train", "algo": "ppo"},  # never aggregated as a serve row
    ]
    out = bench.serve_registry_stats(records)
    assert out["serve_records"] == 1
    row = out["records"][0]
    assert row["kind"] == "serve_train"
    assert row["online"] == {"eval_return_delta": 4.9, "shed_experience": 80}
    assert row["qps@p95"] == 300.0


def test_regress_gives_serve_train_its_own_floored_cell():
    regress = _load("_regress_online_fold", "tools/regress.py")
    rec = {
        "schema": regress.SCHEMA_VERSION,
        "t": 1,
        "kind": "serve_train",
        "algo": "linear",
        "env": "linear_feedback",
        "backend": "cpu",
        "local_device_count": 1,
        "process_count": 1,
        "variant": "bridge",
        "outcome": "completed",
        "online": {"eval_return_delta": 4.9, "shed_experience": 80},
        "serve_stats": {"qps": 300.0, "p95_ms": 25.0, "slo_ms": 100.0},
    }
    assert regress.cell_key(rec) == "serve_train:linear:linear_feedback:cpux1p1:bridge"
    metrics = regress.record_metrics(rec)
    assert metrics["eval_return_delta"] == 4.9
    assert metrics["shed_experience"] == 80.0
    assert regress.cell_floors(regress.cell_key(rec)) == [("eval_return_delta", 0.5)]
    # the floor fires even on a first record: no improvement => regress
    doc = regress.evaluate([{**rec, "online": {"eval_return_delta": 0.0}}])
    cell = doc["cells"]["serve_train:linear:linear_feedback:cpux1p1:bridge"]
    assert cell["verdict"] == "regress"
    assert regress.self_test() == 0
