"""VersionAuthority unit drills: one monotonic counter, publish vs confirm."""

import threading

import pytest

from sheeprl_tpu.online import VersionAuthority

pytestmark = [pytest.mark.online]


def test_boot_step_is_version_zero():
    auth = VersionAuthority(boot_step=100)
    assert auth.version_for_step(100) == 0
    assert auth.published_version == 0
    assert auth.confirmed_version == 0


def test_publish_mints_monotonic_versions_idempotently():
    auth = VersionAuthority(boot_step=100)
    v1 = auth.publish(104)
    v2 = auth.publish(108)
    assert (v1, v2) == (1, 2)
    # republishing a known step returns its existing version, mints nothing
    assert auth.publish(104) == 1
    assert auth.published_version == 2
    assert auth.version_for_step(104) == 1
    assert auth.step_for_version(2) == 108


def test_unknown_step_maps_to_boot_version():
    auth = VersionAuthority(boot_step=100)
    # a request stamped before the authority learned its step (or the
    # served_step=-1 sentinel) falls back to the boot version — conservative:
    # staleness can only be overestimated, never underestimated
    assert auth.version_for_step(999) == 0
    assert auth.version_for_step(-1) == 0


def test_confirm_tracks_gauntlet_promotions_only():
    auth = VersionAuthority(boot_step=100)
    auth.publish(104)
    auth.publish(108)
    assert auth.confirmed_version == 0  # nothing promoted yet
    assert auth.confirm(104) == 1
    assert auth.confirmed_version == 1
    assert auth.confirmed_step == 104
    # confirming an unknown step is a no-op, not an invention
    assert auth.confirm(999) is None
    assert auth.confirmed_version == 1
    assert auth.confirm(108) == 2
    snap = auth.snapshot()
    assert snap["published_version"] == 2
    assert snap["confirmed_version"] == 2
    assert snap["confirmed_step"] == 108


def test_concurrent_publish_stays_monotonic():
    auth = VersionAuthority(boot_step=0)
    minted = []
    lock = threading.Lock()

    def worker(base: int) -> None:
        for i in range(50):
            v = auth.publish(base + i)
            with lock:
                minted.append(v)

    threads = [threading.Thread(target=worker, args=(1 + t * 1000,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(set(minted)) == 200  # every distinct step got a distinct version
    assert auth.published_version == 200
