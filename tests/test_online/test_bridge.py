"""ExperienceBridge unit drills against a real shm trajectory ring: slab
assembly, version tagging, the three shed paths, never-block admission."""

import time

import numpy as np
import pytest

from sheeprl_tpu.net.transport import ShmLearnerTransport, attach_actor_transport
from sheeprl_tpu.online import (
    BridgeFaultSchedule,
    ExperienceBridge,
    GuardedHook,
    OnlineConfig,
    VersionAuthority,
    build_experience_layout,
    parse_bridge_faults,
)
from tests.test_online.conftest import wait_until

pytestmark = [pytest.mark.online]

OBS_SPEC = None


def _spec(in_dim=4):
    import jax

    return {"vector": jax.ShapeDtypeStruct((in_dim,), np.float32)}


def _ring(layout, slots=4):
    lt = ShmLearnerTransport(payload_bytes=layout.nbytes, num_slots=slots, param_nbytes=64)
    at = attach_actor_transport(
        lt.actor_wire(0), actor_id=0, generation=0, slots=list(range(slots))
    )
    return lt, at


def _bridge(layout, at, authority, *, faults=None, rows=4, queue_bound=512, **cfg_kw):
    cfg = OnlineConfig(
        enabled=True, rows_per_slab=rows, ring_slots=4, queue_bound=queue_bound, **cfg_kw
    )
    schedule = BridgeFaultSchedule(parse_bridge_faults(faults)) if faults else None
    guard = GuardedHook(lambda obs, a: (1.5, np.asarray(a) * 0 + 2.0), timeout_s=1.0)
    return ExperienceBridge(
        layout=layout,
        transport=at,
        authority=authority,
        hook=guard,
        cfg=cfg,
        schedule=schedule,
    )


def test_layout_geometry_round_trips():
    layout = build_experience_layout(_spec(4), (2,), rows=8)
    assert set(layout.fields) == {"obs.vector", "action", "reward", "target", "target_mask"}
    assert layout.fields["obs.vector"][0] == (8, 4)
    assert layout.fields["action"][0] == (8, 2)
    buf = np.zeros(layout.nbytes, dtype=np.uint8)
    data = {
        "obs.vector": np.arange(32, dtype=np.float32).reshape(8, 4),
        "action": np.ones((8, 2), np.float32),
        "reward": np.full((8,), -1.0, np.float32),
        "target": np.zeros((8, 2), np.float32),
        "target_mask": np.ones((8,), np.float32),
    }
    layout.pack_into(buf, data)
    out = layout.unpack(buf)
    for k in data:
        assert np.array_equal(out[k], data[k]), k


def test_rows_assemble_into_version_tagged_slabs():
    layout = build_experience_layout(_spec(), (2,), rows=4)
    lt, at = _ring(layout)
    auth = VersionAuthority(boot_step=100)
    auth.publish(104)  # version 1
    bridge = _bridge(layout, at, auth)
    try:
        with bridge:
            for i in range(4):
                ok = bridge.observe(
                    {"vector": np.full(4, float(i), np.float32)}, np.zeros(2, np.float32), 104, i + 1
                )
                assert ok
            assert wait_until(lambda: bridge.slabs_committed == 1)
            meta = lt.poll()
            assert meta is not None
            assert meta.param_version == 1  # step 104 → version 1
            assert meta.n_rows == 4
            assert meta.trace_id != 0
            data = layout.unpack(lt.payload(meta))
            lt.release(meta)
            assert np.allclose(data["obs.vector"][:, 0], [0, 1, 2, 3])
            assert np.allclose(data["reward"], 1.5)
            assert np.allclose(data["target"], 2.0)
            assert np.allclose(data["target_mask"], 1.0)
    finally:
        at.close()
        lt.close()


def test_version_boundary_flushes_partial_slab():
    layout = build_experience_layout(_spec(), (2,), rows=4)
    lt, at = _ring(layout)
    auth = VersionAuthority(boot_step=100)
    auth.publish(104)
    bridge = _bridge(layout, at, auth)
    try:
        with bridge:
            # two rows under boot version, then one under version 1: the
            # boundary must flush the 2-row partial so slabs never mix policies
            for i in range(2):
                bridge.observe({"vector": np.zeros(4, np.float32)}, np.zeros(2, np.float32), 100)
            bridge.observe({"vector": np.zeros(4, np.float32)}, np.zeros(2, np.float32), 104)
            assert wait_until(lambda: bridge.slabs_committed >= 1)
            meta = lt.poll()
            assert meta is not None
            assert (meta.param_version, meta.n_rows) == (0, 2)
            lt.release(meta)
    finally:
        at.close()
        lt.close()


def test_queue_bound_sheds_without_blocking():
    layout = build_experience_layout(_spec(), (2,), rows=4)
    lt, at = _ring(layout)
    auth = VersionAuthority(boot_step=100)
    # collector never started: the queue can only fill
    bridge = _bridge(layout, at, auth, queue_bound=8)
    try:
        t0 = time.monotonic()
        accepted = sum(
            bridge.observe({"vector": np.zeros(4, np.float32)}, np.zeros(2, np.float32), 100)
            for _ in range(20)
        )
        assert time.monotonic() - t0 < 1.0  # non-blocking even when shedding
        assert accepted == 8
        assert bridge.rows_shed_queue == 12
        assert bridge.shed_experience == 12
    finally:
        bridge.hook.close()
        at.close()
        lt.close()


def test_ring_full_sheds_whole_slabs_counted():
    layout = build_experience_layout(_spec(), (2,), rows=2)
    lt, at = _ring(layout)
    auth = VersionAuthority(boot_step=100)
    bridge = _bridge(
        layout, at, auth, rows=2,
        faults=[{"kind": "ring_full", "at_slab": 0, "for_slabs": 2}],
    )
    try:
        with bridge:
            for i in range(8):  # 4 slabs of 2; first two hit the injected window
                bridge.observe({"vector": np.zeros(4, np.float32)}, np.zeros(2, np.float32), 100)
            assert wait_until(lambda: bridge.slabs_committed + bridge.slabs_shed_ring >= 4)
            assert bridge.slabs_shed_ring == 2
            assert bridge.rows_shed_ring == 4
            assert bridge.shed_experience == 4
            assert bridge.slabs_committed == 2
    finally:
        at.close()
        lt.close()


def test_real_ring_exhaustion_sheds_when_no_reader():
    layout = build_experience_layout(_spec(), (2,), rows=2)
    lt, at = _ring(layout, slots=2)  # tiny ring, nobody releases
    auth = VersionAuthority(boot_step=100)
    bridge = _bridge(layout, at, auth, rows=2)
    try:
        with bridge:
            for i in range(12):
                bridge.observe({"vector": np.zeros(4, np.float32)}, np.zeros(2, np.float32), 100)
            # 2 slabs fit; the rest must shed against the genuinely-full ring
            assert wait_until(lambda: bridge.slabs_shed_ring >= 4)
            assert bridge.slabs_committed == 2
            snap = bridge.snapshot()
            assert snap["shed_experience"] == snap["rows_shed_ring"] >= 8
    finally:
        at.close()
        lt.close()
