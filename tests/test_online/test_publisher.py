"""CheckpointPublisher drills: manifest discipline, publish faults, and the
gauntlet as the last line between a degraded checkpoint and the fleet."""

import os

import numpy as np
import pytest

from sheeprl_tpu.online import (
    BridgeFaultSchedule,
    CheckpointPublisher,
    VersionAuthority,
    parse_bridge_faults,
)
from sheeprl_tpu.online.learner import linear_state
from sheeprl_tpu.resilience.discovery import newest_committed
from tests.test_serve.conftest import DRILL_SERVE, commit_linear

pytestmark = [pytest.mark.online]


def _params(seed=0):
    from sheeprl_tpu.serve.policy import make_linear_state

    state = make_linear_state(seed=seed)
    return {k: np.asarray(v, dtype=np.float32) for k, v in state["agent"].items()}


def test_publish_commits_manifested_checkpoint_and_mints_version(tmp_path):
    auth = VersionAuthority(boot_step=100)
    pub = CheckpointPublisher(
        ckpt_dir=str(tmp_path), authority=auth, state_fn=linear_state, boot_step=100
    )
    result = pub.publish(_params())
    assert result["step"] == 101 and result["version"] == 1
    newest = newest_committed(str(tmp_path))
    assert newest is not None and newest.step == 101
    assert auth.published_version == 1
    assert auth.version_for_step(101) == 1
    # confirmed only moves when a gauntlet promotes — no servers attached
    assert auth.confirmed_version == 0
    assert pub.snapshot()["publish_committed"] == 1


def test_boot_step_resumes_from_existing_commits(tmp_path):
    commit_linear(str(tmp_path), 140, seed=0)
    commit_linear(str(tmp_path), 120, seed=0)
    auth = VersionAuthority(boot_step=140)
    pub = CheckpointPublisher(ckpt_dir=str(tmp_path), authority=auth, state_fn=linear_state)
    assert pub.step == 140  # discovery helper found the newest commit
    assert pub.publish(_params())["step"] == 141


def test_torn_publish_leaves_no_manifest_and_mints_no_version(tmp_path):
    schedule = BridgeFaultSchedule(parse_bridge_faults([{"kind": "torn_publish", "at_publish": 1}]))
    auth = VersionAuthority(boot_step=100)
    pub = CheckpointPublisher(
        ckpt_dir=str(tmp_path), authority=auth, state_fn=linear_state,
        schedule=schedule, boot_step=100,
    )
    result = pub.publish(_params())
    assert result["torn"] is True and result["version"] is None
    assert auth.published_version == 0
    # the payload exists but discovery refuses it: no manifest, not committed
    assert os.path.exists(os.path.join(str(tmp_path), "ckpt_101_0.ckpt"))
    assert newest_committed(str(tmp_path)) is None
    # the next publish commits cleanly at the NEXT step
    result = pub.publish(_params())
    assert result["step"] == 102 and result["version"] == 1
    assert newest_committed(str(tmp_path)).step == 102


def test_learner_kill_commits_but_never_pushes(tmp_path, make_server):
    server, ckpt_dir, state = make_server()
    server.start()
    schedule = BridgeFaultSchedule(parse_bridge_faults([{"kind": "learner_kill", "at_publish": 1}]))
    auth = VersionAuthority(boot_step=100)
    server.store.version_authority = auth
    pub = CheckpointPublisher(
        ckpt_dir=ckpt_dir, authority=auth, state_fn=linear_state,
        servers=[server], schedule=schedule, boot_step=100,
    )
    result = pub.publish(_params(seed=1))
    assert result["killed"] is True
    assert result["version"] == 1  # committed before the death
    # the server never heard about it from the publisher
    assert server.store.current.step == 100
    assert pub.swaps_ok == 0 and pub.swap_rejects == 0


def test_poison_publish_rejected_by_gauntlet_serving_continues(tmp_path, make_server):
    from tests.test_serve.conftest import expected_action, linear_obs

    server, ckpt_dir, state = make_server()
    server.start()
    schedule = BridgeFaultSchedule(parse_bridge_faults([{"kind": "poison_publish", "at_publish": 1}]))
    auth = VersionAuthority(boot_step=100)
    server.store.version_authority = auth
    pub = CheckpointPublisher(
        ckpt_dir=ckpt_dir, authority=auth, state_fn=linear_state,
        servers=[server], schedule=schedule, boot_step=100,
    )
    result = pub.publish(_params(seed=0))
    # the poisoned checkpoint COMMITTED (manifest digest matches the poison)
    # — only the gauntlet's finiteness gate stood, and it held
    assert result["rejected"] == 1 and result["swapped"] == 0
    assert "non-finite" in result["reject_reasons"][0]
    assert pub.swap_rejects == 1
    assert server.store.current.step == 100  # still the boot version
    assert auth.confirmed_version == 0
    # serving continues, answers still correct
    obs = linear_obs(state)
    out = server.infer(obs, deadline_s=5.0)
    assert np.allclose(np.asarray(out), expected_action(state, obs), atol=1e-5)
    # the next (clean) publish swaps in fine and confirms
    result = pub.publish(_params(seed=0))
    assert result["swapped"] == 1
    assert server.store.current.step == 102
    assert auth.confirmed_version == 2
