"""Closed-loop drills: served traffic → experience bridge → online learner →
checkpoint publisher → hot-swap gauntlet → back into the serving fleet.

Every test runs the REAL loop in-process (fleet router + replicas, shm
trajectory ring, training thread, committed checkpoints on disk, the PR 6
swap gauntlet) — nothing is mocked. The chaos drills then break exactly one
link and assert the blast radius: serving never blips, sheds are counted,
and the fleet keeps answering from the last validated version.
"""

import numpy as np
import pytest

from tests.test_online.conftest import drive, eval_return, wait_until

pytestmark = [pytest.mark.online]


def test_closed_loop_improves_eval_return_mid_run(make_loop):
    loop = make_loop()
    before = eval_return(loop.server, loop.hook)
    n = drive(loop, 400)
    assert n == 400  # zero dropped admitted requests
    # the learner published and the gauntlet promoted at least a few versions
    assert wait_until(lambda: loop.publisher.swaps_ok >= 3)
    mid = eval_return(loop.server, loop.hook)
    assert mid > before + 0.5, (before, mid)  # measurable, not epsilon
    n = drive(loop, 400, seed=1)
    assert n == 400
    assert wait_until(lambda: loop.publisher.swaps_ok >= 6)
    after = eval_return(loop.server, loop.hook)
    assert after > before + 1.0, (before, mid, after)
    # the version chain is coherent: everything published was confirmed
    snap = loop.authority.snapshot()
    assert snap["published_version"] >= 3
    assert snap["confirmed_version"] == snap["published_version"]
    assert loop.server.store.current.step == snap["confirmed_step"]
    # and the learner actually trained on served experience
    assert loop.learner.updates >= 6
    assert loop.learner.rows_trained >= 48
    assert loop.learner.updates_rejected == 0


def test_poison_publish_mid_ramp_rejected_serving_continues(make_loop):
    loop = make_loop(faults=[{"kind": "poison_publish", "at_publish": 2}])
    n = drive(loop, 300)
    assert n == 300
    assert wait_until(lambda: loop.publisher.attempts >= 3)
    assert loop.publisher.swap_rejects >= 1  # the gauntlet caught the poison
    assert any("non-finite" in r for r in loop.publisher.reject_reasons)
    # serving continued right through the rejected ramp: later CLEAN publishes
    # were promoted, so the fleet is past boot but never served the poison
    assert wait_until(lambda: loop.publisher.swaps_ok >= 1)
    assert loop.server.store.current.step > 100
    assert loop.server.store.current.step != loop.publisher.poisoned_steps[0]
    assert drive(loop, 50, seed=2) == 50
    assert np.isfinite(eval_return(loop.server, loop.hook, n=8))


def test_learner_kill_fleet_serves_last_validated_indefinitely(make_loop):
    loop = make_loop(faults=[{"kind": "learner_kill", "at_publish": 3}])
    drive(loop, 300)
    assert wait_until(lambda: loop.learner.killed)
    assert not loop.learner.running
    last_validated = loop.server.store.current.step
    confirmed = loop.authority.confirmed_version
    assert last_validated > 100  # the first two publishes did land
    # the learner is gone; the fleet must keep serving the last validated
    # version for as long as traffic keeps coming
    for seed in (3, 4, 5):
        assert drive(loop, 60, seed=seed) == 60
    assert loop.server.store.current.step == last_validated
    assert loop.authority.confirmed_version == confirmed
    # with nobody draining the ring, the bridge sheds EXPERIENCE (counted),
    # never admission — every request above completed
    assert wait_until(lambda: loop.bridge.shed_experience > 0)
    assert loop.bridge.rows_shed_ring > 0


def test_ring_full_sheds_experience_not_admission(make_loop):
    loop = make_loop(faults=[{"kind": "ring_full", "at_slab": 1, "for_slabs": 3}])
    n = drive(loop, 300)
    assert n == 300  # admission untouched by ring backpressure
    assert wait_until(lambda: loop.bridge.slabs_shed_ring >= 3)
    assert loop.bridge.shed_experience >= 3 * loop.cfg.rows_per_slab
    kinds = [k for k, _ in loop.events]
    assert "exp_slab_shed" in kinds
    # slabs outside the fault window still flowed and trained
    assert wait_until(lambda: loop.learner.updates >= 1)
    assert loop.learner.slabs_admitted >= 1


def test_trace_chain_request_to_swap(tmp_path, make_loop):
    from sheeprl_tpu.obs.trace import configure_trace, shutdown_trace
    from tools.trace import merge

    trace_path = str(tmp_path / "trace.test.jsonl")
    configure_trace("serve_train", trace_path)
    try:
        loop = make_loop()
        drive(loop, 120)
        assert wait_until(lambda: loop.publisher.swaps_ok >= 1 and loop.learner.updates >= 2)
        # quiesce the learning side BEFORE reading the trace: the learner
        # keeps draining slabs and publishing, so merging a live stream races
        # the confirmed_step assertion below
        loop.bridge.close()
        loop.learner.close()
    finally:
        shutdown_trace()

    merged = merge([trace_path])
    traces = {int(k): v for k, v in merged["traces"].items()}
    untraced = merged.get("untraced", [])

    # 1) a served request chain that terminated in request_done …
    done_tids = {
        tid for tid, evs in traces.items() if any(e["kind"] == "request_done" for e in evs)
    }
    assert done_tids
    # 2) … feeds an experience slab that lists it as provenance …
    slabs = [
        (tid, e)
        for tid, evs in traces.items()
        for e in evs
        if e["kind"] == "exp_slab"
    ]
    assert slabs
    fed = [
        (tid, e) for tid, e in slabs if done_tids.intersection(int(r) for r in e["requests"])
    ]
    assert fed, "no exp_slab lists a completed request as provenance"
    slab_tid, slab_ev = fed[0]
    # 3) … whose SAME trace id reaches the learner's gradient window …
    updates = [e for e in traces[slab_tid] if e["kind"] == "online_update"]
    assert updates, "slab trace id never reached an online_update"
    # 4) … and the published version / hot swap close the chain
    publishes = [e for e in untraced if e["kind"] == "param_publish"]
    swaps = [e for e in untraced if e["kind"] == "model_swap"]
    assert publishes and swaps
    published_steps = {int(e["ckpt_step"]) for e in publishes}
    assert {int(e["ckpt_step"]) for e in swaps} & published_steps
    # the swap the gauntlet promoted is the version the authority confirmed
    assert loop.authority.confirmed_step in {int(e["ckpt_step"]) for e in swaps}


@pytest.mark.slow
def test_full_loop_under_loadgen_meets_slo(make_loop):
    """The acceptance drill at benchmark shape: loadgen IS the served
    traffic, its tap feeds the learner, eval improves, p95 holds."""
    from sheeprl_tpu.serve.config import LoadConfig
    from sheeprl_tpu.serve.loadgen import run_load

    loop = make_loop()
    before = eval_return(loop.server, loop.hook)
    rng = np.random.default_rng(0)
    in_dim = loop.server.policy.obs_spec["vector"].shape[0]

    def obs_factory(i: int):
        return {"vector": rng.standard_normal(in_dim).astype(np.float32)}

    cfg = LoadConfig(enabled=True, rate_hz=400.0, duration_s=3.0, concurrency=4, timeout_ms=500.0)
    report = run_load(
        loop.server, cfg, obs_factory=obs_factory, experience_sink=loop.bridge.observe
    )
    assert report["ok"] > 0
    assert report["slo_met"], report
    assert wait_until(lambda: loop.publisher.swaps_ok >= 1)
    after = eval_return(loop.server, loop.hook)
    assert after > before, (before, after)
