"""tools/trace.py as a tool: the --self-test gate (tier-1, same contract as
jaxcheck's), the CLI surface (merge/summary/perfetto) over real fixture
streams, and registry-driven stream discovery."""

import json
import os
import subprocess
import sys

import pytest

from tools import trace as trace_tool

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_self_test_passes_in_process():
    assert trace_tool.self_test() == 0


def test_self_test_gate_subprocess():
    """The tier-1 gate the drills rely on: `python -m tools.trace --self-test`
    exits 0 — the merger's clock-alignment, join, dedup and torn-terminal
    contracts all hold."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.trace", "--self-test"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


@pytest.fixture()
def fixture_streams(tmp_path):
    """Two on-disk streams carrying one complete slab chain + one torn one."""
    from sheeprl_tpu.obs.trace import TraceRecorder

    t_ok, t_torn = 7001, 7002
    actor = TraceRecorder("actor0", str(tmp_path / "trace.actor0.jsonl"))
    actor.emit("slab_collect", t_ok, collect_us=4000)
    actor.emit("slab_commit", t_ok)
    actor.emit("slab_collect", t_torn, collect_us=9000)
    actor.close()
    learner = TraceRecorder("learner", str(tmp_path / "telemetry.jsonl"))
    learner.emit("slab_admit", t_ok, ring_wait_us=2000)
    learner.emit("slab_train", t_ok, train_us=3000)
    learner.emit("torn", t_torn, source="ring")
    learner.close()
    return [str(tmp_path / "telemetry.jsonl"), str(tmp_path / "trace.actor0.jsonl")]


def test_cli_merge_and_summary(fixture_streams, tmp_path, capsys):
    out = str(tmp_path / "merged.json")
    assert trace_tool.main(["merge", *fixture_streams, "--out", out]) == 0
    with open(out) as f:
        merged = json.load(f)
    assert set(merged["traces"]) == {"7001", "7002"}  # JSON keys are strings
    assert {p["role"] for p in merged["processes"]} == {"actor0", "learner"}

    assert trace_tool.main(["summary", *fixture_streams]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["slabs"]["complete_chains"] == 1
    assert summary["slabs"]["terminals"] == {"slab_train": 1, "torn": 1}
    assert summary["slabs"]["ring_wait_ms"]["p50"] == pytest.approx(2.0)


def test_cli_perfetto_export(fixture_streams, tmp_path):
    out = str(tmp_path / "perfetto.json")
    assert trace_tool.main(["perfetto", *fixture_streams, "--out", out]) == 0
    with open(out) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    tracks = {e["args"]["name"] for e in events if e["ph"] == "M"}
    assert len(tracks) == 2 and any("actor0" in t for t in tracks)
    # measured phases become duration slices; the rest are instants
    spans = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in spans} >= {"slab_collect", "slab_admit", "slab_train"}
    assert all(e["dur"] > 0 for e in spans)


def test_from_registry_resolves_declared_streams(fixture_streams, tmp_path, capsys):
    """--from-registry uses the newest record's declared telemetry_files —
    the no-globbing contract with obs.registry."""
    runs = tmp_path / "RUNS.jsonl"
    with open(runs, "w") as f:
        f.write(json.dumps({"run_id": "old"}) + "\n")
        f.write(json.dumps({"run_id": "new", "telemetry_files": fixture_streams}) + "\n")
    assert trace_tool.main(["summary", "--from-registry", str(runs)]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["slabs"]["traces"] == 2

    with open(runs, "w") as f:
        f.write(json.dumps({"run_id": "bare"}) + "\n")
    with pytest.raises(SystemExit):
        trace_tool.registry_stream_paths(str(runs))
