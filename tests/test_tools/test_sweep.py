"""tools/sweep.py unit tests (ISSUE 19 tentpole part 5).

The budget-tiered sweep runner must (a) enumerate a grid with >= 20 learn
cells all riding the fused path, (b) score reward trends with the
learning_checks.sh method, (c) defer chip-tier cells into benchmarks/
QUEUE.json without duplicating standing entries, and (d) fold executed
verdicts into SCENARIOS.json without clobbering the static sections (the
half tools/regress.py PRESERVED_KEYS carries through its rewrites).

Everything here is pure-stdlib — no subprocess, no jax.
"""

import json
import os

from tools import sweep


def test_grid_has_twenty_learn_cells_all_fused():
    grid = sweep.build_grid()
    learn = [c for c in grid if c["tier"] == "learn"]
    smoke = [c for c in grid if c["tier"] == "smoke"]
    assert len(learn) >= 20, f"acceptance floor: >=20 learn cells, got {len(learn)}"
    assert smoke, "the cheap dry-run tier must cover the off-policy algos too"
    keys = [c["key"] for c in grid]
    assert len(keys) == len(set(keys)), "duplicate cell keys would merge verdicts"
    for cell in learn:
        assert "algo.fused_rollout=True" in cell["argv"], cell["key"]
        assert cell["min_gain"] > 0, "a learn cell must demand an actual reward trend"
    for cell in smoke:
        assert cell["argv"][0] == "dry_run=True"
    # the grid spans algos and scenario compositions, not one env repeated
    algos = {c["key"].split(":")[1] for c in grid}
    assert {"ppo", "a2c", "ppo_recurrent", "dreamer_v3", "sac", "droq"} <= algos
    variant_cells = [c for c in learn if "+" in c["key"]]
    assert len(variant_cells) >= 10, "most learn cells should exercise variants"


def test_chip_deferrals_do_not_collide_with_smoke_keys():
    executed_keys = {c["key"] for c in sweep.build_grid() if c["tier"] != "chip"}
    chip = sweep.chip_deferrals()
    assert chip, "chip tier must defer at least the pixel-Dreamer cells"
    for cell in chip:
        assert cell["key"] not in executed_keys, "chip key would overwrite an executed verdict"
        assert cell["queue_entry"]["requires"] == "tpu"
        assert cell["queue_entry"]["argv"], cell["key"]


def test_reward_trend_first_vs_last_fifth():
    lines = [
        f"Rank-0: policy_step={i * 64}, reward_env_{i % 4}={float(10 + i)}" for i in range(20)
    ]
    trend = sweep.reward_trend("\n".join(lines))
    assert trend["episodes"] == 20
    assert trend["rew_first_fifth"] == 11.5  # mean of 10..13
    assert trend["rew_last_fifth"] == 27.5  # mean of 26..29
    assert trend["rew_best"] == 29.0
    # negative / scientific-notation rewards parse too (Pendulum)
    assert sweep.reward_trend(
        "\n".join(f"Rank-0: policy_step=1, reward_env_0={r}" for r in ["-1200.5"] * 5 + ["-1.2e2"] * 5)
    )["rew_last_fifth"] == -120.0
    # fewer than 10 episodes -> no verdict, not a crash
    assert sweep.reward_trend(lines[0]) is None
    assert sweep.reward_trend("") is None


def test_defer_chip_cells_dedups_and_keeps_standing_entries(tmp_path):
    queue = os.path.join(tmp_path, "QUEUE.json")
    standing = {"id": "xl_mfu_2d", "requires": "tpu", "argv": ["benchmarks/xl.py"]}
    with open(queue, "w") as f:
        json.dump({"schema": 1, "entries": [standing]}, f)
    chip = sweep.chip_deferrals()
    added = sweep.defer_chip_cells(chip, queue)
    assert set(added) == {c["queue_entry"]["id"] for c in chip}
    # a second sweep adds nothing and rewrites nothing
    assert sweep.defer_chip_cells(chip, queue) == []
    with open(queue) as f:
        doc = json.load(f)
    ids = [e["id"] for e in doc["entries"]]
    assert ids[0] == "xl_mfu_2d", "standing entries stay first and untouched"
    assert len(ids) == len(set(ids)) == 1 + len(chip)


def test_fold_executed_merges_and_preserves_static_sections(tmp_path):
    path = os.path.join(tmp_path, "SCENARIOS.json")
    with open(path, "w") as f:
        json.dump(
            {
                "schema": 1,
                "cells": {"train:ppo:CartPole-v1:cpux1p1": {"status": "pass"}},
                "config_cells": {"ppo/gym": {"status": "ok"}},
                "static_findings": [{"rule": "J001"}],
                "executed_cells": {
                    "sweep:ppo:CartPole-v1": {"tier": "learn", "verdict": "learn_pass"}
                },
            },
            f,
        )
    results = {
        "sweep:a2c:CartPole-v1": {"tier": "learn", "verdict": "learn_fail", "wall_s": 9.0},
        "sweep:ppo:CartPole-v1": {"tier": "learn", "verdict": "learn_pass", "wall_s": 30.0},
    }
    chip = sweep.chip_deferrals()[:1]
    summary = sweep.fold_executed(results, chip, path)
    with open(path) as f:
        doc = json.load(f)
    # merged by key: re-run overwrote its old verdict, new cells appended
    assert doc["executed_cells"]["sweep:ppo:CartPole-v1"]["wall_s"] == 30.0
    assert doc["executed_cells"]["sweep:a2c:CartPole-v1"]["verdict"] == "learn_fail"
    assert doc["executed_cells"][chip[0]["key"]]["verdict"] == "deferred_chip"
    assert doc["executed_cells"][chip[0]["key"]]["queue_id"] == chip[0]["queue_entry"]["id"]
    # the static sections next door are untouched
    assert doc["cells"] == {"train:ppo:CartPole-v1:cpux1p1": {"status": "pass"}}
    assert doc["config_cells"] == {"ppo/gym": {"status": "ok"}}
    assert doc["static_findings"] == [{"rule": "J001"}]
    assert summary["cells"] == 3 == doc["executed_summary"]["cells"]
    assert summary["verdicts"] == {"deferred_chip": 1, "learn_fail": 1, "learn_pass": 1}


def test_stats_rolls_up_executed_cells(tmp_path):
    path = os.path.join(tmp_path, "SCENARIOS.json")
    sweep.fold_executed(
        {
            "sweep:ppo:CartPole-v1+sticky_actions": {
                "tier": "learn",
                "verdict": "learn_pass",
                "sps_env": 33000.0,
                "rew_first_fifth": 20.0,
                "rew_last_fifth": 200.0,
                "episodes": 120,
                "wall_s": 35.0,
            },
            "sweep:sac:Pendulum-v1": {"tier": "smoke", "verdict": "smoke_pass", "wall_s": 15.0},
        },
        [],
        path,
    )
    out = sweep.stats(path)
    assert out["cells"] == 2
    assert out["by_verdict"] == {"learn_pass": 1, "smoke_pass": 1}
    (row,) = [r for r in out["rows"] if r["tier"] == "learn"]
    assert row["sps_env"] == 33000.0 and row["rew_last_fifth"] == 200.0
    # unreadable path reports instead of raising (bench.py --sweep-stats UX)
    assert "error" in sweep.stats(os.path.join(tmp_path, "missing.json"))
