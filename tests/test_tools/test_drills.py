"""tools/drills.py: the chaos-drill registry scanner."""

import json
import os
import textwrap

import pytest

from tools import drills

pytestmark = [pytest.mark.online]


def _write(root, rel, content):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(textwrap.dedent(content))
    return path


FAKE_DOMAINS = {"serve": ("replica_crash", "poison_swap"), "online": ("ring_full",)}


def test_scan_attributes_kinds_markers_and_verdicts(tmp_path):
    tests_root = str(tmp_path / "tests")
    _write(
        tests_root,
        "test_chaos.py",
        '''
        import pytest

        pytestmark = [pytest.mark.serve]

        @pytest.mark.slow
        def test_crash_drill():
            faults = [{"kind": "replica_crash", "at_batch": 2}]

        def test_ring_drill():
            faults = [{"kind": "ring_full"}]

        def test_not_a_drill():
            assert 1 + 1 == 2
        ''',
    )
    cache = tmp_path / ".pytest_cache" / "v" / "cache"
    os.makedirs(cache)
    crash_id = os.path.join(tests_root, "test_chaos.py") + "::test_crash_drill"
    ring_id = os.path.join(tests_root, "test_chaos.py") + "::test_ring_drill"
    (cache / "lastfailed").write_text(json.dumps({crash_id: True}))
    (cache / "nodeids").write_text(json.dumps([crash_id, ring_id]))

    registry = drills.scan(
        tests_root, domains=FAKE_DOMAINS, cache_dir=str(tmp_path / ".pytest_cache")
    )
    by_name = {d["nodeid"].rsplit("::", 1)[1]: d for d in registry["drills"]}
    assert set(by_name) == {"test_crash_drill", "test_ring_drill"}
    crash = by_name["test_crash_drill"]
    assert crash["fault_kinds"] == ["replica_crash"]
    assert crash["domains"] == ["serve"]
    assert crash["markers"] == ["serve", "slow"]  # module mark + decorator
    assert crash["verdict"] == "failed"
    ring = by_name["test_ring_drill"]
    assert ring["verdict"] == "passed"
    assert ring["domains"] == ["online"]
    assert registry["coverage"]["serve"] == {"replica_crash": 1, "poison_swap": 0}
    assert registry["uncovered"] == {"serve": ["poison_swap"]}
    assert registry["totals"] == {"drills": 2, "kinds": 3, "kinds_covered": 2}


def test_missing_cache_means_unknown_not_invented(tmp_path):
    tests_root = str(tmp_path / "tests")
    _write(tests_root, "test_x.py", 'def test_d():\n    k = "ring_full"\n')
    registry = drills.scan(
        tests_root, domains=FAKE_DOMAINS, cache_dir=str(tmp_path / "nope")
    )
    assert registry["drills"][0]["verdict"] == "unknown"


def test_repo_registry_has_no_undrilled_fault_kind():
    """The acceptance contract: every fault kind any domain registers has at
    least one drill in the suite — including all six bridge kinds."""
    registry = drills.scan("tests")
    assert registry["uncovered"] == {}, registry["uncovered"]
    online = registry["coverage"]["online"]
    assert set(online) == {
        "poison_publish",
        "torn_publish",
        "learner_kill",
        "hook_exception",
        "hook_hang",
        "ring_full",
    }
    assert all(n >= 1 for n in online.values()), online
