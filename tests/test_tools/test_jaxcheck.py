"""Rule-engine tests for tools/jaxcheck: every rule has a positive and a
negative fixture, every rule honours --disable, and the baseline keys survive
unrelated edits (they carry no line numbers)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools import jaxcheck
from tools.jaxcheck import selftest
from tools.jaxcheck.core import compare_to_baseline, load_baseline, write_baseline

REPO = jaxcheck.repo_root()
FIXTURE_PATH = selftest.FIXTURE_PATH


def _analyze(source, disabled=None):
    return jaxcheck.analyze_source(textwrap.dedent(source), FIXTURE_PATH, disabled=disabled)


@pytest.mark.parametrize("code", sorted(selftest.FIXTURES))
def test_positive_fixture_fires(code):
    positive, _ = selftest.FIXTURES[code]
    assert code in {f.rule for f in _analyze(positive)}


@pytest.mark.parametrize("code", sorted(selftest.FIXTURES))
def test_negative_fixture_is_quiet(code):
    _, negative = selftest.FIXTURES[code]
    assert code not in {f.rule for f in _analyze(negative)}


@pytest.mark.parametrize("code", sorted(selftest.FIXTURES))
def test_disabling_the_rule_silences_it(code):
    positive, _ = selftest.FIXTURES[code]
    assert code in {f.rule for f in _analyze(positive)}
    assert code not in {f.rule for f in _analyze(positive, disabled={code})}


def test_all_twelve_rules_registered():
    # three families, twelve rules, every rule has a self-test fixture pair
    assert sorted(jaxcheck.RULES) == [f"JX{i:02d}" for i in range(1, 13)]
    assert sorted(jaxcheck.FAMILIES) == ["concurrency", "sharding", "tracing"]
    assert sorted(c for codes in jaxcheck.FAMILIES.values() for c in codes) == sorted(jaxcheck.RULES)
    assert sorted(selftest.FIXTURES) == sorted(jaxcheck.RULES)


def test_counts_by_family_buckets_every_rule():
    positive, _ = selftest.FIXTURES["JX06"]
    by_family = jaxcheck.counts_by_family(_analyze(positive))
    assert by_family["concurrency"] >= 1
    assert set(by_family) >= {"tracing", "concurrency", "sharding"}


def test_seqlock_reader_pair():
    # the reader side of the JX07 contract: missing seq re-check fires,
    # the param-lane-shaped re-read-and-compare is quiet
    assert "JX07" in {f.rule for f in _analyze(selftest.SEQLOCK_READER_POSITIVE)}
    assert "JX07" not in {f.rule for f in _analyze(selftest.SEQLOCK_READER_NEGATIVE)}


def test_pr13_stale_incarnation_clobber_is_redetectable():
    # the exact race class PR 13 fixed by review, stripped to its shape:
    # lock-free clear of a lock-guarded in-flight map
    findings = [f for f in _analyze(selftest.PR13_CLOBBER_POSITIVE) if f.rule == "JX06"]
    assert findings and "_inflight" in findings[0].message
    assert "JX06" not in {f.rule for f in _analyze(selftest.PR13_CLOBBER_NEGATIVE)}


def test_lock_inference_tolerates_locked_private_helpers():
    # the SlotPool idiom: a private helper every caller invokes while already
    # holding the lock must count as guarded, not pollute the majority vote
    source = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._free = []

        def _refill_locked(self):
            self._free.append(object())

        def take(self):
            with self._lock:
                if not self._free:
                    self._refill_locked()
                return self._free.pop()

        def put(self, x):
            with self._lock:
                self._free.append(x)
    """
    assert "JX06" not in {f.rule for f in _analyze(source)}


def test_callback_under_lock_sees_one_level_of_indirection():
    # submit -> self._shed -> user hook, with the lock held at the top call
    source = """
    import threading

    class Q:
        def __init__(self, on_shed):
            self._lock = threading.Lock()
            self._on_shed = on_shed

        def submit(self):
            with self._lock:
                self._shed("overloaded")

        def _shed(self, kind):
            self._on_shed(kind)
    """
    findings = [f for f in _analyze(source) if f.rule == "JX10"]
    assert findings and any("submit" in f.qualname for f in findings)


def test_hot_loop_taint_mode():
    # float() per loop iteration on a train_fn result fires; the same loop
    # after a single np.asarray host fetch is quiet — the exact shape of the
    # ppo/a2c per-update loops
    assert "JX02" in {f.rule for f in _analyze(selftest.HOT_LOOP_POSITIVE)}
    assert "JX02" not in {f.rule for f in _analyze(selftest.HOT_LOOP_NEGATIVE)}


def test_hot_loop_mode_only_applies_under_algos():
    findings = jaxcheck.analyze_source(
        textwrap.dedent(selftest.HOT_LOOP_POSITIVE), "sheeprl_tpu/serve/whatever.py"
    )
    assert "JX02" not in {f.rule for f in findings}


def test_jit_factory_donation_tracked_across_functions():
    # donate_argnums declared inside make_train_fn must reach the call site
    source = """
    import jax

    def make_train_fn(step):
        return jax.jit(step, donate_argnums=(0,))

    def main(step, params, batch):
        train_fn = make_train_fn(step)
        out = train_fn(params, batch)
        return params
    """
    findings = [f for f in _analyze(source) if f.rule == "JX03"]
    assert findings and "params" in findings[0].message


def test_finding_keys_have_no_line_numbers():
    positive, _ = selftest.FIXTURES["JX01"]
    (finding,) = [f for f in _analyze(positive) if f.rule == "JX01"]
    assert finding.key == f"JX01:{FIXTURE_PATH}::sample"
    assert str(finding.line) not in finding.key.split("::")[-1]


def test_baseline_round_trip_survives_unrelated_edit(tmp_path):
    positive, _ = selftest.FIXTURES["JX01"]
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, _analyze(positive))
    # unrelated edit: new header lines shift every line number
    edited = "# a comment\nHELPER = 1\n\n" + textwrap.dedent(positive)
    shifted = jaxcheck.analyze_source(edited, FIXTURE_PATH)
    assert shifted, "fixture still has its finding"
    new, stale = compare_to_baseline(shifted, load_baseline(baseline_path))
    assert new == [] and stale == []


def test_baseline_catches_second_hazard_in_same_function(tmp_path):
    positive, _ = selftest.FIXTURES["JX01"]
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, _analyze(positive))
    worse = textwrap.dedent(positive) + textwrap.dedent(
        """
        def another(key):
            a = jax.random.normal(key, (2,))
            b = jax.random.uniform(key, (2,))
            return a + b
        """
    )
    new, _ = compare_to_baseline(
        jaxcheck.analyze_source(worse, FIXTURE_PATH), load_baseline(baseline_path)
    )
    assert [f.qualname for f in new] == ["another"]


def test_baseline_reports_stale_suppressions(tmp_path):
    _, negative = selftest.FIXTURES["JX01"]
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, _analyze(selftest.FIXTURES["JX01"][0]))
    new, stale = compare_to_baseline(_analyze(negative), load_baseline(baseline_path))
    assert new == []
    assert stale == [f"JX01:{FIXTURE_PATH}::sample"]


def _write_fixture_tree(tmp_path, source):
    target = tmp_path / "mod.py"
    target.write_text(textwrap.dedent(source))
    return str(target)


def test_baseline_gc_prunes_stale_and_ci_fails_on_them(tmp_path):
    # a baseline written against the JX01 positive goes stale once the code
    # is fixed: --baseline-gc --ci reports it and exits 1 without touching
    # the file; plain --baseline-gc rewrites it and the next scan is clean
    positive, negative = selftest.FIXTURES["JX01"]
    mod = _write_fixture_tree(tmp_path, positive)
    baseline_path = str(tmp_path / "baseline.json")
    # keys must match the CLI's repo-root-relative rendering of the target
    rel = os.path.relpath(mod, REPO).replace(os.sep, "/")
    write_baseline(baseline_path, jaxcheck.analyze_source(textwrap.dedent(positive), rel))
    (tmp_path / "mod.py").write_text(textwrap.dedent(negative))

    def run(*flags):
        return subprocess.run(
            [sys.executable, "-m", "tools.jaxcheck", mod,
             "--baseline", baseline_path, "--no-configcheck", "--no-scenarios", *flags],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )

    ci = run("--baseline-gc", "--ci")
    assert ci.returncode == 1, ci.stdout + ci.stderr
    assert "stale" in ci.stdout
    assert load_baseline(baseline_path), "--ci must not rewrite the baseline"

    gc = run("--baseline-gc")
    assert gc.returncode == 0, gc.stdout + gc.stderr
    assert load_baseline(baseline_path) == {}, "stale suppression should be pruned"

    clean = run("--baseline-gc", "--ci")
    assert clean.returncode == 0, clean.stdout + clean.stderr


def test_checked_in_baseline_documents_every_suppression():
    baseline = load_baseline(os.path.join(REPO, "tools", "jaxcheck_baseline.json"))
    for key, entry in baseline.items():
        assert entry.get("note"), f"baseline entry {key} has no justification note"


def test_cli_self_test():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxcheck", "--self-test"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_repo_scan_is_clean(tmp_path):
    """The tier-1 gate: the repo-wide scan + config matrix must exit 0 with
    only strictly-documented baseline suppressions."""
    scenarios = tmp_path / "SCENARIOS.json"
    env = dict(os.environ, SHEEPRL_TPU_SKIP_ALGO_IMPORTS="1")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxcheck", "--json", "--scenarios", str(scenarios)],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    assert report["new"] == []
    assert report["parse_errors"] == []
    assert report["config"]["fail"] == 0
    assert report["config"]["cells"] > 100
    # verdicts folded into the grid file
    doc = json.loads(scenarios.read_text())
    assert doc["config_summary"]["pass"] == report["config"]["pass"]
    assert doc["static_findings"]["new"] == 0
    assert len(doc["config_cells"]) == report["config"]["cells"]


def test_regress_rewrite_preserves_jaxcheck_keys(tmp_path):
    """tools/regress.py owns SCENARIOS.json's runtime grid; rewriting it must
    carry the static config_cells/config_summary/static_findings forward."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_regress_under_test", os.path.join(REPO, "tools", "regress.py")
    )
    regress = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(regress)

    path = str(tmp_path / "SCENARIOS.json")
    with open(path, "w") as f:
        json.dump(
            {
                "schema": 1,
                "config_cells": {"config:exp=x:fabric=cpu": {"verdict": "pass"}},
                "config_summary": {"cells": 1, "pass": 1, "fail": 0},
                "static_findings": {"total": 0, "new": 0},
            },
            f,
        )
    regress.write_scenarios(regress.evaluate([]), path)
    doc = json.load(open(path))
    assert doc["config_cells"] == {"config:exp=x:fabric=cpu": {"verdict": "pass"}}
    assert doc["config_summary"]["pass"] == 1
    assert doc["static_findings"]["new"] == 0
    assert "cells" in doc and "summary" in doc  # the regress grid is still there


def test_ci_baseline_gc_gate_is_clean():
    """Tier-1 wiring of ``jaxcheck --ci --baseline-gc``: the CI shape of the
    gate (report stale suppressions, never rewrite, exit nonzero) must pass
    against the checked-in baseline."""
    env = dict(os.environ, SHEEPRL_TPU_SKIP_ALGO_IMPORTS="1")
    proc = subprocess.run(
        [sys.executable, "-m", "tools.jaxcheck", "--ci", "--baseline-gc"],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "no stale suppressions" in proc.stdout


def test_online_package_scanned_with_zero_suppressions():
    """The online-learning bridge is inside the default scan targets and
    carries NO findings — not even baseline-suppressed ones."""
    findings, files_scanned, errors = jaxcheck.scan(["sheeprl_tpu/online"], root=REPO)
    assert files_scanned >= 8
    assert errors == []
    assert findings == [], [f.render() for f in findings]
    # and no baseline entry exists for the package: zero new suppressions
    baseline = load_baseline(os.path.join(REPO, "tools", "jaxcheck_baseline.json"))
    assert not any("sheeprl_tpu/online/" in key for key in baseline)
