"""Config-matrix validation tests: carrier resolution, mandatory-value
stubbing, the divisibility/mesh invariants, SCENARIOS.json folding, and the
full repo matrix composing clean."""

import json
import os

import pytest

from tools.jaxcheck import configcheck


def _write(root, rel, text):
    path = os.path.join(root, rel)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(text)


@pytest.fixture
def config_tree(tmp_path):
    """A miniature config tree with one mandatory value and one interpolation."""
    root = str(tmp_path / "configs")
    _write(
        root,
        "config.yaml",
        "defaults:\n  - algo: null\n  - exp: ???\n  - _self_\nrun_name: ${algo.name}\n",
    )
    _write(root, "algo/tiny.yaml", "name: tiny\nlr: ???\n")
    _write(
        root,
        "exp/smoke.yaml",
        "# @package _global_\ndefaults:\n  - override /algo: tiny\n  - _self_\nseed: 1\n",
    )
    return [root]


def test_carrier_exp_resolution():
    exps = ["ppo", "dreamer_v3", "p2e_dv1_exploration", "p2e_dv1_finetuning"]
    assert configcheck.carrier_exp("ppo", exps) == "ppo"
    assert configcheck.carrier_exp("dreamer_v3_XS", exps) == "dreamer_v3"
    assert configcheck.carrier_exp("p2e_dv1", exps) == "p2e_dv1_exploration"
    assert configcheck.carrier_exp("unrelated", exps) is None


def test_stub_values_are_type_plausible():
    assert configcheck._stub_value("checkpoint.exploration_ckpt_path") == "/dev/null"
    assert configcheck._stub_value("env.wrapper") == {}
    assert configcheck._stub_value("algo.total_steps") == 1
    assert configcheck._stub_value("algo.name") == "stub"


def test_compose_cell_stubs_mandatory_values(config_tree):
    cfg, stubbed, error = configcheck.compose_cell(["exp=smoke"], search_path=config_tree)
    assert error is None
    assert cfg["algo"]["name"] == "tiny"
    assert cfg["run_name"] == "tiny"  # interpolation resolved
    assert stubbed == {"algo.lr": 1}  # ??? auto-stubbed and recorded


def test_compose_cell_reports_missing_group(config_tree):
    # exp is a mandatory *group* choice — not stubbable with a value
    cfg, _, error = configcheck.compose_cell([], search_path=config_tree)
    assert cfg is None
    assert "exp" in error


def test_compose_cell_reports_bad_option(config_tree):
    cfg, _, error = configcheck.compose_cell(["exp=nope"], search_path=config_tree)
    assert cfg is None and error


def _base_cfg(**over):
    cfg = {
        "algo": {"name": "ppo", "total_steps": 1024, "per_rank_batch_size": 64, "rollout_steps": 128},
        "env": {"id": "CartPole-v1", "num_envs": 4},
        "fabric": {"accelerator": "cpu", "devices": "auto", "mesh_axes": ["data"], "mesh_shape": None},
        "buffer": {"size": 128},
    }
    for key, value in over.items():
        node = cfg
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return cfg


def test_invariants_clean_cell():
    violations, warnings = configcheck.check_invariants(_base_cfg())
    assert violations == []
    assert warnings == []


def test_invariants_missing_required_key():
    cfg = _base_cfg()
    del cfg["algo"]["name"]
    violations, _ = configcheck.check_invariants(cfg)
    assert any("algo.name" in v for v in violations)


def test_invariants_unpinned_topology_mismatch_is_a_warning():
    # 5 steps × 4 envs = 20 does not divide over 8 devices, but the cell does
    # not pin 8 devices — elasticity advisory, not an error
    violations, warnings = configcheck.check_invariants(
        _base_cfg(**{"algo.rollout_steps": 5, "algo.per_rank_batch_size": 4, "buffer.size": 8})
    )
    assert violations == []
    assert any("8-device" in w for w in warnings)


def test_invariants_pinned_topology_mismatch_is_a_violation():
    violations, _ = configcheck.check_invariants(
        _base_cfg(
            **{
                "algo.rollout_steps": 5,
                "algo.per_rank_batch_size": 4,
                "buffer.size": 8,
                "fabric.devices": 8,
            }
        )
    )
    assert any("8-device" in v for v in violations)


def test_invariants_mesh_shape_consistency():
    violations, _ = configcheck.check_invariants(
        _base_cfg(**{"fabric.mesh_shape": [2, 2], "fabric.mesh_axes": ["data"]})
    )
    assert any("mesh_axes" in v for v in violations)
    violations, _ = configcheck.check_invariants(
        _base_cfg(**{"fabric.mesh_shape": [4], "fabric.devices": 8})
    )
    assert any("fabric.devices" in v for v in violations)


def test_invariants_zero_minibatch_is_a_violation():
    violations, _ = configcheck.check_invariants(
        _base_cfg(**{"algo.rollout_steps": 8, "env.num_envs": 1, "algo.per_rank_batch_size": 64, "buffer.size": 8})
    )
    assert any("zero minibatches" in v for v in violations)


def test_buffer_smaller_than_rollout_is_a_violation():
    violations, _ = configcheck.check_invariants(_base_cfg(**{"buffer.size": 16}))
    assert any("buffer.size" in v for v in violations)


def test_fold_into_scenarios_preserves_existing_grid(tmp_path):
    path = str(tmp_path / "SCENARIOS.json")
    with open(path, "w") as f:
        json.dump({"schema": 1, "cells": {"train:ppo": {"verdict": "pass"}}, "summary": {"pass": 1}}, f)
    doc = {
        "schema": 1,
        "topologies": [1, 8],
        "cells": 1,
        "summary": {"pass": 1, "fail": 0, "stubbed_cells": 0, "warnings": 0},
        "grid": {"config:exp=x:fabric=cpu": {"verdict": "pass"}},
    }
    configcheck.fold_into_scenarios(path, doc, static_summary={"total": 0, "new": 0})
    merged = json.load(open(path))
    assert merged["cells"] == {"train:ppo": {"verdict": "pass"}}  # regress grid intact
    assert merged["config_cells"] == {"config:exp=x:fabric=cpu": {"verdict": "pass"}}
    assert merged["config_summary"]["pass"] == 1
    assert merged["static_findings"] == {"total": 0, "new": 0}


def test_full_repo_matrix_composes_clean():
    """Acceptance: 100% of the scenario matrix composes, with per-cell
    verdicts, on the real config tree."""
    doc = configcheck.run_configcheck()
    assert doc["cells"] == len(doc["grid"])
    assert doc["cells"] > 100
    failed = {k: v for k, v in doc["grid"].items() if v["verdict"] != "pass"}
    assert failed == {}
    # the exp axis covers every exp option under both explicit fabrics
    exps = {k.split(":")[1] for k in doc["grid"] if k.startswith("config:exp=")}
    assert {"exp=ppo", "exp=dreamer_v3", "exp=sac"} <= exps
    assert any(k.endswith("fabric=tpu") for k in doc["grid"])
    # stubbed cells record exactly which CLI values they needed
    stubbed = [v for v in doc["grid"].values() if v.get("stubbed")]
    assert stubbed and all(isinstance(v["stubbed"], dict) for v in stubbed)
