"""Test harness setup (reference analogue: tests/conftest.py).

Runs everything on CPU with 8 virtual XLA devices so mesh/collective code paths
are exercised without TPU hardware — the JAX equivalent of the reference's
2-process gloo trick (SURVEY.md §4.2).  Must run before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env var alone is not enough on machines where a TPU platform plugin
# (axon) overrides it; the config update always wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_logdir(tmp_path):
    return str(tmp_path / "logs")


@pytest.fixture(autouse=True)
def _no_env_leaks():
    """Fail a test that leaks SHEEPRL_TPU_* env vars (reference conftest.py:20-61)."""
    before = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    yield
    after = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    assert before == after, f"test leaked env vars: {set(after) ^ set(before)}"


@pytest.fixture(autouse=True)
def _reset_observability_switches():
    """run_algorithm() flips the CLASS-level kill-switches
    (MetricAggregator.disabled / timer.disabled) from cfg.metric.log_level;
    restore them so a log_level=0 CLI test cannot poison later metric tests
    (the reference resets global state per test the same way,
    conftest.py:64-69)."""
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    agg_disabled = MetricAggregator.disabled
    timer_disabled = timer.disabled
    yield
    MetricAggregator.disabled = agg_disabled
    timer.disabled = timer_disabled
