"""Test harness setup (reference analogue: tests/conftest.py).

Runs everything on CPU with 8 virtual XLA devices so mesh/collective code paths
are exercised without TPU hardware — the JAX equivalent of the reference's
2-process gloo trick (SURVEY.md §4.2).  Must run before jax initializes.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The env var alone is not enough on machines where a TPU platform plugin
# (axon) overrides it; the config update always wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_logdir(tmp_path):
    return str(tmp_path / "logs")


def run_two_process(code: str, argv=(), cwd=None, extra_env=None, timeout=540):
    """Launch ``code`` in two real ``jax.distributed`` CPU processes
    (TEST_COORD/TEST_NPROC/TEST_PID env contract) and return their outputs,
    asserting both exit 0. Workers are killed on failure/timeout so a wedged
    pair cannot leak into later tests. Shared by the decoupled-topology and
    collective-plane tests."""
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for pid in range(2):
            env = dict(os.environ)
            env.pop("SHEEPRL_TPU_COORDINATOR", None)
            env.pop("SHEEPRL_TPU_NUM_PROCESSES", None)
            env.pop("SHEEPRL_TPU_PROCESS_ID", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
            env["TEST_COORD"] = f"127.0.0.1:{port}"
            env["TEST_NPROC"] = "2"
            env["TEST_PID"] = str(pid)
            env["PYTHONPATH"] = os.pathsep.join(p for p in (repo_root, env.get("PYTHONPATH")) if p)
            env.update(extra_env or {})
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", code, *argv],
                    env=env,
                    cwd=cwd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
    return outs


@pytest.fixture(autouse=True)
def _no_env_leaks():
    """Fail a test that leaks SHEEPRL_TPU_* env vars (reference conftest.py:20-61)."""
    before = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    yield
    after = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    assert before == after, f"test leaked env vars: {set(after) ^ set(before)}"


@pytest.fixture(autouse=True)
def _reset_observability_switches():
    """run_algorithm() flips the CLASS-level kill-switches
    (MetricAggregator.disabled / timer.disabled) from cfg.metric.log_level;
    restore them so a log_level=0 CLI test cannot poison later metric tests
    (the reference resets global state per test the same way,
    conftest.py:64-69)."""
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    agg_disabled = MetricAggregator.disabled
    timer_disabled = timer.disabled
    yield
    MetricAggregator.disabled = agg_disabled
    timer.disabled = timer_disabled
