"""Test harness setup (reference analogue: tests/conftest.py).

Runs everything on CPU with 8 virtual XLA devices so mesh/collective code paths
are exercised without TPU hardware — the JAX equivalent of the reference's
2-process gloo trick (SURVEY.md §4.2).  Must run before jax initializes.
"""

import os
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# Persistent XLA compilation cache shared by this process AND every
# subprocess the suite spawns (multi-process collective tests, CLI children —
# they inherit the env var): identical tiny training graphs recompile once
# per host instead of once per interpreter, which is most of the algo tier's
# wall time on a 1-core host. Opt out with SHEEPRL_TPU_NO_COMPILE_CACHE=1.
if not os.environ.get("SHEEPRL_TPU_NO_COMPILE_CACHE"):
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(), "sheeprl_tpu_xla_cache"),
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# Redirect the run registry's default away from the repo's real RUNS.jsonl:
# every CLI run a test launches (in-process or as a subprocess — both inherit
# this env var) would otherwise append evidence records to the checked-in
# registry. Set at import time so _no_env_leaks (which snapshots per test)
# sees a constant value. Tests that assert on registry contents override via
# metric.telemetry.runs_jsonl, which takes precedence over the env var.
os.environ.setdefault(
    "SHEEPRL_TPU_RUNS_JSONL",
    os.path.join(tempfile.mkdtemp(prefix="sheeprl_tpu_test_runs_"), "RUNS.jsonl"),
)

import jax  # noqa: E402

# The env var alone is not enough on machines where a TPU platform plugin
# (axon) overrides it; the config update always wins.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture()
def tmp_logdir(tmp_path):
    return str(tmp_path / "logs")


def run_multi_process(code: str, argv=(), cwd=None, extra_env=None, timeout=540, nproc=2, device_count=2):
    """Launch ``code`` in ``nproc`` real ``jax.distributed`` CPU processes
    (TEST_COORD/TEST_NPROC/TEST_PID env contract), each with ``device_count``
    virtual CPU devices, and return their outputs, asserting all exit 0.
    Workers are killed on failure/timeout so a wedged group cannot leak into
    later tests. Shared by the decoupled-topology and collective-plane
    tests."""
    import socket
    import subprocess
    import sys

    # the gloo CPU collectives client must be selected before the worker's
    # jax.distributed.initialize — without it the CPU backend refuses
    # cross-process computations. Prepended here so every multi-process
    # worker snippet gets it (the production path sets the same knob in
    # Fabric._maybe_init_distributed).
    code = (
        "import jax as _jax_boot\n"
        "try:\n"
        '    _jax_boot.config.update("jax_cpu_collectives_implementation", "gloo")\n'
        "except Exception:\n"
        "    pass\n"
    ) + code

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    try:
        for pid in range(nproc):
            env = dict(os.environ)
            env.pop("SHEEPRL_TPU_COORDINATOR", None)
            env.pop("SHEEPRL_TPU_NUM_PROCESSES", None)
            env.pop("SHEEPRL_TPU_PROCESS_ID", None)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={device_count}"
            # the persistent trace cache is unusable in gloo worker groups:
            # it neither keys on process topology (a single-process run of
            # the same global program poisons it) nor round-trips a gloo
            # executable from a warm cache of the SAME topology — either way
            # the deserialized collectives silently compute garbage. Fabric
            # drops it too (_maybe_init_distributed); stripping it here also
            # covers workers that call jax.distributed.initialize directly.
            env.pop("JAX_COMPILATION_CACHE_DIR", None)
            env["TEST_COORD"] = f"127.0.0.1:{port}"
            env["TEST_NPROC"] = str(nproc)
            env["TEST_PID"] = str(pid)
            env["PYTHONPATH"] = os.pathsep.join(p for p in (repo_root, env.get("PYTHONPATH")) if p)
            env.update(extra_env or {})
            procs.append(
                subprocess.Popen(
                    [sys.executable, "-c", code, *argv],
                    env=env,
                    cwd=cwd,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                )
            )
        outs = [p.communicate(timeout=timeout)[0] for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"
    return outs


def run_two_process(code: str, argv=(), cwd=None, extra_env=None, timeout=540):
    return run_multi_process(code, argv=argv, cwd=cwd, extra_env=extra_env, timeout=timeout, nproc=2)


@pytest.fixture()
def multichip_run():
    """Run a module-qualified helper over a virtual ``n_devices`` CPU mesh in
    a FRESH subprocess (the ``__graft_entry__`` ``_SHEEPRL_TPU_DRYRUN_CHILD``
    pattern): this pytest process is pinned to 8 virtual devices at import
    time, so tests that need a different mesh size (e.g. the 4-device vs
    1-device sharded-superstep equivalence pair, marked ``multichip``) fork a
    child with its own ``--xla_force_host_platform_device_count``. Usage::

        out = multichip_run("tests.test_parallel.test_x:helper", 4, str(tmp))

    ``target`` is ``module:function``; extra args are passed through as
    strings. Returns the child's combined stdout/stderr, asserting rc == 0."""
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = (
        "import sys, importlib, jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "mod, fn = sys.argv[1].split(':')\n"
        "getattr(importlib.import_module(mod), fn)(*sys.argv[2:])\n"
    )

    def run(target: str, n_devices: int, *argv, timeout: int = 540, extra_env=None):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={int(n_devices)}"
        env["_SHEEPRL_TPU_DRYRUN_CHILD"] = "1"
        env["PYTHONPATH"] = os.pathsep.join(p for p in (repo_root, env.get("PYTHONPATH")) if p)
        env.update(extra_env or {})
        proc = subprocess.run(
            [sys.executable, "-c", code, target, *map(str, argv)],
            env=env,
            cwd=repo_root,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            timeout=timeout,
        )
        assert proc.returncode == 0, f"multichip child ({target}, {n_devices} devices) failed:\n{proc.stdout[-4000:]}"
        return proc.stdout

    return run


@pytest.fixture(autouse=True)
def _no_env_leaks():
    """Fail a test that leaks SHEEPRL_TPU_* env vars (reference conftest.py:20-61)."""
    before = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    yield
    after = {k: v for k, v in os.environ.items() if k.startswith("SHEEPRL_TPU")}
    assert before == after, f"test leaked env vars: {set(after) ^ set(before)}"


@pytest.fixture(autouse=True)
def _reset_observability_switches():
    """run_algorithm() flips the CLASS-level kill-switches
    (MetricAggregator.disabled / timer.disabled) from cfg.metric.log_level;
    restore them so a log_level=0 CLI test cannot poison later metric tests
    (the reference resets global state per test the same way,
    conftest.py:64-69)."""
    from sheeprl_tpu.utils.metric import MetricAggregator
    from sheeprl_tpu.utils.timer import timer

    agg_disabled = MetricAggregator.disabled
    timer_disabled = timer.disabled
    yield
    MetricAggregator.disabled = agg_disabled
    timer.disabled = timer_disabled


def pytest_unconfigure(config):
    """Exit without CPython finalization (two rounds of `free(): invalid
    pointer` AFTER the test summary — the axon TPU-client plugin's C++
    teardown races interpreter shutdown; not reproducible from plain
    imports, only after a full session). By this hook the report is written
    and every fixture finalized, so `os._exit` with pytest's own status
    makes the exit code deterministic instead of whatever the broken
    destructor produces. Disable with SHEEPRL_TPU_NO_FAST_EXIT=1."""
    if os.environ.get("SHEEPRL_TPU_NO_FAST_EXIT"):
        return
    import sys

    sys.stdout.flush()
    sys.stderr.flush()
    status = getattr(config, "_sheeprl_exitstatus", 0)
    os._exit(int(status))


def pytest_sessionfinish(session, exitstatus):
    session.config._sheeprl_exitstatus = int(exitstatus)


def find_checkpoints(base):
    """Every checkpoint under ``base`` (pickle .ckpt files and orbax .ckpt
    directories), oldest first — shared by the resume/decoupled tests."""
    found = []
    for root, dirs, files in os.walk(base):
        found += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
        found += [os.path.join(root, d) for d in dirs if d.endswith(".ckpt")]
    return sorted(set(found), key=os.path.getmtime)
