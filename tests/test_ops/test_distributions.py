import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats

from sheeprl_tpu.ops.distributions import (
    Bernoulli,
    Categorical,
    Independent,
    MSEDistribution,
    Normal,
    OneHotCategorical,
    OneHotCategoricalStraightThrough,
    SymlogDistribution,
    TanhNormal,
    TruncatedNormal,
    TwoHotEncodingDistribution,
    kl_divergence,
)
from sheeprl_tpu.ops.math import symexp, symlog

KEY = jax.random.PRNGKey(0)


def test_normal_log_prob_matches_scipy():
    d = Normal(jnp.asarray(1.5), jnp.asarray(2.0))
    xs = np.linspace(-3, 5, 7)
    np.testing.assert_allclose(
        [float(d.log_prob(jnp.asarray(x))) for x in xs],
        scipy.stats.norm.logpdf(xs, 1.5, 2.0),
        rtol=1e-5,
    )
    np.testing.assert_allclose(float(d.entropy()), scipy.stats.norm.entropy(1.5, 2.0), rtol=1e-6)


def test_independent_sums_event_dims():
    d = Independent(Normal(jnp.zeros((4, 3)), jnp.ones((4, 3))), 1)
    lp = d.log_prob(jnp.zeros((4, 3)))
    assert lp.shape == (4,)
    np.testing.assert_allclose(lp, 3 * scipy.stats.norm.logpdf(0.0), rtol=1e-6)
    assert d.entropy().shape == (4,)


def test_truncated_normal_matches_scipy():
    loc, scale, low, high = 0.3, 0.7, -1.0, 1.0
    a, b = (low - loc) / scale, (high - loc) / scale
    ref = scipy.stats.truncnorm(a, b, loc=loc, scale=scale)
    d = TruncatedNormal(jnp.asarray(loc), jnp.asarray(scale), jnp.asarray(low), jnp.asarray(high))
    np.testing.assert_allclose(float(d.mean), ref.mean(), rtol=1e-5)
    np.testing.assert_allclose(float(d.variance), ref.var(), rtol=1e-4)
    np.testing.assert_allclose(float(d.entropy()), ref.entropy(), rtol=1e-4)
    xs = np.asarray([-0.9, -0.2, 0.0, 0.5, 0.95])
    np.testing.assert_allclose(
        [float(d.log_prob(jnp.asarray(x))) for x in xs], ref.logpdf(xs), rtol=5e-4
    )
    samples = d.rsample(KEY, (20000,))
    assert float(samples.min()) >= low and float(samples.max()) <= high
    np.testing.assert_allclose(float(samples.mean()), ref.mean(), atol=0.02)


def test_truncated_normal_rsample_grads():
    def f(loc):
        d = TruncatedNormal(loc, jnp.asarray(0.5), jnp.asarray(-1.0), jnp.asarray(1.0))
        return d.rsample(KEY, (256,)).mean()

    g = jax.grad(f)(jnp.asarray(0.0))
    assert np.isfinite(float(g)) and float(g) > 0.0


def test_tanh_normal_log_prob_consistency():
    d = TanhNormal(jnp.asarray([0.2, -0.4]), jnp.asarray([0.5, 0.3]))
    a, lp = d.rsample_and_log_prob(KEY)
    assert np.all(np.abs(np.asarray(a)) < 1.0)
    np.testing.assert_allclose(lp, d.log_prob(a), rtol=1e-4, atol=1e-5)


def test_onehot_categorical():
    logits = jnp.asarray([[2.0, 0.5, -1.0], [0.0, 0.0, 0.0]])
    d = OneHotCategorical(logits)
    s = d.sample(KEY)
    assert s.shape == (2, 3)
    np.testing.assert_allclose(s.sum(-1), 1.0)
    assert d.mode[0].argmax() == 0
    # log_prob of one-hot == log softmax at that index
    lp = d.log_prob(jax.nn.one_hot(jnp.asarray([0, 2]), 3))
    np.testing.assert_allclose(lp, jax.nn.log_softmax(logits)[jnp.arange(2), jnp.asarray([0, 2])], rtol=1e-6)
    # entropy of uniform = log(3)
    np.testing.assert_allclose(float(d.entropy()[1]), np.log(3), rtol=1e-4)


def test_straight_through_gradient():
    def f(logits):
        d = OneHotCategoricalStraightThrough(logits)
        sample = d.rsample(KEY)
        return (sample * jnp.asarray([1.0, 2.0, 3.0])).sum()

    g = jax.grad(f)(jnp.asarray([0.1, 0.2, 0.3]))
    # gradient flows through probs (softmax jacobian), not the hard sample
    assert np.any(np.asarray(g) != 0.0)
    np.testing.assert_allclose(float(np.sum(g)), 0.0, atol=1e-6)  # softmax jacobian rows sum to 0


def test_categorical_sample_log_prob():
    logits = jnp.asarray([0.0, 1.0, 2.0, 3.0])
    d = Categorical(logits)
    samples = d.sample(KEY, (5000,))
    # empirical distribution close to softmax
    freq = np.bincount(np.asarray(samples), minlength=4) / 5000
    np.testing.assert_allclose(freq, jax.nn.softmax(logits), atol=0.02)
    np.testing.assert_allclose(d.log_prob(jnp.asarray(2)), jax.nn.log_softmax(logits)[2], rtol=1e-6)


def test_kl_onehot_pair_zero_and_positive():
    p = OneHotCategorical(jnp.asarray([1.0, 2.0, 0.0]))
    np.testing.assert_allclose(float(kl_divergence(p, p)), 0.0, atol=1e-6)
    q = OneHotCategorical(jnp.asarray([0.0, 0.0, 0.0]))
    assert float(kl_divergence(p, q)) > 0.0


def test_kl_normal_matches_closed_form():
    p = Normal(jnp.asarray(0.0), jnp.asarray(1.0))
    q = Normal(jnp.asarray(1.0), jnp.asarray(2.0))
    expected = np.log(2.0) + (1.0 + 1.0) / (2 * 4.0) - 0.5
    np.testing.assert_allclose(float(kl_divergence(p, q)), expected, rtol=1e-5)


def test_symlog_distribution():
    mode = jnp.asarray([[0.5, -0.2]])
    d = SymlogDistribution(mode, dims=1)
    np.testing.assert_allclose(d.mean, symexp(mode), rtol=1e-6)
    x = symexp(mode)  # exact prediction -> distance < tol -> log_prob 0
    np.testing.assert_allclose(np.asarray(d.log_prob(x)).item(), 0.0, atol=1e-6)
    x2 = symexp(mode + 1.0)
    np.testing.assert_allclose(np.asarray(d.log_prob(x2)).item(), -2.0, rtol=1e-4)


def test_mse_distribution():
    mode = jnp.asarray([[1.0, 2.0]])
    d = MSEDistribution(mode, dims=1)
    np.testing.assert_allclose(np.asarray(d.log_prob(jnp.asarray([[0.0, 0.0]]))).item(), -5.0, rtol=1e-6)


def test_two_hot_distribution_mean_and_log_prob():
    n = 255
    logits = jnp.zeros((4, n))
    d = TwoHotEncodingDistribution(logits, dims=1)
    # uniform logits -> symmetric support -> mean 0
    np.testing.assert_allclose(d.mean, np.zeros((4, 1)), atol=1e-4)
    # log_prob is cross-entropy: for uniform logits = -log(n) * total weight
    lp = d.log_prob(jnp.asarray([[0.0], [1.0], [-3.0], [15.0]]))
    np.testing.assert_allclose(lp, np.full((4,), -np.log(n)), rtol=1e-5)


def test_two_hot_distribution_peaked_mean():
    n = 255
    target = 7.3
    # build logits strongly peaked at the two-hot encoding of symlog(target)
    bins = np.linspace(-20, 20, n)
    t = float(symlog(jnp.asarray(target)))
    idx = int(np.searchsorted(bins, t))
    logits = np.full((1, n), -30.0)
    logits[0, idx - 1 : idx + 1] = 10.0
    d = TwoHotEncodingDistribution(jnp.asarray(logits), dims=1)
    assert abs(np.asarray(d.mean).item() - target) < 0.5


def test_bernoulli_safe_mode():
    d = Bernoulli(jnp.asarray([2.0, -3.0, 0.0]))
    np.testing.assert_allclose(d.mode, [1.0, 0.0, 0.0])
    # log_prob matches scipy bernoulli at p
    p = float(jax.nn.sigmoid(jnp.asarray(2.0)))
    np.testing.assert_allclose(float(d.log_prob(jnp.asarray([1.0, 0.0, 1.0]))[0]), np.log(p), rtol=1e-4)
    s = d.sample(KEY, (1000,))
    np.testing.assert_allclose(s.mean(0), jax.nn.sigmoid(d.logits), atol=0.05)


def test_distributions_jittable():
    @jax.jit
    def run(key, logits):
        d = OneHotCategoricalStraightThrough(logits)
        s = d.rsample(key)
        return s, d.entropy(), kl_divergence(d, OneHotCategorical(jnp.zeros_like(logits)))

    s, ent, kl = run(KEY, jnp.asarray([[1.0, 2.0, 3.0]]))
    assert s.shape == (1, 3)
    assert np.all(np.isfinite(np.asarray(ent))) and np.all(np.isfinite(np.asarray(kl)))
