"""AOT executable cache unit tests (ISSUE 17): serialize/deserialize round
trip, every invalidation axis of the key schema (params structure, topology,
jax version), corrupt/torn-entry GC mirroring the torn-manifest discipline,
and the soft-failure contract (a broken cache degrades to compile, never
raises into a cold path)."""

import os
import pickle
import shutil

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import sheeprl_tpu.ops.aotcache as aotcache
from sheeprl_tpu.ops.aotcache import (
    CACHE_VERSION,
    AotCache,
    AotCachedFunction,
    ENTRY_SUFFIX,
    TMP_PREFIX,
    avals_digest,
    config_fingerprint,
)


@pytest.fixture(autouse=True)
def _real_compiles():
    """Disable the suite-wide XLA persistent trace cache (tests/conftest.py)
    for these tests: a trace-cache HIT yields an executable whose serialized
    payload cannot be loaded back (CPU backend, "Symbols not found"), which
    the store-time verification in AotCache would rightly reject — but these
    tests need real round trips, so compiles must be real."""
    old = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    yield
    jax.config.update("jax_enable_compilation_cache", old)


def _jitted():
    return jax.jit(lambda w, x: jnp.tanh(x @ w).sum(-1))


def _args(width=8, batch=4):
    w = jnp.asarray(np.random.default_rng(0).normal(size=(width, width)), jnp.float32)
    x = jnp.ones((batch, width), jnp.float32)
    return w, x


@pytest.fixture
def cache(tmp_path):
    c = AotCache(str(tmp_path / "aot"))
    yield c
    c.close()


def test_round_trip_numerics_and_counters(cache):
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    fn, hit = cache.load_or_compile(key, lambda: _jitted().lower(w, x).compile(), sync_store=True)
    assert not hit and cache.stats() == {"hits": 0, "misses": 1, "stores": 1, "errors": 0}
    expect = np.asarray(fn(w, x))
    assert cache.has(key) and cache.entry_path(key).endswith(ENTRY_SUFFIX)

    # a fresh cache object over the same dir = a fresh process booting
    reloaded = AotCache(cache.cache_dir)
    try:
        fn2, hit2 = reloaded.load_or_compile(key, lambda: pytest.fail("hit expected, compiled instead"))
        assert hit2 and reloaded.stats()["hits"] == 1
        np.testing.assert_allclose(np.asarray(fn2(w, x)), expect, rtol=0, atol=0)
    finally:
        reloaded.close()


def test_cached_function_resume(tmp_path):
    """AotCachedFunction across two cache instances — the preemption-resume
    shape: run 1 compiles+stores, run 2 deserializes (from_cache True)."""
    w, x = _args()
    first = AotCache(str(tmp_path / "aot"))
    try:
        f1 = AotCachedFunction(_jitted(), first, tag="superstep.unit", fingerprint="cfg")
        out1 = np.asarray(f1(w, x))
        assert f1.from_cache == {avals_digest((w, x)): False}
        first.flush()
    finally:
        first.close()

    second = AotCache(str(tmp_path / "aot"))
    try:
        f2 = AotCachedFunction(_jitted(), second, tag="superstep.unit", fingerprint="cfg")
        out2 = np.asarray(f2(w, x))
        assert f2.from_cache == {avals_digest((w, x)): True}
        assert second.stats() == {"hits": 1, "misses": 0, "stores": 0, "errors": 0}
        np.testing.assert_allclose(out2, out1, rtol=0, atol=0)
    finally:
        second.close()


def test_params_structure_invalidation(cache):
    """Same structure + different values -> SAME key (hot-swap reuse); a
    different structure (extra leaf) -> clean miss."""
    w, x = _args()
    params = {"agent": {"w": w}}
    key = cache.key(tag="unit", avals=(x,), params=params)
    swapped = cache.key(tag="unit", avals=(x,), params={"agent": {"w": w + 1.0}})
    assert swapped.digest == key.digest
    grown = cache.key(tag="unit", avals=(x,), params={"agent": {"w": w, "b": x}})
    assert grown.digest != key.digest
    assert not cache.has(grown)
    assert cache.load(grown) is None and cache.stats()["misses"] == 1


def test_topology_and_fingerprint_invalidation(cache):
    w, x = _args()
    base = cache.key(tag="unit", avals=(w, x))
    # pinned replica device participates (executables bake in their device)
    pinned = cache.key(tag="unit", avals=(w, x), device=jax.devices()[0])
    assert pinned.digest != base.digest
    # config fingerprint drift (a constant baked into the graph changed)
    refit = cache.key(tag="unit", avals=(w, x), fingerprint=config_fingerprint({"lr": 3e-4}))
    assert refit.digest != base.digest
    # different input avals (a new batch rung)
    wider = cache.key(tag="unit", avals=_args(batch=8))
    assert wider.digest != base.digest


def test_jax_version_bump_misses(cache, monkeypatch):
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    cache.store(key, _jitted().lower(w, x).compile(), sync=True)
    assert cache.has(key)
    monkeypatch.setattr(
        aotcache, "_runtime_versions", lambda: {"jax": "99.99.99", "platform_version": "future"}
    )
    bumped = cache.key(tag="unit", avals=(w, x))
    assert bumped.digest != key.digest
    assert not cache.has(bumped)
    assert cache.load(bumped) is None  # clean miss, old entry untouched
    assert cache.has(key) and cache.stats()["errors"] == 0


def test_corrupt_entry_gc(cache):
    """Garbage bytes behind a valid entry name: load -> None, file removed,
    errors counted — the torn-manifest contract for executables."""
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    with open(cache.entry_path(key), "wb") as f:
        f.write(b"not a pickle")
    assert cache.load(key) is None
    assert not cache.has(key)
    assert cache.stats()["errors"] == 1


def test_foreign_entry_gc(cache):
    """A structurally-valid entry whose embedded key disagrees with its file
    name (copied/renamed across keys) is rejected and GC'd."""
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    cache.store(key, _jitted().lower(w, x).compile(), sync=True)
    other = cache.key(tag="unit", avals=_args(batch=16))
    shutil.copyfile(cache.entry_path(key), cache.entry_path(other))
    assert cache.load(other) is None
    assert not cache.has(other)
    assert cache.has(key) and cache.stats()["errors"] == 1


def test_version_bumped_entry_gc(cache):
    """An entry from a future cache schema is skipped and GC'd, not parsed."""
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    with open(cache.entry_path(key), "wb") as f:
        pickle.dump({"cache_version": CACHE_VERSION + 1, "key": key.parts}, f)
    assert cache.load(key) is None
    assert not cache.has(key) and cache.stats()["errors"] == 1


def test_torn_staging_gc(tmp_path):
    cache_dir = tmp_path / "aot"
    cache_dir.mkdir()
    torn = cache_dir / f"{TMP_PREFIX}dead-writer{ENTRY_SUFFIX}"
    torn.write_bytes(b"partial")
    cache = AotCache(str(cache_dir))  # init sweep is age-gated: young file survives
    try:
        assert torn.exists()
        assert cache.torn_entries(max_age_s=0.0) == [str(torn)]
        assert cache.gc_torn(max_age_s=0.0) == [str(torn)]
        assert not torn.exists() and cache.torn_entries() == []
    finally:
        cache.close()


def test_unloadable_payload_never_committed(cache, monkeypatch):
    """Store-time verification: if the serialized payload cannot be loaded
    back (the trace-cache-hit poison mode), the entry is NOT committed —
    store_failed, no file, and the next boot simply compiles."""
    import jax.experimental.serialize_executable as se

    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    compiled = _jitted().lower(w, x).compile()

    def unloadable(payload, in_tree, out_tree):
        raise RuntimeError("Symbols not found: [ dot_add_fusion ]")

    monkeypatch.setattr(se, "deserialize_and_load", unloadable)
    cache.store(key, compiled, sync=True)
    assert not cache.has(key)
    assert cache.stats() == {"hits": 0, "misses": 0, "stores": 0, "errors": 1}
    assert cache.torn_entries() == []  # staging file cleaned up too


def test_store_failure_is_soft(cache, monkeypatch):
    """A store that cannot serialize emits an event and counts an error —
    it never raises into the compile path."""
    w, x = _args()
    key = cache.key(tag="unit", avals=(w, x))
    cache.store(key, object(), sync=True)  # not a Compiled: serialize() raises inside
    assert cache.stats()["errors"] == 1 and not cache.has(key)
    # and the combined path still returns the freshly-compiled executable
    fn, hit = cache.load_or_compile(key, lambda: _jitted().lower(w, x).compile())
    assert not hit
    assert np.asarray(fn(w, x)).shape == (4,)
