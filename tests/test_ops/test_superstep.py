"""Fused training supersteps (ops/superstep.py): a superstep over K steps is
numerically equivalent — params, optimizer state, target-EMA schedule, key
stream — to K sequential train calls driven by the host loop (the ISSUE's
acceptance criterion, CPU fp32)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.ops.superstep import (
    fold_sample_key,
    make_superstep_fn,
    periodic_target_ema,
    pregathered,
)

EMA_FREQ = 2
EMA_TAU = 0.25


def _init_state(seed=0):
    """A tiny regression 'agent': params + target params (EMA'd), adam opt
    state as the donated aux — the same carry split the algo loops use."""
    k = jax.random.PRNGKey(seed)
    kw, kt = jax.random.split(k)
    model = {"w": jax.random.normal(kw, (4, 3)), "b": jnp.zeros((3,))}
    target = {"w": jax.random.normal(kt, (4, 3)), "b": jnp.ones((3,))}
    tx = optax.adam(1e-2)
    return (model, target), (tx.init(model),), tx


def _train_body(tx):
    def body(params, aux, batch, key):
        model, target = params
        (opt_state,) = aux

        def loss_fn(p):
            pred = batch["x"] @ p["w"] + p["b"]
            # the key enters the loss like dropout/exploration noise would,
            # so a key-schedule mismatch shows up as a numeric mismatch
            noise = 0.01 * jax.random.normal(key, pred.shape)
            return jnp.mean((pred + noise - batch["y"]) ** 2)

        loss, grads = jax.value_and_grad(loss_fn)(model)
        updates, opt_state = tx.update(grads, opt_state, model)
        model = optax.apply_updates(model, updates)
        return (model, target), (opt_state,), loss

    return body


def _pre_step(params, aux, counter):
    model, target = params
    target = periodic_target_ema(counter, model, target, EMA_FREQ, EMA_TAU)
    return (model, target), aux


def _batches(n, seed=7):
    k = jax.random.PRNGKey(seed)
    kx, ky = jax.random.split(k)
    return {
        "x": jax.random.normal(kx, (n, 8, 4)),
        "y": jax.random.normal(ky, (n, 8, 3)),
    }


def _host_loop(params, aux, counter0, batches, key, tx, n_steps):
    """The per-step host path the superstep must reproduce: EMA before the
    step on the cumulative-counter schedule (hard copy at step 0), one key
    split per step, one jitted train call per step."""
    train_fn = jax.jit(_train_body(tx))
    model, target = params
    for i in range(n_steps):
        counter = counter0 + i
        if counter % EMA_FREQ == 0:
            tau = 1.0 if counter == 0 else EMA_TAU
            target = jax.tree.map(lambda m, t: tau * m + (1 - tau) * t, model, target)
        key, k_train = jax.random.split(key)
        batch = {k: v[i] for k, v in batches.items()}
        (model, target), aux, loss = train_fn((model, target), aux, batch, k_train)
    return (model, target), aux, key


def _assert_trees_close(a, b, **kw):
    jax.tree.map(lambda x, y: np.testing.assert_allclose(x, y, **kw), a, b)


def test_superstep_matches_sequential_train_calls():
    n_steps = 5
    params, aux, tx = _init_state()
    batches = _batches(n_steps)
    key = jax.random.PRNGKey(42)

    ref_params, ref_aux, ref_key = _host_loop(params, aux, 0, batches, key, tx, n_steps)

    superstep = make_superstep_fn(_train_body(tx), pregathered, n_steps, pre_step=_pre_step)
    fused_params, fused_aux, fused_key, metrics = superstep(
        params, aux, jnp.int32(0), batches, key
    )

    _assert_trees_close(fused_params, ref_params, rtol=1e-6, atol=1e-6)
    _assert_trees_close(fused_aux, ref_aux, rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(fused_key), np.asarray(ref_key))
    assert metrics.shape == (n_steps,)  # per-step losses, stacked on device


def test_superstep_chunking_carries_the_counter_and_key():
    """Two fused chunks (4 + 3) with the counter threaded between them equal
    one 7-step host loop — the window-chunking the loops do for K < G."""
    params, aux, tx = _init_state(seed=3)
    batches = _batches(7, seed=11)
    key = jax.random.PRNGKey(5)

    ref_params, ref_aux, ref_key = _host_loop(params, aux, 0, batches, key, tx, 7)

    body = _train_body(tx)
    first = make_superstep_fn(body, pregathered, 4, pre_step=_pre_step)
    second = make_superstep_fn(body, pregathered, 3, pre_step=_pre_step)
    b1 = {k: v[:4] for k, v in batches.items()}
    b2 = {k: v[4:] for k, v in batches.items()}
    params, aux, key, _ = first(params, aux, jnp.int32(0), b1, key)
    params, aux, key, _ = second(params, aux, jnp.int32(4), b2, key)

    _assert_trees_close(params, ref_params, rtol=1e-6, atol=1e-6)
    _assert_trees_close(aux, ref_aux, rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(key), np.asarray(ref_key))


def test_periodic_target_ema_schedule():
    source = {"w": jnp.full((2,), 4.0)}
    target = {"w": jnp.full((2,), 8.0)}
    # step 0: hard copy regardless of tau
    out = periodic_target_ema(jnp.int32(0), source, target, 2, 0.25)
    np.testing.assert_array_equal(np.asarray(out["w"]), 4.0)
    # off-cadence step: unchanged
    out = periodic_target_ema(jnp.int32(1), source, target, 2, 0.25)
    np.testing.assert_array_equal(np.asarray(out["w"]), 8.0)
    # on-cadence step > 0: tau blend
    out = periodic_target_ema(jnp.int32(2), source, target, 2, 0.25)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.25 * 4.0 + 0.75 * 8.0)


def test_fold_sample_key_is_deterministic_and_distinct():
    key = jax.random.PRNGKey(0)
    folded = fold_sample_key(key)
    assert not np.array_equal(np.asarray(folded), np.asarray(key))
    np.testing.assert_array_equal(np.asarray(folded), np.asarray(fold_sample_key(key)))
    # and distinct from the split outputs the train body consumes
    for part in jax.random.split(key):
        assert not np.array_equal(np.asarray(folded), np.asarray(part))


def test_make_superstep_fn_rejects_nonpositive_length():
    with pytest.raises(ValueError, match="num_steps"):
        make_superstep_fn(lambda p, a, b, k: (p, a, jnp.zeros(())), pregathered, 0)
