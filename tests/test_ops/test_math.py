import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.math import (
    compute_lambda_values,
    gae,
    init_moments,
    normalize,
    symexp,
    symlog,
    two_hot_decoder,
    two_hot_encoder,
    update_moments,
)

# ---- two-hot: specs ported from reference tests/test_utils/test_two_hot_*.py ----


def test_two_hot_standard_case():
    result = two_hot_encoder(jnp.asarray(2.3), 5)
    expected = np.zeros(11)
    expected[5 + 2] = 0.7
    expected[5 + 3] = 0.3
    assert result.shape == (11,)
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_two_hot_more_buckets():
    result = two_hot_encoder(jnp.asarray(2.3), 5, 21)
    expected = np.zeros(21)
    expected[10 + 4] = 0.4
    expected[10 + 5] = 0.6
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_two_hot_batch_case():
    result = two_hot_encoder(jnp.asarray([[2.3], [3.4]]), 5)
    expected = np.zeros((2, 11))
    expected[0, 5 + 2] = 0.7
    expected[0, 5 + 3] = 0.3
    expected[1, 5 + 3] = 0.6
    expected[1, 5 + 4] = 0.4
    assert result.shape == (2, 11)
    np.testing.assert_allclose(result, expected, atol=1e-6)


def test_two_hot_support_size_1():
    result = two_hot_encoder(jnp.asarray(2.3), 0)
    np.testing.assert_allclose(result, [1.0])


def test_two_hot_overflow_underflow():
    up = two_hot_encoder(jnp.asarray(6.1), 5)
    assert up[10] == 1.0 and up.sum() == 1.0
    down = two_hot_encoder(jnp.asarray(-6.1), 5)
    assert down[0] == 1.0 and down.sum() == 1.0


def test_two_hot_even_buckets_rejected():
    with pytest.raises(ValueError):
        two_hot_encoder(jnp.asarray(1.0), 5, 10)
    with pytest.raises(ValueError):
        two_hot_decoder(jnp.zeros(10), 5)


def test_two_hot_roundtrip():
    xs = jnp.asarray([[-4.99], [-1.5], [0.0], [0.25], [4.99]])
    decoded = two_hot_decoder(two_hot_encoder(xs, 5), 5)
    np.testing.assert_allclose(decoded, xs, atol=1e-5)


def test_two_hot_decoder_cases():
    t = np.zeros(11)
    t[5 + 2] = 0.7
    t[5 + 3] = 0.3
    np.testing.assert_allclose(two_hot_decoder(jnp.asarray(t), 5), [2.3], atol=1e-6)
    np.testing.assert_allclose(two_hot_decoder(jnp.asarray([1.0]), 0), [0.0])


# ---- symlog ----


def test_symlog_roundtrip():
    x = jnp.asarray([-100.0, -1.0, 0.0, 0.5, 1000.0])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-3)
    assert float(symlog(jnp.asarray(0.0))) == 0.0


# ---- GAE: against a numpy port of the reference recurrence (utils.py:63-100) ----


def _ref_gae(rewards, values, dones, next_value, gamma, lam):
    T = rewards.shape[0]
    lastgaelam = 0.0
    not_dones = 1.0 - dones
    nextvalues = next_value
    nextnonterminal = not_dones[-1]
    advantages = np.zeros_like(rewards)
    for t in reversed(range(T)):
        if t < T - 1:
            nextnonterminal = not_dones[t]
            nextvalues = values[t + 1]
        delta = rewards[t] + nextvalues * nextnonterminal * gamma - values[t]
        advantages[t] = lastgaelam = delta + nextnonterminal * lastgaelam * gamma * lam
    return advantages + values, advantages


def test_gae_matches_reference_recurrence():
    rng = np.random.default_rng(0)
    T, B = 16, 4
    rewards = rng.normal(size=(T, B)).astype(np.float32)
    values = rng.normal(size=(T, B)).astype(np.float32)
    dones = (rng.random((T, B)) < 0.15).astype(np.float32)
    next_value = rng.normal(size=(B,)).astype(np.float32)
    ref_ret, ref_adv = _ref_gae(rewards, values, dones, next_value, 0.99, 0.95)
    ret, adv = jax.jit(gae, static_argnums=(4, 5))(rewards, values, dones, next_value, 0.99, 0.95)
    np.testing.assert_allclose(adv, ref_adv, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(ret, ref_ret, rtol=1e-4, atol=1e-5)


# ---- lambda values: against the reference python loop (dreamer_v3/utils.py:66-77) ----


def test_lambda_values_match_reference():
    rng = np.random.default_rng(1)
    T, B = 15, 3
    rewards = rng.normal(size=(T, B, 1)).astype(np.float32)
    values = rng.normal(size=(T, B, 1)).astype(np.float32)
    continues = (rng.random((T, B, 1)) < 0.9).astype(np.float32) * 0.997

    vals = [values[-1]]
    interm = rewards + continues * values * (1 - 0.95)
    for t in reversed(range(T)):
        vals.append(interm[t] + continues[t] * 0.95 * vals[-1])
    expected = np.stack(list(reversed(vals))[:-1])

    got = jax.jit(compute_lambda_values)(rewards, values, continues, 0.95)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_lambda_values_dv1_match_reference():
    """Against the reference python loop (dreamer_v1/utils.py:42-78)."""
    from sheeprl_tpu.ops.math import compute_lambda_values_dv1

    rng = np.random.default_rng(4)
    H, N = 15, 6
    lmbda = 0.95
    rewards = rng.normal(size=(H, N, 1)).astype(np.float32)
    values = rng.normal(size=(H, N, 1)).astype(np.float32)
    continues = (rng.random((H, N, 1)) < 0.9).astype(np.float32) * 0.99

    last_lambda = 0.0
    out = []
    for step in reversed(range(H - 1)):
        next_values = values[-1] if step == H - 2 else values[step + 1] * (1 - lmbda)
        delta = rewards[step] + next_values * continues[step]
        last_lambda = delta + lmbda * continues[step] * last_lambda
        out.append(last_lambda)
    expected = np.stack(list(reversed(out)))

    got = jax.jit(compute_lambda_values_dv1)(rewards, values, continues, lmbda)
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


# ---- normalize ----


def test_normalize_unmasked():
    x = jnp.asarray(np.random.default_rng(2).normal(5, 3, size=(128,)).astype(np.float32))
    y = normalize(x)
    assert abs(float(y.mean())) < 1e-5
    assert abs(float(y.std(ddof=1)) - 1.0) < 1e-3


def test_normalize_masked():
    rng = np.random.default_rng(3)
    x = rng.normal(size=(64,)).astype(np.float32)
    mask = rng.random(64) < 0.5
    y = normalize(jnp.asarray(x), mask=jnp.asarray(mask))
    sel = np.asarray(y)[mask]
    np.testing.assert_allclose(sel.mean(), 0.0, atol=1e-5)
    np.testing.assert_allclose(sel.std(ddof=1), 1.0, atol=1e-3)


# ---- moments ----


def test_moments_ema():
    state = init_moments()
    x = jnp.linspace(0.0, 100.0, 1000)
    state, (low, invscale) = update_moments(state, x, decay=0.0)
    np.testing.assert_allclose(float(low), 5.0, atol=0.2)
    np.testing.assert_allclose(float(invscale), 90.0, atol=0.5)
    # decay keeps history
    state2, (low2, _) = update_moments(state, x, decay=0.99)
    assert abs(float(low2) - float(low)) < 0.1
