"""Pallas fused RSSM step vs the flax reference path.

Runs the kernel in interpreter mode (CPU test mesh); on a real TPU the same
code path compiles to Mosaic.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.ops.pallas_gru import (
    fits_vmem,
    fused_recurrent_step,
    reference_step,
    resolve_backend,
    sharded_recurrent_step,
)


def _random_args(key, batch=5, in_dim=12, dense=16, hidden=8):
    ks = jax.random.split(key, 9)
    x = jax.random.normal(ks[0], (batch, in_dim), jnp.float32)
    h = jax.random.normal(ks[1], (batch, hidden), jnp.float32)
    w1 = jax.random.normal(ks[2], (in_dim, dense), jnp.float32) * 0.3
    b1 = jax.random.normal(ks[3], (dense,), jnp.float32) * 0.1
    g1 = 1.0 + 0.1 * jax.random.normal(ks[4], (dense,), jnp.float32)
    be1 = 0.1 * jax.random.normal(ks[5], (dense,), jnp.float32)
    w2 = jax.random.normal(ks[6], (hidden + dense, 3 * hidden), jnp.float32) * 0.3
    g2 = 1.0 + 0.1 * jax.random.normal(ks[7], (3 * hidden,), jnp.float32)
    be2 = 0.1 * jax.random.normal(ks[8], (3 * hidden,), jnp.float32)
    return x, h, w1, b1, g1, be1, w2, g2, be2


@pytest.mark.parametrize("batch", [1, 5, 16])
def test_fused_matches_reference(batch):
    args = _random_args(jax.random.PRNGKey(0), batch=batch)
    got = fused_recurrent_step(*args, interpret=True)
    want = reference_step(*args)
    assert got.shape == (batch, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_fused_gradients_match_reference():
    args = _random_args(jax.random.PRNGKey(1))

    def loss_fused(*a):
        return jnp.sum(jnp.square(fused_recurrent_step(*a, interpret=True)))

    def loss_ref(*a):
        return jnp.sum(jnp.square(reference_step(*a)))

    grads_fused = jax.grad(loss_fused, argnums=tuple(range(9)))(*args)
    grads_ref = jax.grad(loss_ref, argnums=tuple(range(9)))(*args)
    for gf, gr in zip(grads_fused, grads_ref):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gr), atol=1e-4, rtol=1e-4)


def test_fused_matches_flax_recurrent_model():
    """Identical math to the flax RecurrentModel (Dense→LN→SiLU→LN-GRU)."""
    from sheeprl_tpu.algos.dreamer_v3.agent import RecurrentModel

    batch, in_dim, dense, hidden = 4, 10, 12, 8
    model = RecurrentModel(hidden, dense)
    x = jax.random.normal(jax.random.PRNGKey(2), (batch, in_dim), jnp.float32)
    h = jax.random.normal(jax.random.PRNGKey(3), (batch, hidden), jnp.float32)
    params = model.init(jax.random.PRNGKey(4), x, h)
    want = model.apply(params, x, h)

    p = params["params"]
    got = fused_recurrent_step(
        x,
        h,
        p["Dense_0"]["kernel"],
        p["Dense_0"]["bias"],
        p["LayerNorm_0"]["LayerNorm_0"]["scale"],
        p["LayerNorm_0"]["LayerNorm_0"]["bias"],
        p["LayerNormGRUCell_0"]["Dense_0"]["kernel"],
        p["LayerNormGRUCell_0"]["LayerNorm_0"]["LayerNorm_0"]["scale"],
        p["LayerNormGRUCell_0"]["LayerNorm_0"]["LayerNorm_0"]["bias"],
        eps1=1e-3,
        eps2=1e-5,
        interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_checkpoint_interchange_with_flax_module():
    """FusedRecurrentModel declares the SAME param tree as RecurrentModel, so
    checkpoints restore across the fused/flax backend flag — and the same
    params give the same output."""
    from sheeprl_tpu.algos.dreamer_v3.agent import FusedRecurrentModel, RecurrentModel

    flax_model = RecurrentModel(8, 12)
    fused_model = FusedRecurrentModel(8, 12, interpret=True)
    x = jax.random.normal(jax.random.PRNGKey(10), (4, 10), jnp.float32)
    h = jax.random.normal(jax.random.PRNGKey(11), (4, 8), jnp.float32)
    flax_params = flax_model.init(jax.random.PRNGKey(12), x, h)
    fused_params = fused_model.init(jax.random.PRNGKey(12), x, h)
    assert jax.tree_util.tree_structure(flax_params) == jax.tree_util.tree_structure(fused_params)
    # flax-trained params drop into the fused module (and vice versa)
    np.testing.assert_allclose(
        np.asarray(fused_model.apply(flax_params, x, h)),
        np.asarray(flax_model.apply(flax_params, x, h)),
        atol=1e-5,
        rtol=1e-5,
    )


def test_fused_module_trains():
    """FusedRecurrentModel initializes, applies, and has finite grads."""
    from sheeprl_tpu.algos.dreamer_v3.agent import FusedRecurrentModel

    model = FusedRecurrentModel(8, 12, interpret=True)
    x = jax.random.normal(jax.random.PRNGKey(5), (3, 10), jnp.float32)
    h = jnp.zeros((3, 8), jnp.float32)
    params = model.init(jax.random.PRNGKey(6), x, h)
    out = model.apply(params, x, h)
    assert out.shape == (3, 8)

    def loss(p):
        return jnp.sum(jnp.square(model.apply(p, x, h)))

    grads = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(grads)
    assert leaves and all(bool(jnp.all(jnp.isfinite(g))) for g in leaves)
    assert any(float(jnp.max(jnp.abs(g))) > 0 for g in leaves)


def test_resolve_backend_policy():
    # off: never pallas
    assert resolve_backend(False, 64, 64, 64) == (False, False)
    assert resolve_backend("flax", 64, 64, 64) == (False, False)
    # auto off-TPU (CPU test mesh): stays flax
    on_tpu = jax.default_backend() == "tpu"
    use, interp = resolve_backend("auto", 64, 64, 64)
    assert use == on_tpu and interp is False
    # forced: pallas with interpret off-TPU
    use, interp = resolve_backend("pallas", 64, 64, 64)
    assert use is True and interp == (not on_tpu)
    # forced but too large for VMEM: falls back
    use, _ = resolve_backend("pallas", 4096, 8192, 8192)
    assert use is False
    with pytest.raises(ValueError):
        resolve_backend("bogus", 64, 64, 64)


def test_fits_vmem_regimes():
    assert fits_vmem(1536, 512, 512)  # Dreamer-V3 S
    assert not fits_vmem(8192, 8192, 8192)


def test_tile_bytes_dtype_and_shard_accounting():
    """ISSUE-14 satellite: the VMEM budget accounts weights at their STORAGE
    dtype (the old 4-byte hardcode under-admitted bf16 runs) and divides W2
    by the model-shard count. The L 4-shard case is the verdict flip: over
    budget in fp32, within it in bf16."""
    from sheeprl_tpu.ops.pallas_gru import _tile_bytes

    in_dim, dense, hidden = 1536, 768, 2048  # Dreamer-V3 L
    fp32 = _tile_bytes(in_dim, dense, hidden, 8, jnp.float32, 4)
    bf16 = _tile_bytes(in_dim, dense, hidden, 8, jnp.bfloat16, 4)
    assert bf16 < fp32  # activations stay fp32; only the weight term halves
    assert not fits_vmem(in_dim, dense, hidden, jnp.float32, model_shards=4)
    assert fits_vmem(in_dim, dense, hidden, jnp.bfloat16, model_shards=4)
    # XL per-shard slice on a 16-way model axis fits in bf16
    assert fits_vmem(32 * 32 + 6, 1024, 4096, jnp.bfloat16, model_shards=16)
    # legacy positional calls (no dtype, no shards) still mean fp32 x 1
    assert _tile_bytes(1536, 512, 512, 8) == _tile_bytes(1536, 512, 512, 8, jnp.float32, 1)


def test_resolve_backend_model_shards():
    """auto at model_shards > 1 adopts the sharded kernel exactly when
    on-TPU and the per-shard slice fits VMEM (the ISSUE-14 adoption hook);
    forced pallas honors the sharded budget the same way."""
    on_tpu = jax.default_backend() == "tpu"
    use, interp = resolve_backend("auto", 32 * 32 + 6, 1024, 4096, jnp.bfloat16, 16)
    assert use == on_tpu and interp is False
    # sharded but the slice does NOT fit: stays flax
    use, _ = resolve_backend("auto", 8192, 8192, 8192, jnp.float32, 2)
    assert use is False
    use, interp = resolve_backend("pallas", 1536, 768, 2048, jnp.bfloat16, 4)
    assert use is True and interp == (not on_tpu)
    use, _ = resolve_backend("pallas", 1536, 768, 2048, jnp.float32, 4)
    assert use is False  # the L fp32 4-shard flip case falls back


# --------------------------------------------------------------------------
# model-sharded step (interpret mode on the session's 8 virtual CPU devices)
# --------------------------------------------------------------------------
def _mesh_2d():
    from jax.sharding import Mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh")
    return Mesh(np.asarray(jax.devices()[:8]).reshape(2, 4), ("data", "model"))


@pytest.mark.parametrize("use_pallas,data_axis", [(True, "data"), (False, None)])
def test_sharded_step_matches_reference(use_pallas, data_axis):
    """sharded_recurrent_step (per-shard W2 slice + psum'd LN stats + tiled
    all_gather) reproduces the replicated reference on a (2 data x 4 model)
    mesh — with and without the pallas projection, replicated and
    batch-sharded."""
    mesh = _mesh_2d()
    args = _random_args(jax.random.PRNGKey(7), batch=4)
    got = sharded_recurrent_step(
        *args, mesh=mesh, data_axis=data_axis, use_pallas=use_pallas, interpret=True
    )
    want = reference_step(*args)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5)


def test_sharded_step_gradients_match_reference():
    """The custom-vjp projection backward (three plain matmuls) and the
    collective-threaded gate math give the same gradients as the reference
    for every input."""
    mesh = _mesh_2d()
    args = _random_args(jax.random.PRNGKey(8), batch=4)

    def loss_sharded(*a):
        out = sharded_recurrent_step(
            *a, mesh=mesh, data_axis="data", use_pallas=True, interpret=True
        )
        return jnp.sum(jnp.square(out))

    def loss_ref(*a):
        return jnp.sum(jnp.square(reference_step(*a)))

    grads_sharded = jax.grad(loss_sharded, argnums=tuple(range(9)))(*args)
    grads_ref = jax.grad(loss_ref, argnums=tuple(range(9)))(*args)
    for gs, gr in zip(grads_sharded, grads_ref):
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gr), atol=1e-4, rtol=1e-4)


def test_sharded_step_rejects_indivisible_hidden():
    mesh = _mesh_2d()
    args = _random_args(jax.random.PRNGKey(9), batch=4, hidden=6)  # 6 % 4 != 0
    with pytest.raises(ValueError, match="must divide"):
        sharded_recurrent_step(*args, mesh=mesh, interpret=True)
