"""Commit manifests: a checkpoint exists iff its manifest does
(sheeprl_tpu/resilience/manifest.py). Covers the ISSUE satellites: manifest
round-trip on both backends, prune-by-manifest-step (not mtime), foreign
files skipped, torn writes garbage-collected."""

import json
import os
import time

import numpy as np
import pytest

from sheeprl_tpu.resilience.manifest import (
    MANIFEST_SUFFIX,
    TMP_PREFIX,
    build_manifest,
    checkpoint_step,
    committed_checkpoints,
    gc_torn,
    is_committed,
    read_manifest,
    torn_checkpoints,
    write_manifest,
)
from sheeprl_tpu.utils.callback import CheckpointCallback
from sheeprl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def _state(step=7):
    return {
        "agent": {"w": np.random.rand(4, 3).astype(np.float32), "b": np.zeros(3)},
        "update": step,
        "batch_size": 64,
    }


def _ckpt_name(step, rank=0):
    return f"ckpt_{step}_{rank}.ckpt"


def _save_committed(ckpt_dir, step, backend="pickle", batch_size=64, world_size=1):
    os.makedirs(ckpt_dir, exist_ok=True)
    state = _state(step)
    state["batch_size"] = batch_size
    path = os.path.join(ckpt_dir, _ckpt_name(step))
    man = build_manifest(step=step, backend=backend, world_size=world_size, state=state)
    save_checkpoint(path, state, backend=backend, manifest=man)
    return path


def test_checkpoint_step_parsing():
    assert checkpoint_step("ckpt_128_0.ckpt") == 128
    assert checkpoint_step("/a/b/ckpt_5_3.ckpt") == 5
    assert checkpoint_step("notes.txt") is None
    assert checkpoint_step("ckpt_abc_0.ckpt") is None
    assert checkpoint_step("ckpt_5.ckpt") is None  # missing rank


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_manifest_roundtrip(tmp_path, backend):
    """save_checkpoint(manifest=...) commits on both backends: the manifest
    lands last (sidecar / inside the promoted dir) and round-trips the
    step/backend/world-size/digest fields."""
    path = _save_committed(str(tmp_path), step=42, backend=backend, batch_size=96, world_size=2)
    assert is_committed(path)
    man = read_manifest(path)
    assert man["step"] == 42
    assert man["backend"] == backend
    assert man["world_size"] == 2
    assert man["batch_size"] == 96
    assert man["leaf_count"] > 0 and len(man["tree_digest"]) == 12
    # the payload itself still loads
    assert load_checkpoint(path)["update"] == 42
    # manifest location matches the backend layout
    if backend == "orbax":
        assert os.path.isfile(os.path.join(path, "manifest.json"))
    else:
        assert os.path.isfile(path + MANIFEST_SUFFIX)


def test_save_without_manifest_is_not_committed(tmp_path):
    path = str(tmp_path / _ckpt_name(3))
    save_checkpoint(path, _state(3))
    assert not is_committed(path)
    assert committed_checkpoints(str(tmp_path)) == []
    # writing the marker afterwards commits it
    write_manifest(path, build_manifest(step=3, backend="pickle", world_size=1))
    assert is_committed(path)
    assert [c.step for c in committed_checkpoints(str(tmp_path))] == [3]


def test_unparseable_manifest_is_not_committed(tmp_path):
    path = str(tmp_path / _ckpt_name(3))
    save_checkpoint(path, _state(3))
    with open(path + MANIFEST_SUFFIX, "w") as f:
        f.write("{ not json")
    assert read_manifest(path) is None and not is_committed(path)
    # valid json but no integer step -> still not committed
    with open(path + MANIFEST_SUFFIX, "w") as f:
        json.dump({"backend": "pickle"}, f)
    assert not is_committed(path)


def test_committed_checkpoints_order_and_foreign_skip(tmp_path):
    d = str(tmp_path)
    for step in (30, 2, 10):
        _save_committed(d, step)
    # a foreign file and an uncommitted checkpoint must not be enumerated
    (tmp_path / "notes.txt").write_text("keep me")
    save_checkpoint(os.path.join(d, _ckpt_name(99)), _state(99))
    out = committed_checkpoints(d)
    assert [c.step for c in out] == [2, 10, 30]  # oldest step first
    assert all(c.manifest["step"] == c.step for c in out)


def test_torn_detection_and_gc(tmp_path):
    d = str(tmp_path)
    good = _save_committed(d, 10)
    # torn entries: staging dir, stray .tmp file, our-naming ckpt without a
    # manifest, and an orphaned manifest sidecar
    os.makedirs(os.path.join(d, TMP_PREFIX + _ckpt_name(20)))
    (tmp_path / ".manifest-x.tmp").write_text("")
    save_checkpoint(os.path.join(d, _ckpt_name(30)), _state(30))
    write_manifest(
        os.path.join(d, _ckpt_name(40)), build_manifest(step=40, backend="pickle", world_size=1)
    )  # sidecar only: its checkpoint was never written
    # a foreign file is neither torn nor committed
    (tmp_path / "notes.txt").write_text("keep me")

    torn = torn_checkpoints(d)
    assert len(torn) == 4
    assert good not in torn and os.path.join(d, "notes.txt") not in torn

    removed = gc_torn(d)
    assert sorted(removed) == sorted(torn)
    assert os.path.exists(good) and is_committed(good)
    assert (tmp_path / "notes.txt").exists()
    assert torn_checkpoints(d) == []


def test_prune_keeps_newest_by_manifest_step_not_mtime(tmp_path):
    """The pre-resilience _prune sorted by mtime; clock skew could evict the
    newest checkpoint. Now only committed checkpoints count, ordered by
    manifest step, and unrecognized entries are untouched."""
    d = str(tmp_path)
    paths = {step: _save_committed(d, step) for step in (10, 2, 30)}
    # adversarial mtimes: the NEWEST step looks oldest on disk
    now = time.time()
    os.utime(paths[30], (now - 1000, now - 1000))
    os.utime(paths[30] + MANIFEST_SUFFIX, (now - 1000, now - 1000))
    os.utime(paths[2], (now, now))
    # a torn write and a foreign file sit in the same dir
    save_checkpoint(os.path.join(d, _ckpt_name(99)), _state(99))
    (tmp_path / "notes.txt").write_text("keep me")

    CheckpointCallback(keep_last=2)._prune(d)

    assert not os.path.exists(paths[2]) and not os.path.exists(paths[2] + MANIFEST_SUFFIX)
    assert os.path.exists(paths[10]) and os.path.exists(paths[30])
    assert (tmp_path / "notes.txt").exists()
    assert not os.path.exists(os.path.join(d, _ckpt_name(99)))  # torn -> GC'd
    assert [c.step for c in committed_checkpoints(d)] == [10, 30]


def test_prune_orbax_dirs_by_step(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3):
        _save_committed(d, step, backend="orbax")
    CheckpointCallback(keep_last=1, backend="orbax")._prune(d)
    left = committed_checkpoints(d)
    assert [c.step for c in left] == [3]
    assert not os.path.exists(os.path.join(d, _ckpt_name(1)))
