"""Non-finite sentinel + rollback manager unit tests
(sheeprl_tpu/resilience/sentinel.py, manager.py): jittable all_finite, the
superstep's fused [K] finite vector, deterministic fault injection, rollback
budget/restore/resalt semantics."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sheeprl_tpu.resilience import RunResilience, all_finite, host_all_finite, parse_nan_faults
from sheeprl_tpu.resilience.manifest import build_manifest
from sheeprl_tpu.utils.checkpoint import save_checkpoint


class _FakeFabric:
    num_processes = 1
    world_size = 1
    is_global_zero = True


def _cfg(**res):
    # preemption=False: unit tests must not install signal handlers
    return {"resilience": {"enabled": True, "preemption": False, **res}, "checkpoint": {}}


def test_all_finite_jittable():
    fn = jax.jit(all_finite)
    good = {"a": jnp.ones(3), "b": (jnp.zeros(2), jnp.arange(4))}  # ints ignored
    assert bool(fn(good))
    bad = {"a": jnp.ones(3).at[1].set(jnp.nan), "b": (jnp.zeros(2), jnp.arange(4))}
    assert not bool(fn(bad))
    assert not bool(fn({"x": jnp.asarray([1.0, jnp.inf])}))
    # integer-only trees are vacuously finite
    assert bool(fn({"count": jnp.arange(3)}))


def test_host_all_finite_nested():
    assert host_all_finite({"a": [1.0, 2.0], "b": {"c": np.ones(3)}})
    assert not host_all_finite({"a": [1.0, float("nan")]})
    assert not host_all_finite([np.asarray([np.inf])])
    # non-numeric leaves are ignored, integer arrays are always finite
    assert host_all_finite({"name": "run", "n": np.arange(5)})


def test_parse_nan_faults():
    assert parse_nan_faults({}) == set()
    assert parse_nan_faults({"fault_injection": {"enabled": False, "faults": [{"at_update": 1}]}}) == set()
    cfg = {"fault_injection": {"enabled": True, "faults": [{"kind": "nan", "at_update": 3}, {"at_update": 7}]}}
    assert parse_nan_faults(cfg) == {3, 7}
    with pytest.raises(ValueError, match="kind"):
        parse_nan_faults({"fault_injection": {"enabled": True, "faults": [{"kind": "crash", "at_update": 1}]}})
    with pytest.raises(ValueError, match="at_update"):
        parse_nan_faults({"fault_injection": {"enabled": True, "faults": [{"kind": "nan"}]}})
    with pytest.raises(ValueError, match="mappings"):
        parse_nan_faults({"fault_injection": {"enabled": True, "faults": ["nan@3"]}})


def test_superstep_finite_vector():
    """check_finite=True appends a [K] per-step finite vector to the fused
    scan's outputs: once a NaN enters the params, every later step reports
    non-finite too (the window verdict the dreamer loop reduces)."""
    from sheeprl_tpu.ops.superstep import make_superstep_fn

    def train_body(params, aux, batch, key):
        params = params + batch
        return params, aux, {"loss": params}

    superstep = make_superstep_fn(
        train_body, lambda ctx, key, i: ctx[i], num_steps=3, check_finite=True
    )
    ctx = jnp.asarray([1.0, jnp.nan, 1.0])
    params, aux, key, metrics, finite = superstep(
        jnp.asarray(0.0), jnp.asarray(0.0), 0, ctx, jax.random.PRNGKey(0)
    )
    assert finite.shape == (3,)
    assert list(np.asarray(finite)) == [True, False, False]
    assert not np.isfinite(np.asarray(params))

    # all-finite context: the vector is all True and params stay finite
    _, _, _, _, finite_ok = superstep(
        jnp.asarray(0.0), jnp.asarray(0.0), 0, jnp.ones(3), jax.random.PRNGKey(0)
    )
    assert np.asarray(finite_ok).all()


def test_check_finite_and_fault_injection(tmp_path):
    resil = RunResilience(
        _FakeFabric(),
        _cfg(fault_injection={"enabled": True, "faults": [{"kind": "nan", "at_update": 3}]}),
        str(tmp_path),
    )
    assert resil.check_finite({"loss": 1.0}, update=1)
    assert not resil.check_finite({"loss": float("nan")}, update=2)
    # injected fault fires exactly once at its update
    with pytest.warns(UserWarning, match="fault_injection"):
        assert not resil.check_finite({"loss": 1.0}, update=3)
    assert resil.check_finite({"loss": 1.0}, update=3)
    # window_ok shares the same schedule for loops with an on-device verdict
    assert resil.window_ok(True, update=4)
    assert not resil.window_ok(False, update=4)


def test_disabled_sentinel_is_inert(tmp_path):
    resil = RunResilience(_FakeFabric(), _cfg(check_finite=False), str(tmp_path))
    assert resil.check_finite({"loss": float("nan")}, update=1)
    assert resil.window_ok(False, update=1)


def test_rollback_budget_exhausted(tmp_path):
    resil = RunResilience(_FakeFabric(), _cfg(max_rollbacks=0), str(tmp_path))
    with pytest.raises(RuntimeError, match="max_rollbacks"):
        resil.rollback(update=5)


def test_rollback_without_checkpoint(tmp_path):
    resil = RunResilience(_FakeFabric(), _cfg(max_rollbacks=2), str(tmp_path))
    with pytest.raises(RuntimeError, match="no committed checkpoint"):
        resil.rollback(update=5)


def test_rollback_restores_newest_committed_and_resalts(tmp_path):
    ckpt_dir = os.path.join(str(tmp_path), "checkpoint")
    os.makedirs(ckpt_dir)
    for step, val in ((64, 1.0), (128, 2.0)):
        state = {"agent": {"w": np.full(3, val, np.float32)}, "update": step // 64}
        save_checkpoint(
            os.path.join(ckpt_dir, f"ckpt_{step}_0.ckpt"),
            state,
            manifest=build_manifest(step=step, backend="pickle", world_size=1, state=state),
        )
    # a torn newer write must NOT win over the committed ones
    save_checkpoint(os.path.join(ckpt_dir, "ckpt_192_0.ckpt"), {"agent": {"w": np.zeros(3)}})

    resil = RunResilience(_FakeFabric(), _cfg(max_rollbacks=2), str(tmp_path))
    with pytest.warns(UserWarning, match="rolled back"):
        restored = resil.rollback(update=9)
    np.testing.assert_array_equal(restored["agent"]["w"], np.full(3, 2.0, np.float32))
    assert resil.rollbacks == 1

    # the restored key is forked away from the stream that produced the NaN
    key = jax.random.PRNGKey(0)
    resalted = resil.resalt_key(key)
    assert not np.array_equal(np.asarray(key), np.asarray(resalted))

    # place_like puts host arrays back under the live leaves' placements
    live = {"w": jnp.zeros(3)}
    placed = resil.place_like(restored["agent"], live)
    assert isinstance(placed["w"], jax.Array)
    np.testing.assert_array_equal(np.asarray(placed["w"]), np.full(3, 2.0, np.float32))

    # second rollback exhausts the budget
    with pytest.warns(UserWarning, match="rolled back"):
        resil.rollback(update=10)
    with pytest.raises(RuntimeError, match="max_rollbacks"):
        resil.rollback(update=11)
