"""Preemption-resume with the AOT executable cache (ISSUE 17 acceptance):
SIGTERM a fused Dreamer-V3 run AFTER its superstep executable has been
committed to ``fabric.aot_cache_dir``, auto-resume the run, and prove the
resumed process deserialized the fused-window executable — ``aot_cache_hits
>= 1`` and ``recompiles == 0`` in its run_end telemetry — instead of paying
the compile again."""

import glob
import json
import os
import subprocess
import sys

import pytest

from sheeprl_tpu.resilience import PREEMPTED_EXIT_CODE

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def drill_args(tmp_path):
    """A tiny fused Dreamer-V3 run (the make_fused_train_fn path — the one
    wired to fabric.aot_cache): 4 train windows on dummy envs, run_name
    pinned for auto-resume."""
    return [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=dummy_discrete",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=1",
        "algo.learning_starts=2",
        "algo.replay_ratio=1",
        "algo.horizon=8",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "env.screen_size=16",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=0",
        "metric.telemetry.enabled=True",
        "metric.telemetry.poll_interval=0.0",
        "run_name=aot_drill",
        f"log_base_dir={tmp_path}/logs",
        "fabric.devices=1",
        "buffer.device=True",
        "buffer.size=64",
        "algo.total_steps=16",
        "algo.fused_gradient_steps=256",
        f"fabric.aot_cache_dir={tmp_path}/aotcache",
    ]


def _child_env():
    """Subprocess env with REAL compiles: the suite-wide XLA persistent
    trace cache (tests/conftest.py) would make every compiled executable
    serialize into an unloadable payload (CPU backend), which AotCache's
    store-time verification rejects — the drill needs committed entries."""
    env = dict(os.environ)
    env.pop("JAX_COMPILATION_CACHE_DIR", None)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_end_events(tmp_path):
    events = []
    for path in glob.glob(os.path.join(str(tmp_path), "logs", "**", "telemetry.jsonl"), recursive=True):
        with open(path) as f:
            for line in f:
                if line.strip():
                    e = json.loads(line)
                    if e.get("event") in ("run_end", "auto_resume", "preempt"):
                        events.append(e)
    return events


@pytest.mark.slow
def test_preemption_resume_reuses_cached_superstep(tmp_path):
    cache_dir = f"{tmp_path}/aotcache"
    args = drill_args(tmp_path)
    # SIGTERM only once BOTH fused-window signatures (the ratio bookkeeping
    # compiles two window lengths) are COMMITTED to the cache — whichever
    # window length the resumed run opens with, its executable is there.
    # The async writer promotes entries moments after each window's compile,
    # well before the 16-step run can finish.
    child = f"""
import glob, os, signal
import sheeprl_tpu.resilience.manager as M
orig = M.RunResilience.preempt_requested
fired = [False]
def patched(self):
    if not fired[0] and len(glob.glob(os.path.join({cache_dir!r}, "*.aotx"))) >= 2:
        fired[0] = True
        os.kill(os.getpid(), signal.SIGTERM)
    return orig(self)
M.RunResilience.preempt_requested = patched
from sheeprl_tpu.cli import run
run({args!r})
raise SystemExit(0)
"""
    proc = subprocess.run(
        [sys.executable, "-c", child],
        cwd=str(tmp_path),
        env=_child_env(),
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == PREEMPTED_EXIT_CODE, (
        f"expected exit {PREEMPTED_EXIT_CODE}, got {proc.returncode}\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}"
    )
    entries = glob.glob(os.path.join(cache_dir, "*.aotx"))
    assert len(entries) >= 2, f"preempted run committed {entries}, expected both signatures"
    assert any(e["event"] == "preempt" for e in _run_end_events(tmp_path))

    # --- resume: same invocation + resume_from=auto, fresh process — the
    # cold path the cache exists for. It must deserialize, not recompile.
    resume = f"""
from sheeprl_tpu.cli import run
run({args!r} + ["checkpoint.resume_from=auto"])
"""
    proc = subprocess.run(
        [sys.executable, "-c", resume],
        cwd=str(tmp_path),
        env=_child_env(),
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == 0, (
        f"resume failed rc={proc.returncode}\n"
        f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}"
    )
    events = _run_end_events(tmp_path)
    assert any(e["event"] == "auto_resume" for e in events)
    run_ends = [e for e in events if e["event"] == "run_end"]
    assert run_ends, "resumed run wrote no run_end telemetry"
    resumed = run_ends[-1]
    # the acceptance bar: the fused-window executable came from the cache,
    # and the resumed run never recompiled anything post-warmup
    assert resumed.get("aot_cache_hits", 0) >= 1, resumed
    assert resumed.get("aot_cache_errors", 0) == 0, resumed
    assert resumed.get("recompiles") == 0, resumed
