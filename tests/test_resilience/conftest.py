"""Shared fixtures for the resilience suite."""

import pytest


@pytest.fixture(autouse=True)
def _fresh_async_writer():
    """The async checkpoint writer is a process-wide singleton; every test
    must start with no in-flight save and no accumulated counters."""
    import sheeprl_tpu.resilience.async_writer as aw

    aw.drain_async_checkpoints(timeout=30.0)
    with aw._writer_lock:
        aw._writer = None
    yield
    aw.drain_async_checkpoints(timeout=30.0)
    with aw._writer_lock:
        aw._writer = None


@pytest.fixture(autouse=True)
def _no_queued_resilience_events():
    """Auto-resume queues telemetry events module-side until cli.run_algorithm
    flushes them; don't let one test's queue leak into the next."""
    from sheeprl_tpu.resilience import autoresume

    autoresume._pending_events.clear()
    yield
    autoresume._pending_events.clear()
