"""CLI acceptance drills (ISSUE acceptance criteria): SIGTERM mid-run lands a
committed emergency checkpoint and exits 77, resume_from=auto continues at the
saved step; an injected NaN triggers exactly one rollback and the run still
completes; async saves block the loop for the snapshot span only."""

import json
import os
import subprocess
import sys

from sheeprl_tpu.cli import run
from sheeprl_tpu.resilience import PREEMPTED_EXIT_CODE, committed_checkpoints, read_manifest
from sheeprl_tpu.utils.checkpoint import load_checkpoint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 4 updates of 64 policy steps each (2 envs x 32 rollout steps) on tiny nets;
# run_name is PINNED because the default carries a ${now:...} timestamp and
# auto-resume scans <log_base_dir>/<root_dir>/<run_name>
def drill_args(tmp_path):
    return [
        "exp=ppo",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.total_steps=256",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=1",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=0",
        "metric.telemetry.enabled=True",
        "metric.telemetry.poll_interval=0.0",
        "run_name=drill",
        f"log_base_dir={tmp_path}/logs",
    ]


def _telemetry_events(tmp_path):
    for root, _, files in os.walk(tmp_path):
        if "telemetry.jsonl" in files:
            with open(os.path.join(root, "telemetry.jsonl")) as f:
                return [json.loads(line) for line in f if line.strip()], os.path.join(
                    root, "telemetry.jsonl"
                )
    return [], None


def _ckpt_dirs(tmp_path):
    out = []
    for root, dirs, _ in os.walk(tmp_path):
        out += [os.path.join(root, d) for d in dirs if d == "checkpoint"]
    return out


def _bench():
    sys.path.insert(0, REPO_ROOT)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_sigterm_drill_and_auto_resume(tmp_path, monkeypatch):
    """Preemption end to end, in a real subprocess: SIGTERM at the update-2
    boundary -> drained async saves, committed emergency checkpoint of update
    1, exit code 77; then resume_from=auto finds it and finishes the run."""
    args = drill_args(tmp_path) + ["checkpoint.every=0"]
    # deliver a REAL SIGTERM to the child at its second train-loop boundary:
    # the handler sets the flag, the poll returns True, and the run drains
    child = f"""
import os, signal
import sheeprl_tpu.resilience.manager as M
orig = M.RunResilience.preempt_requested
count = [0]
def patched(self):
    count[0] += 1
    if count[0] == 2:
        os.kill(os.getpid(), signal.SIGTERM)
    return orig(self)
M.RunResilience.preempt_requested = patched
from sheeprl_tpu.cli import run
run({args!r})
raise SystemExit(0)
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-c", child],
        cwd=str(tmp_path),
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )
    assert proc.returncode == PREEMPTED_EXIT_CODE, (
        f"expected exit {PREEMPTED_EXIT_CODE}, got {proc.returncode}\n"
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
    )

    (ckpt_dir,) = _ckpt_dirs(tmp_path)
    (emergency,) = committed_checkpoints(ckpt_dir)
    assert emergency.step == 64  # policy step at the update-2 boundary
    assert read_manifest(emergency.path)["emergency"] is True
    saved = load_checkpoint(emergency.path)
    assert saved["update"] == 1  # update 2 never ran

    events, _ = _telemetry_events(tmp_path)
    assert any(e["event"] == "preempt" for e in events)
    commits = [e for e in events if e["event"] == "ckpt_committed"]
    assert len(commits) == 1 and commits[0]["emergency"]

    # --- auto-resume: same invocation + resume_from=auto picks the emergency
    # checkpoint (same pinned run_name) and continues from update 2
    monkeypatch.chdir(tmp_path)
    run(args + ["checkpoint.resume_from=auto"])

    finals = [
        c for d in _ckpt_dirs(tmp_path) for c in committed_checkpoints(d) if c.step == 256
    ]
    assert finals, "resumed run did not reach the final checkpoint"
    assert load_checkpoint(finals[0].path)["update"] == 4

    events, jsonl = _telemetry_events(tmp_path)
    resumed = [e for e in events if e["event"] == "auto_resume"]
    assert len(resumed) == 1
    assert resumed[0]["path"] == emergency.path and resumed[0]["ckpt_step"] == 64

    # bench --resilience-stats digests the drill without log scraping
    stats = _bench().resilience_stats(jsonl)
    assert stats["totals"]["preemptions"] == 1
    assert 64 in stats["emergency_steps"]
    assert stats["auto_resume"][0]["ckpt_step"] == 64


def test_nan_drill_one_rollback_run_completes(tmp_path, monkeypatch):
    """Deterministic NaN injection at update 3: exactly one nan_rollback
    event, the state restored from the update-2 checkpoint, and the run still
    completes all 4 updates (ISSUE acceptance)."""
    monkeypatch.chdir(tmp_path)
    args = drill_args(tmp_path) + [
        "checkpoint.every=64",
        "checkpoint.async_save=False",  # the rollback point must be committed before update 3
        "resilience.fault_injection.enabled=True",
        "resilience.fault_injection.faults=[{kind: nan, at_update: 3}]",
    ]
    run(args)  # must not raise: the rollback keeps the run alive

    events, jsonl = _telemetry_events(tmp_path)
    rollbacks = [e for e in events if e["event"] == "nan_rollback"]
    assert len(rollbacks) == 1
    assert rollbacks[0]["update"] == 3
    assert rollbacks[0]["remaining"] == 2  # default budget 3, one spent
    restored_step = read_manifest(rollbacks[0]["path"])["step"]
    assert restored_step == 128  # the update-2 checkpoint

    run_end = [e for e in events if e["event"] == "run_end"][-1]
    assert run_end["nan_rollbacks"] == 1

    # evidence engine (ISSUE acceptance): the rollback dumped the flight
    # recorder next to telemetry.jsonl — one valid JSON document, bounded
    # ring, the nan_rollback trigger event LAST among its events
    flight_path = os.path.join(os.path.dirname(jsonl), "flightrec.json")
    assert os.path.exists(flight_path)
    with open(flight_path) as f:
        flight = json.load(f)
    assert flight["trigger"] == "nan_rollback"
    assert len(flight["events"]) <= flight["ring_capacity"]
    assert flight["events"][-1]["event"] == "nan_rollback"
    assert flight["events"][-1]["update"] == 3

    # the run completed: the save_last checkpoint carries the final update
    finals = [
        c for d in _ckpt_dirs(tmp_path) for c in committed_checkpoints(d) if c.step == 256
    ]
    assert finals and load_checkpoint(finals[0].path)["update"] == 4

    stats = _bench().resilience_stats(jsonl)
    assert stats["totals"]["nan_rollbacks"] == 1
    assert stats["nan_rollbacks"][0]["update"] == 3


def test_async_save_blocks_snapshot_only(tmp_path, monkeypatch):
    """checkpoint.async_save=True: every periodic save shows up as a blocking
    ckpt/snapshot span plus a background ckpt/write span (async: no sync
    attr), and commits equal the checkpoints on disk (ISSUE acceptance: the
    loop pays snapshot time only, asserted via span durations)."""
    monkeypatch.chdir(tmp_path)
    run(drill_args(tmp_path) + ["checkpoint.every=64", "checkpoint.async_save=True"])

    events, jsonl = _telemetry_events(tmp_path)
    snapshots = [e for e in events if e["event"] == "span" and e["name"] == "ckpt/snapshot"]
    writes = [e for e in events if e["event"] == "span" and e["name"] == "ckpt/write"]
    assert snapshots, "async saves must emit the blocking ckpt/snapshot span"
    assert writes, "async saves must emit the background ckpt/write span"
    assert all(e["dur"] >= 0 for e in snapshots + writes)
    # the loop-blocking part is the snapshot; the write rode the background
    # thread (async writes carry no sync attr)
    assert any(not (e.get("attrs") or {}).get("sync") for e in writes)

    committed = [c for d in _ckpt_dirs(tmp_path) for c in committed_checkpoints(d)]
    commits = [e for e in events if e["event"] == "ckpt_committed"]
    skips = [e for e in events if e["event"] == "ckpt_skipped"]
    assert len(commits) == len(committed) and commits
    # every periodic boundary either committed or was accounted as skipped
    assert len(commits) + len(skips) == 4

    stats = _bench().resilience_stats(jsonl)
    assert stats["snapshot"]["count"] == len(snapshots)
    assert stats["write"]["async_count"] >= 1
    assert stats["totals"]["ckpt_commits"] == len(commits)
