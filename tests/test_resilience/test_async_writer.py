"""Async checkpoint writer: at-most-one save in flight, dropped overlaps,
snapshot isolation through the CheckpointCallback async path
(sheeprl_tpu/resilience/async_writer.py)."""

import os
import threading

import numpy as np
import pytest

from sheeprl_tpu.resilience import drain_async_checkpoints, get_async_writer
from sheeprl_tpu.resilience.async_writer import AsyncCheckpointWriter
from sheeprl_tpu.resilience.manifest import is_committed, read_manifest
from sheeprl_tpu.utils.callback import CheckpointCallback
from sheeprl_tpu.utils.checkpoint import load_checkpoint


class _FakeFabric:
    num_processes = 1
    world_size = 1
    is_global_zero = True


def test_single_inflight_skip_and_drain():
    w = AsyncCheckpointWriter()
    release = threading.Event()
    done = []

    def slow_write():
        release.wait(timeout=30)
        done.append(True)

    assert w.submit(slow_write, path="a.ckpt", step=1) is True
    assert w.busy
    # overlapping request: dropped, accounted, never queued
    assert w.submit(lambda: done.append("overlap"), path="b.ckpt", step=2) is False
    assert w.skipped == 1 and w.submitted == 1
    release.set()
    assert w.drain(timeout=30) is True
    assert done == [True]
    # idle again: the next submit goes through
    assert w.submit(lambda: done.append("next"), path="c.ckpt", step=3) is True
    assert w.drain(timeout=30)
    assert done == [True, "next"]
    assert w.submitted == 2


def test_record_skip_without_submit():
    w = AsyncCheckpointWriter()
    w.record_skip(path="x.ckpt", step=5)
    assert w.skipped == 1


def test_write_error_never_raises():
    w = AsyncCheckpointWriter()

    def boom():
        raise OSError("disk full")

    with pytest.warns(UserWarning, match="disk full"):
        assert w.submit(boom, path="bad.ckpt", step=1) is True
        assert w.drain(timeout=30) is True
    assert isinstance(w.last_error, OSError)
    # the writer survives a failed write
    ok = []
    assert w.submit(lambda: ok.append(1), path="good.ckpt", step=2) is True
    assert w.drain(timeout=30) and ok == [1]


@pytest.mark.parametrize("backend", ["pickle", "orbax"])
def test_callback_async_snapshot_isolation(tmp_path, backend):
    """The hook snapshots state to host BEFORE returning: mutating the live
    tree after the hook must not leak into the checkpoint the background
    thread serializes (the async correctness property)."""
    cb = CheckpointCallback(backend=backend, async_save=True)
    state = {"agent": {"w": np.ones((4, 3), np.float32)}, "update": 1, "batch_size": 8}
    path = str(tmp_path / "ckpt_64_0.ckpt")
    cb.on_checkpoint_coupled(_FakeFabric(), path, state)
    # the env/train loop keeps going while the write is in flight
    state["agent"]["w"] *= 0.0
    assert drain_async_checkpoints(timeout=60)
    assert is_committed(path)
    man = read_manifest(path)
    assert man["step"] == 64 and man["backend"] == backend and not man.get("emergency")
    out = load_checkpoint(path)
    np.testing.assert_array_equal(out["agent"]["w"], np.ones((4, 3), np.float32))


def test_callback_busy_writer_drops_save(tmp_path):
    """A checkpoint request that lands while a write is in flight is dropped
    before paying for a snapshot — and nothing is written for it."""
    writer = get_async_writer()
    release = threading.Event()
    writer.submit(lambda: release.wait(timeout=30), path="inflight.ckpt", step=1)
    try:
        cb = CheckpointCallback(async_save=True)
        path = str(tmp_path / "ckpt_128_0.ckpt")
        cb.on_checkpoint_coupled(_FakeFabric(), path, {"update": 2})
        assert writer.skipped == 1
        assert not os.path.exists(path)
    finally:
        release.set()
        writer.drain(timeout=30)


def test_callback_async_buffer_snapshot_restores_live_flags(tmp_path):
    """The truncated-flag fixup must be undone by the time the hook returns
    (not when the background write finishes), and the SAVED copy keeps it."""
    from sheeprl_tpu.data.buffers import ReplayBuffer

    rb = ReplayBuffer(8, n_envs=2, seed=0)
    rb.add(
        {
            "observations": np.zeros((3, 2, 4), np.float32),
            "terminated": np.zeros((3, 2, 1), np.float32),
            "truncated": np.zeros((3, 2, 1), np.float32),
        }
    )
    cb = CheckpointCallback(async_save=True)
    path = str(tmp_path / "ckpt_32_0.ckpt")
    cb.on_checkpoint_coupled(_FakeFabric(), path, {"update": 1}, replay_buffer=rb)
    # live buffer already restored, even if the write is still in flight
    assert rb["truncated"][(rb._pos - 1) % rb.buffer_size].sum() == 0
    assert drain_async_checkpoints(timeout=60)
    saved = load_checkpoint(path)["rb"]
    assert saved["truncated"][(saved._pos - 1) % saved.buffer_size].sum() == 2


def test_emergency_save_is_synchronous(tmp_path):
    """emergency=True bypasses the background writer entirely: the checkpoint
    is committed (manifest flagged) by the time the hook returns."""
    cb = CheckpointCallback(async_save=True)
    path = str(tmp_path / "ckpt_96_0.ckpt")
    cb.on_checkpoint_coupled(_FakeFabric(), path, {"update": 3}, emergency=True)
    assert is_committed(path)  # no drain needed
    assert read_manifest(path)["emergency"] is True
    assert get_async_writer().submitted == 0
