"""checkpoint.resume_from=auto resolution (sheeprl_tpu/resilience/autoresume.py):
newest valid committed checkpoint wins, corrupted/mesh-incompatible candidates
fall back to the next-newest with a queued resume_fallback event, no candidate
starts fresh."""

import os

import numpy as np
import pytest

from sheeprl_tpu.resilience import resolve_auto_resume, scan_run_checkpoints
from sheeprl_tpu.resilience.autoresume import _pending_events
from sheeprl_tpu.resilience.manifest import build_manifest
from sheeprl_tpu.utils.checkpoint import save_checkpoint


def _cfg(tmp_path, devices=1):
    return {
        "root_dir": "ppo/Cart",
        "run_name": "drill",
        "log_base_dir": str(tmp_path / "logs"),
        "fabric": {"devices": devices},
        "checkpoint": {"resume_from": "auto"},
    }


def _run_root(tmp_path):
    return os.path.join(str(tmp_path), "logs", "ppo", "Cart", "drill")


def _add_ckpt(tmp_path, version, step, batch_size=8, with_config=True):
    vdir = os.path.join(_run_root(tmp_path), f"version_{version}")
    ckpt_dir = os.path.join(vdir, "checkpoint")
    os.makedirs(ckpt_dir, exist_ok=True)
    if with_config:
        with open(os.path.join(vdir, "config.yaml"), "w") as f:
            f.write("env:\n  id: CartPole-v1\n")
    state = {
        "agent": {"w": np.full(3, float(step), np.float32)},
        "update": step // 64,
        "batch_size": batch_size,
    }
    path = os.path.join(ckpt_dir, f"ckpt_{step}_0.ckpt")
    save_checkpoint(
        path, state, manifest=build_manifest(step=step, backend="pickle", world_size=1, state=state)
    )
    return path


def test_auto_resume_picks_newest_across_versions(tmp_path):
    _add_ckpt(tmp_path, 0, 64)
    _add_ckpt(tmp_path, 0, 128)
    newest = _add_ckpt(tmp_path, 1, 192)
    assert resolve_auto_resume(_cfg(tmp_path)) == newest
    kinds = [k for k, _ in _pending_events]
    assert kinds == ["auto_resume"]
    assert _pending_events[0][1]["ckpt_step"] == 192
    assert _pending_events[0][1]["candidates"] == 3


def test_auto_resume_skips_corrupted_newest(tmp_path):
    older = _add_ckpt(tmp_path, 0, 64)
    newest = _add_ckpt(tmp_path, 0, 128)
    # torn-at-the-payload corruption that still carries a manifest: the
    # validation load must reject it and fall back
    with open(newest, "wb") as f:
        f.write(b"\x00garbage")
    with pytest.warns(UserWarning, match="falling back"):
        assert resolve_auto_resume(_cfg(tmp_path)) == older
    kinds = [k for k, _ in _pending_events]
    assert kinds == ["resume_fallback", "auto_resume"]
    assert _pending_events[0][1]["path"] == newest


def test_auto_resume_mesh_mismatch_falls_back(tmp_path):
    older = _add_ckpt(tmp_path, 0, 64, batch_size=8)
    _add_ckpt(tmp_path, 0, 128, batch_size=3)  # 3 does not split over 2 devices
    with pytest.warns(UserWarning, match="falling back"):
        assert resolve_auto_resume(_cfg(tmp_path, devices=2)) == older
    assert [k for k, _ in _pending_events] == ["resume_fallback", "auto_resume"]


def test_auto_resume_requires_config_yaml(tmp_path):
    older = _add_ckpt(tmp_path, 0, 64)
    newest = _add_ckpt(tmp_path, 1, 128, with_config=False)
    with pytest.warns(UserWarning, match="config.yaml"):
        assert resolve_auto_resume(_cfg(tmp_path)) == older
    assert _pending_events[0][1]["path"] == newest


def test_auto_resume_no_candidates_starts_fresh(tmp_path):
    with pytest.warns(UserWarning, match="fresh run"):
        assert resolve_auto_resume(_cfg(tmp_path)) is None
    assert _pending_events == []


def test_auto_resume_all_rejected_starts_fresh(tmp_path):
    bad = _add_ckpt(tmp_path, 0, 64)
    with open(bad, "wb") as f:
        f.write(b"nope")
    with pytest.warns(UserWarning, match="rejected"):
        assert resolve_auto_resume(_cfg(tmp_path)) is None


def test_scan_ignores_uncommitted_and_gcs_torn(tmp_path):
    good = _add_ckpt(tmp_path, 0, 64)
    ckpt_dir = os.path.dirname(good)
    torn = os.path.join(ckpt_dir, "ckpt_128_0.ckpt")
    save_checkpoint(torn, {"agent": {"w": np.zeros(3)}})  # no manifest
    os.makedirs(os.path.join(ckpt_dir, ".tmp-ckpt_192_0.ckpt"))
    with pytest.warns(UserWarning, match="garbage-collected"):
        found = scan_run_checkpoints(_run_root(tmp_path))
    assert [c.step for c in found] == [64]
    assert not os.path.exists(torn)
    assert not os.path.exists(os.path.join(ckpt_dir, ".tmp-ckpt_192_0.ckpt"))
