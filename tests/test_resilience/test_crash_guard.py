"""Crash guard (RunResilience.arm_crash_guard + cli crash_drain): an
unhandled train-loop exception drains the async writer, commits an emergency
checkpoint through the normal callback path and re-raises — so a crashed run
restarts with checkpoint.resume_from=auto exactly like a preempted one."""

import json
import os

import pytest

from sheeprl_tpu.resilience import RunResilience, committed_checkpoints, crash_drain, read_manifest
from sheeprl_tpu.resilience import manager as manager_mod
from sheeprl_tpu.utils.checkpoint import load_checkpoint


class _FakeFabric:
    num_processes = 1
    world_size = 1
    is_global_zero = True

    def __init__(self):
        self.calls = []

    def call(self, hook, **kwargs):
        self.calls.append((hook, kwargs))


def _cfg(**res):
    # preemption=False: unit tests must not install signal handlers
    return {"resilience": {"enabled": True, "preemption": False, **res}, "checkpoint": {}}


@pytest.fixture(autouse=True)
def _clean_guard():
    yield
    manager_mod._ARMED_GUARD = None


def test_crash_drain_unarmed_is_noop(tmp_path):
    assert crash_drain(RuntimeError("boom")) is None


def test_crash_checkpoint_saves_once_and_disarms(tmp_path):
    fabric = _FakeFabric()
    resil = RunResilience(fabric, _cfg(), str(tmp_path))
    state = {"agent": {"w": 1.0}, "update": 4}
    resil.arm_crash_guard(
        path_fn=lambda: str(tmp_path / "ckpt_64_0.ckpt"),
        state_fn=lambda: state,
    )
    path = crash_drain(RuntimeError("boom"))
    assert path == str(tmp_path / "ckpt_64_0.ckpt")
    assert fabric.calls == [
        (
            "on_checkpoint_coupled",
            {"ckpt_path": path, "state": state, "replay_buffer": None, "emergency": True},
        )
    ]
    # at-most-once: the guard disarmed itself
    assert crash_drain(RuntimeError("again")) is None
    assert len(fabric.calls) == 1


def test_crash_guard_config_gated(tmp_path):
    fabric = _FakeFabric()
    resil = RunResilience(fabric, _cfg(crash_checkpoint=False), str(tmp_path))
    resil.arm_crash_guard(path_fn=lambda: "x", state_fn=lambda: {})
    assert crash_drain(RuntimeError("boom")) is None
    assert fabric.calls == []


def test_crash_guard_never_masks_the_original_error(tmp_path):
    """A failing state_fn (e.g. NameError on a not-yet-bound loop variable)
    is swallowed with a warning — the crash path must stay silent."""
    resil = RunResilience(_FakeFabric(), _cfg(), str(tmp_path))
    resil.arm_crash_guard(
        path_fn=lambda: "x",
        state_fn=lambda: (_ for _ in ()).throw(NameError("update")),
    )
    with pytest.warns(UserWarning, match="emergency checkpoint failed"):
        assert crash_drain(RuntimeError("boom")) is None


def test_crash_guard_skips_save_on_multiprocess(tmp_path):
    """One crashing rank cannot enter the save collectives alone — only the
    async-writer drain runs, no checkpoint call."""
    fabric = _FakeFabric()
    fabric.num_processes = 2
    resil = RunResilience(fabric, _cfg(), str(tmp_path))
    resil.arm_crash_guard(path_fn=lambda: "x", state_fn=lambda: {})
    with pytest.warns(UserWarning, match="multi-process"):
        assert crash_drain(RuntimeError("boom")) is None
    assert fabric.calls == []


def test_close_disarms(tmp_path):
    resil = RunResilience(_FakeFabric(), _cfg(), str(tmp_path))
    resil.arm_crash_guard(path_fn=lambda: "x", state_fn=lambda: {})
    resil.close()
    assert crash_drain(RuntimeError("boom")) is None


def test_crash_drill_emergency_save_and_auto_resume(tmp_path, monkeypatch):
    """End to end, in process: a RuntimeError injected at the update-2
    boundary propagates out of cli.run (the crash guard does NOT eat it), a
    committed emergency checkpoint of update 1 lands, and resume_from=auto
    continues the run to completion."""
    from tests.test_resilience.test_drills import _ckpt_dirs, _telemetry_events, drill_args

    from sheeprl_tpu.cli import run

    monkeypatch.chdir(tmp_path)
    args = drill_args(tmp_path) + ["checkpoint.every=0"]

    orig = RunResilience.preempt_requested
    count = [0]

    def exploding_poll(self):
        count[0] += 1
        if count[0] == 2:
            raise RuntimeError("injected train-loop crash")
        return orig(self)

    monkeypatch.setattr(RunResilience, "preempt_requested", exploding_poll)
    with pytest.raises(RuntimeError, match="injected train-loop crash"):
        run(args)
    monkeypatch.setattr(RunResilience, "preempt_requested", orig)

    (ckpt_dir,) = _ckpt_dirs(tmp_path)
    (emergency,) = committed_checkpoints(ckpt_dir)
    assert emergency.step == 64  # policy step at the update-2 boundary
    assert read_manifest(emergency.path)["emergency"] is True
    assert load_checkpoint(emergency.path)["update"] == 1  # update 2 never ran

    events, jsonl = _telemetry_events(tmp_path)
    crashes = [e for e in events if e["event"] == "crash_checkpoint"]
    assert len(crashes) == 1
    assert crashes[0]["path"] == emergency.path
    assert "injected train-loop crash" in crashes[0]["error"]
    run_end = [e for e in events if e["event"] == "run_end"][-1]
    assert run_end["crash_checkpoints"] == 1

    # the crash-guard path also dumped the flight recorder (evidence engine):
    # the crash_checkpoint event is the newest thing in the ring
    with open(os.path.join(os.path.dirname(jsonl), "flightrec.json")) as f:
        flight = json.load(f)
    assert flight["trigger"] == "crash"
    assert flight["events"][-1]["event"] == "crash_checkpoint"
    assert len(flight["events"]) <= flight["ring_capacity"]

    # the crashed run restarts exactly like a preempted one
    run(args + ["checkpoint.resume_from=auto"])
    finals = [
        c for d in _ckpt_dirs(tmp_path) for c in committed_checkpoints(d) if c.step == 256
    ]
    assert finals and load_checkpoint(finals[0].path)["update"] == 4
