"""Worker-process hygiene: TPU/coordinator env sanitization and the
capture_video single-recorder guarantee under every backend."""

import os

import gymnasium as gym
import numpy as np

from sheeprl_tpu.envs import build_vector_env
from sheeprl_tpu.rollout import EnvPool, PoolConfig

from .conftest import toy_cfg


def test_worker_environ_is_sanitized_and_parent_restored(monkeypatch):
    # pose as a TPU learner mid-distributed-init: the worker must see none of
    # this (JAX pinned to cpu, coordinator vars stripped, worker marker set)
    monkeypatch.setenv("SHEEPRL_TPU_COORDINATOR", "10.0.0.1:8476")
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.1:8476")

    class EnvProbe(gym.Env):
        """Reports the environ it was constructed under as its observation."""

        observation_space = gym.spaces.Dict({"state": gym.spaces.Box(0.0, 1.0, (4,), np.float32)})
        action_space = gym.spaces.Discrete(2)

        def __init__(self):
            self._flags = np.array(
                [
                    os.environ.get("JAX_PLATFORMS") == "cpu",
                    "SHEEPRL_TPU_COORDINATOR" not in os.environ,
                    "JAX_COORDINATOR_ADDRESS" not in os.environ,
                    os.environ.get("SHEEPRL_TPU_ENV_WORKER") == "1",
                ],
                dtype=np.float32,
            )

        def reset(self, *, seed=None, options=None):
            return {"state": self._flags.copy()}, {}

        def step(self, action):
            return {"state": self._flags.copy()}, 0.0, False, False, {}

    envs = EnvPool([EnvProbe, EnvProbe], config=PoolConfig(num_workers=1))
    try:
        obs, _ = envs.reset(seed=0)
        assert obs["state"].shape == (2, 4)
        assert np.all(obs["state"] == 1.0), f"worker environ not sanitized: {obs['state']}"
    finally:
        envs.close()
    # the sanitized window is scoped to Process.start(): the learner's own
    # environ (and so its TPU/distributed setup) is untouched afterwards
    assert os.environ["SHEEPRL_TPU_COORDINATOR"] == "10.0.0.1:8476"
    assert os.environ["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:8476"


class _StubRecorder(gym.wrappers.RecordVideo):
    """Instantiable RecordVideo stand-in (moviepy is absent in CI, and the
    real wrapper refuses to construct without it). isinstance checks — the
    worker's recorder detection — still see a RecordVideo."""

    def __init__(self, env, *args, **kwargs):
        gym.Wrapper.__init__(self, env)
        self.recording = False
        self.recorded_frames = []


def test_capture_video_gating_sync_backend(monkeypatch, tmp_path):
    monkeypatch.setattr(gym.wrappers, "RecordVideo", _StubRecorder)
    cfg = toy_cfg(backend="sync", capture_video=True)

    def recorders(envs):
        found = []
        for i, env in enumerate(envs.envs):
            node = env
            while isinstance(node, gym.Wrapper):
                if isinstance(node, _StubRecorder):
                    found.append(i)
                    break
                node = node.env
        return found

    rank0 = build_vector_env(cfg, 0, str(tmp_path), "train")
    try:
        assert recorders(rank0) == [0]  # exactly one recorder: slot 0
    finally:
        rank0.close()
    rank1 = build_vector_env(cfg, 1, None, "train")
    try:
        assert recorders(rank1) == []  # non-zero ranks never record
    finally:
        rank1.close()


def test_pool_reports_video_slots():
    from sheeprl_tpu.envs.toy import PixelCatcher

    def make(slot):
        def thunk():
            env = PixelCatcher(seed=slot, size=16, paddle_width=4)
            if slot == 0:
                env = _StubRecorder(env)
            return env

        return thunk

    envs = EnvPool([make(i) for i in range(4)], config=PoolConfig(num_workers=2))
    try:
        # slot 0 lands on worker 0, yet the report is global-slot indexed:
        # exactly one recorder across the whole pool, at slot 0
        assert envs.video_slots == [0]
    finally:
        envs.close()
