"""Process-free units: fault parsing/scheduling, config, shm layout, backend
selection, backoff policy, worker environ sanitization."""

import gymnasium as gym
import numpy as np
import pytest

from sheeprl_tpu.envs import resolve_env_backend
from sheeprl_tpu.rollout import FaultSpec, FaultSchedule, PoolConfig, parse_fault_config, pool_config_from_cfg
from sheeprl_tpu.rollout.shm import ShmObsBuffers, obs_layout
from sheeprl_tpu.rollout.supervisor import RestartBudget, Supervisor
from sheeprl_tpu.rollout.worker import _COORDINATOR_VARS, sanitize_worker_environ
from sheeprl_tpu.utils.utils import dotdict

from .conftest import toy_cfg


# ---------------------------------------------------------- fault injection
def test_parse_fault_config():
    faults = parse_fault_config(
        [
            {"kind": "crash", "worker": 0, "at_step": 5},
            {"kind": "hang", "worker": 1, "at_step": 2, "duration_s": 3.0},
        ]
    )
    assert [f.kind for f in faults] == ["crash", "hang"]
    assert faults[1].duration_s == 3.0


@pytest.mark.parametrize(
    "bad",
    [
        {"kind": "explode", "worker": 0, "at_step": 1},
        {"kind": "crash", "worker": -1, "at_step": 1},
        {"kind": "crash", "worker": 0, "at_step": -2},
    ],
)
def test_parse_fault_config_rejects(bad):
    with pytest.raises(ValueError):
        parse_fault_config([bad])


def test_fault_schedule_fires_once_and_late():
    schedule = FaultSchedule(
        parse_fault_config(
            [
                {"kind": "crash", "worker": 0, "at_step": 3},
                {"kind": "slow", "worker": 1, "at_step": 3, "duration_s": 0.1},
                {"kind": "crash", "worker": 0, "at_step": 10},
            ]
        )
    )
    assert schedule.pop_due(0) == {}
    due = schedule.pop_due(3)
    assert sorted(due) == [0, 1] and due[0][0].kind == "crash" and due[1][0].kind == "slow"
    # each spec fires exactly once
    assert schedule.pop_due(3) == {}
    # a fault scheduled earlier than the current step is late, not lost
    due = schedule.pop_due(12)
    assert due[0][0].at_step == 10


def test_fault_spec_wire_roundtrip():
    spec = FaultSpec(kind="slow", worker=2, at_step=7, duration_s=0.25)
    wire = spec.to_wire()
    assert wire["kind"] == "slow" and wire["duration_s"] == 0.25


# ------------------------------------------------------------------- config
def test_pool_config_from_cfg_reads_rollout_node():
    cfg = toy_cfg(faults=[{"kind": "crash", "worker": 0, "at_step": 1}], max_restarts=5)
    pc = pool_config_from_cfg(cfg)
    assert pc.max_restarts == 5
    assert pc.num_workers == 2
    assert len(pc.faults) == 1 and pc.faults[0].kind == "crash"


def test_pool_config_defaults_without_node():
    pc = pool_config_from_cfg(dotdict({"env": {"num_envs": 4}}))
    assert pc.max_restarts == 3 and pc.faults == []


def test_pool_config_faults_gated_by_enabled():
    cfg = toy_cfg(faults=[{"kind": "crash", "worker": 0, "at_step": 1}])
    cfg.rollout.fault_injection.enabled = False
    assert pool_config_from_cfg(cfg).faults == []


def test_resolve_num_workers():
    assert PoolConfig(num_workers=3).resolve_num_workers(8) == 3
    assert PoolConfig(num_workers=16).resolve_num_workers(4) == 4  # capped at envs
    assert PoolConfig().resolve_num_workers(2) <= 2
    with pytest.raises(ValueError):
        PoolConfig(num_workers=0).resolve_num_workers(4)


def test_heartbeat_grace_defaults_to_step_timeout():
    assert PoolConfig(step_timeout_s=7.0).heartbeat_grace == 7.0
    assert PoolConfig(step_timeout_s=7.0, heartbeat_grace_s=2.0).heartbeat_grace == 2.0


# ------------------------------------------------------------------ backend
def test_resolve_env_backend_alias_and_override():
    cfg = toy_cfg(backend=None)
    cfg.env.sync_env = True
    assert resolve_env_backend(cfg) == "sync"
    cfg.env.sync_env = False
    assert resolve_env_backend(cfg) == "async"
    cfg.env.backend = "pool"
    assert resolve_env_backend(cfg) == "pool"
    cfg.env.backend = "turbo"
    with pytest.raises(ValueError):
        resolve_env_backend(cfg)


# ---------------------------------------------------------------------- shm
def test_obs_layout_requires_dict_of_box():
    space = gym.spaces.Dict(
        {"rgb": gym.spaces.Box(0, 255, (8, 8, 3), np.uint8), "state": gym.spaces.Box(-1, 1, (4,), np.float32)}
    )
    layout = obs_layout(space, num_envs=3)
    assert layout["rgb"] == ((3, 8, 8, 3), np.dtype(np.uint8))
    assert layout["state"] == ((3, 4), np.dtype(np.float32))
    with pytest.raises(TypeError):
        obs_layout(gym.spaces.Box(0, 255, (8, 8, 3), np.uint8), num_envs=3)
    with pytest.raises(TypeError):
        obs_layout(gym.spaces.Dict({"d": gym.spaces.Discrete(4)}), num_envs=3)


def test_shm_buffers_roundtrip_and_zero():
    space = gym.spaces.Dict({"rgb": gym.spaces.Box(0, 255, (4, 4, 1), np.uint8)})
    buf = ShmObsBuffers(space, num_envs=2)
    try:
        buf.views["rgb"][1] = 9
        out = buf.read(copy=True)
        assert out["rgb"][1].max() == 9
        buf.views["rgb"][1] = 7
        assert out["rgb"][1].max() == 9  # copy=True detaches from the shm
        buf.zero_slot(1)
        assert buf.views["rgb"][1].max() == 0
    finally:
        buf.close()


# ------------------------------------------------------------- supervision
def test_backoff_is_exponential_and_capped():
    sup = Supervisor(PoolConfig(backoff_base_s=0.5, backoff_max_s=3.0), num_workers=1)
    assert [sup.backoff_s(n) for n in (1, 2, 3, 4, 10)] == [0.5, 1.0, 2.0, 3.0, 3.0]


def test_sanitize_worker_environ():
    env = {var: "x" for var in _COORDINATOR_VARS}
    env["JAX_PLATFORMS"] = "tpu"
    env["HOME"] = "/root"
    out = sanitize_worker_environ(env)
    assert out["JAX_PLATFORMS"] == "cpu"
    assert out["SHEEPRL_TPU_ENV_WORKER"] == "1"
    assert out["HOME"] == "/root"
    assert not any(var in out for var in _COORDINATOR_VARS)


def test_restart_budget_fixed_cap_without_refund():
    budget = RestartBudget(max_restarts=2, refund_after_s=None)
    assert not budget.exhausted
    assert budget.charge() == 1
    assert budget.charge() == 2
    assert budget.exhausted  # cap reached, no healthy window can save it


def test_restart_budget_healthy_window_refunds():
    now = [0.0]
    budget = RestartBudget(max_restarts=2, refund_after_s=100.0, clock=lambda: now[0])
    assert budget.charge() == 1
    assert budget.charge() == 2
    assert budget.exhausted
    # one full healthy window refunds one restart — the worker earns back
    # headroom instead of staying one fault from a mask forever
    now[0] = 101.0
    assert not budget.exhausted
    assert budget.used == 1
    # the next fault's backoff restarts from the post-refund charge count
    assert budget.charge() == 2
    # two windows refund two, clamped at zero
    now[0] = 301.0
    assert not budget.exhausted
    assert budget.used == 0


def test_restart_budget_refund_keeps_window_remainder():
    """A 1.5-window healthy stretch refunds exactly one restart and the
    leftover half-window still counts toward the next refund."""
    now = [0.0]
    budget = RestartBudget(max_restarts=3, refund_after_s=100.0, clock=lambda: now[0])
    budget.charge()
    budget.charge()
    now[0] = 150.0
    assert not budget.exhausted
    assert budget.used == 1
    # only 50s more completes the window that already half-elapsed
    now[0] = 200.0
    assert not budget.exhausted
    assert budget.used == 0


def test_restart_budget_clustered_faults_still_mask():
    """Faults inside one window get no refund — a crash-looping worker is
    masked exactly as with the fixed cap."""
    now = [0.0]
    budget = RestartBudget(max_restarts=2, refund_after_s=100.0, clock=lambda: now[0])
    budget.charge()
    now[0] = 50.0
    budget.charge()
    now[0] = 99.0
    assert budget.exhausted
