"""Supervision behavior under injected faults: restart, timeout, masking.

Each test boots one real spawn pool (a few seconds: the children import the
package → jax-on-cpu), injects a deterministic fault from the parent-side
schedule and asserts the run completes the exact requested step count — the
degradation contract from the ISSUE: a training loop over a pool never loses
steps to a crashed or wedged env worker.
"""

import numpy as np

from sheeprl_tpu.envs import build_vector_env

from .conftest import toy_cfg


def _run_steps(envs, n, seed=7):
    rng = np.random.default_rng(0)
    envs.reset(seed=seed)
    last = None
    for _ in range(n):
        last = envs.step(rng.integers(0, 3, size=envs.num_envs))
    return last


def test_crash_restarts_within_budget_and_exact_step_count():
    envs = build_vector_env(
        toy_cfg(faults=[{"kind": "crash", "worker": 0, "at_step": 5}]), 0
    )
    try:
        obs, rewards, terminations, truncations, infos = _run_steps(envs, 20)
        assert envs.restart_counts == [1, 0]
        assert envs.masked_slots == []
        # post-restart the pool serves live observations for every slot
        assert obs["rgb"].shape == (4, 16, 16, 3)
        assert all(obs["rgb"][i].any() for i in range(4))
    finally:
        envs.close()


def test_crash_truncates_in_flight_episode():
    envs = build_vector_env(
        toy_cfg(faults=[{"kind": "crash", "worker": 0, "at_step": 2}]), 0
    )
    try:
        rng = np.random.default_rng(0)
        envs.reset(seed=7)
        infos = {}
        for t in range(3):
            obs, rewards, terminations, truncations, infos = envs.step(
                rng.integers(0, 3, size=4)
            )
            if t == 2:
                # worker 0 owns slots {0, 1}: its lost episodes are reported
                # truncated, with the post-restart reset obs as final_obs and
                # a worker_restart marker in final_info
                assert truncations[0] and truncations[1]
                assert rewards[0] == 0.0 and rewards[1] == 0.0
                assert infos["final_obs"][0] is not None
                assert np.array_equal(infos["final_obs"][0]["rgb"], obs["rgb"][0])
                assert infos["final_info"]["worker_restart"][0]
                assert not infos["final_info"]["_worker_restart"][2:].any()
    finally:
        envs.close()


def test_hung_worker_trips_step_timeout():
    envs = build_vector_env(
        toy_cfg(
            faults=[{"kind": "hang", "worker": 1, "at_step": 3, "duration_s": 60.0}],
            step_timeout_s=1.5,
        ),
        0,
    )
    try:
        _run_steps(envs, 8)
        assert envs.restart_counts == [0, 1]
        assert envs.masked_slots == []
    finally:
        envs.close()


def test_slow_worker_heartbeat_prevents_false_timeout():
    # 2.5s of injected slowness against a 1.5s step timeout: the worker keeps
    # heartbeating through the slowdown, so the deadline extends and no
    # restart fires (the hang test above proves the timeout itself works)
    envs = build_vector_env(
        toy_cfg(
            faults=[{"kind": "slow", "worker": 0, "at_step": 2, "duration_s": 2.5}],
            step_timeout_s=1.5,
        ),
        0,
    )
    try:
        _run_steps(envs, 5)
        assert envs.restart_counts == [0, 0]
    finally:
        envs.close()


def test_exhausted_restarts_mask_slots_and_pool_degrades():
    faults = [{"kind": "crash", "worker": 0, "at_step": s} for s in (2, 4, 6, 8)]
    envs = build_vector_env(toy_cfg(faults=faults, max_restarts=2), 0)
    try:
        obs, rewards, terminations, truncations, infos = _run_steps(envs, 14)
        # two restarts consumed the budget; the third crash masks worker 0
        assert envs.restart_counts[0] == 2
        assert envs.masked_slots == [0, 1]
        # masked slots serve zeros / all-False; live slots keep stepping
        assert not obs["rgb"][[0, 1]].any()
        assert rewards[[0, 1]].sum() == 0.0
        assert not terminations[[0, 1]].any() and not truncations[[0, 1]].any()
        assert obs["rgb"][2].any() and obs["rgb"][3].any()
    finally:
        envs.close()
