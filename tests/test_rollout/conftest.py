"""Shared config builder for the rollout-pool suite.

Every pool test boots real spawn workers (each imports the package, hence
jax-on-cpu: a few seconds per boot), so the suite keeps one pool per test
and small toy envs. PixelCatcher is the env under test — pure numpy,
deterministic under seeding, pixel Dict obs like the real workloads.
"""

from sheeprl_tpu.utils.utils import dotdict

TOY_WRAPPER = {
    "_target_": "sheeprl_tpu.envs.toy.PixelCatcher",
    "id": "toy",
    "size": 16,
    "paddle_width": 4,
}


def toy_cfg(
    backend="pool",
    num_envs=4,
    num_workers=2,
    faults=None,
    max_restarts=3,
    step_timeout_s=30.0,
    capture_video=False,
    seed=7,
):
    return dotdict(
        {
            "seed": seed,
            "env": {
                "id": "toy",
                "num_envs": num_envs,
                "frame_stack": 1,
                "sync_env": True,
                "backend": backend,
                "screen_size": 16,
                "action_repeat": 1,
                "grayscale": False,
                "clip_rewards": False,
                "capture_video": capture_video,
                "frame_stack_dilation": 1,
                "max_episode_steps": None,
                "reward_as_observation": False,
                "wrapper": dict(TOY_WRAPPER),
            },
            "algo": {"cnn_keys": {"encoder": ["rgb"]}, "mlp_keys": {"encoder": []}},
            "rollout": {
                "num_workers": num_workers,
                "step_timeout_s": step_timeout_s,
                "spawn_timeout_s": 120.0,
                "heartbeat_grace_s": None,
                "max_restarts": max_restarts,
                # fast backoff: these tests assert recovery, not pacing
                "backoff_base_s": 0.05,
                "backoff_max_s": 0.2,
                "copy_obs": True,
                "start_method": "spawn",
                "fault_injection": {"enabled": faults is not None, "faults": faults or []},
            },
        }
    )
