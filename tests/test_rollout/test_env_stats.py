"""Rollout telemetry plumbing end to end, without worker processes:
RunTelemetry's env-step reservoir / restart / mask counters → the JSONL
stream → bench.py's ``--env-stats`` reader."""

import json

import numpy as np
import pytest

import bench
from sheeprl_tpu.obs import configure_telemetry, shutdown_telemetry, span
from sheeprl_tpu.rollout import EnvPool, PoolConfig


@pytest.fixture()
def telemetry(tmp_path):
    saved_timers, saved_disabled = dict(span.timers), span.disabled
    span.timers, span.disabled = {}, False
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    assert tel is not None
    yield tel
    shutdown_telemetry()
    span.timers, span.disabled = saved_timers, saved_disabled


def _events(tel):
    tel.writer.flush()
    with open(tel.writer.path) as f:
        return [json.loads(line) for line in f if line.strip()]


def _heartbeat(tel):
    tel.heartbeat(
        None, step=1, env_steps=10, train_steps=0, train_invocations=None, timer_window={}
    )


def test_env_step_latency_lands_in_heartbeat(telemetry):
    for dur in (0.010, 0.020, 0.100):
        telemetry.record_env_step(dur, queue_wait_s=dur / 2)
    _heartbeat(telemetry)
    (hb,) = [e for e in _events(telemetry) if e["event"] == "heartbeat"]
    assert hb["env_step_samples"] == 3
    assert hb["env_step_p50_ms"] == pytest.approx(20.0, rel=0.01)
    assert hb["env_step_p95_ms"] == pytest.approx(92.0, rel=0.01)
    assert hb["env_queue_wait_p50_ms"] == pytest.approx(10.0, rel=0.01)
    # the reservoir is per-window: a second heartbeat reports no env fields
    _heartbeat(telemetry)
    hb2 = [e for e in _events(telemetry) if e["event"] == "heartbeat"][-1]
    assert "env_step_p50_ms" not in hb2


def test_restart_and_mask_events_and_run_end_totals(telemetry):
    telemetry.record_worker_restart(worker=1, reason="timeout", restarts=1)
    telemetry.record_worker_restart(worker=1, reason="crash", restarts=2)
    telemetry.record_masked_slot(worker=1, slots=[2, 3], reason="crash")
    _heartbeat(telemetry)
    events = _events(telemetry)
    restarts = [e for e in events if e["event"] == "worker_restart"]
    assert [e["reason"] for e in restarts] == ["timeout", "crash"]
    (mask,) = [e for e in events if e["event"] == "masked_slot"]
    assert mask["slots"] == [2, 3]
    (hb,) = [e for e in events if e["event"] == "heartbeat"]
    assert hb["window_worker_restarts"] == 2
    assert hb["worker_restarts_total"] == 2
    assert hb["masked_slots_total"] == 2

    path = telemetry.writer.path
    shutdown_telemetry()
    events = bench.read_telemetry(path)
    (end,) = [e for e in events if e["event"] == "run_end"]
    assert end["worker_restarts"] == 2
    assert end["masked_slots"] == 2


def test_bench_env_stats_summary(telemetry):
    telemetry.emit_span("rollout/env_reset", None, 0.050, {"busy_s": 0.045, "queue_wait_s": 0.005})
    for dur in (0.010, 0.012, 0.300):
        telemetry.emit_span("rollout/env_step", None, dur, {"busy_s": dur * 0.9, "queue_wait_s": dur * 0.1})
        telemetry.record_env_step(dur, queue_wait_s=dur * 0.1)
    telemetry.record_worker_restart(worker=0, reason="crash during step", restarts=1)
    telemetry.record_masked_slot(worker=0, slots=[0, 1], reason="crash")
    path = telemetry.writer.path
    shutdown_telemetry()

    stats = bench.env_stats_summary(path)
    assert stats["env_step"]["count"] == 3
    assert stats["env_step"]["p50_ms"] == pytest.approx(12.0, rel=0.01)
    assert stats["env_step"]["max_ms"] == pytest.approx(300.0, rel=0.01)
    assert stats["env_step"]["queue_wait_p50_ms"] == pytest.approx(1.2, rel=0.01)
    assert stats["env_reset"]["count"] == 1
    assert stats["worker_restarts"] == [
        {"worker": 0, "reason": "crash during step", "restarts": 1, "step": 0}
    ]
    assert stats["masked_slots"][0]["slots"] == [0, 1]
    # totals prefer run_end (emitted by the shutdown above)
    assert stats["totals"] == {"worker_restarts": 1, "masked_slots": 2}


def test_bench_env_stats_empty_stream(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "heartbeat", "t": 0.0}) + "\n")
    stats = bench.env_stats_summary(path)
    assert "env_step" not in stats
    assert stats["totals"] == {"worker_restarts": 0, "masked_slots": 0}


def test_bench_percentile_matches_numpy():
    vals = sorted([0.3, 1.0, 2.5, 9.0, 4.2, 0.01])
    for q in (50, 95, 99):
        assert bench._percentile(vals, q) == pytest.approx(float(np.percentile(vals, q)))


def test_pool_step_emits_spans_and_latency(telemetry, tmp_path):
    """One real pool under live telemetry: step/reset spans land in the
    stream and bench --env-stats can read the run."""
    from sheeprl_tpu.envs.toy import PixelCatcher

    def thunk():
        return PixelCatcher(seed=3, size=16, paddle_width=4)

    envs = EnvPool([thunk, thunk], config=PoolConfig(num_workers=1))
    try:
        envs.reset(seed=5)
        for _ in range(3):
            envs.step(np.zeros(2, dtype=np.int64))
    finally:
        envs.close()
    events = _events(telemetry)
    step_spans = [e for e in events if e["event"] == "span" and e["name"] == "rollout/env_step"]
    reset_spans = [e for e in events if e["event"] == "span" and e["name"] == "rollout/env_reset"]
    assert len(step_spans) == 3 and len(reset_spans) == 1
    for e in step_spans:
        assert e["attrs"]["queue_wait_s"] >= 0.0
        assert e["dur"] >= e["attrs"]["busy_s"]
    stats = bench.env_stats_summary(events)
    assert stats["env_step"]["count"] == 3
    assert stats["totals"] == {"worker_restarts": 0, "masked_slots": 0}
