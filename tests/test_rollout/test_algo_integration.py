"""The ISSUE acceptance path end to end: a real algorithm main trained over
``env.backend=pool`` with an injected worker crash completes normally, and
``bench.py --env-stats`` surfaces the restart from the run's telemetry."""

import json
import os

import bench
from sheeprl_tpu.cli import run


def _args(tmp_path):
    return [
        "exp=ppo",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=False",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        f"log_base_dir={tmp_path}/logs",
        # the subsystem under test: pooled workers, one injected crash
        "env.backend=pool",
        "rollout.num_workers=2",
        "rollout.step_timeout_s=30.0",
        "rollout.backoff_base_s=0.05",
        "rollout.backoff_max_s=0.2",
        "rollout.fault_injection.enabled=True",
        "rollout.fault_injection.faults=[{kind: crash, worker: 0, at_step: 5}]",
    ]


def test_ppo_over_pool_with_crash_completes_and_reports(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(_args(tmp_path))

    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1, jsonls
    stats = bench.env_stats_summary(jsonls[0])

    # the run finished (run() returning IS the exact-step-count proof: the
    # rollout loop iterates a fixed schedule and a lost step would deadlock
    # or crash it) and the crash is visible in the artifacts
    assert stats["totals"]["worker_restarts"] >= 1
    assert stats["totals"]["masked_slots"] == 0
    assert any(r["reason"].startswith("crash") for r in stats["worker_restarts"])
    assert stats["env_step"]["count"] >= 32
    assert stats["env_step"]["p95_ms"] > 0
    # and the stream stays machine-readable through the normal CLI entrypoint
    assert json.dumps(stats)
