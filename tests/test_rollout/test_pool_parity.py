"""EnvPool must be bit-identical to SyncVectorEnv with faults off.

This is the pool's core contract (ISSUE acceptance): same seeds, same
actions → same observations, rewards, flags and the full SAME_STEP
``final_obs``/``final_info`` batching, so flipping ``env.backend=pool`` on
any algorithm main changes nothing about the produced trajectories.
"""

import numpy as np

from sheeprl_tpu.envs import build_vector_env

from .conftest import toy_cfg


def test_pool_matches_sync_vector_env_bitwise():
    sync_envs = build_vector_env(toy_cfg(backend="sync"), 0)
    pool_envs = build_vector_env(toy_cfg(backend="pool"), 0)
    try:
        assert pool_envs.single_observation_space == sync_envs.single_observation_space
        assert pool_envs.single_action_space == sync_envs.single_action_space
        assert pool_envs.observation_space == sync_envs.observation_space
        assert pool_envs.action_space == sync_envs.action_space

        obs_s, info_s = sync_envs.reset(seed=7)
        obs_p, info_p = pool_envs.reset(seed=7)
        assert np.array_equal(obs_s["rgb"], obs_p["rgb"])

        rng = np.random.default_rng(0)
        episode_ends = 0
        for t in range(50):
            actions = rng.integers(0, 3, size=4)
            obs_s, rew_s, term_s, trunc_s, info_s = sync_envs.step(actions)
            obs_p, rew_p, term_p, trunc_p, info_p = pool_envs.step(actions)
            assert np.array_equal(obs_s["rgb"], obs_p["rgb"]), f"obs diverged at step {t}"
            assert np.array_equal(rew_s, rew_p) and rew_s.dtype == rew_p.dtype
            assert np.array_equal(term_s, term_p) and np.array_equal(trunc_s, trunc_p)
            if "final_obs" in info_s:
                episode_ends += 1
                assert np.array_equal(info_s["_final_obs"], info_p["_final_obs"])
                for e in range(4):
                    fin_s, fin_p = info_s["final_obs"][e], info_p["final_obs"][e]
                    assert (fin_s is None) == (fin_p is None)
                    if fin_s is not None:
                        assert np.array_equal(fin_s["rgb"], fin_p["rgb"])
            if "final_info" in info_s:
                ep_s = info_s["final_info"].get("episode")
                ep_p = info_p["final_info"].get("episode")
                assert (ep_s is None) == (ep_p is None)
                if ep_s is not None:
                    assert np.array_equal(ep_s["_r"], ep_p["_r"])
                    assert np.allclose(
                        np.asarray(ep_s["r"], dtype=float), np.asarray(ep_p["r"], dtype=float)
                    )
        # the toy env terminates well within 50 steps: the SAME_STEP final
        # batching path above actually ran
        assert episode_ends > 0
        assert pool_envs.restart_counts == [0, 0] and pool_envs.masked_slots == []
    finally:
        sync_envs.close()
        pool_envs.close()


def test_pool_reset_with_seed_list_and_reuse():
    pool_envs = build_vector_env(toy_cfg(backend="pool", num_workers=2), 0)
    try:
        obs_a, _ = pool_envs.reset(seed=[11, 12, 13, 14])
        obs_b, _ = pool_envs.reset(seed=[11, 12, 13, 14])
        assert np.array_equal(obs_a["rgb"], obs_b["rgb"])
        # default copy_obs=True detaches returned obs from the shm buffers:
        # stepping must not mutate an already-returned observation
        before = obs_b["rgb"].copy()
        pool_envs.step(np.zeros(4, dtype=np.int64))
        assert np.array_equal(obs_b["rgb"], before)
        obs_c, _ = pool_envs.reset(seed=[99, 98, 97, 96])
        assert not np.array_equal(obs_a["rgb"], obs_c["rgb"])
    finally:
        pool_envs.close()
