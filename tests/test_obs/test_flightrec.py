"""Flight recorder (bounded event ring -> flightrec.json) and size-capped
telemetry.jsonl rotation."""

import json
import os

import pytest

from sheeprl_tpu.obs.telemetry import (
    TelemetryWriter,
    configure_telemetry,
    shutdown_telemetry,
    telemetry_dump_flight_record,
)


@pytest.fixture()
def telemetry(tmp_path):
    """Active telemetry with a tiny 8-event ring; always shut down."""
    cfg = {
        "metric": {
            "telemetry": {
                "enabled": True,
                "poll_interval": 0.0,
                "flightrec_events": 8,
            }
        }
    }
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    try:
        yield tel
    finally:
        shutdown_telemetry()


def test_ring_is_bounded_newest_last(telemetry, tmp_path):
    for n in range(30):
        telemetry.emit("unit", n=n)
    path = telemetry_dump_flight_record("manual")
    assert path == str(tmp_path / "flightrec.json")
    with open(path) as f:  # must be one valid JSON document
        dump = json.load(f)
    assert dump["schema"] == 1
    assert dump["trigger"] == "manual"
    assert dump["ring_capacity"] == 8
    # only the NEWEST 8 events survive, in order, newest last
    assert [e["n"] for e in dump["events"]] == list(range(22, 30))


def test_abnormal_exit_paths_dump_with_trigger_event_last(telemetry, tmp_path):
    for n in range(5):
        telemetry.emit("unit", n=n)
    telemetry.record_nan_rollback(None, reason="unit", remaining=1)
    with open(tmp_path / "flightrec.json") as f:
        dump = json.load(f)
    assert dump["trigger"] == "nan_rollback"
    assert dump["events"][-1]["event"] == "nan_rollback"

    # a later abnormal exit overwrites: the newest post-mortem wins
    telemetry.record_preemption(15)
    with open(tmp_path / "flightrec.json") as f:
        dump = json.load(f)
    assert dump["trigger"] == "preempt"
    assert dump["events"][-1]["event"] == "preempt"
    assert dump["events"][-2]["event"] == "nan_rollback"


def test_dump_carries_process_identity_and_active_traces(telemetry, tmp_path):
    """A crash artifact must be placeable on the merged timeline: the dump
    names who wrote it (role, pid, clock offset) and which causal chains were
    in flight when the process died."""
    from sheeprl_tpu.obs.trace import new_trace_id, set_trace_role, trace_event

    set_trace_role("learner")
    tids = [new_trace_id() for _ in range(3)]
    for tid in tids:
        trace_event("slab_admit", tid, ring_wait_us=10)
    path = telemetry_dump_flight_record("manual")
    with open(path) as f:
        dump = json.load(f)
    assert dump["role"] == "learner"
    assert dump["pid"] == os.getpid()
    assert isinstance(dump["clock_offset"], float)
    assert dump["active_traces"] == tids  # newest last, ids intact


def test_ring_disabled(tmp_path):
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0, "flightrec_events": 0}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    try:
        tel.emit("unit", n=1)
        assert tel.dump_flight_record("manual") is None
        assert not os.path.exists(tmp_path / "flightrec.json")
    finally:
        shutdown_telemetry()


def test_writer_rotation_bounds_disk(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    writer = TelemetryWriter(path, max_bytes=2000)
    for n in range(200):
        writer.write({"event": "unit", "n": n, "pad": "x" * 64})
        writer.flush()
    writer.close()
    assert writer.rotations >= 1
    assert writer.segments() == [path + ".1", path]
    # each segment stays around the cap: total disk ~<= 2x max_bytes
    assert os.path.getsize(path + ".1") <= 2000 + 200
    assert os.path.getsize(path) <= 2000 + 200
    # both segments are intact JSONL and jointly hold the newest events
    events = []
    for seg in writer.segments():
        with open(seg) as f:
            events += [json.loads(line) for line in f if line.strip()]
    ns = [e["n"] for e in events]
    assert ns == sorted(ns)
    assert ns[-1] == 199


def test_rotation_off_by_default(tmp_path):
    path = str(tmp_path / "telemetry.jsonl")
    writer = TelemetryWriter(path)
    for n in range(200):
        writer.write({"event": "unit", "n": n, "pad": "x" * 64})
    writer.close()
    assert writer.rotations == 0
    assert writer.segments() == [path]


def test_run_end_reports_rotation_and_segments(tmp_path):
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0, "max_bytes": 1500}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    for n in range(100):
        tel.emit("unit", n=n, pad="x" * 64)
        tel.writer.flush()
    shutdown_telemetry()
    # run_end lands in the CURRENT (newest) segment
    with open(tmp_path / "telemetry.jsonl") as f:
        events = [json.loads(line) for line in f if line.strip()]
    run_end = [e for e in events if e["event"] == "run_end"][-1]
    assert run_end["telemetry_rotations"] >= 1
    assert run_end["telemetry_segments"] == ["telemetry.jsonl.1", "telemetry.jsonl"]
