"""Triggered XLA profiler capture (obs/profile.py): explicit-window and
slow-window triggers against injected start/stop, plus the CLI acceptance
run that lands a real Perfetto trace and registers it in RUNS.jsonl."""

import os

import pytest

from sheeprl_tpu.obs.profile import TriggeredProfiler


class FakeTracer:
    def __init__(self, fail_start=False):
        self.starts = []
        self.stops = 0
        self.fail_start = fail_start

    def start(self, path):
        if self.fail_start:
            raise RuntimeError("profiler busy")
        self.starts.append(path)

    def stop(self):
        self.stops += 1


def test_explicit_windows_merge_consecutive(tmp_path):
    tracer = FakeTracer()
    prof = TriggeredProfiler(
        str(tmp_path / "traces"), windows=[2, 3], start_trace=tracer.start, stop_trace=tracer.stop
    )
    for window in range(1, 6):
        prof.on_window(window)
    captures = prof.finish()
    # windows 2 and 3 are consecutive: ONE trace spans both
    assert len(captures) == 1
    assert captures[0]["trigger"] == "explicit"
    assert captures[0]["windows"] == [2, 3]
    assert tracer.starts == [str(tmp_path / "traces" / "window_00002")]
    assert tracer.stops == 1
    assert os.path.isdir(captures[0]["trace_dir"])
    assert captures[0]["t_end"] >= captures[0]["t_start"]


def test_disjoint_windows_produce_separate_captures(tmp_path):
    tracer = FakeTracer()
    prof = TriggeredProfiler(
        str(tmp_path / "t"), windows=[1, 4], start_trace=tracer.start, stop_trace=tracer.stop
    )
    for window in range(1, 6):
        prof.on_window(window)
    captures = prof.finish()
    assert [c["windows"] for c in captures] == [[1], [4]]
    assert tracer.stops == 2


def test_capture_straddling_run_end_is_closed_by_finish(tmp_path):
    tracer = FakeTracer()
    prof = TriggeredProfiler(str(tmp_path / "t"), windows=[3], start_trace=tracer.start, stop_trace=tracer.stop)
    for window in range(1, 4):
        prof.on_window(window)  # run ends while window 3 is being traced
    captures = prof.finish()
    assert len(captures) == 1 and tracer.stops == 1


def test_slow_window_fires_exactly_once_and_captures_next_window(tmp_path):
    tracer = FakeTracer()
    prof = TriggeredProfiler(
        str(tmp_path / "t"),
        slow_factor=3.0,
        slow_min_history=4,
        start_trace=tracer.start,
        stop_trace=tracer.stop,
    )
    slow_at = {6: 1.0, 9: 2.0}  # second anomaly must NOT re-trigger
    for window in range(1, 12):
        prof.on_window(window)
        prof.observe_span("Time/env_interaction_time", 99.0)  # non-train spans ignored
        prof.observe_span("Time/train_time", slow_at.get(window, 0.1))
    captures = prof.finish()
    assert len(captures) == 1
    assert captures[0]["trigger"] == "slow_window"
    assert captures[0]["windows"] == [7]  # window 6 already ran untraced
    assert tracer.starts == [str(tmp_path / "t" / "window_00007")]


def test_slow_window_needs_history(tmp_path):
    tracer = FakeTracer()
    prof = TriggeredProfiler(
        str(tmp_path / "t"), slow_factor=3.0, slow_min_history=8, start_trace=tracer.start, stop_trace=tracer.stop
    )
    for window in range(1, 5):  # only 4 healthy windows: watchdog not armed
        prof.on_window(window)
        prof.observe_span("Time/train_time", 10.0 if window == 4 else 0.1)
    assert prof.finish() == []


def test_failed_start_trace_is_swallowed(tmp_path):
    tracer = FakeTracer(fail_start=True)
    prof = TriggeredProfiler(str(tmp_path / "t"), windows=[1], start_trace=tracer.start, stop_trace=tracer.stop)
    prof.on_window(1)
    prof.on_window(2)
    assert prof.finish() == []  # no capture, no crash
    assert tracer.stops == 0


@pytest.mark.profile
def test_cli_profile_window_lands_trace_and_registry_record(tmp_path, monkeypatch):
    """ISSUE acceptance: a tiny CartPole PPO run with
    metric.telemetry.profile_windows=[2] produces a non-empty Perfetto trace
    dir AND appends a schema-valid RUNS.jsonl record carrying the capture."""
    from sheeprl_tpu.cli import run
    from sheeprl_tpu.obs.registry import SCHEMA_VERSION, read_run_records

    monkeypatch.chdir(tmp_path)
    runs = str(tmp_path / "RUNS.jsonl")
    run(
        [
            "exp=ppo",
            "env.capture_video=False",
            "buffer.memmap=False",
            "algo.total_steps=256",
            "algo.rollout_steps=32",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=1",
            "algo.dense_units=8",
            "algo.mlp_layers=1",
            "algo.encoder.cnn_features_dim=16",
            "algo.encoder.mlp_features_dim=8",
            "env.num_envs=2",
            "algo.run_test=False",
            "checkpoint.save_last=False",
            "metric.log_level=1",
            "metric.telemetry.enabled=True",
            "metric.telemetry.poll_interval=0.0",
            "metric.telemetry.profile_windows=[2]",
            f"metric.telemetry.runs_jsonl={runs}",
            "run_name=evidence",
            f"log_base_dir={tmp_path}/logs",
        ]
    )

    (record,) = read_run_records(runs)
    assert record["schema"] == SCHEMA_VERSION
    assert record["kind"] == "train"
    assert record["outcome"] == "completed"
    assert record["algo"] == "ppo"
    assert record["env"] == "CartPole-v1"
    assert record["backend"] == "cpu"
    assert record["config_digest"] and record["git_sha"]
    assert record["sps_env"] > 0 and record["sps_train"] > 0
    assert record["final_metrics"], "aggregator scalars must reach the record"

    (capture,) = record["profile_captures"]
    assert capture["trigger"] == "explicit"
    assert capture["windows"] == [2]
    trace_dir = capture["trace_dir"]
    assert os.path.isdir(trace_dir)
    traced_files = [os.path.join(r, f) for r, _, fs in os.walk(trace_dir) for f in fs]
    assert traced_files, "jax.profiler must have written trace artifacts"
    assert any(os.path.getsize(p) > 0 for p in traced_files)
