"""Run registry (obs/registry.py): record round-trip, path resolution,
tolerant reads, atomic concurrent appends, and the register_run hook's
outcome reclassification."""

import json
import os
import threading

from sheeprl_tpu.obs.registry import (
    SCHEMA_VERSION,
    append_run_record,
    build_run_record,
    read_run_records,
    register_run,
    runs_jsonl_path,
)
from sheeprl_tpu.obs.telemetry import configure_telemetry, shutdown_telemetry


def _cfg(runs_path=None):
    cfg = {
        "algo": {"name": "ppo"},
        "env": {"id": "CartPole-v1"},
        "exp_name": "ppo_CartPole-v1",
        "run_name": "unit",
        "seed": 5,
        "metric": {"telemetry": {"enabled": True, "poll_interval": 0.0}},
    }
    if runs_path is not None:
        cfg["metric"]["telemetry"]["runs_jsonl"] = runs_path
    return cfg


def test_record_round_trip(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    record = build_run_record(_cfg(), kind="train", outcome="completed", summary={"sps_env": 123.0})
    append_run_record(record, path)
    (back,) = read_run_records(path)
    assert back["schema"] == SCHEMA_VERSION
    assert back["kind"] == "train"
    assert back["outcome"] == "completed"
    assert back["algo"] == "ppo"
    assert back["env"] == "CartPole-v1"
    assert back["seed"] == 5
    assert back["sps_env"] == 123.0
    assert isinstance(back["t"], float)
    # the digest is stable across identical configs, sensitive to any change
    assert back["config_digest"] == build_run_record(_cfg(), kind="train", outcome="completed")["config_digest"]
    other = _cfg()
    other["seed"] = 6
    assert back["config_digest"] != build_run_record(other, kind="train", outcome="completed")["config_digest"]


def test_unknown_outcome_recorded_as_crashed():
    assert build_run_record(None, kind="train", outcome="exploded")["outcome"] == "crashed"


def test_path_precedence(tmp_path, monkeypatch):
    # 1. explicit argument wins over everything
    assert runs_jsonl_path(_cfg("from_cfg.jsonl"), path="explicit.jsonl") == "explicit.jsonl"
    # 2. config beats the env var
    monkeypatch.setenv("SHEEPRL_TPU_RUNS_JSONL", "from_env.jsonl")
    assert runs_jsonl_path(_cfg("from_cfg.jsonl")) == "from_cfg.jsonl"
    # config False disables even with the env var set
    assert runs_jsonl_path(_cfg(False)) is None
    # 3. env var when the config is silent; empty env var disables
    assert runs_jsonl_path(_cfg()) == "from_env.jsonl"
    monkeypatch.setenv("SHEEPRL_TPU_RUNS_JSONL", "")
    assert runs_jsonl_path(_cfg()) is None
    # 4. default: <cwd>/RUNS.jsonl
    monkeypatch.delenv("SHEEPRL_TPU_RUNS_JSONL")
    monkeypatch.chdir(tmp_path)
    assert runs_jsonl_path(_cfg()) == str(tmp_path / "RUNS.jsonl")


def test_reader_skips_garbage_and_newer_schema(tmp_path):
    path = str(tmp_path / "RUNS.jsonl")
    append_run_record({"schema": SCHEMA_VERSION, "kind": "train", "n": 1}, path)
    with open(path, "a") as f:
        f.write("{torn line\n")
        f.write("\n")
        f.write("[1, 2, 3]\n")  # parseable but not a record
        f.write(json.dumps({"schema": SCHEMA_VERSION + 1, "kind": "future"}) + "\n")
    append_run_record({"schema": SCHEMA_VERSION, "kind": "train", "n": 2}, path)
    records = read_run_records(path)
    assert [r["n"] for r in records] == [1, 2]
    assert read_run_records(str(tmp_path / "missing.jsonl")) == []


def test_concurrent_appends_interleave_whole_lines(tmp_path):
    """8 writer threads x 25 records each: every line must parse back — the
    O_APPEND + flock append can never tear a record."""
    path = str(tmp_path / "RUNS.jsonl")

    def writer(tid):
        for n in range(25):
            append_run_record(
                {"schema": SCHEMA_VERSION, "kind": "train", "tid": tid, "n": n, "pad": "x" * 256},
                path,
            )

    threads = [threading.Thread(target=writer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    with open(path) as f:
        lines = [line for line in f if line.strip()]
    assert len(lines) == 200
    records = read_run_records(path)
    assert len(records) == 200
    assert {(r["tid"], r["n"]) for r in records} == {(t, n) for t in range(8) for n in range(25)}


def test_register_run_rolls_up_telemetry_and_reclassifies(tmp_path):
    """register_run folds the live telemetry's run_summary into the record
    and reclassifies crashed -> rolled_back when NaN rollbacks happened."""
    runs = str(tmp_path / "RUNS.jsonl")
    cfg = _cfg(runs)
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    try:
        tel.record_nan_rollback(None, reason="unit", remaining=2)
        record = register_run(cfg, kind="train", outcome="crashed", error="boom " * 200)
    finally:
        shutdown_telemetry()
    assert record is not None
    assert record["outcome"] == "rolled_back"
    assert record["nan_rollbacks"] == 1
    assert record["backend"] == "cpu"
    assert len(record["error"]) <= 500
    (back,) = read_run_records(runs)
    assert back["outcome"] == "rolled_back"
    assert back["config_digest"] == record["config_digest"]


def test_register_run_disabled_and_without_telemetry(tmp_path, monkeypatch):
    # runs_jsonl=False: no record, no file — and never raises
    assert register_run(_cfg(False), kind="eval", outcome="completed") is None
    # telemetry off but registry on: identity-only record still lands
    monkeypatch.chdir(tmp_path)
    cfg = _cfg(str(tmp_path / "RUNS.jsonl"))
    cfg["metric"]["telemetry"]["enabled"] = False
    record = register_run(cfg, kind="eval", outcome="completed", checkpoint="x.ckpt")
    assert record["algo"] == "ppo" and record["checkpoint"] == "x.ckpt"
    assert "backend" not in record  # no telemetry -> no rollup
    assert len(read_run_records(str(tmp_path / "RUNS.jsonl"))) == 1
