"""Trace plane (sheeprl_tpu.obs.trace) end-to-end with the merger
(tools/trace.py): recorder durability, the telemetry-attached sink,
cross-process joins over real stream files, rotated-segment merges, and the
hedged-request id contract on a real Router + SlotPool pair."""

import json
import time

import numpy as np
import pytest

from sheeprl_tpu.obs.telemetry import configure_telemetry, shutdown_telemetry
from sheeprl_tpu.obs.trace import (
    TraceRecorder,
    clock_offset,
    configure_trace,
    get_trace,
    new_trace_id,
    set_trace_role,
    shutdown_trace,
    trace_event,
    tracing_active,
)
from tools import trace as trace_tool


@pytest.fixture(autouse=True)
def _clean_trace_state():
    """Every test starts and ends with no recorder and no telemetry — the
    trace plane's module state is per-process."""
    shutdown_trace()
    yield
    shutdown_trace()
    shutdown_telemetry()


def read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ------------------------------------------------------------- recorder ----


def test_recorder_flushes_every_event_without_close(tmp_path):
    """The standalone sink is crash-durable: handshake + every event are on
    disk immediately (actor children die via os._exit on the drills)."""
    path = str(tmp_path / "trace.actor0.jsonl")
    rec = configure_trace("actor0", path, actor=0)
    assert tracing_active() and get_trace() is rec
    tid = new_trace_id()
    trace_event("slab_collect", tid, seq=0, collect_us=1234)
    trace_event("slab_commit", tid, seq=0)

    # NOT closed — read what an os._exit would leave behind
    events = read_jsonl(path)
    assert [e["event"] for e in events] == ["trace_handshake", "trace", "trace"]
    hs = events[0]
    assert hs["role"] == "actor0" and hs["actor"] == 0
    assert isinstance(hs["pid"], int) and "clock_offset" in hs
    assert abs(hs["clock_offset"] - clock_offset()) < 1.0
    for ev in events[1:]:
        assert ev["trace_id"] == tid and "t" in ev and "t_mono" in ev
    assert rec.active_trace_ids() == [tid, tid]

    # role rename re-handshakes on the same stream; the merger keeps the newest
    set_trace_role("actor0-restarted")
    events = read_jsonl(path)
    assert events[-1]["event"] == "trace_handshake"
    assert events[-1]["role"] == "actor0-restarted"


def test_new_trace_id_nonzero_63bit_and_distinct():
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64
    for tid in ids:
        assert 0 < tid < (1 << 63)


def test_clock_offset_aligns_monotonic_to_epoch():
    off = clock_offset()
    assert abs((time.monotonic() + off) - time.time()) < 0.5


def test_trace_event_is_noop_without_any_sink(tmp_path):
    assert not tracing_active()
    trace_event("slab_collect", new_trace_id())  # must not raise
    assert list(tmp_path.iterdir()) == []


# ---------------------------------------------------- telemetry-attached ----


def test_telemetry_sink_handshakes_lazily_and_on_role_change(tmp_path):
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    assert tracing_active() and get_trace() is None  # telemetry sink, no recorder
    tid = new_trace_id()
    trace_event("slab_admit", tid, ring_wait_us=500)
    set_trace_role("learner")  # re-handshake: the merger keeps the newest role
    trace_event("slab_train", tid, train_us=900)
    tel.writer.flush()

    events = read_jsonl(tmp_path / "telemetry.jsonl")
    handshakes = [e for e in events if e["event"] == "trace_handshake"]
    traces = [e for e in events if e["event"] == "trace"]
    assert len(handshakes) >= 2 and handshakes[-1]["role"] == "learner"
    assert [e["kind"] for e in traces] == ["slab_admit", "slab_train"]
    for ev in traces:
        assert ev["trace_id"] == tid and "t_mono" in ev
        assert "step" in ev and "process_index" in ev  # telemetry's own stamps

    # the merger reads the telemetry stream directly — handshake applies
    merged = trace_tool.merge([str(tmp_path / "telemetry.jsonl")])
    assert trace_tool.trace_kinds(merged["traces"][tid]) == ["slab_admit", "slab_train"]
    assert merged["processes"][0]["role"] == "learner"


# --------------------------------------------------- cross-process joins ----


def test_merge_joins_real_recorder_streams(tmp_path):
    """2 actors + learner, real stream files: one causal chain per slab,
    ordered by aligned time, terminals classified per trace."""
    t_ok, t_torn = new_trace_id(), new_trace_id()
    a0 = TraceRecorder("actor0", str(tmp_path / "trace.actor0.jsonl"))
    a0.emit("slab_collect", t_ok, seq=0, collect_us=4000)
    a0.emit("slab_commit", t_ok, seq=0)
    a0.close()
    a1 = TraceRecorder("actor1", str(tmp_path / "trace.actor1.jsonl"))
    a1.emit("slab_collect", t_torn, seq=0, collect_us=5000)
    # actor1 "dies" mid-write: no slab_commit ever lands
    a1.close()
    lrn = TraceRecorder("learner", str(tmp_path / "telemetry.jsonl"))
    lrn.emit("slab_admit", t_ok, ring_wait_us=2000)
    lrn.emit("slab_train", t_ok, train_us=3000)
    lrn.emit("torn", t_torn, source="ring")
    lrn.close()

    merged = trace_tool.merge(
        [str(tmp_path / p) for p in ("telemetry.jsonl", "trace.actor0.jsonl", "trace.actor1.jsonl")]
    )
    assert {p["role"] for p in merged["processes"]} == {"actor0", "actor1", "learner"}
    assert trace_tool.trace_kinds(merged["traces"][t_ok]) == [
        "slab_collect",
        "slab_commit",
        "slab_admit",
        "slab_train",
    ]
    # the torn victim keeps its actor-side half and terminates at `torn`
    assert trace_tool.trace_kinds(merged["traces"][t_torn]) == ["slab_collect", "torn"]

    summary = trace_tool.summarize(merged)
    assert summary["slabs"]["traces"] == 2
    assert summary["slabs"]["complete_chains"] == 1
    assert summary["slabs"]["terminals"] == {"slab_train": 1, "torn": 1}
    assert summary["slabs"]["age_ms"]["p50"] == pytest.approx(9.0)


def test_merge_reads_rotated_telemetry_segments(tmp_path):
    """A rotated stream contributes BOTH segments (oldest first) — the bug
    class where `.1` silently vanishes from analysis."""
    cfg = {"metric": {"telemetry": {"enabled": True, "poll_interval": 0.0, "max_bytes": 1500}}}
    tel = configure_telemetry(cfg, log_dir=str(tmp_path))
    tids = []
    while tel.writer.rotations < 1 and len(tids) < 200:
        tid = new_trace_id()
        tids.append(tid)
        trace_event("unit_mark", tid, pad="x" * 64)
        tel.writer.flush()
    # a couple more so both segments hold trace events
    for _ in range(3):
        tid = new_trace_id()
        tids.append(tid)
        trace_event("unit_mark", tid, pad="x" * 64)
    tel.writer.flush()
    assert tel.writer.rotations >= 1

    base = str(tmp_path / "telemetry.jsonl")
    assert trace_tool.segments(base) == [base + ".1", base]
    survivors = set()
    for seg in trace_tool.segments(base):
        for e in read_jsonl(seg):
            if e.get("event") == "trace":
                survivors.add(e["trace_id"])
    assert len(survivors) > 3  # events on disk straddle the rotation boundary

    merged = trace_tool.merge([base])  # base path only: .1 auto-included
    assert set(merged["traces"]) == survivors
    assert {p["stream"] for p in merged["processes"]} == set(trace_tool.segments(base))


# ---------------------------------------------------------- hedge dedup ----


def test_hedged_request_keeps_one_trace_id_across_twins(tmp_path):
    """The trace id lives on the SHARED Request object: the hedge twin, the
    loser's dropped copy and the winner all carry the same id, so the merged
    trace is one causal chain with hedge + drop marked exactly once."""
    from sheeprl_tpu.serve.router import Router
    from sheeprl_tpu.serve.slots import SlotPool, safe_complete

    configure_trace("serve", str(tmp_path / "trace.serve.jsonl"))
    try:
        pools = [SlotPool(capacity=4, backlog_bound=64) for _ in range(2)]
        from sheeprl_tpu.serve.router import RouteTarget

        router = Router(
            targets=lambda: [RouteTarget(i, p, 1.0, "device") for i, p in enumerate(pools)],
            max_pending=100,
            slo_s=0.02,  # few samples -> hedge threshold = max(floor, slo)
            hedge_scan_s=0.002,
        ).start()
        try:
            req = router.submit(np.float32(7.0), 60.0)
            assert req.trace_id != 0
            deadline = time.monotonic() + 5.0
            while req.hedges < 1 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert req.hedges == 1 and req.placements == [0, 1]
            batch = pools[1].take_batch(1.0)
            assert [r.rid for r in batch] == [req.rid]
            assert safe_complete(batch[0], "served-by-1")
            pools[1].complete_batch(batch)
            assert req.future.result(timeout=1.0) == "served-by-1"
            assert pools[0].take_batch(0.05) == []  # loser's copy dropped here
        finally:
            router.close()
    finally:
        shutdown_trace()

    merged = trace_tool.merge([str(tmp_path / "trace.serve.jsonl")])
    assert list(merged["traces"]) == [req.trace_id]  # ONE chain, no twin id
    kinds = trace_tool.trace_kinds(merged["traces"][req.trace_id])
    assert kinds[0] == "request_admit"
    assert kinds.count("request_hedge") == 1
    assert kinds.count("request_hedge_drop") == 1
    routes = [e for e in merged["traces"][req.trace_id] if e["kind"] == "request_route"]
    assert [e["replica"] for e in routes] == [0, 1]

    summary = trace_tool.summarize(merged)
    assert summary["requests"]["hedged"] == 1
    assert summary["requests"]["hedge_drops"] == 1
    assert "hedge_winner_dupes" not in summary["requests"]
