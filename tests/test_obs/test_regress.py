"""Regression gates (tools/regress.py + bench.py --regress): verdicts on
synthetic history, the SCENARIOS.json grid, exit codes, BENCH_*.json
folding, and the CLI surfaces."""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
REGRESS_PY = os.path.join(REPO_ROOT, "tools", "regress.py")


@pytest.fixture(scope="module")
def regress():
    spec = importlib.util.spec_from_file_location("_regress_under_test", REGRESS_PY)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _rec(t, algo="ppo", kind="train", outcome="completed", **metrics):
    return {
        "schema": 1,
        "t": t,
        "kind": kind,
        "algo": algo,
        "env": "CartPole-v1",
        "backend": "cpu",
        "local_device_count": 1,
        "process_count": 1,
        "outcome": outcome,
        **metrics,
    }


def test_verdicts_pass_regress_insufficient(regress):
    records = (
        [_rec(t, "ppo", sps_env=100.0 + t) for t in range(4)]
        + [_rec(t, "sac", sps_env=200.0) for t in range(3)]
        + [_rec(3, "sac", sps_env=100.0)]  # far below the 20% band
        + [_rec(0, "dreamer_v3", sps_env=50.0)]  # lone record
    )
    doc = regress.evaluate(records)
    verdicts = {key.split(":")[1]: cell["verdict"] for key, cell in doc["cells"].items()}
    assert verdicts == {"ppo": "pass", "sac": "regress", "dreamer_v3": "insufficient_history"}
    assert doc["summary"] == {"pass": 1, "regress": 1, "insufficient_history": 1}
    assert regress.exit_code(doc) == 1
    sac = doc["cells"]["train:sac:CartPole-v1:cpux1p1"]
    assert sac["metrics"]["sps_env"]["verdict"] == "regress"
    assert sac["metrics"]["sps_env"]["baseline"] == 200.0


def test_not_completed_runs_never_enter_a_cell(regress):
    records = [_rec(t, sps_env=100.0) for t in range(3)] + [
        _rec(3, sps_env=1.0, outcome="crashed"),
        _rec(4, sps_env=1.0, outcome="preempted"),
    ]
    doc = regress.evaluate(records)
    cell = doc["cells"]["train:ppo:CartPole-v1:cpux1p1"]
    assert cell["verdict"] == "pass"  # the crashed/preempted SPS never gated
    assert cell["newest_outcome"] == "completed"
    assert doc["records_ignored_not_completed"] == 2


def test_lower_is_better_and_count_slack(regress):
    # serve p95 going UP is a regression
    serve = [_rec(t, kind="serve", serve={"stats": {"qps": 100.0, "p95_ms": 10.0}}) for t in range(3)]
    doc = regress.evaluate(serve + [_rec(3, kind="serve", serve={"stats": {"qps": 100.0, "p95_ms": 30.0}})])
    cell = next(iter(doc["cells"].values()))
    assert cell["metrics"]["serve_p95_ms"]["verdict"] == "regress"
    assert cell["metrics"]["serve_qps"]["verdict"] == "pass"

    # count metrics carry +1 absolute slack: 0 -> 1 restart passes, 0 -> 5 regresses
    quiet = [_rec(t, worker_restarts=0, sps_env=100.0) for t in range(3)]
    doc = regress.evaluate(quiet + [_rec(3, worker_restarts=1, sps_env=100.0)])
    assert next(iter(doc["cells"].values()))["verdict"] == "pass"
    doc = regress.evaluate(quiet + [_rec(3, worker_restarts=5, sps_env=100.0)])
    cell = next(iter(doc["cells"].values()))
    assert cell["verdict"] == "regress"
    assert cell["metrics"]["worker_restarts"]["verdict"] == "regress"


def test_cells_split_by_kind_algo_env_topology(regress):
    a = _rec(0, sps_env=100.0)
    b = dict(_rec(1, sps_env=1.0), local_device_count=8)  # different topology
    c = dict(_rec(2, sps_env=1.0), env="Walker-v4")  # different env
    d = _rec(3, kind="eval", sps_env=1.0)  # different kind
    doc = regress.evaluate([a, b, c, d])
    assert len(doc["cells"]) == 4  # none of them compare against each other
    assert all(cell["verdict"] == "insufficient_history" for cell in doc["cells"].values())
    assert regress.exit_code(doc) == 0


def test_run_gate_writes_scenarios_and_exit_code(regress, tmp_path):
    runs = str(tmp_path / "RUNS.jsonl")
    out = str(tmp_path / "SCENARIOS.json")
    with open(runs, "w") as f:
        for t in range(3):
            f.write(json.dumps(_rec(t, sps_env=100.0)) + "\n")
        f.write("{torn\n")  # reader tolerance
        f.write(json.dumps(_rec(3, sps_env=10.0)) + "\n")
    assert regress.run_gate(runs, out, quiet=True) == 1
    with open(out) as f:
        doc = json.load(f)
    assert doc["summary"]["regress"] == 1
    assert doc["cells"]["train:ppo:CartPole-v1:cpux1p1"]["verdict"] == "regress"

    # repair the newest record -> gate goes green, grid is rewritten
    with open(runs, "a") as f:
        f.write(json.dumps(_rec(4, sps_env=101.0)) + "\n")
    assert regress.run_gate(runs, out, quiet=True) == 0
    with open(out) as f:
        assert json.load(f)["summary"]["regress"] == 0


def test_bench_json_folding(regress, tmp_path):
    for n, (value, outage) in enumerate([(50.0, False), (51.0, False), (49.0, True), (20.0, False)]):
        parsed = {
            "metric": "dreamer_v3_env_steps_per_sec_per_chip",
            "value": value,
            "secondary": {"metric": "ppo_cartpole_env_steps_per_sec", "value": value * 10},
        }
        if outage:
            parsed["outage"] = True
        with open(tmp_path / f"BENCH_r{n:02d}.json", "w") as f:
            json.dump({"n": n, "rc": 0, "parsed": parsed}, f)
    records = regress.bench_records(str(tmp_path / "BENCH_r*.json"))
    # 3 rounds kept (outage skipped), each contributing primary + secondary
    assert len(records) == 6
    doc = regress.evaluate(records)
    assert doc["cells"]["bench:dreamer_v3:bench:?x?p?"]["verdict"] == "regress"  # 50,51 -> 20
    assert doc["cells"]["bench:ppo:bench:?x?p?"]["verdict"] == "regress"


def test_self_test_cli(tmp_path):
    proc = subprocess.run(
        [sys.executable, REGRESS_PY, "--self-test"],
        capture_output=True,
        text=True,
        timeout=60,
        cwd=str(tmp_path),
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok" in proc.stdout


def test_bench_regress_cli(tmp_path):
    """bench.py --regress drives the gate from the jax-free parent: grid on
    disk, nonzero exit on a synthetically regressed record."""
    runs = tmp_path / "RUNS.jsonl"
    out = tmp_path / "SCENARIOS.json"
    with open(runs, "w") as f:
        for t, sps in enumerate([100.0, 102.0, 98.0, 10.0]):
            f.write(json.dumps(_rec(t, sps_env=sps)) + "\n")
    cmd = [
        sys.executable,
        os.path.join(REPO_ROOT, "bench.py"),
        "--regress",
        "--runs",
        str(runs),
        "--scenarios-out",
        str(out),
        "--bench-glob",
        "",
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120, cwd=str(tmp_path))
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "REGRESS" in proc.stdout
    with open(out) as f:
        assert json.load(f)["summary"]["regress"] == 1

    # make the newest healthy again: exit 0
    with open(runs, "a") as f:
        f.write(json.dumps(_rec(9, sps_env=101.0)) + "\n")
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120, cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
