"""Canonical test entry point (reference: tests/run_tests.py).

Runs the suite in two tiers so CI and humans share one definition of "the
tests pass":

- ``unit`` (default): everything except the algorithm smoke suites — the
  fast tier (data/ops/models/config/utils/envs/parallel), a few minutes on
  one core.
- ``all``: the full suite including the CLI-driven algorithm smoke tests
  (each jit-compiles tiny training graphs; ~30 min on one core).

A wall-clock budget guards against hangs: the run is aborted (exit 2) when
the budget expires. Everything executes on the 8-device virtual CPU mesh —
``tests/conftest.py`` forces ``jax_platforms=cpu`` with
``--xla_force_host_platform_device_count=8`` so no accelerator is needed.
"""

import argparse
import os
import sys

import pytest

UNIT_DIRS = [
    "tests/test_data",
    "tests/test_ops",
    "tests/test_models",
    "tests/test_config",
    "tests/test_utils",
    "tests/test_envs",
    "tests/test_parallel",
]


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("tier", nargs="?", default="unit", choices=["unit", "algos", "all"])
    env_budget = os.environ.get("SHEEPRL_TPU_TEST_BUDGET_MINUTES")
    parser.add_argument(
        "--budget-minutes",
        type=float,
        default=float(env_budget) if env_budget is not None else None,
        help="abort the whole run after this many minutes (0 = no budget; default: per-tier)",
    )
    # unknown args (incl. dash options like -k/-x) forward to pytest
    args, pytest_args = parser.parse_known_args()

    targets = {"unit": UNIT_DIRS, "algos": ["tests/test_algos"], "all": ["tests"]}[args.tier]
    # budgets are sized for a 1-core host with a COLD compilation cache; the
    # persistent XLA cache (tests/conftest.py) makes re-runs much faster
    default_budget = {"unit": 15, "algos": 60, "all": 90}[args.tier]
    budget = args.budget_minutes if args.budget_minutes is not None else default_budget

    if budget:
        import threading

        def expire() -> None:
            print(f"\n[run_tests] budget of {budget} min expired — aborting", file=sys.stderr)
            os._exit(2)

        timer = threading.Timer(budget * 60, expire)
        timer.daemon = True
        timer.start()

    return pytest.main(["-q", *targets, *pytest_args])


if __name__ == "__main__":
    sys.exit(main())
