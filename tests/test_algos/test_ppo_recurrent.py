"""Recurrent PPO smoke tests (reference: tests/test_algos/test_algos.py::test_ppo_recurrent)."""

import os

import pytest

from sheeprl_tpu.cli import run


def rppo_args(tmp_path, env_id="dummy_discrete"):
    return [
        "exp=ppo_recurrent",
        "env=dummy",
        f"env.id={env_id}",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=8",
        "algo.per_rank_sequence_length=4",
        "algo.per_rank_num_batches=2",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.rnn.lstm.hidden_size=8",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "env.screen_size=64",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


@pytest.mark.parametrize("env_id", ["dummy_discrete", "dummy_multidiscrete", "dummy_continuous"])
def test_ppo_recurrent_dummy(tmp_path, monkeypatch, env_id):
    monkeypatch.chdir(tmp_path)
    run(rppo_args(tmp_path, env_id))
    assert find_checkpoints(tmp_path)


def test_ppo_recurrent_mlp_only(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(rppo_args(tmp_path) + ["algo.cnn_keys.encoder=[]", "algo.mlp_keys.encoder=[state]"])


def test_ppo_recurrent_resume_and_evaluate(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(rppo_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(rppo_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])
