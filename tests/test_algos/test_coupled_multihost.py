"""Coupled (SPMD) multi-host e2e tests: full PPO and Dreamer-V3 ``main()``
across 2 real ``jax.distributed`` CPU processes × 2 virtual devices each —
the exact topology of the milestone multi-host configs (BASELINE.md (2)/(4)),
which round 3 had only covered with unit-level collective tests.

Each process owns its own envs, samples its block of the global batch,
assembles mesh-global arrays (``fabric.make_global`` — for DV3 through the
multi-host prefetch pipeline), runs the shard_map'd train step with its grad
pmean over the 4-device mesh, and writes its rank's checkpoint shard.
"""

import pytest

from tests.conftest import find_checkpoints, run_multi_process

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def test_ppo_coupled_two_process(tmp_path):
    args = [
        "exp=ppo",
        "env=dummy",
        "env.id=dummy_discrete",
        # forked AsyncVectorEnv workers inherit the jax.distributed client
        # and wedge its shutdown barrier; drive sync envs multi-process
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.total_steps=64",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_multi_process(RUNNER, argv=args, cwd=str(tmp_path), nproc=2, device_count=2, timeout=600)
    ckpts = find_checkpoints(tmp_path)
    assert len(ckpts) >= 1, "coupled multi-host PPO wrote no checkpoint"


@pytest.mark.slow
def test_dreamer_v3_coupled_two_process(tmp_path):
    args = [
        "exp=dreamer_v3",
        "env=dummy",
        "env.id=dummy_discrete",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "buffer.size=64",
        "buffer.checkpoint=True",
        "buffer.prefetch=2",  # the multi-host prefetch pipeline stays ON
        "algo.total_steps=24",
        "algo.learning_starts=8",
        "algo.replay_ratio=0.5",
        "algo.per_rank_batch_size=1",
        "algo.per_rank_sequence_length=4",
        "algo.horizon=4",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.world_model.encoder.cnn_channels_multiplier=2",
        "algo.world_model.recurrent_model.recurrent_state_size=8",
        "algo.world_model.representation_model.hidden_size=8",
        "algo.world_model.transition_model.hidden_size=8",
        "algo.world_model.discrete_size=4",
        "algo.world_model.stochastic_size=4",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[]",
        "env.num_envs=1",
        "env.screen_size=64",
        "algo.run_test=False",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_multi_process(RUNNER, argv=args, cwd=str(tmp_path), nproc=2, device_count=2, timeout=600)
    # every rank contributes its checkpoint shard (buffer gather to rank files)
    ckpts = find_checkpoints(tmp_path)
    assert len(ckpts) >= 1, "coupled multi-host Dreamer-V3 wrote no checkpoint"
