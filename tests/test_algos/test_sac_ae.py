"""SAC-AE smoke tests (reference: tests/test_algos/test_algos.py::test_sac_ae).

Pixel + vector continuous control with the autoencoder path on the dummy
continuous env."""

import os
import pytest

from sheeprl_tpu.cli import run


def sac_ae_args(tmp_path):
    return [
        "exp=sac_ae",
        "env=dummy",
        "env.id=dummy_continuous",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=2",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.hidden_size=8",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.cnn_channels_multiplier=1",
        "algo.encoder.features_dim=8",
        "algo.cnn_keys.encoder=[rgb]",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "env.frame_stack=1",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def test_sac_ae_pixel_and_vector(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_ae_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_sac_ae_pixel_only(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_ae_args(tmp_path) + ["algo.mlp_keys.encoder=[]"])


def test_sac_ae_frame_stack(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_ae_args(tmp_path) + ["env.frame_stack=3"])


def test_sac_ae_resume_and_evaluate(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_ae_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(sac_ae_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


@pytest.mark.slow
def test_sac_ae_device_buffer_frame_stack(tmp_path, monkeypatch):
    # HBM ring with raw frame-stacked pixel storage + on-device stack fold
    monkeypatch.chdir(tmp_path)
    args = [a for a in sac_ae_args(tmp_path) if a not in ("dry_run=True", "env.frame_stack=1")]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "env.frame_stack=2",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
        ]
    )
