"""Fused training supersteps through the real CLI entry points: with
``algo.fused_gradient_steps=K`` and the device replay buffer, one train
window of K gradient steps issues a single jitted dispatch — asserted via
the telemetry dispatch counters (the ISSUE's acceptance criterion) — plus
the documented warn-fallbacks and the Dreamer host-buffer pregather path."""

import json
import os
import sys

import pytest

from sheeprl_tpu.cli import run
from tests.test_algos.test_a2c_droq import droq_args
from tests.test_algos.test_dreamer_v3 import dv3_args, find_checkpoints
from tests.test_algos.test_sac import sac_args

TELEMETRY = ["metric.telemetry.enabled=True", "metric.telemetry.poll_interval=0.0"]


def _run_end(tmp_path):
    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1, f"expected exactly one telemetry.jsonl, found {jsonls}"
    events = [json.loads(line) for line in open(jsonls[0]) if line.strip()]
    (end,) = [e for e in events if e["event"] == "run_end"]
    return end, jsonls[0]


def _bench():
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.path.insert(0, repo_root)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_dreamer_v3_fused_device_buffer_single_dispatch_per_window(tmp_path, monkeypatch):
    """ISSUE acceptance: K >= G, device ring -> every train window is exactly
    ONE device program (the per-step device-buffer path would record 2G:
    a gather program + a train program per gradient step)."""
    monkeypatch.chdir(tmp_path)
    args = [
        a
        for a in dv3_args(tmp_path)
        if a != "dry_run=True" and not a.startswith("buffer.size=")
    ]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
            "algo.fused_gradient_steps=256",
        ]
        + TELEMETRY
    )
    assert find_checkpoints(tmp_path)

    end, path = _run_end(tmp_path)
    assert end["train_windows"] >= 2
    # the single-dispatch claim itself
    assert end["train_dispatches"] == end["train_windows"]
    # ... and the windows really fused MULTIPLE gradient steps (the Ratio's
    # first call always yields 1; later windows carry replay_ratio * steps)
    assert end["train_gradient_steps"] > end["train_windows"]

    ds = _bench().dispatch_stats(path)
    assert ds["dispatches_per_window"] == 1.0
    assert ds["train_gradient_steps"] == end["train_gradient_steps"]


def test_dreamer_v3_fused_host_buffer_pregathers(tmp_path, monkeypatch):
    """Without the device ring Dreamer still fuses: K host batches are
    pre-gathered and scanned in one dispatch (bit-identical sampling)."""
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path) + ["fabric.devices=1", "algo.fused_gradient_steps=2"])
    assert find_checkpoints(tmp_path)


@pytest.mark.slow
def test_dreamer_v3_fused_multi_device_single_dispatch_per_window(tmp_path, monkeypatch, recwarn):
    """ISSUE acceptance: on a pure data-parallel mesh the fused path no
    longer falls back — the whole K-step scan runs under shard_map over the
    sharded device ring, each window is ONE dispatch, and no fallback
    warning or ``fused_fallback`` telemetry event is emitted."""
    monkeypatch.chdir(tmp_path)
    args = [
        a
        for a in dv3_args(tmp_path)
        if a != "dry_run=True" and not a.startswith("buffer.size=")
    ]
    run(
        args
        + [
            "fabric.devices=2",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
            "algo.fused_gradient_steps=256",
        ]
        + TELEMETRY
    )
    assert find_checkpoints(tmp_path)
    assert not [
        w for w in recwarn if "falling back" in str(w.message)
    ], [str(w.message) for w in recwarn]

    end, path = _run_end(tmp_path)
    assert end["train_windows"] >= 2
    assert end["train_dispatches"] == end["train_windows"]
    assert end["train_gradient_steps"] > end["train_windows"]
    assert not end.get("fused_fallbacks")

    ds = _bench().dispatch_stats(path)
    assert ds["dispatches_per_window"] == 1.0
    assert "fused_fallbacks" not in ds


def test_dreamer_v3_fused_multi_device_host_buffer_pregathers(tmp_path, monkeypatch, recwarn):
    """The host-buffer pregather fallback fuses on a mesh too: the stacked
    [K, T, B] batches go up batch-axis sharded and the shard_map'd scan
    slices them without warning or falling back."""
    monkeypatch.chdir(tmp_path)
    run(dv3_args(tmp_path) + ["fabric.devices=2", "algo.fused_gradient_steps=2"])
    assert find_checkpoints(tmp_path)
    assert not [w for w in recwarn if "falling back" in str(w.message)]


def test_sac_fused_device_buffer_single_dispatch_per_window(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = [a for a in sac_args(tmp_path) if a != "dry_run=True"]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
            "algo.fused_gradient_steps=8",
        ]
        + TELEMETRY
    )
    assert find_checkpoints(tmp_path)
    end, _ = _run_end(tmp_path)
    assert end["train_windows"] >= 2
    assert end["train_dispatches"] == end["train_windows"]
    assert end["train_gradient_steps"] > end["train_windows"]


def test_sac_fused_host_buffer_falls_back_with_warning(tmp_path, monkeypatch):
    """SAC's host-buffer path already scans each chunk in one jit, so
    fused_gradient_steps without buffer.device warns (once) and is ignored —
    and the reason lands in run_end / ``bench.py --dispatch-stats`` so a
    per-step run is diagnosable after the fact."""
    monkeypatch.chdir(tmp_path)
    with pytest.warns(UserWarning, match="device replay buffer"):
        run(
            sac_args(tmp_path)
            + ["fabric.devices=1", "algo.fused_gradient_steps=4"]
            + TELEMETRY
        )
    assert find_checkpoints(tmp_path)
    end, path = _run_end(tmp_path)
    assert end["fused_fallbacks"] == {"host_buffer": 1}
    assert _bench().dispatch_stats(path)["fused_fallbacks"] == {"host_buffer": 1}


def test_droq_fused_device_buffer_dispatch_budget(tmp_path, monkeypatch):
    """DroQ windows = fused critic chunks + the separate actor update. With
    K >= G that is 1 (critic superstep) + 2 (actor gather + actor program)
    device dispatches per window — the per-step device path records 2G + 2."""
    monkeypatch.chdir(tmp_path)
    args = [a for a in droq_args(tmp_path) if a != "dry_run=True"]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
            "algo.replay_ratio=1",
            "algo.fused_gradient_steps=8",
        ]
        + TELEMETRY
    )
    assert find_checkpoints(tmp_path)
    end, _ = _run_end(tmp_path)
    assert end["train_windows"] >= 2
    assert end["train_dispatches"] == 3 * end["train_windows"]
    # gradient_steps counts the actor step too (G critic + 1 actor per window)
    assert end["train_gradient_steps"] > end["train_windows"]
