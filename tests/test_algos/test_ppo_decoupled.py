"""Decoupled PPO 2-process test (reference: tests/test_algos/test_algos.py::
test_ppo_decoupled, which launches 2 gloo ranks).

Spawns two real processes connected via ``jax.distributed`` on the CPU
backend: process 0 plays (owns the envs, ships the rollout), process 1
trains (fused PPO update on its own trainer mesh) and ships the params
back. Also exercises the host-object collectives cross-process — the
multi-process path that the in-process 8-device mesh tests cannot reach.
"""

import os

from tests.conftest import run_two_process

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def test_ppo_decoupled_two_process(tmp_path):
    args = [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=dummy_discrete",
        # forked AsyncVectorEnv workers inherit the jax.distributed client and
        # wedge its shutdown barrier; the decoupled topology drives sync envs
        "env.sync_env=True",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_two_process(RUNNER, argv=args, cwd=str(tmp_path))

    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert ckpts, "player did not write a checkpoint from the trainer state"
