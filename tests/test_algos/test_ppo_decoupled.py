"""Decoupled PPO 2-process test (reference: tests/test_algos/test_algos.py::
test_ppo_decoupled, which launches 2 gloo ranks).

Spawns two real processes connected via ``jax.distributed`` on the CPU
backend: process 0 plays (owns the envs, ships the rollout), process 1
trains (fused PPO update on its own trainer mesh) and ships the params
back. Also exercises the host-object collectives cross-process — the
multi-process path that the in-process 8-device mesh tests cannot reach.
"""

import os

import pytest

from tests.conftest import find_checkpoints, run_multi_process, run_two_process

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def test_ppo_decoupled_two_process(tmp_path):
    args = [
        "exp=ppo_decoupled",
        "env=dummy",
        "env.id=dummy_discrete",
        # forked AsyncVectorEnv workers inherit the jax.distributed client and
        # wedge its shutdown barrier; the decoupled topology drives sync envs
        "env.sync_env=True",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=8",
        "algo.per_rank_batch_size=4",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_two_process(RUNNER, argv=args, cwd=str(tmp_path))
    assert find_checkpoints(tmp_path), "player did not write a checkpoint from the trainer state"


def _args(tmp_path, **over):
    base = {
        "exp": "ppo_decoupled",
        "env": "dummy",
        "env.id": "dummy_discrete",
        "env.sync_env": "True",
        "env.capture_video": "False",
        "buffer.memmap": "False",
        "algo.rollout_steps": "8",
        "algo.per_rank_batch_size": "4",
        "algo.update_epochs": "1",
        "algo.dense_units": "8",
        "algo.mlp_layers": "1",
        "algo.encoder.cnn_features_dim": "16",
        "algo.encoder.mlp_features_dim": "8",
        "algo.mlp_keys.encoder": "[state]",
        "env.num_envs": "2",
        "algo.run_test": "False",
        "checkpoint.save_last": "True",
        "metric.log_level": "0",
        "log_base_dir": f"{tmp_path}/logs",
    }
    base.update(over)
    return [f"{k}={v}" for k, v in base.items()]


def test_ppo_decoupled_three_process_two_trainers(tmp_path):
    """1 player + 2 trainer processes: the rollout splits across the trainer
    mesh and the gradient pmean runs over two real processes (VERDICT round-2
    item: the decoupled topology had only ever run with one trainer)."""
    run_multi_process(
        RUNNER,
        argv=_args(tmp_path, **{"algo.total_steps": "32"}),
        cwd=str(tmp_path),
        nproc=3,
        device_count=1,
        timeout=600,
    )
    assert find_checkpoints(tmp_path), "no checkpoint written by the 3-process run"


@pytest.mark.slow
def test_ppo_decoupled_resume(tmp_path):
    """Checkpoint mid-run (update 2 of 4), then resume from it and finish:
    the decoupled topology restores params, optimizer state, counters and
    the player's action-sampling stream (reference
    ppo_decoupled.py:45-46,104-116). Resume reloads the run config stored
    beside the checkpoint, so both runs share total_steps=64."""
    run_two_process(
        RUNNER,
        argv=_args(
            tmp_path,
            **{
                "algo.total_steps": "64",
                "checkpoint.every": "32",
                "checkpoint.save_last": "False",
            },
        ),
        cwd=str(tmp_path),
    )
    ckpts = find_checkpoints(tmp_path)
    assert len(ckpts) >= 2, f"expected mid-run + final checkpoints, got {ckpts}"
    midway = [c for c in ckpts if os.path.basename(c).startswith("ckpt_32_")]
    assert midway, ckpts
    run_two_process(
        RUNNER,
        argv=_args(tmp_path, **{"checkpoint.resume_from": midway[0]}),
        cwd=str(tmp_path),
    )
    resumed = [c for c in find_checkpoints(tmp_path) if c not in ckpts]
    assert resumed, "resumed run did not write its own checkpoint"

    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    state = load_checkpoint(resumed[-1])
    assert state["update"] == 4, f"resumed run should end at update 4, got {state['update']}"
    assert "player_rng_key" in state and "opt_state" in state and state["opt_state"] is not None
