"""P2E DV2 smoke tests (reference: tests/test_algos/test_algos.py::test_p2e_dv2)."""

import os

import pytest

from sheeprl_tpu.cli import run

TINY = [
    "env=dummy",
    "dry_run=True",
    "env.capture_video=False",
    "buffer.memmap=False",
    "algo.per_rank_batch_size=1",
    "algo.per_rank_sequence_length=2",
    "buffer.size=10",
    "algo.learning_starts=0",
    "algo.replay_ratio=1",
    "algo.per_rank_pretrain_steps=1",
    "algo.horizon=4",
    "algo.dense_units=8",
    "algo.mlp_layers=1",
    "algo.ensembles.n=3",
    "algo.world_model.encoder.cnn_channels_multiplier=2",
    "algo.world_model.recurrent_model.recurrent_state_size=8",
    "algo.world_model.representation_model.hidden_size=8",
    "algo.world_model.transition_model.hidden_size=8",
    "algo.world_model.discrete_size=4",
    "algo.world_model.stochastic_size=4",
    "algo.cnn_keys.encoder=[rgb]",
    "algo.mlp_keys.encoder=[state]",
    "env.num_envs=2",
    "algo.run_test=True",
    "checkpoint.save_last=True",
    "metric.log_level=1",
]


def expl_args(tmp_path, env_id="dummy_discrete"):
    return ["exp=p2e_dv2_exploration", f"env.id={env_id}", f"log_base_dir={tmp_path}/logs"] + TINY


def find_checkpoints(path):
    ckpts = []
    for root, _, files in os.walk(path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


@pytest.mark.parametrize("env_id", ["dummy_discrete", "dummy_continuous"])
def test_p2e_dv2_exploration(tmp_path, monkeypatch, env_id):
    monkeypatch.chdir(tmp_path)
    run(expl_args(tmp_path, env_id))
    assert find_checkpoints(tmp_path)


@pytest.mark.slow
def test_p2e_dv2_exploration_to_finetuning_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(expl_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(
        ["exp=p2e_dv2_finetuning", "env.id=dummy_discrete", f"log_base_dir={tmp_path}/logs_ft"]
        + TINY
        + [f"checkpoint.exploration_ckpt_path={ckpt}"]
    )
    assert find_checkpoints(f"{tmp_path}/logs_ft")


def test_p2e_dv2_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(expl_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])
