"""Fused-rollout CLI acceptance for the ported algos (ISSUE 19 tentpole part 4).

``algo.fused_rollout=True`` on a2c and ppo_recurrent must meet the same bar
the PPO original is pinned to in ``test_fused_rollout.py``: exactly ONE train
dispatch per update, zero post-warmup recompiles, no fused_fallback, and a
run-registry record with ``variant=fused_rollout`` (the regress-gate cell
key).  Scenario variants (``env.variants.enabled``) must ride the fused path
end-to-end and refuse the host loop loudly rather than silently training the
un-randomized base env.

All CLI runs compile a real program, so everything here is marked ``slow``.
"""

import json
import os

import pytest

from sheeprl_tpu.cli import run


def _telemetry_events(tmp_path):
    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1, f"expected exactly one telemetry.jsonl, found {jsonls}"
    return [json.loads(line) for line in open(jsonls[0]) if line.strip()]


def _registry_records(tmp_path):
    path = os.path.join(tmp_path, "RUNS.jsonl")
    assert os.path.exists(path)
    return [json.loads(line) for line in open(path) if line.strip()]


def _common_args(tmp_path):
    return [
        "fabric.devices=1",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "env.num_envs=2",
        "checkpoint.save_last=False",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        "metric.telemetry.poll_interval=0.0",
        f"metric.telemetry.runs_jsonl={tmp_path}/RUNS.jsonl",
        f"log_base_dir={tmp_path}/logs",
    ]


def _assert_fused_acceptance(tmp_path, updates):
    events = _telemetry_events(tmp_path)
    assert "fused_fallback" not in {e["event"] for e in events}
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end["train_windows"] == updates
    assert run_end["train_dispatches"] == updates  # ONE dispatch per update
    assert run_end.get("recompiles", 0) == 0
    assert run_end["fused_fallbacks"] == {}
    (rec,) = [r for r in _registry_records(tmp_path) if r.get("kind") == "train"]
    assert rec.get("variant") == "fused_rollout"
    assert rec["train_dispatches"] == updates
    return run_end


@pytest.mark.slow
def test_a2c_fused_cli_one_dispatch_per_update(tmp_path, monkeypatch):
    """a2c + fused_rollout over 3 updates: 3 train windows, 3 dispatches,
    0 recompiles once warm."""
    monkeypatch.chdir(tmp_path)
    run(
        _common_args(tmp_path)
        + [
            "exp=a2c",
            "dry_run=False",
            "algo.total_steps=192",  # 3 updates of 32 steps x 2 envs
            "algo.rollout_steps=32",
            "algo.per_rank_batch_size=64",
            "algo.fused_rollout=True",
        ]
    )
    _assert_fused_acceptance(tmp_path, updates=3)


@pytest.mark.slow
def test_ppo_recurrent_fused_cli_one_dispatch_per_update(tmp_path, monkeypatch):
    """ppo_recurrent + fused_rollout over 3 updates: the sequence-chunked
    update (32-step rollout -> 16-step sequences) is still one dispatch."""
    monkeypatch.chdir(tmp_path)
    run(
        _common_args(tmp_path)
        + [
            "exp=ppo_recurrent",
            "dry_run=False",
            "algo.total_steps=192",
            "algo.rollout_steps=32",
            "algo.per_rank_sequence_length=16",
            "algo.per_rank_num_batches=2",
            "algo.update_epochs=2",
            "algo.fused_rollout=True",
        ]
    )
    _assert_fused_acceptance(tmp_path, updates=3)


@pytest.mark.slow
def test_ppo_fused_cli_with_variants_single_dispatch(tmp_path, monkeypatch):
    """env.variants ride the fused superstep: a scenario run (physics +
    sticky + distractors, so the obs is widened too) is still one dispatch
    per update with no fallback breadcrumb."""
    monkeypatch.chdir(tmp_path)
    run(
        _common_args(tmp_path)
        + [
            "exp=ppo",
            "dry_run=True",
            "algo.rollout_steps=32",
            "algo.per_rank_batch_size=8",
            "algo.update_epochs=2",
            "algo.encoder.cnn_features_dim=16",
            "algo.encoder.mlp_features_dim=8",
            "algo.fused_rollout=True",
            "env.variants.enabled=[phys_size,sticky_actions,distractors]",
        ]
    )
    _assert_fused_acceptance(tmp_path, updates=1)


@pytest.mark.slow
def test_variants_refuse_host_loop(tmp_path, monkeypatch):
    """Variants without the fused path must fail loudly: the agent may be
    built against the widened scenario obs and the host loop cannot apply
    variants, so silently training the base env is never an option."""
    monkeypatch.chdir(tmp_path)
    with pytest.raises(RuntimeError, match="env.variants requires the fused rollout path"):
        run(
            _common_args(tmp_path)
            + [
                "exp=ppo",
                "dry_run=True",
                "algo.rollout_steps=32",
                "algo.per_rank_batch_size=8",
                "algo.fused_rollout=False",
                "env.variants.enabled=[sticky_actions]",
            ]
        )
