"""A2C and DroQ smoke tests (reference: tests/test_algos/test_algos.py)."""

import os

from sheeprl_tpu.cli import run


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def a2c_args(tmp_path):
    return [
        "exp=a2c",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=8",
        "algo.dense_units=8",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def test_a2c_cartpole(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(a2c_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_a2c_host_pinned_training(tmp_path, monkeypatch):
    """algo.train_device=cpu runs the whole A2C update on the host backend
    (remote-chip escape hatch shared with plain PPO) — full run + resume."""
    monkeypatch.chdir(tmp_path)
    args = a2c_args(tmp_path) + ["fabric.devices=1", "algo.train_device=cpu"]
    run(args)
    (ckpt,) = find_checkpoints(tmp_path)
    run(args + [f"checkpoint.resume_from={ckpt}", "fabric.devices=1"])


def test_a2c_continuous(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(a2c_args(tmp_path) + ["env.id=Pendulum-v1"])


def test_a2c_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(a2c_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def droq_args(tmp_path):
    return [
        "exp=droq",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=8",
        "algo.hidden_size=16",
        "algo.learning_starts=0",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def test_droq_pendulum(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(droq_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_droq_resume(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(droq_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(droq_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_droq_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(droq_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def test_droq_device_buffer(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = [a for a in droq_args(tmp_path) if a != "dry_run=True"]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
        ]
    )
