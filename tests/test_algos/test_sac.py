"""SAC smoke tests (reference: tests/test_algos/test_algos.py::test_sac)."""

import os

import pytest

from sheeprl_tpu.cli import run


def sac_args(tmp_path):
    return [
        "exp=sac",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=8",
        "algo.hidden_size=16",
        "algo.learning_starts=0",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def test_sac_pendulum(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_sac_sample_next_obs(tmp_path, monkeypatch):
    # dry_run forces a 1-slot buffer, which cannot serve next-obs sampling;
    # run two real updates instead (same constraint as the reference)
    monkeypatch.chdir(tmp_path)
    args = [a for a in sac_args(tmp_path) if a != "dry_run=True" and "learning_starts" not in a]
    run(
        args
        + [
            "buffer.sample_next_obs=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=4",  # >= 2 transitions stored before sampling next-obs
        ]
    )


def test_sac_dummy_continuous(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        sac_args(tmp_path)
        + ["env=dummy", "env.id=dummy_continuous", "algo.mlp_keys.encoder=[state]"]
    )


def test_finite_action_bounds_clamps_unbounded_dims():
    """An unbounded Box action space must NOT become an inf tanh rescale:
    the dummy continuous env is Box(-inf, inf) and a literal inf scale NaNs
    the very first SAC update (caught by the resilience sentinel)."""
    import gymnasium as gym
    import numpy as np

    from sheeprl_tpu.algos.sac.agent import finite_action_bounds

    low, high = finite_action_bounds(gym.spaces.Box(-np.inf, np.inf, shape=(2,)))
    assert low == (-1.0, -1.0) and high == (1.0, 1.0)
    # finite bounds pass through untouched, per dimension
    low, high = finite_action_bounds(
        gym.spaces.Box(np.array([-2.0, -np.inf]), np.array([2.0, np.inf]))
    )
    assert low == (-2.0, -1.0) and high == (2.0, 1.0)


def test_sac_discrete_env_raises(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    with pytest.raises(ValueError, match="continuous action space"):
        run(sac_args(tmp_path) + ["env.id=CartPole-v1"])


def test_sac_resume(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(sac_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_sac_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(sac_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def test_sac_device_buffer(tmp_path, monkeypatch):
    # HBM replay ring on the CPU mesh: a few real updates + a cross-mode
    # resume (device ckpt -> host buffer run)
    monkeypatch.chdir(tmp_path)
    args = [a for a in sac_args(tmp_path) if a != "dry_run=True"]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=2",
        ]
    )
    (ckpt,) = find_checkpoints(tmp_path)
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=False",
            "buffer.size=64",
            "algo.total_steps=16",
            "algo.learning_starts=2",
            f"checkpoint.resume_from={ckpt}",
        ]
    )


def test_sac_device_buffer_sample_next_obs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    args = [a for a in sac_args(tmp_path) if a != "dry_run=True" and "learning_starts" not in a]
    run(
        args
        + [
            "fabric.devices=1",
            "buffer.device=True",
            "buffer.sample_next_obs=True",
            "buffer.size=64",
            "algo.total_steps=8",
            "algo.learning_starts=4",
        ]
    )
