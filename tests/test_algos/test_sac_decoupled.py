"""Decoupled SAC 2-process test (reference: tests/test_algos/test_algos.py::
test_sac_decoupled). Process 0 plays and owns the replay buffer; process 1
trains on its own mesh and ships the actor back."""

import os

from tests.conftest import run_two_process

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def test_sac_decoupled_two_process(tmp_path):
    args = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.sync_env=True",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=2",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_two_process(RUNNER, argv=args, cwd=str(tmp_path))

    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert ckpts, "player did not write a checkpoint from the trainer state"
