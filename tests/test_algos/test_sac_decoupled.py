"""Decoupled SAC 2-process test (reference: tests/test_algos/test_algos.py::
test_sac_decoupled). Process 0 plays and owns the replay buffer; process 1
trains on its own mesh and ships the actor back."""

import os
import socket
import subprocess
import sys

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_sac_decoupled_two_process(tmp_path):
    port = _free_port()
    args = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.sync_env=True",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=2",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("SHEEPRL_TPU_COORDINATOR", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        env["TEST_COORD"] = f"127.0.0.1:{port}"
        env["TEST_NPROC"] = "2"
        env["TEST_PID"] = str(pid)
        env["PYTHONPATH"] = os.pathsep.join(
            p for p in (os.path.dirname(os.path.dirname(os.path.dirname(__file__))), env.get("PYTHONPATH")) if p
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", RUNNER, *args],
                env=env,
                cwd=str(tmp_path),
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=540)
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {pid} failed:\n{out[-4000:]}"

    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    assert ckpts, "player did not write a checkpoint from the trainer state"
