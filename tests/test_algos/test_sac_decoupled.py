"""Decoupled SAC 2-process test (reference: tests/test_algos/test_algos.py::
test_sac_decoupled). Process 0 plays and owns the replay buffer; process 1
trains on its own mesh and ships the actor back."""

import os

import pytest

from tests.conftest import find_checkpoints, run_two_process

RUNNER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["TEST_COORD"],
    num_processes=int(os.environ["TEST_NPROC"]),
    process_id=int(os.environ["TEST_PID"]),
)
from sheeprl_tpu.cli import run
run(sys.argv[1:])
"""


def test_sac_decoupled_two_process(tmp_path):
    args = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.sync_env=True",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=2",
        "buffer.size=10",
        "algo.learning_starts=0",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]
    run_two_process(RUNNER, argv=args, cwd=str(tmp_path))
    assert find_checkpoints(tmp_path), "player did not write a checkpoint from the trainer state"


@pytest.mark.slow
def test_sac_decoupled_resume(tmp_path):
    """Decoupled SAC restores agent, optimizers, replay buffer and counters
    from a player-written checkpoint (round-2 VERDICT: resume was refused)."""
    base = [
        "exp=sac_decoupled",
        "env=dummy",
        "env.id=dummy_continuous",
        "env.sync_env=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.per_rank_batch_size=2",
        "buffer.size=64",
        "buffer.checkpoint=True",
        "algo.learning_starts=2",
        "algo.replay_ratio=1",
        "algo.per_rank_pretrain_steps=1",
        "algo.hidden_size=8",
        "algo.mlp_keys.encoder=[state]",
        "env.num_envs=2",
        "algo.run_test=False",
        "checkpoint.save_last=False",
        "metric.log_level=0",
        f"log_base_dir={tmp_path}/logs",
    ]
    # resume reloads the config stored beside the checkpoint, so the resumed
    # run continues the SAME total_steps=16 from the mid-run checkpoint
    run_two_process(
        RUNNER,
        argv=base + ["algo.total_steps=16", "checkpoint.every=8"],
        cwd=str(tmp_path),
    )
    ckpts = find_checkpoints(tmp_path)
    midway = [c for c in ckpts if os.path.basename(c).startswith("ckpt_8_")]
    assert midway, ckpts
    # resume keeps the CURRENT run's checkpoint settings (reference
    # semantics), so the cadence must be restated
    run_two_process(
        RUNNER,
        argv=base + ["checkpoint.every=8", f"checkpoint.resume_from={midway[0]}"],
        cwd=str(tmp_path),
    )
    resumed = [c for c in find_checkpoints(tmp_path) if c not in ckpts]
    assert resumed, "resumed run did not write its own checkpoint"

    from sheeprl_tpu.utils.checkpoint import load_checkpoint

    state = load_checkpoint(resumed[-1])
    assert state["update"] == 8, f"resumed run should end at update 8, got {state['update']}"
    assert "player_rng_key" in state and "agent" in state
