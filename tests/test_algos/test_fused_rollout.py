"""Fused on-policy collection tests (`algo.fused_rollout` / `algo.overlap_collection`).

Three layers:

1. numerical: the ONE-dispatch superstep (`ops/rollout_scan.py`) must equal an
   eager Python re-implementation of its contract (host-loop key schedule,
   truncation bootstrap, SAME_STEP autoreset, GAE, fused update) on fp32 CPU;
2. key schedule: the in-scan action stream is exactly the host
   ``PPOPlayer.rollout_actions`` stream;
3. integration: the CLI run really issues one train dispatch per update
   (telemetry counters), and the overlap path really attributes train-wait
   time (heartbeat + run-registry fields).

The compile-heavy cases (eager-reference equivalence and the fused CLI runs)
are marked ``slow``; tier-1 keeps the key-schedule, overlap-heartbeat, and
jittable-env parity coverage.
"""

import json
import os
from functools import partial

import gymnasium as gym
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from sheeprl_tpu.algos.ppo.agent import PPOPlayer, build_agent, rollout_step
from sheeprl_tpu.cli import run
from sheeprl_tpu.config.compose import compose, instantiate
from sheeprl_tpu.envs.jittable import JaxCartPole
from sheeprl_tpu.ops.math import gae
from sheeprl_tpu.ops.rollout_scan import ENV_STREAM_SALT, init_env_carry, make_onpolicy_superstep_fn
from sheeprl_tpu.utils.utils import dotdict

T = 8
NUM_ENVS = 4
GAMMA = 0.99
LAM = 0.95


def _tiny_setup(tmp_path):
    cfg = dotdict(
        compose(
            "config",
            [
                "exp=ppo",
                "dry_run=True",
                "fabric.devices=1",
                "fabric.precision=fp32",
                f"algo.rollout_steps={T}",
                "algo.per_rank_batch_size=8",
                "algo.update_epochs=2",
                "algo.dense_units=8",
                "algo.mlp_layers=1",
                "algo.encoder.mlp_features_dim=8",
                "algo.encoder.cnn_features_dim=16",
                f"env.num_envs={NUM_ENVS}",
                f"log_base_dir={tmp_path}/logs",
            ],
        )
    )
    fabric_cfg = dict(cfg.fabric.to_dict())
    fabric_cfg.pop("callbacks", None)
    fabric = instantiate({**fabric_cfg, "callbacks": []})
    obs_space = gym.spaces.Dict({"state": gym.spaces.Box(-np.inf, np.inf, (4,), np.float32)})
    agent, params = build_agent(fabric, (2,), False, cfg, obs_space, None)
    tx = optax.adam(1e-3)
    return cfg, fabric, agent, params, tx


def _eager_update(agent, ref_train, params, opt_state, carry, update_key, key, step0):
    """Plain-Python transliteration of the superstep contract: same
    primitives in the same order, but one eager op at a time instead of one
    scanned jit — an independent oracle for the fused program."""
    spec = JaxCartPole
    env_ids = jnp.arange(NUM_ENVS, dtype=jnp.uint32)
    env_root = jax.random.fold_in(update_key, ENV_STREAM_SALT)
    state, ep_ret, ep_len = carry["state"], carry["ep_ret"], carry["ep_len"]
    counter = jnp.uint32(step0)
    ys = []
    for _ in range(T):
        obs = jax.vmap(spec.observation)(state)
        counter = counter + NUM_ENVS
        k_act = jax.random.fold_in(update_key, counter)
        actions, real_actions, logprobs, values = rollout_step(agent, params, {"state": obs}, k_act)
        act = real_actions[..., 0].astype(jnp.int32)
        env_base = jax.random.fold_in(env_root, counter)
        per_env = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(env_base, env_ids)
        pair = jax.vmap(jax.random.split)(per_env)
        next_state, out = jax.vmap(spec.step)(state, act, pair[:, 0])
        raw_reward = out.reward.astype(jnp.float32)
        v_final = agent.apply(params, {"state": out.obs})[1]
        reward = raw_reward + GAMMA * v_final[:, 0] * out.truncated.astype(jnp.float32)
        done = jnp.logical_or(out.terminated, out.truncated)
        ep_ret = ep_ret + raw_reward
        ep_len = ep_len + 1
        ys.append(
            {
                "state": obs,
                "dones": done[:, None].astype(jnp.float32),
                "values": values,
                "actions": actions,
                "logprobs": logprobs,
                "rewards": reward[:, None],
            }
        )
        reset_state = jax.vmap(spec.init)(pair[:, 1])
        state = jax.tree.map(
            lambda r, n: jnp.where(done.reshape(done.shape + (1,) * (n.ndim - 1)), r, n),
            reset_state,
            next_state,
        )
        ep_ret = jnp.where(done, 0.0, ep_ret)
        ep_len = jnp.where(done, 0, ep_len)

    data = {k: jnp.stack([y[k] for y in ys]) for k in ys[0]}
    next_values = agent.apply(params, {"state": jax.vmap(spec.observation)(state)})[1]
    returns, advantages = gae(
        data["rewards"], data["values"], data["dones"], next_values, gamma=GAMMA, gae_lambda=LAM
    )
    data["returns"] = returns
    data["advantages"] = advantages
    flat = jax.tree.map(lambda x: x.reshape((T * NUM_ENVS,) + x.shape[2:]), data)
    key, k_train = jax.random.split(key)
    params, opt_state, metrics = ref_train(params, opt_state, flat, k_train, np.float32(0.2), np.float32(0.0))
    return params, opt_state, {"state": state, "ep_ret": ep_ret, "ep_len": ep_len}, key, metrics


@pytest.mark.slow
def test_superstep_matches_eager_reference(tmp_path):
    """Two full updates: fused superstep == eager oracle on params, opt
    state, loss metrics, env carry and the evolved train key (fp32 CPU)."""
    from sheeprl_tpu.algos.ppo.ppo import make_local_train

    cfg, fabric, agent, params, tx = _tiny_setup(tmp_path)
    cfg.algo.gamma = GAMMA
    cfg.algo.gae_lambda = LAM
    n_local = T * NUM_ENVS
    local_train = make_local_train(fabric, agent, tx, cfg, ["state"], n_local, use_mesh=False)
    superstep = make_onpolicy_superstep_fn(
        JaxCartPole,
        policy_fn=partial(rollout_step, agent),
        value_fn=lambda p, o: agent.apply(p, o)[1],
        local_train=local_train,
        obs_key="state",
        rollout_steps=T,
        step_increment=NUM_ENVS,
        gamma=GAMMA,
        gae_lambda=LAM,
    )
    ref_train = jax.jit(local_train)

    carry0 = init_env_carry(
        JaxCartPole, NUM_ENVS, jax.random.fold_in(jax.random.PRNGKey(5), ENV_STREAM_SALT)
    )
    player_key = jax.random.fold_in(jax.random.PRNGKey(3), 1)

    params_f = params_r = params
    opt_f = tx.init(params)
    opt_r = tx.init(params)
    carry_f = carry_r = carry0
    key_f = key_r = jax.random.PRNGKey(3)
    step = 0
    for update in (1, 2):
        update_key = jax.random.fold_in(player_key, update)
        params_f, opt_f, carry_f, key_f, metrics_f, ep_stats = superstep(
            params_f, opt_f, carry_f, update_key, key_f, np.uint32(step), np.float32(0.2), np.float32(0.0)
        )
        params_r, opt_r, carry_r, key_r, metrics_r = _eager_update(
            agent, ref_train, params_r, opt_r, carry_r, update_key, key_r, step
        )
        step += T * NUM_ENVS

        assert np.array_equal(np.asarray(key_f), np.asarray(key_r)), "train key stream diverged"
        np.testing.assert_allclose(np.asarray(metrics_f), np.asarray(metrics_r), rtol=1e-5, atol=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5),
            params_f,
            params_r,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5
            ),
            carry_f,
            carry_r,
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5),
            opt_f,
            opt_r,
        )
        assert np.asarray(ep_stats["done"]).shape == (T, NUM_ENVS)


def test_superstep_key_schedule_matches_player(tmp_path):
    """The fused action stream is the host player's stream: for any step
    counter, ``rollout_actions(obs, update_key, counter)`` ==
    ``rollout_step(..., fold_in(update_key, counter))`` — the identity the
    in-scan schedule is built on."""
    _cfg, _fabric, agent, params, _tx = _tiny_setup(tmp_path)
    player = PPOPlayer(agent, params)
    rng = np.random.default_rng(0)
    obs = {"state": rng.normal(size=(NUM_ENVS, 4)).astype(np.float32)}
    update_key = jax.random.fold_in(jax.random.PRNGKey(3), 17)
    for counter in (np.uint32(4), np.uint32(64), np.uint32(4096)):
        from_player = player.rollout_actions(obs, update_key, counter)
        from_schedule = rollout_step(agent, params, obs, jax.random.fold_in(update_key, counter))
        for a, b in zip(from_player, from_schedule):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6)


def _telemetry_events(tmp_path):
    jsonls = []
    for root, _, files in os.walk(tmp_path):
        jsonls += [os.path.join(root, f) for f in files if f == "telemetry.jsonl"]
    assert len(jsonls) == 1, f"expected exactly one telemetry.jsonl, found {jsonls}"
    return [json.loads(line) for line in open(jsonls[0]) if line.strip()]


def _fused_args(tmp_path):
    return [
        "exp=ppo",
        "dry_run=True",
        "fabric.devices=1",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        "metric.telemetry.enabled=True",
        "metric.telemetry.poll_interval=0.0",
        f"metric.telemetry.runs_jsonl={tmp_path}/RUNS.jsonl",
        f"log_base_dir={tmp_path}/logs",
    ]


def _registry_records(tmp_path):
    path = os.path.join(tmp_path, "RUNS.jsonl")
    assert os.path.exists(path)
    return [json.loads(line) for line in open(path) if line.strip()]


@pytest.mark.slow
def test_fused_cli_single_dispatch(tmp_path, monkeypatch):
    """`algo.fused_rollout=True` end-to-end: the whole update is ONE device
    program — telemetry must count train_dispatches == train_windows ==
    num_updates with no fused_fallback, and the run must still checkpoint
    and register with variant=fused_rollout (the regress-gate cell key)."""
    monkeypatch.chdir(tmp_path)
    run(_fused_args(tmp_path) + ["algo.fused_rollout=True"])

    events = _telemetry_events(tmp_path)
    assert "fused_fallback" not in {e["event"] for e in events}
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end["train_windows"] == 1  # dry_run: one update
    assert run_end["train_dispatches"] == 1  # ...and ONE dispatch for it
    assert run_end["fused_fallbacks"] == {}

    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [f for f in files if f.endswith(".ckpt")]
    assert ckpts

    (rec,) = [r for r in _registry_records(tmp_path) if r.get("kind") == "train"]
    assert rec.get("variant") == "fused_rollout"
    assert rec["train_dispatches"] == 1


@pytest.mark.slow
def test_fused_cli_falls_back_without_jittable_twin(tmp_path, monkeypatch):
    """An env with no jittable twin must warn-fallback to the host loop (and
    say why), not crash: Acrobot-v1 has no twin, so the run completes with a
    `jittable_env` fused_fallback breadcrumb and per-step host dispatches."""
    monkeypatch.chdir(tmp_path)
    run(_fused_args(tmp_path) + ["algo.fused_rollout=True", "env.id=Acrobot-v1"])
    events = _telemetry_events(tmp_path)
    fallbacks = [e for e in events if e["event"] == "fused_fallback"]
    assert fallbacks and fallbacks[0]["reason"] == "jittable_env"
    (run_end,) = [e for e in events if e["event"] == "run_end"]
    assert run_end["train_dispatches"] > 1  # the host loop's per-step programs


def test_overlap_cli_heartbeat_attribution(tmp_path, monkeypatch):
    """`algo.overlap_collection=True`: from update 2 on, the blocking metrics
    wait is attributed to Time/train_wait_time, so some heartbeat must carry
    window_train_wait_time + overlap_fraction and the registry record the
    cumulative train_wait_time / sps_end_to_end rollup."""
    monkeypatch.chdir(tmp_path)
    # 3 updates (64 policy-steps each) so at least one post-update-2 window
    # records the wait; log_every=1 puts a heartbeat after every update
    run(
        _fused_args(tmp_path)
        + [
            "algo.overlap_collection=True",
            "dry_run=False",
            "algo.total_steps=192",
            "metric.log_every=1",
        ]
    )
    events = _telemetry_events(tmp_path)
    waits = [e for e in events if e["event"] == "heartbeat" and "window_train_wait_time" in e]
    assert waits, "no heartbeat recorded the overlap train-wait window"
    assert all(0.0 <= hb["overlap_fraction"] <= 1.0 for hb in waits if "overlap_fraction" in hb)
    assert any("overlap_fraction" in hb for hb in waits)

    (rec,) = [r for r in _registry_records(tmp_path) if r.get("kind") == "train"]
    assert rec.get("variant") == "overlap_collection"
    assert rec["train_wait_time"] > 0
    assert rec["sps_end_to_end"] > 0
    assert 0.0 <= rec["overlap_fraction"] <= 1.0
