"""Elastic checkpoint restore: a run saved on one mesh size resumes on
another (VERDICT round-4 item 5b; reference semantics: the checkpoint stores
the GLOBAL batch — ``dreamer_v3.py`` writes ``batch_size = per_rank *
world_size`` and resume divides by the NEW world size — while the reference
itself refuses world-size changes, callback.py:87-142).

Device elasticity is the TPU-native win: params checkpoint as host arrays
(sharding-free), so an 8-chip run's state reshards onto any divisor mesh at
resume. These tests drive DV3 end to end on the virtual CPU mesh: save on 8
devices, resume on 4, then grow 4 -> 8.
"""

import os

from sheeprl_tpu.cli import run
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from tests.conftest import find_checkpoints
from tests.test_algos.test_dreamer_v3 import dv3_args


def _elastic_args(tmp_path):
    # a REAL (non-dry_run) schedule so the resumed half actually trains:
    # 2 envs -> 2 policy steps/update, total 8 steps = 4 updates, mid-run
    # checkpoint at update 2. per_rank_batch_size is per DEVICE: 8 devices
    # x 1 -> global batch 8, which resharding onto 4 devices turns into
    # per-device 2.
    args = [a for a in dv3_args(tmp_path) if a != "dry_run=True"]
    return args + [
        "buffer.checkpoint=True",
        "algo.total_steps=8",
        "algo.learning_starts=2",
        "checkpoint.every=4",
        "algo.run_test=False",
    ]


def test_dv3_save_on_8_resume_on_4(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(_elastic_args(tmp_path) + ["fabric.devices=8"])
    ckpt = min(find_checkpoints(tmp_path), key=os.path.getmtime)  # the mid-run one
    saved = load_checkpoint(ckpt)
    assert saved["batch_size"] == 8  # global batch recorded, not per-device

    latest_before = max(os.path.getmtime(p) for p in find_checkpoints(tmp_path))
    run(_elastic_args(tmp_path) + ["fabric.devices=4", f"checkpoint.resume_from={ckpt}"])
    newest = max(find_checkpoints(tmp_path), key=os.path.getmtime)
    assert os.path.getmtime(newest) > latest_before, "resumed run wrote no checkpoint"
    resumed = load_checkpoint(newest)
    # the global batch is preserved across the mesh change
    assert resumed["batch_size"] == 8
    assert resumed["update"] > saved["update"]


def test_dv3_save_on_4_resume_on_8(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(_elastic_args(tmp_path) + ["fabric.devices=4", "algo.per_rank_batch_size=2"])
    ckpt = min(find_checkpoints(tmp_path), key=os.path.getmtime)
    latest_before = max(os.path.getmtime(p) for p in find_checkpoints(tmp_path))
    run(_elastic_args(tmp_path) + ["fabric.devices=8", f"checkpoint.resume_from={ckpt}"])
    newest = max(find_checkpoints(tmp_path), key=os.path.getmtime)
    assert os.path.getmtime(newest) > latest_before, "resumed run wrote no checkpoint"
    assert load_checkpoint(newest)["batch_size"] == 8
