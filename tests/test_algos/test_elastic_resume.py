"""Elastic checkpoint restore: a run saved on one mesh size resumes on
another (VERDICT round-4 item 5b; reference semantics: the checkpoint stores
the GLOBAL batch — ``dreamer_v3.py`` writes ``batch_size = per_rank *
world_size`` and resume divides by the NEW world size — while the reference
itself refuses world-size changes, callback.py:87-142).

Device elasticity is the TPU-native win: params checkpoint as host arrays
(sharding-free), so an 8-chip run's state reshards onto any divisor mesh at
resume. These tests drive DV3 end to end on the virtual CPU mesh: shrink
8 -> 4, grow 4 -> 8, and cross mesh KINDS (param-sharded -> pure DP).
"""

import os
import pytest

from sheeprl_tpu.cli import run
from sheeprl_tpu.utils.checkpoint import load_checkpoint
from tests.conftest import find_checkpoints
from tests.test_algos.test_dreamer_v3 import dv3_args


def _elastic_args(tmp_path):
    # a REAL (non-dry_run) schedule so the resumed half actually trains:
    # 2 envs -> 2 policy steps/update, total 8 steps = 4 updates, mid-run
    # checkpoint at update 2. per_rank_batch_size is per DEVICE: 8 devices
    # x 1 -> global batch 8, which resharding onto 4 devices turns into
    # per-device 2.
    args = [a for a in dv3_args(tmp_path) if a != "dry_run=True"]
    return args + [
        "buffer.checkpoint=True",
        "algo.total_steps=8",
        "algo.learning_starts=2",
        "checkpoint.every=4",
        "algo.run_test=False",
    ]


def _save_then_resume(tmp_path, save_overrides, resume_overrides):
    """Save a mid-run checkpoint with one topology, resume with another;
    assert the resumed run genuinely trained (updates progressed, a newer
    checkpoint landed) and return ``(saved, resumed)`` states."""
    run(_elastic_args(tmp_path) + save_overrides)
    ckpt = min(find_checkpoints(tmp_path), key=os.path.getmtime)  # the mid-run one
    saved = load_checkpoint(ckpt)
    latest_before = max(os.path.getmtime(p) for p in find_checkpoints(tmp_path))
    run(_elastic_args(tmp_path) + resume_overrides + [f"checkpoint.resume_from={ckpt}"])
    newest = max(find_checkpoints(tmp_path), key=os.path.getmtime)
    assert os.path.getmtime(newest) > latest_before, "resumed run wrote no checkpoint"
    resumed = load_checkpoint(newest)
    assert resumed["update"] > saved["update"], "resume restored state but trained no updates"
    return saved, resumed


@pytest.mark.slow
def test_dv3_save_on_8_resume_on_4(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    saved, resumed = _save_then_resume(tmp_path, ["fabric.devices=8"], ["fabric.devices=4"])
    # global batch recorded (not per-device) and preserved across the change
    assert saved["batch_size"] == 8
    assert resumed["batch_size"] == 8


@pytest.mark.slow
def test_dv3_save_on_4_resume_on_8(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    saved, resumed = _save_then_resume(
        tmp_path, ["fabric.devices=4", "algo.per_rank_batch_size=2"], ["fabric.devices=8"]
    )
    assert saved["batch_size"] == 8
    assert resumed["batch_size"] == 8


@pytest.mark.slow
def test_dv3_model_axis_checkpoint_resumes_on_dp_mesh(tmp_path, monkeypatch):
    """Topology change ACROSS mesh kinds: a checkpoint trained with param
    sharding on a (data=2, model=4) mesh resumes on a plain 8-wide DP mesh —
    possible because checkpoints store host-layout arrays, and because
    explicitly-passed fabric.* overrides (including mesh_axes) win over the
    stored fabric section at resume (cli.resume_from_checkpoint)."""
    monkeypatch.chdir(tmp_path)
    saved, resumed = _save_then_resume(
        tmp_path,
        [
            "fabric.mesh_axes=[data,model]",
            "fabric.mesh_shape=[2,4]",
            "algo.per_rank_batch_size=4",  # data width 2 -> global batch 8
            "algo.dense_units=16",
            "algo.world_model.recurrent_model.recurrent_state_size=16",
        ],
        ["fabric.mesh_axes=[data]", "fabric.mesh_shape=null", "fabric.devices=8"],
    )
    assert saved["batch_size"] == 8
    assert resumed["batch_size"] == 8
