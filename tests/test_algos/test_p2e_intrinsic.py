"""Plan2Explore intrinsic-reward sanity (round-2 VERDICT item 4: nothing
checked that ensemble disagreement actually behaves like an exploration
signal). Two properties of the P2E-DV3 ensemble machinery:

1. training the ensemble on a fixed transition set DRIVES DISAGREEMENT DOWN
   on that set (seen data stops being interesting),
2. after training, disagreement is HIGHER on unseen inputs than on the
   training set (novelty ranks above familiarity).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from sheeprl_tpu.algos.p2e_dv3.agent import Ensemble, ensemble_apply, init_ensembles
from sheeprl_tpu.ops.distributions import MSEDistribution


def _disagreement(ens, params, x):
    preds = ensemble_apply(ens, params, x)  # [N, B, S]
    return float(preds.var(axis=0).mean())


def test_ensemble_disagreement_decreases_on_seen_data_and_ranks_novelty():
    key = jax.random.PRNGKey(0)
    in_dim, out_dim, n_members = 12, 6, 5
    ens = Ensemble(output_dim=out_dim, mlp_layers=2, dense_units=32)
    k_init, k_x, k_y, k_novel = jax.random.split(key, 4)
    params = init_ensembles(ens, n_members, k_init, jnp.zeros((1, in_dim)))

    # a fixed "seen" transition set with a deterministic target function
    x_seen = jax.random.normal(k_x, (64, in_dim))
    w = jax.random.normal(k_y, (in_dim, out_dim)) * 0.3
    y_seen = jnp.tanh(x_seen @ w)
    x_novel = 3.0 + 2.0 * jax.random.normal(k_novel, (64, in_dim))  # off-distribution

    tx = optax.adam(3e-3)
    opt = tx.init(params)

    # the exploration loss of p2e_dv3_exploration.py:237-243: sum over
    # members of the per-member mean MSE NLL against the shared target
    @jax.jit
    def step(params, opt):
        def loss_fn(p):
            outs = ensemble_apply(ens, p, x_seen)
            logp = MSEDistribution(outs, dims=1).log_prob(jnp.broadcast_to(y_seen[None], outs.shape))
            return -logp.mean(axis=1).sum()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, updates), opt, loss

    before = _disagreement(ens, params, x_seen)
    for _ in range(300):
        params, opt, _ = step(params, opt)
    after = _disagreement(ens, params, x_seen)

    assert after < before * 0.5, (
        f"disagreement on seen data should collapse with training: {before} -> {after}"
    )
    novel = _disagreement(ens, params, x_novel)
    assert novel > after * 2, (
        f"novel inputs should stay more 'interesting' than trained ones: seen={after}, novel={novel}"
    )
