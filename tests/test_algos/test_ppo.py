"""PPO smoke tests (reference: tests/test_algos/test_algos.py::test_ppo).

One full CLI-driven update on tiny nets against dummy/gym envs — the
integration layer of the test pyramid (SURVEY.md §4.1). Runs on the 8-device
virtual CPU mesh from conftest, so the shard_map data-parallel path is
exercised on every test.
"""

import os

import numpy as np
import pytest

from sheeprl_tpu.cli import run


def standard_args(tmp_path):
    return [
        "exp=ppo",
        "dry_run=True",
        "env.capture_video=False",
        "buffer.memmap=False",
        "algo.rollout_steps=32",
        "algo.per_rank_batch_size=8",
        "algo.update_epochs=2",
        "algo.dense_units=8",
        "algo.mlp_layers=1",
        "algo.encoder.cnn_features_dim=16",
        "algo.encoder.mlp_features_dim=8",
        "env.num_envs=2",
        "algo.run_test=True",
        "checkpoint.save_last=True",
        "metric.log_level=1",
        f"log_base_dir={tmp_path}/logs",
    ]


def find_checkpoints(tmp_path):
    ckpts = []
    for root, _, files in os.walk(tmp_path):
        ckpts += [os.path.join(root, f) for f in files if f.endswith(".ckpt")]
    return ckpts


def test_ppo_cartpole_vector(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    assert find_checkpoints(tmp_path)


def test_ppo_host_pinned_training(tmp_path, monkeypatch):
    """algo.train_device=cpu: the whole fused update runs on the host
    backend (the remote-chip escape hatch, resolve_train_device) — full
    run + resume through the host-jitted no-mesh train path."""
    monkeypatch.chdir(tmp_path)
    args = standard_args(tmp_path) + ["fabric.devices=1", "algo.train_device=cpu"]
    run(args)
    (ckpt,) = find_checkpoints(tmp_path)
    run(args + [f"checkpoint.resume_from={ckpt}", "fabric.devices=1"])


def test_ppo_dummy_discrete_pixels(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_discrete",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
        ]
    )


def test_ppo_dummy_continuous(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_continuous",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[state]",
        ]
    )


def test_ppo_dummy_multidiscrete(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_multidiscrete",
            "env.screen_size=36",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ]
    )


def test_ppo_frame_stack(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(
        standard_args(tmp_path)
        + [
            "env=dummy",
            "env.id=dummy_discrete",
            "env.screen_size=36",
            "env.frame_stack=2",
            "algo.cnn_keys.encoder=[rgb]",
            "algo.mlp_keys.encoder=[]",
        ]
    )


def test_ppo_resume_from_checkpoint(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    run(standard_args(tmp_path) + [f"checkpoint.resume_from={ckpt}"])


def test_ppo_resume_env_mismatch_raises(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    with pytest.raises(ValueError, match="different environment"):
        run(standard_args(tmp_path) + [f"checkpoint.resume_from={ckpt}", "env.id=Acrobot-v1"])


def test_ppo_evaluate_roundtrip(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}"])


def test_ppo_evaluate_group_override(tmp_path, monkeypatch):
    """`fabric=cpu` on the eval CLI must re-compose the fabric group (hydra
    semantics), not overwrite cfg.fabric with the bare string."""
    monkeypatch.chdir(tmp_path)
    run(standard_args(tmp_path))
    (ckpt,) = find_checkpoints(tmp_path)
    from sheeprl_tpu.cli import evaluation

    evaluation([f"checkpoint_path={ckpt}", "fabric=cpu"])


def test_ppo_unknown_algo_error(tmp_path):
    with pytest.raises(ValueError, match="no registered algorithm"):
        run(standard_args(tmp_path) + ["algo.name=not_an_algo"])
